"""Truth-based tests of the NumPy oracle itself.

The simulator knows the true molecule sequences, so we can assert the
oracle pipeline actually *works* (consensus error far below raw read
error; grouping recovers true molecules) rather than only testing
self-consistency.
"""

import numpy as np
import pytest

from duplexumiconsensusreads_tpu.constants import BASE_N, N_REAL_BASES
from duplexumiconsensusreads_tpu.oracle import (
    apply_cycle_error_model,
    call_consensus,
    fit_cycle_error_model,
    group_reads,
)
from duplexumiconsensusreads_tpu.simulate import SimConfig, simulate_batch
from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams


def test_exact_grouping_recovers_molecules_ss():
    cfg = SimConfig(n_molecules=40, duplex=False, umi_error=0.0, seed=1)
    batch, truth = simulate_batch(cfg)
    fams = group_reads(batch, GroupingParams(strategy="exact", paired=False))
    # with no UMI errors, families == true (molecule) partition
    fam = np.asarray(fams.family_id)
    for f in range(int(fams.n_families)):
        mols = np.unique(truth.read_mol[fam == f])
        assert len(mols) == 1, "exact family mixes molecules"
    # each molecule maps to exactly one family
    for m in np.unique(truth.read_mol):
        fs = np.unique(fam[truth.read_mol == m])
        assert len(fs) == 1, "molecule split across families"


def test_adjacency_grouping_heals_umi_errors():
    cfg = SimConfig(
        n_molecules=30, duplex=False, umi_error=0.03, mean_family_size=6, seed=2
    )
    batch, truth = simulate_batch(cfg)
    exact = group_reads(batch, GroupingParams(strategy="exact"))
    adj = group_reads(batch, GroupingParams(strategy="adjacency", max_hamming=1))
    # adjacency must merge error-UMIs: strictly fewer families than exact
    assert int(adj.n_families) < int(exact.n_families)
    # and most reads should land in a family dominated by their true molecule
    fam = np.asarray(adj.family_id)
    correct = 0
    for f in range(int(adj.n_families)):
        mols, counts = np.unique(truth.read_mol[fam == f], return_counts=True)
        correct += counts.max()
    assert correct / batch.n_reads > 0.95


def test_ss_consensus_beats_raw_error_rate():
    cfg = SimConfig(
        n_molecules=50, duplex=False, base_error=0.02, mean_family_size=6, seed=3
    )
    batch, truth = simulate_batch(cfg)
    fams = group_reads(batch, GroupingParams(strategy="exact"))
    cons = call_consensus(batch, fams, ConsensusParams(mode="single_strand", min_reads=3))
    fam = np.asarray(fams.family_id)
    errs = total = 0
    for f in range(int(fams.n_families)):
        if not cons.valid[f]:
            continue
        mol = truth.read_mol[fam == f][0]
        called = cons.bases[f] < N_REAL_BASES
        total += called.sum()
        errs += (cons.bases[f][called] != truth.mol_seq[mol][called]).sum()
    assert total > 0
    err_rate = errs / total
    assert err_rate < cfg.base_error / 4, f"consensus err {err_rate} not better than raw"


def test_duplex_consensus_better_than_single_strand():
    cfg = SimConfig(
        n_molecules=120, duplex=True, base_error=0.08, mean_family_size=5, seed=4
    )
    batch, truth = simulate_batch(cfg)
    fams = group_reads(batch, GroupingParams(strategy="exact", paired=True))
    ss = call_consensus(batch, fams, ConsensusParams(mode="single_strand", min_reads=2))
    dx = call_consensus(
        batch, fams, ConsensusParams(mode="duplex", min_reads=2, min_duplex_reads=2)
    )

    mol = np.asarray(fams.molecule_id)
    fam = np.asarray(fams.family_id)

    def err_rate(cons, id_arr):
        errs = total = 0
        for f in range(len(cons.valid)):
            if not cons.valid[f]:
                continue
            sel = np.nonzero(id_arr == f)[0]
            true_mol = truth.read_mol[sel[0]]
            called = cons.bases[f] < N_REAL_BASES
            total += called.sum()
            errs += (cons.bases[f][called] != truth.mol_seq[true_mol][called]).sum()
        return errs / max(total, 1)

    e_ss = err_rate(ss, fam)
    e_dx = err_rate(dx, mol)
    assert e_dx < e_ss, f"duplex {e_dx} not better than ss {e_ss}"
    assert e_dx < 2e-3


def test_duplex_quality_boost_on_agreement():
    cfg = SimConfig(n_molecules=20, duplex=True, base_error=0.001, seed=5)
    batch, _ = simulate_batch(cfg)
    fams = group_reads(batch, GroupingParams(strategy="exact", paired=True))
    ss = call_consensus(batch, fams, ConsensusParams(mode="single_strand"))
    dx = call_consensus(batch, fams, ConsensusParams(mode="duplex"))
    # duplex quals on called cycles should (weakly) exceed either strand's typical qual
    assert dx.quals[dx.valid].mean() > ss.quals[ss.valid].mean()


def test_cycle_error_model_caps_late_cycles():
    cfg = SimConfig(
        n_molecules=80,
        duplex=False,
        base_error=0.002,
        cycle_error_slope=0.002,  # error grows with cycle
        mean_family_size=8,
        read_len=60,
        seed=6,
    )
    batch, _ = simulate_batch(cfg)
    fams = group_reads(batch, GroupingParams(strategy="exact"))
    ss = call_consensus(batch, fams, ConsensusParams(mode="single_strand"))
    cap = fit_cycle_error_model(batch, fams, ss)
    # fitted caps must decrease for late cycles (higher true error)
    assert cap[:10].mean() > cap[-10:].mean() + 3
    adj = apply_cycle_error_model(np.asarray(batch.quals), cap)
    assert (adj <= np.asarray(batch.quals)).all()
    assert (adj[:, -5:] <= cap[-5:][None, :]).all()


def test_min_reads_filters_small_families():
    cfg = SimConfig(n_molecules=30, duplex=False, mean_family_size=2, seed=7)
    batch, _ = simulate_batch(cfg)
    fams = group_reads(batch, GroupingParams(strategy="exact"))
    cons = call_consensus(batch, fams, ConsensusParams(min_reads=3))
    fam = np.asarray(fams.family_id)
    sizes = np.bincount(fam[fam >= 0], minlength=int(fams.n_families))
    np.testing.assert_array_equal(cons.valid, sizes >= 3)


def test_n_bases_carry_no_evidence():
    cfg = SimConfig(n_molecules=20, duplex=False, n_frac=0.2, seed=8)
    batch, _ = simulate_batch(cfg)
    fams = group_reads(batch, GroupingParams(strategy="exact"))
    cons = call_consensus(batch, fams, ConsensusParams())
    # depth at each cycle == number of non-N contributing reads
    fam = np.asarray(fams.family_id)
    f = 0
    sel = np.nonzero(fam == f)[0]
    depth_expected = (np.asarray(batch.bases)[sel] < N_REAL_BASES).sum(axis=0)
    np.testing.assert_array_equal(cons.depth[f], depth_expected)
    # zero-depth cycles are N
    assert (cons.bases[f][depth_expected == 0] == BASE_N).all()


def test_cluster_merges_where_directional_splits():
    """UMI-tools semantic distinction: two Hamming-1 neighbours with
    counts 5 and 4 satisfy NO directional edge (5 >= 2*4-1 and
    4 >= 2*5-1 both false) so adjacency keeps two molecules; the
    cluster method has no count condition, so the connected component
    collapses to ONE molecule seeded by the higher-count UMI."""
    import numpy as np

    from duplexumiconsensusreads_tpu.types import GroupingParams, ReadBatch

    n, L = 9, 20
    batch = ReadBatch.empty(n, L, 4)
    umi_a = np.array([0, 1, 2, 3], np.uint8)
    umi_b = np.array([0, 1, 2, 0], np.uint8)  # Hamming 1 from a
    batch.umi[:5] = umi_a
    batch.umi[5:] = umi_b
    batch.bases[:] = 1
    batch.quals[:] = 30
    batch.valid[:] = True
    batch.strand_ab[:] = True
    batch.pos_key[:] = 7
    adj = group_reads(batch, GroupingParams(strategy="adjacency"))
    clu = group_reads(batch, GroupingParams(strategy="cluster"))
    assert int(adj.n_molecules) == 2
    assert int(clu.n_molecules) == 1
    # every read joins the same cluster family
    assert len(set(np.asarray(clu.family_id)[np.asarray(batch.valid)])) == 1
