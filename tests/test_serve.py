"""serve/: the multi-job consensus service.

Every claim the serving layer makes is pinned to an observable
contract on tiny inputs:

  * outputs through the service are BYTE-IDENTICAL to one-shot
    ``stream_call_consensus`` runs of the same jobs (the soak
    acceptance), under preemption, priorities and concurrency;
  * a killed daemon loses no accepted job and double-runs none —
    whether the kill lands before admission, between accept and
    dispatch (the queue-journal crash-recovery satellite), or mid-job;
  * SIGTERM drains gracefully: in-flight work checkpoints, the queue
    journals, the process exits 0, and a restarted daemon finishes
    exactly the remaining work;
  * the service telemetry capture validates against the service schema
    and decomposes per job (check_trace / serve_report).
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from duplexumiconsensusreads_tpu.io import simulated_bam
from duplexumiconsensusreads_tpu.runtime import faults
from duplexumiconsensusreads_tpu.runtime.stream import stream_call_consensus
from duplexumiconsensusreads_tpu.serve import (
    ConsensusService,
    FairScheduler,
    SpoolQueue,
    client,
)
from duplexumiconsensusreads_tpu.serve.job import (
    job_params,
    spec_signature,
    validate_spec,
)
from duplexumiconsensusreads_tpu.serve.queue import (
    JobFenced,
    JournalLockTimeout,
)
from duplexumiconsensusreads_tpu.serve.scheduler import parse_class_depths
from duplexumiconsensusreads_tpu.serve.store import (
    STORE_MARKER,
    LocalLeaseStore,
    SharedFsLeaseStore,
    resolve_store,
)
from duplexumiconsensusreads_tpu.simulate import SimConfig
from duplexumiconsensusreads_tpu.telemetry import report as trace_report
from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams

pytestmark = pytest.mark.serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the same tiny streaming workload the chaos suite uses: ~7 chunks, so
# budgets/preemptions/kills all have room to land
CONFIG = dict(grouping="adjacency", mode="duplex", capacity=128, chunk_reads=90)
GP = GroupingParams(strategy="adjacency", paired=True)
CP = ConsensusParams(mode="duplex")

# every fault site the serving layer owns — the registry-pin test and
# the dutlint lease-discipline rule both anchor on this tuple, and the
# FLEET subset drives the per-site kill/takeover matrix below
SERVE_SITES = (
    "serve.accept", "serve.journal", "serve.preempt",
    "serve.lease", "serve.renew", "serve.expire", "serve.fence",
    "serve.hb", "serve.store",
    "serve.deadline", "serve.watchdog",
    "serve.split", "serve.merge",
)
FLEET_SITES = ("serve.lease", "serve.renew", "serve.expire", "serve.fence")


def test_serve_sites_registered():
    """The serving layer's site registry pin: KNOWN_SITES and this
    suite agree on exactly which sites serve/ owns."""
    assert set(SERVE_SITES) == {
        s for s in faults.KNOWN_SITES if s.startswith("serve.")
    }
    assert set(FLEET_SITES) <= set(SERVE_SITES)


@pytest.fixture(scope="module")
def sim(tmp_path_factory):
    """(input path, reference output bytes): what every service-run
    output must reproduce exactly. The one-shot reference carries the
    job's canonical provenance line — a service output's bytes are a
    pure function of (input, config), independent of which process
    (this one, a daemon, a restarted daemon) finished it."""
    from duplexumiconsensusreads_tpu.serve.job import serve_provenance

    d = tmp_path_factory.mktemp("serve")
    path = str(d / "in.bam")
    cfg = SimConfig(n_molecules=70, n_positions=9, umi_error=0.02, seed=31)
    simulated_bam(cfg, path=path, sort=True)
    ref = str(d / "ref.bam")
    rep = stream_call_consensus(
        path, ref, GP, CP, capacity=128, chunk_reads=90,
        provenance_cl=serve_provenance(CONFIG),
    )
    assert rep.n_chunks >= 3
    with open(ref, "rb") as f:
        return path, f.read()


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.uninstall()


def _spec(job_id="job-x", **over):
    d = {"job_id": job_id, "input": "/i.bam", "output": "/o.bam",
         "config": dict(CONFIG)}
    d.update(over)
    return d


# ------------------------------------------------------------- job specs

class TestJobSpec:
    def test_roundtrip_and_defaults_mirror_cli(self):
        spec = validate_spec(_spec(config={}))
        gp, cp, kw = job_params(spec)
        # the empty-config job runs exactly what a bare `call` would
        assert gp == GroupingParams(
            strategy="exact", max_hamming=1, count_ratio=2, paired=False
        )
        assert cp == ConsensusParams()
        assert kw["capacity"] == 2048 and kw["chunk_reads"] == 500_000
        assert kw["read_group"] == "A" and kw["mate_aware"] == "auto"

    def test_duplex_config_maps_to_params(self):
        gp, cp, kw = job_params(validate_spec(_spec()))
        assert gp.paired and cp.mode == "duplex"
        assert kw["capacity"] == 128 and kw["chunk_reads"] == 90

    @pytest.mark.parametrize("bad", [
        {"config": {"chunk_reads": 0}},          # whole-file: not servable
        {"config": {"grouping": "fuzzy"}},       # invalid choice
        {"config": {"frobnicate": 1}},           # unknown key
        {"priority": -1},
        {"priority": True},                      # bool is not a priority
        {"chaos": "bogus.site:1:oserror"},       # bad schedule
        {"job_id": ""},
        {"extra_field": 1},
    ])
    def test_rejects_bad_specs(self, bad):
        with pytest.raises(ValueError):
            validate_spec(_spec(**bad))

    def test_spec_signature_is_the_compile_identity(self):
        a = validate_spec(_spec())
        b = validate_spec(_spec(job_id="job-y", output="/other.bam"))
        c = validate_spec(_spec(job_id="job-z",
                                config={**CONFIG, "capacity": 256}))
        # same bucket spec -> same signature, capacity change -> new one
        assert spec_signature(a) == spec_signature(b)
        assert spec_signature(a) != spec_signature(c)


# ------------------------------------------------------------- scheduler

class TestFairScheduler:
    def test_priority_then_fifo_within_class(self):
        jobs = {
            "a": {"state": "queued", "priority": 1, "seq": 0},
            "b": {"state": "queued", "priority": 0, "seq": 5},
            "c": {"state": "queued", "priority": 1, "seq": 1},
        }
        assert FairScheduler.pick(jobs) == "b"  # urgent class first
        jobs["b"]["state"] = "done"
        assert FairScheduler.pick(jobs) == "a"  # FIFO inside class 1
        jobs["a"]["state"] = "running"
        assert FairScheduler.pick(jobs) == "c"
        jobs["c"]["state"] = "done"
        assert FairScheduler.pick(jobs) is None

    def test_budget_yield_only_to_equal_or_more_urgent(self):
        jobs = {
            "running0": {"state": "running", "priority": 0, "seq": 0},
            "waiting1": {"state": "queued", "priority": 1, "seq": 1},
        }
        # yielding to a strictly less urgent waiter would just re-pick
        # the yielder: no preemption
        assert not FairScheduler.others_waiting(jobs, "running0")
        assert FairScheduler.others_waiting(jobs, "waiting1") is False
        jobs["waiting1"]["priority"] = 0
        assert FairScheduler.others_waiting(jobs, "running0")

    def test_budget_validation(self):
        with pytest.raises(ValueError):
            FairScheduler(chunk_budget=-1)


# --------------------------------------------------------- state registry

class TestStateRegistry:
    """serve/states.py is the declared state machine; these pins are
    the behaviour contract of the PR that introduced it — the derived
    families must reproduce the pre-refactor literal tuples EXACTLY
    (same members, same order), or the fleet's fence/idle/compaction
    semantics changed. The TRANSITIONS walk doubles as the registry-pin
    coverage the state-machine lint rule's test-exercise leg reads."""

    def test_derived_views_reproduce_pre_refactor_tuples(self):
        from duplexumiconsensusreads_tpu.serve import states

        assert states.JOB_STATES == (
            "queued", "running", "done", "failed", "rejected",
            "expired", "quarantined", "splitting", "fanned", "merging",
        )
        assert states.CLAIMED_STATES == ("running", "splitting", "merging")
        assert states.OPEN_STATES == (
            "queued", "fanned", "running", "splitting", "merging",
        )
        assert states.TERMINAL_STATES == (
            "done", "failed", "rejected", "expired", "quarantined",
        )
        assert states.INITIAL_STATES == ("queued", "rejected")

    def test_transition_graph_is_well_formed(self):
        from duplexumiconsensusreads_tpu.serve.states import (
            INITIAL_STATES,
            JOB_STATES,
            TERMINAL_STATES,
            TRANSITIONS,
        )

        assert set(TRANSITIONS) == set(JOB_STATES)
        for src, succs in sorted(TRANSITIONS.items()):
            for dst in succs:
                assert dst in JOB_STATES, f"{src}->{dst}"
            # terminal means terminal: no outgoing edges
            if src in TERMINAL_STATES:
                assert succs == (), src
        # every state is reachable from admission
        seen = set(INITIAL_STATES)
        frontier = list(INITIAL_STATES)
        while frontier:
            for dst in TRANSITIONS[frontier.pop()]:
                if dst not in seen:
                    seen.add(dst)
                    frontier.append(dst)
        assert seen == set(JOB_STATES)

    def test_queue_re_exports_the_registry(self):
        # queue-side callers (and older imports) read the same objects
        from duplexumiconsensusreads_tpu.serve import queue, states

        assert queue.JOB_STATES is states.JOB_STATES
        assert queue.CLAIMED_STATES is states.CLAIMED_STATES
        assert queue.OPEN_STATES is states.OPEN_STATES
        assert queue.TERMINAL_STATES is states.TERMINAL_STATES
        assert queue.TRANSITIONS is states.TRANSITIONS
        # the client's wait-terminal view is the registry plus its one
        # client-side pseudo-state
        assert client.TERMINAL_STATES == states.TERMINAL_STATES + (
            "unknown",
        )


# ----------------------------------------------------------- spool queue

class TestSpoolQueue:
    def test_accept_journals_then_unlinks_and_dedupes(self, tmp_path):
        q = SpoolQueue(str(tmp_path))
        jid = client.submit(str(tmp_path), __file__, str(tmp_path / "o.bam"),
                            config=dict(CONFIG))
        inbox = tmp_path / "inbox" / f"{jid}.json"
        assert inbox.exists()
        spec, reason = q.accept_one(jid)
        assert spec is not None and reason is None
        assert not inbox.exists()
        assert q.jobs[jid]["state"] == "queued"
        # a fresh queue instance sees the durable journal
        q2 = SpoolQueue(str(tmp_path))
        assert q2.jobs[jid]["state"] == "queued"
        # duplicate submission file for an already-journaled id: cleaned
        # up, never double-entered (the kill-between-journal-and-unlink
        # window)
        inbox.write_text(json.dumps(q.jobs[jid]["spec"]))
        spec2, reason2 = q2.accept_one(jid)
        assert spec2 is None and reason2 is None
        assert not inbox.exists() and q2.jobs[jid]["seq"] == q.jobs[jid]["seq"]

    def test_bounded_admission_rejects_with_reason(self, tmp_path):
        q = SpoolQueue(str(tmp_path), max_queue=1)
        j1 = client.submit(str(tmp_path), __file__, str(tmp_path / "a.bam"),
                           config=dict(CONFIG))
        j2 = client.submit(str(tmp_path), __file__, str(tmp_path / "b.bam"),
                           config=dict(CONFIG))
        assert q.accept_one(j1)[0] is not None
        spec, reason = q.accept_one(j2)
        assert spec is None and "queue full" in reason
        assert q.status(j2)["state"] == "rejected"

    def test_invalid_submission_is_rejected_not_fatal(self, tmp_path):
        q = SpoolQueue(str(tmp_path))
        bad = tmp_path / "inbox" / "job-bad.json"
        bad.write_text('{"job_id": "job-bad"}')  # no input/output
        spec, reason = q.accept_one("job-bad")
        assert spec is None and "input" in reason
        assert q.status("job-bad")["state"] == "rejected"

    def test_torn_journal_is_discarded_never_fatal(self, tmp_path):
        (tmp_path / "queue.json").write_text('{"jobs": [garbage')
        q = SpoolQueue(str(tmp_path))
        assert q.jobs == {}

    def test_status_states(self, tmp_path):
        q = SpoolQueue(str(tmp_path))
        assert q.status("job-nope")["state"] == "unknown"
        jid = client.submit(str(tmp_path), __file__, str(tmp_path / "o.bam"),
                            config=dict(CONFIG))
        assert q.status(jid)["state"] == "submitted"

    def test_compaction_round_trip_preserves_leases_and_decisions(
        self, tmp_path
    ):
        """The compaction satellite: a save (which compacts) followed
        by a fresh load must leave non-terminal entries — INCLUDING
        their lease/token state — intact, so the reloaded journal
        yields identical scheduler decisions and identical fencing
        verdicts."""
        q = SpoolQueue(str(tmp_path), max_terminal_kept=1)
        for i in range(3):  # terminal ballast beyond the cap
            jid = client.submit(str(tmp_path), __file__,
                                str(tmp_path / f"t{i}.bam"),
                                config=dict(CONFIG))
            q.accept_one(jid)
            q.mark_failed(jid, f"ballast {i}")
        running = client.submit(str(tmp_path), __file__,
                                str(tmp_path / "run.bam"),
                                config=dict(CONFIG))
        q.accept_one(running)
        token = q.claim(running, "daemon-1", lease_s=60.0)
        waiting = []
        for pri in (1, 0):
            w = client.submit(str(tmp_path), __file__,
                              str(tmp_path / f"w{pri}.bam"),
                              config=dict(CONFIG), priority=pri)
            q.accept_one(w)
            waiting.append(w)
        pick_before = FairScheduler.pick(q.jobs)
        q.save()  # compacts the terminal ballast
        q2 = SpoolQueue(str(tmp_path), max_terminal_kept=1)
        # identical scheduler decision from the reloaded journal
        assert FairScheduler.pick(q2.jobs) == pick_before == waiting[1]
        # the running job's lease survived the rewrite verbatim
        e = q2.jobs[running]
        assert e["state"] == "running" and e["token"] == token == 1
        assert e["lease"]["owner"] == "daemon-1"
        assert e["lease"]["expires_m"] == q.jobs[running]["lease"]["expires_m"]
        # identical fencing verdicts: the current token passes, a stale
        # or foreign one is fenced
        q2.verify_lease(running, "daemon-1", token)
        with pytest.raises(JobFenced):
            q2.verify_lease(running, "daemon-1", token + 1)
        with pytest.raises(JobFenced):
            q2.verify_lease(running, "daemon-2", token)
        # terminal ballast compacted to the cap, open entries untouched
        n_terminal = sum(
            1 for e in q2.jobs.values() if e["state"] == "failed"
        )
        assert n_terminal == 1
        assert {running, *waiting} <= set(q2.jobs)

    def test_journal_compaction_bounds_terminal_entries(self, tmp_path):
        """A long-lived daemon's journal is rewritten+fsynced on every
        transition, so it must stay bounded: terminal entries beyond
        the cap compact away, and status() still answers for them from
        the durable results/ file."""
        q = SpoolQueue(str(tmp_path), max_terminal_kept=2)
        jids = []
        for i in range(4):
            jid = client.submit(
                str(tmp_path), __file__, str(tmp_path / f"o{i}.bam"),
                config=dict(CONFIG),
            )
            assert q.accept_one(jid)[0] is not None
            q.mark_failed(jid, f"boom {i}")
            jids.append(jid)
        on_disk = json.load(open(tmp_path / "queue.json"))
        assert set(on_disk["jobs"]) == set(jids[-2:])  # oldest 2 compacted
        st = q.status(jids[0])
        assert st["state"] == "failed" and st["compacted"]
        assert "boom 0" in st["result"]["error"]
        # open jobs are never compacted, whatever the cap
        live = client.submit(str(tmp_path), __file__,
                             str(tmp_path / "live.bam"), config=dict(CONFIG))
        q.accept_one(live)
        q.save()
        assert q.status(live)["state"] == "queued"


# --------------------------------------------------------------- service

def _submit_n(spool, in_path, tmp_path, n, priority=None, prefix="out"):
    jobs = []
    for i in range(n):
        out = str(tmp_path / f"{prefix}{i}.bam")
        jobs.append((
            client.submit(
                spool, in_path, out, config=dict(CONFIG),
                priority=(priority[i] if priority else 1),
            ),
            out,
        ))
    return jobs


def _events(trace_path):
    recs = trace_report.load_trace(trace_path)
    return recs, [r for r in recs if r.get("type") == "event"]


class TestServiceSoak:
    def test_three_jobs_byte_identical_and_observable(self, sim, tmp_path):
        """The acceptance soak: N>=3 jobs through the service match the
        one-shot reference byte for byte, the capture validates, and
        the client verbs answer."""
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        trace = str(tmp_path / "service.jsonl")
        jobs = _submit_n(spool, in_path, tmp_path, 3, priority=[1, 0, 1])
        svc = ConsensusService(
            spool, chunk_budget=2, trace_path=trace, heartbeat_s=0.05
        )
        snap = svc.run_until_idle()
        assert snap["jobs_done"] == 3 and snap["jobs_failed"] == 0
        for jid, out in jobs:
            with open(out, "rb") as f:
                assert f.read() == ref_bytes
            st = client.status(spool, jid)
            assert st["state"] == "done"
            assert st["result"]["n_consensus"] > 0
            assert client.wait(spool, jid, timeout_s=1)["state"] == "done"
        # the second+ jobs share the first job's bucket spec: warm
        assert svc.worker.n_spec_hits == 2 and svc.worker.n_spec_misses == 1
        # live metrics snapshot was maintained
        with open(os.path.join(spool, "metrics.json")) as f:
            metrics = json.load(f)
        assert metrics["jobs_done"] == 3
        assert set(metrics["job_seconds"]) == {j for j, _ in jobs}
        assert metrics["daemon_id"] == svc.daemon_id
        # per-class SLO surface: both priority classes carry queue-wait
        # and time-to-first-chunk percentiles
        lat = metrics["class_latency"]
        assert set(lat) == {"0", "1"}
        for row in lat.values():
            assert row["n_queue_wait"] >= 1 and row["n_ttfc"] >= 1
            assert row["queue_wait_p95_s"] >= row["queue_wait_p50_s"] >= 0
            assert row["ttfc_p95_s"] >= row["ttfc_p50_s"] >= 0
        # the capture validates as a service capture, with a summary
        recs, events = _events(trace)
        assert trace_report.validate_service_trace(recs) == []
        assert trace_report.capture_kind(recs) == "service"
        assert trace_report.summary_record(recs) is not None
        names = {e["name"] for e in events}
        assert {"job_accepted", "job_started", "job_completed"} <= names
        hb = [e for e in events if e["name"] == "heartbeat"]
        assert all("queue_depth" in e and "jobs_inflight" in e for e in hb)

    def test_check_trace_and_serve_report_cli(self, sim, tmp_path):
        in_path, _ = sim
        spool = str(tmp_path / "spool")
        trace = str(tmp_path / "svc.jsonl")
        _submit_n(spool, in_path, tmp_path, 2)
        ConsensusService(spool, chunk_budget=1, trace_path=trace).run_until_idle()
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "check_trace.py"),
             trace, "--require-summary"],
            capture_output=True, text=True, timeout=120,
        )
        assert p.returncode == 0, p.stderr
        assert "service capture" in p.stderr
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "serve_report.py"),
             trace, "--json"],
            capture_output=True, text=True, timeout=120,
        )
        assert p.returncode == 0, p.stderr
        rep = json.loads(p.stdout)
        assert rep["n_jobs"] == 2 and rep["n_done"] == 2
        assert rep["clean_shutdown"] is True
        assert rep["n_preemptions"] >= 1  # budget=1 with a waiter
        # human rendering exercises the same capture
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "serve_report.py"),
             trace],
            capture_output=True, text=True, timeout=120,
        )
        assert p.returncode == 0 and "2 jobs" in p.stdout

    def test_service_schema_rejects_anonymous_job_events(self, tmp_path):
        from duplexumiconsensusreads_tpu.telemetry.trace import TraceRecorder

        path = str(tmp_path / "bad.jsonl")
        tr = TraceRecorder(path, kind="service")
        tr.event("job_started", job="j1", lane="main")  # wrong lane
        tr.event("job_completed")  # no job at all
        tr.close()
        probs = trace_report.validate_service_trace(
            trace_report.load_trace(path)
        )
        assert any("lane 'job-j1'" in p for p in probs)
        assert any("without a job id" in p for p in probs)
        # and a RUN capture must not be accepted by the service schema
        run_tr = TraceRecorder(str(tmp_path / "run.jsonl"))
        run_tr.close()
        probs = trace_report.validate_service_trace(
            trace_report.load_trace(str(tmp_path / "run.jsonl"))
        )
        assert any('kind="service"' in p for p in probs)

    def test_preemption_interleaves_equal_priority_jobs(self, sim, tmp_path):
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        trace = str(tmp_path / "svc.jsonl")
        jobs = _submit_n(spool, in_path, tmp_path, 2)
        ConsensusService(spool, chunk_budget=1, trace_path=trace).run_until_idle()
        for _, out in jobs:
            with open(out, "rb") as f:
                assert f.read() == ref_bytes
        _, events = _events(trace)
        starts = [e["job"] for e in events if e["name"] == "job_started"]
        preempts = [e for e in events if e["name"] == "job_preempted"]
        assert len(preempts) >= 2
        assert all(p["reason"] == "budget" for p in preempts)
        # budget=1 with both jobs waiting: consecutive slices alternate
        # between the two jobs until one finishes
        flips = sum(1 for a, b in zip(starts, starts[1:]) if a != b)
        assert flips >= 2

    def test_failed_job_does_not_take_down_the_service(self, sim, tmp_path):
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        bad = client.submit(
            spool, __file__, str(tmp_path / "bad.bam"), config=dict(CONFIG)
        )  # a Python file is not a BAM: the slice must fail cleanly
        good, out = _submit_n(spool, in_path, tmp_path, 1)[0]
        svc = ConsensusService(spool, chunk_budget=0)
        snap = svc.run_until_idle()
        assert snap["jobs_failed"] == 1 and snap["jobs_done"] == 1
        assert client.status(spool, bad)["state"] == "failed"
        with open(out, "rb") as f:
            assert f.read() == ref_bytes
        # the failed slice compiled nothing, so it must NOT have warmed
        # its spec signature: the good job (same signature, ran second)
        # still counts as a cold start
        assert svc.worker.n_spec_hits == 0 and svc.worker.n_spec_misses == 2


class TestCrashRecovery:
    def test_kill_between_accept_and_dispatch_runs_exactly_once(
        self, sim, tmp_path
    ):
        """The queue-journal crash-recovery satellite: a kill at the
        lease claim (site serve.lease) lands AFTER the job is durably
        accepted and BEFORE any work was dispatched — the claim never
        persisted, so the journal still says queued. The restarted
        daemon must run it exactly once and produce the one-shot
        bytes."""
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        jid, out = _submit_n(spool, in_path, tmp_path, 1)[0]
        faults.install(faults.FaultPlan.parse("serve.lease:1:kill"))
        t1 = str(tmp_path / "svc1.jsonl")
        with pytest.raises(faults.InjectedKill):
            ConsensusService(spool, trace_path=t1).run_until_idle()
        # the job was durably accepted (journal #1) and never started
        assert SpoolQueue(spool).jobs[jid]["state"] == "queued"
        assert not os.path.exists(out)
        _, ev1 = _events(t1)
        assert [e for e in ev1 if e["name"] == "job_started"] == []
        # restart on the same spool: the job runs exactly once
        t2 = str(tmp_path / "svc2.jsonl")
        snap = ConsensusService(spool, trace_path=t2).run_until_idle()
        assert snap["jobs_done"] == 1
        with open(out, "rb") as f:
            assert f.read() == ref_bytes
        _, ev2 = _events(t2)
        assert len([e for e in ev2 if e["name"] == "job_started"]) == 1
        assert len([e for e in ev2 if e["name"] == "job_completed"]) == 1

    def test_kill_before_admission_loses_no_submission(self, sim, tmp_path):
        """Kill during the admission read itself: the inbox file is
        untouched, so restart re-admits and runs the job."""
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        jid, out = _submit_n(spool, in_path, tmp_path, 1)[0]
        faults.install(faults.FaultPlan.parse("serve.accept:1:kill"))
        with pytest.raises(faults.InjectedKill):
            ConsensusService(spool).run_until_idle()
        assert os.path.exists(
            os.path.join(spool, "inbox", jid + ".json")
        )
        snap = ConsensusService(spool).run_until_idle()
        assert snap["jobs_done"] == 1 and snap["jobs_accepted"] == 1
        with open(out, "rb") as f:
            assert f.read() == ref_bytes

    def test_kill_mid_job_resumes_from_checkpoint(self, sim, tmp_path):
        """Kill-holding-lease: a kill inside a running slice (stream
        site) leaves the job journaled RUNNING under the dead daemon's
        lease. The next daemon must detect the dead owner, take the
        lease over (bumping the fencing token), and converge to the
        one-shot bytes — the acceptance scenario, in-process."""
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        jid, out = _submit_n(spool, in_path, tmp_path, 1)[0]
        faults.install(faults.FaultPlan.parse("shard.write:3:kill"))
        with pytest.raises(faults.InjectedKill):
            ConsensusService(spool, daemon_id="victim").run_until_idle()
        entry = SpoolQueue(spool).jobs[jid]
        assert entry["state"] == "running"
        # the dead daemon's lease (token 1) is still in the journal
        assert entry["lease"]["owner"] == "victim" and entry["token"] == 1
        t2 = str(tmp_path / "svc2.jsonl")
        snap = ConsensusService(spool, trace_path=t2).run_until_idle()
        assert snap["jobs_done"] == 1 and snap["jobs_recovered"] == 1
        with open(out, "rb") as f:
            assert f.read() == ref_bytes
        # takeover bumped the token: the victim's lease is fenced off
        entry = SpoolQueue(spool).jobs[jid]
        assert entry["token"] == 2 and "lease" not in entry
        assert entry["slices"] == 2  # one victim slice + one takeover slice
        recs, ev2 = _events(t2)
        # the restart recorded both the takeover and the recovery decision
        tk = [e for e in ev2 if e["name"] == "lease_takeover"]
        assert len(tk) == 1 and tk[0]["job"] == jid
        assert tk[0]["reason"] == "dead-owner"
        assert any(
            e["name"] == "resume" and e.get("decision") == "requeued_running"
            for e in ev2
        )


class TestLeaseProtocol:
    """The lease/claim state machine on the bare queue — no service,
    no device: claims bump the fencing token, renewal is fenced,
    expiry/dead-owner leases reclaim, and every verdict comes from the
    durable journal (a fresh SpoolQueue sees the same thing)."""

    def _queued(self, tmp_path, name="job"):
        q = SpoolQueue(str(tmp_path))
        jid = client.submit(str(tmp_path), __file__,
                            str(tmp_path / f"{name}.bam"),
                            config=dict(CONFIG))
        assert q.accept_one(jid)[0] is not None
        return q, jid

    def test_claim_bumps_token_and_is_exclusive(self, tmp_path):
        q, jid = self._queued(tmp_path)
        token = q.claim(jid, "d1", lease_s=60.0)
        assert token == 1
        e = q.jobs[jid]
        assert e["state"] == "running" and e["lease"]["owner"] == "d1"
        assert e["lease"]["pid"] == os.getpid()
        # a second claim of a RUNNING job must lose, whoever asks
        assert q.claim(jid, "d2", lease_s=60.0) is None
        assert q.claim(jid, "d1", lease_s=60.0) is None
        # and another queue instance (another daemon) sees the lease
        assert SpoolQueue(str(tmp_path)).jobs[jid]["lease"]["owner"] == "d1"

    def test_verify_and_renew_are_fenced(self, tmp_path):
        q, jid = self._queued(tmp_path)
        token = q.claim(jid, "d1", lease_s=60.0)
        q.verify_lease(jid, "d1", token)
        before = q.jobs[jid]["lease"]["expires_m"]
        q.renew_lease(jid, "d1", token, lease_s=120.0)
        assert q.jobs[jid]["lease"]["expires_m"] > before
        for daemon, tok in (("d2", token), ("d1", token + 1), ("d1", 0)):
            with pytest.raises(JobFenced):
                q.verify_lease(jid, daemon, tok)
            with pytest.raises(JobFenced):
                q.renew_lease(jid, daemon, tok)

    def test_expired_lease_reclaims_and_next_claim_fences_zombie(
        self, tmp_path
    ):
        q, jid = self._queued(tmp_path)
        token = q.claim(jid, "d1", lease_s=0.05)
        time.sleep(0.08)
        rec = q.reclaim_dead("d2")
        assert [r["job_id"] for r in rec] == [jid]
        assert rec[0]["reason"] == "expired" and rec[0]["prev_owner"] == "d1"
        assert q.jobs[jid]["state"] == "queued" and "lease" not in q.jobs[jid]
        # takeover claim bumps the token past the zombie's
        token2 = q.claim(jid, "d2", lease_s=60.0)
        assert token2 == token + 1
        with pytest.raises(JobFenced):  # the zombie is fenced everywhere
            q.verify_lease(jid, "d1", token)
        with pytest.raises(JobFenced):
            q.requeue(jid, 1, back=False, daemon_id="d1", token=token)
        with pytest.raises(JobFenced):
            q.mark_done(jid, {"n": 1}, daemon_id="d1", token=token)
        # the journal is untouched by the fenced attempts
        assert q.jobs[jid]["state"] == "running"
        assert q.jobs[jid]["lease"]["owner"] == "d2"

    def test_live_lease_is_not_reclaimed(self, tmp_path):
        q, jid = self._queued(tmp_path)
        q.claim(jid, "d1", lease_s=60.0)
        # same pid, no liveness oracle: the owner could be a live
        # daemon in this process — only expiry may take it
        assert q.reclaim_dead("d2") == []
        # with a liveness oracle saying d1 is live: still protected
        assert q.reclaim_dead("d2", is_live=lambda d: d == "d1") == []
        # oracle says dead (in-process daemon unwound): reclaimed now
        rec = q.reclaim_dead("d2", is_live=lambda d: False)
        assert rec and rec[0]["reason"] == "dead-owner"

    def test_dead_pid_lease_is_reclaimed_immediately(self, tmp_path):
        q, jid = self._queued(tmp_path)
        q.claim(jid, "d1", lease_s=3600.0)
        # forge the lease onto a pid that is provably dead (a spawned
        # and reaped child), as a SIGKILLed daemon would leave it
        child = subprocess.Popen([sys.executable, "-c", "pass"])
        child.wait()
        with q._txn():
            q.jobs[jid]["lease"]["pid"] = child.pid
            q.save()
        rec = q.reclaim_dead("d2")
        assert rec and rec[0]["reason"] == "dead-owner"
        assert q.jobs[jid]["state"] == "queued"

    def test_legacy_running_entry_without_lease_is_reclaimed(self, tmp_path):
        """A pre-lease journal (or a torn claim) can say running with
        no lease at all: recovery must requeue it, not strand it."""
        q, jid = self._queued(tmp_path)
        with q._txn():
            q.jobs[jid]["state"] = "running"
            q.save()
        rec = q.reclaim_dead("d1")
        assert rec and rec[0]["reason"] == "no-lease"
        assert q.jobs[jid]["state"] == "queued"

    def test_done_requeue_and_fail_release_the_lease(self, tmp_path):
        q, jid = self._queued(tmp_path)
        token = q.claim(jid, "d1", lease_s=60.0)
        q.requeue(jid, 2, back=True, daemon_id="d1", token=token)
        e = q.jobs[jid]
        assert e["state"] == "queued" and "lease" not in e
        assert e["token"] == token  # token survives the release...
        token2 = q.claim(jid, "d1", lease_s=60.0)
        assert token2 == token + 1  # ...so the next claim still bumps it
        q.mark_done(jid, {"ok": 1}, daemon_id="d1", token=token2)
        e = q.jobs[jid]
        assert e["state"] == "done" and "lease" not in e


class TestFleet:
    """N daemons, one spool: exactly-once under concurrency, takeover
    of a killed daemon, and a fenced zombie — the tentpole acceptance
    scenarios."""

    def test_two_daemons_one_spool_exactly_once(self, sim, tmp_path):
        """Two services drain the same spool concurrently: every job
        completes exactly once (across BOTH captures), byte-identical
        to the one-shot reference."""
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        jobs = _submit_n(spool, in_path, tmp_path, 4)
        traces = [str(tmp_path / f"svc{i}.jsonl") for i in (0, 1)]
        svcs = [
            ConsensusService(
                spool, chunk_budget=2, poll_s=0.02, trace_path=traces[i],
                daemon_id=f"fleet-{i}",
            )
            for i in (0, 1)
        ]
        threads = [
            threading.Thread(target=s.run_until_idle, daemon=True)
            for s in svcs
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads)
        for jid, out in jobs:
            assert client.status(spool, jid)["state"] == "done"
            with open(out, "rb") as f:
                assert f.read() == ref_bytes
        completed = []
        for tp in traces:
            _, ev = _events(tp)
            completed += [
                e["job"] for e in ev if e["name"] == "job_completed"
            ]
        # exactly once ACROSS the fleet, not per daemon
        assert sorted(completed) == sorted(j for j, _ in jobs)
        assert sum(s.counters["jobs_done"] for s in svcs) == len(jobs)
        assert sum(s.counters["jobs_fenced"] for s in svcs) == 0

    @pytest.mark.parametrize("site,nth", [
        ("serve.lease", 1),   # dies claiming: job still queued
        ("serve.renew", 1),   # dies at the first commit's renewal
        ("serve.fence", 2),   # dies at a later commit's fence check
        ("serve.expire", 1),  # dies in the startup takeover sweep
        ("serve.deadline", 1),  # dies in the first deadline sweep
    ])
    def test_kill_at_fleet_site_then_restart_exactly_once(
        self, site, nth, sim, tmp_path
    ):
        """The per-site kill matrix over the lease protocol's own fault
        sites: wherever the daemon dies, a successor runs the job
        exactly once and byte-identical."""
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        jid, out = _submit_n(spool, in_path, tmp_path, 1)[0]
        faults.install(faults.FaultPlan.parse(f"{site}:{nth}:kill"))
        with pytest.raises(faults.InjectedKill):
            ConsensusService(spool, chunk_budget=1).run_until_idle()
        faults.uninstall()
        t2 = str(tmp_path / "svc2.jsonl")
        snap = ConsensusService(spool, trace_path=t2).run_until_idle()
        assert snap["jobs_done"] == 1 and snap["jobs_failed"] == 0
        with open(out, "rb") as f:
            assert f.read() == ref_bytes
        _, ev = _events(t2)
        assert len([e for e in ev if e["name"] == "job_completed"]) == 1

    def test_kill_on_watchdog_thread_takes_daemon_down_then_restart(
        self, sim, tmp_path
    ):
        """serve.watchdog's kill coverage: an injected kill on the
        watchdog thread's scan must take the DAEMON down whole (the
        heartbeat-thread contract), leaving durable state a restart
        completes exactly once. The slice is slowed so the run is
        guaranteed to span the watchdog's first tick."""
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        jid, out = _submit_n(spool, in_path, tmp_path, 1)[0]
        faults.install(faults.FaultPlan.parse("serve.watchdog:1:kill"))
        svc = ConsensusService(spool, chunk_budget=0, poll_s=0.05)
        orig = svc.worker.run_slice

        def slow_run_slice(spec, budget, should_yield, drain_event,
                           lease=None):
            time.sleep(0.6)  # outlive the watchdog's first 0.25s tick
            return orig(spec, budget, should_yield, drain_event,
                        lease=lease)

        svc.worker.run_slice = slow_run_slice
        with pytest.raises(faults.InjectedKill):
            svc.run_until_idle()
        faults.uninstall()
        t2 = str(tmp_path / "svc2.jsonl")
        snap = ConsensusService(spool, trace_path=t2).run_until_idle()
        assert snap["jobs_done"] == 1 and snap["jobs_failed"] == 0
        with open(out, "rb") as f:
            assert f.read() == ref_bytes
        _, ev = _events(t2)
        assert len([e for e in ev if e["name"] == "job_completed"]) == 1

    def test_zombie_daemon_is_fenced_after_expiry_takeover(
        self, sim, tmp_path
    ):
        """The zombie acceptance scenario: daemon A pauses mid-job
        (renewals stop, lease expires), daemon B takes the job over and
        finishes it, then A wakes up — its next commit must be fenced
        by the stale token, with zero corrupted outputs and exactly one
        completion."""
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        jid, out = _submit_n(spool, in_path, tmp_path, 1)[0]
        t_a = str(tmp_path / "svcA.jsonl")
        svc_a = ConsensusService(
            spool, chunk_budget=1, trace_path=t_a, poll_s=0.05,
            lease_s=0.4, daemon_id="daemon-A",
        )
        paused = threading.Event()
        resume = threading.Event()
        orig = svc_a.worker.run_slice

        def pausing_run_slice(spec, budget, should_yield, drain_event,
                              lease=None):
            # the budget check consults should_yield right after the
            # first fresh chunk commit — a deterministic mid-job pause
            # point with the lease held and renewals stopped
            def pause_then_no_yield():
                paused.set()
                resume.wait(timeout=120)
                return False

            return orig(spec, 1, pause_then_no_yield, drain_event,
                        lease=lease)

        svc_a.worker.run_slice = pausing_run_slice
        box = {}
        th = threading.Thread(
            target=lambda: box.setdefault("snap", svc_a.run_until_idle()),
            daemon=True,
        )
        th.start()
        assert paused.wait(timeout=120), "daemon A never reached its pause"
        # A is now a zombie: lease held, renewals stopped. Wait out the
        # lease, then let daemon B take over and finish the job.
        time.sleep(0.5)
        t_b = str(tmp_path / "svcB.jsonl")
        snap_b = ConsensusService(
            spool, trace_path=t_b, poll_s=0.05, daemon_id="daemon-B",
        ).run_until_idle()
        assert snap_b["jobs_done"] == 1 and snap_b["jobs_recovered"] == 1
        # wake the zombie: its very next durable commit must fence
        resume.set()
        th.join(timeout=120)
        assert not th.is_alive() and "snap" in box
        snap_a = box["snap"]
        assert snap_a["jobs_fenced"] == 1
        assert snap_a["jobs_done"] == 0 and snap_a["jobs_failed"] == 0
        # zero corrupted outputs: the published BAM is byte-identical
        # and the journal records B's completion under B's token
        with open(out, "rb") as f:
            assert f.read() == ref_bytes
        entry = SpoolQueue(spool).jobs[jid]
        assert entry["state"] == "done" and entry["token"] == 2
        _, ev_a = _events(t_a)
        _, ev_b = _events(t_b)
        completed = [
            e for e in ev_a + ev_b if e["name"] == "job_completed"
        ]
        assert len(completed) == 1  # exactly once, by B
        tk = [e for e in ev_b if e["name"] == "lease_takeover"]
        assert len(tk) == 1 and tk[0]["reason"] == "expired"
        assert any(e["name"] == "job_fenced" for e in ev_a)

    def test_two_subprocess_daemons_kill_and_takeover(self, sim, tmp_path):
        """The real thing: daemon A (subprocess) claims the job and is
        SIGKILLed mid-slice; daemon B on the same spool detects the
        dead owner, takes the lease over, and finishes exactly once,
        byte-identical."""
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        jid, out = _submit_n(spool, in_path, tmp_path, 1)[0]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "duplexumiconsensusreads_tpu.serve.daemon",
             spool, "--poll", "0.05", "--heartbeat", "0.2",
             "--lease", "30", "--daemon-id", "sub-A"],
            env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        try:
            deadline = time.monotonic() + 120
            claimed = False
            while time.monotonic() < deadline:
                st = client.status(spool, jid)
                if st.get("state") == "running" and st.get("lease"):
                    claimed = True
                    break
                if proc.poll() is not None:
                    break
                time.sleep(0.05)
            assert claimed, (
                proc.communicate()[1] if proc.poll() is not None
                else "job never claimed"
            )
            proc.kill()  # SIGKILL: no drain, the lease stays journaled
            proc.communicate()
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.communicate()
        st = client.status(spool, jid)
        assert st["state"] == "running" and st["lease"]["owner"] == "sub-A"
        # daemon B: the owner pid is provably dead, so takeover is
        # immediate — no 30s lease wait
        p2 = subprocess.run(
            [sys.executable, "-m", "duplexumiconsensusreads_tpu.serve.daemon",
             spool, "--once", "--poll", "0.05", "--heartbeat", "0",
             "--daemon-id", "sub-B"],
            env=env, cwd=REPO, capture_output=True, text=True, timeout=300,
        )
        assert p2.returncode == 0, p2.stderr
        st = client.status(spool, jid)
        assert st["state"] == "done" and st["token"] == 2
        with open(out, "rb") as f:
            assert f.read() == ref_bytes
        # each daemon owns service.<daemon_id>.trace.jsonl (per-daemon
        # default since the fleet flight recorder — members must not
        # rotate each other's live captures); B's holds the takeover
        # and the single completion
        b_trace = os.path.join(spool, "service.sub-B.trace.jsonl")
        recs, ev = _events(b_trace)
        assert trace_report.validate_service_trace(recs) == []
        # the capture names its writer — the stitcher's correlation key
        assert recs[0]["daemon_id"] == "sub-B" and "epoch_m" in recs[0]
        assert len([e for e in ev if e["name"] == "job_completed"]) == 1
        tk = [e for e in ev if e["name"] == "lease_takeover"]
        assert len(tk) == 1 and tk[0]["reason"] == "dead-owner"
        assert tk[0]["prev_owner"] == "sub-A"
        # and serve_report surfaces the takeover (not just the raw event)
        p3 = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "serve_report.py"),
             b_trace, "--json"],
            capture_output=True, text=True, timeout=120,
        )
        assert p3.returncode == 0, p3.stderr
        rep = json.loads(p3.stdout)
        assert rep["n_takeovers"] == 1 and rep["n_done"] == 1
        assert rep["jobs"][jid]["takeovers"] == 1
        assert rep["jobs"][jid]["takeover_reason"] == "dead-owner"


class TestDeadlines:
    """Job deadlines: admission stamps a monotonic expiry, the
    scheduler refuses expired picks, the sweep journals overdue queued
    jobs terminal `expired` with a durable reason, and a running slice
    aborts at its next checkpoint boundary preserving the committed
    prefix byte-for-byte."""

    def test_deadline_stamped_monotonic_and_swept(self, tmp_path):
        q = SpoolQueue(str(tmp_path))
        jid = client.submit(str(tmp_path), __file__,
                            str(tmp_path / "o.bam"),
                            config=dict(CONFIG), deadline_s=60.0)
        assert q.accept_one(jid)[0] is not None
        e = q.jobs[jid]
        assert e["deadline_m"] == pytest.approx(
            time.monotonic() + 60.0, abs=2.0
        )
        assert q.expire_deadlines() == []  # not due yet
        # deadline-aware pick: refused once past, claimable before
        assert FairScheduler.pick(q.jobs, now=e["deadline_m"] + 1) is None
        assert FairScheduler.pick(q.jobs, now=e["deadline_m"] - 1) == jid
        assert FairScheduler.pick(q.jobs) == jid  # no-now callers unchanged
        # force it overdue; the sweep journals terminal expired durably
        with q._txn():
            q.jobs[jid]["deadline_m"] = round(time.monotonic() - 1, 3)
            q.save()
        exp = q.expire_deadlines()
        assert [r["job_id"] for r in exp] == [jid]
        st = SpoolQueue(str(tmp_path)).status(jid)  # fresh load: durable
        assert st["state"] == "expired"
        assert st["result"]["expired"] is True
        assert "deadline passed" in st["error"]

    def test_daemon_default_deadline_applies_at_admission(self, tmp_path):
        q = SpoolQueue(str(tmp_path), default_deadline_s=30.0)
        jid = client.submit(str(tmp_path), __file__,
                            str(tmp_path / "a.bam"), config=dict(CONFIG))
        q.accept_one(jid)
        assert q.jobs[jid]["deadline_m"] == pytest.approx(
            time.monotonic() + 30.0, abs=2.0
        )
        # a job's own deadline wins over the daemon default
        jid2 = client.submit(str(tmp_path), __file__,
                             str(tmp_path / "b.bam"),
                             config=dict(CONFIG), deadline_s=300.0)
        q.accept_one(jid2)
        assert q.jobs[jid2]["deadline_m"] == pytest.approx(
            time.monotonic() + 300.0, abs=2.0
        )

    def test_rejects_bad_deadline(self):
        for bad in (0, -1, True, "soon"):
            with pytest.raises(ValueError, match="deadline_s"):
                validate_spec(_spec(deadline_s=bad))

    def test_overdue_queued_job_expires_before_running(self, sim, tmp_path):
        """A deadline that passes while the job waits in the queue:
        the sweep journals it terminal expired — it is never claimed,
        never started, and the client learns why."""
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        trace = str(tmp_path / "svc.jsonl")
        # job A (no deadline) runs first — same priority, lower seq, so
        # the single worker always claims it in the admission pass —
        # and job B's 1ms deadline lapses while A holds the device
        # (ANY A runtime exceeds it, warm runs included): by the next
        # scheduler pass B is overdue and must be swept, never claimed
        jid_a, out_a = _submit_n(spool, in_path, tmp_path, 1, prefix="a")[0]
        jid_b = client.submit(spool, in_path, str(tmp_path / "b.bam"),
                              config=dict(CONFIG), deadline_s=0.001)
        svc = ConsensusService(spool, chunk_budget=0, trace_path=trace)
        snap = svc.run_until_idle()
        assert snap["jobs_done"] == 1 and snap["jobs_expired"] == 1
        st = client.status(spool, jid_b)
        assert st["state"] == "expired"
        assert "before the job could run" in st["error"]
        assert st["result"]["expired"] is True
        assert not os.path.exists(str(tmp_path / "b.bam"))
        with open(out_a, "rb") as f:
            assert f.read() == ref_bytes
        _, ev = _events(trace)
        assert [e["job"] for e in ev if e["name"] == "job_expired"] == [jid_b]
        assert all(
            e["job"] != jid_b for e in ev if e["name"] == "job_started"
        )
        # expired is terminal: --wait returns immediately, not forever
        assert client.wait(spool, jid_b, timeout_s=5)["state"] == "expired"

    def test_running_job_aborts_at_chunk_boundary_and_resume_skips(
        self, sim, tmp_path
    ):
        """A running slice whose deadline passes aborts at the NEXT
        checkpoint boundary (the commit path's deadline check), the
        job journals terminal expired, and the committed chunk prefix
        survives byte-identical — a re-submitted job RESUMES it (the
        manifest verifies every shard), it never splices or recomputes
        the prefix."""
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        jid, out = _submit_n(spool, in_path, tmp_path, 1)[0]
        t1 = str(tmp_path / "svc1.jsonl")
        svc = ConsensusService(spool, chunk_budget=0, trace_path=t1)
        orig = svc.worker.run_slice

        def expiring_run_slice(spec, budget, should_yield, drain_event,
                               lease=None):
            # deadline already passed when the slice starts: the first
            # chunk commits (mark durable), then the boundary check
            # aborts — deterministic, no timing games
            lease.deadline_m = time.monotonic()
            return orig(spec, budget, should_yield, drain_event,
                        lease=lease)

        svc.worker.run_slice = expiring_run_slice
        snap = svc.run_until_idle()
        assert snap["jobs_expired"] == 1 and snap["jobs_done"] == 0
        assert snap["jobs_failed"] == 0  # expiry is a verdict, not a crash
        st = client.status(spool, jid)
        assert st["state"] == "expired"
        assert "checkpoint preserved" in st["error"]
        assert not os.path.exists(out)  # never finalised
        # the committed prefix is preserved for a future resume
        assert os.path.exists(out + ".ckpt")
        with open(out + ".ckpt") as f:
            n_committed = len(json.load(f)["done"])
        assert n_committed >= 1
        _, ev = _events(t1)
        exp = [e for e in ev if e["name"] == "job_expired"]
        assert len(exp) == 1 and exp[0]["chunks_done"] == n_committed
        # re-submission resumes the preserved checkpoint
        jid2 = client.submit(spool, in_path, out, config=dict(CONFIG))
        snap2 = ConsensusService(spool, chunk_budget=0).run_until_idle()
        assert snap2["jobs_done"] == 1
        st2 = client.status(spool, jid2)
        assert st2["result"]["n_chunks_skipped"] >= n_committed
        with open(out, "rb") as f:
            assert f.read() == ref_bytes


class TestWatchdog:
    """The stuck-run watchdog: a running job whose current chunk makes
    no durable progress for watchdog_s is abort-requeued through the
    lease/fence path — the one hang lease expiry cannot see, because a
    wedged device step keeps the heartbeat renewing the lease."""

    def test_reclaim_stalled_requeues_and_counts_crash(self, tmp_path):
        q = SpoolQueue(str(tmp_path))
        jid = client.submit(str(tmp_path), __file__,
                            str(tmp_path / "o.bam"), config=dict(CONFIG))
        q.accept_one(jid)
        token = q.claim(jid, "d1", lease_s=3600.0)
        assert q.reclaim_stalled(None) == []  # disabled: never fires
        assert q.reclaim_stalled(60.0) == []  # fresh progress: healthy
        with q._txn():
            q.jobs[jid]["progress_m"] = round(time.monotonic() - 10, 3)
            q.save()
        rec = q.reclaim_stalled(5.0)
        assert len(rec) == 1 and rec[0]["reason"] == "stalled"
        assert rec[0]["stalled_s"] > 5.0
        assert rec[0]["crash_count"] == 1 and "quarantined" not in rec[0]
        e = q.jobs[jid]
        assert e["state"] == "queued" and "lease" not in e
        assert e["crash_count"] == 1 and e["token"] == token
        # the next claim bumps the token: the wedged holder is fenced
        token2 = q.claim(jid, "d2", lease_s=3600.0)
        assert token2 == token + 1
        with pytest.raises(JobFenced):
            q.verify_lease(jid, "d1", token)

    def test_wedged_slice_is_watchdog_requeued_and_finished_elsewhere(
        self, sim, tmp_path
    ):
        """In-process acceptance: daemon A's slice wedges mid-chunk
        (lease renewed by commits until the wedge, then nothing), A's
        own watchdog abort-requeues the job, daemon B completes it
        byte-identical, and A's wedged slice — woken later — is fenced
        before it can commit a byte."""
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        jid, out = _submit_n(spool, in_path, tmp_path, 1)[0]
        t_a = str(tmp_path / "svcA.jsonl")
        svc_a = ConsensusService(
            spool, chunk_budget=1, trace_path=t_a, poll_s=0.05,
            lease_s=3600.0,  # expiry can NEVER explain the takeover
            # well above a healthy warm chunk's commit cadence (the
            # fixture already compiled in this process), well below the
            # test's patience: only the wedge can trip it
            watchdog_s=1.5, daemon_id="wd-A",
        )
        wedged = threading.Event()
        resume = threading.Event()
        orig = svc_a.worker.run_slice

        def wedging_run_slice(spec, budget, should_yield, drain_event,
                              lease=None):
            # the budget check consults should_yield right after the
            # first fresh chunk commit: a deterministic wedge point
            # with the lease held and durable progress stopped
            def wedge_then_no_yield():
                wedged.set()
                resume.wait(timeout=120)
                return False

            return orig(spec, 1, wedge_then_no_yield, drain_event,
                        lease=lease)

        svc_a.worker.run_slice = wedging_run_slice
        box = {}
        th = threading.Thread(
            target=lambda: box.setdefault("snap", svc_a.run_until_idle()),
            daemon=True,
        )
        th.start()
        assert wedged.wait(timeout=120), "daemon A never wedged"
        # the watchdog must requeue the stalled job while A's worker is
        # still wedged inside it
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            entry = SpoolQueue(spool).jobs.get(jid, {})
            if entry.get("state") == "queued":
                break
            time.sleep(0.05)
        assert entry.get("state") == "queued", "watchdog never fired"
        assert entry.get("crash_count") == 1
        # daemon B finishes the job (fresh claim bumps the token)
        t_b = str(tmp_path / "svcB.jsonl")
        snap_b = ConsensusService(
            spool, trace_path=t_b, poll_s=0.05, watchdog_s=0,
            daemon_id="wd-B",
        ).run_until_idle()
        assert snap_b["jobs_done"] == 1
        # wake the wedged slice: its next commit must fence
        resume.set()
        th.join(timeout=120)
        assert not th.is_alive() and "snap" in box
        snap_a = box["snap"]
        assert snap_a["watchdog_fired"] == 1
        assert snap_a["jobs_fenced"] == 1 and snap_a["jobs_done"] == 0
        with open(out, "rb") as f:
            assert f.read() == ref_bytes
        entry = SpoolQueue(spool).jobs[jid]
        assert entry["state"] == "done" and entry["token"] == 2
        _, ev_a = _events(t_a)
        wd = [e for e in ev_a if e["name"] == "watchdog_fired"]
        assert len(wd) == 1 and wd[0]["job"] == jid
        assert wd[0]["stalled_s"] > 1.5
        assert any(e["name"] == "job_fenced" for e in ev_a)
        _, ev_b = _events(t_b)
        done = [e for e in ev_a + ev_b if e["name"] == "job_completed"]
        assert len(done) == 1  # exactly once, by B

    def test_sigstopped_worker_subprocess_is_watchdog_requeued(
        self, sim, tmp_path
    ):
        """The real thing: daemon A (subprocess) claims the job and is
        SIGSTOPped mid-slice — its pid stays alive and its lease
        (3600s) never expires, so ONLY the watchdog path can free the
        job. Daemon B runs with an explicit --watchdog and must
        requeue + complete it byte-identical; A is fenced off by the
        token bump whenever it wakes."""
        import fcntl

        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        jid, out = _submit_n(spool, in_path, tmp_path, 1)[0]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "duplexumiconsensusreads_tpu.serve.daemon",
             spool, "--poll", "0.05", "--heartbeat", "0.2",
             "--lease", "3600", "--watchdog", "0",
             "--daemon-id", "stop-A"],
            env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )

        def flock_free(timeout_s=2.0):
            # a STOPPED process keeps any flock it holds — make sure A
            # was not frozen inside a journal transaction before we let
            # B (which must take that lock) anywhere near the spool
            fd = os.open(os.path.join(spool, "journal.lock"),
                         os.O_CREAT | os.O_RDWR, 0o644)
            try:
                t_end = time.monotonic() + timeout_s
                while time.monotonic() < t_end:
                    try:
                        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                        fcntl.flock(fd, fcntl.LOCK_UN)
                        return True
                    except OSError:
                        time.sleep(0.02)
                return False
            finally:
                os.close(fd)

        try:
            deadline = time.monotonic() + 120
            claimed = False
            while time.monotonic() < deadline:
                st = client.status(spool, jid)
                if st.get("state") == "running" and st.get("lease"):
                    claimed = True
                    break
                if proc.poll() is not None:
                    break
                time.sleep(0.05)
            assert claimed, (
                proc.communicate()[1] if proc.poll() is not None
                else "job never claimed"
            )
            for _ in range(20):
                proc.send_signal(signal.SIGSTOP)
                if flock_free():
                    break
                proc.send_signal(signal.SIGCONT)  # frozen mid-txn: retry
                time.sleep(0.05)
            else:
                pytest.fail("could not stop daemon A outside a journal txn")
            # daemon B: lease is live (A renews nothing but 3600s runs),
            # pid alive (stopped != dead) — only --watchdog frees the
            # job. A generous threshold + a high crash bound keep B's
            # own (cold-start) chunks from self-tripping the watchdog
            # into a quarantine on a slow CI host.
            p2 = subprocess.run(
                [sys.executable, "-m",
                 "duplexumiconsensusreads_tpu.serve.daemon",
                 spool, "--once", "--poll", "0.05", "--heartbeat", "0",
                 "--watchdog", "4.0", "--max-crashes", "50",
                 "--daemon-id", "stop-B"],
                env=env, cwd=REPO, capture_output=True, text=True,
                timeout=300,
            )
            assert p2.returncode == 0, p2.stderr
        finally:
            if proc.poll() is None:
                proc.kill()  # SIGKILL terminates a stopped process
                proc.communicate()
        st = client.status(spool, jid)
        assert st["state"] == "done" and st["token"] >= 2
        assert st["crash_count"] >= 1
        with open(out, "rb") as f:
            assert f.read() == ref_bytes
        recs, ev = _events(
            os.path.join(spool, "service.stop-B.trace.jsonl")
        )
        assert trace_report.validate_service_trace(recs) == []
        wd = [e for e in ev if e["name"] == "watchdog_fired"]
        assert len(wd) >= 1 and wd[0]["job"] == jid
        assert len([e for e in ev if e["name"] == "job_completed"]) == 1


class TestPoisonQuarantine:
    """Poison-job quarantine: a job that deterministically kills its
    worker must stop re-entering the queue after max_crashes unclean
    aborts — journaled terminal `quarantined` with a durable diagnosis
    bundle, exactly-once semantics intact, zero re-runs afterward."""

    def test_poison_job_quarantined_after_exactly_max_crashes(
        self, sim, tmp_path
    ):
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        poison_out = str(tmp_path / "poison.bam")
        poison_trace = str(tmp_path / "poison.trace.jsonl")
        # the poison: an injected hard kill at its first shard write,
        # every time any daemon runs it (per-job plans are per-daemon)
        poison = client.submit(
            spool, in_path, poison_out, config=dict(CONFIG),
            chaos="shard.write:1:kill", trace=poison_trace,
        )
        healthy, healthy_out = _submit_n(
            spool, in_path, tmp_path, 1, prefix="healthy"
        )[0]
        deaths = 0
        final_snap = None
        final_trace = None
        for i in range(8):  # bounded: must converge well before this
            # daemons that will RUN the poison slice get no service
            # capture: the job's own trace recorder must be the global
            # hook while its slice runs, so the injected fault lands in
            # the JOB capture — which is what the quarantine diagnosis
            # bundle tails
            t = str(tmp_path / f"svc{i}.jsonl") if deaths >= 3 else None
            svc = ConsensusService(
                spool, chunk_budget=0, poll_s=0.05, trace_path=t,
                daemon_id=f"pd-{i}",
            )
            try:
                final_snap = svc.run_until_idle()
                final_trace = t
                break
            except faults.InjectedKill:
                deaths += 1  # the poison killed this daemon; next picks up
        else:
            pytest.fail("fleet never converged past the poison job")
        # exactly max_crashes (default 3) daemons died to the poison
        assert deaths == 3
        st = client.status(spool, poison)
        assert st["state"] == "quarantined"
        assert "quarantined after 3 crashed runs" in st["error"]
        # the diagnosis bundle is durable and names the poison
        diag = st["result"]["diagnosis"]
        assert diag["crash_count"] == 3 and diag["max_crashes"] == 3
        assert diag["last_abort"] == "dead-owner"
        assert len(diag["lease_history"]) == 3
        assert [h["owner"] for h in diag["lease_history"]] == [
            "pd-0", "pd-1", "pd-2"
        ]
        assert diag["last_fault_site"] == "shard.write"
        assert diag["trace_tail"]  # the capture tail rides along
        # quarantined is terminal for clients too
        assert client.wait(spool, poison, timeout_s=5)["state"] == "quarantined"
        assert not os.path.exists(poison_out)
        # the healthy job survived the carnage, byte-identical
        assert final_snap["jobs_done"] == 1
        assert final_snap["jobs_quarantined"] == 1
        assert client.status(spool, healthy)["state"] == "done"
        with open(healthy_out, "rb") as f:
            assert f.read() == ref_bytes
        # exactly-once accounting: the poison ran exactly max_crashes
        # slices (the journal's slice counter is the fleet-wide truth),
        # and the quarantining daemon recorded the verdict
        assert SpoolQueue(spool).jobs[poison]["slices"] == 3
        assert final_trace is not None
        _, ev = _events(final_trace)
        quarantined = [e for e in ev if e["name"] == "job_quarantined"]
        assert len(quarantined) == 1 and quarantined[0]["job"] == poison
        assert quarantined[0]["crash_count"] == 3
        # zero re-runs afterward: a fresh daemon finds nothing to do
        t_after = str(tmp_path / "after.jsonl")
        snap = ConsensusService(
            spool, trace_path=t_after, daemon_id="pd-after"
        ).run_until_idle()
        assert snap["jobs_quarantined"] == 1  # rebuilt from the journal
        _, ev = _events(t_after)
        assert [e for e in ev if e["name"] == "job_started"] == []

    def test_clean_preemptions_never_count_toward_quarantine(
        self, sim, tmp_path
    ):
        """Budget preemptions are the scheduler working as designed:
        a job preempted many times must carry no crash_count at all."""
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        jobs = _submit_n(spool, in_path, tmp_path, 2)
        svc = ConsensusService(spool, chunk_budget=1)
        snap = svc.run_until_idle()
        assert snap["preemptions"] >= 2 and snap["jobs_quarantined"] == 0
        for jid, out in jobs:
            entry = SpoolQueue(spool).jobs[jid]
            assert entry.get("crash_count", 0) == 0
            with open(out, "rb") as f:
                assert f.read() == ref_bytes


class TestDiskPressure:
    """Disk-pressure degradation: admission sheds below the low-water
    mark with a journaled `shed: disk` reason, after a grace GC pass
    over terminal jobs' shard/checkpoint litter."""

    def _queued_terminal_with_litter(self, tmp_path):
        q = SpoolQueue(str(tmp_path))
        jid = client.submit(str(tmp_path), __file__,
                            str(tmp_path / "t0.bam"), config=dict(CONFIG))
        q.accept_one(jid)
        q.mark_failed(jid, "boom")
        out = q.jobs[jid]["spec"]["output"]
        with open(out + ".ckpt", "w") as f:
            f.write('{"done": {}}')
        os.makedirs(out + ".shards", exist_ok=True)
        with open(os.path.join(out + ".shards", "chunk000000.recs"),
                  "wb") as f:
            f.write(b"x" * 4096)
        with open(out + ".tmp", "wb") as f:
            f.write(b"y" * 2048)
        return q, out

    def test_low_water_sheds_with_disk_reason(self, tmp_path, monkeypatch):
        from duplexumiconsensusreads_tpu.serve import queue as queue_mod

        q = SpoolQueue(str(tmp_path), min_free_bytes=64 << 20)
        jid = client.submit(str(tmp_path), __file__,
                            str(tmp_path / "o.bam"), config=dict(CONFIG))
        monkeypatch.setattr(queue_mod, "free_bytes", lambda p: 1 << 20)
        spec, reason = q.accept_one(jid)
        assert spec is None and reason.startswith("shed: disk")
        st = q.status(jid)
        assert st["state"] == "rejected" and st["shed"] is True
        assert "low-water" in st["error"]

    def test_grace_gc_frees_terminal_litter_then_admits(
        self, tmp_path, monkeypatch
    ):
        from duplexumiconsensusreads_tpu.serve import queue as queue_mod

        q, out = self._queued_terminal_with_litter(tmp_path)
        q.min_free_bytes = 64 << 20
        # first probe low, post-GC probe healthy: the job is ADMITTED
        # and the terminal litter is gone
        probes = iter([1 << 20, 1 << 30])
        monkeypatch.setattr(
            queue_mod, "free_bytes", lambda p: next(probes, 1 << 30)
        )
        jid = client.submit(str(tmp_path), __file__,
                            str(tmp_path / "new.bam"), config=dict(CONFIG))
        spec, reason = q.accept_one(jid)
        assert spec is not None and reason is None
        assert not os.path.exists(out + ".ckpt")
        assert not os.path.exists(out + ".shards")
        assert not os.path.exists(out + ".tmp")

    def test_gc_only_touches_terminal_jobs_litter(self, tmp_path):
        q, out = self._queued_terminal_with_litter(tmp_path)
        # an OPEN job's checkpoint must survive any GC pass
        live = client.submit(str(tmp_path), __file__,
                             str(tmp_path / "live.bam"), config=dict(CONFIG))
        q.accept_one(live)
        live_out = q.jobs[live]["spec"]["output"]
        with open(live_out + ".ckpt", "w") as f:
            f.write('{"done": {}}')
        # the terminal job's published output is never GC fodder either
        with open(out, "wb") as f:
            f.write(b"published bytes")
        freed = q.gc_terminal_litter()
        assert freed >= 4096 + 2048
        assert not os.path.exists(out + ".ckpt")
        assert os.path.exists(out)  # published output untouched
        assert os.path.exists(live_out + ".ckpt")  # open job untouched

    def test_probe_disabled_never_sheds(self, tmp_path, monkeypatch):
        from duplexumiconsensusreads_tpu.serve import queue as queue_mod

        q = SpoolQueue(str(tmp_path), min_free_bytes=0)
        monkeypatch.setattr(queue_mod, "free_bytes", lambda p: 0)
        jid = client.submit(str(tmp_path), __file__,
                            str(tmp_path / "o.bam"), config=dict(CONFIG))
        assert q.accept_one(jid)[0] is not None

    def test_free_bytes_probe_answers_on_real_fs(self, tmp_path):
        from duplexumiconsensusreads_tpu.io.durable import free_bytes

        free = free_bytes(str(tmp_path))
        assert isinstance(free, int) and free > 0
        assert free_bytes(str(tmp_path / "nope" / "deeper")) is None


class TestCounterRebuild:
    def test_counters_rebuilt_from_journal_across_restart(
        self, sim, tmp_path
    ):
        """The metrics-truth satellite: a restarted daemon's counters
        (and therefore metrics.json) must reflect the journal it
        inherited, not restart at zero while the spool says otherwise."""
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        jobs = _submit_n(spool, in_path, tmp_path, 2)
        bad = client.submit(spool, __file__, str(tmp_path / "bad.bam"),
                            config=dict(CONFIG))  # not a BAM: fails
        snap = ConsensusService(spool, chunk_budget=0).run_until_idle()
        assert snap["jobs_done"] == 2 and snap["jobs_failed"] == 1
        # a fresh instance on the same spool starts TRUTHFUL
        svc2 = ConsensusService(spool, chunk_budget=0)
        stats = svc2.stats()
        assert stats["jobs_done"] == 2 and stats["jobs_failed"] == 1
        assert stats["jobs_accepted"] == 3
        # and its final snapshot (metrics.json) keeps the totals
        snap2 = svc2.run_until_idle()
        assert snap2["jobs_done"] == 2 and snap2["jobs_failed"] == 1
        with open(os.path.join(spool, "metrics.json")) as f:
            metrics = json.load(f)
        assert metrics["jobs_done"] == 2 and metrics["jobs_failed"] == 1
        for _, out in jobs:
            with open(out, "rb") as f:
                assert f.read() == ref_bytes
        assert client.status(spool, bad)["state"] == "failed"


class TestAdmissionControl:
    def test_class_depth_shed_with_reason(self, sim, tmp_path, capsys):
        """Per-class admission control: submissions beyond their
        class's queued-depth bound are shed with a journaled reason,
        the shed surfaces through --status, and the service still runs
        what it admitted."""
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        trace = str(tmp_path / "svc.jsonl")
        jobs = _submit_n(spool, in_path, tmp_path, 3)
        svc = ConsensusService(
            spool, chunk_budget=0, trace_path=trace, class_depths={1: 1},
        )
        snap = svc.run_until_idle()
        assert snap["jobs_done"] == 1 and snap["jobs_shed"] == 2
        assert snap["jobs_rejected"] == 0  # sheds are not spec errors
        states = {jid: client.status(spool, jid) for jid, _ in jobs}
        shed = [st for st in states.values() if st.get("shed")]
        assert len(shed) == 2
        for st in shed:
            assert st["state"] == "rejected"
            assert st["error"].startswith("shed: priority class 1")
        done = [jid for jid, st in states.items() if st["state"] == "done"]
        assert len(done) == 1
        with open(dict(jobs)[done[0]], "rb") as f:
            assert f.read() == ref_bytes
        # the capture distinguishes sheds from invalid-spec rejections
        _, ev = _events(trace)
        shed_ev = [e for e in ev if e["name"] == "job_shed"]
        assert len(shed_ev) == 2
        assert all("admission control" in e["reason"] for e in shed_ev)
        # and the CLI surfaces the reason on --status (exit 1 + stderr)
        from duplexumiconsensusreads_tpu.cli.main import main as cli_main

        shed_jid = next(j for j, st in states.items() if st.get("shed"))
        rc = cli_main(["call", "--status", shed_jid, "--spool", spool])
        captured = capsys.readouterr()
        assert rc == 1
        assert json.loads(captured.out)["shed"] is True
        assert "shed by admission control" in captured.err

    def test_parse_class_depths(self):
        assert parse_class_depths("0=8,1=4") == {0: 8, 1: 4}
        assert parse_class_depths(" 2=1 ") == {2: 1}
        for bad in ("0", "a=1", "0=0", "0=-1", "-1=2", "0:3"):
            with pytest.raises(ValueError):
                parse_class_depths(bad)

    def test_global_bound_sheds_with_reason(self, tmp_path):
        """The pre-existing global open-jobs bound now sheds with the
        same explicit shed marker as the class bounds."""
        q = SpoolQueue(str(tmp_path), max_queue=1)
        j1 = client.submit(str(tmp_path), __file__, str(tmp_path / "a.bam"),
                           config=dict(CONFIG))
        j2 = client.submit(str(tmp_path), __file__, str(tmp_path / "b.bam"),
                           config=dict(CONFIG))
        assert q.accept_one(j1)[0] is not None
        spec, reason = q.accept_one(j2)
        assert spec is None and reason.startswith("shed: queue full")
        st = q.status(j2)
        assert st["state"] == "rejected" and st["shed"] is True

    def test_shed_reason_survives_journal_compaction(self, tmp_path):
        """Overload is exactly when sheds are frequent AND journal
        churn is fastest: a shed verdict must outlive its journal
        entry's compaction (durable rejection results, like
        done/failed), not degrade to 'unknown'."""
        q = SpoolQueue(str(tmp_path), max_queue=1, max_terminal_kept=0)
        j1 = client.submit(str(tmp_path), __file__, str(tmp_path / "a.bam"),
                           config=dict(CONFIG))
        j2 = client.submit(str(tmp_path), __file__, str(tmp_path / "b.bam"),
                           config=dict(CONFIG))
        assert q.accept_one(j1)[0] is not None
        _, reason = q.accept_one(j2)  # shed + compacted away immediately
        assert j2 not in SpoolQueue(str(tmp_path)).jobs
        st = q.status(j2)
        assert st["state"] == "rejected" and st["compacted"]
        assert st["shed"] is True
        assert "queue full" in st["error"]
        # invalid-spec rejections survive the same way
        bad = tmp_path / "inbox" / "job-bad.json"
        bad.write_text('{"job_id": "job-bad"}')
        q.accept_one("job-bad")
        assert "job-bad" not in SpoolQueue(str(tmp_path)).jobs
        st = q.status("job-bad")
        assert st["state"] == "rejected" and st["compacted"]
        assert "input" in st["error"] and "shed" not in st

    def test_sweep_orphan_tmps_removes_dead_writers_litter(self, tmp_path):
        """Crash litter: pid-suffixed staging files whose writer pid is
        dead are swept at daemon startup; a live writer's in-flight
        staging file is untouched."""
        q = SpoolQueue(str(tmp_path))
        child = subprocess.Popen([sys.executable, "-c", "pass"])
        child.wait()
        dead = tmp_path / f"queue.json.tmp.{child.pid}.140001"
        dead.write_text("torn half-write")
        dead2 = tmp_path / "results" / f"job-x.json.tmp.{child.pid}.140002"
        dead2.write_text("torn")
        live = tmp_path / f"queue.json.tmp.{os.getpid()}.140003"
        live.write_text("in flight")
        other = tmp_path / "queue.json"  # not a tmp: never touched
        other.write_text('{"jobs": {}, "seq": 0, "version": 1}')
        assert q.sweep_orphan_tmps() == 2
        assert not dead.exists() and not dead2.exists()
        assert live.exists() and other.exists()


class TestWaitBackoff:
    def test_wait_backoff_doubles_jitters_and_caps(self, tmp_path,
                                                   monkeypatch):
        """--wait polling satellite: delays double from poll_s toward
        the ~2s cap, each scaled by jitter in [0.5, 1.0), and the
        final sleep never overshoots the deadline."""
        spool = str(tmp_path / "spool")
        jid = client.submit(spool, __file__, str(tmp_path / "o.bam"),
                            config=dict(CONFIG))  # submitted, never run
        clock = [0.0]
        delays = []

        def fake_monotonic():
            return clock[0]

        def fake_sleep(s):
            delays.append(s)
            clock[0] += s

        monkeypatch.setattr(time, "monotonic", fake_monotonic)
        monkeypatch.setattr(time, "sleep", fake_sleep)
        st = client.wait(spool, jid, timeout_s=30.0, poll_s=0.1)
        assert st["timed_out"] is True and st["state"] == "submitted"
        assert len(delays) >= 8
        # nominal schedule 0.1, 0.2, 0.4, ... capped at 2.0; each delay
        # jitters within [0.5, 1.0] of nominal — except the FINAL sleep,
        # which is clamped to the remaining deadline and may be shorter
        nominal = 0.1
        for d in delays[:-1]:
            assert 0.5 * nominal - 1e-9 <= d <= nominal + 1e-9
            nominal = min(nominal * 2, client.WAIT_BACKOFF_CAP_S)
        assert delays[-1] <= nominal + 1e-9
        assert max(delays) <= client.WAIT_BACKOFF_CAP_S
        # the deadline was respected exactly: total sleep <= timeout
        assert sum(delays) <= 30.0 + 1e-6


class TestGracefulDrain:
    def test_drain_mid_queue_then_restart_completes_everything(
        self, sim, tmp_path
    ):
        """The SIGTERM contract, in-process: drain after the first
        completion, restart, and every job ends done exactly once with
        one-shot bytes."""
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        jobs = _submit_n(spool, in_path, tmp_path, 3)
        t1 = str(tmp_path / "svc1.jsonl")
        svc = ConsensusService(spool, chunk_budget=0, trace_path=t1,
                               poll_s=0.05)
        done = {}
        th = threading.Thread(target=lambda: done.setdefault("snap", svc.run()))
        th.start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if svc.stats()["jobs_done"] >= 1:
                break
            time.sleep(0.02)
        svc.request_drain()
        th.join(timeout=60)
        assert not th.is_alive() and "snap" in done
        q = SpoolQueue(spool)
        states = {jid: q.jobs[jid]["state"] for jid, _ in jobs if jid in q.jobs}
        # nothing lost, nothing stuck running
        assert all(s in ("done", "queued") for s in states.values())
        n_done_1 = sum(1 for s in states.values() if s == "done")
        assert n_done_1 >= 1
        t2 = str(tmp_path / "svc2.jsonl")
        snap2 = ConsensusService(spool, trace_path=t2).run_until_idle()
        # counters rebuild from the inherited journal, so the restarted
        # daemon reports the spool's TOTAL (first daemon's completions
        # included), not just its own session's work
        assert snap2["jobs_done"] == 3
        for jid, out in jobs:
            assert client.status(spool, jid)["state"] == "done"
            with open(out, "rb") as f:
                assert f.read() == ref_bytes
        # no double-run: each job completed exactly once across both
        # daemon lifetimes
        _, ev1 = _events(t1)
        _, ev2 = _events(t2)
        completed = [
            e["job"] for e in ev1 + ev2 if e["name"] == "job_completed"
        ]
        assert sorted(completed) == sorted(j for j, _ in jobs)

    def test_drain_preempts_running_job_at_chunk_boundary(
        self, sim, tmp_path
    ):
        """Drain during a long job: the slice yields with reason=drain,
        the job re-journals as queued, and the restart resumes it from
        its checkpoint (skipping the committed prefix) to identical
        bytes."""
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        jid, out = _submit_n(spool, in_path, tmp_path, 1)[0]
        t1 = str(tmp_path / "svc1.jsonl")
        svc = ConsensusService(spool, chunk_budget=1, trace_path=t1,
                               poll_s=0.05)
        # request the drain from the executor's own chunk-commit path
        # (the budget check consults should_yield after the first fresh
        # chunk) — deterministic mid-job drain, no sleeps
        orig = svc.worker.run_slice

        def run_slice_then_drain(spec, budget, should_yield, drain_event,
                                 lease=None):
            def drain_not_yield():
                svc.request_drain()
                return False
            return orig(spec, budget, drain_not_yield, drain_event,
                        lease=lease)

        svc.worker.run_slice = run_slice_then_drain
        snap = svc.run()
        assert snap["preemptions"] == 1 and snap["jobs_done"] == 0
        _, ev1 = _events(t1)
        pre = [e for e in ev1 if e["name"] == "job_preempted"]
        assert len(pre) == 1 and pre[0]["reason"] == "drain"
        assert pre[0]["chunks_done"] >= 1
        assert SpoolQueue(spool).jobs[jid]["state"] == "queued"
        t2 = str(tmp_path / "svc2.jsonl")
        snap2 = ConsensusService(spool, trace_path=t2).run_until_idle()
        assert snap2["jobs_done"] == 1
        with open(out, "rb") as f:
            assert f.read() == ref_bytes
        # the second daemon finished the job in its SECOND slice — the
        # committed prefix came from the first daemon's checkpoint
        assert SpoolQueue(spool).jobs[jid]["slices"] == 2

    def test_sigterm_daemon_subprocess_exits_zero_and_resumes(
        self, sim, tmp_path
    ):
        """The real daemon under a real SIGTERM: exit code 0, queue
        journaled, and a --once restart finishes the work."""
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        jobs = _submit_n(spool, in_path, tmp_path, 2)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "duplexumiconsensusreads_tpu.serve.daemon",
             spool, "--poll", "0.05", "--heartbeat", "0.2",
             "--chunk-budget", "2"],
            env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        try:
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if any(
                    client.status(spool, jid)["state"] == "done"
                    for jid, _ in jobs
                ):
                    break
                if proc.poll() is not None:
                    break
                time.sleep(0.1)
            assert proc.poll() is None, proc.communicate()[1]
            proc.send_signal(signal.SIGTERM)
            out_s, err_s = proc.communicate(timeout=60)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.communicate()
        assert proc.returncode == 0, err_s
        assert "graceful drain" in err_s
        # restart in batch mode finishes whatever remained
        p2 = subprocess.run(
            [sys.executable, "-m", "duplexumiconsensusreads_tpu.serve.daemon",
             spool, "--once", "--poll", "0.05", "--heartbeat", "0"],
            env=env, cwd=REPO, capture_output=True, text=True, timeout=300,
        )
        assert p2.returncode == 0, p2.stderr
        for jid, out in jobs:
            assert client.status(spool, jid)["state"] == "done"
            with open(out, "rb") as f:
                assert f.read() == ref_bytes


# ------------------------------------------------------------ CLI verbs

class TestCliVerbs:
    def test_submit_status_wait_roundtrip(self, sim, tmp_path, capsys):
        from duplexumiconsensusreads_tpu.cli.main import main as cli_main

        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        out = str(tmp_path / "cli_out.bam")
        rc = cli_main([
            "call", in_path, "-o", out, "--submit", "--spool", spool,
            "--grouping", "adjacency", "--mode", "duplex",
            "--capacity", "128", "--chunk-reads", "90",
        ])
        assert rc == 0
        jid = capsys.readouterr().out.strip()
        assert jid.startswith("job-")
        rc = cli_main(["call", "--status", jid, "--spool", spool])
        assert rc == 0
        assert json.loads(capsys.readouterr().out)["state"] == "submitted"
        # a daemon drains it; --wait then reports done
        ConsensusService(spool).run_until_idle()
        rc = cli_main(["call", "--wait", jid, "--spool", spool,
                       "--wait-timeout", "5"])
        assert rc == 0
        st = json.loads(capsys.readouterr().out)
        assert st["state"] == "done"
        with open(out, "rb") as f:
            assert f.read() == ref_bytes

    def test_unknown_job_and_usage_errors(self, tmp_path, capsys):
        from duplexumiconsensusreads_tpu.cli.main import main as cli_main

        spool = str(tmp_path / "spool")
        rc = cli_main(["call", "--status", "job-nope", "--spool", spool])
        assert rc == 1
        assert json.loads(capsys.readouterr().out)["state"] == "unknown"
        with pytest.raises(SystemExit, match="spool"):
            cli_main(["call", "--status", "job-x"])
        with pytest.raises(SystemExit, match="INPUT"):
            cli_main(["call"])
        with pytest.raises(SystemExit, match="chunk-reads"):
            cli_main(["call", __file__, "-o", str(tmp_path / "o.bam"),
                      "--submit", "--spool", spool, "--chunk-reads", "0"])
        with pytest.raises(SystemExit, match="whole-file"):
            cli_main(["call", __file__, "-o", str(tmp_path / "o.bam"),
                      "--submit", "--spool", spool, "--ref-projected"])
        # flags the daemon owns are refused loudly, never silently
        # dropped from the spooled job
        with pytest.raises(SystemExit, match="service"):
            cli_main(["call", __file__, "-o", str(tmp_path / "o.bam"),
                      "--submit", "--spool", spool, "--report", "r.json"])
        with pytest.raises(SystemExit, match="daemon-side"):
            cli_main(["call", __file__, "-o", str(tmp_path / "o.bam"),
                      "--submit", "--spool", spool, "--cycle-shards", "2"])
        with pytest.raises(SystemExit, match="daemon-side"):
            cli_main(["call", __file__, "-o", str(tmp_path / "o.bam"),
                      "--submit", "--spool", spool, "--devices", "2"])

    def test_wait_timeout_reports_not_hangs(self, sim, tmp_path):
        in_path, _ = sim
        spool = str(tmp_path / "spool")
        jid, _ = _submit_n(spool, in_path, tmp_path, 1)[0]
        st = client.wait(spool, jid, timeout_s=0.2, poll_s=0.05)
        assert st["timed_out"] is True and st["state"] == "submitted"

    def test_wait_timeout_distinct_exit_code_and_state_line(
        self, sim, tmp_path, capsys
    ):
        """--wait-timeout satellite: a timeout is 'still running', not
        'dead' — distinct exit code 3, and the job's last journaled
        state on stderr so the operator knows what they are waiting
        on."""
        from duplexumiconsensusreads_tpu.cli.main import main as cli_main

        in_path, _ = sim
        spool = str(tmp_path / "spool")
        jid, _ = _submit_n(spool, in_path, tmp_path, 1)[0]
        rc = cli_main(["call", "--wait", jid, "--spool", spool,
                       "--wait-timeout", "0.2"])
        captured = capsys.readouterr()
        assert rc == 3
        st = json.loads(captured.out)
        assert st["timed_out"] is True
        assert "last journaled state" in captured.err
        assert "submitted" in captured.err

    def test_submit_deadline_flag_round_trips(self, sim, tmp_path, capsys):
        from duplexumiconsensusreads_tpu.cli.main import main as cli_main

        in_path, _ = sim
        spool = str(tmp_path / "spool")
        out = str(tmp_path / "dl.bam")
        rc = cli_main([
            "call", in_path, "-o", out, "--submit", "--spool", spool,
            "--grouping", "adjacency", "--mode", "duplex",
            "--capacity", "128", "--chunk-reads", "90",
            "--deadline", "120",
        ])
        assert rc == 0
        jid = capsys.readouterr().out.strip()
        q = SpoolQueue(spool)
        assert q.accept_one(jid)[0] is not None
        assert q.jobs[jid]["deadline_m"] == pytest.approx(
            time.monotonic() + 120.0, abs=5.0
        )
        # --deadline outside --submit is refused, not silently ignored
        with pytest.raises(SystemExit, match="deadline"):
            cli_main(["call", in_path, "-o", out, "--chunk-reads", "90",
                      "--deadline", "10"])
        with pytest.raises(SystemExit, match="deadline"):
            cli_main(["call", in_path, "-o", out, "--submit",
                      "--spool", spool, "--chunk-reads", "90",
                      "--deadline", "-1"])


# --------------------------------------------------- scatter-gather shard

class TestSharding:
    """serve/shard/: scatter-gather job sharding. The headline contract
    is A/B byte identity — a sharded job's merged output equals the
    same job run unsharded, at any K and daemon count, and stays
    identical under chaos kills at serve.split / serve.merge and a
    mid-shard daemon death. The parent walks queued -> "splitting" ->
    "fanned" -> queued -> "merging" -> done in the journal."""

    def _submit_sharded(self, spool, in_path, out, shards, **kw):
        return client.submit(
            spool, in_path, out, config=dict(CONFIG), shards=shards, **kw
        )

    def _run_fleet(self, spool, traces, n=2, **svc_kw):
        svcs = [
            ConsensusService(
                spool, chunk_budget=2, poll_s=0.02, trace_path=traces[i],
                daemon_id=f"shard-fleet-{i}", **svc_kw,
            )
            for i in range(n)
        ]
        threads = [
            threading.Thread(target=s.run_until_idle, daemon=True)
            for s in svcs
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads)
        return svcs

    # ------------------------------------------------------- the planner

    @pytest.fixture(scope="class")
    def multi_contig(self, tmp_path_factory):
        """Multi-contig, unevenly covered input: two contigs, one
        position hammered with most of the families (uneven coverage),
        plus an unmapped sentinel tail — the planner must tile it
        exactly whatever K asks."""
        import numpy as np

        from duplexumiconsensusreads_tpu.io.bam import (
            BamHeader,
            FLAG_UNMAPPED,
            write_bam,
        )
        from duplexumiconsensusreads_tpu.io.convert import readbatch_to_records
        from duplexumiconsensusreads_tpu.simulate import simulate_batch

        d = tmp_path_factory.mktemp("shard_plan")
        cfg = SimConfig(n_molecules=90, n_positions=6, umi_error=0.02,
                        seed=77)
        batch, _ = simulate_batch(cfg)
        order = np.argsort(np.asarray(batch.pos_key), kind="stable")
        batch = batch.take(order)
        recs = readbatch_to_records(batch, duplex=True)
        pos = np.asarray(recs.pos)
        # contig split: everything at/above the median position moves to
        # contig 1 (order stays sorted: ref 0 block then ref 1 block);
        # the tail of the file becomes unmapped records (sentinel keys)
        cut = int(np.median(pos))
        ref_id = np.asarray(recs.ref_id)
        ref_id[pos >= cut] = 1
        flags = np.asarray(recs.flags)
        n = len(flags)
        unm = slice(n - max(n // 12, 1), n)
        ref_id[unm] = -1
        flags[unm] |= FLAG_UNMAPPED
        header = BamHeader.synthetic(
            ref_names=("chr1", "chr2"), ref_lengths=(10_000_000,) * 2,
            sort_order="coordinate",
        )
        path = str(d / "multi.bam")
        write_bam(path, header, recs)
        return path, n

    @pytest.mark.parametrize("k", [1, 3, 5])
    def test_planner_tiles_multi_contig_uneven_exactly(
        self, multi_contig, k
    ):
        """Exact tiling: the shard ranges partition the whole-file
        chunk grid, and streaming each range yields every record's
        pos_key exactly once, in order — no read lost, none duplicated
        at range boundaries (edge families land in exactly one shard),
        the unmapped tail included."""
        import numpy as np

        from duplexumiconsensusreads_tpu.runtime.stream import (
            iter_batch_chunks,
        )
        from duplexumiconsensusreads_tpu.serve.shard.plan import plan_shards

        path, n_records = multi_contig
        plan = plan_shards(path, 64, duplex=True, n_shards=k)
        assert 1 <= len(plan.ranges) <= k
        # the ranges partition the chunk grid
        assert plan.ranges[0].chunk_base == 0
        for a, b in zip(plan.ranges, plan.ranges[1:]):
            assert b.chunk_base == a.chunk_base + a.n_chunks
            assert a.key_hi == b.key_lo
        last = plan.ranges[-1]
        assert last.chunk_base + last.n_chunks == plan.n_chunks
        assert last.key_hi is None
        assert plan.n_records == n_records
        assert sum(r.n_records for r in plan.ranges) == n_records
        # whole-file pos_key sequence == concatenation of the shards'
        whole = []
        for _, batch, _info in iter_batch_chunks(path, 64, True,
                                                 warn_mixed=False):
            whole.append(np.asarray(batch.pos_key))
        whole = np.concatenate(whole)
        got = []
        for r in plan.ranges:
            n_chunks = 0
            for _, batch, _info in iter_batch_chunks(
                path, 64, True,
                start=r.start, key_lo=r.key_lo, key_hi=r.key_hi,
                first_read=r.first_read, warn_mixed=False,
            ):
                got.append(np.asarray(batch.pos_key))
                n_chunks += 1
            assert n_chunks == r.n_chunks
        got = np.concatenate(got)
        assert len(got) == n_records
        assert (got == whole).all()

    def test_planner_rejects_bad_requests(self, multi_contig):
        from duplexumiconsensusreads_tpu.serve.shard.plan import plan_shards

        path, _ = multi_contig
        with pytest.raises(ValueError, match="exactly one"):
            plan_shards(path, 64, duplex=True)
        with pytest.raises(ValueError, match="exactly one"):
            plan_shards(path, 64, duplex=True, n_shards=2, shard_bytes=1)

    # ------------------------------------------ the state machine (unit)

    def test_parent_stage_literals_and_status_rollup(self, tmp_path):
        """The parent's journal walk, literal by literal: claim of a
        phase="split" parent is "splitting", registration parks it
        "fanned", the advance sweep requeues it for merge, and the
        merge claim is "merging" — with --status aggregating the
        sub-jobs throughout."""
        spool = str(tmp_path / "spool")
        q = SpoolQueue(spool)
        q.submit(validate_spec(_spec("job-p", shards=2)))
        spec, reason = q.accept_one("job-p")
        assert reason is None and q.jobs["job-p"]["phase"] == "split"
        token = q.claim("job-p", "d1")
        assert q.jobs["job-p"]["state"] == "splitting"
        children = [
            {
                "job_id": f"job-p.s{i:03d}", "input": "/i.bam",
                "output": f"/o.bam.shard{i:03d}.bam",
                "config": dict(CONFIG),
                "shard": {"parent": "job-p", "idx": i, "k": 2,
                          "chunk_base": i, "n_chunks": 1,
                          "key_lo": None, "key_hi": None,
                          "start": None, "first_read": None},
            }
            for i in range(2)
        ]
        assert q.register_shards("job-p", "d1", token, children) == 2
        assert q.jobs["job-p"]["state"] == "fanned"
        assert q.jobs["job-p"]["children"] == [
            "job-p.s000", "job-p.s001"
        ]
        # registration is idempotent: a re-plan dedupes on derived ids
        tok2 = None
        st = q.status("job-p")
        assert st["shards"] == {
            "n_shards": 2, "done": 0, "running": 0, "queued": 2,
            "failed": 0,
        }
        # children run the ordinary claimed path
        for cid in ("job-p.s000", "job-p.s001"):
            t = q.claim(cid, "d1")
            assert q.jobs[cid]["state"] == "running"
            q.mark_done(cid, {"n_consensus": 1}, "d1", t)
        assert q.status("job-p")["shards"]["done"] == 2
        moved = q.advance_parents()
        assert moved == [
            {"job_id": "job-p", "decision": "merge", "n_shards": 2}
        ]
        entry = q.jobs["job-p"]
        assert entry["state"] == "queued" and entry["phase"] == "merge"
        tok2 = q.claim("job-p", "d2")
        assert q.jobs["job-p"]["state"] == "merging"
        assert tok2 == token + 1  # the merge claim fences the planner
        q.mark_done("job-p", {"n_consensus": 2}, "d2", tok2)
        assert q.jobs["job-p"]["state"] == "done"

    def test_failed_shard_fails_parent_with_diagnosis(self, tmp_path):
        """A terminally-failed sub-job fails the parent with a durable
        diagnosis naming the shard; queued siblings are failed
        alongside instead of running for a dead parent."""
        spool = str(tmp_path / "spool")
        q = SpoolQueue(spool)
        q.submit(validate_spec(_spec("job-p", shards=2)))
        q.accept_one("job-p")
        token = q.claim("job-p", "d1")
        children = [
            {
                "job_id": f"job-p.s{i:03d}", "input": "/i.bam",
                "output": f"/o.bam.shard{i:03d}.bam",
                "config": dict(CONFIG),
                "shard": {"parent": "job-p", "idx": i, "k": 2,
                          "chunk_base": i, "n_chunks": 1,
                          "key_lo": None, "key_hi": None,
                          "start": None, "first_read": None},
            }
            for i in range(2)
        ]
        q.register_shards("job-p", "d1", token, children)
        t = q.claim("job-p.s000", "d1")
        q.mark_failed("job-p.s000", "boom: not a BAM", "d1", t)
        moved = q.advance_parents()
        assert moved[0]["decision"] == "failed"
        entry = q.jobs["job-p"]
        assert entry["state"] == "failed"
        assert "job-p.s000" in entry["error"]
        # the durable result names the shard (survives compaction)
        st = q.status("job-p")
        assert st["result"]["shard_failure"]["shard"] == "job-p.s000"
        assert "boom" in st["result"]["shard_failure"]["error"]
        # the queued sibling was failed alongside
        assert q.jobs["job-p.s001"]["state"] == "failed"
        assert "parent" in q.jobs["job-p.s001"]["error"]

    def test_requeued_orphan_of_failed_parent_is_reaped_not_rerun(
        self, tmp_path
    ):
        """A child that was RUNNING when its parent failed escapes the
        sibling cancellation; when it later requeues (preempt or
        takeover) the sweep must reap it instead of letting the fleet
        re-run work nothing will ever merge."""
        spool = str(tmp_path / "spool")
        q = SpoolQueue(spool)
        q.submit(validate_spec(_spec("job-p", shards=2)))
        q.accept_one("job-p")
        token = q.claim("job-p", "d1")
        children = [
            {
                "job_id": f"job-p.s{i:03d}", "input": "/i.bam",
                "output": f"/o.bam.shard{i:03d}.bam",
                "config": dict(CONFIG),
                "shard": {"parent": "job-p", "idx": i, "k": 2,
                          "chunk_base": i, "n_chunks": 1,
                          "key_lo": None, "key_hi": None,
                          "start": None, "first_read": None},
            }
            for i in range(2)
        ]
        q.register_shards("job-p", "d1", token, children)
        # shard 1 is mid-slice when shard 0 fails the parent
        t1 = q.claim("job-p.s001", "d1")
        t0 = q.claim("job-p.s000", "d1")
        q.mark_failed("job-p.s000", "boom", "d1", t0)
        assert q.advance_parents()[0]["decision"] == "failed"
        assert q.jobs["job-p"]["state"] == "failed"
        assert q.jobs["job-p.s001"]["state"] == "running"  # escaped
        # ... then preempts back to the queue
        q.requeue("job-p.s001", 1, back=False, daemon_id="d1", token=t1)
        moved = q.advance_parents()
        assert {"job_id": "job-p.s001", "decision": "orphaned",
                "parent": "job-p"} in moved
        assert q.jobs["job-p.s001"]["state"] == "failed"
        assert "orphaned" in q.jobs["job-p.s001"]["error"]
        # and the scheduler has nothing left to pick
        assert FairScheduler.pick(q.jobs) is None
        # a directly-spooled sub-job with NO journaled parent is a
        # deliberate debug/re-run, not an orphan: the sweep leaves it
        q.submit(validate_spec({
            "job_id": "job-lone.s000", "input": "/i.bam",
            "output": "/lone.shard000.bam", "config": dict(CONFIG),
            "shard": {"parent": "job-lone", "idx": 0, "k": 1,
                      "chunk_base": 0},
        }))
        q.accept_one("job-lone.s000")
        assert q.advance_parents() == []
        assert q.jobs["job-lone.s000"]["state"] == "queued"

    def test_compaction_protects_children_of_open_parents(self, tmp_path):
        """A done sub-job must survive journal compaction while its
        parent is open: the advance sweep decides the merge from the
        children's journal states."""
        spool = str(tmp_path / "spool")
        q = SpoolQueue(spool)
        q.max_terminal_kept = 0  # compact every terminal entry away
        q.submit(validate_spec(_spec("job-p", shards=1)))
        q.accept_one("job-p")
        token = q.claim("job-p", "d1")
        q.register_shards("job-p", "d1", token, [{
            "job_id": "job-p.s000", "input": "/i.bam",
            "output": "/o.bam.shard000.bam", "config": dict(CONFIG),
            "shard": {"parent": "job-p", "idx": 0, "k": 1,
                      "chunk_base": 0, "n_chunks": 1, "key_lo": None,
                      "key_hi": None, "start": None, "first_read": None},
        }])
        t = q.claim("job-p.s000", "d1")
        q.mark_done("job-p.s000", {"n_consensus": 1}, "d1", t)
        # the save inside mark_done ran compaction with
        # max_terminal_kept=0 — the done child must still be there
        assert q.jobs["job-p.s000"]["state"] == "done"
        assert q.advance_parents()[0]["decision"] == "merge"

    # ----------------------------------------------- the A/B acceptance

    def test_sharded_fleet_byte_identical_and_observable(
        self, sim, tmp_path
    ):
        """THE acceptance A/B: one job scattered at K=4 across 2
        daemons merges byte-identical to the unsharded reference, with
        the lifecycle observable end to end (rollup, events, lineage,
        serve_report)."""
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        out = str(tmp_path / "sharded.bam")
        traces = [str(tmp_path / f"svc{i}.jsonl") for i in (0, 1)]
        jid = self._submit_sharded(spool, in_path, out, shards=4)
        svcs = self._run_fleet(spool, traces)
        st = client.status(spool, jid)
        assert st["state"] == "done"
        assert st["shards"] == {
            "n_shards": 4, "done": 4, "running": 0, "queued": 0,
            "failed": 0,
        }
        with open(out, "rb") as f:
            assert f.read() == ref_bytes
        assert st["result"]["n_consensus"] > 0
        assert st["result"]["sharded"]["n_shards"] == 4
        # split and merge each happened exactly once, fleet-wide
        assert sum(s.counters["jobs_split"] for s in svcs) == 1
        assert sum(s.counters["jobs_merged"] for s in svcs) == 1
        events = []
        for tp in traces:
            recs, ev = _events(tp)
            assert trace_report.validate_service_trace(recs) == []
            events += ev
        assert len([e for e in events if e["name"] == "job_split"]) == 1
        assert len([e for e in events if e["name"] == "job_merged"]) == 1
        completed = [e for e in events if e["name"] == "job_completed"]
        # 4 children + 1 parent, each exactly once across the fleet
        assert sorted(e["job"] for e in completed) == sorted(
            [jid] + [f"{jid}.s{i:03d}" for i in range(4)]
        )
        # lineage attrs ride the child job_started events
        child_starts = [
            e for e in events
            if e["name"] == "job_started" and e.get("parent") == jid
        ]
        assert {e["shard_idx"] for e in child_starts} == {0, 1, 2, 3}
        # intermediate shard outputs are reclaimed after the merge
        assert not [
            p for p in os.listdir(tmp_path) if ".shard" in p
        ]
        # serve_report rolls the parent up with its shard states
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "serve_report.py"),
             traces[0], "--json"],
            capture_output=True, text=True, timeout=120,
        )
        assert p.returncode == 0, p.stderr
        rep = json.loads(p.stdout)
        assert jid in rep.get("parents", {})
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "serve_report.py"),
             traces[0]],
            capture_output=True, text=True, timeout=120,
        )
        assert p.returncode == 0 and "sharding:" in p.stdout

    def test_k1_degenerates_byte_identical_with_index(
        self, sim, tmp_path
    ):
        """K=1 still runs the full split/fan/merge pipeline and must
        degenerate to the unsharded path byte-for-byte — merged BAM
        and rebuilt BAI alike."""
        from duplexumiconsensusreads_tpu.serve.job import serve_provenance

        in_path, _ = sim
        config = dict(CONFIG, write_index=True)
        ref = str(tmp_path / "ref.bam")
        stream_call_consensus(
            in_path, ref, GP, CP, capacity=128, chunk_reads=90,
            provenance_cl=serve_provenance(config), write_index=True,
        )
        spool = str(tmp_path / "spool")
        out = str(tmp_path / "k1.bam")
        jid = client.submit(spool, in_path, out, config=config, shards=1)
        snap = ConsensusService(spool, poll_s=0.02).run_until_idle()
        assert snap["jobs_split"] == 1 and snap["jobs_merged"] == 1
        assert client.status(spool, jid)["state"] == "done"
        with open(out, "rb") as f, open(ref, "rb") as r:
            assert f.read() == r.read()
        with open(out + ".bai", "rb") as f, open(ref + ".bai", "rb") as r:
            assert f.read() == r.read()

    # ------------------------------------------------------------- chaos

    @pytest.mark.parametrize("site,nth", [
        ("serve.split", 1),  # dies committing the shard plan
        ("serve.merge", 1),  # dies in the first parent advance sweep
    ])
    def test_kill_at_shard_site_then_restart_byte_identical(
        self, site, nth, sim, tmp_path
    ):
        """The shard sites join the kill matrix: wherever the daemon
        dies, a successor converges to the identical merged bytes with
        children registered (and the merge published) exactly once."""
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        out = str(tmp_path / "out.bam")
        jid = self._submit_sharded(spool, in_path, out, shards=3)
        faults.install(faults.FaultPlan.parse(f"{site}:{nth}:kill"))
        with pytest.raises(faults.InjectedKill):
            ConsensusService(spool, poll_s=0.02).run_until_idle()
        faults.uninstall()
        if site == "serve.split":
            # the kill landed inside the split txn: the journal must
            # show the parent claimed for splitting under a lease the
            # successor can reclaim
            entry = SpoolQueue(spool).jobs[jid]
            assert entry["state"] == "splitting"
        t2 = str(tmp_path / "svc2.jsonl")
        ConsensusService(spool, poll_s=0.02, trace_path=t2).run_until_idle()
        st = client.status(spool, jid)
        assert st["state"] == "done"
        assert st["shards"]["done"] == st["shards"]["n_shards"] == 3
        with open(out, "rb") as f:
            assert f.read() == ref_bytes
        _, ev = _events(t2)
        assert len([
            e for e in ev
            if e["name"] == "job_completed" and e["job"] == jid
        ]) == 1

    def test_kill_mid_splice_then_takeover_remerges_exactly_once(
        self, sim, tmp_path
    ):
        """Daemon A dies between shard splices (merge half-written to
        its staging file); daemon B reclaims the merging parent and
        re-merges from scratch — exactly one completion, identical
        bytes, A's token fenced."""
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        out = str(tmp_path / "out.bam")
        jid = self._submit_sharded(spool, in_path, out, shards=3)
        t_a = str(tmp_path / "svcA.jsonl")
        svc_a = ConsensusService(
            spool, poll_s=0.02, trace_path=t_a, daemon_id="merge-victim",
        )
        orig = svc_a._fenced_renew
        fences = [0]

        def dying_fence(job_id, token):
            # fence 1 = the split stage's pre-registration renewal;
            # fences 2.. = the merge splice guards. Die on the SECOND
            # merge fence: the staging file already holds shard 0
            if job_id == jid:
                fences[0] += 1
                if fences[0] == 3:
                    raise faults.InjectedKill("die mid-splice")
            orig(job_id, token)

        svc_a._fenced_renew = dying_fence
        with pytest.raises(faults.InjectedKill):
            svc_a.run_until_idle()
        entry = SpoolQueue(spool).jobs[jid]
        assert entry["state"] == "merging"  # died holding the merge lease
        t_b = str(tmp_path / "svcB.jsonl")
        snap_b = ConsensusService(
            spool, poll_s=0.02, trace_path=t_b, daemon_id="merge-b",
        ).run_until_idle()
        assert snap_b["jobs_merged"] == 1
        st = client.status(spool, jid)
        assert st["state"] == "done"
        with open(out, "rb") as f:
            assert f.read() == ref_bytes
        completed = []
        for tp in (t_a, t_b):
            _, ev = _events(tp)
            completed += [
                e for e in ev
                if e["name"] == "job_completed" and e["job"] == jid
            ]
        assert len(completed) == 1

    def test_mid_shard_sigkill_takeover_byte_identical(
        self, sim, tmp_path
    ):
        """Daemon A dies mid-CHILD-slice (the modelled SIGKILL, lease
        still journaled); daemon B takes the sub-job over, resumes its
        checkpoint, finishes the remaining shards AND the merge —
        byte-identical, exactly once."""
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        out = str(tmp_path / "out.bam")
        jid = self._submit_sharded(spool, in_path, out, shards=3)
        t_a = str(tmp_path / "svcA.jsonl")
        svc_a = ConsensusService(
            spool, chunk_budget=0, poll_s=0.02, trace_path=t_a,
            daemon_id="shard-victim",
        )
        orig = svc_a.worker.run_slice

        def dying_run_slice(spec, budget, should_yield, drain_event,
                            lease=None):
            if spec.shard is None:
                return orig(spec, budget, should_yield, drain_event,
                            lease=lease)

            def die():
                raise faults.InjectedKill("mid-shard daemon death")

            # budget=1: the first fresh chunk commits durably, then the
            # yield check kills the daemon with the lease still held
            return orig(spec, 1, die, drain_event, lease=lease)

        svc_a.worker.run_slice = dying_run_slice
        with pytest.raises(faults.InjectedKill):
            svc_a.run_until_idle()
        t_b = str(tmp_path / "svcB.jsonl")
        snap_b = ConsensusService(
            spool, poll_s=0.02, trace_path=t_b, daemon_id="shard-b",
        ).run_until_idle()
        assert snap_b["jobs_recovered"] >= 1  # the dead child takeover
        assert snap_b["jobs_merged"] == 1
        st = client.status(spool, jid)
        assert st["state"] == "done"
        with open(out, "rb") as f:
            assert f.read() == ref_bytes
        completed = []
        for tp in (t_a, t_b):
            _, ev = _events(tp)
            completed += [
                e["job"] for e in ev if e["name"] == "job_completed"
            ]
        assert sorted(completed) == sorted(
            [jid] + [f"{jid}.s{i:03d}" for i in range(3)]
        )

    # --------------------------------------------------------- CLI verbs

    def test_cli_submit_shards_flag_round_trips(self, sim, tmp_path,
                                                capsys):
        from duplexumiconsensusreads_tpu.cli.main import main as cli_main

        in_path, _ = sim
        spool = str(tmp_path / "spool")
        out = str(tmp_path / "cli.bam")
        rc = cli_main([
            "call", in_path, "-o", out, "--submit", "--spool", spool,
            "--grouping", "adjacency", "--mode", "duplex",
            "--capacity", "128", "--chunk-reads", "90", "--shards", "4",
        ])
        assert rc == 0
        jid = capsys.readouterr().out.strip()
        q = SpoolQueue(spool)
        spec, reason = q.accept_one(jid)
        assert reason is None and spec.shards == 4
        assert q.jobs[jid]["phase"] == "split"
        # sharding flags are a --submit contract, refused elsewhere
        with pytest.raises(SystemExit, match="shards"):
            cli_main(["call", in_path, "-o", out, "--chunk-reads", "90",
                      "--shards", "2"])
        with pytest.raises(SystemExit, match="mutually"):
            cli_main(["call", in_path, "-o", out, "--submit",
                      "--spool", spool, "--chunk-reads", "90",
                      "--shards", "2", "--shard-bytes", "1000"])
        with pytest.raises(SystemExit, match="shards"):
            cli_main(["call", in_path, "-o", out, "--submit",
                      "--spool", spool, "--chunk-reads", "90",
                      "--shards", "0"])

    def test_aborted_merge_leaks_no_staging_file(self, sim, tmp_path):
        """A merge that fails (or is fenced/killed in-process) must not
        leave its output-sized staging tmp behind — the pid/tid-unique
        name is never reused, so nothing else would reclaim it."""
        from duplexumiconsensusreads_tpu.serve.shard.merge import (
            splice_shards,
        )

        out = str(tmp_path / "merged.bam")
        with pytest.raises(ValueError, match="finalised"):
            # a shard that is not a finalised BAM fails the span scan
            bad = tmp_path / "bad.shard000.bam"
            bad.write_bytes(b"not a bam at all")
            splice_shards(out, [str(bad)])
        # and a failure mid-splice (second shard vanishes) cleans up too
        in_path, _ = sim
        good = str(tmp_path / "good.bam")
        stream_call_consensus(in_path, good, GP, CP, capacity=128,
                              chunk_reads=90)
        with pytest.raises(FileNotFoundError):
            splice_shards(out, [good, str(tmp_path / "gone.bam")])
        litter = [n for n in os.listdir(tmp_path) if ".tmp" in n]
        assert litter == []

    def test_rollup_counts_compacted_children_as_history_not_failed(
        self, tmp_path
    ):
        """Once the parent is terminal its children may compact away;
        --status must report them as compacted history, never as
        failures with a bogus first_failure."""
        spool = str(tmp_path / "spool")
        q = SpoolQueue(spool)
        q.submit(validate_spec(_spec("job-p", shards=1)))
        q.accept_one("job-p")
        token = q.claim("job-p", "d1")
        q.register_shards("job-p", "d1", token, [{
            "job_id": "job-p.s000", "input": "/i.bam",
            "output": "/o.bam.shard000.bam", "config": dict(CONFIG),
            "shard": {"parent": "job-p", "idx": 0, "k": 1,
                      "chunk_base": 0, "n_chunks": 1, "key_lo": None,
                      "key_hi": None, "start": None, "first_read": None},
        }])
        t = q.claim("job-p.s000", "d1")
        q.mark_done("job-p.s000", {"n_consensus": 1}, "d1", t)
        q.advance_parents()
        tok2 = q.claim("job-p", "d1")
        q.mark_done("job-p", {"n_consensus": 1}, "d1", tok2)
        del q.jobs["job-p.s000"]  # the compacted-child shape
        q.save()  # status() reloads the journal, so persist the shape
        sh = q.status("job-p")["shards"]
        assert sh["failed"] == 0 and "first_failure" not in sh
        assert sh["compacted"] == 1

    def test_fanout_capped_at_queue_bound(self, multi_contig):
        """One parent must not swamp the fleet's open-jobs bound: K is
        clamped by the caller-supplied cap (the service passes its
        max_queue)."""
        from duplexumiconsensusreads_tpu.serve.shard.plan import plan_shards

        path, _ = multi_contig
        plan = plan_shards(path, 64, duplex=True, n_shards=500,
                           max_shards=3)
        assert len(plan.ranges) == 3
        plan = plan_shards(path, 64, duplex=True, shard_bytes=1,
                           max_shards=2)
        assert len(plan.ranges) == 2

    def test_children_inherit_chaos_and_per_shard_trace(self, tmp_path):
        """--chaos/--trace on a sharded submit must not be silently
        dropped: the schedule installs per sub-job (the workers), and
        each child gets its own capture path (K recorders on one file
        would interleave)."""
        from duplexumiconsensusreads_tpu.serve.shard.plan import (
            ShardPlan,
            ShardRange,
            child_spec_dicts,
        )

        parent = validate_spec(_spec(
            "job-p", shards=2, chaos="shard.write:1:oserror",
            trace="/t/cap.jsonl", deadline_s=60.0,
        ))
        plan = ShardPlan(
            input="/i.bam", chunk_reads=90, n_chunks=2, n_records=10,
            mate_aware="off",
            ranges=(
                ShardRange(0, 0, 1, None, None, 5, None, 5, 100),
                ShardRange(1, 1, 1, (0, 9), 5, None, 7, 5, 100),
            ),
        )
        dicts = child_spec_dicts(parent, plan)
        for i, d in enumerate(dicts):
            child = validate_spec(d)
            assert child.chaos == "shard.write:1:oserror"
            assert child.trace == f"/t/cap.jsonl.s{i:03d}"
            assert child.deadline_s == 60.0
            assert child.shard["mate_aware"] == "off"
            assert child.config == parent.config  # provenance identity

    def test_spec_validation_rejects_bad_shard_fields(self):
        with pytest.raises(ValueError, match="shards"):
            validate_spec(_spec(shards=0))
        with pytest.raises(ValueError, match="mutually exclusive"):
            validate_spec(_spec(shards=2, shard_bytes=100))
        with pytest.raises(ValueError, match="shard_bytes"):
            validate_spec(_spec(shard_bytes=True))
        with pytest.raises(ValueError, match="cannot itself"):
            validate_spec(_spec(
                shards=2,
                shard={"parent": "p", "idx": 0, "k": 2, "chunk_base": 0},
            ))
        with pytest.raises(ValueError, match="required keys"):
            validate_spec(_spec(shard={"parent": "p"}))


# ----------------------------------------------- bucket-ladder serving

class TestBucketLadder:
    """Serve-side half of the ladder acceptance matrix: jobs at every
    --bucket-ladder setting are byte-identical to the off/serial
    reference (the @PG CL deliberately excludes the ladder — a shape
    knob the tuner may override per slice must never reach the bytes),
    and a fleet's auto jobs converge through the spool's verdict
    store."""

    @pytest.mark.parametrize("ladder", ["off", "auto", [32, 128],
                                        [32, 64, 128]])
    def test_job_bytes_identical_at_every_ladder(
        self, sim, tmp_path, ladder
    ):
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        out = str(tmp_path / "out.bam")
        jid = client.submit(
            spool, in_path, out,
            config={**CONFIG, "bucket_ladder": ladder},
        )
        svc = ConsensusService(spool, chunk_budget=0)
        snap = svc.run_until_idle()
        assert snap["jobs_done"] == 1, snap
        with open(out, "rb") as f:
            assert f.read() == ref_bytes
        st = SpoolQueue(spool).status(jid)
        assert st["state"] == "done"
        # the result report records the resolved ladder
        ladder_res = st["result"]["bucket_ladder"]
        if ladder == "off":
            assert ladder_res == []
        elif isinstance(ladder, list):
            assert ladder_res == ladder
        else:
            assert ladder_res and ladder_res[-1] == CONFIG["capacity"]

    def test_ladder_joins_the_compile_signature(self):
        a = validate_spec(_spec())
        b = validate_spec(_spec(config={**CONFIG, "bucket_ladder": "auto"}))
        c = validate_spec(
            _spec(config={**CONFIG, "bucket_ladder": [32, 128]})
        )
        assert len({spec_signature(s) for s in (a, b, c)}) == 3

    def test_invalid_ladder_config_rejected_at_submission(self):
        with pytest.raises(ValueError, match="bucket_ladder"):
            validate_spec(_spec(config={**CONFIG, "bucket_ladder": [7, 9]}))
        with pytest.raises(ValueError, match="bucket_ladder"):
            validate_spec(_spec(config={**CONFIG, "bucket_ladder": 12}))
        # well-formed but top rung != capacity: the explicit ladder
        # would silently replace the capacity the @PG CL records
        # (serve_provenance excludes bucket_ladder), so the recorded
        # command line could no longer reproduce the job's bytes
        with pytest.raises(ValueError, match="top rung"):
            validate_spec(
                _spec(config={**CONFIG, "bucket_ladder": [32, 256]})
            )

    def test_fleet_converges_through_the_verdict_store(
        self, sim, tmp_path
    ):
        from duplexumiconsensusreads_tpu import tuning

        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        outs = [str(tmp_path / f"o{i}.bam") for i in range(2)]
        for o in outs:
            client.submit(
                spool, in_path, o,
                config={**CONFIG, "bucket_ladder": "auto"},
            )
        svc_trace = str(tmp_path / "svc.trace.jsonl")
        svc = ConsensusService(spool, chunk_budget=0,
                               trace_path=svc_trace)
        snap = svc.run_until_idle()
        assert snap["jobs_done"] == 2
        for o in outs:
            with open(o, "rb") as f:
                assert f.read() == ref_bytes
        # job 1 profiled fresh and PERSISTED; job 2 (same input profile)
        # REUSED the stored verdict instead of re-profiling
        assert svc.worker.n_verdict_puts == 1
        assert svc.worker.n_verdict_hits == 1
        # ...and BOTH decisions are ledgered in the service capture
        # (KNOWN_EVENTS tuner_verdict: the fleet's shape decisions are
        # auditable from any capture), on their jobs' lanes
        with open(svc_trace) as f:
            recs = [json.loads(ln) for ln in f if ln.strip()]
        tv = [r for r in recs
              if r.get("type") == "event" and r.get("name") == "tuner_verdict"]
        assert sorted(r["source"] for r in tv) == ["run", "store"]
        for r in tv:
            assert r["ladder"][-1] == CONFIG["capacity"]
            assert r["lane"] == f"job-{r['job']}"
        store = tuning.VerdictStore(os.path.join(spool,
                                                 "tuner_verdicts.json"))
        assert len(store) == 1
        sig = spec_signature(
            validate_spec(_spec(config={**CONFIG, "bucket_ladder": "auto"}))
        )
        hit = store.get(tuning.profile_key(in_path, sig))
        assert hit is not None and hit["ladder"][-1] == CONFIG["capacity"]
        # a SECOND daemon on the same spool starts converged: its first
        # auto job is a store hit, zero fresh profiles
        out3 = str(tmp_path / "o3.bam")
        client.submit(spool, in_path, out3,
                      config={**CONFIG, "bucket_ladder": "auto"})
        svc2 = ConsensusService(spool, chunk_budget=0)
        # jobs_done includes the 2 journal-rebuilt completions (the
        # restart-truthful-counters contract) plus this one
        assert svc2.run_until_idle()["jobs_done"] == 3
        assert svc2.worker.n_verdict_hits == 1
        assert svc2.worker.n_verdict_puts == 0
        with open(out3, "rb") as f:
            assert f.read() == ref_bytes

    def test_wrong_capacity_stored_verdict_is_refused(self, sim, tmp_path):
        """A well-formed store entry whose top rung != the job's
        capacity must NOT be reused: it would silently change the run's
        effective capacity (and the oversized/jumbo escape thresholds)
        while the @PG CL still claims the configured one. The slice
        re-profiles honestly and overwrites the bad entry."""
        from duplexumiconsensusreads_tpu import tuning
        from duplexumiconsensusreads_tpu.serve.worker import verdict_key

        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        out = str(tmp_path / "o.bam")
        cfg = {**CONFIG, "bucket_ladder": "auto"}
        client.submit(spool, in_path, out, config=cfg)
        vkey = verdict_key(
            validate_spec(_spec(input=in_path, config=cfg))
        )
        store = tuning.VerdictStore(
            os.path.join(spool, "tuner_verdicts.json")
        )
        # valid pow2 ascending, but top rung 64 != capacity 128
        store.put(vkey, {"ladder": [32, 64], "source": "run"})
        svc = ConsensusService(spool, chunk_budget=0)
        assert svc.run_until_idle()["jobs_done"] == 1
        with open(out, "rb") as f:
            assert f.read() == ref_bytes
        assert svc.worker.n_verdict_hits == 0
        assert svc.worker.n_verdict_puts == 1
        assert store.get(vkey)["ladder"][-1] == CONFIG["capacity"]


# ------------------------------------------------------- lease stores

class TestLeaseStore:
    """The store seam itself: per-spool marker pinning, the
    backend-specific lease documents, and the sharedfs heartbeat
    document round trip."""

    def test_fresh_spool_defaults_local_without_pinning(self, tmp_path):
        store = resolve_store(str(tmp_path))
        assert store.kind == "local"
        # clients never pin: a status read must not mutate the spool
        assert not os.path.exists(str(tmp_path / STORE_MARKER))

    def test_daemon_pins_and_conflicts_fail_loudly(self, tmp_path):
        spool = str(tmp_path / "spool")
        resolve_store(spool, "sharedfs", pin=True)
        with open(os.path.join(spool, STORE_MARKER)) as f:
            assert json.load(f)["store"] == "sharedfs"
        # no kind requested -> the pin decides, for daemons and clients
        assert resolve_store(spool).kind == "sharedfs"
        with pytest.raises(ValueError, match="pinned"):
            resolve_store(spool, "local")
        with pytest.raises(ValueError, match="unknown lease store"):
            resolve_store(str(tmp_path / "other"), "redis")

    def test_implicit_local_default_is_pinned_by_the_first_daemon(
        self, tmp_path
    ):
        spool = str(tmp_path / "spool")
        assert resolve_store(spool, None, pin=True).kind == "local"
        # the SECOND daemon cannot diverge from the implicit default
        with pytest.raises(ValueError, match="pinned"):
            resolve_store(spool, "sharedfs")

    def test_local_docs_keep_the_single_host_shape(self):
        store = LocalLeaseStore()
        doc = store.lease_doc("d-1", 30.0)
        assert set(doc) == {"owner", "pid", "host", "expires_m"}
        assert doc["owner"] == "d-1" and doc["pid"] == os.getpid()
        rec = store.claim_rec("d-1", 3)
        assert rec["pid"] == os.getpid() and rec["token"] == 3
        assert store.pid_alive(os.getpid())

    def test_sharedfs_docs_carry_no_pid(self, tmp_path):
        store = SharedFsLeaseStore(str(tmp_path), host_id="h-A")
        doc = store.lease_doc("d-1", 30.0)
        assert set(doc) == {"owner", "host", "boot", "expires_m"}
        assert doc["host"] == "h-A" and doc["boot"] == store.boot
        assert "pid" not in store.claim_rec("d-1", 1)
        # staging litter stamped with another host's pid is
        # unprobeable: never reap
        assert store.pid_alive(2 ** 30)

    def test_heartbeat_documents_round_trip_observe(self, tmp_path):
        a = SharedFsLeaseStore(str(tmp_path), host_id="h-A")
        b = SharedFsLeaseStore(str(tmp_path), host_id="h-B")
        a.attach("d-A", 0.5)
        b.attach("d-B", 0.5)
        # torn/alien documents are skipped, never fatal
        with open(str(tmp_path / "hosts" / "junk.json"), "w") as f:
            f.write("{not json")
        seen = b.observe()
        assert set(seen) == {"d-A", "d-B"}
        assert seen["d-A"]["host_id"] == "h-A"
        assert seen["d-A"]["boot"] == a.boot
        assert seen["d-A"]["stale_s"] == pytest.approx(1.0)
        # beats refresh the stamp monotonically (in the shared domain)
        first = seen["d-A"]["stamp_m"]
        a.beat()
        assert b.observe()["d-A"]["stamp_m"] >= first


# the synthetic-host epoch matrix: zero, fractional, negative, and
# day-sized skews in both directions — every pair must agree after
# calibration, or a cross-host lease verdict is undefined
SKEW_MATRIX = [
    (0.0, 0.0),
    (0.0, 137.25),
    (-250.5, 9999.0),
    (86400.0, -86400.0),
]


class TestClockMatrix:
    """Clock-domain translation: the sharedfs probe calibration must
    cancel arbitrary per-host monotonic epochs exactly, so lease
    verdicts are invariant under skew — the property the whole
    pid-free takeover story stands on."""

    @pytest.mark.parametrize("skew_a,skew_b", SKEW_MATRIX)
    def test_now_agrees_across_skewed_hosts(self, tmp_path, skew_a,
                                            skew_b):
        a = SharedFsLeaseStore(str(tmp_path), "h-A", skew_a)
        b = SharedFsLeaseStore(str(tmp_path), "h-B", skew_b)
        # error budget: two write-to-stat probe latencies + timestamp
        # granularity — far under any sane lease_s
        assert abs(a.now() - b.now()) < 0.05
        t0 = a.now()
        time.sleep(0.05)
        assert a.now() > t0  # the translated clock still advances

    @pytest.mark.parametrize("skew_a,skew_b", SKEW_MATRIX)
    def test_lease_verdicts_are_skew_invariant(self, tmp_path, skew_a,
                                               skew_b):
        a = SharedFsLeaseStore(str(tmp_path), "h-A", skew_a)
        b = SharedFsLeaseStore(str(tmp_path), "h-B", skew_b)
        a.attach("d-A", 0.25)
        lease = a.lease_doc("d-A", 0.25)
        hosts = b.observe()
        # held lease: every observer agrees, whatever its epoch
        assert a.reclaim_reason(lease, a.now(), hosts=hosts) is None
        assert b.reclaim_reason(lease, b.now(), hosts=hosts) is None
        time.sleep(0.35)
        # expired lease: every observer agrees, by translated expiry
        assert a.reclaim_reason(lease, a.now(), hosts=hosts) == "expired"
        assert b.reclaim_reason(lease, b.now(), hosts=hosts) == "expired"

    def test_restarted_daemon_is_reclaimed_instantly(self, tmp_path):
        first = SharedFsLeaseStore(str(tmp_path), "h-A", 500.0)
        first.attach("d-A", 30.0)
        lease = first.lease_doc("d-A", 30.0)  # far-future expiry
        peer = SharedFsLeaseStore(str(tmp_path), "h-B", -500.0)
        assert peer.reclaim_reason(
            lease, peer.now(), hosts=peer.observe()
        ) is None
        # the daemon restarts: same daemon id, NEW boot nonce — its
        # own heartbeat document is the proof, no 30s lease wait
        second = SharedFsLeaseStore(str(tmp_path), "h-A", 123.0)
        second.attach("d-A", 30.0)
        assert second.boot != first.boot
        assert peer.reclaim_reason(
            lease, peer.now(), hosts=peer.observe()
        ) == "restarted"

    def test_stale_heartbeat_is_the_backstop_for_garbage_expiry(
        self, tmp_path
    ):
        b = SharedFsLeaseStore(str(tmp_path), "h-B")
        boot = "cafecafecafe"
        lease = {"owner": "d-X", "host": "h-X", "boot": boot,
                 "expires_m": b.now() + 1e9}  # untrustworthy expiry
        hosts = {"d-X": {"boot": boot, "stamp_m": b.now() - 10.0,
                         "stale_s": 1.0}}
        assert b.reclaim_reason(lease, b.now(), hosts=hosts) == "dead-owner"
        # a fresh heartbeat holds even a garbage-expiry lease in place
        hosts["d-X"]["stamp_m"] = b.now()
        assert b.reclaim_reason(lease, b.now(), hosts=hosts) is None

    def test_in_process_registry_is_inadmissible_cross_host(
        self, tmp_path
    ):
        # the local backend's is_live registry is single-host evidence;
        # the sharedfs ladder must ignore it entirely
        b = SharedFsLeaseStore(str(tmp_path), "h-B")
        lease = b.lease_doc("d-X", 30.0)
        assert b.reclaim_reason(
            lease, b.now(), is_live=lambda owner: False, hosts={}
        ) is None


class TestJournalLockBound:
    """Bounded journal-lock acquisition: a wedged peer's flock
    surfaces as a typed JournalLockTimeout plus one ledgered
    lock_stall event — and the liveness heartbeat keeps beating,
    because the heartbeat document is journal-lock-free by design."""

    def test_wedged_flock_times_out_typed_stalls_and_beats(
        self, tmp_path
    ):
        import fcntl

        from duplexumiconsensusreads_tpu.telemetry import trace as trace_mod

        spool = str(tmp_path / "spool")
        q = SpoolQueue(spool, lock_timeout_s=1.4)
        store = SharedFsLeaseStore(spool, host_id="h-A")
        store.attach("d-A", 0.5)
        beats_before = store.observe()["d-A"]["beats"]
        cap = str(tmp_path / "cap.jsonl")
        rec = trace_mod.TraceRecorder(cap, kind="service")
        trace_mod.install(rec)
        holder = os.open(q._lock_path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(holder, fcntl.LOCK_EX)  # the wedged peer
            t0 = time.monotonic()
            with pytest.raises(JournalLockTimeout) as exc:
                q.refresh()  # any journal transaction takes the flock
            waited = time.monotonic() - t0
            # typed AND absorbable: the OSError ladders that wrap
            # journal transactions treat it as one more I/O failure
            assert isinstance(exc.value, OSError)
            assert "journal.lock" in str(exc.value)
            assert 1.3 <= waited < 10.0
            # the heartbeat does not need the journal lock
            store.beat()
            assert store.observe()["d-A"]["beats"] > beats_before
        finally:
            trace_mod.uninstall()
            rec.close()
            os.close(holder)
        with open(cap) as f:
            ev = [json.loads(ln) for ln in f]
        stalls = [e for e in ev if e.get("name") == "lock_stall"]
        assert len(stalls) == 1  # one-shot, not one per poll
        assert stalls[0]["waited_s"] >= 1.0
        assert stalls[0]["spool"] == spool

    def test_zero_timeout_disables_the_bound(self, tmp_path):
        # lock_timeout_s <= 0 keeps the old unbounded-wait contract;
        # the uncontended fast path is a single non-blocking attempt
        q = SpoolQueue(str(tmp_path), lock_timeout_s=0.0)
        assert q.lock_timeout_s == 0.0
        jid = q.submit(validate_spec(_spec(input=__file__)))
        spec, reason = q.accept_one(jid)
        assert reason is None and q.jobs[jid]["state"] == "queued"


class TestDiagnosisCaptureOrder:
    """The quarantine diagnosis scans service captures newest-first —
    'newest' meaning stitched event time (meta epoch_m + last relative
    t), NOT file mtime, which is meaningless across hosts."""

    @staticmethod
    def _capture(spool, name, epoch, t, site):
        p = os.path.join(spool, f"service.{name}.trace.jsonl")
        with open(p, "w") as f:
            f.write(json.dumps({
                "type": "meta", "version": 1, "kind": "service",
                "clock": "monotonic-relative", "epoch_m": epoch,
            }) + "\n")
            f.write(json.dumps({
                "type": "event", "name": "fault_injected", "t": t,
                "site": site, "hit": 1, "kind": "oserror",
            }) + "\n")
        return p

    def test_stitched_end_beats_contradicting_mtimes(self, tmp_path):
        spool = str(tmp_path)
        q = SpoolQueue(spool)
        newest = self._capture(spool, "new", 1000.0, 5.0, "serve.renew")
        stale = self._capture(spool, "old", 900.0, 1.0, "serve.lease")
        # contradicting mtimes: the STALE capture looks newest on disk
        # (a skewed host's wall clock, a coarse shared-fs timestamp)
        os.utime(newest, (1, 1))
        os.utime(stale, (2_000_000_000, 2_000_000_000))
        diag = q._diagnosis({"crash_count": 1}, "watchdog")
        assert diag["last_fault_site"] == "serve.renew"

    def test_pre_fleet_captures_fall_back_to_mtime_behind_epochs(
        self, tmp_path
    ):
        spool = str(tmp_path)
        q = SpoolQueue(spool)
        # a legacy capture with no epoch_m, newest mtime of all
        legacy = os.path.join(spool, "service.trace.jsonl")
        with open(legacy, "w") as f:
            f.write(json.dumps({"type": "meta", "version": 1}) + "\n")
            f.write(json.dumps({
                "type": "event", "name": "fault_injected", "t": 2.0,
                "site": "serve.fence", "hit": 1, "kind": "oserror",
            }) + "\n")
        epoch = self._capture(spool, "new", 50.0, 0.5, "serve.renew")
        os.utime(legacy, (2_000_000_000, 2_000_000_000))
        os.utime(epoch, (1, 1))
        # epoch-bearing captures rank ahead of every mtime-ranked one
        diag = q._diagnosis({}, "watchdog")
        assert diag["last_fault_site"] == "serve.renew"


# --------------------------------------------------- cross-host fleet

class TestCrossHost:
    """The multi-host chaos matrix: one sharedfs spool shared by
    synthetic hosts (distinct host ids, wildly skewed monotonic
    epochs), daemons dying mid-slice / mid-split / mid-merge. Pins:
    the surviving host converges to byte-identical output exactly
    once, and no takeover verdict ever rests on pid evidence."""

    @staticmethod
    def _store(spool, host, skew):
        return resolve_store(spool, "sharedfs", pin=True,
                             host_id=host, epoch_skew=skew)

    def test_host_killed_mid_slice_pid_free_takeover(self, sim, tmp_path):
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        store_a = self._store(spool, "host-A", 7200.0)
        jid, out = _submit_n(spool, in_path, tmp_path, 1)[0]
        t_a = str(tmp_path / "a.jsonl")
        svc_a = ConsensusService(
            spool, chunk_budget=0, poll_s=0.02, trace_path=t_a,
            lease_s=0.4, daemon_id="xh-A", store=store_a,
        )
        orig = svc_a.worker.run_slice

        def dying_run_slice(spec, budget, should_yield, drain_event,
                            lease=None):
            def die():
                raise faults.InjectedKill("host-A dies mid-slice")

            # budget=1: one fresh chunk commits durably, then the
            # yield check kills the daemon with the lease still held
            return orig(spec, 1, die, drain_event, lease=lease)

        svc_a.worker.run_slice = dying_run_slice
        with pytest.raises(faults.InjectedKill):
            svc_a.run_until_idle()
        entry = SpoolQueue(spool).jobs[jid]
        assert entry["state"] == "running"
        # the lease carries NO pid: there is nothing for a pid probe
        # to consult, on this host or any other
        assert entry["lease"]["owner"] == "xh-A"
        assert "pid" not in entry["lease"]
        assert entry["lease"]["boot"] == store_a.boot
        time.sleep(0.5)  # the dead host's lease expires (shared domain)
        t_b = str(tmp_path / "b.jsonl")
        store_b = self._store(spool, "host-B", -3600.0)
        snap_b = ConsensusService(
            spool, poll_s=0.02, trace_path=t_b, lease_s=0.4,
            daemon_id="xh-B", store=store_b,
        ).run_until_idle()
        assert snap_b["jobs_done"] == 1 and snap_b["jobs_recovered"] == 1
        with open(out, "rb") as f:
            assert f.read() == ref_bytes
        entry = SpoolQueue(spool).jobs[jid]
        assert entry["state"] == "done" and entry["token"] == 2
        completed = []
        for tp in (t_a, t_b):
            _, ev = _events(tp)
            completed += [e for e in ev if e["name"] == "job_completed"]
        assert len(completed) == 1  # exactly once, by host B
        _, ev_b = _events(t_b)
        tk = [e for e in ev_b if e["name"] == "lease_takeover"]
        assert len(tk) == 1 and tk[0]["reason"] == "expired"
        assert tk[0]["prev_owner"] == "xh-A"

    def test_restarted_host_reclaims_instantly_despite_long_lease(
        self, sim, tmp_path
    ):
        """Host A dies mid-slice holding a LONG (30s) lease; the same
        daemon id comes back with a fresh boot nonce. Its heartbeat
        document proves the restart, so the reclaim is instant — the
        'restarted' rung, not a 30s expiry wait."""
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        store_a = self._store(spool, "host-A", 300.0)
        jid, out = _submit_n(spool, in_path, tmp_path, 1)[0]
        svc_a = ConsensusService(
            spool, chunk_budget=0, poll_s=0.02,
            trace_path=str(tmp_path / "a.jsonl"),
            lease_s=30.0, daemon_id="xh-A", store=store_a,
        )
        orig = svc_a.worker.run_slice

        def dying_run_slice(spec, budget, should_yield, drain_event,
                            lease=None):
            def die():
                raise faults.InjectedKill("host-A dies mid-slice")

            return orig(spec, 1, die, drain_event, lease=lease)

        svc_a.worker.run_slice = dying_run_slice
        with pytest.raises(faults.InjectedKill):
            svc_a.run_until_idle()
        # the restart: same spool, same daemon id, NEW store boot
        store_a2 = self._store(spool, "host-A", 301.5)
        assert store_a2.boot != store_a.boot
        t2 = str(tmp_path / "a2.jsonl")
        t0 = time.monotonic()
        snap = ConsensusService(
            spool, poll_s=0.02, trace_path=t2, lease_s=30.0,
            daemon_id="xh-A", store=store_a2,
        ).run_until_idle()
        assert time.monotonic() - t0 < 25.0  # no lease-length wait
        assert snap["jobs_done"] == 1 and snap["jobs_recovered"] == 1
        with open(out, "rb") as f:
            assert f.read() == ref_bytes
        _, ev = _events(t2)
        tk = [e for e in ev if e["name"] == "lease_takeover"]
        assert len(tk) == 1 and tk[0]["reason"] == "restarted"

    @pytest.mark.parametrize("site", ["serve.split", "serve.merge"])
    def test_host_killed_at_shard_site_other_host_converges(
        self, site, sim, tmp_path
    ):
        """A K-sharded parent crosses hosts: host A dies inside the
        split txn / the merge sweep; host B re-runs the stage under
        its own fencing token — children registered once, merge
        published once, bytes identical, and every takeover verdict
        in the matrix is 'expired' or 'restarted', never pid-based."""
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        store_a = self._store(spool, "host-A", 12345.0)
        out = str(tmp_path / "out.bam")
        jid = client.submit(spool, in_path, out, config=dict(CONFIG),
                            shards=3)
        faults.install(faults.FaultPlan.parse(f"{site}:1:kill"))
        t_a = str(tmp_path / "a.jsonl")
        with pytest.raises(faults.InjectedKill):
            ConsensusService(
                spool, poll_s=0.02, lease_s=0.4, trace_path=t_a,
                daemon_id="xh-A", store=store_a,
            ).run_until_idle()
        faults.uninstall()
        time.sleep(0.5)
        t_b = str(tmp_path / "b.jsonl")
        store_b = self._store(spool, "host-B", -777.25)
        ConsensusService(
            spool, poll_s=0.02, lease_s=0.4, trace_path=t_b,
            daemon_id="xh-B", store=store_b,
        ).run_until_idle()
        st = client.status(spool, jid)
        assert st["state"] == "done"
        with open(out, "rb") as f:
            assert f.read() == ref_bytes
        completed, takeovers = [], []
        for tp in (t_a, t_b):
            _, ev = _events(tp)
            completed += [
                e for e in ev
                if e["name"] == "job_completed" and e["job"] == jid
            ]
            takeovers += [e for e in ev if e["name"] == "lease_takeover"]
        assert len(completed) == 1
        # pid evidence is inadmissible cross-host: any takeover in the
        # matrix is by translated expiry or restart proof. The split
        # kill is guaranteed one (it dies holding the splitting lease);
        # the merge kill lands in the advance sweep, which may run
        # lease-free — takeover only if B found a claim to reclaim.
        assert all(
            e["reason"] in ("expired", "restarted") for e in takeovers
        )
        if site == "serve.split":
            assert takeovers

    def test_two_subprocess_hosts_sigkill_and_fleet_report(
        self, sim, tmp_path
    ):
        """The real thing, cross-host flavoured: two dut-serve
        subprocesses on one sharedfs spool, each a synthetic host
        (DUT_HOST_ID + DUT_HOST_EPOCH_SKEW). Host A is SIGKILLed
        mid-slice; host B — whose kernel knows nothing of A's pid —
        takes over by translated lease expiry, finishes byte-identical
        exactly once, and the stitched fleet report is green across
        both hosts' captures."""
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        jid, out = _submit_n(spool, in_path, tmp_path, 1)[0]
        env_a = dict(os.environ, JAX_PLATFORMS="cpu",
                     DUT_HOST_ID="host-A", DUT_HOST_EPOCH_SKEW="3600.5")
        proc = subprocess.Popen(
            [sys.executable, "-m",
             "duplexumiconsensusreads_tpu.serve.daemon",
             spool, "--poll", "0.05", "--heartbeat", "0.2",
             "--lease", "1", "--store", "sharedfs",
             "--daemon-id", "xh-A"],
            env=env_a, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        try:
            deadline = time.monotonic() + 120
            claimed = False
            while time.monotonic() < deadline:
                st = client.status(spool, jid)
                if st.get("state") == "running" and st.get("lease"):
                    claimed = True
                    break
                if proc.poll() is not None:
                    break
                time.sleep(0.05)
            assert claimed, (
                proc.communicate()[1] if proc.poll() is not None
                else "job never claimed"
            )
            proc.kill()  # SIGKILL: no drain, the lease stays journaled
            proc.communicate()
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.communicate()
        st = client.status(spool, jid)
        assert st["state"] == "running" and st["lease"]["owner"] == "xh-A"
        assert "pid" not in st["lease"]  # nothing for a pid probe to read
        # the status read answers in the STORE's clock domain — what
        # client.status_document computes its countdowns against
        assert isinstance(st.get("now_m"), float)
        time.sleep(1.2)  # A's 1s lease expires in the shared domain
        env_b = dict(os.environ, JAX_PLATFORMS="cpu",
                     DUT_HOST_ID="host-B",
                     DUT_HOST_EPOCH_SKEW="-7200.25")
        p2 = subprocess.run(
            [sys.executable, "-m",
             "duplexumiconsensusreads_tpu.serve.daemon",
             spool, "--once", "--poll", "0.05", "--heartbeat", "0.2",
             "--lease", "1", "--daemon-id", "xh-B"],
            env=env_b, cwd=REPO, capture_output=True, text=True,
            timeout=300,
        )
        assert p2.returncode == 0, p2.stderr
        # --store omitted on B: the spool's marker pin decides, and the
        # startup banner names the inherited backend
        assert "store=sharedfs" in p2.stderr
        st = client.status(spool, jid)
        assert st["state"] == "done" and st["token"] == 2
        with open(out, "rb") as f:
            assert f.read() == ref_bytes
        b_trace = os.path.join(spool, "service.xh-B.trace.jsonl")
        recs, ev = _events(b_trace)
        assert trace_report.validate_service_trace(recs) == []
        tk = [e for e in ev if e["name"] == "lease_takeover"]
        assert len(tk) == 1 and tk[0]["reason"] == "expired"
        assert tk[0]["prev_owner"] == "xh-A"
        assert len([e for e in ev if e["name"] == "job_completed"]) == 1
        # both hosts heartbeat durable liveness documents
        hosts_dir = os.path.join(spool, "hosts")
        assert {"xh-A.json", "xh-B.json"} <= set(os.listdir(hosts_dir))
        # the stitched fleet report crosses both hosts' captures green
        p3 = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "fleet_report.py"),
             spool, "--json"],
            capture_output=True, text=True, timeout=120,
        )
        assert p3.returncode == 0, p3.stderr
        rep = json.loads(p3.stdout)
        assert rep["ok"] is True and rep["problems"] == []
        assert jid in rep["jobs"]


class TestXhostBenchRegistry:
    def test_xhost_keys_ride_the_compact_line_and_trajectory(self):
        from duplexumiconsensusreads_tpu import benchhist
        from duplexumiconsensusreads_tpu.benchmark import COMPACT_KEYS

        gates = {k: g for k, _, g in benchhist.CANONICAL_METRICS}
        for key in ("serve_xhost_takeover_latency_s",
                    "serve_xhost_recovered"):
            assert key in COMPACT_KEYS
            assert key in gates
            # takeover latency is lease-expiry-dominated by design
            # (pid-free detection waits out the translated lease):
            # informational, never gated
            assert not gates[key]
