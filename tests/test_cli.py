"""End-to-end CLI tests: simulate → call (all five benchmark presets,
both backends) → validate against simulation truth. These are the
framework's acceptance tests for the driver's five configs."""

import json
import os
import zlib

import numpy as np
import pytest

from duplexumiconsensusreads_tpu.cli import main
from duplexumiconsensusreads_tpu.io import read_bam


def _simulate(tmp_path, **kw):
    bam = str(tmp_path / "sim.bam")
    truth = str(tmp_path / "truth.npz")
    args = [
        "simulate",
        "-o",
        bam,
        "--truth",
        truth,
        "--molecules",
        str(kw.get("molecules", 60)),
        "--read-len",
        "40",
        "--positions",
        "6",
        "--umi-error",
        str(kw.get("umi_error", 0.0)),
        "--base-error",
        str(kw.get("base_error", 0.01)),
        "--cycle-error-slope",
        str(kw.get("cycle_error_slope", 0.0)),
        "--seed",
        str(kw.get("seed", 0)),
    ]
    if kw.get("single_strand"):
        args.append("--single-strand")
    if kw.get("sorted"):
        args.append("--sorted")
    assert main(args) == 0
    return bam, truth


@pytest.mark.parametrize("config", ["config1", "config2", "config3", "config4", "config5"])
def test_call_presets_tpu(tmp_path, config):
    single = config in ("config1", "config2")
    bam, truth = _simulate(
        tmp_path,
        single_strand=single,
        umi_error=0.02 if config != "config1" else 0.0,
        cycle_error_slope=0.002 if config == "config5" else 0.0,
        seed=zlib.crc32(config.encode()) % 1000,
    )
    out = str(tmp_path / "cons.bam")
    report = str(tmp_path / "report.json")
    assert (
        main(
            [
                "call",
                bam,
                "-o",
                out,
                "--config",
                config,
                "--backend",
                "tpu",
                "--capacity",
                "512",
                "--report",
                report,
            ]
        )
        == 0
    )
    with open(report) as f:
        rep = json.load(f)
    assert rep["n_consensus"] > 0
    assert rep["n_valid_reads"] == rep["n_records"]

    _, recs = read_bam(out)
    assert len(recs) == rep["n_consensus"]
    assert all(u for u in recs.umi)  # every consensus carries RX
    assert all(b"cD" in a for a in recs.aux_raw)


def test_cpu_tpu_backends_agree(tmp_path):
    bam, truth = _simulate(tmp_path, umi_error=0.02, seed=5)
    out_cpu = str(tmp_path / "cpu.bam")
    out_tpu = str(tmp_path / "tpu.bam")
    for backend, out in (("cpu", out_cpu), ("tpu", out_tpu)):
        assert (
            main(
                ["call", bam, "-o", out, "--config", "config3",
                 "--backend", backend, "--capacity", "512"]
            )
            == 0
        )
    _, r_cpu = read_bam(out_cpu)
    _, r_tpu = read_bam(out_tpu)
    assert len(r_cpu) == len(r_tpu)
    # same molecules called at the same positions with identical bases;
    # quality tolerance ±2 (f32 vs f64 floor boundaries, see
    # tests/test_kernels_parity.py docstring)
    key_cpu = {(int(r_cpu.pos[i]), r_cpu.umi[i]): i for i in range(len(r_cpu))}
    for j in range(len(r_tpu)):
        i = key_cpu[(int(r_tpu.pos[j]), r_tpu.umi[j])]
        np.testing.assert_array_equal(r_cpu.seq[i], r_tpu.seq[j])
        assert np.abs(r_cpu.qual[i].astype(int) - r_tpu.qual[j].astype(int)).max() <= 2


def test_validate_error_rate(tmp_path, capsys):
    bam, truth = _simulate(tmp_path, molecules=80, base_error=0.02, seed=9)
    out = str(tmp_path / "cons.bam")
    assert main(["call", bam, "-o", out, "--config", "config3", "--capacity", "512"]) == 0
    assert main(["validate", out, "--truth", truth, "--json"]) == 0
    res = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert res["n_matched_to_truth"] > 0.9 * res["n_consensus"]
    # duplex consensus must crush the raw 2% error rate
    assert res["error_rate"] < 0.002
    assert res["n_bases"] > 0
    assert sum(res["unmatched"].values()) == res["n_unmatched"]


def test_validate_unmatched_classification(tmp_path, capsys):
    """With UMI read errors, every unmatched consensus must be explained:
    over-split or seed-mismatch (both Hamming<=1 artifacts of UMI
    errors), never a position miss, and multi-error 'other' rare
    (VERDICT r1 item 9)."""
    bam, truth = _simulate(
        tmp_path, molecules=150, umi_error=0.04, seed=11, single_strand=True
    )
    out = str(tmp_path / "cons.bam")
    assert main(["call", bam, "-o", out, "--config", "config2", "--capacity", "512"]) == 0
    assert main(["validate", out, "--truth", truth, "--json"]) == 0
    res = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    cls = res["unmatched"]
    assert sum(cls.values()) == res["n_unmatched"]
    # simulator only moves reads, never invents coordinates
    assert cls["position_miss"] == 0
    # Per-class CEILINGS at these fixed sim parameters (VERDICT r2 item
    # 8: a clustering regression that doubles a class must fail CI).
    # Measured on this exact sim (seed 11, 150 molecules, 4% UMI error,
    # deterministic): 156 consensus, 12 seed-mismatch, 7 other, 0
    # over-split. Bounds are 1.5x the measured values.
    assert res["n_consensus"] <= 170  # over-splitting inflates calls
    assert cls["seed_mismatch"] <= 18
    assert cls["other"] <= 10
    assert cls["over_split"] <= 5
    if res["n_unmatched"]:
        assert cls["over_split"] + cls["seed_mismatch"] > 0


def test_config_file_layer(tmp_path):
    """--config-file supplies call settings; explicit flags override it;
    unknown keys are rejected (VERDICT r1 weak #6)."""
    bam, truth = _simulate(tmp_path, molecules=40, seed=21)
    out = str(tmp_path / "o.bam")
    conf = str(tmp_path / "c.json")
    with open(conf, "w") as f:
        json.dump(
            {"config": "config3", "capacity": 256, "min_duplex_reads": 1}, f
        )
    rep_path = str(tmp_path / "r.json")
    assert main(
        ["call", bam, "-o", out, "--config-file", conf, "--report", rep_path]
    ) == 0
    rep = json.load(open(rep_path))
    assert rep["n_consensus"] > 0
    # file can be TOML too; drain_workers round-trips through the
    # config schema onto the streaming executor (which needs a
    # coordinate-sorted input)
    bam_s, _ = _simulate(tmp_path, molecules=40, seed=21, sorted=True)
    conf_t = str(tmp_path / "c.toml")
    with open(conf_t, "w") as f:
        f.write(
            'config = "config3"\ncapacity = 256\n'
            "chunk_reads = 120\ndrain_workers = 3\n"
        )
    rep_t_path = str(tmp_path / "rt.json")
    assert main(
        ["call", bam_s, "-o", out, "--config-file", conf_t,
         "--report", rep_t_path]
    ) == 0
    assert json.load(open(rep_t_path))["n_drain_workers"] == 3
    # unknown keys must be rejected, not ignored
    bad = str(tmp_path / "bad.json")
    with open(bad, "w") as f:
        json.dump({"capcity": 256}, f)
    import pytest as _pytest

    with _pytest.raises(SystemExit, match="unknown config-file keys"):
        main(["call", bam, "-o", out, "--config-file", bad])
    # explicit flag beats file: min-reads 3 shrinks the call set
    rep2_path = str(tmp_path / "r2.json")
    assert main(
        ["call", bam, "-o", out, "--config-file", conf, "--min-reads", "3",
         "--report", rep2_path]
    ) == 0
    assert json.load(open(rep2_path))["n_consensus"] < rep["n_consensus"]


def test_stats_subcommand(tmp_path, capsys):
    bam, _ = _simulate(tmp_path, molecules=80, umi_error=0.02, seed=31)
    assert main(["stats", bam, "--duplex", "--json"]) == 0
    res = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert res["n_valid_reads"] > 0
    assert res["n_molecules"] > 0
    assert res["n_families"] >= res["n_molecules"]
    assert sum(res["family_size_hist"].values()) == res["n_families"]
    assert res["duplex_complete_molecules"] > 0
    assert res["mean_family_size"] > 0
    # CollectDuplexSeqMetrics-style strand-pair metrics: the size-pair
    # histogram counts every molecule once, and the yield curve is
    # monotone with min_reads=1 equal to the duplex-complete fraction
    assert sum(res["duplex_family_size_hist"].values()) <= res["n_molecules"]
    y = res["duplex_yield"]
    assert y["min_reads=1"] == round(
        res["duplex_complete_molecules"] / res["n_molecules"], 4
    )
    assert y["min_reads=1"] >= y["min_reads=2"] >= y["min_reads=3"] >= y["min_reads=5"]


def test_npz_input(tmp_path):
    from duplexumiconsensusreads_tpu.io import save_readbatch
    from duplexumiconsensusreads_tpu.simulate import SimConfig, simulate_batch

    batch, _ = simulate_batch(SimConfig(n_molecules=30, duplex=True, seed=2))
    p = str(tmp_path / "b.npz")
    save_readbatch(p, batch)
    out = str(tmp_path / "cons.bam")
    assert main(["call", p, "-o", out, "--config", "config3", "--capacity", "256"]) == 0
    _, recs = read_bam(out)
    assert len(recs) > 0


def test_unknown_backend_rejected(tmp_path):
    with pytest.raises(SystemExit):
        main(["call", "x.bam", "-o", "y.bam", "--backend", "gpu"])


@pytest.mark.xfail(
    strict=False,
    reason="needs the package pip-installed into site-packages; this "
    "container runs from the source tree only (PYTHONPATH), so the "
    "tempdir subprocess cannot import it",
)
def test_installed_entry_point_from_tempdir(tmp_path):
    """The package must work installed: module entry point runnable from
    an arbitrary cwd with the repo root NOT on sys.path (VERDICT item 7)."""
    import subprocess
    import sys

    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    code = (
        "import duplexumiconsensusreads_tpu, sys;"
        "from duplexumiconsensusreads_tpu.cli import main;"
        "sys.exit(main(['simulate', '--out', 'x.bam', '--molecules', '5']))"
    )
    r = subprocess.run(
        [sys.executable, "-c", code],
        cwd=str(tmp_path),
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert r.returncode == 0, r.stderr
    assert (tmp_path / "x.bam").exists()


def test_group_subcommand_tags_molecules(tmp_path, capsys):
    """`group` = the standalone UmiGrouper operator: every groupable
    read gets an MI:Z tag; reads of one oracle molecule share the MI
    stem; duplex mode carries the /A-/B strand suffix; records are
    otherwise byte-preserved."""
    bam, truth = _simulate(tmp_path, molecules=60, umi_error=0.02, seed=17)
    out = str(tmp_path / "grouped.bam")
    assert main([
        "group", bam, "-o", out, "--grouping", "adjacency", "--duplex",
        "--json",
    ]) == 0
    res = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert res["n_tagged"] > 0 and res["n_molecules"] > 0

    from duplexumiconsensusreads_tpu.io.convert import records_to_readbatch
    from duplexumiconsensusreads_tpu.oracle import group_reads
    from duplexumiconsensusreads_tpu.types import GroupingParams

    _, r_in = read_bam(bam)
    _, r_out = read_bam(out)
    assert len(r_in) == len(r_out)
    assert r_out.names == r_in.names
    np.testing.assert_array_equal(r_out.seq, r_in.seq)

    def mi_of(aux):
        i = aux.find(b"MIZ")
        if i < 0:
            return None
        return aux[i + 3 : aux.index(b"\x00", i)].decode()

    mis = [mi_of(a) for a in r_out.aux_raw]
    assert sum(m is not None for m in mis) == res["n_tagged"]
    # oracle agreement: same oracle molecule <=> same MI stem
    batch, _ = records_to_readbatch(r_in, duplex=True)
    fams = group_reads(batch, GroupingParams(strategy="adjacency", paired=True))
    mol = np.asarray(fams.molecule_id)
    valid = np.asarray(batch.valid, bool)
    stem_to_mol = {}
    for i in np.nonzero(valid & (mol >= 0))[0]:
        assert mis[i] is not None
        stem, suffix = mis[i].split("/")
        assert suffix == ("A" if batch.strand_ab[i] else "B")
        if stem in stem_to_mol:
            assert stem_to_mol[stem] == mol[i]
        else:
            stem_to_mol[stem] = mol[i]
    assert len(stem_to_mol) == res["n_molecules"]


def test_group_matches_call_mate_aware_semantics(tmp_path, capsys):
    """VERDICT r3 weak #4: group exposes the SAME grouping knobs as
    call (--mate-aware auto-resolution, --count-ratio), so its MI
    partition reproduces the family structure call --mate-aware
    consensuses: family == (MI stem, strand suffix, read-number)."""
    import json as _json

    from duplexumiconsensusreads_tpu.io.bam import FLAG_READ2
    from duplexumiconsensusreads_tpu.io.convert import records_to_readbatch
    from duplexumiconsensusreads_tpu.oracle import group_reads
    from duplexumiconsensusreads_tpu.runtime.executor import resolve_mate_aware
    from duplexumiconsensusreads_tpu.types import GroupingParams

    bam = str(tmp_path / "pr.bam")
    assert main([
        "simulate", "-o", bam, "--molecules", "50", "--read-len", "40",
        "--positions", "6", "--umi-error", "0.02", "--seed", "27",
        "--paired-reads", "--sorted",
    ]) == 0
    out = str(tmp_path / "grp.bam")
    assert main(["group", bam, "-o", out, "--duplex", "--json"]) == 0
    res = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert res["mate_aware"] is True  # auto-resolved exactly like call

    _, r_out = read_bam(out)
    mis = []
    for a in r_out.aux_raw:
        i = a.find(b"MIZ")
        mis.append(None if i < 0 else a[i + 3 : a.index(b"\x00", i)].decode())

    # oracle family structure under the SAME resolved params
    batch, info = records_to_readbatch(r_out, duplex=True)
    gp = resolve_mate_aware(
        GroupingParams(strategy="adjacency", paired=True), info, "auto"
    )
    assert gp.mate_aware
    fams = group_reads(batch, gp)
    fam = np.asarray(fams.family_id)
    pair = np.asarray(fams.pair_id)
    valid = np.asarray(batch.valid, bool)
    sel = np.nonzero(valid & (fam >= 0))[0]
    # 1. MI stem == source molecule: bijective with oracle pair_id
    stem_to_mol, mol_to_stem = {}, {}
    for i in sel:
        stem = mis[i].split("/")[0]
        assert stem_to_mol.setdefault(stem, pair[i]) == pair[i]
        assert mol_to_stem.setdefault(pair[i], stem) == stem
    # 2. (MI, readnum) == oracle family: a consumer re-deriving call's
    # consensus units from the annotation gets the identical partition
    key_to_fam, fam_to_key = {}, {}
    for i in sel:
        rn = int(bool(r_out.flags[i] & FLAG_READ2))
        key = (mis[i], rn)
        assert key_to_fam.setdefault(key, fam[i]) == fam[i]
        assert fam_to_key.setdefault(fam[i], key) == key
    assert len(fam_to_key) == int(fams.n_families)


def test_group_backends_agree(tmp_path):
    bam, _ = _simulate(tmp_path, molecules=40, umi_error=0.03, seed=23)
    out_t = str(tmp_path / "t.bam")
    out_c = str(tmp_path / "c.bam")
    assert main(["group", bam, "-o", out_t, "--duplex", "--backend", "tpu"]) == 0
    assert main(["group", bam, "-o", out_c, "--duplex", "--backend", "cpu"]) == 0
    _, a = read_bam(out_t)
    _, b = read_bam(out_c)
    assert a.aux_raw == b.aux_raw


def test_group_regroup_replaces_mi(tmp_path):
    """Re-grouping an already-grouped BAM must REPLACE the MI tag, not
    stack a second one."""
    bam, _ = _simulate(tmp_path, molecules=30, umi_error=0.02, seed=29)
    out1 = str(tmp_path / "g1.bam")
    out2 = str(tmp_path / "g2.bam")
    assert main(["group", bam, "-o", out1, "--duplex"]) == 0
    assert main(["group", out1, "-o", out2, "--duplex"]) == 0
    _, a = read_bam(out1)
    _, b = read_bam(out2)
    for aux_a, aux_b in zip(a.aux_raw, b.aux_raw):
        assert aux_a.count(b"MIZ") <= 1
        assert aux_b.count(b"MIZ") == aux_a.count(b"MIZ")
    # grouping an annotated file reproduces the same partition
    assert a.aux_raw == b.aux_raw


def _cd_array(aux, tag=b"cdB"):
    # subtype-tolerant: the writer emits the smallest sufficient
    # integer subtype (B,S normally, B,I for jumbo depths)
    import struct

    i = aux.find(tag)
    assert i >= 0, f"missing {tag} per-base tag"
    sub = aux[i + 3 : i + 4]
    dt = {b"S": "<u2", b"I": "<u4", b"s": "<i2", b"i": "<i4", b"C": "u1"}[sub]
    (cnt,) = struct.unpack_from("<I", aux, i + 4)
    return np.frombuffer(aux, dt, cnt, i + 8).astype(np.uint32)


def test_per_base_tags(tmp_path):
    """--per-base-tags emits a cd:B,I per-base depth array consistent
    with the record-level cD/cM stats, identically in whole-file,
    streamed, and cpu-backend runs."""
    import struct

    bam = str(tmp_path / "pb.bam")
    assert main([
        "simulate", "-o", bam, "--molecules", "50", "--read-len", "40",
        "--positions", "6", "--umi-error", "0.02", "--seed", "41", "--sorted",
    ]) == 0
    outs = {}
    for tag, extra in (
        ("whole", []),
        ("stream", ["--chunk-reads", "120"]),
        ("cpu", ["--backend", "cpu"]),
    ):
        out = str(tmp_path / f"{tag}.bam")
        assert main([
            "call", bam, "-o", out, "--config", "config3",
            "--capacity", "256", "--per-base-tags", *extra,
        ]) == 0
        outs[tag] = read_bam(out)[1]
    w = outs["whole"]
    assert len(w) > 0
    for r in (w, outs["stream"], outs["cpu"]):
        for k in range(len(r)):
            cd_arr = _cd_array(r.aux_raw[k])
            assert len(cd_arr) == int(r.lengths[k])
            i = r.aux_raw[k].find(b"cDi")
            (cD,) = struct.unpack_from("<i", r.aux_raw[k], i + 3)
            i = r.aux_raw[k].find(b"cMi")
            (cM,) = struct.unpack_from("<i", r.aux_raw[k], i + 3)
            assert cd_arr.max() == cD
            pos_d = cd_arr[cd_arr > 0]
            assert (pos_d.min() if len(pos_d) else 0) == cM
            # ce (per-base disagreeing reads) rides along, bounded by cd
            ce_arr = _cd_array(r.aux_raw[k], b"ceB")
            assert len(ce_arr) == len(cd_arr)
            assert (ce_arr <= cd_arr).all()
    # the three run modes agree elementwise on the arrays
    for other in ("stream", "cpu"):
        o = outs[other]
        # streamed names differ (chunk prefix); match on (pos, umi, flags)
        key_w = {
            (int(w.pos[k]), w.umi[k], int(w.flags[k])): k for k in range(len(w))
        }
        assert len(key_w) == len(w)
        for k in range(len(o)):
            i = key_w[(int(o.pos[k]), o.umi[k], int(o.flags[k]))]
            np.testing.assert_array_equal(_cd_array(o.aux_raw[k]), _cd_array(w.aux_raw[i]))
            np.testing.assert_array_equal(
                _cd_array(o.aux_raw[k], b"ceB"), _cd_array(w.aux_raw[i], b"ceB")
            )
    # without the flag, no cd/ce arrays are emitted
    out0 = str(tmp_path / "plain.bam")
    assert main(["call", bam, "-o", out0, "--config", "config3",
                 "--capacity", "256"]) == 0
    _, r0 = read_bam(out0)
    assert all(a.find(b"cdB") < 0 and a.find(b"ceB") < 0 for a in r0.aux_raw)


def test_umi_whitelist_correction(tmp_path, capsys):
    """--umi-whitelist (CorrectUmis analogue): 1-mismatch UMIs snap to
    the whitelist and their reads rejoin the right family; too-distant
    and ambiguous UMIs are dropped and counted."""
    from duplexumiconsensusreads_tpu.io.bam import (
        BamHeader,
        BamRecords,
        write_bam,
    )

    rng = np.random.default_rng(55)
    L = 30
    # whitelist of two well-separated UMIs (Hamming 4 apart)
    wl = tmp_path / "wl.txt"
    wl.write_text("# expected UMIs\nAAAA\nCCGG\n")
    seqs = rng.integers(0, 4, (8, L)).astype(np.uint8)
    umis = [
        "AAAA", "AAAA", "AAAT",  # third heals to AAAA (1 mismatch)
        "CCGG", "CCGG", "CCGA",  # sixth heals to CCGG
        "GGTT",                  # distance 4 from both: dropped
        "ACGT",                  # dist(AAAA)=3, dist(CCGG)=3: dropped
    ]
    n = len(umis)
    recs = BamRecords(
        names=[f"r{i}" for i in range(n)],
        flags=np.zeros(n, np.uint16),
        ref_id=np.zeros(n, np.int32),
        pos=np.full(n, 50, np.int32),
        mapq=np.full(n, 60, np.uint8),
        next_ref_id=np.full(n, -1, np.int32),
        next_pos=np.full(n, -1, np.int32),
        tlen=np.zeros(n, np.int32),
        lengths=np.full(n, L, np.int32),
        seq=seqs,
        qual=np.full((n, L), 30, np.uint8),
        cigars=[[(L, "M")]] * n,
        umi=umis,
        aux_raw=[b"RXZ" + u.encode() + b"\x00" for u in umis],
    )
    bam = str(tmp_path / "wl.bam")
    write_bam(bam, BamHeader.synthetic(sort_order="coordinate"), recs)
    out = str(tmp_path / "c.bam")
    rep_p = str(tmp_path / "r.json")
    assert main([
        "call", bam, "-o", out, "--mode", "ss", "--grouping", "exact",
        "--capacity", "64", "--backend", "cpu", "--report", rep_p,
        "--umi-whitelist", str(wl),
    ]) == 0
    rep = json.load(open(rep_p))
    assert rep["n_umi_corrected"] == 2
    assert rep["n_dropped_whitelist"] == 2, rep
    _, cons = read_bam(out)
    # exactly the two whitelist families remain, healed members included
    assert len(cons) == 2
    assert sorted(cons.umi) == ["AAAA", "CCGG"]
    # bad whitelist file fails loudly
    badwl = tmp_path / "bad.txt"
    badwl.write_text("AAAA\nCCC\n")
    import pytest as _pytest

    with _pytest.raises(SystemExit, match="length"):
        main([
            "call", bam, "-o", out, "--mode", "ss", "--capacity", "64",
            "--backend", "cpu", "--umi-whitelist", str(badwl),
        ])


def test_umi_whitelist_recovers_molecules_under_noise(tmp_path, capsys):
    """Whitelisting the TRUE molecule UMIs at 4% UMI error: corrected
    exact grouping must recover (nearly) the true molecule count — at
    least as well as adjacency clustering without the whitelist, with
    zero unmatched consensus against truth."""
    bam, truth = _simulate(
        tmp_path, molecules=120, umi_error=0.04, seed=77, single_strand=True
    )
    with np.load(truth) as z:
        mol_umi = z["mol_umi"]
    wl = tmp_path / "wl.txt"
    chars = np.frombuffer(b"ACGT", np.uint8)
    lines = {bytes(chars[r]).decode() for r in mol_umi}
    wl.write_text("\n".join(sorted(lines)) + "\n")

    def run(extra):
        out = str(tmp_path / f"o{len(extra)}.bam")
        rep_p = str(tmp_path / "rep.json")
        assert main([
            "call", bam, "-o", out, "--mode", "ss", "--grouping",
            "exact", "--capacity", "512", "--report", rep_p, *extra,
        ]) == 0
        rep = json.load(open(rep_p))
        capsys.readouterr()
        assert main(["validate", out, "--truth", truth, "--json"]) == 0
        return rep, json.loads(capsys.readouterr().out.strip().splitlines()[-1])

    rep_wl, v_wl = run(["--umi-whitelist", str(wl)])
    rep_plain, v_plain = run([])
    assert rep_wl["n_umi_corrected"] > 0
    # correction collapses errored-UMI splinter families: strictly
    # fewer consensus calls, closer to the 120 true molecules, and no
    # more unmatched than uncorrected exact grouping. (A random 6-mer
    # whitelist is NOT Hamming-separated, so a few cross-talk
    # mis-corrections are expected — the comparative claim is the
    # honest one; fgbio likewise documents distance-separated sets.)
    assert v_wl["n_consensus"] < v_plain["n_consensus"]
    assert v_wl["n_consensus"] - 120 <= (v_plain["n_consensus"] - 120) // 3
    assert v_wl["n_unmatched"] <= v_plain["n_unmatched"]
