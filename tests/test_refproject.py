"""--ref-projected: per-reference-position (CIGAR-projected) consensus.

The acceptance contract (VERDICT r4 item 2): on the indel simulator,
families whose minority carries an indel produce a correct
reference-space consensus — truth-validated — with the minority's
evidence realigned instead of dropped; the oracle path consumes the
identical projected grid, so parity is structural; and structural
majorities (not minorities) decide the consensus CIGAR.
"""

import json

import numpy as np
import pytest

from duplexumiconsensusreads_tpu.cli import main
from duplexumiconsensusreads_tpu.io import read_bam
from duplexumiconsensusreads_tpu.io.bam import (
    BamHeader,
    BamRecords,
    write_bam,
)
from duplexumiconsensusreads_tpu.io.convert import records_to_readbatch, simulated_bam
from duplexumiconsensusreads_tpu.simulate import SimConfig


def _family_bam(path, cigars, seqs, pos=None, L=40, umi="ACGTAA"):
    n = len(cigars)
    seqs = np.asarray(seqs, np.uint8)
    recs = BamRecords(
        names=[f"r{i}" for i in range(n)],
        flags=np.zeros(n, np.uint16),
        ref_id=np.zeros(n, np.int32),
        pos=np.full(n, 100, np.int32) if pos is None else np.asarray(pos, np.int32),
        mapq=np.full(n, 60, np.uint8),
        next_ref_id=np.full(n, -1, np.int32),
        next_pos=np.full(n, -1, np.int32),
        tlen=np.zeros(n, np.int32),
        lengths=np.full(n, L, np.int32),
        seq=seqs,
        qual=np.full((n, L), 30, np.uint8),
        cigars=cigars,
        umi=[umi] * n,
        aux_raw=[b"RXZ" + umi.encode() + b"\x00"] * n,
    )
    write_bam(path, BamHeader.synthetic(sort_order="coordinate"), recs)
    return recs


def _call(in_path, out_path, tmp_path, *extra):
    rep = str(tmp_path / "rep.json")
    rc = main([
        "call", str(in_path), "-o", str(out_path), "--mode", "ss",
        "--grouping", "exact", "--capacity", "256", "--backend", "cpu",
        "--report", rep, "--ref-projected", *extra,
    ])
    assert rc == 0
    return json.load(open(rep))


def test_minority_indel_reads_realigned(tmp_path):
    """One insertion read + one deletion read in a 6-read family: both
    contribute realigned evidence, the consensus equals the true
    sequence over the full read span, and the CIGAR stays all-M."""
    rng = np.random.default_rng(5)
    L = 40
    true = rng.integers(0, 4, L).astype(np.uint8)
    seqs = np.broadcast_to(true, (6, L)).copy()
    cigars = [[(L, "M")] for _ in range(6)]
    # read 4: 1bp insertion after query 9 — bases shift right, the
    # inserted base is junk, the last true base is lost off the end
    p = 10
    seqs[4, p + 1 :] = true[p : L - 1]
    seqs[4, p] = (true[p] + 1) % 4
    cigars[4] = [(p, "M"), (1, "I"), (L - p - 1, "M")]
    # read 5: 1bp deletion at query 19 — bases shift left, the read
    # observes one EXTRA reference base we model as junk
    d = 20
    seqs[5, d : L - 1] = true[d + 1 :]
    seqs[5, L - 1] = 0
    cigars[5] = [(d, "M"), (1, "D"), (L - d, "M")]

    bam = tmp_path / "fam.bam"
    _family_bam(str(bam), cigars, seqs, L=L)
    out = tmp_path / "cons.bam"
    rep = _call(bam, out, tmp_path)
    assert rep["n_projected_reads"] == 6
    assert rep["n_dropped_cigar_ab"] + rep["n_dropped_cigar_ba"] == 0
    _, cons = read_bam(str(out))
    assert len(cons) == 1
    # majority is indel-free -> all-M CIGAR over the reference span
    # (the deletion read extends the span by one junk-observed base)
    (ln0, op0), *restops = cons.cigars[0]
    assert op0 == "M"
    assert int(cons.pos[0]) == 100
    called = cons.seq[0, : int(cons.lengths[0])]
    # the first L reference columns must equal the true sequence —
    # including cycles past the indel points, where the two indel
    # reads' evidence only agrees with the majority BECAUSE it was
    # realigned (cycle-space voting would have them all shifted)
    np.testing.assert_array_equal(called[:L], true)


def test_majority_insertion_emits_I(tmp_path):
    """4 of 5 reads share a 2bp insertion: the consensus CIGAR carries
    2I at the right offset and the inserted bases are called."""
    rng = np.random.default_rng(7)
    L = 30
    true = rng.integers(0, 4, L).astype(np.uint8)
    ins = np.array([2, 3], np.uint8)
    p = 12  # insertion before reference offset 12
    seqs = np.zeros((5, L), np.uint8)
    cigars = []
    for k in range(4):  # carriers: 12M 2I 16M (query truncated at L)
        row = np.concatenate([true[:p], ins, true[p : L - 2]])
        seqs[k] = row
        cigars.append([(p, "M"), (2, "I"), (L - p - 2, "M")])
    seqs[4] = true
    cigars.append([(L, "M")])
    bam = tmp_path / "insfam.bam"
    _family_bam(str(bam), cigars, seqs, L=L)
    out = tmp_path / "cons.bam"
    _call(bam, out, tmp_path)
    _, cons = read_bam(str(out))
    assert len(cons) == 1
    assert cons.cigars[0] == [(p, "M"), (2, "I"), (L - p, "M")], cons.cigars[0]
    called = cons.seq[0, : int(cons.lengths[0])]
    np.testing.assert_array_equal(called[p : p + 2], ins)
    np.testing.assert_array_equal(called[:p], true[:p])
    np.testing.assert_array_equal(called[p + 2 :], true[p:])


def test_majority_deletion_emits_D(tmp_path):
    """4 of 5 reads delete one reference base: the consensus carries D
    there and the deleted base is absent from the sequence."""
    rng = np.random.default_rng(11)
    L = 30
    true = rng.integers(0, 4, L).astype(np.uint8)
    d = 14
    seqs = np.zeros((5, L), np.uint8)
    cigars = []
    for k in range(4):  # carriers observe one base past the end
        row = np.concatenate([true[:d], true[d + 1 :], [1]])
        seqs[k] = row
        cigars.append([(d, "M"), (1, "D"), (L - d, "M")])
    seqs[4] = true
    cigars.append([(L, "M")])
    bam = tmp_path / "delfam.bam"
    _family_bam(str(bam), cigars, seqs, L=L)
    out = tmp_path / "cons.bam"
    _call(bam, out, tmp_path)
    _, cons = read_bam(str(out))
    assert len(cons) == 1
    ops = cons.cigars[0]
    assert ops[0] == (d, "M") and ops[1] == (1, "D"), ops
    called = cons.seq[0, : int(cons.lengths[0])]
    np.testing.assert_array_equal(called[:d], true[:d])
    # deleted base absent: the next emitted base is true[d + 1]
    assert called[d] == true[d + 1]


def test_minority_insertion_suppressed(tmp_path):
    """A lone insertion (1 of 5) must NOT appear in the CIGAR — only
    its inserted base's evidence is lost, everything else realigns."""
    rng = np.random.default_rng(13)
    L = 30
    true = rng.integers(0, 4, L).astype(np.uint8)
    seqs = np.broadcast_to(true, (5, L)).copy()
    cigars = [[(L, "M")] for _ in range(5)]
    p = 8
    seqs[0, p + 1 :] = true[p : L - 1]
    seqs[0, p] = 3
    cigars[0] = [(p, "M"), (1, "I"), (L - p - 1, "M")]
    bam = tmp_path / "minifam.bam"
    _family_bam(str(bam), cigars, seqs, L=L)
    out = tmp_path / "cons.bam"
    _call(bam, out, tmp_path)
    _, cons = read_bam(str(out))
    assert cons.cigars[0] == [(L, "M")]
    np.testing.assert_array_equal(cons.seq[0, :L], true)


def test_wide_group_falls_back(tmp_path):
    """Two reads sharing a pos_key but aligned 500 bp apart exceed the
    projection cap: the group keeps the cycle layout and the fallback
    counters say so."""
    rng = np.random.default_rng(17)
    L = 40
    seqs = rng.integers(0, 4, (2, L)).astype(np.uint8)
    cigars = [[(L, "M")], [(L, "M")]]
    recs = _family_bam(str(tmp_path / "wide.bam"), cigars, seqs, pos=[100, 600], L=L)
    # same pos_key requires same canonical key: single-end records key
    # on their own pos, so force the pos_key by editing after parse
    _, r2 = read_bam(str(tmp_path / "wide.bam"))
    batch, info = records_to_readbatch(r2, duplex=False, ref_projected=True)
    assert info["n_projection_fallback_reads"] == 0  # distinct pos_keys: both project
    # now a true shared-key wide group via paired-style records is
    # covered by the executor-level sim test; here assert the cap logic
    # directly on the helper
    from duplexumiconsensusreads_tpu.io.refproject import ref_project

    pk = np.zeros(2, np.int64)  # force one shared group
    pb, pq, proj, fb, _ = ref_project(
        np.asarray(r2.seq), np.asarray(r2.qual), np.ones(2, bool), pk,
        np.zeros((2, 4), np.uint8), np.asarray(r2.pos),
        lambda i: r2.cigars[i],
    )
    assert fb.all()
    assert proj.n_fallback_groups == 1
    np.testing.assert_array_equal(pb[:, :L], np.asarray(r2.seq))


def test_fallback_group_emits_cycle_width(tmp_path):
    """Mixed run through the executor: one group projects WIDER than L
    (a 5bp majority deletion stretches its reference span to L+5) while
    another exceeds the span cap and falls back — the fallback family's
    record must keep the original read length, an all-M CIGAR, and
    read-length per-base tags, NOT the widened projected width
    (r5 review regression: lens defaulted to cons_base.shape[1])."""
    rng = np.random.default_rng(23)
    L = 40
    t45 = rng.integers(0, 4, L + 5).astype(np.uint8)
    t2 = rng.integers(0, 4, L).astype(np.uint8)
    # family A (pos 100): 3 reads, all deleting ref [20, 25) -> width 45
    row_a = np.concatenate([t45[:20], t45[25:45]])
    # family B (pos 600): 2 clean reads + 1 monster deletion whose span
    # (240) blows the 2L cap -> whole group falls back; the modal vote
    # then drops the monster
    row_mon = np.concatenate([t2[:10], rng.integers(0, 4, 30)]).astype(np.uint8)
    seqs = np.stack([row_a, row_a, row_a, t2, t2, row_mon]).astype(np.uint8)
    cigars = [
        [(20, "M"), (5, "D"), (20, "M")],
        [(20, "M"), (5, "D"), (20, "M")],
        [(20, "M"), (5, "D"), (20, "M")],
        [(L, "M")],
        [(L, "M")],
        [(10, "M"), (200, "D"), (30, "M")],
    ]
    umis = ["ACGTAA"] * 3 + ["GGCCTT"] * 3
    n = 6
    recs = BamRecords(
        names=[f"r{i}" for i in range(n)],
        flags=np.zeros(n, np.uint16),
        ref_id=np.zeros(n, np.int32),
        pos=np.asarray([100] * 3 + [600] * 3, np.int32),
        mapq=np.full(n, 60, np.uint8),
        next_ref_id=np.full(n, -1, np.int32),
        next_pos=np.full(n, -1, np.int32),
        tlen=np.zeros(n, np.int32),
        lengths=np.full(n, L, np.int32),
        seq=seqs,
        qual=np.full((n, L), 30, np.uint8),
        cigars=cigars,
        umi=umis,
        aux_raw=[b"RXZ" + u.encode() + b"\x00" for u in umis],
    )
    bam = tmp_path / "mixed.bam"
    write_bam(str(bam), BamHeader.synthetic(sort_order="coordinate"), recs)
    out = tmp_path / "cons.bam"
    rep = _call(bam, out, tmp_path, "--per-base-tags")
    assert rep["n_projection_fallback_groups"] == 1
    assert rep["n_projection_fallback_reads"] == 3
    assert rep["n_projected_reads"] == 3
    _, cons = read_bam(str(out))
    assert len(cons) == 2
    # record 0: projected family A — the majority deletion is real
    assert int(cons.pos[0]) == 100
    assert cons.cigars[0] == [(20, "M"), (5, "D"), (20, "M")]
    assert int(cons.lengths[0]) == L
    np.testing.assert_array_equal(cons.seq[0, :L], row_a)
    # record 1: fallback family B — cycle width, never the projected 45
    assert int(cons.pos[1]) == 600
    assert cons.cigars[1] == [(L, "M")]
    assert int(cons.lengths[1]) == L
    np.testing.assert_array_equal(cons.seq[1, :L], t2)
    # per-base cd tag counts match each record's own emitted length
    import struct

    for i, want in ((0, L), (1, L)):
        raw = cons.aux_raw[i]
        j = raw.index(b"cdB")
        cnt = struct.unpack("<I", raw[j + 4 : j + 8])[0]
        assert cnt == want, (i, cnt)


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
def test_indel_sim_truth_and_parity(tmp_path, backend, capsys):
    """End-to-end on the indel simulator: nothing dropped, every
    consensus matches truth, and the error rate does not exceed the
    classic (drop-minority) path's — the recovered evidence must help,
    not hurt. Runs on both executors; the projected grid is shared, so
    backend parity is also asserted record-for-record."""
    cfg = SimConfig(
        n_molecules=100, mean_family_size=5, indel_error=0.08,
        base_error=0.01, duplex=True, seed=21,
    )
    bam = str(tmp_path / "ind.bam")
    truth = str(tmp_path / "truth.npz")
    simulated_bam(cfg, path=bam, sort=True)
    # simulated_bam writes no truth file; regenerate via CLI for the
    # validate step
    assert main([
        "simulate", "-o", bam, "--truth", truth, "--molecules", "100",
        "--family-size", "5", "--indel-error", "0.08", "--base-error",
        "0.01", "--sorted", "--seed", "21",
    ]) == 0
    out = str(tmp_path / f"cons_{backend}.bam")
    rep_p = str(tmp_path / "rp.json")
    assert main([
        "call", bam, "-o", out, "--config", "config3", "--capacity", "512",
        "--backend", backend, "--ref-projected", "--report", rep_p,
    ]) == 0
    rep = json.load(open(rep_p))
    assert rep["n_projected_reads"] > 0
    assert rep["n_dropped_cigar_ab"] + rep["n_dropped_cigar_ba"] == 0
    capsys.readouterr()
    assert main([
        "validate", out, "--truth", truth, "--json", "--pos-window", "200",
    ]) == 0
    v = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert v["n_unmatched"] == 0
    assert v["n_matched_to_truth"] == v["n_consensus"] > 0
    # classic path on the same input for the comparison ceiling
    out_c = str(tmp_path / "cons_classic.bam")
    assert main([
        "call", bam, "-o", out_c, "--config", "config3", "--capacity",
        "512", "--backend", backend, "--report", rep_p,
    ]) == 0
    capsys.readouterr()
    assert main(["validate", out_c, "--truth", truth, "--json"]) == 0
    vc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert v["error_rate"] <= vc["error_rate"] * 1.5 + 1e-6, (
        v["error_rate"], vc["error_rate"],
    )


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
def test_mate_aware_ref_projected(tmp_path, capsys, backend):
    """Mate-aware + --ref-projected: mixed-R1/R2 paired input projects
    per (pos_key, fragment end) — each mate side gets its own column
    table — and emits linked consensus R1+R2 pairs whose bases match
    truth. The indel minority is realigned, not dropped. Both
    executors run the same projected grid (cons_end plumbing differs:
    fused segment-min on tpu, np.minimum.at on cpu)."""
    bam = str(tmp_path / "pair.bam")
    truth = str(tmp_path / "truth.npz")
    assert main([
        "simulate", "-o", bam, "--truth", truth, "--molecules", "80",
        "--family-size", "5", "--indel-error", "0.06", "--base-error",
        "0.01", "--paired-reads", "--sorted", "--seed", "41",
    ]) == 0
    out = str(tmp_path / "cons.bam")
    rep_p = str(tmp_path / "rp.json")
    assert main([
        "call", bam, "-o", out, "--config", "config3", "--capacity",
        "512", "--backend", backend, "--ref-projected", "--report", rep_p,
    ]) == 0
    rep = json.load(open(rep_p))
    assert rep["mate_aware"] is True
    assert rep["n_projected_reads"] > 0
    assert rep["n_dropped_cigar_ab"] + rep["n_dropped_cigar_ba"] == 0
    assert rep["n_consensus_pairs"] > 0
    # complete pairs must point at EACH OTHER: projection moves each
    # mate's POS independently, so PNEXT is the partner's (possibly
    # moved) POS and TLEN spans leftmost-start..rightmost-end with
    # opposite signs (r5 review regression: PNEXT was the row's own POS)
    _, cons = read_bam(out)
    by_name: dict = {}
    for i in range(len(cons)):
        if cons.names[i].endswith("p"):
            by_name.setdefault(cons.names[i], []).append(i)
    n_pairs_checked = 0
    for nm, rows in by_name.items():
        assert len(rows) == 2, nm
        a, b = rows
        assert int(cons.next_pos[a]) == int(cons.pos[b]), nm
        assert int(cons.next_pos[b]) == int(cons.pos[a]), nm
        ta, tb = int(cons.tlen[a]), int(cons.tlen[b])
        assert ta == -tb and ta != 0, (nm, ta, tb)
        lo = min(int(cons.pos[a]), int(cons.pos[b]))
        assert abs(ta) >= max(int(cons.pos[a]), int(cons.pos[b])) - lo, nm
        n_pairs_checked += 1
    assert n_pairs_checked == rep["n_consensus_pairs"] > 0
    capsys.readouterr()
    assert main([
        "validate", out, "--truth", truth, "--json", "--pos-window", "200",
    ]) == 0
    v = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert v["n_unmatched"] == 0
    assert v["error_rate"] < 5e-3, v
    # classic mate-aware path on the same input: recovering the indel
    # reads' evidence must not cost accuracy
    out_c = str(tmp_path / "cons_classic.bam")
    assert main([
        "call", bam, "-o", out_c, "--config", "config3", "--capacity",
        "512", "--backend", backend,
    ]) == 0
    capsys.readouterr()
    assert main(["validate", out_c, "--truth", truth, "--json"]) == 0
    vc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert v["error_rate"] <= vc["error_rate"] * 1.5 + 1e-6, (
        v["error_rate"], vc["error_rate"],
    )


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
def test_projected_pair_with_real_insert(tmp_path, backend):
    """Mates at POS 100 / 250 (a real insert): the projected consensus
    pair must share ONE qname (SAM contract — r5 review found the name
    embedded each row's own moved POS), cross-point PNEXT at each
    other's moved POS, and span the full insert in TLEN."""
    from duplexumiconsensusreads_tpu.io.bam import (
        FLAG_MATE_REVERSE,
        FLAG_PAIRED,
        FLAG_READ1,
        FLAG_READ2,
        FLAG_REVERSE,
    )

    rng = np.random.default_rng(61)
    L = 40
    t1 = rng.integers(0, 4, L).astype(np.uint8)
    t2 = rng.integers(0, 4, L).astype(np.uint8)
    k = 3  # read pairs
    n = 2 * k
    seqs = np.stack([t1] * k + [t2] * k)
    # top-strand template: R1 forward at 100, R2 reverse at 250
    flags = np.asarray(
        [FLAG_PAIRED | FLAG_READ1 | FLAG_MATE_REVERSE] * k
        + [FLAG_PAIRED | FLAG_READ2 | FLAG_REVERSE] * k,
        np.uint16,
    )
    pos = np.asarray([100] * k + [250] * k, np.int32)
    npos = np.asarray([250] * k + [100] * k, np.int32)
    recs = BamRecords(
        names=[f"t{i % k}" for i in range(n)],
        flags=flags,
        ref_id=np.zeros(n, np.int32),
        pos=pos,
        mapq=np.full(n, 60, np.uint8),
        next_ref_id=np.zeros(n, np.int32),
        next_pos=npos,
        tlen=np.asarray([190] * k + [-190] * k, np.int32),
        lengths=np.full(n, L, np.int32),
        seq=seqs,
        qual=np.full((n, L), 30, np.uint8),
        cigars=[[(L, "M")]] * n,
        umi=["ACGTAA"] * n,
        aux_raw=[b"RXZACGTAA\x00"] * n,
    )
    bam = str(tmp_path / "ins.bam")
    write_bam(bam, BamHeader.synthetic(sort_order="coordinate"), recs)
    out = str(tmp_path / "cons.bam")
    rep_p = str(tmp_path / "rp.json")
    assert main([
        "call", bam, "-o", out, "--mode", "ss", "--grouping", "exact",
        "--capacity", "64", "--backend", backend, "--ref-projected",
        "--mate-aware", "on", "--report", rep_p,
    ]) == 0
    rep = json.load(open(rep_p))
    assert rep["n_consensus_pairs"] == 1
    _, cons = read_bam(out)
    prow = [i for i in range(len(cons)) if cons.names[i].endswith("p")]
    assert len(prow) == 2
    a, b = prow
    assert cons.names[a] == cons.names[b], (cons.names[a], cons.names[b])
    pa, pb = int(cons.pos[a]), int(cons.pos[b])
    assert sorted([pa, pb]) == [100, 250]
    assert int(cons.next_pos[a]) == pb and int(cons.next_pos[b]) == pa
    ta, tb = int(cons.tlen[a]), int(cons.tlen[b])
    assert ta == -tb and abs(ta) == 250 + L - 100


def test_backend_parity_on_projected_grid(tmp_path):
    """cpu (oracle operators) and tpu (fused pipeline) executors consume
    the identical projected batch — outputs must agree record-for-record
    (same base-parity contract as the cycle path)."""
    cfg = SimConfig(
        n_molecules=60, mean_family_size=4, indel_error=0.06,
        base_error=0.01, duplex=True, seed=33,
    )
    bam = str(tmp_path / "p.bam")
    simulated_bam(cfg, path=bam, sort=True)
    outs = {}
    for backend in ("cpu", "tpu"):
        out = str(tmp_path / f"c_{backend}.bam")
        assert main([
            "call", bam, "-o", out, "--config", "config3", "--capacity",
            "512", "--backend", backend, "--ref-projected",
        ]) == 0
        outs[backend] = read_bam(out)[1]
    a, b = outs["cpu"], outs["tpu"]
    assert len(a) == len(b)
    assert a.names == b.names
    np.testing.assert_array_equal(a.pos, b.pos)
    assert a.cigars == b.cigars
    np.testing.assert_array_equal(a.lengths, b.lengths)
    # base identity everywhere both call a real base (evidence-tie cells
    # are covered by the cycle-path contract; here the grids are equal
    # by construction so calls should agree exactly on CPU-vs-CPU XLA)
    for i in range(len(a)):
        la = int(a.lengths[i])
        np.testing.assert_array_equal(a.seq[i, :la], b.seq[i, :la])


def test_unanchored_reads_invalidated(tmp_path):
    """A read whose CIGAR consumes no reference (soft-clip/insertion
    only) places nothing on the projected grid: it must be counted in
    n_projection_unanchored_reads AND invalidated — an all-PAD row
    would inflate family size (min-reads gates, depth denominators)
    without contributing evidence (ADVICE r5)."""
    rng = np.random.default_rng(3)
    L = 40
    true = rng.integers(0, 4, L).astype(np.uint8)
    seqs = np.broadcast_to(true, (4, L)).copy()
    cigars = [[(L, "M")] for _ in range(4)]
    cigars[3] = [(L, "S")]  # fully soft-clipped: no reference anchor
    bam = tmp_path / "unanch.bam"
    _family_bam(str(bam), cigars, seqs, L=L)
    _, recs = read_bam(str(bam))
    batch, info = records_to_readbatch(recs, duplex=False, ref_projected=True)
    assert info["n_projection_unanchored_reads"] == 1
    assert not batch.valid[3]
    assert int(np.asarray(batch.valid).sum()) == 3
    assert info["n_valid"] == 3
    assert info["n_dropped_cigar"] == 0  # drop counters stay disjoint

    # end-to-end: consensus depth counts only the anchored evidence
    out = tmp_path / "cons.bam"
    rep = _call(bam, out, tmp_path)
    assert rep["n_projection_unanchored_reads"] == 1
    _, cons = read_bam(str(out))
    assert len(cons) == 1
    import struct as _struct

    from duplexumiconsensusreads_tpu.io.bam import iter_aux_fields

    cd = None
    for _s, t, _typ, vs, _e in iter_aux_fields(cons.aux_raw[0]):
        if t == b"cD":
            cd = _struct.unpack_from("<i", cons.aux_raw[0], vs)[0]
    assert cd == 3
