"""Registry-pin tests for the knob/thread declarations
(runtime/knobs.py) — the single source dutlint's knob-taint and
thread-confinement rules model-check the tree against.

Three kinds of pin:

- table pins: the registry's defaults/choices/surfaces match what the
  CLI and the serve layer actually ship (a registry edit that would
  change resolved behaviour fails HERE, before the linter even runs);
- closed-world pins: every ``call`` flag on the real argparse parser
  maps to a declared knob (or an explicitly exempt run-control flag),
  and every thread the tree starts maps to a declared THREAD_ROLES
  row;
- the byte-identity matrix (``SCHEDULING_MATRIX``): each scheduling
  job knob names the test proving it is byte-neutral — dutlint's
  knob-taint coverage leg reads this file, so dropping a knob from the
  matrix (or declaring a new scheduling knob without an exercise) is a
  lint failure, TRANSITIONS-style.

This file is a dutlint TEST_ANCHOR: it is linted like the package.
"""

import ast
import os

import pytest

from duplexumiconsensusreads_tpu.io import simulated_bam
from duplexumiconsensusreads_tpu.runtime import knobs
from duplexumiconsensusreads_tpu.simulate import SimConfig
from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "duplexumiconsensusreads_tpu")

# the byte-identity matrix: scheduling job knob -> the test proving a
# value change cannot change output bytes. dutlint's knob-taint
# coverage leg requires every scheduling job_config knob to appear
# here (the keys are the exercise evidence); test_matrix_targets_exist
# keeps the values honest.
SCHEDULING_MATRIX = {
    "max_inflight": "tests/test_knobs.py::test_max_inflight_ab_byte_identical",
    "drain_workers": "tests/test_stream.py::test_drain_workers_ab_byte_identical",
    "packed": "tests/test_stream.py::TestWireDietMatrix",
    "prefetch_depth": "tests/test_stream.py::TestWireDietMatrix",
    "ingest_overlap": "tests/test_stream.py::TestIngestOverlap",
    "mesh": "tests/test_mesh.py::test_cli_mesh_flag_streams_byte_identical",
    "bucket_ladder": "tests/test_tuning.py::TestLadderMatrix",
    "follow": "tests/test_live.py::TestFollowByteIdentity",
    "finalize_on": "tests/test_live.py::TestFollowByteIdentity",
    "live_poll_s": "tests/test_live.py::TestFollowByteIdentity",
    "snapshot_chunks": "tests/test_live.py::test_snapshot_chunks_ab_byte_identical",
}

# `call` parser dests that are deliberately NOT knobs: run-control and
# service-client plumbing (paths, handles, liveness) — they steer THE
# RUN, not the result function, and are refused on --submit where they
# would be silently dropped
RUN_CONTROL_DESTS = {
    "cmd", "help", "input", "output", "index",
    "checkpoint", "resume", "report", "profile", "trace", "heartbeat",
    "chaos", "n_hosts", "host_id",
    "submit", "spool", "priority", "status", "wait", "wait_timeout",
    "json", "deadline", "shards", "shard_bytes", "config_file",
}


def _call_parser_dests():
    from duplexumiconsensusreads_tpu.cli.main import build_parser

    p = build_parser()
    sub = next(
        a for a in p._actions
        if getattr(a, "choices", None) and "call" in a.choices
    )
    call = sub.choices["call"]
    return {a.dest for a in call._actions}


class TestKnobTable:
    def test_classes_and_surfaces_are_closed(self):
        for name, k in knobs.KNOBS.items():
            assert k.knob_class in ("semantic", "scheduling"), name
            assert set(k.surfaces) <= set(knobs.SURFACES), name

    def test_job_defaults_pin(self):
        """The resolved job defaults, pinned literally: an empty-config
        job must run the identical workload as a bare
        `call --chunk-reads` — editing a KNOB_TABLE default is a
        behaviour change and must fail here, not ship silently."""
        assert knobs.job_config_defaults() == {
            "grouping": "exact", "mode": "ss", "error_model": "none",
            "max_hamming": 1, "count_ratio": 2, "min_reads": 1,
            "min_duplex_reads": 1, "max_qual": 90, "max_input_qual": 50,
            "min_input_qual": 0, "capacity": 2048,
            "chunk_reads": 500_000, "max_inflight": 4,
            "drain_workers": 2, "packed": "auto", "prefetch_depth": 2,
            "ingest_overlap": "auto", "mesh": "auto",
            "bucket_ladder": "off", "mate_aware": "auto", "max_reads": 0,
            "per_base_tags": False, "read_group_id": "A",
            "write_index": False, "follow": False, "finalize_on": "eof",
            "live_poll_s": 0.25, "snapshot_chunks": 0,
        }

    def test_job_choices_pin(self):
        assert knobs.job_choice_map() == {
            "grouping": {"exact", "adjacency", "cluster"},
            "mode": {"ss", "duplex"},
            "error_model": {"none", "cycle"},
            "mate_aware": {"auto", "on", "off"},
            "packed": {"auto", "byte", "off"},
            "ingest_overlap": {"auto", "on", "off"},
        }

    def test_serve_layer_is_registry_derived(self):
        from duplexumiconsensusreads_tpu.serve import job

        assert job.CONFIG_DEFAULTS == knobs.job_config_defaults()
        assert list(job.CONFIG_DEFAULTS) == list(knobs.job_config_defaults())
        assert job._CHOICES == knobs.job_choice_map()
        assert set(knobs.job_min_int_keys()) == {
            "capacity", "max_inflight", "drain_workers", "prefetch_depth",
        }

    def test_streaming_only_set_pin(self):
        assert knobs.streaming_only_keys() == (
            "packed", "prefetch_depth", "ingest_overlap", "mesh",
            "bucket_ladder", "follow", "finalize_on", "live_poll_s",
            "snapshot_chunks",
        )

    def test_every_cli_flag_maps_to_a_declared_knob(self):
        """The closed world: a new `call` flag is either a KNOB_TABLE
        row or an explicit RUN_CONTROL_DESTS entry — never a third
        thing that slips both the registry and the linter."""
        dests = _call_parser_dests()
        knob_dests = dests - RUN_CONTROL_DESTS
        undeclared = knob_dests - set(knobs.KNOBS)
        assert not undeclared, (
            f"parser flags without a KNOB_TABLE row: {sorted(undeclared)}"
        )
        # and the registry carries no phantom CLI rows: every declared
        # knob resolves from the parser (config-file keys included —
        # they share the dest namespace)
        phantom = {
            n for n in knobs.KNOBS if n not in dests
        }
        assert not phantom, (
            f"KNOB_TABLE rows with no parser flag: {sorted(phantom)}"
        )

    def test_config_file_keys_are_exactly_the_knobs(self):
        assert knobs.config_file_keys() == frozenset(knobs.KNOBS)


def _thread_name_literals():
    """(path, name-or-prefix) for every thread the package starts:
    threading.Thread(name=...) and ThreadPoolExecutor
    thread_name_prefix=... literals/f-string prefixes."""
    found = []
    for dirpath, dirnames, filenames in os.walk(PKG):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path) as f:
                tree = ast.parse(f.read())
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                callee = node.func
                cname = (
                    callee.attr if isinstance(callee, ast.Attribute)
                    else callee.id if isinstance(callee, ast.Name) else ""
                )
                if cname not in ("Thread", "ThreadPoolExecutor"):
                    continue
                for kw in node.keywords or ():
                    if kw.arg not in ("name", "thread_name_prefix"):
                        continue
                    v = kw.value
                    if isinstance(v, ast.Constant) and isinstance(
                        v.value, str
                    ):
                        found.append((os.path.relpath(path, REPO), v.value))
                    elif isinstance(v, ast.JoinedStr) and v.values:
                        head = v.values[0]
                        if isinstance(head, ast.Constant):
                            found.append(
                                (os.path.relpath(path, REPO),
                                 str(head.value))
                            )
    return found


class TestThreadRoles:
    def test_every_started_thread_maps_to_a_declared_role(self):
        """Closed world for threads: a Thread/pool the tree starts
        carries a name, and that name is a declared THREAD_ROLES
        marker — a new thread without a registry row fails here even
        before the confinement rule has an entry to walk. The bench
        harness is exempt: its threads drive the system under test,
        they are not part of it."""
        markers = sorted(
            (str(row.get("marker", "")) for row in
             knobs.THREAD_ROLES.values() if row.get("marker")),
            key=len, reverse=True,
        )
        assert markers
        for path, name in _thread_name_literals():
            if os.path.basename(path) == "benchmark.py":
                continue
            assert any(name.startswith(m) for m in markers), (
                f"{path}: thread name {name!r} matches no THREAD_ROLES "
                f"marker — declare the role in runtime/knobs.py"
            )

    def test_declared_entries_exist(self):
        for role, row in knobs.THREAD_ROLES.items():
            entry = str(row["entry"])
            if not entry:
                continue
            mod = os.path.join(PKG, *str(row["module"]).split("/"))
            with open(mod) as f:
                tree = ast.parse(f.read())
            names = {
                n.name for n in ast.walk(tree)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            assert entry in names, (
                f"THREAD_ROLES[{role!r}] entry {entry}() not found in "
                f"{row['module']}"
            )


class TestSchedulingMatrix:
    def test_every_scheduling_job_knob_is_in_the_matrix(self):
        declared = {
            n for n, k in knobs.KNOBS.items()
            if k.knob_class == "scheduling" and "job_config" in k.surfaces
        }
        assert declared == set(SCHEDULING_MATRIX)

    def test_matrix_targets_exist(self):
        for knob_name, target in SCHEDULING_MATRIX.items():
            rel, _, obj = target.partition("::")
            path = os.path.join(REPO, rel)
            assert os.path.exists(path), target
            with open(path) as f:
                tree = ast.parse(f.read())
            names = {
                n.name for n in ast.walk(tree)
                if isinstance(n, (ast.FunctionDef, ast.ClassDef))
            }
            assert obj.split("::")[0] in names, (
                f"{knob_name}: {target} names no test in {rel}"
            )


def test_max_inflight_ab_byte_identical(tmp_path):
    """The missing rung of the byte-identity matrix: the in-flight
    window depth is a scheduling knob (it bounds how many chunks the
    dispatch pipeline overlaps), so a serial window (1) and a wide one
    must produce byte-identical output."""
    from duplexumiconsensusreads_tpu.runtime.stream import (
        stream_call_consensus,
    )

    path = str(tmp_path / "in.bam")
    cfg = SimConfig(n_molecules=80, n_positions=8, umi_error=0.02, seed=29)
    simulated_bam(cfg, path=path, sort=True)
    gp = GroupingParams(strategy="adjacency", paired=True)
    cp = ConsensusParams(mode="duplex")
    outs = {}
    for n in (1, 4):
        out = str(tmp_path / f"mi{n}.bam")
        stream_call_consensus(
            path, out, gp, cp, capacity=256, chunk_reads=120,
            max_inflight=n,
        )
        with open(out, "rb") as f:
            outs[n] = f.read()
    assert outs[1] == outs[4]
