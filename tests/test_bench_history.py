"""Bench trajectory + stdout contract: benchhist salvage/gate units,
the bench_history.py CLI over the driver's real BENCH_r0N.json files,
and the subprocess test pinning `python bench.py`'s LAST-stdout-line
contract (the r5 regression: the result line outgrew the driver's
~2000-byte tail window and the trajectory went dark)."""

import json
import os
import subprocess
import sys

import pytest

from duplexumiconsensusreads_tpu import benchhist

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _round(name: str, metrics: dict) -> dict:
    return {"name": name, "path": name, "metrics": metrics,
            "salvaged": False, "rc": 0}


class TestSalvage:
    def test_whole_json_line_wins(self):
        tail = 'noise\n{"value": 2.5, "mfu": 0.05}\n# journal\n'
        m = benchhist.salvage_metrics(tail)
        assert m == {"value": 2.5, "mfu": 0.05}

    def test_truncated_head_fragment_recovers_scalars_and_lists(self):
        # the r5 shape: the line's head fell off the bounded tail
        tail = (
            '3.2, "e2e_wire_floor_frac": [0.63, 0.72], '
            '"e2e_packed_speedup": 1.163, "label": "not-a-number"}\n'
            "# reads=5 journal line\n"
        )
        m = benchhist.salvage_metrics(tail)
        assert m["e2e_wire_floor_frac"] == [0.63, 0.72]
        assert m["e2e_packed_speedup"] == 1.163
        assert "label" not in m

    def test_real_r5_capture_salvages_floor_metrics(self):
        p = os.path.join(REPO, "BENCH_r05.json")
        if not os.path.exists(p):
            pytest.skip("driver trajectory not present")
        r = benchhist.load_round(p)
        if not r["salvaged"]:
            pytest.skip("driver has since re-parsed r5")
        assert benchhist._metric_value(
            r["metrics"], "e2e_wire_floor_frac"
        ) is not None

    def test_load_round_accepts_bare_result_json(self, tmp_path):
        p = tmp_path / "cand.json"
        p.write_text(json.dumps({"value": 5.0}))
        r = benchhist.load_round(str(p))
        assert r["metrics"] == {"value": 5.0} and not r["salvaged"]


class TestGate:
    def test_regression_beyond_threshold_fails(self):
        rounds = [
            _round("r01", {"e2e_reads_per_sec": 40000, "value": 3e6}),
            _round("r02", {"e2e_reads_per_sec": 10000, "value": 3e6}),
        ]
        ok, problems = benchhist.check_regression(rounds, threshold=0.5)
        assert not ok and "e2e_reads_per_sec" in problems[0]

    def test_within_threshold_and_missing_metrics_pass(self):
        rounds = [
            _round("r01", {"e2e_reads_per_sec": 40000, "value": 3e6}),
            # a smoke round without the e2e leg must not fail the gate
            _round("r02", {"value": 2.9e6}),
        ]
        ok, problems = benchhist.check_regression(rounds, threshold=0.5)
        assert ok, problems

    def test_gate_skips_rounds_that_never_measured_the_metric(self):
        rounds = [
            _round("r01", {"e2e_reads_per_sec": 40000}),
            _round("r02", {}),  # parse hole (the r5 shape)
            _round("r03", {"e2e_reads_per_sec": 39000}),
        ]
        ok, _ = benchhist.check_regression(rounds, threshold=0.5)
        assert ok  # r03 compares against r01, across the hole

    def test_gate_never_relitigates_historical_regressions(self):
        """A newest round that did not measure a metric must not be
        failed for a drop between two OLDER rounds (the real repo
        shape: r3→r4's e2e weather dip with r5's reading lost to the
        tail truncation)."""
        rounds = [
            _round("r03", {"e2e_reads_per_sec": 40419}),
            _round("r04", {"e2e_reads_per_sec": 13883}),  # historical dip
            _round("r05", {}),  # the round under judgment: no e2e leg
        ]
        ok, problems = benchhist.check_regression(rounds, threshold=0.5)
        assert ok, problems
        # but a newest round that DID measure it is still gated
        rounds[-1] = _round("r05", {"e2e_reads_per_sec": 1000})
        ok, problems = benchhist.check_regression(rounds, threshold=0.5)
        assert not ok and "r05" in problems[0]

    def test_lower_is_better_direction(self):
        rounds = [
            _round("r01", {"e2e_wall_s": 100}),
            _round("r02", {"e2e_wall_s": 400}),
        ]
        ok, problems = benchhist.check_regression(
            rounds, threshold=0.5, metrics=["e2e_wall_s"]
        )
        assert not ok and "e2e_wall_s" in problems[0]


class TestCli:
    def _run(self, *args, cwd=None):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "bench_history.py"),
             *args],
            capture_output=True, text=True, env=env, cwd=cwd or REPO,
        )

    def test_trajectory_over_the_real_driver_files(self):
        """Acceptance: run over BENCH_r01..r05, print the e2e
        trajectory, no error — salvaged rounds included."""
        if not benchhist.default_paths(REPO):
            pytest.skip("driver trajectory not present")
        r = self._run("--dir", REPO)
        assert r.returncode == 0, r.stderr
        assert "e2e_reads_per_sec" in r.stdout
        assert "value" in r.stdout

    def test_check_exits_1_on_synthetic_regression(self, tmp_path):
        for name, v in (("BENCH_r01.json", 40000), ("BENCH_r02.json", 5000)):
            (tmp_path / name).write_text(json.dumps({
                "n": 1, "cmd": "x", "rc": 0, "tail": "",
                "parsed": {"e2e_reads_per_sec": v},
            }))
        r = self._run("--dir", str(tmp_path), "--check")
        assert r.returncode == 1
        assert "BENCH REGRESSION" in r.stderr
        r = self._run("--dir", str(tmp_path), "--check", "--threshold", "0.95")
        assert r.returncode == 0

    def test_candidate_round_joins_the_trajectory(self, tmp_path):
        (tmp_path / "BENCH_r01.json").write_text(json.dumps({
            "n": 1, "cmd": "x", "rc": 0, "tail": "",
            "parsed": {"e2e_reads_per_sec": 40000},
        }))
        cand = tmp_path / "fresh.json"
        cand.write_text(json.dumps({"e2e_reads_per_sec": 41000}))
        r = self._run("--dir", str(tmp_path), "--candidate", str(cand),
                      "--check", "--json")
        assert r.returncode == 0
        doc = json.loads(r.stdout)
        assert doc["trajectory"]["rounds"][-1] == "fresh"
        assert doc["gate"]["ok"]

    def test_no_files_is_a_usage_error(self, tmp_path):
        r = self._run("--dir", str(tmp_path))
        assert r.returncode == 2


class TestBenchStdoutContract:
    def test_tiny_bench_final_stdout_line_is_compact_json(self, tmp_path):
        """THE r5 fix, subprocess-pinned: a real `python bench.py` run
        ends stdout with a parseable JSON line that carries the
        canonical headline metrics AND fits the driver's tail window;
        the full result rides the line above it."""
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            DUT_BENCH_READS="2500",
            DUT_BENCH_CPU_SAMPLE="150",
            DUT_BENCH_REPS="1",
            DUT_BENCH_VEC_REPS="1",
            DUT_BENCH_VEC_SAMPLE="2000",
            DUT_BENCH_PER_CONFIG="0",
            DUT_BENCH_E2E_READS="0",  # skip e2e/serve/cpu legs: this
            # test pins the stdout contract, not the e2e pipeline
            DUT_BENCH_CACHE=str(tmp_path / "cache"),
        )
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True, env=env,
            cwd=str(tmp_path),  # no BENCH_r0N.json here: gate is vacuous
            timeout=540,
        )
        assert r.returncode == 0, r.stderr[-2000:]
        lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
        assert len(lines) >= 2
        compact = json.loads(lines[-1])  # MUST parse: the contract
        assert compact["metric"] == "reads_per_sec_duplex_consensus"
        assert compact["value"] > 0 and compact["unit"] == "reads/s"
        # the whole point of the compact line: it fits the window even
        # after the journal line spends its ~500 bytes of the budget
        assert len(lines[-1]) < 1400
        full = json.loads(lines[-2])
        assert full["value"] == compact["value"]
        assert "vs_baseline" in full
        # the full result is mirrored beside the cache for post-mortem
        assert compact.get("full") and os.path.exists(compact["full"])
