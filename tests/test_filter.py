"""min-input-base-quality masking + the consensus post-filter
(FilterConsensusReads analogue) + multi-chromosome input."""

import json

import numpy as np
import pytest

from duplexumiconsensusreads_tpu.cli import main
from duplexumiconsensusreads_tpu.io import read_bam
from duplexumiconsensusreads_tpu.oracle import call_consensus, group_reads
from duplexumiconsensusreads_tpu.ops import ConsensusCaller
from duplexumiconsensusreads_tpu.simulate import SimConfig, simulate_batch
from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams


def test_min_input_qual_masks_evidence_and_depth():
    """A base below the threshold contributes nothing — including to
    depth — on both backends, bit-identically."""
    cfg = SimConfig(n_molecules=30, duplex=False, qual_lo=10, qual_hi=40, seed=3)
    batch, _ = simulate_batch(cfg)
    gp = GroupingParams(strategy="exact")
    fams = group_reads(batch, gp)
    for miq in (0, 25):
        cp = ConsensusParams(mode="single_strand", min_input_qual=miq)
        cpu = ConsensusCaller(cp, backend="cpu")(batch, fams)
        tpu = ConsensusCaller(cp, backend="tpu")(batch, fams)
        cv = np.asarray(cpu.valid, bool)
        np.testing.assert_array_equal(
            np.asarray(cpu.depth)[cv], np.asarray(tpu.depth)[: len(cv)][cv]
        )
        np.testing.assert_array_equal(
            np.asarray(cpu.bases)[cv], np.asarray(tpu.bases)[: len(cv)][cv]
        )
    # with a high threshold, depth must strictly drop somewhere
    lo = ConsensusCaller(
        ConsensusParams(mode="single_strand"), backend="cpu"
    )(batch, fams)
    hi = ConsensusCaller(
        ConsensusParams(mode="single_strand", min_input_qual=35), backend="cpu"
    )(batch, fams)
    assert np.asarray(hi.depth).sum() < np.asarray(lo.depth).sum()


def _make_consensus(tmp_path, **sim_kw):
    bam = str(tmp_path / "in.bam")
    truth = str(tmp_path / "t.npz")
    cons = str(tmp_path / "cons.bam")
    args = [
        "simulate", "-o", bam, "--truth", truth,
        "--molecules", str(sim_kw.get("molecules", 120)),
        "--read-len", "40", "--positions", "8",
        "--base-error", "0.03", "--sorted", "--seed", "5",
    ]
    assert main(args) == 0
    assert main(
        ["call", bam, "-o", cons, "--config", "config3", "--capacity", "512"]
    ) == 0
    return cons


def test_filter_min_depth(tmp_path, capsys):
    cons = _make_consensus(tmp_path)
    out = str(tmp_path / "f.bam")
    assert main(["filter", cons, "-o", out, "--min-depth", "4"]) == 0
    _, before = read_bam(cons)
    _, after = read_bam(out)
    assert 0 < len(after) < len(before)
    import struct

    for a in after.aux_raw:
        i = a.find(b"cDi")
        assert struct.unpack_from("<i", a, i + 3)[0] >= 4
    # records below threshold really existed
    lows = 0
    for a in before.aux_raw:
        i = a.find(b"cDi")
        lows += struct.unpack_from("<i", a, i + 3)[0] < 4
    assert lows == len(before) - len(after)


def test_filter_mask_and_nfrac(tmp_path):
    cons = _make_consensus(tmp_path)
    out = str(tmp_path / "m.bam")
    assert main(
        ["filter", cons, "-o", out, "--mask-qual", "60", "--max-n-frac", "0.5"]
    ) == 0
    _, after = read_bam(out)
    # masked bases are N with qual 2
    for i in range(len(after)):
        l = int(after.lengths[i])
        q = after.qual[i, :l]
        s = after.seq[i, :l]
        assert ((q >= 60) | ((s == 4) & (q == 2))).all()
        assert (s == 4).sum() <= 0.5 * l


def test_filter_foreign_int_types_and_missing_tags(tmp_path, capsys):
    """Depth filtering must accept every BAM integer aux type (other
    writers store small depths as c/s/S), and records LACKING the depth
    tags must be counted + warned about, not silently conflated with
    low depth (ADVICE r2)."""
    import struct

    from duplexumiconsensusreads_tpu.io.bam import write_bam

    cons = _make_consensus(tmp_path)
    header, recs = read_bam(cons)
    # rewrite aux: record 0 loses its depth tags entirely; the rest get
    # cD as int16 's' and cM as uint8 'C' (foreign-writer flavour)
    for i in range(len(recs)):
        a = recs.aux_raw[i]
        j = a.find(b"cDi")
        cd = struct.unpack_from("<i", a, j + 3)[0]
        k = a.find(b"cMi")
        cm = struct.unpack_from("<i", a, k + 3)[0]
        rx_end = a.find(b"cDi")
        if i == 0:
            recs.aux_raw[i] = a[:rx_end]
        else:
            recs.aux_raw[i] = (
                a[:rx_end]
                + b"cDs" + struct.pack("<h", cd)
                + b"cMC" + struct.pack("<B", min(cm, 255))
            )
    foreign = str(tmp_path / "foreign.bam")
    write_bam(foreign, header, recs)
    out = str(tmp_path / "ff.bam")
    assert main(["filter", foreign, "-o", out, "--min-depth", "1"]) == 0
    err = capsys.readouterr().err
    assert "1 records lack a required depth tag" in err
    _, after = read_bam(out)
    # every tagged record had cD >= 1 (they produced consensus), so only
    # the tagless record is dropped
    assert len(after) == len(recs) - 1


def test_filter_passthrough_identity(tmp_path):
    cons = _make_consensus(tmp_path)
    out = str(tmp_path / "id.bam")
    assert main(["filter", cons, "-o", out]) == 0
    _, a = read_bam(cons)
    _, b = read_bam(out)
    assert len(a) == len(b)
    np.testing.assert_array_equal(a.seq, b.seq)
    np.testing.assert_array_equal(a.qual, b.qual)
    assert a.names == b.names


def test_mixed_mates_warns():
    """A family holding both R1 and R2 mates (opposite fragment ends)
    must warn loudly — cycle-space consensus cannot mix them."""
    import warnings

    from duplexumiconsensusreads_tpu.io.bam import (
        FLAG_MATE_REVERSE,
        FLAG_PAIRED,
        FLAG_READ1,
        FLAG_READ2,
        FLAG_REVERSE,
    )
    from duplexumiconsensusreads_tpu.io.convert import (
        records_to_readbatch,
        simulated_bam,
    )

    cfg = SimConfig(n_molecules=20, duplex=False, seed=8)
    _, recs, _, _ = simulated_bam(cfg, sort=True)
    n = len(recs)
    # make half of each family's reads R2 mates of the same template:
    # F1R2 — R1 forward and R2 reverse BOTH classify as top strand,
    # so the two mates land in one family
    flags = np.asarray(recs.flags)
    flags[::2] = FLAG_PAIRED | FLAG_READ1 | FLAG_MATE_REVERSE
    flags[1::2] = FLAG_PAIRED | FLAG_READ2 | FLAG_REVERSE
    recs.flags = flags.astype(np.uint16)
    with pytest.warns(UserWarning, match="R1 and R2 mates"):
        records_to_readbatch(recs, duplex=False)
    # simulator's own paired-end convention (one read per strand) must
    # NOT warn
    cfg2 = SimConfig(n_molecules=20, duplex=True, seed=9)
    _, recs2, _, _ = simulated_bam(cfg2, sort=True, paired_end=True)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        records_to_readbatch(recs2, duplex=True)


def test_multi_chromosome_grouping_and_call(tmp_path):
    """Reads on different chromosomes at the same coordinate are
    different families (pos_key packs ref_id); the whole pipeline and
    BAM round-trip must respect it."""
    from duplexumiconsensusreads_tpu.io.bam import BamHeader, write_bam
    from duplexumiconsensusreads_tpu.io.convert import (
        readbatch_to_records,
        records_to_readbatch,
        pack_pos_key,
    )
    from duplexumiconsensusreads_tpu.runtime.executor import (
        call_batch_cpu,
        call_batch_tpu,
    )
    from duplexumiconsensusreads_tpu.types import ReadBatch

    rng = np.random.default_rng(9)
    n, l, u = 60, 30, 6
    half = n // 2
    # per-chromosome true sequence + sparse errors (uniformly random
    # bases would create plurality ties where f32/f64 argmax differ)
    seq1 = rng.integers(0, 4, size=l, dtype=np.uint8)
    seq2 = rng.integers(0, 4, size=l, dtype=np.uint8)
    bases = np.r_[np.tile(seq1, (half, 1)), np.tile(seq2, (n - half, 1))]
    err = rng.random((n, l)) < 0.05
    bases[err] = (bases[err] + 1) % 4
    batch = ReadBatch(
        bases=bases,
        quals=np.full((n, l), 30, np.uint8),
        umi=np.tile(rng.integers(0, 4, size=u, dtype=np.uint8), (n, 1)),
        pos_key=pack_pos_key(
            np.r_[np.zeros(half, np.int64), np.ones(n - half, np.int64)],
            np.full(n, 500, np.int64),
        ),
        strand_ab=np.ones(n, bool),
        frag_end=np.zeros(n, bool),
        valid=np.ones(n, bool),
    )
    gp = GroupingParams(strategy="exact")
    cp = ConsensusParams(mode="single_strand")
    t = call_batch_tpu(batch, gp, cp, capacity=64)
    c = call_batch_cpu(batch, gp, cp)
    # same UMI + same coordinate, two chromosomes -> exactly 2 families
    assert len(t[0]) == len(c[0]) == 2
    np.testing.assert_array_equal(t[0], c[0])

    # BAM round-trip keeps the two ref_ids distinct
    recs = readbatch_to_records(batch, duplex=False)
    header = BamHeader.synthetic(
        ref_names=("chr1", "chr2"), ref_lengths=(10_000, 10_000)
    )
    p = str(tmp_path / "multi.bam")
    write_bam(p, header, recs)
    h2, recs2 = read_bam(p)
    assert h2.ref_names == ["chr1", "chr2"]
    batch2, _ = records_to_readbatch(recs2, duplex=False)
    assert len(np.unique(np.asarray(batch2.pos_key))) == 2


class TestMaxReadsDownsampling:
    def _batch(self):
        from duplexumiconsensusreads_tpu.types import ReadBatch

        rng = np.random.default_rng(3)
        n, l, u = 40, 20, 6
        umi = np.tile(rng.integers(0, 4, size=u, dtype=np.uint8), (n, 1))
        umi[20:, 0] = (umi[20:, 0] + 1) % 4  # two families of 20
        return ReadBatch(
            bases=rng.integers(0, 4, size=(n, l), dtype=np.uint8),
            quals=rng.integers(10, 41, size=(n, l), dtype=np.uint8),
            umi=umi,
            pos_key=np.full(n, 777, np.int64),
            strand_ab=np.ones(n, bool),
            frag_end=np.zeros(n, bool),
            valid=np.ones(n, bool),
        )

    def test_keeps_top_quality_per_subfamily(self):
        from duplexumiconsensusreads_tpu.io.convert import downsample_families

        batch = self._batch()
        score = (batch.quals.astype(int) * (batch.bases < 4)).sum(axis=1)
        dropped = downsample_families(batch, 5)
        assert dropped == 30
        for fam in (np.arange(20), np.arange(20, 40)):
            kept = fam[batch.valid[fam]]
            assert len(kept) == 5
            # kept reads are exactly the 5 best scores of the family
            assert set(score[kept]) == set(np.sort(score[fam])[-5:])

    def test_strands_and_ends_capped_independently(self):
        from duplexumiconsensusreads_tpu.io.convert import downsample_families

        batch = self._batch()
        batch.umi[:] = batch.umi[0]  # one (pos, UMI) pair
        batch.strand_ab[:20] = False
        batch.frag_end[10:20] = True
        dropped = downsample_families(batch, 4)
        # sub-families: (BA,end1) 10, (BA,end2) 10, (AB,end1) 20
        assert dropped == (10 - 4) + (10 - 4) + (20 - 4)
        assert batch.valid.sum() == 12

    def test_zero_means_off_and_determinism(self):
        from duplexumiconsensusreads_tpu.io.convert import downsample_families

        b1, b2 = self._batch(), self._batch()
        assert downsample_families(b1, 0) == 0
        assert b1.valid.all()
        downsample_families(b1, 3)
        downsample_families(b2, 3)
        np.testing.assert_array_equal(b1.valid, b2.valid)

    def test_cli_max_reads_end_to_end(self, tmp_path):
        import json as _json

        from duplexumiconsensusreads_tpu.cli.main import main
        from duplexumiconsensusreads_tpu.io.bam import read_bam

        bam = str(tmp_path / "in.bam")
        truth = str(tmp_path / "t.npz")
        assert main([
            "simulate", "-o", bam, "--truth", truth, "--molecules", "60",
            "--family-size", "8", "--max-family-size", "16", "--sorted",
            "--seed", "2",
        ]) == 0
        out1 = str(tmp_path / "c1.bam")
        out2 = str(tmp_path / "c2.bam")
        rep1 = str(tmp_path / "r1.json")
        rep2 = str(tmp_path / "r2.json")
        # whole-file and streamed runs with the same cap must agree
        assert main([
            "call", bam, "-o", out1, "--config", "config3",
            "--capacity", "256", "--max-reads", "3", "--report", rep1,
        ]) == 0
        assert main([
            "call", bam, "-o", out2, "--config", "config3",
            "--capacity", "256", "--max-reads", "3", "--report", rep2,
            "--chunk-reads", "150",
        ]) == 0
        r1 = _json.load(open(rep1))
        r2 = _json.load(open(rep2))
        assert r1["n_downsampled_reads"] > 0
        assert r1["n_downsampled_reads"] == r2["n_downsampled_reads"]
        _, a = read_bam(out1)
        _, b = read_bam(out2)
        assert len(a) == len(b) > 0
        np.testing.assert_array_equal(a.seq, b.seq)
        np.testing.assert_array_equal(a.qual, b.qual)
        # depth tags reflect the cap: no consensus saw more than
        # 2 strands * 3 reads
        from duplexumiconsensusreads_tpu.io.convert import depth_stats  # noqa: F401
        import struct as _struct
        for aux in a.aux_raw:
            i = aux.find(b"cDi")
            assert i >= 0
            (cd,) = _struct.unpack_from("<i", aux, i + 3)
            assert cd <= 6


def test_filter_min_base_depth_masks_shallow_cycles(tmp_path, capsys):
    """--min-base-depth consumes the cd:B per-base arrays: cycles below
    the threshold go N/qual-2; records lacking cd are warned about and
    left unmasked."""
    import struct

    from duplexumiconsensusreads_tpu.cli.main import main as cli_main
    from duplexumiconsensusreads_tpu.io.bam import read_bam

    bam = str(tmp_path / "in.bam")
    assert cli_main([
        "simulate", "-o", bam, "--molecules", "40", "--read-len", "30",
        "--positions", "4", "--seed", "8", "--sorted",
    ]) == 0
    cons = str(tmp_path / "c.bam")
    assert cli_main([
        "call", bam, "-o", cons, "--config", "config3", "--capacity", "256",
        "--per-base-tags",
    ]) == 0
    _, before = read_bam(cons)
    # choose a threshold between min and max observed per-base depth so
    # the mask demonstrably fires without wiping every base
    def cd_arr(a):
        i = a.find(b"cdB")
        sub = a[i + 3 : i + 4]
        dt = {b"S": "<u2", b"I": "<u4"}[sub]
        (cnt,) = struct.unpack_from("<I", a, i + 4)
        return np.frombuffer(a, dt, cnt, i + 8).astype(np.uint32)

    depths = np.concatenate([cd_arr(a) for a in before.aux_raw])
    thr = int(depths.max())  # masks every cycle shallower than the max
    out = str(tmp_path / "f.bam")
    assert cli_main([
        "filter", cons, "-o", out, "--min-base-depth", str(thr),
    ]) == 0
    _, after = read_bam(out)
    n_shallow = int((depths < thr).sum())
    assert n_shallow > 0
    n_masked = sum(
        int(((after.seq[k][: after.lengths[k]] == 4)
             & (cd_arr(after.aux_raw[k])[: after.lengths[k]] < thr)).sum())
        for k in range(len(after))
    )
    assert n_masked >= n_shallow * 0.9  # all shallow cycles went N
    err = capsys.readouterr().err
    assert f"masked" in err

    # input without cd tags: warned, not dropped
    plain = str(tmp_path / "plain.bam")
    assert cli_main([
        "call", bam, "-o", plain, "--config", "config3", "--capacity", "256",
    ]) == 0
    out2 = str(tmp_path / "f2.bam")
    assert cli_main([
        "filter", plain, "-o", out2, "--min-base-depth", "2",
    ]) == 0
    err = capsys.readouterr().err
    assert "lack a usable per-base cd array" in err
    _, kept = read_bam(out2)
    assert len(kept) == len(before)  # nothing dropped


def test_filter_error_rate_thresholds(tmp_path, capsys):
    """--max-base-error-rate masks high-disagreement cycles from ce/cd;
    --max-read-error-rate drops high-disagreement records; inputs
    lacking the arrays are warned about and skipped (fgbio
    FilterConsensusReads' error-rate pair)."""
    import struct

    from duplexumiconsensusreads_tpu.cli.main import main as cli_main
    from duplexumiconsensusreads_tpu.io.bam import read_bam

    bam = str(tmp_path / "in.bam")
    assert cli_main([
        "simulate", "-o", bam, "--molecules", "50", "--read-len", "30",
        "--positions", "4", "--base-error", "0.08", "--seed", "9",
        "--sorted",
    ]) == 0
    cons = str(tmp_path / "c.bam")
    assert cli_main([
        "call", bam, "-o", cons, "--config", "config3", "--capacity",
        "256", "--per-base-tags",
    ]) == 0
    _, before = read_bam(cons)

    def b_arr(a, tag):
        i = a.find(tag + b"B")
        sub = a[i + 3 : i + 4]
        dt = {b"S": "<u2", b"I": "<u4"}[sub]
        (cnt,) = struct.unpack_from("<I", a, i + 4)
        return np.frombuffer(a, dt, cnt, i + 8).astype(np.int64)

    # per-record read error rates on the input
    rates = []
    for k in range(len(before)):
        d = b_arr(before.aux_raw[k], b"cd")
        e = b_arr(before.aux_raw[k], b"ce")
        rates.append(e.sum() / max(int(d.sum()), 1))
    rates = np.asarray(rates)
    thr = float(np.median(rates))
    want_drop = int((rates > thr).sum())
    assert 0 < want_drop < len(before)  # threshold splits the records

    out = str(tmp_path / "f.bam")
    assert cli_main([
        "filter", cons, "-o", out, "--max-read-error-rate", str(thr),
    ]) == 0
    _, after = read_bam(out)
    assert len(after) == len(before) - want_drop

    # base-level: mask every cycle with ANY disagreement (rate 0 keeps
    # only unanimous cycles; e > 0*d <=> e > 0)
    out2 = str(tmp_path / "f2.bam")
    assert cli_main([
        "filter", cons, "-o", out2, "--max-base-error-rate", "0.0",
    ]) == 0
    _, after2 = read_bam(out2)
    assert len(after2) == len(before)  # masking only, no drops
    for k in range(len(after2)):
        li = int(after2.lengths[k])
        e = b_arr(after2.aux_raw[k], b"ce")[:li]
        called = after2.seq[k][:li]
        assert not np.any((e > 0) & (called != 4)), k

    # input without the arrays: warned, untouched
    plain = str(tmp_path / "plain.bam")
    assert cli_main([
        "call", bam, "-o", plain, "--config", "config3", "--capacity",
        "256",
    ]) == 0
    out3 = str(tmp_path / "f3.bam")
    capsys.readouterr()
    assert cli_main([
        "filter", plain, "-o", out3, "--max-read-error-rate", "0.01",
    ]) == 0
    err = capsys.readouterr().err
    assert "skipped the error-rate filters" in err
    _, kept = read_bam(out3)
    assert len(kept) == len(before)
