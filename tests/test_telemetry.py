"""Telemetry suite: the span recorder, the streaming executor's
per-chunk capture, the offline analysis (critical path, lane
utilization, percentiles, sum-check), the Chrome exporter, the
heartbeat, the capture schema validator, and the report-shape
satellites (--report -, profile_phases tolerance, RunReport golden
schema).

The load-bearing contract: a capture's per-stage span totals must
reproduce ``RunReport.seconds`` busy totals exactly (the recorder logs
the same measured dt), chaos/retry/resume machinery must leave
structured events, and with tracing off the executor behaves
byte-identically to an untraced run.
"""

import dataclasses
import json
import os
import subprocess
import sys
import threading

import pytest

from duplexumiconsensusreads_tpu.io import read_bam, simulated_bam
from duplexumiconsensusreads_tpu.runtime import faults
from duplexumiconsensusreads_tpu.runtime.stream import stream_call_consensus
from duplexumiconsensusreads_tpu.simulate import SimConfig
from duplexumiconsensusreads_tpu.telemetry import (
    chrome,
    device,
    devledger,
    ledger,
    report,
    trace,
)
from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams

GP = GroupingParams(strategy="adjacency", paired=True)
CP = ConsensusParams(mode="duplex")
KW = dict(capacity=128, chunk_reads=90)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# every per-chunk stage a fresh (non-resumed) streaming run must record
CHUNK_STAGES = (
    "ingest", "bucketing", "dispatch", "device_wait_fetch", "scatter",
    "deflate", "shard_write", "ckpt", "finalise",
)


@pytest.fixture(scope="module")
def traced(tmp_path_factory):
    """One traced + heartbeat streaming run shared by the read-only
    assertions: (records, report dict, paths dict)."""
    d = tmp_path_factory.mktemp("telemetry")
    in_path = str(d / "in.bam")
    cfg = SimConfig(n_molecules=70, n_positions=9, umi_error=0.02, seed=31)
    simulated_bam(cfg, path=in_path, sort=True)
    paths = {
        "in": in_path,
        "out": str(d / "out.bam"),
        "trace": str(d / "trace.jsonl"),
        "report": str(d / "report.json"),
    }
    stream_call_consensus(
        in_path, paths["out"], GP, CP,
        # tight interval: a fully WARM run (full-suite ordering leaves
        # every kernel compiled by the time this fixture executes) can
        # finish in well under 50ms, and the heartbeat assertions need
        # at least one sample inside the run's wall
        trace_path=paths["trace"], heartbeat_s=0.005,
        report_path=paths["report"], **KW,
    )
    records = report.load_trace(paths["trace"])
    with open(paths["report"]) as f:
        rep = json.load(f)
    return records, rep, paths


# ------------------------------------------------------------- recorder

class TestRecorder:
    def test_meta_first_summary_last(self, tmp_path):
        p = str(tmp_path / "t.jsonl")
        tr = trace.TraceRecorder(p)
        tr.span("ingest", tr._t0, 0.5, chunk=0)
        tr.event("retry", site="ingest.read", attempt=1)
        tr.write_summary(seconds={"ingest": 0.5, "total": 1.0})
        tr.close()
        recs = report.load_trace(p)
        assert recs[0]["type"] == "meta"
        assert recs[0]["version"] == trace.TRACE_VERSION
        assert recs[-1]["type"] == "summary"
        assert recs[-1]["n_events"] == 2
        assert report.validate_trace(recs) == []
        # span carries the relative timestamp + attrs envelope
        sp = [r for r in recs if r["type"] == "span"][0]
        assert sp["stage"] == "ingest" and sp["chunk"] == 0
        assert sp["t"] == 0.0 and sp["dur"] == 0.5

    def test_lane_from_thread_name(self, tmp_path):
        p = str(tmp_path / "t.jsonl")
        tr = trace.TraceRecorder(p)

        def record():
            tr.span("scatter", tr._t0, 0.1, chunk=1)

        for name in ("dut-drain_3", "dut-xfer_0"):
            t = threading.Thread(target=record, name=name)
            t.start()
            t.join()
        tr.span("finalise", tr._t0, 0.1)
        tr.close()
        lanes = {r["lane"] for r in report.load_trace(p) if r["type"] == "span"}
        assert lanes == {"drain-3", "xfer-0", "main"}

    def test_bounded_capture_truncates(self, tmp_path):
        p = str(tmp_path / "t.jsonl")
        tr = trace.TraceRecorder(p, max_events=3)
        for i in range(10):
            tr.span("ingest", tr._t0, 0.01, chunk=i)
        assert tr.n_events == 3 and tr.n_dropped == 7
        tr.write_summary(seconds={})
        tr.close()
        recs = report.load_trace(p)
        assert report.validate_trace(recs) == []
        spans = [r for r in recs if r["type"] == "span"]
        assert len(spans) == 3
        assert any(
            r.get("name") == "truncated" and r["max_events"] == 3
            for r in recs
        )

    def test_summary_seals_the_capture(self, tmp_path):
        """Nothing may follow the terminal summary: a straggling
        heartbeat/worker record after write_summary is dropped, so a
        healthy run can never flake the check_trace CI gate."""
        p = str(tmp_path / "t.jsonl")
        tr = trace.TraceRecorder(p)
        tr.span("ingest", tr._t0, 0.1, chunk=0)
        tr.write_summary(seconds={"ingest": 0.1, "total": 0.2})
        tr.event("heartbeat", chunks_done=1)  # late beat: must drop
        tr.span("finalise", tr._t0, 0.1)
        tr.write_summary(seconds={})  # double summary: must drop too
        tr.close()
        recs = report.load_trace(p)
        assert report.validate_trace(recs) == []
        assert recs[-1]["type"] == "summary"
        assert report.summary_record(recs) is not None

    def test_existing_capture_rotated_not_truncated(self, tmp_path):
        """The documented crash flow is 'rerun with --resume': the new
        run's recorder must rotate the crashed run's capture to .prev,
        not destroy the post-mortem evidence."""
        p = str(tmp_path / "t.jsonl")
        tr1 = trace.TraceRecorder(p)
        tr1.event("retry", site="ingest.read", attempt=1)
        tr1.close()
        tr2 = trace.TraceRecorder(p)
        tr2.close()
        prev = report.load_trace(p + ".prev")
        assert any(r.get("name") == "retry" for r in prev)
        assert [r["type"] for r in report.load_trace(p)] == ["meta"]

    def test_truncated_capture_sum_check_one_sided(self, tmp_path):
        """A capture bounded by max_events must NOT fail the sum-check
        (its totals are a lower bound, not an instrumentation bug);
        an impossible EXCESS still fails."""
        p = str(tmp_path / "t.jsonl")
        tr = trace.TraceRecorder(p, max_events=2)
        for i in range(6):
            tr.span("ingest", tr._t0, 1.0, chunk=i)
        tr.write_summary(seconds={"ingest": 6.0, "total": 6.0})
        tr.close()
        recs = report.load_trace(p)
        assert report.validate_trace(recs) == []
        rows, ok = report.sum_check(recs)
        assert ok, rows  # shortfall tolerated under truncation
        lines, ok2 = report.render_report(recs)
        assert ok2
        assert any("one-sided" in ln and "dropped" in ln for ln in lines)
        # trace > report stays a failure even when truncated
        _, ok3 = report.sum_check(recs, seconds={"ingest": 0.5, "total": 6.0})
        assert not ok3

    def test_close_is_idempotent_and_late_writes_drop(self, tmp_path):
        p = str(tmp_path / "t.jsonl")
        tr = trace.TraceRecorder(p)
        tr.close()
        tr.close()
        tr.span("ingest", tr._t0, 0.1)  # must not raise on closed file
        tr.event("retry")
        tr.write_summary(seconds={})
        assert [r["type"] for r in report.load_trace(p)] == ["meta"]

    def test_global_hook_zero_when_uninstalled(self):
        trace.uninstall()
        assert trace.get_active() is None
        trace.emit_event("retry", site="x")  # no recorder: must be a no-op


# -------------------------------------------------- streaming capture

class TestStreamCapture:
    def test_capture_is_schema_valid(self, traced):
        records, _, _ = traced
        assert report.validate_trace(records) == []
        assert report.summary_record(records) is not None

    def test_every_chunk_covered_by_every_stage(self, traced):
        records, rep, _ = traced
        n_chunks = rep["n_chunks"]
        assert n_chunks >= 3
        by_stage = {}
        for r in records:
            if r["type"] == "span" and "chunk" in r:
                by_stage.setdefault(r["stage"], set()).add(r["chunk"])
        for stage in CHUNK_STAGES:
            assert by_stage.get(stage) == set(range(n_chunks)), stage

    def test_lanes_cover_main_xfer_drain(self, traced):
        records, _, _ = traced
        util = report.lane_utilization(records)
        assert "main" in util
        assert any(lane.startswith("drain-") for lane in util)
        assert any(lane.startswith("xfer-") for lane in util)
        # drain stages really ran on drain lanes, dispatch on xfer
        for r in records:
            if r["type"] != "span":
                continue
            if r["stage"] in ("scatter", "deflate", "device_wait_fetch"):
                assert r["lane"].startswith("drain-"), r
            # ingest/bucketing ride the producer's ingest lane when the
            # pipelined-ingest default (auto=on) runs them off-thread
            if r["stage"] in ("ingest", "bucketing"):
                assert r["lane"] in ("main", "ingest"), r
            if r["stage"] in ("ckpt", "finalise", "main_loop_stall"):
                assert r["lane"] == "main", r

    def test_sum_check_against_report_seconds(self, traced):
        """THE acceptance contract: per-stage span totals reproduce the
        RunReport busy totals — checked against both the embedded
        summary and the separately-written --report JSON."""
        records, rep, _ = traced
        rows, ok = report.sum_check(records)
        assert ok, [r for r in rows if not r["ok"]]
        rows2, ok2 = report.sum_check(records, seconds=rep["seconds"])
        assert ok2, [r for r in rows2 if not r["ok"]]
        # and a corrupted report must FAIL the check (the canary works)
        bad = dict(rep["seconds"], scatter=rep["seconds"]["scatter"] + 5.0)
        _, ok3 = report.sum_check(records, seconds=bad)
        assert not ok3

    def test_critical_path_and_percentiles(self, traced):
        records, rep, _ = traced
        paths = report.chunk_critical_paths(records)
        assert set(paths) == set(range(rep["n_chunks"]))
        for p in paths.values():
            assert p["latency_s"] > 0
            assert p["dominant"] in p["stages"]
            # the chain is time-ordered and begins with ingest
            assert p["chain"][0][0] == "ingest"
        pct = report.chunk_latency_percentiles(records)
        assert pct["n_chunks"] == rep["n_chunks"]
        assert 0 < pct["p50_s"] <= pct["p95_s"] <= pct["max_s"]
        assert sum(pct["dominant_stages"].values()) == rep["n_chunks"]

    def test_heartbeat_samples_in_capture_and_report_fields(self, traced):
        records, _, _ = traced
        beats = [
            r for r in records
            if r["type"] == "event" and r.get("name") == "heartbeat"
        ]
        assert beats  # 0.05s interval over a multi-second run
        for b in beats:
            assert {"chunks_done", "chunks_inflight", "stall_frac",
                    "retries", "drain_util"} <= set(b)

    def test_durable_writes_recorded(self, traced):
        records, rep, _ = traced
        dw = [
            r for r in records
            if r["type"] == "event" and r.get("name") == "durable_write"
        ]
        # at least one per shard (chunks) + checkpoint marks
        assert len(dw) >= rep["n_chunks"]
        assert all(r.get("bytes", -1) >= 0 and r.get("dur", -1) >= 0 for r in dw)

    def test_render_report_human_output(self, traced):
        records, _, _ = traced
        lines, ok = report.render_report(records)
        assert ok
        text = "\n".join(lines)
        assert "sum-check vs RunReport.seconds: OK" in text
        assert "chunk critical path" in text
        assert "drain-0" in text

    def test_chrome_export_opens_lanes_as_tracks(self, traced, tmp_path):
        records, _, _ = traced
        out = str(tmp_path / "chrome.json")
        n = chrome.write_chrome(records, out)
        with open(out) as f:
            doc = json.load(f)
        evs = doc["traceEvents"]
        assert len(evs) == n
        n_spans = sum(1 for r in records if r["type"] == "span")
        assert sum(1 for e in evs if e["ph"] == "X") == n_spans
        names = {
            e["args"]["name"] for e in evs
            if e["ph"] == "M" and e["name"] == "thread_name"
        }
        assert "main" in names and "drain-0" in names
        # spans lose "dur" to the X-event field, but on point events it
        # is payload (durable_write's fsync cost) and must survive
        assert not any("dur" in e["args"] for e in evs if e["ph"] == "X")
        dwr = [e for e in evs if e["ph"] == "i" and e["name"] == "durable_write"]
        assert dwr and all("dur" in e["args"] for e in dwr)
        # main is the first track (stable sort order)
        tids = {e["args"]["name"]: e["tid"] for e in evs
                if e["ph"] == "M" and e["name"] == "thread_name"}
        assert tids["main"] == min(tids.values())

    def test_untraced_run_byte_identical_and_no_capture(self, traced, tmp_path):
        """Tracing must be pure observation: the same input without
        --trace produces byte-identical output, and no recorder is left
        installed after a traced run."""
        records, _, paths = traced
        assert trace.get_active() is None
        out2 = str(tmp_path / "plain.bam")
        stream_call_consensus(paths["in"], out2, GP, CP, **KW)
        with open(paths["out"], "rb") as a, open(out2, "rb") as b:
            assert a.read() == b.read()


# ------------------------------------------------------------ byte ledger

class TestByteLedger:
    """The xfer record contract (telemetry/ledger.py): per-chunk
    per-direction byte accounting whose record totals reproduce the
    summary totals exactly and whose shard bytes reproduce the
    finalised output, on-disk, to the byte."""

    def test_xfer_record_schema_golden(self, traced):
        records, rep, _ = traced
        xf = ledger.xfer_records(records)
        assert xf, "a traced streaming run must carry ledger records"
        # golden envelopes per direction — a new field is a schema
        # change and must be made here (and in ARCHITECTURE.md) on
        # purpose, not by drift. h2d records carry the rung's bits per
        # cycle (bpc) since the wire-diet-v2 packing ladder.
        for r in xf:
            assert r["dir"] in trace.KNOWN_XFER_DIRS
            base = {"type", "dir", "t", "dur", "wire", "lane", "chunk"}
            if r.get("resumed"):
                assert set(r) == base | {"resumed"}
            elif r["dir"] == "h2d":
                # bpc joined with the wire-diet-v2 packing ladder;
                # rows_real/rows_pad/cap with the bucket auto-tuner's
                # fill-factor audit trail (wirestat's fill column);
                # mesh_pad with mesh-sharded execution (the alignment
                # pad buckets this dispatch shipped)
                assert set(r) == base | {
                    "logical", "bpc", "rows_real", "rows_pad", "cap",
                    "mesh_pad",
                }
                assert r["bpc"] in (16, 8, 7, 5)
                assert 0 <= r["rows_real"] <= r["rows_pad"]
                assert r["rows_pad"] % r["cap"] == 0
            else:
                assert set(r) == base | {"logical"}
            assert isinstance(r["wire"], int) and r["wire"] >= 0
            assert r["t"] >= 0 and r["dur"] >= 0
        # every chunk of the run is covered in every direction
        per = ledger.per_chunk_bytes(records)
        assert sorted(per) == list(range(rep["n_chunks"]))
        for row in per.values():
            assert {"h2d", "d2h", "shard"} <= set(row)
        # packing can only shrink the wire, in BOTH directions now —
        # the packed consensus-only return path gives d2h records a
        # real logical-vs-wire gap (the default sim input engages it)
        for r in xf:
            if r["dir"] in ("h2d", "d2h"):
                assert r["logical"] >= r["wire"] > 0
        assert any(
            r["logical"] > r["wire"] for r in xf if r["dir"] == "d2h"
        ), "packed d2h must engage on the default traced run"

    def test_totals_sum_check_and_on_disk_output(self, traced):
        records, rep, paths = traced
        rows, ok = ledger.sum_check_bytes(records)
        assert ok and rows
        b = ledger.summary_bytes(records)
        # the summary totals are the RunReport's wire counters
        assert b["h2d_wire"] == rep["bytes_h2d"]
        assert b["d2h_wire"] == rep["bytes_d2h"]
        # the byte identity the whole ledger is anchored to: overhead
        # (header shell + EOF) plus every shard's wire bytes IS the
        # finalised BAM, measured on disk
        tot = ledger.byte_totals(records)
        assert b["output_bytes"] == os.path.getsize(paths["out"])
        assert (
            b["output_overhead_bytes"] + tot["shard"]["wire"]
            == b["output_bytes"]
        )
        problems, ok2 = ledger.output_check(records)
        assert ok2, problems

    def test_wire_floor_and_bandwidth_are_measured(self, traced):
        records, _, _ = traced
        fl = ledger.wire_floor(records)
        assert 0 < fl["floor_s"] <= fl["wall_s"]
        assert 0 < fl["frac"] <= 1
        # the union can only collapse overlap, never exceed the sums
        assert fl["floor_s"] <= fl["h2d_s"] + fl["d2h_s"] + 1e-9
        bw = ledger.bandwidth_stats(records)
        assert set(bw) == {"h2d", "d2h"}
        for row in bw.values():
            assert row["p95_mb_s"] >= row["p50_mb_s"] >= 0
        pack = ledger.packing_stats(records)
        assert pack["h2d_packing_ratio"] >= 1.0
        # the return path is packed too now: a real d2h ratio > 1
        assert pack["d2h_packing_ratio"] > 1.0
        assert pack["bytes_per_read"] > 0

    def test_validator_rejects_malformed_xfer(self):
        base = [{"type": "meta", "version": trace.TRACE_VERSION,
                 "kind": "run", "clock": "monotonic-relative"}]
        bad_dir = base + [{"type": "xfer", "dir": "warp", "t": 0.0,
                           "dur": 0.0, "wire": 1, "lane": "main"}]
        assert any("warp" in p for p in report.validate_trace(bad_dir))
        bad_wire = base + [{"type": "xfer", "dir": "h2d", "t": 0.0,
                            "dur": 0.0, "wire": 1.5, "lane": "main"}]
        assert any("wire" in p for p in report.validate_trace(bad_wire))
        float_total = base + [{"type": "summary", "t": 1.0, "n_events": 0,
                               "n_dropped": 0, "bytes": {"h2d_wire": 1.5}}]
        assert any("bytes" in p for p in report.validate_trace(float_total))

    def test_wirestat_cli_ok_tampered_record_and_output_drift(
        self, traced, tmp_path
    ):
        """The corruption contract: a healthy capture exits 0; a
        capture whose ledger disagrees with its summary exits 1; a
        capture whose output file no longer matches the ledgered size
        exits 1."""
        _, _, paths = traced
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        wirestat = os.path.join(REPO, "tools", "wirestat.py")
        r = subprocess.run(
            [sys.executable, wirestat, paths["trace"]],
            capture_output=True, text=True, env=env, cwd=REPO,
        )
        assert r.returncode == 0, r.stderr + r.stdout
        assert "byte sum-check" in r.stdout and "OK" in r.stdout
        rj = subprocess.run(
            [sys.executable, wirestat, paths["trace"], "--json"],
            capture_output=True, text=True, env=env, cwd=REPO,
        )
        assert rj.returncode == 0
        doc = json.loads(rj.stdout)
        assert doc["sum_check"]["ok"] and doc["output_check"]["ok"]
        assert doc["wire_floor"]["frac"] > 0
        # tamper one shard record's wire bytes -> record/summary drift
        tampered = str(tmp_path / "tampered.jsonl")
        with open(paths["trace"]) as f, open(tampered, "w") as g:
            done = False
            for line in f:
                rec = json.loads(line)
                if (
                    not done and rec.get("type") == "xfer"
                    and rec.get("dir") == "shard"
                ):
                    rec["wire"] += 512
                    done = True
                g.write(json.dumps(rec, separators=(",", ":")) + "\n")
        assert done
        r = subprocess.run(
            [sys.executable, wirestat, tampered],
            capture_output=True, text=True, env=env, cwd=REPO,
        )
        assert r.returncode == 1
        assert "DRIFT" in r.stderr
        # tamper a d2h record's LOGICAL bytes: the packed return path's
        # logical-vs-wire gap is sum-checked too (a corrupted logical
        # total must not pass just because the wire side still adds up)
        tampered2 = str(tmp_path / "tampered_d2h.jsonl")
        with open(paths["trace"]) as f, open(tampered2, "w") as g:
            done = False
            for line in f:
                rec = json.loads(line)
                if (
                    not done and rec.get("type") == "xfer"
                    and rec.get("dir") == "d2h"
                ):
                    rec["logical"] += 4096
                    done = True
                g.write(json.dumps(rec, separators=(",", ":")) + "\n")
        assert done
        r = subprocess.run(
            [sys.executable, wirestat, tampered2],
            capture_output=True, text=True, env=env, cwd=REPO,
        )
        assert r.returncode == 1
        assert "DRIFT" in r.stderr
        # grow a COPY of the output -> on-disk size drift via --out
        grown = str(tmp_path / "grown.bam")
        with open(paths["out"], "rb") as f:
            data = f.read()
        with open(grown, "wb") as f:
            f.write(data + b"\x00")
        r = subprocess.run(
            [sys.executable, wirestat, paths["trace"], "--out", grown],
            capture_output=True, text=True, env=env, cwd=REPO,
        )
        assert r.returncode == 1

    def test_chrome_export_carries_byte_counters(self, traced):
        records, _, _ = traced
        doc = chrome.to_chrome(records)
        # dev records export their own FLOP/s counters; the byte
        # contract is on the xfer-cat counters only
        counters = [
            e for e in doc["traceEvents"]
            if e["ph"] == "C" and e.get("cat") == "xfer"
        ]
        assert counters
        names = {e["name"] for e in counters}
        assert any(n.startswith("h2d_bytes") for n in names)
        assert any(n.startswith("d2h_bytes") for n in names)
        # every raise has a matching drop back to zero
        for e in counters:
            assert e["args"].get("bytes") is not None
        by_name: dict = {}
        for e in counters:
            by_name.setdefault(e["name"], []).append(e["args"]["bytes"])
        for vals in by_name.values():
            assert 0 in vals and any(v > 0 for v in vals)


# ---------------------------------------------------------- dev ledger

class TestDeviceLedger:
    """The FLOP twin of TestByteLedger: dev-record schema golden,
    the dev sum-check against the executor's phase totals, the devstat
    CLI corruption contract, interval-union busy accounting, and the
    shared peak-FLOP/s table."""

    def test_dev_record_schema_golden(self, traced):
        records, rep, _ = traced
        recs = devledger.dev_records(records)
        assert recs, "a traced streaming run must carry dev records"
        # golden envelope — a new field is a schema change and must be
        # made here (and in ARCHITECTURE.md) on purpose, not by drift
        envelope = {"type", "t", "dur", "chunk", "lane"} | set(
            trace.KNOWN_DEV_FIELDS
        )
        from duplexumiconsensusreads_tpu.ops.pipeline import SSC_METHOD_COSTS

        for r in recs:
            assert set(r) == envelope
            assert r["method"] in SSC_METHOD_COSTS
            assert r["flops"] > 0 and r["buckets"] > 0
            assert r["cap"] > 0 and r["cycles"] > 0
            assert r["dur"] >= 0 and r["disp_s"] >= 0
        # one record per chunk on a clean run — every chunk attributed
        chunks = sorted(r["chunk"] for r in recs)
        n_chunks = rep["n_chunks"]
        assert chunks == list(range(n_chunks))

    def test_sum_check_and_totals(self, traced):
        records, rep, _ = traced
        rows, ok = devledger.sum_check_dev(records)
        assert ok, rows
        assert {r["stage"] for r in rows} == {
            "device_wait_fetch", "dispatch"
        }
        totals = devledger.device_totals(records)
        classes = devledger.class_stats(records)
        assert totals and classes
        # union busy can never exceed summed durations, and per-class
        # FLOPs must add up to the run total (exact: same floats)
        assert totals["busy_s"] <= totals["dev_s"] + 1e-9
        assert sum(d["flops"] for d in classes.values()) == pytest.approx(
            totals["flops"], rel=1e-9
        )
        # RunReport carries the same ledger (rounded at to_json time)
        assert rep["device_flops"] == pytest.approx(
            totals["flops"], rel=1e-6
        )
        assert rep["device_seconds"] == pytest.approx(
            totals["dev_s"], abs=2e-3
        )
        roof = devledger.roofline(records)
        assert roof["classes"].keys() == classes.keys()
        for v in roof["classes"].values():
            assert v["verdict"] in ("compute-bound", "wire-bound")
        comp = devledger.compile_stats(records)
        assert comp["n_compiles"] >= 1 and comp["compile_s"] > 0

    def test_busy_seconds_are_union_not_sum(self):
        """Overlapping dev windows (wide drain pool) must collapse —
        a sum would claim more device time than the wall contains.
        Same contract as ledger.overlap_stats's device union."""
        base = [{"type": "meta", "version": trace.TRACE_VERSION,
                 "kind": "run", "clock": "monotonic-relative"}]
        dev = dict(cap=128, cycles=9, buckets=1, method="matmul",
                   flops=100.0, h2d_wire=10, d2h_wire=10, disp_s=0.01)
        recs = base + [
            {"type": "dev", "t": 0.0, "dur": 1.0, "chunk": 0,
             "lane": "drain-0", **dev},
            {"type": "dev", "t": 0.5, "dur": 1.0, "chunk": 1,
             "lane": "drain-1", **dev},
        ]
        totals = devledger.device_totals(recs)
        assert totals["dev_s"] == pytest.approx(2.0)
        assert totals["busy_s"] == pytest.approx(1.5)
        # the span-side twin: overlap_stats' device occupancy is the
        # same union over device_wait_fetch spans
        spans = base + [
            {"type": "span", "stage": "device_wait_fetch", "t": 0.0,
             "dur": 1.0, "lane": "drain-0"},
            {"type": "span", "stage": "device_wait_fetch", "t": 0.5,
             "dur": 1.0, "lane": "drain-1"},
            {"type": "span", "stage": "ingest", "t": 0.0, "dur": 0.2,
             "lane": "ingest"},
        ]
        ov = ledger.overlap_stats(spans)
        assert ov["device_busy_s"] == pytest.approx(1.5)

    def test_validator_rejects_malformed_dev(self):
        base = [{"type": "meta", "version": trace.TRACE_VERSION,
                 "kind": "run", "clock": "monotonic-relative"}]
        good = {"type": "dev", "t": 0.0, "dur": 0.1, "chunk": 0,
                "lane": "main", "cap": 128, "cycles": 9, "buckets": 1,
                "method": "matmul", "flops": 1.0, "h2d_wire": 1,
                "d2h_wire": 1, "disp_s": 0.01}
        assert not report.validate_trace(base + [dict(good)])
        bad_field = dict(good, gflops=3.0)
        assert any(
            "unregistered dev field" in p
            for p in report.validate_trace(base + [bad_field])
        )
        bad_cap = dict(good, cap=1.5)
        assert any(
            "cap" in p for p in report.validate_trace(base + [bad_cap])
        )
        bad_method = dict(good, method="")
        assert any(
            "method" in p
            for p in report.validate_trace(base + [bad_method])
        )

    def test_devstat_cli_ok_and_tampered_record(self, traced, tmp_path):
        """The corruption contract, FLOP edition: healthy capture
        exits 0 with the dev sum-check green; a capture whose dev
        records disagree with the summary's phase totals exits 1."""
        _, _, paths = traced
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        devstat = os.path.join(REPO, "tools", "devstat.py")
        r = subprocess.run(
            [sys.executable, devstat, paths["trace"]],
            capture_output=True, text=True, env=env, cwd=REPO,
        )
        assert r.returncode == 0, r.stderr + r.stdout
        assert "dev sum-check" in r.stdout and "OK" in r.stdout
        rj = subprocess.run(
            [sys.executable, devstat, paths["trace"], "--json"],
            capture_output=True, text=True, env=env, cwd=REPO,
        )
        assert rj.returncode == 0
        doc = json.loads(rj.stdout)
        assert doc["sum_check"]["ok"]
        assert doc["classes"] and doc["totals"]["mfu"] > 0
        assert doc["roofline"]["critical_intensity"] > 0
        assert doc["peak_entry"]
        # tamper one dev record's interval -> records/summary drift
        tampered = str(tmp_path / "dev_tampered.jsonl")
        with open(paths["trace"]) as f, open(tampered, "w") as g:
            done = False
            for line in f:
                rec = json.loads(line)
                if not done and rec.get("type") == "dev":
                    rec["dur"] = round(rec["dur"] + 1.5, 6)
                    done = True
                g.write(json.dumps(rec) + "\n")
        assert done
        r = subprocess.run(
            [sys.executable, devstat, tampered],
            capture_output=True, text=True, env=env, cwd=REPO,
        )
        assert r.returncode == 1
        assert "DEVICE LEDGER DRIFT" in r.stderr

    def test_devstat_pre_devledger_capture_is_vacuously_ok(self, tmp_path):
        """Captures that predate the dev ledger (the committed CI
        fixture) must pass with every check vacuous, not crash."""
        p = str(tmp_path / "old.jsonl")
        with open(p, "w") as f:
            f.write(json.dumps(
                {"type": "meta", "version": trace.TRACE_VERSION,
                 "kind": "run", "clock": "monotonic-relative"}) + "\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "devstat.py"), p],
            capture_output=True, text=True, env=env, cwd=REPO,
        )
        assert r.returncode == 0, r.stderr + r.stdout
        assert "no dev records" in r.stdout
        rows, ok = devledger.sum_check_dev(report.load_trace(p))
        assert ok and rows == []

    def test_chrome_export_carries_flops_counters(self, traced):
        records, _, _ = traced
        doc = chrome.to_chrome(records)
        counters = [
            e for e in doc["traceEvents"]
            if e["ph"] == "C" and e.get("cat") == "dev"
        ]
        assert counters
        for e in counters:
            assert e["name"].startswith("device_gflops_s (c")
            assert e["args"].get("gflops_s") is not None
        by_name: dict = {}
        for e in counters:
            by_name.setdefault(e["name"], []).append(e["args"]["gflops_s"])
        for vals in by_name.values():
            assert 0 in vals and any(v > 0 for v in vals)

    def test_device_peak_table_resolution(self, monkeypatch):
        monkeypatch.delenv("DUT_PEAK_TFLOPS", raising=False)
        assert device.device_peak_flops("TPU v5p") == (459.0e12, "v5p")
        assert device.device_peak_flops("TPU v5 lite") == (197.0e12, "v5e")
        assert device.device_peak_flops("tpu v4") == (275.0e12, "v4")
        assert device.device_peak_flops("cpu") == (197.0e12, "cpu-sim")
        flops, entry = device.device_peak_flops("quantum-accelerator-9000")
        assert (flops, entry) == (197.0e12, "default-v5e")
        # env override wins over any kind and names its provenance
        monkeypatch.setenv("DUT_PEAK_TFLOPS", "42")
        flops, entry = device.device_peak_flops("TPU v5p")
        assert flops == 42e12 and entry == "env:42T"

    def test_analytic_flops_registry_and_cost_analysis(self):
        """Satellite check: the analytic cost model vs XLA's own
        cost_analysis() on the jitted fused pipeline (CPU backend).

        analytic_flops is a documented LOWER BOUND — it counts the
        MXU-shaped work (adjacency/cluster GEMMs + seed propagation)
        and excludes elementwise/VPU ops, while XLA counts every HLO
        flop and may also simplify GEMMs the model charges for. On the
        canonical small config the ratio measures ~0.85; the window
        [0.2, 1.2] asserts same-order agreement without welding the
        test to XLA's costing of one compiler version."""
        from duplexumiconsensusreads_tpu.bucketing import build_buckets
        from duplexumiconsensusreads_tpu.ops import spec_for_buckets
        from duplexumiconsensusreads_tpu.ops.pipeline import (
            SSC_METHOD_COSTS,
            analytic_flops,
            fused_pipeline,
        )
        from duplexumiconsensusreads_tpu.simulate import (
            SimConfig,
            simulate_batch,
        )

        cfg = SimConfig(n_molecules=80, duplex=True, umi_error=0.03, seed=31)
        batch, _ = simulate_batch(cfg)
        buckets = build_buckets(batch, capacity=128, adjacency=True)
        spec = spec_for_buckets(buckets, GP, CP)
        bk = buckets[0]
        lowered = fused_pipeline.lower(
            bk.pos, bk.umi, bk.strand_ab, bk.frag_end, bk.valid,
            bk.bases, bk.quals, spec=spec,
        )
        ca = lowered.compile().cost_analysis()
        if isinstance(ca, list):  # older jax returns one dict per device
            ca = ca[0]
        xla = float(ca.get("flops", 0.0))
        an = analytic_flops(spec, bk.capacity, bk.bases.shape[1], 1)
        assert xla > 0 and an > 0
        assert 0.2 * xla <= an <= 1.2 * xla, (an, xla)
        # the registry is closed: unknown kernel methods must raise at
        # dispatch time, not silently cost zero
        bad = dataclasses.replace(spec, ssc_method="warp")
        with pytest.raises(ValueError, match="warp"):
            analytic_flops(bad, bk.capacity, bk.bases.shape[1], 1)
        assert set(SSC_METHOD_COSTS) >= {
            "matmul", "blockseg", "segment", "runsum",
            "pallas", "pallas_interpret",
        }


# ------------------------------------------------ chaos + resume events

class TestStructuredEvents:
    @pytest.fixture(autouse=True)
    def _fast(self, monkeypatch):
        monkeypatch.setattr(
            "duplexumiconsensusreads_tpu.runtime.stream.time.sleep",
            lambda s: None,
        )
        yield
        faults.uninstall()

    def _sim(self, tmp_path):
        p = str(tmp_path / "in.bam")
        cfg = SimConfig(n_molecules=60, n_positions=8, umi_error=0.02, seed=5)
        simulated_bam(cfg, path=p, sort=True)
        return p

    def test_chaos_faults_and_retries_are_distinct_events(self, tmp_path):
        """Acceptance: a chaos run's capture shows the injected fault
        AND each retry attempt as separate structured records."""
        in_path = self._sim(tmp_path)
        tp = str(tmp_path / "chaos.jsonl")
        faults.install(
            faults.FaultPlan.parse("shard.write:1:oserror,fetch.result:1:oserror")
        )
        stream_call_consensus(
            in_path, str(tmp_path / "o.bam"), GP, CP, trace_path=tp, **KW
        )
        records = report.load_trace(tp)
        assert report.validate_trace(records) == []
        inj = [r for r in records if r.get("name") == "fault_injected"]
        assert {r["site"] for r in inj} == {"shard.write", "fetch.result"}
        assert all(r["kind"] == "oserror" for r in inj)
        retries = [r for r in records if r.get("name") == "retry"]
        # the host-I/O ladder retried shard.write; the device ladder
        # retried the failed fetch — both visible, with attempt+backoff
        assert any(r["site"] == "shard.write" for r in retries)
        assert any(r["site"] == "device.execute" for r in retries)
        assert all(r["attempt"] >= 1 and r["backoff_s"] >= 0 for r in retries)

    def test_kill_leaves_valid_summaryless_capture(self, tmp_path):
        """The wrapper owns teardown: after an injected kill the capture
        file is closed, parseable, schema-valid — just summary-less."""
        in_path = self._sim(tmp_path)
        tp = str(tmp_path / "kill.jsonl")
        faults.install(faults.FaultPlan.parse("ckpt.save:2:kill"))
        with pytest.raises(faults.InjectedKill):
            stream_call_consensus(
                in_path, str(tmp_path / "o.bam"), GP, CP, trace_path=tp, **KW
            )
        assert trace.get_active() is None  # uninstalled on the kill path
        records = report.load_trace(tp)
        assert report.validate_trace(records) == []
        assert report.summary_record(records) is None
        assert any(r.get("name") == "fault_injected" for r in records)
        # a crashed run's capture is LEGAL: trace_report must exit 0 in
        # both text and --json modes (sum-check skipped, not failed)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        for extra in ([], ["--json"]):
            r = subprocess.run(
                [sys.executable,
                 os.path.join(REPO, "tools", "trace_report.py"), tp, *extra],
                capture_output=True, text=True, env=env, cwd=REPO,
            )
            assert r.returncode == 0, (extra, r.stderr, r.stdout)
        assert json.loads(r.stdout)["sum_check"].get("skipped")

    def test_resume_decisions_recorded(self, tmp_path):
        in_path = self._sim(tmp_path)
        out = str(tmp_path / "r.bam")
        ck = str(tmp_path / "ck.json")
        rep1 = stream_call_consensus(
            in_path, out, GP, CP, checkpoint_path=ck, **KW
        )
        tp = str(tmp_path / "resume.jsonl")
        rep2 = stream_call_consensus(
            in_path, out, GP, CP, checkpoint_path=ck, resume=True,
            trace_path=tp, **KW,
        )
        assert rep2.n_chunks_skipped == rep1.n_chunks
        records = report.load_trace(tp)
        assert report.validate_trace(records) == []
        decisions = {
            r["chunk"]: r["decision"]
            for r in records
            if r.get("name") == "resume"
        }
        assert decisions == {k: "reused" for k in range(rep1.n_chunks)}
        # a fully-resumed capture still passes the sum-check (no drain
        # stages on either side)
        _, ok = report.sum_check(records)
        assert ok

    def test_ledger_survives_kill_resume_without_double_counting(
        self, tmp_path
    ):
        """Chaos pass for the byte ledger: kill mid-run, resume with a
        fresh capture — reused chunks appear in the resumed capture as
        exactly one wire-free shard record each (no h2d/d2h), fresh
        chunks carry the full transfer set, and the shard totals still
        reproduce the finalised output byte-for-byte."""
        in_path = self._sim(tmp_path)
        out = str(tmp_path / "o.bam")
        t1 = str(tmp_path / "kill.jsonl")
        t2 = str(tmp_path / "resume.jsonl")
        faults.install(faults.FaultPlan.parse("ckpt.save:3:kill"))
        with pytest.raises(faults.InjectedKill):
            stream_call_consensus(
                in_path, out, GP, CP, trace_path=t1, **KW
            )
        faults.uninstall()
        stream_call_consensus(
            in_path, out, GP, CP, trace_path=t2, resume=True, **KW
        )
        records = report.load_trace(t2)
        assert report.validate_trace(records) == []
        reused = {
            r["chunk"] for r in records
            if r.get("name") == "resume" and r["decision"] == "reused"
        }
        assert reused, "the kill must land after at least one durable mark"
        per = ledger.per_chunk_bytes(records)
        for chunk, row in per.items():
            if chunk in reused:
                # reused: one resumed shard record, zero wire traffic
                assert set(row) == {"shard"}
                assert row["shard"]["resumed"]
            else:
                assert {"h2d", "d2h", "shard"} <= set(row)
                assert not row["shard"]["resumed"]
        # each chunk's shard bytes counted exactly once: the capture
        # still reproduces the output file exactly
        rows, ok = ledger.sum_check_bytes(records)
        assert ok, rows
        problems, ok2 = ledger.output_check(records)
        assert ok2, problems
        b = ledger.summary_bytes(records)
        assert b["output_bytes"] == os.path.getsize(out)


# ------------------------------------------------------------ CLI + tools

class TestCliAndTools:
    def test_trace_and_heartbeat_require_streaming(self, tmp_path):
        from duplexumiconsensusreads_tpu.cli import main

        p = str(tmp_path / "in.bam")
        simulated_bam(SimConfig(n_molecules=10, seed=1), path=p, sort=True)
        with pytest.raises(SystemExit, match="--trace requires"):
            main(["call", p, "-o", str(tmp_path / "o.bam"),
                  "--trace", str(tmp_path / "t.jsonl")])
        with pytest.raises(SystemExit, match="--heartbeat requires"):
            main(["call", p, "-o", str(tmp_path / "o.bam"),
                  "--heartbeat", "5"])
        with pytest.raises(SystemExit, match="--heartbeat must be > 0"):
            main(["call", p, "-o", str(tmp_path / "o.bam"),
                  "--chunk-reads", "50", "--heartbeat", "-1"])

    def test_cli_trace_report_stdout_and_tools(self, tmp_path, capsys):
        """End-to-end through the CLI: --trace writes a capture the
        check_trace/trace_report tools accept, and --report - writes
        the (stable-key, ms-rounded) RunReport JSON to stdout."""
        from duplexumiconsensusreads_tpu.cli import main

        p = str(tmp_path / "in.bam")
        simulated_bam(
            SimConfig(n_molecules=60, n_positions=8, seed=3), path=p, sort=True
        )
        tp = str(tmp_path / "t.jsonl")
        rc = main([
            "call", p, "-o", str(tmp_path / "o.bam"), "--config", "config3",
            "--capacity", "128", "--chunk-reads", "90",
            "--trace", tp, "--report", "-",
        ])
        assert rc == 0
        out = capsys.readouterr().out
        rep = json.loads(out)
        assert rep["backend"] == "tpu-stream"
        # --report -: ms-rounded values, stable (sorted) key order
        assert list(rep["seconds"]) == sorted(rep["seconds"])
        for v in rep["seconds"].values():
            assert round(v, 3) == v
        assert list(rep) == sorted(rep)

        env = dict(os.environ, JAX_PLATFORMS="cpu")
        chk = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "check_trace.py"),
             tp, "--require-summary"],
            capture_output=True, text=True, env=env, cwd=REPO,
        )
        assert chk.returncode == 0, chk.stderr
        trp = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
             tp, "--chrome", str(tmp_path / "chrome.json")],
            capture_output=True, text=True, env=env, cwd=REPO,
        )
        assert trp.returncode == 0, trp.stderr + trp.stdout
        assert "sum-check vs RunReport.seconds: OK" in trp.stdout
        assert "chunk critical path" in trp.stdout
        with open(str(tmp_path / "chrome.json")) as f:
            assert json.load(f)["traceEvents"]

    def test_check_trace_rejects_garbage(self, tmp_path):
        bad = str(tmp_path / "bad.jsonl")
        with open(bad, "w") as f:
            f.write(json.dumps({"type": "meta", "version": 99}) + "\n")
            f.write(json.dumps({"type": "span", "stage": "bogus",
                                "t": -1, "dur": "x", "lane": ""}) + "\n")
            f.write(json.dumps({"type": "wat"}) + "\n")
        recs = report.load_trace(bad)
        problems = report.validate_trace(recs)
        assert any("version" in p for p in problems)
        assert any("unknown span stage" in p for p in problems)
        assert any("unknown record type" in p for p in problems)
        # non-numeric summary seconds: named problem, and sum_check on
        # such seconds degrades to a row mismatch instead of crashing
        corrupt = [
            {"type": "meta", "version": trace.TRACE_VERSION},
            {"type": "summary", "t": 1.0, "n_events": 0,
             "seconds": {"ingest": None}},
        ]
        assert any("non-numeric" in p for p in report.validate_trace(corrupt))
        rows, ok = report.sum_check(corrupt, seconds={"ingest": None})
        assert rows[0]["report_s"] == 0.0 and ok  # trace total 0 == 0
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        chk = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "check_trace.py"), bad],
            capture_output=True, text=True, env=env, cwd=REPO,
        )
        assert chk.returncode == 1
        assert "unknown span stage" in chk.stderr

    def test_heartbeat_unit(self):
        lines = []
        stats = {"chunks_done": 3, "stall_frac": 0.25}
        hb = trace.Heartbeat(60.0, lambda: stats, sink=lines.append)
        hb.beat()
        hb.stop()  # never started: stop must be safe
        assert lines == ["[duplexumi] heartbeat chunks_done=3 stall_frac=0.25"]


# --------------------------------------------------- report-shape tests

class TestReportShape:
    def test_profile_phases_tolerates_pre_pipelined_reports(self, tmp_path):
        """Satellite: old report JSONs (whole-file shape, or streaming
        reports from before main_loop_stall / drain_utilization /
        n_drain_workers existed) must render, not KeyError."""
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            from profile_phases import report_busy_wall
        finally:
            sys.path.pop(0)
        old_shapes = [
            # pre-streaming whole-file report
            {"seconds": {"read_input": 1.2, "bucketing": 0.3,
                         "device_dispatch": 0.8, "write_output": 0.5}},
            # pre-PR-2 streaming report: no stall/util/total/worker count
            {"seconds": {"ingest": 1.0, "dispatch": 2.0, "finalise": 0.2}},
            # degenerate but parseable
            {"seconds": {}},
            {"seconds": {"total": 5.0, "weird": "text"}},
            # non-numeric values in the NON-stage keys too
            {"seconds": {"ingest": 1.0, "total": "n/a",
                         "drain_utilization": "n/a",
                         "main_loop_stall": None}},
            {"seconds": {"main_loop_stall": "x", "total": 2.0}},
        ]
        for i, shape in enumerate(old_shapes):
            p = str(tmp_path / f"old{i}.json")
            with open(p, "w") as f:
                json.dump(shape, f)
            assert report_busy_wall(p) == 0, shape

    def test_profile_phases_busy_wall_canary_exits_1(self, tmp_path, capsys):
        """Satellite: the busy > wall x pool accounting canary must
        return exit status 1 (the CI contract)."""
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            from profile_phases import report_busy_wall
        finally:
            sys.path.pop(0)
        p = str(tmp_path / "bug.json")
        with open(p, "w") as f:
            json.dump({"seconds": {"ingest": 12.0, "total": 10.0},
                       "n_drain_workers": 2}, f)
        assert report_busy_wall(p) == 1
        err = capsys.readouterr().err
        assert "ACCOUNTING BUG" in err and "ingest" in err
        # non-report JSON: clean failure, not a traceback
        p2 = str(tmp_path / "notrep.json")
        with open(p2, "w") as f:
            json.dump(["not", "a", "report"], f)
        assert report_busy_wall(p2) == 1

    def test_runreport_schema_golden(self):
        """New RunReport fields must be added DELIBERATELY: extend this
        frozen list in the same change that adds the field (report JSON
        is a driver-facing contract)."""
        from duplexumiconsensusreads_tpu.runtime.executor import RunReport

        golden = {
            "n_records", "n_valid_reads", "n_dropped", "n_buckets",
            "n_families", "n_molecules", "n_consensus", "n_devices",
            "n_chunks", "n_chunks_skipped", "n_size_classes",
            "n_pipeline_compiles", "n_retries", "n_drain_workers",
            "n_mixed_mate_families", "n_consensus_pairs",
            "n_precluster_fallback_groups", "n_precluster_fallback_reads",
            "n_jumbo_hardcut_families", "n_jumbo_hardcut_splits",
            "n_downsampled_reads", "n_rescued_cigar", "n_dropped_cigar_ab",
            "n_dropped_cigar_ba", "n_projected_reads",
            "n_projection_fallback_reads", "n_projection_fallback_groups",
            "n_projection_unanchored_reads", "n_umi_corrected",
            "n_dropped_whitelist", "mate_aware", "ingest_overlap", "backend",
            "bytes_h2d", "bytes_d2h", "n_rows_real", "n_rows_padded",
            "n_mesh_pad_buckets", "bucket_ladder",
            # the device ledger's run totals (telemetry/devledger.py)
            "device_flops", "device_seconds", "snapshot_seq", "seconds",
        }
        assert {f.name for f in dataclasses.fields(RunReport)} == golden

    def test_streaming_seconds_keys_golden(self, traced):
        """The streaming executor's stage-key set is part of the same
        contract (trace stages, busy_wall_table pools, and the BENCH
        phases dict all key on it)."""
        _, rep, _ = traced
        assert set(rep["seconds"]) == {
            "ingest", "bucketing", "dispatch", "mesh_h2d",
            "device_wait_fetch",
            "scatter", "deflate", "shard_write", "ckpt", "finalise",
            "main_loop_stall", "prefetch_stall", "ingest_stall",
            "ingest_backpressure", "drain_utilization",
            "live_poll", "live_wait",
            "total",
        }

    def test_to_json_stable_and_ms_rounded(self):
        from duplexumiconsensusreads_tpu.runtime.executor import RunReport

        rep = RunReport(backend="x")
        rep.seconds = {"zeta": 1.23456789, "alpha": 0.0004}
        d = json.loads(rep.to_json())
        assert list(d["seconds"]) == ["alpha", "zeta"]
        assert d["seconds"]["zeta"] == 1.235
        assert list(d) == sorted(d)

    def test_write_report_stdout(self, capsys):
        from duplexumiconsensusreads_tpu.runtime.executor import (
            RunReport,
            write_report,
        )

        write_report(RunReport(backend="t"), "-")
        out = capsys.readouterr().out
        assert json.loads(out)["backend"] == "t"
