"""telemetry/fleet.py + tools/fleet_report.py: the fleet flight
recorder.

Two layers of pinning:

  * synthetic captures (the deterministic 2-daemon scenario the
    committed ``tests/data/fleet.fixture.*.trace.jsonl`` files hold —
    a SIGKILL takeover mid-slice and a K=2 sharded parent) exercise
    the stitcher's segment/gap/sum-check mechanics, the tamper exits,
    the SLO gates, and the prom/Perfetto exports without touching jax;
  * live drives (real 2-daemon in-process fleets running real consensus
    jobs on this host) prove the chaos acceptance: a daemon SIGKILLed
    mid-slice and a K=4 sharded parent both stitch to exactly-once
    timelines with every admission→terminal sum-check green, straight
    off the captures + journal the real protocol produced.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from duplexumiconsensusreads_tpu.io import simulated_bam
from duplexumiconsensusreads_tpu.runtime import faults
from duplexumiconsensusreads_tpu.runtime.stream import stream_call_consensus
from duplexumiconsensusreads_tpu.serve import ConsensusService, client
from duplexumiconsensusreads_tpu.simulate import SimConfig
from duplexumiconsensusreads_tpu.telemetry import chrome, fleet
from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams

pytestmark = pytest.mark.serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FLEET_REPORT = os.path.join(REPO, "tools", "fleet_report.py")

CONFIG = dict(grouping="adjacency", mode="duplex", capacity=128, chunk_reads=90)
GP = GroupingParams(strategy="adjacency", paired=True)
CP = ConsensusParams(mode="duplex")


@pytest.fixture(scope="module")
def sim(tmp_path_factory):
    """(input path, reference bytes) — same tiny workload as
    tests/test_serve.py: ~7 chunks, room for takeovers to land."""
    from duplexumiconsensusreads_tpu.serve.job import serve_provenance

    d = tmp_path_factory.mktemp("fleetsim")
    path = str(d / "in.bam")
    cfg = SimConfig(n_molecules=70, n_positions=9, umi_error=0.02, seed=31)
    simulated_bam(cfg, path=path, sort=True)
    ref = str(d / "ref.bam")
    stream_call_consensus(
        path, ref, GP, CP, capacity=128, chunk_reads=90,
        provenance_cl=serve_provenance(CONFIG),
    )
    with open(ref, "rb") as f:
        return path, f.read()


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.uninstall()


# ------------------------------------------------- synthetic fixtures
#
# The generator below IS the committed tests/data fixture content — a
# pin test regenerates and compares byte-for-byte, so the files the CI
# gate (tools/ci_check.sh) stitches can never drift from what this
# suite proved about them.

def _ev(name, t, job, **attrs):
    return {"type": "event", "name": name, "t": t,
            "lane": f"job-{job}", "job": job, **attrs}


def fixture_records():
    """The canonical synthetic scenario: daemon fleet-a completes
    job-aa, starts job-bb and dies holding its lease (capture ends
    without a summary — the SIGKILL marker); daemon fleet-b takes
    job-bb over and completes it, and runs a K=2 sharded parent
    (split → two child runs → merge) end to end. Returns
    (records_a, records_b)."""
    a = [
        {"type": "meta", "version": 1, "kind": "service",
         "clock": "monotonic-relative", "epoch_m": 1000.0,
         "daemon_id": "fleet-a"},
        _ev("job_accepted", 0.1, "job-aa", priority=1, seq=0,
            queue_depth=1),
        _ev("job_accepted", 0.15, "job-bb", priority=0, seq=1,
            queue_depth=2),
        _ev("job_started", 0.2, "job-aa", slice=1, warm=False,
            resumed=False, token=1),
        _ev("job_completed", 1.2, "job-aa", wall_s=1.0, token=1,
            n_chunks=3, n_consensus=5, warm=False, seconds={}),
        _ev("job_started", 1.3, "job-bb", slice=1, warm=True,
            resumed=False, token=1),
        # no end event and no summary: fleet-a died here
    ]
    b = [
        {"type": "meta", "version": 1, "kind": "service",
         "clock": "monotonic-relative", "epoch_m": 1000.5,
         "daemon_id": "fleet-b"},
        _ev("job_accepted", 0.1, "job-pp", priority=1, seq=2,
            queue_depth=1),
        _ev("job_started", 0.3, "job-pp", slice=1, stage="split",
            token=1),
        _ev("job_split", 0.5, "job-pp", token=1, n_shards=2, n_chunks=6,
            n_records=100, wall_s=0.2),
        _ev("job_started", 0.7, "job-pp.s000", slice=1, warm=False,
            resumed=False, token=1, parent="job-pp", shard_idx=0),
        _ev("job_completed", 1.0, "job-pp.s000", wall_s=0.3, token=1,
            n_chunks=3, n_consensus=2, warm=False, seconds={}),
        _ev("job_started", 1.1, "job-pp.s001", slice=1, warm=True,
            resumed=False, token=1, parent="job-pp", shard_idx=1),
        _ev("job_completed", 1.4, "job-pp.s001", wall_s=0.3, token=1,
            n_chunks=3, n_consensus=2, warm=True, seconds={}),
        _ev("lease_takeover", 1.6, "job-bb", reason="dead-owner",
            prev_owner="fleet-a", by="fleet-b"),
        _ev("job_started", 1.7, "job-bb", slice=2, warm=True,
            resumed=True, token=2),
        _ev("job_completed", 2.7, "job-bb", wall_s=1.0, token=2,
            n_chunks=3, n_consensus=5, warm=True, seconds={}),
        _ev("job_started", 2.8, "job-pp", slice=2, stage="merge",
            token=2),
        _ev("job_merged", 3.2, "job-pp", token=2, n_shards=2,
            merge_s=0.4, output_bytes=1234),
        _ev("job_completed", 3.25, "job-pp", wall_s=0.45, token=2,
            n_chunks=6, n_consensus=4, warm=False, seconds={}),
        {"type": "event", "name": "heartbeat", "t": 3.3, "lane": "main",
         "queue_depth": 0, "jobs_inflight": 0},
    ]
    b.append({"type": "summary", "t": 3.4, "n_events": len(b) - 1,
              "n_dropped": 0, "counters": {"jobs_done": 4}})
    return a, b


def _write_jsonl(path, records):
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r, separators=(",", ":")) + "\n")


def _fixture_paths(tmp_path):
    a, b = fixture_records()
    pa = str(tmp_path / "service.fleet-a.trace.jsonl")
    pb = str(tmp_path / "service.fleet-b.trace.jsonl")
    _write_jsonl(pa, a)
    _write_jsonl(pb, b)
    return pa, pb


def test_committed_fixtures_pin_the_generator():
    """The CI gate stitches tests/data/fleet.fixture.*.trace.jsonl;
    those files must be exactly what :func:`fixture_records` produces
    (and what this suite proves green/tamper-red below)."""
    for name, recs in zip(("a", "b"), fixture_records()):
        path = os.path.join(REPO, "tests", "data",
                            f"fleet.fixture.{name}.trace.jsonl")
        want = "".join(
            json.dumps(r, separators=(",", ":")) + "\n" for r in recs
        )
        with open(path) as f:
            assert f.read() == want, f"{path} drifted from the generator"


# ---------------------------------------------------- stitcher (unit)

class TestStitch:
    def stitched(self, tmp_path):
        pa, pb = _fixture_paths(tmp_path)
        caps = fleet.load_captures([pa, pb])
        assert caps["problems"] == []
        return fleet.stitch(caps)

    def test_takeover_timeline_exact_sum_check(self, tmp_path):
        st = self.stitched(tmp_path)
        assert st["ok"], st["problems"]
        bb = st["jobs"]["job-bb"]
        assert bb["state"] == "done" and bb["sum_check_ok"]
        kinds = [(s["kind"], s["daemon"], s["end"]) for s in bb["segments"]]
        assert kinds == [("run", "fleet-a", "takeover"),
                         ("run", "fleet-b", "completed")]
        gaps = [g["kind"] for g in bb["gaps"]]
        assert gaps == ["queue_wait", "takeover"]
        # exactness: microsecond-integer tiling of admission→terminal
        total = sum(s["t1_us"] - s["t0_us"] for s in bb["segments"])
        total += sum(g["t1_us"] - g["t0_us"] for g in bb["gaps"])
        assert total == bb["wall_us"] == bb["terminal_us"] - bb["admission_us"]

    def test_sharded_parent_split_fanned_merge(self, tmp_path):
        st = self.stitched(tmp_path)
        pp = st["jobs"]["job-pp"]
        assert pp["sum_check_ok"]
        assert [s["kind"] for s in pp["segments"]] == ["split", "merge"]
        assert [g["kind"] for g in pp["gaps"]] == ["queue_wait", "fanned"]
        # children stitched exactly once, attributed to their daemon
        for cid in ("job-pp.s000", "job-pp.s001"):
            c = st["jobs"][cid]
            assert c["state"] == "done"
            assert len(c["segments"]) == 1
            assert c["segments"][0]["daemon"] == "fleet-b"

    def test_unclean_capture_is_lenient_one_sided(self, tmp_path):
        # fleet-a died: its open job-bb slice is closed at the reclaim
        # with a warning, never a problem — the one-sided policy
        st = self.stitched(tmp_path)
        assert st["ok"] and st["problems"] == []

    def test_dropped_start_in_clean_capture_is_drift(self, tmp_path):
        a, b = fixture_records()
        b2 = [r for r in b
              if not (r.get("name") == "job_started"
                      and r.get("job") == "job-pp.s001")]
        b2[-1]["n_events"] -= 1  # a "smart" tamper fixes the count too
        pa = str(tmp_path / "sa.trace.jsonl")
        pb = str(tmp_path / "sb.trace.jsonl")
        _write_jsonl(pa, a)
        _write_jsonl(pb, b2)
        st = fleet.stitch(fleet.load_captures([pa, pb]))
        assert not st["ok"]
        assert any("no matching job_started" in p for p in st["problems"])

    def test_dropped_end_in_clean_capture_is_drift(self, tmp_path):
        a, b = fixture_records()
        b2 = [r for r in b
              if not (r.get("name") == "job_completed"
                      and r.get("job") == "job-pp.s000")]
        b2[-1]["n_events"] -= 1
        pa = str(tmp_path / "sa.trace.jsonl")
        pb = str(tmp_path / "sb.trace.jsonl")
        _write_jsonl(pa, a)
        _write_jsonl(pb, b2)
        st = fleet.stitch(fleet.load_captures([pa, pb]))
        assert not st["ok"]
        assert any("never closed in a clean capture" in p
                   for p in st["problems"])

    def test_duplicate_terminal_is_drift(self, tmp_path):
        a, b = fixture_records()
        dup = _ev("job_completed", 1.25, "job-aa", wall_s=1.0, token=1)
        a2 = a + [dup]
        pa = str(tmp_path / "sa.trace.jsonl")
        pb = str(tmp_path / "sb.trace.jsonl")
        _write_jsonl(pa, a2)
        _write_jsonl(pb, b)
        st = fleet.stitch(fleet.load_captures([pa, pb]))
        assert any("duplicate terminal" in p for p in st["problems"])

    def test_multi_capture_stitch_requires_epoch(self, tmp_path):
        a, b = fixture_records()
        del a[0]["epoch_m"]
        pa = str(tmp_path / "sa.trace.jsonl")
        pb = str(tmp_path / "sb.trace.jsonl")
        _write_jsonl(pa, a)
        _write_jsonl(pb, b)
        caps = fleet.load_captures([pa, pb])
        assert any("epoch_m" in p for p in caps["problems"])

    def test_restarted_daemon_prev_capture_is_history_not_duplicate(
        self, tmp_path
    ):
        # a restart rotates service.<id>.trace.jsonl to .prev: same
        # daemon_id, DIFFERENT recorder epoch. That is legitimate fleet
        # history the spool discovery deliberately feeds the stitcher —
        # it must stitch green, never exit 1 as a "duplicate capture"
        a, b = fixture_records()
        a2 = [
            {"type": "meta", "version": 1, "kind": "service",
             "clock": "monotonic-relative", "epoch_m": 1005.0,
             "daemon_id": "fleet-a"},
            _ev("job_accepted", 0.1, "job-cc", priority=1, seq=3,
                queue_depth=1),
            _ev("job_started", 0.2, "job-cc", slice=1, warm=False,
                resumed=False, token=1),
            _ev("job_completed", 0.9, "job-cc", wall_s=0.7, token=1,
                n_chunks=3, n_consensus=5, warm=False, seconds={}),
        ]
        a2.append({"type": "summary", "t": 1.0, "n_events": len(a2) - 1,
                   "n_dropped": 0})
        live = str(tmp_path / "service.fleet-a.trace.jsonl")
        prev = str(tmp_path / "service.fleet-a.trace.jsonl.prev")
        pb = str(tmp_path / "service.fleet-b.trace.jsonl")
        _write_jsonl(prev, a)   # first life: died holding job-bb
        _write_jsonl(live, a2)  # second life: clean
        _write_jsonl(pb, b)
        caps = fleet.load_captures(
            fleet.discover_service_captures(str(tmp_path))
        )
        assert caps["problems"] == []
        st = fleet.stitch(caps)
        assert st["ok"], st["problems"]
        assert st["jobs"]["job-cc"]["state"] == "done"
        assert st["jobs"]["job-bb"]["sum_check_ok"] is True
        # one balance row for fleet-a; its unclean first life marks it
        assert st["daemons"]["fleet-a"]["clean"] is False

    def test_same_recorder_life_passed_twice_is_duplicate(self, tmp_path):
        a, b = fixture_records()
        pa = str(tmp_path / "sa.trace.jsonl")
        pa2 = str(tmp_path / "sa-copy.trace.jsonl")
        pb = str(tmp_path / "sb.trace.jsonl")
        _write_jsonl(pa, a)
        _write_jsonl(pa2, a)  # identical copy: same daemon_id AND epoch
        _write_jsonl(pb, b)
        caps = fleet.load_captures([pa, pa2, pb])
        assert any("duplicate capture" in p for p in caps["problems"])

    def test_seg_and_gap_constructors_refuse_unknown_kinds(self):
        with pytest.raises(ValueError, match="segment kind"):
            fleet.seg_rec("warp", 0, 1, "d")
        with pytest.raises(ValueError, match="gap kind"):
            fleet.gap_rec("warp", 0, 1)

    def test_journal_slice_count_cross_check(self, tmp_path):
        # clean captures + a journal claiming more slices than captured
        # job_started events = a missing/tampered capture
        a, b = fixture_records()
        a.append({"type": "summary", "t": 1.4,
                  "n_events": len(a) - 1, "n_dropped": 0})
        # drop job-bb from a so its story is clean-but-partial
        a = [r for r in a if r.get("job") != "job-bb"]
        a[-1]["n_events"] -= 2
        pa = str(tmp_path / "sa.trace.jsonl")
        pb = str(tmp_path / "sb.trace.jsonl")
        _write_jsonl(pa, a)
        _write_jsonl(pb, b)
        journal = {"job-aa": {"state": "done", "slices": 3, "priority": 1}}
        st = fleet.stitch(fleet.load_captures([pa, pb]), journal=journal)
        assert any("journal says 3 slices" in p for p in st["problems"])


# ------------------------------------------------- metrics / SLO / prom

class TestRunDevice:
    def test_fleet_device_ledger_sums_exactly_across_runs(self):
        """Distinct captures never share a device interval, so fleet
        FLOPs/busy SUM across runs; per-class and fleet MFU must both
        survive CPU-sim magnitudes (~1e-7) without flushing to 0."""
        dev = dict(cap=128, cycles=9, buckets=1, method="matmul",
                   h2d_wire=10, d2h_wire=10, disp_s=0.01)
        recs = [
            {"type": "meta", "version": 1, "kind": "run",
             "clock": "monotonic-relative"},
            {"type": "dev", "t": 0.0, "dur": 1.0, "chunk": 0,
             "lane": "drain-0", "flops": 197e6, **dev},
        ]
        caps = [
            {"path": "a.trace.jsonl", "records": recs},
            {"path": "b.trace.jsonl", "records": recs},
        ]
        d = fleet.run_device(caps)
        assert d["n_runs"] == 2
        assert d["flops"] == pytest.approx(2 * 197e6)
        assert d["busy_s"] == pytest.approx(2.0)
        assert d["mfu"] > 0
        assert d["classes"]["c128xL9/matmul"]["mfu"] > 0
        assert d["peak_entry"]
        # pre-devledger captures contribute nothing -> {}
        empty = [{"path": "c", "records": recs[:1]}]
        assert fleet.run_device(empty) == {}


class TestFleetMetrics:
    def metrics(self, tmp_path):
        pa, pb = _fixture_paths(tmp_path)
        st = fleet.stitch(fleet.load_captures([pa, pb]))
        return fleet.fleet_metrics(st)

    def test_metric_surface_is_exactly_the_registry(self, tmp_path):
        m = self.metrics(tmp_path)
        extra = {"classes", "daemons", "sum_check_ok", "n_problems"}
        assert set(m) == set(fleet.FLEET_METRIC_KEYS) | extra

    def test_totals_and_percentiles(self, tmp_path):
        m = self.metrics(tmp_path)
        assert m["fleet_jobs"] == 5 and m["fleet_done"] == 5
        assert m["fleet_takeovers"] == 1
        assert m["takeover_gap_max_s"] == pytest.approx(0.1)
        assert m["e2e_p95_s"] > m["e2e_p50_s"] > 0
        # class tables: job-bb is priority 0, the rest priority 1
        assert set(m["classes"]) == {"0", "1"}
        # daemon balance: both daemons ran slices; fleet-a is unclean
        assert m["daemons"]["fleet-a"]["clean"] is False
        assert m["daemons"]["fleet-b"]["n_slices"] == 5

    def test_slo_gates_fail_and_pass(self, tmp_path):
        m = self.metrics(tmp_path)
        rows, ok = fleet.check_slo(m, {"e2e_p95_s": {"max": 0.01}})
        assert not ok and rows[0]["verdict"] == "fail"
        rows, ok = fleet.check_slo(m, {
            "e2e_p95_s": {"max": 60.0},
            "queue_wait_p95_s": {"max": 60.0, "class": "1"},
        })
        assert ok and all(r["verdict"] == "pass" for r in rows)

    def test_slo_unknown_metric_fails_no_data_skips(self, tmp_path):
        m = self.metrics(tmp_path)
        rows, ok = fleet.check_slo(m, {"not_a_metric": {"max": 1.0}})
        assert not ok and rows[0]["verdict"] == "error"
        rows, ok = fleet.check_slo(m, {"deadline_hit_rate": {"min": 0.9}})
        assert ok and rows[0]["verdict"] == "skipped"

    def test_prom_exposition(self, tmp_path):
        text = fleet.render_prom(self.metrics(tmp_path))
        assert "dut_fleet_fleet_done 5" in text
        assert 'dut_fleet_daemon_n_slices{daemon="fleet-b"} 5' in text
        assert 'class="0"' in text
        # absent metrics are omitted, never zeroed
        assert "ttfc_p95_s" not in text

    def test_ttfc_merged_from_raw_samples(self, tmp_path):
        pa, pb = _fixture_paths(tmp_path)
        st = fleet.stitch(fleet.load_captures([pa, pb]))
        docs = [
            {"daemon_id": "fleet-a",
             "class_latency_samples": {"1": {"ttfc": [0.5, 0.7]}}},
            {"daemon_id": "fleet-b",
             "class_latency_samples": {"1": {"ttfc": [0.9]}}},
        ]
        m = fleet.fleet_metrics(st, metrics_docs=docs)
        assert m["ttfc_p50_s"] == pytest.approx(0.7)
        assert m["classes"]["1"]["n_ttfc"] == 3

    def test_chrome_fleet_lanes(self, tmp_path):
        pa, pb = _fixture_paths(tmp_path)
        st = fleet.stitch(fleet.load_captures([pa, pb]))
        doc = chrome.fleet_to_chrome(st)
        names = {e["args"]["name"] for e in doc["traceEvents"]
                 if e.get("ph") == "M" and e["name"] == "thread_name"}
        assert "daemon fleet-a" in names and "daemon fleet-b" in names
        assert "job job-bb" in names
        # the takeover reads as the same job name on two daemon lanes
        lanes_of_bb = set()
        tid_to_name = {
            e["tid"]: e["args"]["name"] for e in doc["traceEvents"]
            if e.get("ph") == "M" and e["name"] == "thread_name"
        }
        for e in doc["traceEvents"]:
            if e.get("ph") == "X" and e["name"] == "job-bb":
                lanes_of_bb.add(tid_to_name[e["tid"]])
        assert lanes_of_bb == {"daemon fleet-a", "daemon fleet-b"}
        # gaps render on the job's own lane
        assert any(
            e.get("ph") == "X" and e["name"] == "gap:takeover"
            for e in doc["traceEvents"]
        )


# ----------------------------------------------------------- CLI shell

class TestFleetReportCli:
    def test_exit_0_and_json_over_fixture_captures(self, tmp_path):
        pa, pb = _fixture_paths(tmp_path)
        p = subprocess.run(
            [sys.executable, FLEET_REPORT, pa, pb, "--json"],
            capture_output=True, text=True, timeout=120,
        )
        assert p.returncode == 0, p.stderr
        doc = json.loads(p.stdout)
        assert doc["ok"] and doc["metrics"]["fleet_done"] == 5
        assert doc["jobs"]["job-bb"]["sum_check_ok"] is True

    def test_tampered_capture_exits_1(self, tmp_path):
        a, b = fixture_records()
        b = [r for r in b
             if not (r.get("name") == "job_started"
                     and r.get("job") == "job-pp.s001")]
        b[-1]["n_events"] -= 1
        pa = str(tmp_path / "sa.trace.jsonl")
        pb = str(tmp_path / "sb.trace.jsonl")
        _write_jsonl(pa, a)
        _write_jsonl(pb, b)
        p = subprocess.run(
            [sys.executable, FLEET_REPORT, pa, pb],
            capture_output=True, text=True, timeout=120,
        )
        assert p.returncode == 1
        assert "FLEET TIMELINE DRIFT" in p.stderr

    def test_check_slo_exits_both_directions(self, tmp_path):
        pa, pb = _fixture_paths(tmp_path)
        tight = tmp_path / "tight.toml"
        tight.write_text('[e2e_p95_s]\nmax = 0.01\n')
        loose = tmp_path / "loose.toml"
        loose.write_text('[e2e_p95_s]\nmax = 60.0\n')
        for slo, rc in ((tight, 1), (loose, 0)):
            p = subprocess.run(
                [sys.executable, FLEET_REPORT, pa, pb,
                 "--slo", str(slo), "--check-slo"],
                capture_output=True, text=True, timeout=120,
            )
            assert p.returncode == rc, (slo, p.stdout, p.stderr)


# -------------------------------------------------------- live drives

def _drain_fleet(spool, traces, n_daemons=2, **kw):
    """Run ``n_daemons`` concurrent services until the spool is idle;
    returns the services."""
    svcs = [
        ConsensusService(
            spool, chunk_budget=2, poll_s=0.02, trace_path=traces[i],
            daemon_id=f"live-{i}", **kw,
        )
        for i in range(n_daemons)
    ]
    threads = [
        threading.Thread(target=s.run_until_idle, daemon=True)
        for s in svcs
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    assert not any(t.is_alive() for t in threads)
    return svcs


def _stitch_spool(spool):
    caps = fleet.load_captures(fleet.discover_service_captures(spool))
    journal = fleet.load_journal(os.path.join(spool, "queue.json"))
    return fleet.stitch(caps, journal=journal)


class TestFleetLive:
    """The acceptance drives: real jobs, real protocol, stitched."""

    def test_sigkill_takeover_stitches_exactly_once(self, sim, tmp_path):
        """Daemon A dies mid-slice (InjectedKill — the modelled
        SIGKILL, lease still journaled, capture left summary-less the
        way a real kill leaves it); daemon B takes the job over and
        finishes everything. The stitched timelines must show the
        victim's slice closed at the reclaim, an attributed takeover
        gap, exactly one terminal per job, and every sum-check green —
        and fleet_report over the spool must exit 0."""
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        jobs = []
        for i in range(3):
            out = str(tmp_path / f"out{i}.bam")
            jobs.append((client.submit(spool, in_path, out,
                                       config=dict(CONFIG)), out))
        victim = ConsensusService(
            spool, chunk_budget=0, poll_s=0.02, lease_s=5.0,
            daemon_id="live-victim",
            trace_path=os.path.join(spool, "service.live-victim.trace.jsonl"),
        )
        orig = victim.worker.run_slice

        def dying_run_slice(spec, budget, should_yield, drain_event,
                            lease=None):
            def die():
                raise faults.InjectedKill("fleet test: victim killed")

            return orig(spec, 1, die, drain_event, lease=lease)

        victim.worker.run_slice = dying_run_slice

        def run_victim():
            # run() re-raises the InjectedKill; the daemon is dead
            # either way — exactly what the stitcher must cope with
            try:
                victim.run_until_idle()
            except faults.InjectedKill:
                pass

        vt = threading.Thread(target=run_victim, daemon=True)
        vt.start()
        vt.join(timeout=600)
        assert not vt.is_alive()
        survivor = ConsensusService(
            spool, chunk_budget=0, poll_s=0.02, lease_s=5.0,
            daemon_id="live-B",
            trace_path=os.path.join(spool, "service.live-B.trace.jsonl"),
        )
        survivor.run_until_idle()
        for jid, out in jobs:
            assert client.status(spool, jid)["state"] == "done"
            with open(out, "rb") as f:
                assert f.read() == ref_bytes

        st = _stitch_spool(spool)
        assert st["ok"], st["problems"]
        timelines = st["jobs"]
        assert len(timelines) == 3
        n_takeover_segs = 0
        for jid, _ in jobs:
            tl = timelines[jid]
            assert tl["state"] == "done"
            assert tl["sum_check_ok"] is True
            ends = [s["end"] for s in tl["segments"]]
            assert ends.count("completed") == 1  # exactly-once terminal
            n_takeover_segs += ends.count("takeover")
        assert n_takeover_segs == 1  # the victim held exactly one lease
        # the takeover gap is attributed and the metrics see it
        m = fleet.fleet_metrics(
            st, metrics_docs=fleet.load_metrics_docs(spool)
        )
        assert m["fleet_takeovers"] == 1
        assert m["takeover_gap_max_s"] is not None
        assert m["fleet_done"] == 3 and m["e2e_p95_s"] > 0
        # the CLI agrees, writes the durable artifact, exits 0
        p = subprocess.run(
            [sys.executable, FLEET_REPORT, spool],
            capture_output=True, text=True, timeout=300,
        )
        assert p.returncode == 0, p.stdout + p.stderr
        assert os.path.exists(os.path.join(spool, "fleet_metrics.json"))

    def test_sharded_parent_k4_stitches_exactly_once(self, sim, tmp_path):
        """A K=4 sharded parent through a 2-daemon fleet: the stitched
        parent timeline decomposes into split → fanned → merge, every
        child runs exactly once somewhere, and all sum-checks are
        green against the real journal."""
        in_path, ref_bytes = sim
        spool = str(tmp_path / "spool")
        out = str(tmp_path / "sharded.bam")
        parent = client.submit(spool, in_path, out, config=dict(CONFIG),
                               shards=4)
        traces = [
            os.path.join(spool, f"service.live-{i}.trace.jsonl")
            for i in (0, 1)
        ]
        _drain_fleet(spool, traces)
        assert client.status(spool, parent)["state"] == "done"
        with open(out, "rb") as f:
            assert f.read() == ref_bytes

        st = _stitch_spool(spool)
        assert st["ok"], st["problems"]
        tl = st["jobs"][parent]
        assert tl["state"] == "done" and tl["sum_check_ok"] is True
        kinds = [s["kind"] for s in tl["segments"]]
        assert kinds[0] == "split" and kinds[-1] == "merge"
        assert "fanned" in [g["kind"] for g in tl["gaps"]]
        children = [j for j in st["jobs"] if j.startswith(parent + ".s")]
        assert len(children) == 4
        for cid in children:
            c = st["jobs"][cid]
            assert c["state"] == "done"
            assert c["sum_check_ok"] is True  # journal admitted_m anchors
            assert [s["end"] for s in c["segments"]].count("completed") == 1
        m = fleet.fleet_metrics(st)
        assert m["fleet_splits"] == 1 and m["fleet_merges"] == 1


# ------------------------------------------------- satellite contracts

class TestStatusJson:
    """`call --status/--wait --json`: the machine-readable status
    document (satellite: external monitors stop scraping stderr)."""

    def test_status_json_document(self, sim, tmp_path):
        in_path, _ = sim
        spool = str(tmp_path / "spool")
        out = str(tmp_path / "out.bam")
        jid = client.submit(spool, in_path, out, config=dict(CONFIG))
        ConsensusService(spool, chunk_budget=0).run_until_idle()
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        p = subprocess.run(
            [sys.executable, "-m", "duplexumiconsensusreads_tpu", "call",
             "--status", jid, "--spool", spool, "--json"],
            capture_output=True, text=True, timeout=300, cwd=REPO, env=env,
        )
        assert p.returncode == 0, p.stderr
        assert p.stderr == ""  # machine mode: stdout only
        doc = json.loads(p.stdout)
        assert doc["state"] == "done" and doc["job_id"] == jid
        assert "timestamps" in doc and "reason" in doc
        assert doc["timestamps"]["admitted_age_s"] >= 0

    def test_wait_json_on_unknown_job_exits_1(self, tmp_path):
        spool = str(tmp_path / "spool")
        os.makedirs(os.path.join(spool, "inbox"), exist_ok=True)
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        p = subprocess.run(
            [sys.executable, "-m", "duplexumiconsensusreads_tpu", "call",
             "--wait", "job-nope", "--spool", spool, "--json"],
            capture_output=True, text=True, timeout=300, cwd=REPO, env=env,
        )
        assert p.returncode == 1
        doc = json.loads(p.stdout)
        assert doc["state"] == "unknown" and p.stderr == ""

    def test_json_refused_off_the_client_verbs(self, tmp_path):
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        p = subprocess.run(
            [sys.executable, "-m", "duplexumiconsensusreads_tpu", "call",
             "in.bam", "-o", "out.bam", "--json"],
            capture_output=True, text=True, timeout=300, cwd=REPO, env=env,
        )
        assert p.returncode != 0
        assert "--json applies to --status/--wait" in p.stderr

    def test_shard_rollup_rides_the_document(self):
        doc = client.status_document({
            "job_id": "job-p", "state": "fanned",
            "shards": {"n_shards": 4, "done": 2, "running": 1,
                       "queued": 1, "failed": 0},
            "admitted_m": time.monotonic() - 5.0,
            "deadline_m": time.monotonic() + 30.0,
        })
        assert doc["shards"]["done"] == 2
        assert doc["timestamps"]["admitted_age_s"] == pytest.approx(5.0, abs=1.0)
        assert doc["timestamps"]["deadline_in_s"] == pytest.approx(30.0, abs=1.0)


class TestHeartbeatIdentity:
    """Satellite: the live heartbeat line + metrics.json carry the
    daemon's short id and the tuner verdict hit rate."""

    def test_stats_carry_daemon_and_verdict_hit_rate(self, tmp_path):
        svc = ConsensusService(str(tmp_path / "spool"),
                               daemon_id="beat-me-12345678")
        snap = svc.stats()
        assert snap["daemon"] == "beat-me-1234"  # short form
        assert snap["verdict_hit_rate"] == 0.0
        svc.worker.n_verdict_hits = 3
        svc.worker.n_verdict_puts = 1
        assert svc.stats()["verdict_hit_rate"] == 0.75

    def test_per_daemon_metrics_file_with_samples(self, sim, tmp_path):
        in_path, _ = sim
        spool = str(tmp_path / "spool")
        out = str(tmp_path / "out.bam")
        client.submit(spool, in_path, out, config=dict(CONFIG))
        svc = ConsensusService(spool, chunk_budget=0, daemon_id="metrics-d")
        svc.run_until_idle()
        mine = os.path.join(spool, "metrics", "metrics-d.json")
        with open(mine) as f:
            doc = json.load(f)
        assert doc["daemon_id"] == "metrics-d"
        assert doc["daemon"] == "metrics-d"[:12]
        assert "verdict_hit_rate" in doc
        samples = doc["class_latency_samples"]
        assert samples["1"]["queue_wait"] and samples["1"]["ttfc"]
        # the merged fleet view reads these docs
        docs = fleet.load_metrics_docs(spool)
        assert any(d.get("daemon_id") == "metrics-d" for d in docs)
