"""Tests for the performance-path kernels: presorted grouping, axis
auto-sizing, and the Pallas banded segment-GEMM (interpret mode on CPU).

All of these are exact-optimization paths — outputs must be identical
to the reference paths, not merely close.
"""

import numpy as np
import pytest

from duplexumiconsensusreads_tpu.bucketing import build_buckets
from duplexumiconsensusreads_tpu.kernels.grouping import group_kernel
from duplexumiconsensusreads_tpu.ops import PipelineSpec, run_bucket, spec_for_buckets
from duplexumiconsensusreads_tpu.ops.grouper import dense_pos_ids
from duplexumiconsensusreads_tpu.simulate import SimConfig, simulate_batch
from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams


def _bucket_inputs(cfg):
    batch, _ = simulate_batch(cfg)
    buckets = build_buckets(batch, capacity=512, adjacency=True)
    return buckets


@pytest.mark.parametrize("strategy", ["exact", "adjacency"])
@pytest.mark.parametrize("paired", [True, False])
def test_presorted_matches_sorting_path(strategy, paired):
    cfg = SimConfig(n_molecules=80, duplex=True, umi_error=0.03, seed=31)
    for bk in _bucket_inputs(cfg):
        outs = []
        for presorted in (False, True):
            outs.append(
                group_kernel(
                    bk.pos,
                    bk.umi,
                    bk.strand_ab,
                    bk.frag_end,
                    bk.valid,
                    strategy=strategy,
                    paired=paired,
                    u_max=256,
                    presorted=presorted,
                )
            )
        for a, b in zip(*outs):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_long_umi_32_codes():
    """Duplex UMI pairs can exceed the 31-code int64 pack limit (e.g.
    2x16 bases) — grouping, bucketing, pipeline and scatter-back must
    all handle multi-word UMI keys (regression: host paths once crashed
    or would have mis-sorted)."""
    from duplexumiconsensusreads_tpu.ops import UmiGrouper
    from duplexumiconsensusreads_tpu.runtime.executor import call_batch_tpu

    cfg = SimConfig(n_molecules=40, umi_len=16, duplex=True, umi_error=0.01, seed=3)
    batch, _ = simulate_batch(cfg)
    assert batch.umi_len == 32
    gp = GroupingParams(strategy="adjacency", paired=True)
    f_cpu = UmiGrouper(gp, backend="cpu")(batch)
    f_tpu = UmiGrouper(gp, backend="tpu")(batch)
    np.testing.assert_array_equal(
        np.asarray(f_cpu.family_id), np.asarray(f_tpu.family_id)
    )
    np.testing.assert_array_equal(
        np.asarray(f_cpu.molecule_id), np.asarray(f_tpu.molecule_id)
    )
    cp = ConsensusParams(mode="duplex")
    cb, cq, cd, cv, fp, fu, _mate, _pair, _end = call_batch_tpu(batch, gp, cp, capacity=256)
    assert cv.sum() > 0
    assert fu.shape[1] == 32


def test_spec_for_buckets_bounds():
    cfg = SimConfig(n_molecules=200, duplex=True, umi_error=0.02, seed=8)
    buckets = _bucket_inputs(cfg)
    gp = GroupingParams(strategy="adjacency", paired=True)
    cp = ConsensusParams(mode="duplex")
    spec = spec_for_buckets(buckets, gp, cp)
    max_u = max(b.n_unique_umi for b in buckets)
    assert spec.u_max >= max_u
    assert spec.f_max >= min(2 * max_u, buckets[0].capacity)
    assert spec.m_max >= min(max_u, buckets[0].capacity)
    # auto-sized spec must produce zero overflow and same results as
    # the worst-case spec
    for bk in buckets:
        out_auto = run_bucket(bk, spec)
        out_full = run_bucket(bk, PipelineSpec(gp, cp))
        assert int(out_auto["n_overflow"]) == 0
        np.testing.assert_array_equal(
            np.asarray(out_auto["family_id"]), np.asarray(out_full["family_id"])
        )
        na = int(out_auto["n_molecules"])
        np.testing.assert_array_equal(
            np.asarray(out_auto["cons_base"])[:na],
            np.asarray(out_full["cons_base"])[:na],
        )
        np.testing.assert_array_equal(
            np.asarray(out_auto["cons_qual"])[:na],
            np.asarray(out_full["cons_qual"])[:na],
        )
        assert not np.asarray(out_full["cons_valid"])[na:].any()


class TestSortedSegmentMethods:
    """blockseg / runsum: the family-sorted reduction paths. blockseg is
    sum-order-exact per family (block partials accumulate in block
    order); runsum differs only by prefix-cancellation, bounded to ±1
    qual at f32 rounding boundaries."""

    @pytest.mark.parametrize("method", ["blockseg", "runsum"])
    @pytest.mark.parametrize("strategy", ["exact", "adjacency"])
    def test_pipeline_parity(self, method, strategy):
        # full fused pipeline: presorted buckets, paired + mate-aware
        # bits make family ids NON-monotone in read order — the internal
        # re-sort must recover contiguity; adjacency additionally
        # reorders molecules by cluster seed
        cfg = SimConfig(
            n_molecules=150, duplex=True, umi_error=0.03, paired_reads=True,
            seed=11,
        )
        batch, _ = simulate_batch(cfg)
        gp = GroupingParams(strategy=strategy, paired=True, mate_aware=True)
        cp = ConsensusParams(mode="duplex", error_model="cycle")
        buckets = build_buckets(batch, capacity=512, grouping=gp)
        ref_spec = spec_for_buckets(buckets, gp, cp, ssc_method="matmul")
        new_spec = spec_for_buckets(buckets, gp, cp, ssc_method=method)
        for bk in buckets:
            a = run_bucket(bk, ref_spec)
            b = run_bucket(bk, new_spec)
            np.testing.assert_array_equal(
                np.asarray(a["family_id"]), np.asarray(b["family_id"])
            )
            ba_, bb_ = np.asarray(a["cons_base"]), np.asarray(b["cons_base"])
            if method == "blockseg":
                np.testing.assert_array_equal(ba_, bb_)
            else:
                # a +-1 qual shift can flip the duplex agree/disagree
                # tie-break (base <-> N); bound the rate
                assert (ba_ != bb_).mean() < 1e-3
            np.testing.assert_array_equal(
                np.asarray(a["cons_depth"]), np.asarray(b["cons_depth"])
            )
            qa = np.asarray(a["cons_qual"]).astype(np.int32)
            qb = np.asarray(b["cons_qual"]).astype(np.int32)
            if method == "blockseg":
                np.testing.assert_array_equal(qa, qb)
            else:
                # runsum: prefix sums reach ~24*R magnitude, so the
                # boundary subtraction loses ~0.01-0.03 absolute loglik;
                # quals shift at floor boundaries and the duplex q_ab+q_ba
                # sum compounds the two strands (measured here: <=0.7% of
                # elements off by >1, max 6). blockseg accumulates per
                # family only: exact.
                # (a +-1 deviation in the pass-1 consensus can move a
                # per-cycle cap by 1, shifting a whole qual column —
                # measured 5.9% off-by->=1 on one adjacency bucket)
                diff = np.abs(qa - qb)
                assert (diff > 0).mean() < 0.10 and diff.max() <= 15

    @pytest.mark.parametrize("method", ["blockseg", "runsum"])
    def test_unsorted_fid_and_ragged_r(self, method):
        # operator-path contract: fids arrive in arbitrary read order;
        # R not a multiple of the block size exercises the pad tail
        from duplexumiconsensusreads_tpu.kernels.consensus import ssc_kernel
        from duplexumiconsensusreads_tpu.oracle import group_reads

        cfg = SimConfig(n_molecules=40, duplex=False, read_len=37, seed=4)
        batch, _ = simulate_batch(cfg)
        n = (batch.bases.shape[0] // 128) * 128 + 57  # force ragged tail
        sub = batch.take(np.arange(min(n, batch.bases.shape[0])))
        fams = group_reads(sub, GroupingParams(strategy="exact"))
        args = (
            np.asarray(sub.bases),
            np.asarray(sub.quals),
            np.asarray(fams.family_id),
            np.asarray(sub.valid),
        )
        a = ssc_kernel(*args, f_max=128, method="matmul")
        b = ssc_kernel(*args, f_max=128, method=method)
        for x, y in zip(a, b):
            np.testing.assert_allclose(
                np.asarray(x).astype(np.int64),
                np.asarray(y).astype(np.int64),
                atol=0 if method == "blockseg" else 3,
            )


class TestPackedIO:
    def test_pack_base_qual_roundtrip(self):
        from duplexumiconsensusreads_tpu.ops.pipeline import (
            PACKED_NONE,
            PACKED_QUAL_MAX,
            pack_base_qual,
        )

        rng = np.random.default_rng(9)
        bases = rng.integers(0, 6, size=(40, 30)).astype(np.uint8)  # incl N=4, PAD=5
        quals = rng.integers(0, 64, size=(40, 30)).astype(np.uint8)
        bq = pack_base_qual(bases, quals)
        real = bases < 4
        assert (bq[~real] == PACKED_NONE).all()
        np.testing.assert_array_equal(bq[real] & 3, bases[real])
        np.testing.assert_array_equal(
            bq[real] >> 2, np.minimum(quals, PACKED_QUAL_MAX)[real]
        )
        # a real base can never alias the NONE marker
        assert (bq[real] != PACKED_NONE).all()

    def test_packed_pipeline_bit_equal(self):
        """packed_io=True must reproduce the unpacked pipeline outputs
        bit-for-bit (quals < 62 — the executors' packed_io_ok gate)."""
        import dataclasses as dc

        from duplexumiconsensusreads_tpu.ops.pipeline import pack_stacked

        cfg = SimConfig(n_molecules=120, duplex=True, umi_error=0.02, seed=13)
        batch, _ = simulate_batch(cfg)
        gp = GroupingParams(strategy="adjacency", paired=True)
        cp = ConsensusParams(mode="duplex", error_model="cycle")
        buckets = build_buckets(batch, capacity=512, grouping=gp)
        spec_raw = spec_for_buckets(buckets, gp, cp)
        spec_pk = dc.replace(
            spec_raw, packed_io=True, umi_len=int(buckets[0].umi.shape[1])
        )
        for bk in buckets:
            a = run_bucket(bk, spec_raw)
            # the FULL wire convention: bases|quals byte, 2-bit umi,
            # u16 pos, flag byte (r4 packing-ladder completion)
            stacked = {
                "bases": bk.bases[None], "quals": bk.quals[None],
                "umi": bk.umi[None], "pos": bk.pos[None],
                "strand_ab": bk.strand_ab[None],
                "frag_end": bk.frag_end[None], "valid": bk.valid[None],
            }
            pack_stacked(stacked)
            assert stacked["umi"].dtype == np.uint8
            assert stacked["umi"].shape[2] == -(-bk.umi.shape[1] // 4)
            assert stacked["pos"].dtype == np.uint16
            from duplexumiconsensusreads_tpu.ops import fused_pipeline

            b = fused_pipeline(
                stacked["pos"][0], stacked["umi"][0], stacked["strand_ab"][0],
                stacked["frag_end"][0], stacked["valid"][0],
                stacked["bases"][0], stacked["quals"][0], spec_pk,
            )
            for key in ("family_id", "cons_base", "cons_qual", "cons_depth",
                        "cons_valid", "cons_mate", "cons_pair"):
                np.testing.assert_array_equal(
                    np.asarray(a[key]), np.asarray(b[key]), err_msg=key
                )

    def test_packed_io_gate(self):
        from duplexumiconsensusreads_tpu.runtime.executor import packed_io_ok

        assert packed_io_ok(ConsensusParams(max_input_qual=50))
        assert not packed_io_ok(ConsensusParams(max_input_qual=80))

    def test_bitplane_roundtrip(self):
        """Host bit-plane pack (pack_stacked's sub-byte layout) vs the
        device unpack: codes survive exactly at both dictionary widths
        and at non-multiple-of-8 cycle counts."""
        from duplexumiconsensusreads_tpu.kernels.encoding import (
            unpack_bitplanes,
        )

        rng = np.random.default_rng(11)
        for nbits, l in ((5, 150), (7, 30), (5, 8), (7, 13)):
            codes = rng.integers(0, 1 << nbits, size=(3, 17, l)).astype(
                np.uint8
            )
            planes = np.concatenate(
                [
                    np.packbits((codes >> b) & 1, axis=-1, bitorder="little")
                    for b in range(nbits)
                ],
                axis=-1,
            )
            assert planes.shape[-1] == nbits * (-(-l // 8))
            back = np.asarray(unpack_bitplanes(planes, l, nbits))
            np.testing.assert_array_equal(back, codes)

    def test_subbyte_rung_selection(self):
        from duplexumiconsensusreads_tpu.ops.pipeline import subbyte_qbits_for

        assert subbyte_qbits_for(1) == 3
        assert subbyte_qbits_for(7) == 3
        assert subbyte_qbits_for(8) == 5
        assert subbyte_qbits_for(31) == 5
        assert subbyte_qbits_for(32) is None

    def test_subbyte_packed_pipeline_bit_equal(self):
        """The sub-byte qual-dictionary rung must reproduce the
        unpacked pipeline outputs bit-for-bit — including at an input
        qual cap past the byte rung's 6-bit gate, where only the
        dictionary keeps the transfer exact."""
        import dataclasses as dc

        from duplexumiconsensusreads_tpu.ops.pipeline import (
            pack_stacked,
            qual_alphabet,
            spec_for_buckets,
        )

        cfg = SimConfig(n_molecules=120, duplex=True, umi_error=0.02, seed=13)
        batch, _ = simulate_batch(cfg)
        gp = GroupingParams(strategy="adjacency", paired=True)
        cp = ConsensusParams(mode="duplex", error_model="cycle",
                             max_input_qual=80)
        buckets = build_buckets(batch, capacity=512, grouping=gp)
        spec_raw = spec_for_buckets(buckets, gp, cp)
        alpha = qual_alphabet(buckets)
        assert 7 < len(alpha) <= 31  # default sim: the 5-bit-index rung
        spec_pk = spec_for_buckets(
            buckets, gp, cp, packed_io=True, packed_qbits=5, qual_lut=alpha,
        )
        assert spec_pk.cycles_len == buckets[0].bases.shape[1]
        from duplexumiconsensusreads_tpu.ops import fused_pipeline

        for bk in buckets:
            a = run_bucket(bk, spec_raw)
            stacked = {
                "bases": bk.bases[None], "quals": bk.quals[None],
                "umi": bk.umi[None], "pos": bk.pos[None],
                "strand_ab": bk.strand_ab[None],
                "frag_end": bk.frag_end[None], "valid": bk.valid[None],
            }
            pack_stacked(stacked, spec_pk)
            # 7 bits/cycle: 7 * ceil(L/8) wire bytes per read
            l = bk.bases.shape[1]
            assert stacked["bases"].shape[2] == 7 * (-(-l // 8))
            b = fused_pipeline(
                stacked["pos"][0], stacked["umi"][0], stacked["strand_ab"][0],
                stacked["frag_end"][0], stacked["valid"][0],
                stacked["bases"][0], stacked["quals"][0], spec_pk,
            )
            for key in ("family_id", "cons_base", "cons_qual", "cons_depth",
                        "cons_valid", "cons_mate", "cons_pair"):
                np.testing.assert_array_equal(
                    np.asarray(a[key]), np.asarray(b[key]), err_msg=key
                )

    def test_d2h_pack_roundtrip(self):
        """Device packed-D2H epilogue -> host unpack reproduces the
        unpacked FETCH_KEYS arrays exactly at every position the
        scatter reads (rows below each bucket's n_out)."""
        from duplexumiconsensusreads_tpu.bucketing import stack_buckets
        from duplexumiconsensusreads_tpu.ops.pipeline import spec_for_buckets
        from duplexumiconsensusreads_tpu.parallel import make_mesh
        from duplexumiconsensusreads_tpu.parallel.sharded import (
            sharded_pipeline,
        )
        from duplexumiconsensusreads_tpu.runtime.executor import (
            FETCH_KEYS,
            d2h_k_pad,
            d2h_logical_nbytes,
            fetch_outputs,
            pack_fetch_outputs,
            start_fetch,
            unpack_fetch_outputs,
        )

        cfg = SimConfig(n_molecules=150, duplex=True, umi_error=0.02, seed=21)
        batch, _ = simulate_batch(cfg)
        gp = GroupingParams(strategy="adjacency", paired=True)
        cp = ConsensusParams(mode="duplex")  # default max_qual=90: the
        # pack must be exact far past any 6-bit payload
        buckets = build_buckets(batch, capacity=256, grouping=gp)
        spec = spec_for_buckets(buckets, gp, cp)
        mesh = make_mesh(1)
        stacked = stack_buckets(buckets)
        out = sharded_pipeline(stacked, spec, mesh)
        plain = fetch_outputs(start_fetch(out))
        k_pad = d2h_k_pad(buckets, spec)
        packed = fetch_outputs(
            start_fetch(
                pack_fetch_outputs(out, spec, k_pad),
                keys=tuple(pack_fetch_outputs(out, spec, k_pad)),
            )
        )
        # the compact transfer must actually be smaller than the padded
        # one, and the ledger's logical side must equal the unpacked sum
        wire = sum(v.nbytes for v in packed.values())
        logical = d2h_logical_nbytes(packed, buckets, spec)
        assert wire < logical
        assert logical == sum(v.nbytes for v in plain.values())
        full = unpack_fetch_outputs(packed, buckets, spec)
        n_out = np.clip(np.asarray(plain["n_molecules"]), 0,
                        np.asarray(plain["cons_valid"]).shape[1])
        assert set(full) == (set(FETCH_KEYS) - {"family_id"})
        for key in full:
            got, want = np.asarray(full[key]), np.asarray(plain[key])
            assert got.dtype == want.dtype, key
            if got.ndim >= 2 and key not in ("molecule_id",):
                for bi, n in enumerate(n_out):
                    np.testing.assert_array_equal(
                        got[bi, :n], want[bi, :n], err_msg=key
                    )
            else:
                np.testing.assert_array_equal(got, want, err_msg=key)


class TestPallasSegmentGemm:
    def _ref(self, big, fid, f):
        ref = np.zeros((f, big.shape[1]), np.float32)
        for i in range(len(fid)):
            if 0 <= fid[i] < f:
                ref[fid[i]] += big[i]
        return ref

    @pytest.mark.parametrize("sorted_ids", [True, False])
    def test_parity_interpret(self, sorted_ids):
        from duplexumiconsensusreads_tpu.kernels.pallas_ssc import segment_gemm

        rng = np.random.default_rng(3)
        r, c, f = 600, 140, 260
        big = rng.standard_normal((r, c)).astype(np.float32)
        fid = rng.integers(-1, f, size=r).astype(np.int32)
        if sorted_ids:
            fid = np.sort(fid)
        out = segment_gemm(big, fid, f_max=f, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), self._ref(big, fid, f), rtol=1e-5, atol=1e-5
        )

    def test_ssc_method_pallas_interpret(self):
        from duplexumiconsensusreads_tpu.kernels.consensus import ssc_kernel

        cfg = SimConfig(n_molecules=40, duplex=False, seed=4)
        batch, _ = simulate_batch(cfg)
        from duplexumiconsensusreads_tpu.oracle import group_reads

        fams = group_reads(batch, GroupingParams(strategy="exact"))
        a = ssc_kernel(
            np.asarray(batch.bases),
            np.asarray(batch.quals),
            np.asarray(fams.family_id),
            np.asarray(batch.valid),
            f_max=128,
            method="matmul",
        )
        b = ssc_kernel(
            np.asarray(batch.bases),
            np.asarray(batch.quals),
            np.asarray(fams.family_id),
            np.asarray(batch.valid),
            f_max=128,
            method="pallas_interpret",
        )
        for x, y in zip(a, b):
            np.testing.assert_allclose(
                np.asarray(x).astype(np.float64),
                np.asarray(y).astype(np.float64),
                atol=1,  # qual may differ by 1 at f32 sum-order boundaries
            )

class TestBlocksegSparseIds:
    """blockseg must be exact for SPARSE reduction ids: the strided
    duplex path keys the ssc by molecule*2 + strand, so single-strand
    molecules leave id gaps and a sorted block of T rows can span up to
    2T id values. The earlier offset-based routing (fid - fid[first],
    clipped to T) silently scatter-added out-of-window families into a
    neighbour's consensus row (advisor r4, high); the rank-based
    routing has no density assumption."""

    def test_direct_sparse_ids_exact(self):
        from duplexumiconsensusreads_tpu.kernels.consensus import ssc_kernel

        rng = np.random.default_rng(17)
        # singletons at even ids only: a block of T=8 rows spans 16 ids
        k = 96
        ids = (np.arange(k, dtype=np.int32) * 2)
        l = 24
        bases = rng.integers(0, 4, (k, l)).astype(np.uint8)
        quals = rng.integers(20, 41, (k, l)).astype(np.uint8)
        valid = np.ones(k, bool)
        a = ssc_kernel(bases, quals, ids, valid, f_max=2 * k, method="matmul")
        b = ssc_kernel(
            bases, quals, ids, valid, f_max=2 * k, method="blockseg",
            blockseg_t=8,
        )
        for x, y in zip(a, b):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    def test_duplex_blockseg_singleton_families(self):
        """Full strided-duplex pipeline with blockseg on singleton-heavy
        data: half the molecules lose their BA strand entirely, so the
        strided ids are gappy exactly where the old blockseg corrupted.
        Mean family size 1 keeps blocks spanning many molecules."""
        import dataclasses as dc

        cfg = SimConfig(
            n_molecules=300, duplex=True, mean_family_size=1,
            max_family_size=2, seed=23,
        )
        batch, truth = simulate_batch(cfg)
        # drop the BA strand of every even molecule -> strided-id gaps
        drop = (truth.read_mol % 2 == 0) & ~truth.read_strand
        sub = batch.take(np.nonzero(~drop)[0])
        gp = GroupingParams(strategy="exact", paired=True)
        cp = ConsensusParams(mode="duplex", min_duplex_reads=1)
        buckets = build_buckets(sub, capacity=512, grouping=gp)
        ref_spec = spec_for_buckets(buckets, gp, cp, ssc_method="matmul")
        new_spec = dc.replace(
            spec_for_buckets(buckets, gp, cp, ssc_method="blockseg"),
            blockseg_t=16,
        )
        # the scenario must actually exercise the strided path
        assert new_spec.consensus.mode == "duplex"
        checked = 0
        for bk in buckets:
            a = run_bucket(bk, ref_spec)
            b = run_bucket(bk, new_spec)
            for key in ("family_id", "cons_base", "cons_qual",
                        "cons_depth", "cons_valid"):
                np.testing.assert_array_equal(
                    np.asarray(a[key]), np.asarray(b[key]), err_msg=key
                )
            checked += int(np.asarray(a["cons_valid"]).sum())
        assert checked > 100


def test_runsum_fit_mode_uses_depth_mask():
    """columns='fit' under runsum: a lone high-qual read's ~1e-9 loglik
    cancels to exact 0.0 against the large prefix sums, so the sign
    test that replaces the depth>0 mask misses its evidence (advisor
    r4). runsum must keep depth columns in fit mode and match the full
    pass's calls exactly."""
    from duplexumiconsensusreads_tpu.kernels.consensus import ssc_kernel

    rng = np.random.default_rng(3)
    l = 16
    # family 0: 64 qual-30 reads all base A -> prefix magnitude ~0.064
    # per match column, ulp >> 1e-9; family 1: one qual-90 read whose
    # match-column contribution log1p(-1e-9) ~ -1e-9 vanishes into it
    n0 = 64
    bases = np.zeros((n0 + 1, l), np.uint8)
    quals = np.concatenate(
        [np.full((n0, l), 30, np.uint8), np.full((1, l), 90, np.uint8)]
    )
    ids = np.concatenate(
        [np.zeros(n0, np.int32), np.ones(1, np.int32)]
    )
    valid = np.ones(n0 + 1, bool)
    kw = dict(f_max=4, min_reads=1, max_input_qual=90, method="runsum")
    full_b, _fq, full_d, _sz, _fv = ssc_kernel(
        bases, quals, ids, valid, **kw
    )
    fit_b, _fsz, _ffv = ssc_kernel(
        bases, quals, ids, valid, columns="fit", **kw
    )
    # the lone read's family must be CALLED (base A), not masked to N
    assert (np.asarray(full_d)[1] > 0).all()
    np.testing.assert_array_equal(np.asarray(fit_b), np.asarray(full_b))
