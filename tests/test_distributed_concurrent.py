"""True concurrent multi-process distributed execution (VERDICT r3
missing #5 / weak #6): N real OS processes run the multihost CLI
against ONE input at the same time — first wired into a genuine
jax.distributed runtime (localhost coordinator, CPU backend), then
through a kill-and-resume cycle with checkpoints on shared storage.

Previously config-4 correctness rested on single-process emulation
(sequential host-id loops); these tests exercise the real thing:
concurrent index/manifest/shard file access, per-host checkpoint
isolation, and a resumed host that replays nothing it shouldn't.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from duplexumiconsensusreads_tpu.cli import main
from duplexumiconsensusreads_tpu.io import read_bam
from duplexumiconsensusreads_tpu.io.index import build_linear_index
from duplexumiconsensusreads_tpu.runtime.stream import stream_call_consensus
from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams


def _sorted_bam(tmp_path, n_mol, n_positions, name="in.bam"):
    path = str(tmp_path / name)
    assert main([
        "simulate", "-o", path, "--molecules", str(n_mol), "--read-len", "40",
        "--positions", str(n_positions), "--umi-error", "0.02", "--seed", "13",
        "--sorted",
    ]) == 0
    return path


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _host_cmd(in_path, out, pid, n_hosts, chunk_reads, extra=()):
    return [
        sys.executable, "-m", "duplexumiconsensusreads_tpu.cli.main",
        "call", in_path, "-o", out, "--config", "config3",
        "--capacity", "128", "--chunk-reads", str(chunk_reads),
        "--n-hosts", str(n_hosts), "--host-id", str(pid), *extra,
    ]


def _cpu_env(**extra):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # the parent test process pins an 8-device CPU topology in
    # conftest via jax.config; children get plain 1-device CPU
    env.pop("XLA_FLAGS", None)
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _assert_concat_equals_whole(part_paths, whole_path):
    _, r_whole = read_bam(whole_path)
    cat = [read_bam(p)[1] for p in part_paths if os.path.exists(p)]
    n_cat = sum(len(r) for r in cat)
    assert n_cat == len(r_whole)
    pos = np.concatenate([np.asarray(r.pos) for r in cat])
    np.testing.assert_array_equal(pos, np.asarray(r_whole.pos))
    seq = np.concatenate([np.asarray(r.seq) for r in cat])
    np.testing.assert_array_equal(seq, np.asarray(r_whole.seq))
    umi = [u for r in cat for u in r.umi]
    assert umi == list(r_whole.umi)


def test_concurrent_hosts_with_jax_distributed(tmp_path):
    """Two OS processes, one jax.distributed runtime (localhost
    coordinator), both streaming their input partition CONCURRENTLY.
    Their outputs must concatenate to the whole-file result, and both
    must report an initialized 2-process runtime."""
    path = _sorted_bam(tmp_path, n_mol=120, n_positions=12)
    build_linear_index(path, every=60).save(path + ".dlix")

    whole = str(tmp_path / "whole.bam")
    stream_call_consensus(
        path, whole,
        GroupingParams(strategy="adjacency", paired=True),
        ConsensusParams(mode="duplex"),
        capacity=128, chunk_reads=100,
    )

    port = _free_port()
    out = str(tmp_path / "mh.bam")
    procs = []
    for pid in range(2):
        env = _cpu_env(
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES=2,
            JAX_PROCESS_ID=pid,
        )
        procs.append(subprocess.Popen(
            _host_cmd(path, out, pid, 2, 100),
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    errs = []
    for p in procs:
        _, err = p.communicate(timeout=300)
        errs.append(err)
        assert p.returncode == 0, err[-3000:]
    for err in errs:
        assert "distributed runtime: process" in err, err[-3000:]
        assert "/2," in err  # 2-process runtime actually came up

    parts = [str(tmp_path / f"mh.host{pid}.bam") for pid in range(2)]
    _assert_concat_equals_whole(parts, whole)


def test_concurrent_hosts_kill_and_resume(tmp_path):
    """Both hosts run concurrently on shared storage with checkpoints;
    host 1 is SIGKILLed mid-run and relaunched with --resume. The
    final concatenation must equal the whole-file result and the
    resumed host must skip exactly the chunks its manifest had
    completed (replaying nothing it shouldn't)."""
    path = _sorted_bam(tmp_path, n_mol=400, n_positions=40, name="big.bam")
    build_linear_index(path, every=100).save(path + ".dlix")

    whole = str(tmp_path / "whole.bam")
    stream_call_consensus(
        path, whole,
        GroupingParams(strategy="adjacency", paired=True),
        ConsensusParams(mode="duplex"),
        capacity=128, chunk_reads=60,
    )

    out = str(tmp_path / "mh.bam")
    ckpt = str(tmp_path / "ckpt")
    extra = ["--checkpoint", ckpt]
    p0 = subprocess.Popen(
        _host_cmd(path, out, 0, 2, 60, extra), env=_cpu_env(),
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True,
    )
    p1 = subprocess.Popen(
        _host_cmd(path, out, 1, 2, 60, extra), env=_cpu_env(),
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True,
    )

    # kill host 1 once its per-host manifest shows real progress but
    # (expectedly) not completion
    ckpt1 = ckpt + ".host1"
    deadline = time.time() + 240
    killed = False
    while time.time() < deadline:
        if p1.poll() is not None:
            break  # finished before we could kill — resume still tested below
        try:
            with open(ckpt1) as f:
                done = json.load(f).get("done", {})
        except (OSError, json.JSONDecodeError):
            done = {}
        if len(done) >= 2:
            p1.send_signal(signal.SIGKILL)
            killed = True
            break
        time.sleep(0.1)
    p1.wait(timeout=60)

    _, err0 = p0.communicate(timeout=300)
    assert p0.returncode == 0, err0[-3000:]

    # manifest state at relaunch: these chunks must be SKIPPED, not
    # recomputed
    with open(ckpt1) as f:
        done_before_resume = json.load(f).get("done", {})
    assert len(done_before_resume) >= 2

    report = str(tmp_path / "resume_report.json")
    rc = subprocess.run(
        _host_cmd(path, out, 1, 2, 60,
                  extra + ["--resume", "--report", report]),
        env=_cpu_env(), capture_output=True, text=True, timeout=300,
    )
    assert rc.returncode == 0, rc.stderr[-3000:]
    # multihost runs suffix the report per host (a shared --report path
    # would have every host clobber the same file)
    with open(report + ".host1") as f:
        rep = json.load(f)
    assert rep["n_chunks_skipped"] == len(done_before_resume)
    if killed:
        # the kill landed mid-run: the resumed process did fresh work too
        assert rep["n_chunks"] > rep["n_chunks_skipped"]

    parts = [str(tmp_path / f"mh.host{pid}.bam") for pid in range(2)]
    _assert_concat_equals_whole(parts, whole)
