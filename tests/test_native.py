"""Native C++ BAM loader: parity vs the pure-Python codec.

The native path must produce byte-identical ReadBatch tensors — it is
an accelerated implementation of the same io/convert.py contract, not
a second semantics. Tests skip if the toolchain can't build the lib.
"""

import numpy as np
import pytest

from duplexumiconsensusreads_tpu.io import read_bam, records_to_readbatch, simulated_bam
from duplexumiconsensusreads_tpu.io.native_reader import read_bam_native
from duplexumiconsensusreads_tpu.native import native_available
from duplexumiconsensusreads_tpu.simulate import SimConfig

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native toolchain unavailable"
)

_FIELDS = ("bases", "quals", "umi", "pos_key", "strand_ab", "valid")


def _assert_batches_equal(a, b):
    for f in _FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, f)), np.asarray(getattr(b, f)), err_msg=f
        )


@pytest.mark.parametrize("duplex", [True, False])
def test_native_matches_python(tmp_path, duplex):
    path = str(tmp_path / "x.bam")
    cfg = SimConfig(
        n_molecules=120, duplex=duplex, umi_error=0.02, read_len=80,
        n_positions=8, n_frac=0.01, seed=13,
    )
    simulated_bam(cfg, path=path)
    h_nat, b_nat, info = read_bam_native(path, duplex=duplex)
    h_py, recs = read_bam(path)
    b_py, info_py = records_to_readbatch(recs, duplex=duplex)
    assert h_nat.ref_names == h_py.ref_names
    assert info["n_valid"] == info_py["n_valid"]
    _assert_batches_equal(b_nat, b_py)


def test_native_drops_bad_umis(tmp_path):
    from duplexumiconsensusreads_tpu.io import BamHeader, write_bam

    path = str(tmp_path / "y.bam")
    _, recs, *_ = simulated_bam(SimConfig(n_molecules=6, seed=7))
    from duplexumiconsensusreads_tpu.io.bam import make_aux_z

    recs.umi[0] = ""
    recs.aux_raw[0] = b""
    recs.umi[1] = "NNNACG-ACGTTT"
    recs.aux_raw[1] = make_aux_z("RX", recs.umi[1])
    write_bam(path, BamHeader.synthetic(), recs)

    _, batch, info = read_bam_native(path, duplex=True)
    assert not batch.valid[0]
    assert not batch.valid[1]
    assert batch.valid[2:].all()
    # python codec agrees
    _, recs2 = read_bam(path)
    b_py, _ = records_to_readbatch(recs2, duplex=True)
    _assert_batches_equal(batch, b_py)


def test_unparseable_long_rx_does_not_inflate_umi_len(tmp_path):
    """A read with an oversized non-ACGT RX must not change umi_len for
    everyone else (regression: native once computed umi_len over ALL
    reads, zeroing n_valid). Lowercase RX must parse like the codec."""
    from duplexumiconsensusreads_tpu.io import BamHeader, write_bam
    from duplexumiconsensusreads_tpu.io.bam import make_aux_z

    path = str(tmp_path / "w.bam")
    _, recs, *_ = simulated_bam(SimConfig(n_molecules=8, seed=17))
    recs.umi[0] = "NACGTACGNN-ACGTACGTNN"  # longer than everyone, unparseable
    recs.aux_raw[0] = make_aux_z("RX", recs.umi[0])
    recs.umi[1] = recs.umi[1].lower()  # lowercase must still parse
    recs.aux_raw[1] = make_aux_z("RX", recs.umi[1])
    write_bam(path, BamHeader.synthetic(), recs)

    _, b_nat, info = read_bam_native(path, duplex=True)
    _, recs2 = read_bam(path)
    b_py, info_py = records_to_readbatch(recs2, duplex=True)
    assert info["n_valid"] == info_py["n_valid"] == len(recs) - 1
    assert not b_nat.valid[0] and b_nat.valid[1]
    _assert_batches_equal(b_nat, b_py)


def test_native_flag_filter_parity(tmp_path):
    """Flag-excluded reads (secondary/supplementary/unmapped) are
    invalid in BOTH paths, with matching drop counts."""
    from duplexumiconsensusreads_tpu.io import BamHeader, write_bam
    from duplexumiconsensusreads_tpu.io.bam import (
        FLAG_SECONDARY,
        FLAG_SUPPLEMENTARY,
        FLAG_UNMAPPED,
    )

    path = str(tmp_path / "fl.bam")
    _, recs, *_ = simulated_bam(SimConfig(n_molecules=10, seed=19))
    recs.flags[0] |= FLAG_SECONDARY
    recs.flags[1] |= FLAG_SUPPLEMENTARY
    recs.flags[2] |= FLAG_UNMAPPED
    recs.ref_id[2] = -1
    recs.pos[2] = -1
    write_bam(path, BamHeader.synthetic(), recs)

    _, b_nat, info = read_bam_native(path, duplex=True)
    _, recs2 = read_bam(path)
    b_py, info_py = records_to_readbatch(recs2, duplex=True)
    assert info["n_dropped_flag"] == info_py["n_dropped_flag"] == 3
    assert not b_nat.valid[:3].any()
    _assert_batches_equal(b_nat, b_py)


def test_native_degenerate_rx_parity(tmp_path):
    """An RX of only separators ('-') is parseable with zero UMI chars:
    valid iff umi_len == 0 — identical in both codecs."""
    from duplexumiconsensusreads_tpu.io import BamHeader, write_bam
    from duplexumiconsensusreads_tpu.io.bam import make_aux_z

    # case 1: mixed — the '-' read is length-inconsistent, dropped
    path = str(tmp_path / "deg1.bam")
    _, recs, *_ = simulated_bam(SimConfig(n_molecules=6, seed=29))
    recs.umi[0] = "-"
    recs.aux_raw[0] = make_aux_z("RX", "-")
    write_bam(path, BamHeader.synthetic(), recs)
    _, b_nat, info = read_bam_native(path, duplex=True)
    _, recs2 = read_bam(path)
    b_py, info_py = records_to_readbatch(recs2, duplex=True)
    assert info["n_valid"] == info_py["n_valid"] == len(recs) - 1
    _assert_batches_equal(b_nat, b_py)

    # case 2: ALL reads have '-' RX -> umi_len == 0, everyone valid
    path2 = str(tmp_path / "deg2.bam")
    _, recs3, *_ = simulated_bam(SimConfig(n_molecules=4, seed=31))
    for i in range(len(recs3)):
        recs3.umi[i] = "-"
        recs3.aux_raw[i] = make_aux_z("RX", "-")
    write_bam(path2, BamHeader.synthetic(), recs3)
    _, b_nat2, info2 = read_bam_native(path2, duplex=True)
    _, recs4 = read_bam(path2)
    b_py2, info_py2 = records_to_readbatch(recs4, duplex=True)
    assert info2["umi_len"] == info_py2["umi_len"] == 0
    assert info2["n_valid"] == info_py2["n_valid"] == len(recs3)
    _assert_batches_equal(b_nat2, b_py2)


def test_native_uncompressed_and_aux_types(tmp_path):
    """Records with diverse aux tag types parse identically."""
    import struct

    from duplexumiconsensusreads_tpu.io import BamHeader, write_bam
    from duplexumiconsensusreads_tpu.io.bam import make_aux_i, make_aux_z, serialize_bam

    path = str(tmp_path / "z.bam")
    _, recs, *_ = simulated_bam(SimConfig(n_molecules=10, seed=3))
    # decorate reads with extra tags around RX
    for i in range(len(recs)):
        extra = (
            make_aux_i("NM", i)
            + b"XFf" + struct.pack("<f", 1.5)
            + b"XBB" + b"C" + struct.pack("<I", 3) + bytes([1, 2, 3])
            + b"XAA" + b"Q"
        )
        recs.aux_raw[i] = extra + recs.aux_raw[i] + make_aux_z("XZ", "trailing")
    write_bam(path, BamHeader.synthetic(), recs)

    _, b_nat, info = read_bam_native(path, duplex=True)
    _, recs2 = read_bam(path)
    b_py, _ = records_to_readbatch(recs2, duplex=True)
    _assert_batches_equal(b_nat, b_py)
    assert info["n_valid"] == len(recs)


def test_native_bgzf_large_multiblock(tmp_path):
    """>64KiB BAM exercises multi-block parallel BGZF decompression."""
    path = str(tmp_path / "big.bam")
    cfg = SimConfig(n_molecules=2000, read_len=120, n_positions=32, seed=21)
    simulated_bam(cfg, path=path)
    _, b_nat, info = read_bam_native(path, duplex=True, n_threads=4)
    _, recs = read_bam(path)
    b_py, _ = records_to_readbatch(recs, duplex=True)
    _assert_batches_equal(b_nat, b_py)
    assert info["n_records"] > 10_000
