"""Randomized property tests: for many random (config, seed) draws the
fused device pipeline must agree with the NumPy oracle, and paired-end
flag encoding must be transparent to the whole workflow."""

import numpy as np
import pytest

from duplexumiconsensusreads_tpu.bucketing import build_buckets
from duplexumiconsensusreads_tpu.io import (
    read_bam,
    records_to_readbatch,
    simulated_bam,
)
from duplexumiconsensusreads_tpu.oracle import group_reads
from duplexumiconsensusreads_tpu.ops import ConsensusCaller, run_bucket, spec_for_buckets
from duplexumiconsensusreads_tpu.simulate import SimConfig, simulate_batch
from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams


def _random_case(rng):
    duplex = bool(rng.integers(0, 2))
    strategy = ["exact", "adjacency"][rng.integers(0, 2)]
    cfg = SimConfig(
        n_molecules=int(rng.integers(10, 80)),
        read_len=int(rng.integers(20, 90)),
        umi_len=int(rng.integers(4, 9)),
        n_positions=int(rng.integers(1, 9)),
        mean_family_size=int(rng.integers(1, 6)),
        base_error=float(rng.uniform(0, 0.08)),
        umi_error=float(rng.uniform(0, 0.04)) if strategy == "adjacency" else 0.0,
        cycle_error_slope=float(rng.uniform(0, 0.002)),
        n_frac=float(rng.uniform(0, 0.03)),
        duplex=duplex,
        seed=int(rng.integers(0, 1 << 30)),
    )
    gp = GroupingParams(strategy=strategy, paired=duplex)
    cp = ConsensusParams(
        mode="duplex" if duplex else "single_strand",
        min_reads=int(rng.integers(1, 3)),
        min_duplex_reads=int(rng.integers(1, 3)),
        min_input_qual=int(rng.choice([0, 0, 15, 25])),
        error_model=[None, "cycle"][rng.integers(0, 2)],
    )
    return cfg, gp, cp


@pytest.mark.parametrize("trial", range(12))
def test_pipeline_matches_oracle_random(trial):
    rng = np.random.default_rng(1000 + trial)
    cfg, gp, cp = _random_case(rng)
    batch, _ = simulate_batch(cfg)

    fams = group_reads(batch, gp)
    oracle = ConsensusCaller(cp, backend="cpu")(batch, fams)

    # the cycle error model is fitted per bucket; comparing against the
    # whole-batch oracle fit requires a single bucket. error_model=None
    # cases use a small capacity to also fuzz the bucket splitter.
    capacity = 8192 if cp.error_model else 256
    buckets = build_buckets(batch, capacity=capacity, adjacency=gp.strategy == "adjacency")
    spec = spec_for_buckets(buckets, gp, cp)

    # collect device outputs keyed by (pos_key, umi) of a member read,
    # then compare against the oracle row of the same family
    duplex = cp.mode == "duplex"
    n_checked = 0
    for bk in buckets:
        out = {k: np.asarray(v) for k, v in run_bucket(bk, spec).items()}
        ids = out["molecule_id"] if duplex else out["family_id"]
        oracle_ids = np.asarray(fams.molecule_id if duplex else fams.family_id)
        cv = out["cons_valid"]
        for slot in range(bk.capacity):
            if not (bk.valid[slot] and bk.read_index[slot] >= 0):
                continue
            dev_id = ids[slot]
            if dev_id < 0:
                continue
            src = int(bk.read_index[slot])
            o_id = int(oracle_ids[src])
            if o_id < 0:
                continue
            dev_valid = bool(cv[dev_id])
            o_valid = bool(np.asarray(oracle.valid)[o_id])
            assert dev_valid == o_valid
            if not dev_valid:
                continue
            dev_b = out["cons_base"][dev_id]
            dev_q = out["cons_qual"][dev_id].astype(int)
            o_b = np.asarray(oracle.bases)[o_id]
            o_q = np.asarray(oracle.quals)[o_id].astype(int)
            # Parity contract: bases identical EXCEPT at evidence ties,
            # where f32-vs-f64 rounding may break the argmax either way
            # — both sides then report (near-)zero confidence. Quals
            # within +-1 of each other except where such a tie flipped
            # a duplex site between agree/disagree scoring; those sites
            # are low-confidence on at least one side.
            b_diff = dev_b != o_b
            if b_diff.any():
                assert dev_q[b_diff].max() <= 3 and o_q[b_diff].max() <= 3
            dq = np.abs(dev_q - o_q)
            # duplex quals are sums of two ss quals, so the inherent
            # ±1-per-strand rounding window doubles
            tol = 2 if duplex else 1
            rough = dq > tol
            if rough.any():
                # beyond-tolerance divergence is allowed only at
                # (a) tie flips — low confidence on both sides — or
                # (b) deep sites where the Phred is the log of a tiny
                # f32 residual (41 vs 47 is the same certainty; the TPU
                # HIGHEST-precision 6-pass bf16 GEMM rounds these
                # residuals differently than CPU f32). The mid-range,
                # where quality actually informs callers, stays ±tol.
                mn = np.minimum(dev_q, o_q)[rough]
                assert ((mn <= 10) | (mn >= 25)).all()
                assert rough.mean() <= 0.2  # sites, not systematic drift
                assert dq[rough].max() <= 12
            n_checked += 1
    # a config can legitimately call nothing (strict min_reads vs tiny
    # families) — but if the oracle called anything we must have
    # compared at least one row
    if int(np.asarray(oracle.valid).sum()) > 0:
        assert n_checked > 0


@pytest.mark.parametrize("trial", range(6))
def test_streamed_call_matches_wholefile_random(trial, tmp_path):
    """Random configs (with indels): the streaming executor's output
    must equal the whole-file executor's, byte for byte."""
    from duplexumiconsensusreads_tpu.cli import main

    rng = np.random.default_rng(7000 + trial)
    cfg = SimConfig(
        n_molecules=int(rng.integers(40, 150)),
        read_len=int(rng.integers(25, 70)),
        n_positions=int(rng.integers(2, 10)),
        mean_family_size=int(rng.integers(1, 6)),
        umi_error=float(rng.uniform(0, 0.03)),
        indel_error=float(rng.choice([0.0, 0.05])),
        duplex=True,
        seed=int(rng.integers(0, 1 << 30)),
    )
    path = str(tmp_path / "in.bam")
    simulated_bam(cfg, path=path, sort=True)
    common = ["--config", "config3", "--capacity", "128"]
    whole = str(tmp_path / "w.bam")
    stream = str(tmp_path / "s.bam")
    assert main(["call", path, "-o", whole, *common]) == 0
    assert main(
        ["call", path, "-o", stream, "--chunk-reads",
         str(int(rng.integers(50, 400))), *common]
    ) == 0
    _, rw = read_bam(whole)
    _, rs = read_bam(stream)
    assert len(rw) == len(rs)
    np.testing.assert_array_equal(rw.pos, rs.pos)
    np.testing.assert_array_equal(rw.seq, rs.seq)
    np.testing.assert_array_equal(rw.qual, rs.qual)
    assert list(rw.umi) == list(rs.umi)


def test_paired_end_flags_roundtrip(tmp_path):
    """Paired-end flag encoding must produce the identical ReadBatch —
    strand from F1R2/F2R1 and pos_key through min(pos, next_pos)."""
    cfg = SimConfig(n_molecules=60, duplex=True, umi_error=0.02, seed=44)
    path_se = str(tmp_path / "se.bam")
    path_pe = str(tmp_path / "pe.bam")
    simulated_bam(cfg, path=path_se, paired_end=False)
    simulated_bam(cfg, path=path_pe, paired_end=True)

    _, recs_pe = read_bam(path_pe)
    assert all(f & 0x1 for f in recs_pe.flags)  # all paired
    _, recs_se = read_bam(path_se)
    b_se, _ = records_to_readbatch(recs_se, duplex=True)
    b_pe, _ = records_to_readbatch(recs_pe, duplex=True)
    for f in ("bases", "quals", "umi", "pos_key", "strand_ab", "valid"):
        np.testing.assert_array_equal(
            np.asarray(getattr(b_se, f)), np.asarray(getattr(b_pe, f)), err_msg=f
        )


def test_paired_end_native_parity(tmp_path):
    from duplexumiconsensusreads_tpu.io.native_reader import read_bam_native
    from duplexumiconsensusreads_tpu.native import native_available

    if not native_available():
        pytest.skip("native toolchain unavailable")
    cfg = SimConfig(n_molecules=40, duplex=True, seed=9)
    path = str(tmp_path / "pe.bam")
    simulated_bam(cfg, path=path, paired_end=True)
    _, b_nat, _ = read_bam_native(path, duplex=True)
    _, recs = read_bam(path)
    b_py, _ = records_to_readbatch(recs, duplex=True)
    for f in ("bases", "quals", "umi", "pos_key", "strand_ab", "valid"):
        np.testing.assert_array_equal(
            np.asarray(getattr(b_nat, f)), np.asarray(getattr(b_py, f)), err_msg=f
        )
