"""Linear BGZF index + multi-host input partitioning (VERDICT r1 item 5).

The acceptance test: N partitioned "hosts", each opening the BAM at its
index-derived virtual offset and streaming only its key range, must
together produce exactly the whole-file streaming output.
"""

import numpy as np
import pytest

from duplexumiconsensusreads_tpu.io import read_bam, simulated_bam
from duplexumiconsensusreads_tpu.io.index import BamLinearIndex, build_linear_index
from duplexumiconsensusreads_tpu.parallel.distributed import (
    host_input_range,
    multihost_call,
)
from duplexumiconsensusreads_tpu.runtime.stream import (
    iter_batch_chunks,
    stream_call_consensus,
)
from duplexumiconsensusreads_tpu.simulate import SimConfig
from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams


def _sorted_bam(tmp_path, n_mol=150, n_positions=16, seed=3):
    path = str(tmp_path / "in.bam")
    cfg = SimConfig(
        n_molecules=n_mol, n_positions=n_positions, umi_error=0.02, seed=seed
    )
    simulated_bam(cfg, path=path, sort=True)
    return path


def test_index_roundtrip_and_shape(tmp_path):
    path = _sorted_bam(tmp_path)
    idx = build_linear_index(path, every=100)
    assert idx.n_records > 0
    assert len(idx.pos_key) == -(-idx.n_records // 100)
    assert (np.diff(idx.pos_key) >= 0).all()
    p = str(tmp_path / "i.dlix.npz")
    idx.save(p)
    idx2 = BamLinearIndex.load(p)
    np.testing.assert_array_equal(idx.pos_key, idx2.pos_key)
    np.testing.assert_array_equal(idx.coffset, idx2.coffset)
    assert idx2.every == 100 and idx2.n_records == idx.n_records


def test_range_reader_covers_partition(tmp_path):
    """Chunks read per host range concatenate to the full record set."""
    path = _sorted_bam(tmp_path)
    idx = build_linear_index(path, every=97)
    n_hosts = 3
    seen = 0
    all_keys = []
    for pid in range(n_hosts):
        rng = host_input_range(idx, process_id=pid, num_processes=n_hosts)
        if rng is None:
            continue
        start, lo, hi = rng
        for _, batch, info in iter_batch_chunks(
            path, 64, duplex=True, start=start, key_lo=lo, key_hi=hi
        ):
            k = np.asarray(batch.pos_key)
            if lo is not None:
                assert (k >= lo).all()
            if hi is not None:
                assert (k < hi).all()
            seen += info["n_records"]
            all_keys.append(k)
    full = sum(
        info["n_records"] for _, _, info in iter_batch_chunks(path, 64, duplex=True)
    )
    assert seen == full
    keys = np.concatenate(all_keys)
    assert (np.diff(keys) >= 0).all()  # host order == genomic order


@pytest.mark.parametrize("n_hosts", [2, 3])
def test_multihost_outputs_concatenate_to_wholefile(tmp_path, n_hosts):
    path = _sorted_bam(tmp_path, n_mol=120, n_positions=12)
    gp = GroupingParams(strategy="adjacency", paired=True)
    cp = ConsensusParams(mode="duplex")
    kw = dict(capacity=128, chunk_reads=100)

    whole = str(tmp_path / "whole.bam")
    stream_call_consensus(path, whole, gp, cp, **kw)

    parts = []
    for pid in range(n_hosts):
        out = str(tmp_path / f"host{pid}.bam")
        rep = multihost_call(
            path, out, gp, cp, process_id=pid, num_processes=n_hosts,
            index_every=60, **kw
        )
        if rep is not None:
            parts.append(out)
    assert len(parts) >= 2

    _, r_whole = read_bam(whole)
    cat = [read_bam(p)[1] for p in parts]
    n_cat = sum(len(r) for r in cat)
    assert n_cat == len(r_whole)
    pos = np.concatenate([np.asarray(r.pos) for r in cat])
    np.testing.assert_array_equal(pos, np.asarray(r_whole.pos))
    seq = np.concatenate([np.asarray(r.seq) for r in cat])
    np.testing.assert_array_equal(seq, np.asarray(r_whole.seq))
    qual = np.concatenate([np.asarray(r.qual) for r in cat])
    np.testing.assert_array_equal(qual, np.asarray(r_whole.qual))
    umi = [u for r in cat for u in r.umi]
    assert umi == list(r_whole.umi)


def test_cli_multihost_per_host_outputs(tmp_path):
    """CLI multi-host mode must write per-host suffixed outputs (a
    verbatim --output would have every pod host clobber the same file
    and checkpoint)."""
    import os

    from duplexumiconsensusreads_tpu.cli import main

    path = _sorted_bam(tmp_path, n_mol=80, n_positions=8)
    from duplexumiconsensusreads_tpu.io.index import build_linear_index

    build_linear_index(path, every=60).save(path + ".dlix")
    outs = []
    trace = str(tmp_path / "mh.trace.jsonl")
    report = str(tmp_path / "mh.report.json")
    for pid in range(2):
        out = str(tmp_path / "mh.bam")
        assert main(
            ["call", path, "-o", out, "--config", "config3",
             "--capacity", "128", "--chunk-reads", "100",
             "--n-hosts", "2", "--host-id", str(pid),
             "--trace", trace, "--report", report]
        ) == 0
        hp = str(tmp_path / f"mh.host{pid}.bam")
        assert os.path.exists(hp)
        # --trace/--report get the same per-host suffix as the output:
        # pod hosts share storage, a verbatim path would clobber
        assert os.path.exists(f"{trace}.host{pid}")
        assert os.path.exists(f"{report}.host{pid}")
        assert not os.path.exists(trace) and not os.path.exists(report)
        outs.append(hp)
    total = sum(len(read_bam(p)[1]) for p in outs)
    assert total > 0


def test_ranged_checkpoint_not_resumed_across_iterator_flavors(
    tmp_path, monkeypatch
):
    """A RANGED checkpoint manifest written by the native iterator must
    not be resumed by the Python fallback (their chunk boundaries
    differ in range mode); no-range manifests stay interchangeable."""
    from duplexumiconsensusreads_tpu.native import native_available

    if not native_available():
        pytest.skip("native loader unavailable")
    path = _sorted_bam(tmp_path, n_mol=100, n_positions=10)
    idx = build_linear_index(path, every=80)
    rng = host_input_range(idx, process_id=1, num_processes=2)
    assert rng is not None
    gp = GroupingParams(strategy="adjacency", paired=True)
    cp = ConsensusParams(mode="duplex")
    out = str(tmp_path / "r.bam")
    ck = str(tmp_path / "ck.json")
    kw = dict(capacity=128, chunk_reads=80, checkpoint_path=ck)

    rep1 = stream_call_consensus(path, out, gp, cp, input_range=rng, **kw)
    assert rep1.n_chunks > 0
    # same flavor: resume skips everything
    rep2 = stream_call_consensus(
        path, out, gp, cp, input_range=rng, resume=True, **kw
    )
    assert rep2.n_chunks_skipped == rep2.n_chunks > 0
    # other flavor: fingerprint differs -> nothing skipped
    monkeypatch.setenv("DUT_NO_NATIVE", "1")
    rep3 = stream_call_consensus(
        path, out, gp, cp, input_range=rng, resume=True, **kw
    )
    assert rep3.n_chunks_skipped == 0


def test_fallback_range_filtering_matches_native(tmp_path, monkeypatch):
    """DUT_NO_NATIVE range mode must yield the same records (no seek,
    full scan + filter)."""
    from duplexumiconsensusreads_tpu.native import native_available

    if not native_available():
        pytest.skip("native loader unavailable")
    path = _sorted_bam(tmp_path, n_mol=60, n_positions=8)
    idx = build_linear_index(path, every=50)
    rng = host_input_range(idx, process_id=1, num_processes=2)
    assert rng is not None
    start, lo, hi = rng

    def collect():
        return np.concatenate(
            [
                np.asarray(b.pos_key)
                for _, b, _ in iter_batch_chunks(
                    path, 64, duplex=True, start=start, key_lo=lo, key_hi=hi
                )
            ]
        )

    nat = collect()
    monkeypatch.setenv("DUT_NO_NATIVE", "1")
    py = collect()
    np.testing.assert_array_equal(nat, py)
