"""Parity: JAX device kernels vs the NumPy oracle.

Grouping ids must match bit-for-bit (both implementations define dense
ids by the same sorted-key order). Consensus bases must match exactly;
qualities may differ by ±1 on rare float32-vs-float64 rounding
boundaries at the floor() in the Phred conversion.
"""

import numpy as np
import pytest

from duplexumiconsensusreads_tpu.constants import NO_FAMILY
from duplexumiconsensusreads_tpu.kernels import (
    apply_cycle_cap,
    duplex_kernel,
    fit_cycle_cap_kernel,
    group_kernel,
    ssc_kernel,
)
from duplexumiconsensusreads_tpu.oracle import (
    call_consensus,
    fit_cycle_error_model,
    group_reads,
)
from duplexumiconsensusreads_tpu.simulate import SimConfig, simulate_batch, pad_batch
from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams


from duplexumiconsensusreads_tpu.ops.grouper import dense_pos_ids


def _run_group_kernel(batch, params, u_max=None):
    fam, mol, _pair, n_fam, n_mol, n_over = group_kernel(
        dense_pos_ids(batch.pos_key),
        np.asarray(batch.umi),
        np.asarray(batch.strand_ab),
        np.asarray(batch.frag_end),
        np.asarray(batch.valid),
        strategy=params.strategy,
        max_hamming=params.max_hamming,
        count_ratio=params.count_ratio,
        paired=params.paired,
        mate_aware=params.mate_aware,
        u_max=u_max,
    )
    return (
        np.asarray(fam),
        np.asarray(mol),
        int(n_fam),
        int(n_mol),
        int(n_over),
    )


CASES = [
    ("exact_ss", SimConfig(n_molecules=40, duplex=False, seed=10), GroupingParams()),
    (
        "exact_paired",
        SimConfig(n_molecules=30, duplex=True, seed=11),
        GroupingParams(strategy="exact", paired=True),
    ),
    (
        "adj_ss",
        SimConfig(n_molecules=25, duplex=False, umi_error=0.04, mean_family_size=6, seed=12),
        GroupingParams(strategy="adjacency"),
    ),
    (
        "adj_paired",
        SimConfig(n_molecules=20, duplex=True, umi_error=0.03, mean_family_size=5, seed=13),
        GroupingParams(strategy="adjacency", paired=True),
    ),
    (
        "cluster_ss",
        SimConfig(n_molecules=25, duplex=False, umi_error=0.04, mean_family_size=6, seed=14),
        GroupingParams(strategy="cluster"),
    ),
    (
        "cluster_paired",
        SimConfig(n_molecules=20, duplex=True, umi_error=0.03, mean_family_size=5, seed=16),
        GroupingParams(strategy="cluster", paired=True),
    ),
]


@pytest.mark.parametrize("name,cfg,gp", CASES, ids=[c[0] for c in CASES])
def test_grouping_parity(name, cfg, gp):
    batch, _ = simulate_batch(cfg)
    batch = pad_batch(batch, batch.n_reads + 37)  # exercise padding slots
    oracle = group_reads(batch, gp)
    fam, mol, n_fam, n_mol, n_over = _run_group_kernel(batch, gp)
    assert n_over == 0
    assert n_fam == int(oracle.n_families)
    assert n_mol == int(oracle.n_molecules)
    np.testing.assert_array_equal(fam, np.asarray(oracle.family_id))
    np.testing.assert_array_equal(mol, np.asarray(oracle.molecule_id))


def test_grouping_long_umi():
    """UMI pair of 64+ codes must cluster, not raise (regression: the
    bf16 Hamming path once guarded 4*b < 256; with f32 accumulation the
    matmul is exact for any b, so the guard was removed)."""
    cfg = SimConfig(
        n_molecules=12, duplex=True, umi_len=33, umi_error=0.02,
        mean_family_size=4, seed=15,
    )
    batch, _ = simulate_batch(cfg)
    gp = GroupingParams(strategy="adjacency", paired=True)
    oracle = group_reads(batch, gp)
    fam, mol, n_fam, n_mol, n_over = _run_group_kernel(batch, gp)
    assert n_over == 0
    assert n_fam == int(oracle.n_families)
    np.testing.assert_array_equal(fam, np.asarray(oracle.family_id))
    np.testing.assert_array_equal(mol, np.asarray(oracle.molecule_id))


def test_grouping_overflow_flagged():
    cfg = SimConfig(n_molecules=40, duplex=False, seed=14)
    batch, _ = simulate_batch(cfg)
    gp = GroupingParams(strategy="adjacency")
    fam, mol, n_fam, n_mol, n_over = _run_group_kernel(batch, gp, u_max=8)
    assert n_over > 0
    assert (fam[np.asarray(batch.valid)] == NO_FAMILY).sum() == n_over


def _qual_close(q_dev, q_orc, where):
    d = np.abs(q_dev.astype(int) - q_orc.astype(int))[where]
    assert (d <= 1).all(), f"qual diff >1 at {np.argwhere(d > 1)[:5]}"


@pytest.mark.parametrize("method", ["matmul", "segment"])
def test_ssc_parity(method):
    cfg = SimConfig(n_molecules=40, duplex=False, base_error=0.02, n_frac=0.05, seed=15)
    batch, _ = simulate_batch(cfg)
    gp = GroupingParams()
    oracle_f = group_reads(batch, gp)
    cp = ConsensusParams(mode="single_strand", min_reads=2)
    oracle_c = call_consensus(batch, oracle_f, cp)

    f_max = batch.n_reads
    cb, cq, dep, size, fvalid = ssc_kernel(
        np.asarray(batch.bases),
        np.asarray(batch.quals),
        np.asarray(oracle_f.family_id),
        np.asarray(batch.valid),
        f_max=f_max,
        min_reads=cp.min_reads,
        max_qual=cp.max_qual,
        max_input_qual=cp.max_input_qual,
        method=method,
    )
    n_fam = int(oracle_f.n_families)
    cb, cq, dep, fvalid = (
        np.asarray(cb)[:n_fam],
        np.asarray(cq)[:n_fam],
        np.asarray(dep)[:n_fam],
        np.asarray(fvalid)[:n_fam],
    )
    np.testing.assert_array_equal(fvalid, oracle_c.valid)
    np.testing.assert_array_equal(dep[fvalid], oracle_c.depth[fvalid])
    np.testing.assert_array_equal(cb[fvalid], oracle_c.bases[fvalid])
    _qual_close(cq, oracle_c.quals, fvalid[:, None] & np.ones_like(cq, bool))


def test_duplex_parity():
    cfg = SimConfig(n_molecules=50, duplex=True, base_error=0.04, mean_family_size=4, seed=16)
    batch, _ = simulate_batch(cfg)
    gp = GroupingParams(strategy="exact", paired=True)
    fams = group_reads(batch, gp)
    cp = ConsensusParams(mode="duplex", min_reads=1, min_duplex_reads=2)
    oracle_dx = call_consensus(batch, fams, cp)

    f_max = m_max = batch.n_reads
    cb, cq, dep, size, fvalid = ssc_kernel(
        np.asarray(batch.bases),
        np.asarray(batch.quals),
        np.asarray(fams.family_id),
        np.asarray(batch.valid),
        f_max=f_max,
        min_reads=cp.min_reads,
        max_qual=cp.max_qual,
        max_input_qual=cp.max_input_qual,
    )
    db, dq, dd, dvalid = duplex_kernel(
        cb,
        cq,
        dep,
        fvalid,
        np.asarray(fams.family_id),
        np.asarray(fams.molecule_id),
        np.asarray(batch.strand_ab),
        np.asarray(batch.valid),
        m_max=m_max,
        min_duplex_reads=cp.min_duplex_reads,
        max_qual=cp.max_qual,
    )
    n_mol = int(fams.n_molecules)
    db, dq, dd, dvalid = (
        np.asarray(db)[:n_mol],
        np.asarray(dq)[:n_mol],
        np.asarray(dd)[:n_mol],
        np.asarray(dvalid)[:n_mol],
    )
    np.testing.assert_array_equal(dvalid, oracle_dx.valid)
    np.testing.assert_array_equal(db[dvalid], oracle_dx.bases[dvalid])
    np.testing.assert_array_equal(dd[dvalid], oracle_dx.depth[dvalid])
    # duplex quals: sums/differences of ±1-rounded ssc quals → allow ±2
    d = np.abs(dq.astype(int) - oracle_dx.quals.astype(int))[dvalid]
    assert (d <= 2).all()


def test_error_model_parity():
    cfg = SimConfig(
        n_molecules=60,
        duplex=False,
        base_error=0.003,
        cycle_error_slope=0.002,
        mean_family_size=6,
        read_len=60,
        seed=17,
    )
    batch, _ = simulate_batch(cfg)
    fams = group_reads(batch, GroupingParams())
    cp = ConsensusParams(mode="single_strand")
    oracle_c = call_consensus(batch, fams, cp)
    cap_oracle = fit_cycle_error_model(batch, fams, oracle_c)

    f_max = batch.n_reads
    cb, cq, dep, size, fvalid = ssc_kernel(
        np.asarray(batch.bases),
        np.asarray(batch.quals),
        np.asarray(fams.family_id),
        np.asarray(batch.valid),
        f_max=f_max,
        min_reads=cp.min_reads,
        max_qual=cp.max_qual,
        max_input_qual=cp.max_input_qual,
    )
    cap_dev = np.asarray(
        fit_cycle_cap_kernel(
            np.asarray(batch.bases),
            np.asarray(fams.family_id),
            np.asarray(batch.valid),
            cb,
            fvalid,
        )
    )
    assert (np.abs(cap_dev.astype(int) - cap_oracle.astype(int)) <= 1).all()
    q2 = np.asarray(apply_cycle_cap(np.asarray(batch.quals), cap_dev))
    assert (q2 <= np.asarray(batch.quals)).all()


@pytest.mark.parametrize("min_input_qual", [0, 15])
def test_fit_from_counts_bit_identical(min_input_qual):
    """The family-side fit (counts from the ssc GEMM) must equal the
    read-side gather fit BIT-FOR-BIT — including min_input_qual > 0,
    where the consensus argmax excludes sub-threshold reads but the
    mismatch tally must still count them (oracle fit contract)."""
    from duplexumiconsensusreads_tpu.kernels.error_model import (
        fit_cycle_cap_from_counts,
    )

    rng = np.random.default_rng(99)
    r, l, f_max = 300, 40, 64
    bases = rng.integers(0, 6, (r, l)).astype(np.uint8)  # includes N
    quals = rng.integers(2, 41, (r, l)).astype(np.uint8)
    fid = rng.integers(-1, f_max, r).astype(np.int32)
    valid = rng.random(r) < 0.9
    kw = dict(
        f_max=f_max, min_reads=2, max_qual=90, max_input_qual=50,
        min_input_qual=min_input_qual,
    )
    cb0, sz0, fv0, counts0 = ssc_kernel(
        bases, quals, fid, valid, columns="fit_counts", **kw
    )
    _cb_ref, sz_ref, _fv_ref = ssc_kernel(
        bases, quals, fid, valid, columns="fit", **kw
    )
    # NOTE: cb0 vs the plain-fit argmax is NOT asserted bit-wise — the
    # wider column layout can change XLA's f32 reduction tiling, and a
    # last-ulp loglik difference flips evidence-tie argmax cells (same
    # tie-cell caveat the oracle-comparison contract carries). The
    # integer outputs must be exact:
    np.testing.assert_array_equal(np.asarray(sz0), np.asarray(sz_ref))
    # counts columns vs an independent NumPy recount (no qual filter,
    # invalid reads and unassigned families excluded)
    ok = valid & (fid >= 0)
    want = np.zeros((f_max, l, 4), np.int64)
    for i in np.nonzero(ok)[0]:
        for c in range(l):
            if bases[i, c] < 4:
                want[fid[i], c, bases[i, c]] += 1
    np.testing.assert_array_equal(
        np.asarray(counts0).reshape(f_max, l, 4), want
    )
    # given the SAME pass-1 consensus, the two fit formulations must
    # agree bit-for-bit
    cap_counts = np.asarray(fit_cycle_cap_from_counts(cb0, counts0, fv0))
    cap_gather = np.asarray(
        fit_cycle_cap_kernel(bases, fid, valid, cb0, fv0)
    )
    np.testing.assert_array_equal(cap_counts, cap_gather)
