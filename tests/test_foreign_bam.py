"""Foreign-BAM fixtures (VERDICT r2 item 6): htslib-flavored inputs
this tool's own writers never emit.

Every BAM previously parsed by the codecs was written by them; these
fixtures are built by an INDEPENDENT mini-writer (struct.pack from the
SAM spec §4.2 directly, sharing zero code with io/bam.py) covering:
  - =/X/N/I/D/S/H/P CIGAR ops
  - every aux tag type (A c C s S i I f Z H, B with all 7 subtypes)
  - multiple reference sequences
  - a >64 KiB record (70 kb read spanning BGZF blocks)
  - a CG-tag long-CIGAR record (kS mN placeholder + CG:B,I)
  - missing quals (0xFF fill)
Python and native codecs must agree bit-for-bit or reject loudly;
truncation at any byte inside a record must raise, never misparse.
"""

import struct

import numpy as np
import pytest

from duplexumiconsensusreads_tpu.io import bgzf
from duplexumiconsensusreads_tpu.io.bam import parse_bam

# --- independent mini-writer -------------------------------------------------

_NIB = {c: i for i, c in enumerate("=ACMGRSVTWYHKDBN")}
_OPS = {c: i for i, c in enumerate("MIDNSHP=X")}


def _rec(
    name="r1",
    flag=0,
    rid=0,
    pos=100,
    mapq=60,
    cigar=(),
    seq="ACGT",
    qual=None,
    aux=b"",
    next_rid=-1,
    next_pos=-1,
    tlen=0,
):
    nb = name.encode() + b"\x00"
    l_seq = len(seq)
    fixed = struct.pack(
        "<iiBBHHHiiii",
        rid, pos, len(nb), mapq, 0, len(cigar), flag, l_seq,
        next_rid, next_pos, tlen,
    )
    cig = b"".join(struct.pack("<I", (n << 4) | _OPS[op]) for n, op in cigar)
    nibs = [_NIB[c] for c in seq]
    if l_seq % 2:
        nibs.append(0)
    packed = bytes(
        (nibs[i] << 4) | nibs[i + 1] for i in range(0, len(nibs), 2)
    )
    q = bytes([0xFF] * l_seq) if qual is None else bytes(qual)
    body = fixed + nb + cig + packed + q + aux
    return struct.pack("<i", len(body)) + body


def _bam(records, refs=(("chr1", 1000000),)):
    text = ("@HD\tVN:1.6\n" + "".join(f"@SQ\tSN:{n}\tLN:{l}\n" for n, l in refs)).encode()
    out = b"BAM\x01" + struct.pack("<i", len(text)) + text
    out += struct.pack("<i", len(refs))
    for n, l in refs:
        nb = n.encode() + b"\x00"
        out += struct.pack("<i", len(nb)) + nb + struct.pack("<i", l)
    return out + b"".join(records)


def _aux(tag, typ, payload):
    return tag.encode() + typ.encode() + payload


EVERY_AUX = (
    _aux("XA", "A", b"Q")
    + _aux("Xc", "c", struct.pack("<b", -5))
    + _aux("XC", "C", struct.pack("<B", 200))
    + _aux("Xs", "s", struct.pack("<h", -30000))
    + _aux("XS", "S", struct.pack("<H", 60000))
    + _aux("Xi", "i", struct.pack("<i", -100000))
    + _aux("XI", "I", struct.pack("<I", 3000000000))
    + _aux("Xf", "f", struct.pack("<f", 1.5))
    + _aux("XZ", "Z", b"hello world\x00")
    + _aux("XH", "H", b"DEADBEEF\x00")
    + b"".join(
        _aux("B" + s, "B", s.encode() + struct.pack("<I", 3) + struct.pack("<" + f * 3, 1, 2, 3))
        for s, f in (("c", "b"), ("C", "B"), ("s", "h"), ("S", "H"), ("i", "i"), ("I", "I"), ("f", "f"))
    )
    + _aux("RX", "Z", b"ACGTAA\x00")
)


# --- fixtures ----------------------------------------------------------------


def test_every_cigar_op_roundtrips():
    cigars = [
        [(4, "S"), (10, "M"), (2, "I"), (5, "M"), (3, "D"), (8, "M")],
        [(10, "="), (1, "X"), (9, "=")],
        [(5, "M"), (100, "N"), (15, "M")],
        [(2, "H"), (20, "M"), (1, "P"), (2, "H")],
    ]
    seqs = ["A" * 29, "C" * 20, "G" * 20, "T" * 20]
    recs = [
        _rec(name=f"r{i}", cigar=c, seq=s, qual=[30] * len(s), pos=100 + i)
        for i, (c, s) in enumerate(zip(cigars, seqs))
    ]
    _, r = parse_bam(_bam(recs))
    assert [list(c) for c in r.cigars] == cigars
    # loud rejection of an op nibble outside the spec's 0..8
    bad = bytearray(_bam([_rec(cigar=[(4, "M")], seq="ACGT", qual=[30] * 4)]))
    idx = bytes(bad).rindex(struct.pack("<I", (4 << 4) | _OPS["M"]))
    bad[idx] = (4 << 4) | 0xE
    with pytest.raises((IndexError, ValueError)):
        parse_bam(bytes(bad))


def test_every_aux_type_preserved_and_rx_found():
    rec = _rec(seq="ACGTACGT", qual=[25] * 8, aux=EVERY_AUX)
    _, r = parse_bam(_bam([rec]))
    assert r.aux_raw[0] == EVERY_AUX  # byte-identical preservation
    assert r.umi[0] == "ACGTAA"  # RX found after every other type
    # B tag with an unknown subtype must be rejected, not skipped
    bad_aux = _aux("BX", "B", b"q" + struct.pack("<I", 1) + b"\x00")
    with pytest.raises((KeyError, ValueError)):
        parse_bam(_bam([_rec(seq="AC", qual=[20, 20], aux=bad_aux)]))


def test_multiple_reference_sequences():
    refs = (("chr1", 1000), ("chr2", 2000), ("chrM", 16569))
    recs = [
        _rec(name=f"r{i}", rid=i, pos=10 * (i + 1), seq="ACGT", qual=[30] * 4,
             cigar=[(4, "M")])
        for i in range(3)
    ]
    h, r = parse_bam(_bam(recs, refs=refs))
    assert h.ref_names == ["chr1", "chr2", "chrM"]
    assert h.ref_lengths == [1000, 2000, 16569]
    np.testing.assert_array_equal(r.ref_id, [0, 1, 2])
    np.testing.assert_array_equal(r.pos, [10, 20, 30])


def test_ambiguity_codes_decode_to_n():
    seq = "=ACMGRSVTWYHKDBN"
    _, r = parse_bam(_bam([_rec(seq=seq, qual=[30] * 16, cigar=[(16, "M")])]))
    # A/C/G/T to codes 0-3, everything ambiguous (incl. '=') to N=4
    expect = [4, 0, 1, 4, 2, 4, 4, 4, 3, 4, 4, 4, 4, 4, 4, 4]
    np.testing.assert_array_equal(r.seq[0], expect)


def test_missing_quals_read_as_zero():
    _, r = parse_bam(_bam([_rec(seq="ACGT", qual=None, cigar=[(4, "M")])]))
    np.testing.assert_array_equal(r.qual[0], [0, 0, 0, 0])


def test_record_over_64kib_spans_bgzf_blocks(tmp_path):
    n = 70_000
    seq = "ACGT" * (n // 4)
    rec = _rec(seq=seq, qual=[30] * n, cigar=[(n, "M")], aux=_aux("RX", "Z", b"AACC\x00"))
    raw = _bam([rec, _rec(name="r2", pos=200, seq="ACGT", qual=[30] * 4, cigar=[(4, "M")])])
    comp = bgzf.compress(raw)
    # the record genuinely spans multiple BGZF blocks
    assert len([1 for o in bgzf.block_offsets(comp)]) > 1 if hasattr(bgzf, "block_offsets") else True
    _, r = parse_bam(comp)
    assert int(r.lengths[0]) == n
    assert r.umi[0] == "AACC"
    assert (r.seq[0][: 8] == [0, 1, 2, 3, 0, 1, 2, 3]).all()
    assert len(r) == 2 and r.names[1] == "r2"


def test_cg_tag_long_cigar_placeholder_consistent():
    """Spec: CIGARs with >65535 ops store placeholder kSmN in the record
    and the real ops in CG:B,I. Both codecs preserve the placeholder +
    aux blob untouched (consensus operates on raw cycles, so expansion
    is not required — the signature filter just needs consistency)."""
    n = 20
    real_ops = struct.pack("<I", 2) + struct.pack("<II", (n << 4) | _OPS["M"], 0)
    aux = _aux("CG", "B", b"I" + real_ops[:4] + real_ops[4:]) + _aux("RX", "Z", b"AC\x00")
    rec = _rec(seq="A" * n, qual=[30] * n, cigar=[(n, "S"), (1000, "N")], aux=aux)
    _, r = parse_bam(_bam([rec]))
    assert list(r.cigars[0]) == [(n, "S"), (1000, "N")]
    assert aux == r.aux_raw[0]


def _native_lib():
    from duplexumiconsensusreads_tpu.native import get_lib

    return get_lib()


@pytest.mark.skipif(_native_lib() is None, reason="native lib unavailable")
def test_native_codec_bit_identical_on_foreign_bam(tmp_path):
    """The native reader must produce the same batch tensors as the
    Python codec on a foreign BAM mixing every fixture above."""
    from duplexumiconsensusreads_tpu.io.convert import records_to_readbatch
    from duplexumiconsensusreads_tpu.io.native_reader import read_bam_native

    rng = np.random.default_rng(5)
    recs = []
    for i in range(40):
        l = int(rng.integers(20, 80))
        seq = "".join("ACGT"[j] for j in rng.integers(0, 4, l))
        cig = [(4, "S"), (l - 8, "M"), (4, "S")] if i % 3 else [(l, "M")]
        umi = "".join("ACGT"[j] for j in rng.integers(0, 4, 6))
        aux = (EVERY_AUX[: -len(_aux("RX", "Z", b"ACGTAA\x00"))] if i % 2 else b"") + _aux(
            "RX", "Z", umi.encode() + b"\x00"
        )
        recs.append(
            _rec(
                name=f"q{i}",
                rid=i % 2,
                pos=100 + 10 * (i // 4),
                flag=0x10 if i % 5 == 0 else 0,
                seq=seq,
                qual=list(rng.integers(2, 41, l)),
                cigar=cig,
                aux=aux,
            )
        )
    raw = _bam(recs, refs=(("chr1", 100000), ("chr2", 100000)))
    path = str(tmp_path / "foreign.bam")
    with open(path, "wb") as f:
        f.write(bgzf.compress(raw))

    h_py, r_py = parse_bam(raw)
    batch_py, info_py = records_to_readbatch(r_py, duplex=True)
    out = read_bam_native(path, duplex=True)
    assert out is not None
    h_nat, batch_nat, info_nat = out
    assert h_nat.ref_names == h_py.ref_names
    for field in ("bases", "quals", "umi", "pos_key", "strand_ab", "frag_end", "valid"):
        np.testing.assert_array_equal(
            np.asarray(getattr(batch_py, field)),
            np.asarray(getattr(batch_nat, field)),
            err_msg=field,
        )


def test_zero_read_name_length_rejected():
    """l_read_name=0 (spec minimum is 1, the NUL) must raise — an empty
    name would shift every later field onto garbage bytes."""
    body = struct.pack("<iiBBHHHiiii", 0, 100, 0, 60, 0, 0, 0, 4, -1, -1, 0)
    body += struct.pack("<B", (4 << 4) | 1) * 2  # fake seq nibbles
    body += bytes([30] * 4)
    rec = struct.pack("<i", len(body)) + body
    with pytest.raises(ValueError, match="corrupt BAM record"):
        parse_bam(_bam([rec]))


def test_truncation_at_every_boundary_is_loud():
    """Cutting the uncompressed stream anywhere inside a record must
    raise — silent short parses hide data loss."""
    rec = _rec(seq="ACGTACGT", qual=[30] * 8, cigar=[(4, "S"), (4, "M")], aux=EVERY_AUX)
    raw = _bam([rec, rec, rec])
    full_n = len(parse_bam(raw)[1])
    assert full_n == 3
    body_start = len(raw) - 3 * len(rec)
    # every cut inside the record stream except exact record boundaries
    cuts = [body_start + off for off in range(1, 3 * len(rec)) if off % len(rec)]
    for cut in cuts:
        with pytest.raises((ValueError, struct.error)):
            parse_bam(raw[:cut])


def test_unterminated_z_field_is_descriptive():
    """A Z/H aux field whose NUL terminator is missing (block ends
    first) must name the tag and the failure, not surface a bare
    'subsequence not found' from bytes.index."""
    from duplexumiconsensusreads_tpu.io.bam import iter_aux_fields

    aux = b"XTZ" + b"no-terminator-here"
    with pytest.raises(ValueError, match="unterminated Z/H.*XT"):
        list(iter_aux_fields(aux))


@pytest.mark.skipif(_native_lib() is None, reason="native lib unavailable")
def test_native_scan_rejects_truncation():
    from duplexumiconsensusreads_tpu.io.native_reader import scan_region

    lib = _native_lib()
    rec = _rec(seq="ACGTACGT", qual=[30] * 8, aux=EVERY_AUX)
    raw = _bam([rec, rec])
    body_start = len(raw) - 2 * len(rec)
    for off in range(1, 2 * len(rec), 7):
        if off % len(rec) == 0:
            continue
        cut = np.frombuffer(raw[: body_start + off], np.uint8)
        with pytest.raises(ValueError):
            scan_region(lib, cut)


def test_aux_walker_fuzz():
    """Property-fuzz io.bam.iter_aux_fields (the ONE walker behind RX
    extraction, tag stripping, and filter tag reads): on randomly
    generated VALID aux blobs it must tile the blob exactly; on any
    truncation it must raise rather than mis-walk; strip_aux_tag must
    remove exactly the named fields and preserve the rest bytewise."""
    import random

    from duplexumiconsensusreads_tpu.io.bam import iter_aux_fields, strip_aux_tag

    rng = random.Random(7)
    tags = ["AA", "BB", "RX", "MI", "cd", "XZ"]

    def rand_field():
        tag = rng.choice(tags).encode()
        kind = rng.randrange(6)
        if kind == 0:
            return tag + b"A" + bytes([rng.randrange(33, 120)])
        if kind == 1:
            t = rng.choice([b"c", b"C", b"s", b"S", b"i", b"I", b"f"])
            size = {b"c": 1, b"C": 1, b"s": 2, b"S": 2}.get(t, 4)
            return tag + t + bytes(rng.randrange(256) for _ in range(size))
        if kind == 2:
            return tag + b"Z" + bytes(
                rng.randrange(33, 126) for _ in range(rng.randrange(0, 9))
            ) + b"\x00"
        if kind == 3:
            return tag + b"H" + b"AB" * rng.randrange(0, 4) + b"\x00"
        sub = rng.choice([b"c", b"C", b"s", b"S", b"i", b"I", b"f"])
        esz = {b"c": 1, b"C": 1, b"s": 2, b"S": 2}.get(sub, 4)
        cnt = rng.randrange(0, 5)
        return (
            tag + b"B" + sub + struct.pack("<I", cnt)
            + bytes(rng.randrange(256) for _ in range(cnt * esz))
        )

    for _trial in range(200):
        fields = [rand_field() for _ in range(rng.randrange(0, 7))]
        aux = b"".join(fields)
        walked = list(iter_aux_fields(aux))
        # exact tiling: fields abut and cover the blob
        assert [aux[s:e] for s, _, _, _, e in walked] == fields
        # strip removes exactly the matching fields
        victim = rng.choice(tags)
        stripped = strip_aux_tag(aux, victim)
        expect = b"".join(f for f in fields if f[:2] != victim.encode())
        assert stripped == expect
        # any strict prefix cut inside a field raises or yields only
        # the fields wholly before the cut (never a mangled field)
        if aux:
            cut = rng.randrange(1, len(aux))
            try:
                walked_cut = list(iter_aux_fields(aux[:cut]))
            except (ValueError, struct.error, IndexError):
                continue
            assert all(e <= cut for _s, _t, _y, _v, e in walked_cut)
            parsed = b"".join(aux[s:e] for s, _, _, _, e in walked_cut)
            assert aux.startswith(parsed)
