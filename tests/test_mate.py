"""Mate-aware paired-end consensus (VERDICT r2 item 1).

Contracts pinned here:
- with no second-end reads, mate-aware grouping/consensus is
  BIT-IDENTICAL to classic grouping (safe-by-construction auto mode);
- kernel == oracle on true paired-mate simulations;
- single-strand mate-aware calling equals the split-by-read-number
  workflow exactly;
- duplex mate-aware calling pairs top-R1 with bottom-R2 (fgbio
  pairing): both mates' consensus validate against their own
  fragment-end truth, and NOT running mate-aware on the same input is
  measurably catastrophic;
- emission re-links consensus R1/R2 mates as proper pairs;
- CLI auto-resolution: on for mixed-mate input (no warning), off (and
  loudly warned) when forced off; streaming == whole-file.
"""

import json

import numpy as np
import pytest

from duplexumiconsensusreads_tpu.cli import main
from duplexumiconsensusreads_tpu.io import read_bam, simulated_bam
from duplexumiconsensusreads_tpu.io.bam import (
    FLAG_PAIRED,
    FLAG_READ1,
    FLAG_READ2,
)
from duplexumiconsensusreads_tpu.oracle import group_reads
from duplexumiconsensusreads_tpu.runtime.executor import (
    call_batch_cpu,
    call_batch_tpu,
    resolve_mate_aware,
)
from duplexumiconsensusreads_tpu.simulate import SimConfig, simulate_batch
from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams

PAIRED_CFG = SimConfig(
    n_molecules=60,
    read_len=40,
    n_positions=8,
    mean_family_size=4,
    duplex=True,
    paired_reads=True,
    umi_error=0.02,
    seed=21,
)


def _sorted_rows(t):
    from duplexumiconsensusreads_tpu.utils.phred import umi_sort_keys

    cb, cq, cd, _, fp, fu = t[:6]
    order = np.lexsort((*reversed(umi_sort_keys(fu)), fp))
    return cb[order], cq[order], cd[order], fp[order], fu[order]


# ---------------------------------------------------------------- identity

@pytest.mark.parametrize("strategy", ["exact", "adjacency"])
@pytest.mark.parametrize("paired", [True, False])
def test_no_second_end_bitwise_identity(strategy, paired):
    """mate_aware on a batch with NO second-end reads must reproduce
    classic grouping bit-for-bit (family AND molecule ids) — the
    property that makes auto mode safe."""
    cfg = SimConfig(n_molecules=50, duplex=True, umi_error=0.02, seed=5)
    batch, _ = simulate_batch(cfg)
    assert not np.asarray(batch.frag_end).any()
    for mate_aware in (False, True):
        gp = GroupingParams(strategy=strategy, paired=paired, mate_aware=mate_aware)
        fams = group_reads(batch, gp)
        if not mate_aware:
            base = fams
        else:
            np.testing.assert_array_equal(base.family_id, fams.family_id)
            np.testing.assert_array_equal(base.molecule_id, fams.molecule_id)
            assert int(base.n_families) == int(fams.n_families)
            assert int(base.n_molecules) == int(fams.n_molecules)


def test_no_second_end_consensus_identity():
    cfg = SimConfig(n_molecules=40, duplex=True, umi_error=0.02, seed=6)
    batch, _ = simulate_batch(cfg)
    cp = ConsensusParams(mode="duplex")
    outs = []
    for mate_aware in (False, True):
        gp = GroupingParams(strategy="adjacency", paired=True, mate_aware=mate_aware)
        outs.append(call_batch_tpu(batch, gp, cp, capacity=256))
    for a, b in zip(_sorted_rows(outs[0]), _sorted_rows(outs[1])):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------- parity

@pytest.mark.parametrize("strategy", ["exact", "adjacency"])
def test_kernel_matches_oracle_on_paired_mates(strategy):
    batch, _ = simulate_batch(PAIRED_CFG)
    gp = GroupingParams(strategy=strategy, paired=True, mate_aware=True)
    from duplexumiconsensusreads_tpu.ops import UmiGrouper

    f_cpu = group_reads(batch, gp)
    f_tpu = UmiGrouper(gp, backend="tpu")(batch)
    np.testing.assert_array_equal(
        np.asarray(f_cpu.family_id), np.asarray(f_tpu.family_id)
    )
    np.testing.assert_array_equal(
        np.asarray(f_cpu.molecule_id), np.asarray(f_tpu.molecule_id)
    )
    np.testing.assert_array_equal(
        np.asarray(f_cpu.pair_id), np.asarray(f_tpu.pair_id)
    )
    assert int(f_cpu.n_families) == int(f_tpu.n_families)
    assert int(f_cpu.n_molecules) == int(f_tpu.n_molecules)


def test_duplex_pipeline_matches_oracle_on_paired_mates():
    batch, _ = simulate_batch(PAIRED_CFG)
    gp = GroupingParams(strategy="adjacency", paired=True, mate_aware=True)
    cp = ConsensusParams(mode="duplex")
    t = call_batch_tpu(batch, gp, cp, capacity=256)
    c = call_batch_cpu(batch, gp, cp)
    assert len(t[0]) == len(c[0]) > 0
    ts, cs = _sorted_rows(t), _sorted_rows(c)
    np.testing.assert_array_equal(ts[0], cs[0])  # bases
    np.testing.assert_array_equal(ts[3], cs[3])  # pos
    np.testing.assert_array_equal(ts[4], cs[4])  # umi
    dq = np.abs(ts[1].astype(int) - cs[1].astype(int))
    assert (dq <= 3).all() and (dq <= 1).mean() > 0.97


# ------------------------------------------------------------- semantics

def test_units_pair_top_r1_with_bottom_r2():
    """The fgbio pairing, checked structurally: within one molecule,
    the end-1 unit's reads are exactly {top-R1, bottom-R2}."""
    batch, truth = simulate_batch(PAIRED_CFG)
    gp = GroupingParams(strategy="exact", paired=True, mate_aware=True)
    fams = group_reads(batch, gp)
    mol = np.asarray(fams.molecule_id)
    s = np.asarray(batch.strand_ab, bool)
    e2 = np.asarray(batch.frag_end, bool)
    r2 = e2 ^ ~s  # read number, by the frag_end definition
    for unit in np.unique(mol[mol >= 0])[:50]:
        sel = mol == unit
        # one fragment end per unit
        assert len(np.unique(e2[sel])) == 1
        # within the unit: top-strand reads are R1 iff end1, bottom are R2
        if not e2[sel][0]:
            assert not r2[sel][s[sel]].any()  # top reads are R1
            assert r2[sel][~s[sel]].all() or (~s[sel]).sum() == 0  # bottom are R2
        else:
            assert r2[sel][s[sel]].all() or s[sel].sum() == 0
            assert not r2[sel][~s[sel]].any()


def test_ss_mate_aware_equals_split_by_readnumber(tmp_path):
    """Single-strand mate-aware calling on a mixed-mate BAM must be
    bit-equal to the split-by-read-number-then-call workflow.

    Exact grouping only: under ADJACENCY grouping the two workflows
    legitimately differ, because mate-aware clustering sees the whole
    molecule's UMI counts (both mates aggregate, the fgbio
    template-level view) while the split workflow clusters each mate's
    half-counts separately — directional merge decisions can then
    diverge. That difference is by design, not drift."""
    bam = str(tmp_path / "in.bam")
    simulated_bam(PAIRED_CFG, path=bam, sort=True)
    header, recs = read_bam(bam)

    flags = np.asarray(recs.flags)
    cp = ConsensusParams(mode="single_strand")
    gp_split = GroupingParams(strategy="exact", paired=True)

    # split workflow: R1-only and R2-only calls with classic grouping
    from duplexumiconsensusreads_tpu.cli.main import _take_records
    from duplexumiconsensusreads_tpu.io.convert import records_to_readbatch

    split_rows = []
    for want in (FLAG_READ1, FLAG_READ2):
        sub = _take_records(recs, np.nonzero(flags & want)[0])
        b, _ = records_to_readbatch(sub, duplex=True)
        split_rows.append(_sorted_rows(call_batch_tpu(b, gp_split, cp, capacity=256)))

    # mate-aware call on the full mixed input
    gp_mate = GroupingParams(strategy="exact", paired=True, mate_aware=True)
    full_b, info = records_to_readbatch(recs, duplex=True, warn_mixed=False)
    assert info["mixed_mates"]
    full = _sorted_rows(call_batch_tpu(full_b, gp_mate, cp, capacity=256))

    # a molecule emits several ss rows sharing (pos, UMI), so compare
    # as multisets of full row content rather than by ambiguous sort
    def rowset(parts):
        return sorted(
            (int(parts[3][i]), parts[4][i].tobytes(), parts[0][i].tobytes(),
             parts[1][i].tobytes(), parts[2][i].tobytes())
            for i in range(len(parts[0]))
        )

    merged = [np.concatenate([a, b]) for a, b in zip(*split_rows)]
    assert len(full[0]) == len(merged[0]) > 0
    assert rowset(full) == rowset(merged)


def test_duplex_mate_aware_validates_against_both_truths():
    """Duplex mate-aware consensus: every emitted row matches ITS
    fragment end's true sequence at a tiny error rate — and the same
    input called WITHOUT mate-aware is catastrophically wrong."""
    cfg = SimConfig(
        n_molecules=80, read_len=40, n_positions=8, mean_family_size=5,
        duplex=True, paired_reads=True, base_error=0.01, seed=22,
    )
    batch, truth = simulate_batch(cfg)
    cp = ConsensusParams(mode="duplex")

    def error_rate(mate_aware):
        gp = GroupingParams(
            strategy="exact", paired=True, mate_aware=mate_aware
        )
        cb, cq, cd, cv, fp, fu, mate, pair, _end = call_batch_tpu(
            batch, gp, cp, capacity=512
        )
        # map each output row to its truth molecule via (pos, umi)
        key_to_mol = {
            (int(truth.mol_pos_key[m]), truth.mol_umi[m].tobytes()): m
            for m in range(len(truth.mol_seq))
        }
        errs = bases = n_r1 = n_r2 = 0
        for i in range(len(cb)):
            m = key_to_mol[(int(fp[i]), fu[i].tobytes())]
            true = truth.mol_seq2[m] if mate[i] else truth.mol_seq[m]
            real = cb[i] != 4
            errs += int((cb[i][real] != true[real]).sum())
            bases += int(real.sum())
            n_r1 += int(mate[i] == 0)
            n_r2 += int(mate[i] == 1)
        return errs / max(bases, 1), n_r1, n_r2, len(cb)

    rate_on, n_r1, n_r2, n_rows = error_rate(True)
    assert n_r1 > 0 and n_r2 > 0
    assert rate_on < 1e-3, rate_on
    # without mate-awareness the mixed families average two different
    # true sequences: both mates' columns are wrong ~at random
    rate_off, _, _, _ = error_rate(False)
    assert rate_off > 0.2, rate_off


# -------------------------------------------------------------- emission

def test_cli_mate_aware_end_to_end(tmp_path, capsys, recwarn):
    """simulate --paired-reads → call (auto) → validate: R1+R2 pairs
    out, both mates truth-validated, auto-on resolution, no warning."""
    bam = str(tmp_path / "in.bam")
    truth = str(tmp_path / "t.npz")
    out = str(tmp_path / "o.bam")
    rep_path = str(tmp_path / "rep.json")
    assert main(
        ["simulate", "-o", bam, "--truth", truth, "--molecules", "150",
         "--read-len", "50", "--positions", "16", "--family-size", "5",
         "--paired-reads", "--umi-error", "0.02", "--sorted", "--seed", "31"]
    ) == 0
    assert main(
        ["call", bam, "-o", out, "--config", "config3", "--capacity", "512",
         "--report", rep_path]
    ) == 0
    rep = json.load(open(rep_path))
    assert rep["mate_aware"] is True
    assert rep["n_consensus_pairs"] > 0
    assert not [w for w in recwarn if "R1 and R2" in str(w.message)]

    _, recs = read_bam(out)
    from duplexumiconsensusreads_tpu.io.bam import FLAG_PROPER_PAIR

    flags = np.asarray(recs.flags)
    pp = FLAG_PAIRED | FLAG_PROPER_PAIR
    r1 = (flags & (pp | FLAG_READ1)) == (pp | FLAG_READ1)
    r2 = (flags & (pp | FLAG_READ2)) == (pp | FLAG_READ2)
    assert r1.sum() == r2.sum() == rep["n_consensus_pairs"] > 0
    # paired records come with mate pointers at the shared position and
    # a qname shared by exactly the two mates
    names = np.asarray(recs.names)
    for i in np.nonzero(r1)[0][:20]:
        j = np.nonzero(names == names[i])[0]
        assert len(j) == 2
        other = j[j != i][0]
        assert r2[other]
        assert recs.pos[i] == recs.next_pos[i] == recs.pos[other]

    assert main(["validate", out, "--truth", truth, "--json"]) == 0
    res = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert res["n_consensus_pairs"] == rep["n_consensus_pairs"]
    assert res["n_matched_to_truth"] > 0.9 * res["n_consensus"]
    assert res["error_rate"] < 1e-3


def test_pair_links_survive_class_dispatch():
    """Pair keys must be unique across DISPATCH CLASSES, not just
    within one scatter call (regression: per-class bucket offsets
    restarted at 0, colliding unrelated molecules into 4-row groups
    that failed pair completeness — most pairs silently demoted to
    singletons)."""
    cfg = SimConfig(
        n_molecules=120, read_len=32, n_positions=40, mean_family_size=4,
        duplex=True, paired_reads=True, umi_error=0.02, seed=13,
    )
    batch, _ = simulate_batch(cfg)
    gp = GroupingParams(strategy="adjacency", paired=True, mate_aware=True)
    cp = ConsensusParams(mode="duplex")
    # small capacity -> many buckets across several size classes
    t = call_batch_tpu(batch, gp, cp, capacity=128)
    c = call_batch_cpu(batch, gp, cp)

    def n_pairs(parts):
        pair, mate = parts[7], parts[6]
        vals, cnt = np.unique(pair[pair >= 0], return_counts=True)
        n = 0
        for v, k in zip(vals, cnt):
            if k == 2 and set(mate[pair == v]) == {0, 1}:
                n += 1
        return n

    assert n_pairs(t) == n_pairs(c) > 0


def test_cli_mate_aware_off_warns(tmp_path):
    bam = str(tmp_path / "in.bam")
    out = str(tmp_path / "o.bam")
    assert main(
        ["simulate", "-o", bam, "--molecules", "40", "--read-len", "30",
         "--paired-reads", "--sorted", "--seed", "3"]
    ) == 0
    with pytest.warns(UserWarning, match="R1 and R2 mates"):
        main(["call", bam, "-o", out, "--config", "config3",
              "--capacity", "256", "--mate-aware", "off"])


def test_stream_matches_wholefile_on_paired_input(tmp_path):
    cfg = SimConfig(
        n_molecules=120, read_len=36, n_positions=24, duplex=True,
        paired_reads=True, umi_error=0.02, seed=17,
    )
    bam = str(tmp_path / "in.bam")
    simulated_bam(cfg, path=bam, sort=True)
    whole = str(tmp_path / "whole.bam")
    streamed = str(tmp_path / "stream.bam")
    assert main(
        ["call", bam, "-o", whole, "--config", "config3", "--capacity", "256"]
    ) == 0
    assert main(
        ["call", bam, "-o", streamed, "--config", "config3",
         "--capacity", "256", "--chunk-reads", "300"]
    ) == 0
    _, a = read_bam(whole)
    _, b = read_bam(streamed)
    assert len(a) == len(b) > 0
    # same records modulo name prefixes and ordering: compare by
    # (pos, RX, mate flag) -> sequence/quals
    def rows(recs):
        flags = np.asarray(recs.flags)
        out = {}
        for i in range(len(recs)):
            key = (int(recs.pos[i]), recs.umi[i], bool(flags[i] & FLAG_READ2))
            assert key not in out
            out[key] = (recs.seq[i].tobytes(), recs.qual[i].tobytes())
        return out

    ra, rb = rows(a), rows(b)
    assert ra.keys() == rb.keys()
    mismatch = sum(1 for k in ra if ra[k] != rb[k])
    assert mismatch == 0
    # both emitted true pairs
    fl = np.asarray(a.flags)
    assert ((fl & FLAG_PAIRED) != 0).sum() > 0


def test_classic_paired_end_flags_stay_single(tmp_path):
    """Classic one-read-per-strand F1R2/F2R1 input carries both R1 and
    R2 FLAGS, but no family mixes fragment ends — auto must resolve
    OFF and emission must keep plain single-end consensus records
    (regression: flag-presence detection turned mate-aware on and gave
    every record spurious PAIRED|MATE_UNMAPPED flags)."""
    bam = str(tmp_path / "in.bam")
    out = str(tmp_path / "o.bam")
    rep_path = str(tmp_path / "rep.json")
    cfg = SimConfig(n_molecules=40, read_len=30, duplex=True, seed=12)
    simulated_bam(cfg, path=bam, sort=True, paired_end=True)
    assert main(
        ["call", bam, "-o", out, "--config", "config3", "--capacity", "256",
         "--report", rep_path]
    ) == 0
    rep = json.load(open(rep_path))
    assert rep["mate_aware"] is False
    _, recs = read_bam(out)
    assert (np.asarray(recs.flags) == 0).all()


def test_split_by_readnumber_input_resolves_off(tmp_path):
    """An R1-only file (the split workflow) HAS second-end reads
    (bottom-strand R1 covers fragment end 2), but no family mixes ends
    — auto must resolve OFF so classic duplex strand pairing still
    applies."""
    from duplexumiconsensusreads_tpu.cli.main import _take_records
    from duplexumiconsensusreads_tpu.io.bam import write_bam

    bam = str(tmp_path / "in.bam")
    simulated_bam(PAIRED_CFG, path=bam, sort=True)
    header, recs = read_bam(bam)
    r1_only = _take_records(
        recs, np.nonzero(np.asarray(recs.flags) & FLAG_READ1)[0]
    )
    split = str(tmp_path / "r1.bam")
    write_bam(split, header, r1_only)
    out = str(tmp_path / "o.bam")
    rep_path = str(tmp_path / "rep.json")
    assert main(
        ["call", split, "-o", out, "--config", "config3", "--capacity", "256",
         "--report", rep_path]
    ) == 0
    rep = json.load(open(rep_path))
    assert rep["mate_aware"] is False
    assert rep["n_consensus"] > 0  # classic strand pairing still produced calls


def test_ss_unpaired_mate_aware_pairs_by_fragment_end(tmp_path, capsys):
    """--mode ss (unpaired grouping) on true mate-pair input: families
    are (molecule, fragment end) and can mix strands, so rows are
    labeled by fragment end — R1/R2 pairs still form and validate
    against the right truth (regression: the read-number label was not
    constant within a family and pairing silently never completed)."""
    bam = str(tmp_path / "in.bam")
    truth = str(tmp_path / "t.npz")
    out = str(tmp_path / "o.bam")
    rep_path = str(tmp_path / "rep.json")
    assert main(
        ["simulate", "-o", bam, "--truth", truth, "--molecules", "80",
         "--read-len", "40", "--positions", "8", "--family-size", "5",
         "--paired-reads", "--sorted", "--seed", "41"]
    ) == 0
    assert main(
        ["call", bam, "-o", out, "--mode", "ss", "--grouping", "exact",
         "--capacity", "512", "--report", rep_path]
    ) == 0
    rep = json.load(open(rep_path))
    assert rep["mate_aware"] is True
    assert rep["n_consensus_pairs"] > 0
    assert main(["validate", out, "--truth", truth, "--json"]) == 0
    res = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    # ss with min_reads=1 keeps singleton families, so a few 1e-2-ish
    # columns survive; the guarded failure mode (R2 rows validated
    # against the WRONG end's truth) would read ~0.5, not <1e-2
    assert res["error_rate"] < 1e-2


def test_resumed_stream_reports_pairs(tmp_path):
    """n_consensus_pairs is counted from shard bytes at finalise, so a
    fully-resumed run reports the same pair count as the original."""
    from duplexumiconsensusreads_tpu.runtime.stream import stream_call_consensus

    bam = str(tmp_path / "in.bam")
    simulated_bam(PAIRED_CFG, path=bam, sort=True)
    gp = GroupingParams(strategy="adjacency", paired=True)
    cp = ConsensusParams(mode="duplex")
    ck = str(tmp_path / "ck.json")
    rep1 = stream_call_consensus(
        bam, str(tmp_path / "o1.bam"), gp, cp, capacity=256,
        chunk_reads=300, checkpoint_path=ck,
    )
    assert rep1.mate_aware and rep1.n_consensus_pairs > 0
    rep2 = stream_call_consensus(
        bam, str(tmp_path / "o2.bam"), gp, cp, capacity=256,
        chunk_reads=300, checkpoint_path=ck, resume=True,
    )
    assert rep2.n_chunks_skipped == rep2.n_chunks > 0
    assert rep2.n_consensus_pairs == rep1.n_consensus_pairs
    assert rep2.n_consensus == rep1.n_consensus


def test_resolve_mate_aware_settings():
    gp = GroupingParams(paired=True)
    assert resolve_mate_aware(gp, {"mixed_mates": True}, "auto").mate_aware
    assert not resolve_mate_aware(gp, {"mixed_mates": False}, "auto").mate_aware
    assert not resolve_mate_aware(gp, {}, "auto").mate_aware
    assert resolve_mate_aware(gp, {}, "on").mate_aware
    assert not resolve_mate_aware(gp, {"mixed_mates": True}, "off").mate_aware
    with pytest.raises(ValueError):
        resolve_mate_aware(gp, {}, "bogus")


def test_npz_backward_compat(tmp_path):
    """Pre-mate-aware npz files (no frag_end array) still load."""
    from duplexumiconsensusreads_tpu.io.npz import load_readbatch

    cfg = SimConfig(n_molecules=10, seed=1)
    batch, _ = simulate_batch(cfg)
    p = str(tmp_path / "old.npz")
    with open(p, "wb") as f:
        np.savez_compressed(
            f,
            **{
                k: np.asarray(getattr(batch, k))
                for k in ("bases", "quals", "umi", "pos_key", "strand_ab", "valid")
            },
        )
    b = load_readbatch(p)
    assert not np.asarray(b.frag_end).any()
    np.testing.assert_array_equal(b.bases, np.asarray(batch.bases))
