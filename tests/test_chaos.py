"""Chaos suite: deterministic fault injection against the streaming
executor (runtime/faults.py).

Every recovery claim is pinned to the STRONGEST observable contract:
after injected transient faults the final BAM is byte-identical to the
fault-free run, and after an injected hard kill at each phase boundary
a resume=True rerun converges to the same bytes. A corrupted shard
under resume must be caught by the manifest size+CRC verification and
recomputed, never spliced.

All schedules are seeded/explicit, so every failure here replays
identically. The suite is deliberately small and fast (tier-1, not
slow): one shared simulated input, one shared fault-free reference.
"""

import json
import os
import time
import zlib

import pytest

# the autouse fixture no-ops time.sleep (retry backoff); the
# out-of-order drain tests need a REAL sleep to stagger worker
# completion, captured before any patching
_REAL_SLEEP = time.sleep

from duplexumiconsensusreads_tpu.io import read_bam, simulated_bam
from duplexumiconsensusreads_tpu.runtime import faults
from duplexumiconsensusreads_tpu.runtime.stream import stream_call_consensus
from duplexumiconsensusreads_tpu.simulate import SimConfig
from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams

pytestmark = pytest.mark.chaos

GP = GroupingParams(strategy="adjacency", paired=True)
CP = ConsensusParams(mode="duplex")
KW = dict(capacity=128, chunk_reads=90)


@pytest.fixture(scope="module")
def sim(tmp_path_factory):
    """(input path, fault-free reference output bytes) — computed once;
    every chaos run must reproduce these bytes exactly."""
    d = tmp_path_factory.mktemp("chaos")
    path = str(d / "in.bam")
    cfg = SimConfig(n_molecules=70, n_positions=9, umi_error=0.02, seed=31)
    simulated_bam(cfg, path=path, sort=True)
    ref = str(d / "ref.bam")
    rep = stream_call_consensus(path, ref, GP, CP, **KW)
    assert rep.n_chunks >= 3  # enough phase-boundary hits for every nth below
    with open(ref, "rb") as f:
        return path, f.read()


@pytest.fixture(scope="module")
def serve_ref(sim, tmp_path_factory):
    """Fault-free reference bytes for SERVICE-run jobs: same input and
    params as ``sim``, but carrying the canonical serve provenance line
    (service outputs embed the config-derived @PG CL, not argv)."""
    from duplexumiconsensusreads_tpu.serve.job import serve_provenance

    path, _ = sim
    d = tmp_path_factory.mktemp("chaos_serve")
    ref = str(d / "serve_ref.bam")
    config = dict(
        grouping="adjacency", mode="duplex",
        capacity=KW["capacity"], chunk_reads=KW["chunk_reads"],
    )
    stream_call_consensus(
        path, ref, GP, CP, provenance_cl=serve_provenance(config), **KW
    )
    with open(ref, "rb") as f:
        return f.read()


@pytest.fixture(autouse=True)
def _no_sleep_and_clean_plan(monkeypatch):
    # retries back off via stream.time.sleep; don't spend wall time on it
    monkeypatch.setattr(
        "duplexumiconsensusreads_tpu.runtime.stream.time.sleep",
        lambda s: None,
    )
    yield
    faults.uninstall()


class TestPlanParsing:
    def test_parse_and_seeded_replay(self):
        p1 = faults.FaultPlan.parse("seed:1234:6")
        p2 = faults.FaultPlan.parse("seed:1234:6")
        assert p1.schedule == p2.schedule  # seeded schedules replay identically
        p3 = faults.FaultPlan.parse("shard.write:2:enospc,ckpt.save:1:kill")
        assert p3.schedule["shard.write"][2] == "enospc"
        assert p3.schedule["ckpt.save"][1] == "kill"

    def test_parse_rejects_garbage(self):
        for bad in (
            "bogus.site:1:oserror",
            "shard.write:0:oserror",
            "shard.write:1:frobnicate",
            "shard.write:1",
        ):
            with pytest.raises(ValueError):
                faults.FaultPlan.parse(bad)

    def test_env_malformed_spec_names_the_var(self, monkeypatch):
        monkeypatch.setenv("DUT_FAULTS", "shard.write:1")
        faults.uninstall()
        with pytest.raises(ValueError, match="DUT_FAULTS"):
            faults.install_from_env()

    def test_fault_point_is_noop_when_uninstalled(self):
        faults.uninstall()
        faults.fault_point("shard.write")  # must not raise or count

    def test_fires_exactly_once_per_entry(self):
        plan = faults.FaultPlan.parse("shard.write:2:oserror")
        faults.install(plan)
        faults.fault_point("shard.write")
        with pytest.raises(faults.InjectedFault):
            faults.fault_point("shard.write")
        faults.fault_point("shard.write")  # hit 3: schedule exhausted
        assert plan.n_fired == 1 and plan.hits("shard.write") == 3


@pytest.mark.parametrize("site", faults.KNOWN_SITES)
def test_transient_fault_at_each_site_byte_identical(
    site, sim, serve_ref, tmp_path
):
    """One seeded transient fault at each named site: the run must
    absorb it through its retry/isolation ladders and produce a final
    BAM byte-identical to the fault-free run. The serve.* sites live in
    the serving layer, so they are driven through a two-job service
    pass over the same input (equal priorities + chunk_budget=1 forces
    the preempt path every slice; the second job is SHARDED so the
    scatter-gather sites serve.split/serve.merge fire in every pass);
    the stream sites keep the direct streaming run."""
    path, ref_bytes = sim
    plan = faults.FaultPlan.seeded(
        zlib.crc32(site.encode()), sites=(site,), n_faults=1, max_nth=1
    )
    faults.install(plan)
    if site.startswith("serve."):
        from duplexumiconsensusreads_tpu.serve import ConsensusService, client

        spool = str(tmp_path / "spool")
        config = dict(
            grouping="adjacency", mode="duplex",
            capacity=KW["capacity"], chunk_reads=KW["chunk_reads"],
        )
        outs = [str(tmp_path / f"out{i}.bam") for i in (1, 2)]
        client.submit(spool, path, outs[0], config=config)
        client.submit(spool, path, outs[1], config=config, shards=2)
        ConsensusService(spool, chunk_budget=1).run_until_idle()
        assert plan.n_fired >= 1  # the schedule really injected
        for o in outs:
            with open(o, "rb") as f:
                assert f.read() == serve_ref
        return
    if site.startswith("live."):
        # live.* sites exist only on the follow path: tail the already-
        # finished input (the tailer terminates on its BGZF EOF block)
        # with a snapshot every chunk, so live.snapshot publishes — and
        # absorbs its transient — on every commit, not just at the end.
        # The follow A/B contract makes the batch reference the oracle.
        out = str(tmp_path / "live.bam")
        stream_call_consensus(
            path, out, GP, CP, follow=True, live_poll_s=0.01,
            snapshot_chunks=1, **KW
        )
        assert plan.n_fired >= 1  # the schedule really injected
        with open(out, "rb") as f:
            assert f.read() == ref_bytes
        return
    out = str(tmp_path / "out.bam")
    stream_call_consensus(path, out, GP, CP, **KW)
    assert plan.n_fired >= 1  # the schedule really injected
    with open(out, "rb") as f:
        assert f.read() == ref_bytes


def test_seeded_multi_fault_schedule_byte_identical(sim, tmp_path):
    """A seeded schedule spraying several transient faults across sites
    mid-run still converges to the reference bytes."""
    path, ref_bytes = sim
    plan = faults.FaultPlan.seeded(20260803, n_faults=8)
    faults.install(plan)
    out = str(tmp_path / "multi.bam")
    stream_call_consensus(path, out, GP, CP, **KW)
    assert plan.n_fired >= 1
    with open(out, "rb") as f:
        assert f.read() == ref_bytes


# the phase boundaries of the write/recover spine. With the pipelined
# drain, finalise is INCREMENTAL: finalise.write hits happen mid-run
# (header write + per-shard appends into out.tmp, in frontier order)
# and the terminal EOF/fsync/rename hits come last:
#   shard.write:1    killed during the first shard write (tmp only —
#                    the durable rename never happened), on a drain
#                    worker; the kill must surface through the future
#   ckpt.save:2      post-shard-write, pre-mark persist (save 1 is the
#                    manifest clear in the run preamble)
#   finalise.write:1 killed writing the tmp's header — chunk 0 was
#                    already durably marked (mark precedes append)
#   finalise.write:2 mid-incremental-finalise: out.tmp partially
#                    assembled, a prefix of chunks durable
BOUNDARY_KILLS = [
    ("shard.write", 1),
    ("ckpt.save", 2),
    ("finalise.write", 1),
    ("finalise.write", 2),
    # wire-diet v2 sites: killed inside the host-side H2D pack on an
    # xfer worker (surfaces through the dispatch future) and inside the
    # packed-D2H unpack on a drain worker — both before anything of the
    # chunk is durable, so resume recomputes it
    ("dispatch.pack", 2),
    ("fetch.unpack", 2),
    # pipelined-ingest site: killed at the producer thread's 2nd queue
    # handoff (default ingest_overlap=auto runs the background producer)
    # — the kill must cross the thread boundary and surface on the main
    # loop as the same typed exception, with nothing durable yet for
    # chunks the consumer never committed, so resume recomputes exactly
    # the missing suffix
    ("ingest.queue", 2),
    # live-snapshot site: killed publishing the first partial snapshot
    # (the publish runs AFTER the chunk's checkpoint mark is durable,
    # so resume skips the chunk and republishes the snapshot)
    ("live.snapshot", 1),
]

# per-site kwargs that make a boundary site reachable at all: snapshot
# publishing only happens when snapshot_chunks > 0 (applied to the kill
# run AND the resume, which must also clean the snapshot artifacts up)
_BOUNDARY_KILL_KW = {
    "live.snapshot": {"snapshot_chunks": 1},
}


@pytest.mark.parametrize("site,nth", BOUNDARY_KILLS)
def test_kill_at_phase_boundary_then_resume_converges(site, nth, sim, tmp_path):
    path, ref_bytes = sim
    out = str(tmp_path / "k.bam")
    kw = {**KW, **_BOUNDARY_KILL_KW.get(site, {})}
    faults.install(faults.FaultPlan.parse(f"{site}:{nth}:kill"))
    with pytest.raises(faults.InjectedKill):
        stream_call_consensus(path, out, GP, CP, **kw)
    faults.uninstall()
    # atomic finalise: no half-written BAM may be visible at the real
    # path after ANY kill — resume decides from the manifest alone
    assert not os.path.exists(out)
    rep = stream_call_consensus(path, out, GP, CP, resume=True, **kw)
    if site == "finalise.write":
        # finalise.write fires only at commit time, and the commit
        # marks BEFORE it appends — so at least the frontier chunk was
        # durable and resume must skip it
        assert rep.n_chunks_skipped >= 1
    with open(out, "rb") as f:
        assert f.read() == ref_bytes
    assert not os.path.exists(out + ".ckpt")  # auto-ckpt cleaned on success
    if site == "live.snapshot":
        # snapshot side artifacts are working state, cleaned with the ckpt
        assert not os.path.exists(out + ".snapshot.bam")
        assert not os.path.exists(out + ".snapshot.bam.bai")


def test_resume_refuses_runtime_codec_fallback_shards(sim, tmp_path, monkeypatch):
    """ROADMAP item (PR 3 review): native and pure-Python BGZF deflate
    emit different (both valid) bytes, and ``compress_fast`` falls back
    to Python SILENTLY when the native compress fails at runtime — so a
    python-deflate shard could ride under a ``deflate:native``
    fingerprint, and a later resume on a healthy-native host would
    splice mixed-codec shards. The manifest now records the codec
    actually used per shard; resume must prune and recompute those
    shards, converging to the reference bytes."""
    from duplexumiconsensusreads_tpu import native
    from duplexumiconsensusreads_tpu.io import bgzf

    path, ref_bytes = sim
    out = str(tmp_path / "codec.bam")
    # both runs fingerprint deflate:native (capability probe says yes),
    # whatever this container actually has built
    monkeypatch.setattr(bgzf, "native_compress_capable", lambda: True)
    monkeypatch.delenv("DUT_NO_NATIVE", raising=False)

    # run 1: the native compress entry point fails AT RUNTIME (after
    # the successful probe) -> every shard silently falls back to the
    # pure-Python codec; a kill at the chunk-1 mark leaves chunk 0
    # durably marked with its real codec
    with pytest.MonkeyPatch.context() as mp:
        mp.setattr(native, "bgzf_compress_native", lambda *a, **k: None)
        faults.install(faults.FaultPlan.parse("ckpt.save:3:kill"))
        with pytest.raises(faults.InjectedKill):
            stream_call_consensus(path, out, GP, CP, **KW)
    faults.uninstall()
    with open(out + ".ckpt") as f:
        manifest = json.load(f)
    assert manifest["done"], "kill must land after at least one mark"
    assert {e["codec"] for e in manifest["done"].values()} == {"python"}

    # run 2: healthy-native resume — the python-deflate shards fail the
    # manifest codec check, are recomputed (never spliced), and the
    # output is byte-identical to the fault-free reference
    rep = stream_call_consensus(path, out, GP, CP, resume=True, **KW)
    assert rep.n_chunks_skipped == 0
    with open(out, "rb") as f:
        assert f.read() == ref_bytes


@pytest.mark.parametrize("damage", ["flip", "truncate"])
def test_corrupted_shard_detected_and_recomputed(damage, sim, tmp_path):
    """Resume against a deliberately corrupted shard: the manifest
    size+CRC verification must drop the entry and recompute the chunk,
    not splice the bad bytes into the output."""
    path, ref_bytes = sim
    out = str(tmp_path / "c.bam")
    ck = str(tmp_path / "ck.json")  # explicit checkpoint: shards survive
    stream_call_consensus(path, out, GP, CP, checkpoint_path=ck, **KW)
    with open(ck) as f:
        manifest = json.load(f)
    entry = manifest["done"]["0"]
    assert {"path", "size", "crc32"} <= set(entry)
    assert entry["size"] > 0
    if damage == "flip":
        # size unchanged: only the CRC can catch this
        with open(entry["path"], "r+b") as f:
            f.seek(entry["size"] // 2)
            b = f.read(1)
            f.seek(entry["size"] // 2)
            f.write(bytes([b[0] ^ 0xFF]))
    else:
        with open(entry["path"], "r+b") as f:
            f.truncate(entry["size"] // 2)
    rep = stream_call_consensus(
        path, out, GP, CP, checkpoint_path=ck, resume=True, **KW
    )
    assert rep.n_chunks_skipped == rep.n_chunks - 1  # only chunk 0 recomputed
    with open(out, "rb") as f:
        assert f.read() == ref_bytes


@pytest.mark.parametrize(
    "garbage",
    ['{"fingerprint": "x", "done"', "[1, 2]", "", '{"done": null}'],
)
def test_torn_manifest_discarded_not_fatal(garbage, sim, tmp_path):
    """A torn/garbage checkpoint manifest (crash mid-write where the
    rename wasn't durable, external corruption) must be discarded and
    the run recomputed — never a JSON traceback that needs a manual
    rm of the .ckpt."""
    path, ref_bytes = sim
    out = str(tmp_path / "t.bam")
    ck = str(tmp_path / "ck.json")
    with open(ck, "w") as f:
        f.write(garbage)
    rep = stream_call_consensus(
        path, out, GP, CP, checkpoint_path=ck, resume=True, **KW
    )
    assert rep.n_chunks_skipped == 0  # nothing trustworthy to skip
    with open(out, "rb") as f:
        assert f.read() == ref_bytes
    with open(ck) as f:
        assert len(json.load(f)["done"]) == rep.n_chunks  # manifest healed


def test_env_var_activates_schedule(sim, tmp_path, monkeypatch):
    """DUT_FAULTS installs a fresh plan (fresh counters) per run."""
    path, ref_bytes = sim
    monkeypatch.setenv("DUT_FAULTS", "shard.write:1:enospc")
    out = str(tmp_path / "env.bam")
    stream_call_consensus(path, out, GP, CP, **KW)
    plan = faults.get_active()
    assert plan is not None and plan.n_fired == 1
    with open(out, "rb") as f:
        assert f.read() == ref_bytes


def test_cli_chaos_flag(sim, tmp_path, monkeypatch):
    """`call --chaos` wires a schedule through the CLI; a bad schedule
    is a clean CLI error."""
    from duplexumiconsensusreads_tpu.cli import main

    path, ref_bytes = sim
    out = str(tmp_path / "cli.bam")
    # a stale env schedule must NOT override the explicit flag
    monkeypatch.setenv("DUT_FAULTS", "shard.write:1:kill")
    rc = main(
        ["call", path, "-o", out, "--config", "config3", "--capacity", "128",
         "--chunk-reads", "90", "--chaos", "fetch.result:1:oserror"]
    )
    assert rc == 0
    plan = faults.get_active()
    assert plan is not None and plan.n_fired == 1
    assert plan.spec == "fetch.result:1:oserror"
    with open(out, "rb") as f:
        assert f.read() == ref_bytes
    with pytest.raises(SystemExit, match="--chaos"):
        main(
            ["call", path, "-o", out, "--chunk-reads", "90",
             "--chaos", "nope:1:oserror"]
        )
    # only the streaming executor threads the fault sites — on the
    # whole-file path the flag would be silently inert
    with pytest.raises(SystemExit, match="--chunk-reads"):
        main(["call", path, "-o", out, "--chaos", "fetch.result:1:oserror"])


def _force_reverse_drain(monkeypatch, order_log=None):
    """Delay _finish_chunk so drain workers complete early chunks LAST
    (chunk 0 slowest): with a wide pool, completion order inverts chunk
    order and the ordered frontier is what must restore it."""
    import duplexumiconsensusreads_tpu.runtime.stream as stream_mod

    real = stream_mod._finish_chunk

    def reordering(k, *a, **kw):
        _REAL_SLEEP(0.45 * max(0, 3 - k))
        res = real(k, *a, **kw)
        if order_log is not None:
            order_log.append(k)
        return res

    monkeypatch.setattr(stream_mod, "_finish_chunk", reordering)


OOO_KW = dict(capacity=128, chunk_reads=90, drain_workers=4, max_inflight=4)


def test_out_of_order_drain_byte_identical_marks_in_order(
    sim, tmp_path, monkeypatch
):
    """Drain workers forced to finish chunks in reverse order: output
    bytes must be identical to the serial reference and checkpoint
    marks must still be committed strictly in chunk order (the
    ordered-completion frontier)."""
    import duplexumiconsensusreads_tpu.runtime.stream as stream_mod

    path, ref_bytes = sim
    done_order: list = []
    _force_reverse_drain(monkeypatch, done_order)
    marks: list = []
    real_mark = stream_mod.Checkpoint.mark

    def recording_mark(self, chunk, *a, **kw):
        marks.append(chunk)
        return real_mark(self, chunk, *a, **kw)

    monkeypatch.setattr(stream_mod.Checkpoint, "mark", recording_mark)
    out = str(tmp_path / "ooo.bam")
    rep = stream_call_consensus(path, out, GP, CP, **OOO_KW)
    assert rep.n_chunks >= 3
    # the delays really inverted completion order...
    assert done_order != sorted(done_order)
    # ...yet marks landed strictly in chunk order, gap-free
    assert marks == list(range(rep.n_chunks))
    with open(out, "rb") as f:
        assert f.read() == ref_bytes


def test_kill_mid_out_of_order_drain_then_resume_converges(
    sim, tmp_path, monkeypatch
):
    """A hard kill at the new drain.scatter site while workers are
    completing out of order: on-disk state is whatever prefix the
    frontier made durable, and --resume must converge to the reference
    bytes (extends the boundary-kill matrix to the pipelined drain)."""
    path, ref_bytes = sim
    _force_reverse_drain(monkeypatch)
    out = str(tmp_path / "oookill.bam")
    faults.install(faults.FaultPlan.parse("drain.scatter:2:kill"))
    with pytest.raises(faults.InjectedKill):
        stream_call_consensus(path, out, GP, CP, **OOO_KW)
    faults.uninstall()
    assert not os.path.exists(out)  # rename is still terminal-only
    rep = stream_call_consensus(path, out, GP, CP, resume=True, **OOO_KW)
    assert rep.n_chunks >= 3
    with open(out, "rb") as f:
        assert f.read() == ref_bytes
    assert not os.path.exists(out + ".ckpt")


def test_enospc_fails_only_the_victim_job_service_survives(
    sim, serve_ref, tmp_path
):
    """Disk-pressure acceptance: a real ENOSPC surfacing from a durable
    write inside one job (here: every retry of its first shard write)
    must fail THAT job cleanly — durable reason in results/, daemon
    alive — while every other job completes byte-identical. The repo's
    pre-defensive behaviour on persistent write errors was a daemon
    that either died or retried forever; this pins the degradation
    contract instead."""
    from duplexumiconsensusreads_tpu.serve import ConsensusService, client

    path, _ = sim
    spool = str(tmp_path / "spool")
    config = dict(
        grouping="adjacency", mode="duplex",
        capacity=KW["capacity"], chunk_reads=KW["chunk_reads"],
    )
    # one past the host-I/O retry budget, so the ladder really
    # exhausts and surfaces ENOSPC instead of absorbing it; a single
    # drain worker keeps every hit on ONE chunk's ladder (two workers
    # would interleave hit counts across chunks and could let both
    # ladders squeak through)
    schedule = ",".join(f"shard.write:{n}:enospc" for n in range(1, 6))
    victim = client.submit(
        spool, path, str(tmp_path / "victim.bam"),
        config={**config, "drain_workers": 1}, chaos=schedule,
    )
    healthy = client.submit(
        spool, path, str(tmp_path / "healthy.bam"), config=config
    )
    svc = ConsensusService(spool, chunk_budget=0)
    snap = svc.run_until_idle()  # must return, not raise: daemon alive
    assert snap["jobs_failed"] == 1 and snap["jobs_done"] == 1
    st = client.status(spool, victim)
    assert st["state"] == "failed"
    assert "enospc" in st["error"].lower()
    # the reason is durable beyond the journal: the results/ file holds it
    with open(os.path.join(spool, "results", victim + ".json")) as f:
        assert "enospc" in json.load(f)["error"].lower()
    assert not os.path.exists(str(tmp_path / "victim.bam"))
    with open(str(tmp_path / "healthy.bam"), "rb") as f:
        assert f.read() == serve_ref


def test_ingest_retry_is_bounded(sim, tmp_path):
    """More consecutive transient failures than the retry budget at one
    site must surface the error, not loop forever."""
    path, _ = sim
    spec = ",".join(f"ingest.read:{n}:oserror" for n in range(1, 6))
    faults.install(faults.FaultPlan.parse(spec))
    with pytest.raises(OSError, match="injected"):
        stream_call_consensus(path, str(tmp_path / "x.bam"), GP, CP, **KW)
