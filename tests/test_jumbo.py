"""Oversized position groups and jumbo families (VERDICT r1 item 4).

A position group larger than the bucket capacity must not change
adjacency results: the bucketing layer host-preclusters the group with
the oracle's directional algorithm, relabels member UMIs to the cluster
seed, and dispatches those buckets through exact grouping. A single
family larger than the capacity gets its own jumbo pow2-capacity
bucket. Both paths must match the oracle bit-for-bit (quals within the
usual f32 tolerance).
"""

import warnings

import numpy as np
import pytest

from duplexumiconsensusreads_tpu.bucketing import build_buckets
from duplexumiconsensusreads_tpu.runtime.executor import (
    call_batch_cpu,
    call_batch_tpu,
)
from duplexumiconsensusreads_tpu.simulate import SimConfig, simulate_batch
from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams
from duplexumiconsensusreads_tpu.utils.phred import pack_umi_words64


def _sorted_by_key(cb, cq, cd, fp, fu):
    order = np.lexsort(
        (
            *[
                pack_umi_words64(fu)[:, i]
                for i in range(pack_umi_words64(fu).shape[1] - 1, -1, -1)
            ],
            fp,
        )
    )
    return cb[order], cq[order], cd[order], fp[order], fu[order]


def _assert_tpu_matches_cpu(batch, gp, cp, capacity):
    t = call_batch_tpu(batch, gp, cp, capacity=capacity)
    c = call_batch_cpu(batch, gp, cp)
    tb, tq, td, tp_, tu = _sorted_by_key(t[0], t[1], t[2], t[4], t[5])
    ob, oq, od, op_, ou = _sorted_by_key(c[0], c[1], c[2], c[4], c[5])
    assert len(tb) == len(ob), (len(tb), len(ob))
    np.testing.assert_array_equal(tp_, op_)
    np.testing.assert_array_equal(tu, ou)
    np.testing.assert_array_equal(tb, ob)
    np.testing.assert_array_equal(td, od)
    dq = np.abs(tq.astype(int) - oq.astype(int))
    assert (dq <= 3).all()
    assert (dq <= 1).mean() > 0.97


def test_oversized_position_group_adjacency_matches_oracle():
    """One position group ~3x the capacity, adjacency + duplex: results
    must equal the oracle's (the old family-boundary split could not
    merge UMIs across the split)."""
    cfg = SimConfig(
        n_molecules=220,
        n_positions=2,
        mean_family_size=4,
        umi_error=0.04,
        duplex=True,
        seed=42,
    )
    batch, _ = simulate_batch(cfg)
    gp = GroupingParams(strategy="adjacency", paired=True)
    cp = ConsensusParams(mode="duplex", min_duplex_reads=1)
    capacity = 256
    # precondition: at least one position group really is oversized
    pos = np.asarray(batch.pos_key)[np.asarray(batch.valid, bool)]
    assert np.unique(pos, return_counts=True)[1].max() > 3 * capacity

    with warnings.catch_warnings():
        warnings.simplefilter("error")  # the old path warned; new one must not
        _assert_tpu_matches_cpu(batch, gp, cp, capacity)


def test_oversized_group_buckets_are_preclustered():
    cfg = SimConfig(
        n_molecules=150, n_positions=1, umi_error=0.03, duplex=True, seed=5
    )
    batch, _ = simulate_batch(cfg)
    gp = GroupingParams(strategy="adjacency", paired=True)
    buckets = build_buckets(batch, capacity=128, grouping=gp)
    assert any(b.preclustered for b in buckets)
    for b in buckets:
        assert b.capacity >= 128
        assert b.capacity & (b.capacity - 1) == 0 or b.capacity == 128


def test_jumbo_family_exact_matches_oracle():
    """A single exact family far larger than the capacity must produce
    ONE consensus (jumbo bucket), identical to the oracle, instead of
    being hard-cut into several partial families."""
    rng = np.random.default_rng(11)
    n, l, u = 700, 40, 6
    from duplexumiconsensusreads_tpu.types import ReadBatch

    seq = rng.integers(0, 4, size=l, dtype=np.uint8)
    batch = ReadBatch(
        bases=np.tile(seq, (n, 1)),
        quals=rng.integers(20, 40, size=(n, l), dtype=np.uint8),
        umi=np.tile(rng.integers(0, 4, size=u, dtype=np.uint8), (n, 1)),
        pos_key=np.full(n, 5000, np.int64),
        strand_ab=np.ones(n, bool),
        frag_end=np.zeros(n, bool),
        valid=np.ones(n, bool),
    )
    # sprinkle errors so consensus actually has work to do
    err = rng.random((n, l)) < 0.05
    batch.bases[err] = (batch.bases[err] + 1) % 4

    gp = GroupingParams(strategy="exact")
    cp = ConsensusParams(mode="single_strand", min_reads=2)
    capacity = 256

    buckets = build_buckets(batch, capacity=capacity, grouping=gp)
    assert len(buckets) == 1
    assert buckets[0].capacity == 1024  # pow2(700)

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        t = call_batch_tpu(batch, gp, cp, capacity=capacity)
    c = call_batch_cpu(batch, gp, cp)
    assert len(t[0]) == len(c[0]) == 1
    np.testing.assert_array_equal(t[0], c[0])
    np.testing.assert_array_equal(t[2], c[2])


def test_jumbo_cluster_adjacency_duplex():
    """An adjacency cluster larger than capacity (post-relabel family)
    routes through a preclustered jumbo bucket and still matches the
    oracle."""
    rng = np.random.default_rng(13)
    from duplexumiconsensusreads_tpu.types import ReadBatch

    n, l, u = 600, 32, 12
    seed_umi = rng.integers(0, 4, size=u, dtype=np.uint8)
    umi = np.tile(seed_umi, (n, 1))
    # ~15% of reads carry a 1-off UMI (adjacency should fold them in)
    off = rng.random(n) < 0.15
    col = rng.integers(0, u, size=n)
    umi[off, col[off]] = (umi[off, col[off]] + 1) % 4
    seq = rng.integers(0, 4, size=l, dtype=np.uint8)
    batch = ReadBatch(
        bases=np.tile(seq, (n, 1)),
        quals=rng.integers(20, 40, size=(n, l), dtype=np.uint8),
        umi=umi,
        pos_key=np.full(n, 9000, np.int64),
        strand_ab=rng.random(n) < 0.5,
        frag_end=np.zeros(n, bool),
        valid=np.ones(n, bool),
    )
    gp = GroupingParams(strategy="adjacency", paired=True)
    cp = ConsensusParams(mode="duplex", min_duplex_reads=1)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        _assert_tpu_matches_cpu(batch, gp, cp, capacity=256)


def test_precluster_fallback_does_not_duplicate_reads(monkeypatch):
    """When an oversized group exceeds PRECLUSTER_MAX_UNIQUE (warned
    fallback), the following plain groups must not re-emit the group's
    reads (regression: a skipped range reset once merged them into the
    next plain bucket)."""
    import duplexumiconsensusreads_tpu.bucketing.buckets as bmod
    from duplexumiconsensusreads_tpu.types import ReadBatch

    monkeypatch.setattr(bmod, "PRECLUSTER_MAX_UNIQUE", 4)
    rng = np.random.default_rng(3)
    n1, n2, l, u = 40, 10, 16, 6
    batch = ReadBatch(
        bases=rng.integers(0, 4, size=(n1 + n2, l), dtype=np.uint8),
        quals=np.full((n1 + n2, l), 30, np.uint8),
        umi=rng.integers(0, 4, size=(n1 + n2, u), dtype=np.uint8),
        pos_key=np.r_[np.full(n1, 1000, np.int64), np.full(n2, 2000, np.int64)],
        strand_ab=np.ones(n1 + n2, bool),
        frag_end=np.zeros(n1 + n2, bool),
        valid=np.ones(n1 + n2, bool),
    )
    gp = GroupingParams(strategy="adjacency", paired=True)
    counters: dict = {}
    with pytest.warns(UserWarning, match="precluster limit"):
        buckets = build_buckets(batch, capacity=16, grouping=gp, counters=counters)
    seen = np.concatenate([bk.read_index[bk.read_index >= 0] for bk in buckets])
    assert len(seen) == n1 + n2
    assert len(np.unique(seen)) == n1 + n2  # every read exactly once
    # the result-changing fallback must be tallied, not just warned about
    assert counters["n_precluster_fallback_groups"] == 1
    assert counters["n_precluster_fallback_reads"] == n1
    assert "n_jumbo_hardcut_families" not in counters


def test_fallback_counters_in_report(monkeypatch):
    """VERDICT r2 item 7: every result-changing fallback lands a
    RunReport counter — jumbo hard-cuts here (with the duplicate
    per-split records they emit), and zero on a standard workload."""
    import duplexumiconsensusreads_tpu.bucketing.buckets as bmod
    from duplexumiconsensusreads_tpu.runtime.executor import RunReport
    from duplexumiconsensusreads_tpu.types import ReadBatch

    rng = np.random.default_rng(7)
    n, l, u = 600, 24, 6
    batch = ReadBatch(
        bases=np.tile(rng.integers(0, 4, size=l, dtype=np.uint8), (n, 1)),
        quals=np.full((n, l), 30, np.uint8),
        umi=np.tile(rng.integers(0, 4, size=u, dtype=np.uint8), (n, 1)),
        pos_key=np.full(n, 5000, np.int64),
        strand_ab=np.ones(n, bool),
        frag_end=np.zeros(n, bool),
        valid=np.ones(n, bool),
    )
    gp = GroupingParams(strategy="exact")
    cp = ConsensusParams(mode="single_strand")
    # jumbo limit = capacity*64; capacity=4 -> limit 256, family of 600
    # reads is hard-cut into 3 pieces, each emitting its own consensus
    rep = RunReport()
    with pytest.warns(UserWarning, match="jumbo bucket limit"):
        t = call_batch_tpu(batch, gp, cp, capacity=4, report=rep)
    assert rep.n_jumbo_hardcut_families == 1
    assert rep.n_jumbo_hardcut_splits == 3
    assert len(t[0]) == 3  # the duplicate per-split records, tallied
    assert rep.n_precluster_fallback_groups == 0

    # standard workload: all fallback counters must stay zero
    cfg = SimConfig(n_molecules=120, duplex=True, umi_error=0.02, seed=5)
    sim_batch, _ = simulate_batch(cfg)
    rep2 = RunReport()
    call_batch_tpu(
        sim_batch,
        GroupingParams(strategy="adjacency", paired=True),
        ConsensusParams(mode="duplex"),
        capacity=512,
        report=rep2,
    )
    for k in bmod.FALLBACK_COUNTERS:
        assert getattr(rep2, k) == 0, k


@pytest.mark.parametrize("chunk_reads", [200])
def test_streaming_oversized_group_matches_whole_file(tmp_path, chunk_reads):
    """Streaming path with an oversized position group: output must
    equal the whole-file path's."""
    from duplexumiconsensusreads_tpu.cli.main import main as cli_main

    cfg_args = [
        "simulate",
        "--out",
        str(tmp_path / "in.bam"),
        "--molecules",
        "160",
        "--positions",
        "2",
        "--umi-error",
        "0.03",
        "--sorted",
        "--seed",
        "9",
    ]
    assert cli_main(cfg_args) == 0
    common = [
        "--config",
        "config3",
        "--backend",
        "tpu",
        "--capacity",
        "128",
    ]
    assert (
        cli_main(
            [
                "call",
                str(tmp_path / "in.bam"),
                "--out",
                str(tmp_path / "whole.bam"),
                *common,
            ]
        )
        == 0
    )
    assert (
        cli_main(
            [
                "call",
                str(tmp_path / "in.bam"),
                "--out",
                str(tmp_path / "stream.bam"),
                "--chunk-reads",
                str(chunk_reads),
                *common,
            ]
        )
        == 0
    )
    from duplexumiconsensusreads_tpu.io import read_bam

    _, rw = read_bam(str(tmp_path / "whole.bam"))
    _, rs = read_bam(str(tmp_path / "stream.bam"))
    assert len(rw) == len(rs)
    np.testing.assert_array_equal(rw.pos, rs.pos)
    np.testing.assert_array_equal(rw.seq, rs.seq)
    np.testing.assert_array_equal(rw.qual, rs.qual)


def test_oversized_position_group_cluster_matches_oracle():
    """The same oversized-group precluster path under the CLUSTER
    strategy: the host precluster must use the effective (zeroed) count
    ratio, or cross-piece components the directional condition would
    reject stay split — oracle parity catches it."""
    cfg = SimConfig(
        n_molecules=200,
        n_positions=2,
        mean_family_size=4,
        umi_error=0.04,
        duplex=True,
        seed=43,
    )
    batch, _ = simulate_batch(cfg)
    gp = GroupingParams(strategy="cluster", paired=True)
    cp = ConsensusParams(mode="duplex", min_duplex_reads=1)
    capacity = 256
    pos = np.asarray(batch.pos_key)[np.asarray(batch.valid, bool)]
    assert np.unique(pos, return_counts=True)[1].max() > 3 * capacity
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        _assert_tpu_matches_cpu(batch, gp, cp, capacity)
