"""IO layer tests: BGZF codec, BAM parse/serialize roundtrip, and the
BamRecords ↔ ReadBatch conversion contract (strand derivation, UMI
canonicalisation, pos_key packing)."""

import numpy as np
import pytest

from duplexumiconsensusreads_tpu.io import bgzf
from duplexumiconsensusreads_tpu.io.bam import (
    FLAG_PAIRED,
    FLAG_READ1,
    FLAG_READ2,
    FLAG_REVERSE,
    BamHeader,
    parse_bam,
    read_bam,
    serialize_bam,
    write_bam,
)
from duplexumiconsensusreads_tpu.io.convert import (
    pack_pos_key,
    read_is_top_strand,
    readbatch_to_records,
    records_to_readbatch,
    simulated_bam,
    unpack_pos_key,
)
from duplexumiconsensusreads_tpu.io.npz import load_readbatch, save_readbatch
from duplexumiconsensusreads_tpu.simulate import SimConfig, simulate_batch


class TestBgzf:
    def test_roundtrip_small(self):
        data = b"hello bgzf world" * 100
        assert bgzf.decompress(bgzf.compress(data)) == data

    def test_roundtrip_multiblock(self):
        rng = np.random.default_rng(0)
        data = rng.integers(0, 256, size=200_000, dtype=np.uint8).tobytes()
        comp = bgzf.compress(data)
        assert bgzf.decompress(comp) == data
        # must be multiple independent blocks + EOF marker
        offsets = list(bgzf.iter_block_offsets(comp))
        assert len(offsets) >= 4
        assert comp.endswith(bgzf.BGZF_EOF)

    def test_per_block_decompress_matches(self):
        data = bytes(range(256)) * 1000
        comp = bgzf.compress(data)
        joined = b"".join(
            bgzf.decompress_block(comp, off, size)
            for off, size in bgzf.iter_block_offsets(comp)
        )
        assert joined == data

    def test_is_bgzf(self):
        assert bgzf.is_bgzf(bgzf.compress(b"x"))
        assert not bgzf.is_bgzf(b"plainly not gzip")
        import gzip

        assert not bgzf.is_bgzf(gzip.compress(b"x"))  # gzip but not BGZF

    def test_empty(self):
        assert bgzf.decompress(bgzf.compress(b"")) == b""


class TestBamRoundtrip:
    def test_simulated_roundtrip(self, tmp_path):
        path = str(tmp_path / "sim.bam")
        header, recs, batch, _ = simulated_bam(
            SimConfig(n_molecules=20, duplex=True, seed=3), path=path
        )
        header2, recs2 = read_bam(path)
        assert header2.ref_names == header.ref_names
        assert header2.ref_lengths == header.ref_lengths
        assert recs2.names == recs.names
        np.testing.assert_array_equal(recs2.flags, recs.flags)
        np.testing.assert_array_equal(recs2.pos, recs.pos)
        np.testing.assert_array_equal(recs2.seq, recs.seq)
        np.testing.assert_array_equal(recs2.qual, recs.qual)
        assert recs2.umi == recs.umi
        assert recs2.cigars == recs.cigars
        assert recs2.aux_raw == recs.aux_raw

    def test_batch_conversion_roundtrip(self, tmp_path):
        """BAM → ReadBatch must invert ReadBatch → BAM exactly."""
        cfg = SimConfig(n_molecules=30, duplex=True, umi_error=0.02, seed=11)
        batch, _ = simulate_batch(cfg)
        recs = readbatch_to_records(batch, duplex=True)
        batch2, info = records_to_readbatch(recs, duplex=True)
        assert info["n_valid"] == int(np.asarray(batch.valid).sum())
        np.testing.assert_array_equal(batch2.bases, np.asarray(batch.bases))
        np.testing.assert_array_equal(batch2.quals, np.asarray(batch.quals))
        np.testing.assert_array_equal(batch2.umi, np.asarray(batch.umi))
        np.testing.assert_array_equal(batch2.strand_ab, np.asarray(batch.strand_ab))
        # pos_key is re-packed (ref<<36|pos); ordering/grouping structure
        # must be preserved even though raw values differ
        _, inv1 = np.unique(np.asarray(batch.pos_key), return_inverse=True)
        _, inv2 = np.unique(batch2.pos_key, return_inverse=True)
        np.testing.assert_array_equal(inv1, inv2)

    def test_uncompressed_parse(self):
        header, recs, *_ = simulated_bam(SimConfig(n_molecules=5, seed=1))
        raw = serialize_bam(header, recs)
        header2, recs2 = parse_bam(raw)  # raw (non-BGZF) BAM also parses
        assert recs2.names == recs.names

    def test_dropped_reads(self, tmp_path):
        header, recs, *_ = simulated_bam(SimConfig(n_molecules=5, seed=2))
        recs.umi[0] = ""  # no RX
        recs.aux_raw[0] = b""
        recs.umi[1] = "NNN-ACG"  # N in UMI
        batch, info = records_to_readbatch(recs, duplex=True)
        assert info["n_dropped_no_umi"] == 2  # N-containing → unparseable too
        assert not batch.valid[0] and not batch.valid[1]
        assert batch.valid[2:].all()


class TestFlagFiltering:
    def test_excluded_flags_marked_invalid(self):
        from duplexumiconsensusreads_tpu.io.bam import (
            FLAG_DUP,
            FLAG_SECONDARY,
            FLAG_SUPPLEMENTARY,
            FLAG_UNMAPPED,
        )

        header, recs, *_ = simulated_bam(SimConfig(n_molecules=8, seed=4))
        recs.flags[0] |= FLAG_SECONDARY
        recs.flags[1] |= FLAG_SUPPLEMENTARY
        recs.flags[2] |= FLAG_UNMAPPED
        recs.flags[3] |= FLAG_DUP  # duplicates stay IN — collapsing them is the job
        batch, info = records_to_readbatch(recs, duplex=True)
        assert not batch.valid[:3].any()
        assert batch.valid[3]
        assert info["n_dropped_flag"] == 3
        assert info["n_valid"] == len(recs) - 3

    def test_excluded_read_does_not_inflate_umi_len(self):
        from duplexumiconsensusreads_tpu.io.bam import FLAG_SECONDARY

        header, recs, *_ = simulated_bam(SimConfig(n_molecules=5, seed=6))
        recs.umi[0] = "ACGTACGTACGT-ACGTACGTACGT"  # longer RX, but excluded
        recs.flags[0] |= FLAG_SECONDARY
        batch, info = records_to_readbatch(recs, duplex=True)
        assert info["umi_len"] == 12  # 2 * umi_len=6 from the valid reads
        assert info["n_dropped_umi_len"] == 0

    def test_negative_ref_id_excluded_even_without_flag(self):
        """ref_id<0 maps to the sentinel pos_key; such records must be
        excluded unconditionally (the streaming chunker's sentinel flush
        assumes they can never form a family), flag or no flag."""
        header, recs, *_ = simulated_bam(SimConfig(n_molecules=5, seed=8))
        recs.ref_id[0] = -1  # flags untouched — still excluded
        batch, info = records_to_readbatch(recs, duplex=True)
        assert not batch.valid[0]
        assert info["n_dropped_flag"] == 1

    def test_unmapped_pos_key_sorts_last(self):
        from duplexumiconsensusreads_tpu.io.convert import UNMAPPED_POS_KEY

        key = pack_pos_key(np.array([-1]), np.array([-1]))
        assert key[0] == UNMAPPED_POS_KEY
        big = pack_pos_key(np.array([1000]), np.array([(1 << 31) - 1]))
        assert key[0] > big[0]

    def test_pos_key_rejects_ref_id_aliasing_sentinel(self):
        """ref_id >= 2^26 would alias UNMAPPED_POS_KEY (or overflow);
        pack must refuse rather than silently corrupt grouping."""
        with pytest.raises(ValueError, match="ref_id"):
            pack_pos_key(np.array([1 << 26]), np.array([0]))
        # largest legal ref_id still packs below the sentinel
        from duplexumiconsensusreads_tpu.io.convert import UNMAPPED_POS_KEY

        ok = pack_pos_key(np.array([(1 << 26) - 1]), np.array([(1 << 36) - 1]))
        assert ok[0] < UNMAPPED_POS_KEY


class TestStrandAndKeys:
    @pytest.mark.parametrize(
        "flag,expect_top",
        [
            (0, True),  # unpaired forward
            (FLAG_REVERSE, False),  # unpaired reverse
            (FLAG_PAIRED | FLAG_READ1, True),  # F1
            (FLAG_PAIRED | FLAG_READ1 | FLAG_REVERSE, False),  # R1
            (FLAG_PAIRED | FLAG_READ2 | FLAG_REVERSE, True),  # R2 → top
            (FLAG_PAIRED | FLAG_READ2, False),  # F2 → bottom
        ],
    )
    def test_strand_rule(self, flag, expect_top):
        assert read_is_top_strand(flag) == expect_top

    def test_pos_key_pack_unpack(self):
        ref = np.array([0, 3, 120], np.int32)
        pos = np.array([0, 1_000_000, (1 << 31) - 1], np.int64)
        ref2, pos2 = unpack_pos_key(pack_pos_key(ref, pos))
        np.testing.assert_array_equal(ref2, ref)
        np.testing.assert_array_equal(pos2, pos)

    def test_ba_umi_swap(self):
        """BA reads must carry the swapped (canonical) UMI pair."""
        cfg = SimConfig(n_molecules=8, duplex=True, seed=5)
        batch, _ = simulate_batch(cfg)
        recs = readbatch_to_records(batch, duplex=True)
        strand = np.asarray(batch.strand_ab, bool)
        ab = np.nonzero(strand)[0]
        ba = np.nonzero(~strand)[0]
        assert len(ab) and len(ba)
        # In the BAM, a molecule's AB and BA reads have RX halves swapped
        canon = {}
        for i in ab:
            canon[recs.umi[i]] = i
        half = len(recs.umi[0].replace("-", "")) // 2
        for i in ba:
            a, b = recs.umi[i].split("-")
            swapped = b + "-" + a
            # swapped form should exist among AB reads of the same molecule
            # (at least for error-free UMIs; seed=5 has umi_error=0)
            assert swapped in canon


class TestNpz:
    def test_roundtrip(self, tmp_path):
        batch, _ = simulate_batch(SimConfig(n_molecules=10, seed=9))
        p = str(tmp_path / "b.npz")
        save_readbatch(p, batch)
        batch2 = load_readbatch(p)
        for f in ("bases", "quals", "umi", "pos_key", "strand_ab", "valid"):
            np.testing.assert_array_equal(
                np.asarray(getattr(batch, f)), getattr(batch2, f)
            )


def test_host_cpu_fingerprint_stable_and_flagged():
    """The per-host CPU cache key: 12 hex chars, stable within a host,
    and derived from real feature flags (not the empty-parse collision
    the r5 segfault postmortem guards against)."""
    from duplexumiconsensusreads_tpu.utils.compile_cache import (
        host_cpu_fingerprint,
    )

    a = host_cpu_fingerprint()
    b = host_cpu_fingerprint()
    assert a == b
    assert len(a) == 12 and all(c in "0123456789abcdef" for c in a)
    import hashlib

    assert a != hashlib.sha256(b"").hexdigest()[:12]


def test_record_bin_uses_cigar_reference_span():
    """The per-record serializer's bin field must cover the CIGAR
    reference span (M/D/N ops), not l_seq: a consensus record with a
    deletion spans more reference than it has bases, and strict
    validators check bin == reg2bin(pos, pos + ref_span) (ADVICE r5)."""
    import struct

    from duplexumiconsensusreads_tpu.io.bam import BamRecords, _reg2bin

    L = 20
    # pos chosen so pos + L stays inside one 16 kb leaf window while
    # pos + 25 (the M+D+M reference span) crosses into the next — the
    # two candidate bins genuinely differ
    pos = 70 * 16384 - 22
    recs = BamRecords(
        names=["r0"],
        flags=np.zeros(1, np.uint16),
        ref_id=np.zeros(1, np.int32),
        pos=np.array([pos], np.int32),
        mapq=np.full(1, 60, np.uint8),
        next_ref_id=np.full(1, -1, np.int32),
        next_pos=np.full(1, -1, np.int32),
        tlen=np.zeros(1, np.int32),
        lengths=np.array([L], np.int32),
        seq=np.zeros((1, L), np.uint8),
        qual=np.full((1, L), 30, np.uint8),
        cigars=[[(10, "M"), (5, "D"), (10, "M")]],
        umi=["ACGT"],
        aux_raw=[b""],
    )
    header = BamHeader(
        text="@HD\tVN:1.6\tSO:coordinate\n",
        ref_names=["chr1"],
        ref_lengths=[10_000_000],
    )
    assert _reg2bin(pos, pos + 25) != _reg2bin(pos, pos + L)  # test is live
    data = serialize_bam(header, recs)
    text_len = len(header.text.encode())
    rec_off = 4 + 4 + text_len + 4 + (4 + len(b"chr1\x00") + 4)
    (got_bin,) = struct.unpack_from("<H", data, rec_off + 4 + 10)
    assert got_bin == _reg2bin(pos, pos + 25)
