"""End-to-end fused pipeline + bucketing + mesh sharding tests.

All five benchmark configs (BASELINE.json `configs`) are exercised:
  1. ss consensus, exact grouping
  2. adjacency grouping (Hamming<=1)
  3. duplex consensus
  4. bucketed shards across an 8-device mesh
  5. per-cycle error model + duplex
and results are checked against the oracle operator path.
"""

import numpy as np
import pytest

import jax

from duplexumiconsensusreads_tpu.bucketing import build_buckets, stack_buckets
from duplexumiconsensusreads_tpu.oracle import call_consensus, group_reads
from duplexumiconsensusreads_tpu.ops import (
    ConsensusCaller,
    PipelineSpec,
    UmiGrouper,
    fused_pipeline,
    run_bucket,
)
from duplexumiconsensusreads_tpu.parallel import make_mesh, sharded_pipeline
from duplexumiconsensusreads_tpu.simulate import SimConfig, simulate_batch
from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams


def _oracle_pipeline(batch, gp, cp):
    fams = group_reads(batch, gp)
    caller = ConsensusCaller(cp, backend="cpu")
    return fams, caller(batch, fams)


def _check_bucket_against_oracle(bucket, out, gp, cp, qual_tol=3):
    """Re-run the oracle on exactly the bucket's reads and compare."""
    from duplexumiconsensusreads_tpu.types import ReadBatch

    sub = ReadBatch(
        bases=bucket.bases,
        quals=bucket.quals,
        umi=bucket.umi,
        pos_key=bucket.pos.astype(np.int64),
        strand_ab=bucket.strand_ab,
        frag_end=bucket.frag_end,
        valid=bucket.valid,
    )
    fams, cons = _oracle_pipeline(sub, gp, cp)
    n = len(cons.valid)
    np.testing.assert_array_equal(np.asarray(out["family_id"]), fams.family_id)
    np.testing.assert_array_equal(np.asarray(out["molecule_id"]), fams.molecule_id)
    ov = np.asarray(out["cons_valid"])[:n]
    np.testing.assert_array_equal(ov, cons.valid)
    dev_b = np.asarray(out["cons_base"])[:n][ov]
    dev_q = np.asarray(out["cons_qual"])[:n][ov].astype(int)
    orc_b = cons.bases[ov]
    orc_q = cons.quals[ov].astype(int)
    # Base parity contract (ARCHITECTURE.md): identical EXCEPT at
    # evidence ties, where f32-vs-f64 (and XLA-CPU-vs-TPU accumulation
    # order) may break the argmax either way — both sides then report
    # near-zero confidence. Only the near-floor-qual config (qual_tol
    # > 3) makes real ties plausible (first observed live: 1/1920
    # cells on the REAL chip under cfg5_min_input_qual) — every other
    # config keeps the bit-exact assertion, and a flip at a CONFIDENT
    # cell stays a hard failure everywhere. The tie allowance is
    # count-based (<= 1 per ~500 cells, rounded up) so one legitimate
    # tie in a small bucket doesn't trip a per-bucket percentage.
    mism = dev_b != orc_b
    if qual_tol <= 3:
        np.testing.assert_array_equal(dev_b, orc_b)
    elif mism.any():
        assert mism.sum() <= max(1, dev_b.size // 500), (
            f"{mism.sum()} base mismatches in {dev_b.size} cells"
        )
        assert (dev_q[mism] <= 5).all() and (orc_q[mism] <= 5).all(), (
            "base mismatch at a CONFIDENT cell — not an evidence tie"
        )
    dq = np.abs(dev_q[~mism] - orc_q[~mism])
    # f32-vs-f64 floor rounding: ±1 per strand ssc, ±1 more through the
    # error-model qual cap; duplex sums two strands → up to 3, and rarely
    # (qual_tol>3 configs: near-floor quals (qual_lo~2) can stack a
    # boundary flip on BOTH strands — verified 1 cell in 36k on
    # cfg5_min_input_qual with fit/caps/bases all bit-exact)
    assert (dq <= qual_tol).all()
    if qual_tol <= 3:
        assert (dq <= 1).mean() > 0.97
    else:
        # adversarial near-floor-qual configs on REAL hardware: one
        # tie-flipped read in the fit can move a cycle's cap a single
        # threshold step, shifting every qual at that cycle by 1-2
        # (measured on-chip: 89% within ±1, all within ±5) — the
        # distribution check stays, just calibrated to that mode
        assert (dq <= 2).mean() > 0.9


CONFIGS = [
    (
        "cfg1_ss_exact",
        SimConfig(n_molecules=50, duplex=False, seed=20),
        GroupingParams(strategy="exact"),
        ConsensusParams(mode="single_strand", min_reads=2),
    ),
    (
        "cfg2_adjacency",
        SimConfig(n_molecules=30, duplex=False, umi_error=0.04, mean_family_size=6, seed=21),
        GroupingParams(strategy="adjacency"),
        ConsensusParams(mode="single_strand"),
    ),
    (
        "cfg3_duplex",
        SimConfig(n_molecules=40, duplex=True, seed=22),
        GroupingParams(strategy="exact", paired=True),
        ConsensusParams(mode="duplex", min_duplex_reads=1),
    ),
    (
        "cfg5_error_model_duplex",
        SimConfig(
            n_molecules=40,
            duplex=True,
            cycle_error_slope=0.002,
            mean_family_size=5,
            seed=23,
        ),
        GroupingParams(strategy="adjacency", paired=True),
        ConsensusParams(mode="duplex", error_model="cycle"),
    ),
    (
        # min_input_qual x error model: (family, cycle)s where EVERY
        # read is sub-threshold have zero evidence, and the fit pass
        # must exclude them exactly like the oracle (its pass-1
        # consensus is BASE_N there) — regression for the fit-only
        # column mode's sign-based depth masking (r4 review finding).
        # Tuned so the fitted caps stay ABOVE min_input_qual: with a
        # too-high threshold the cap clips every qual below it, pass 2
        # masks everything, and the test can't discriminate (verified:
        # an unmasked-argmax fit fails this config, caps 17->9).
        "cfg5_min_input_qual",
        SimConfig(
            n_molecules=40,
            duplex=True,
            cycle_error_slope=0.002,
            mean_family_size=2,
            qual_lo=2,
            qual_hi=40,
            seed=24,
        ),
        GroupingParams(strategy="adjacency", paired=True),
        ConsensusParams(mode="duplex", error_model="cycle", min_input_qual=10),
    ),
]


@pytest.mark.parametrize("name,cfg,gp,cp", CONFIGS, ids=[c[0] for c in CONFIGS])
def test_fused_pipeline_matches_oracle(name, cfg, gp, cp):
    batch, _ = simulate_batch(cfg)
    buckets = build_buckets(batch, capacity=512, adjacency=gp.strategy == "adjacency")
    spec = PipelineSpec(grouping=gp, consensus=cp)
    tol = 5 if name == "cfg5_min_input_qual" else 3
    for bucket in buckets:
        out = run_bucket(bucket, spec)
        _check_bucket_against_oracle(bucket, out, gp, cp, qual_tol=tol)


@pytest.mark.parametrize("strategy", ["adjacency", "cluster"])
def test_operator_boundary_backends_agree(strategy):
    """UmiGrouper/ConsensusCaller (the preserved operator API) must give
    identical results on cpu and tpu backends — for the directional AND
    cluster strategies (the latter also pins the standalone grouper's
    data-driven u_max sizing under cluster, fixed late r5)."""
    cfg = SimConfig(n_molecules=30, duplex=True, umi_error=0.02, seed=24)
    batch, _ = simulate_batch(cfg)
    gp = GroupingParams(strategy=strategy, paired=True)
    cp = ConsensusParams(mode="duplex", error_model="cycle")

    f_cpu = UmiGrouper(gp, backend="cpu")(batch)
    f_tpu = UmiGrouper(gp, backend="tpu")(batch)
    np.testing.assert_array_equal(np.asarray(f_tpu.family_id), f_cpu.family_id)
    np.testing.assert_array_equal(np.asarray(f_tpu.molecule_id), f_cpu.molecule_id)

    c_cpu = ConsensusCaller(cp, backend="cpu")(batch, f_cpu)
    c_tpu = ConsensusCaller(cp, backend="tpu")(batch, f_tpu)
    np.testing.assert_array_equal(c_tpu.valid, c_cpu.valid)
    v = c_cpu.valid
    np.testing.assert_array_equal(c_tpu.bases[v], c_cpu.bases[v])
    assert (np.abs(c_tpu.quals[v].astype(int) - c_cpu.quals[v].astype(int)) <= 2).all()


def test_bucketing_preserves_reads_and_groups():
    cfg = SimConfig(n_molecules=200, n_positions=20, duplex=True, seed=25)
    batch, _ = simulate_batch(cfg)
    buckets = build_buckets(batch, capacity=128)
    # every valid read appears exactly once
    all_idx = np.concatenate([b.read_index[b.valid] for b in buckets])
    assert sorted(all_idx) == sorted(np.nonzero(batch.valid)[0])
    # a position group is only ever split if it exceeds the capacity
    pos_all = np.asarray(batch.pos_key)
    group_sizes = {p: (pos_all[batch.valid] == p).sum() for p in np.unique(pos_all)}
    pos_of: dict = {}
    for bi, b in enumerate(buckets):
        for p in np.unique(pos_all[b.read_index[b.valid]]):
            pos_of.setdefault(p, set()).add(bi)
    for p, bs in pos_of.items():
        if len(bs) > 1:
            assert group_sizes[p] > 128, f"group {p} split though it fits"
    # and within each bucket, no exact family is torn apart
    from duplexumiconsensusreads_tpu.utils.phred import pack_umi

    fam_of: dict = {}
    for bi, b in enumerate(buckets):
        idx = b.read_index[b.valid]
        keys = zip(pos_all[idx], pack_umi(np.asarray(batch.umi)[idx]))
        for k in set(keys):
            fam_of.setdefault(k, set()).add(bi)
    torn = [k for k, bs in fam_of.items() if len(bs) > 1]
    assert not torn, f"families split across buckets: {torn[:3]}"


def test_bucketing_giant_family_jumbo():
    """A single UMI family much larger than capacity gets ONE jumbo
    pow2-capacity bucket (deep families are routine in ctDNA), keeping
    consensus over the whole family intact."""
    import warnings as _warnings

    from duplexumiconsensusreads_tpu.types import ReadBatch

    n, cap = 100, 32
    b = ReadBatch.empty(n, 20, 6)
    b.valid[:] = True
    b.bases[:] = 0
    b.pos_key[:] = 1000
    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        buckets = build_buckets(b, capacity=cap)
    assert len(buckets) == 1
    assert buckets[0].capacity == 128  # pow2(100)
    all_idx = np.concatenate([bk.read_index[bk.valid] for bk in buckets])
    assert sorted(all_idx) == list(range(n))


def test_duplex_requires_paired_grouping():
    with pytest.raises(ValueError, match="paired"):
        PipelineSpec(
            grouping=GroupingParams(paired=False),
            consensus=ConsensusParams(mode="duplex"),
        )


@pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)
def test_sharded_pipeline_on_mesh():
    cfg = SimConfig(n_molecules=150, n_positions=24, duplex=True, seed=26)
    batch, truth = simulate_batch(cfg)
    gp = GroupingParams(strategy="exact", paired=True)
    cp = ConsensusParams(mode="duplex")
    buckets = build_buckets(batch, capacity=256)
    assert len(buckets) >= 2
    mesh = make_mesh(8)
    stacked = stack_buckets(buckets, multiple_of=8)
    out = sharded_pipeline(stacked, PipelineSpec(grouping=gp, consensus=cp), mesh)
    # padding buckets produce nothing
    nb = stacked["n_real_buckets"]
    assert np.asarray(out["cons_valid"])[nb:].sum() == 0
    # each real bucket matches the oracle
    for i, bucket in enumerate(buckets):
        sub_out = {k: np.asarray(v)[i] for k, v in out.items()}
        _check_bucket_against_oracle(bucket, sub_out, gp, cp)


def test_cycle_error_model_earns_its_flops():
    """VERDICT r2 item 9: on a sim with elevated late-cycle error and
    overconfident reported quals (the simulator draws quals uniformly,
    blind to the true per-cycle error), config 5 (cycle error model)
    must beat config 3 (plain duplex) — both on high-confidence
    calibration (error rate among consensus bases reported at >= Q40)
    and without degrading the overall consensus error rate."""
    from duplexumiconsensusreads_tpu.runtime.executor import call_batch_tpu

    cfg = SimConfig(
        n_molecules=500,
        read_len=60,
        n_positions=12,
        mean_family_size=3,
        base_error=0.002,
        cycle_error_slope=0.004,  # cycle 59 true error ~0.24, reported Q30-40
        umi_error=0.0,
        duplex=True,
        qual_lo=30,
        qual_hi=40,
        seed=42,
    )
    batch, truth = simulate_batch(cfg)
    gp = GroupingParams(strategy="exact", paired=True)
    lut = {
        (int(p), u.tobytes()): i
        for i, (p, u) in enumerate(zip(truth.mol_pos_key, truth.mol_umi))
    }

    stats = {}
    for em in (None, "cycle"):
        cp = ConsensusParams(mode="duplex", error_model=em, min_duplex_reads=1)
        cb, cq, _cd, cv, fp, fu, _m, _p, _e = call_batch_tpu(
            batch, gp, cp, capacity=1024
        )
        n_err = n_base = hi_err = hi_base = 0
        for i in range(len(cb)):
            if not cv[i]:
                continue
            true_seq = truth.mol_seq[lut[(int(fp[i]), fu[i].tobytes())]]
            real = cb[i] < 4
            wrong = real & (cb[i] != true_seq)
            n_err += int(wrong.sum())
            n_base += int(real.sum())
            hi = real & (cq[i] >= 40)
            hi_err += int((wrong & hi).sum())
            hi_base += int(hi.sum())
        assert n_base > 10_000  # enough signal for the rates below
        stats[em] = (n_err / n_base, hi_err / max(hi_base, 1), hi_base)

    (err3, hi3, nhi3), (err5, hi5, nhi5) = stats[None], stats["cycle"]
    # the error model must not hurt overall accuracy...
    assert err5 <= err3 * 1.05, (err5, err3)
    # ...and must fix the Q40+ calibration: without it, overconfident
    # late-cycle bases carry wrong calls at high reported quality
    assert nhi3 > 0 and nhi5 > 0
    assert hi5 < hi3, (hi5, hi3)
    assert hi5 <= 10 ** (-40 / 10) * 20, hi5  # within 20x of claimed Q40


@pytest.mark.parametrize("ssc_method", ["matmul", "blockseg", "segment"])
@pytest.mark.parametrize(
    "gp_kw, cp_kw",
    [
        (dict(strategy="exact", paired=False), dict(mode="single_strand")),
        (dict(strategy="adjacency", paired=True), dict(mode="duplex")),
        (
            dict(strategy="adjacency", paired=True),
            dict(mode="duplex", error_model="cycle"),
        ),
    ],
)
def test_per_base_err_counts_match_oracle(gp_kw, cp_kw, ssc_method):
    """spec.per_base_counts: the device err matrix (reads disagreeing
    with the called base, the ce tag) must equal the oracle's exactly —
    counts are order-independent integer sums, so no f32 tolerance."""
    import dataclasses as dc

    from duplexumiconsensusreads_tpu.ops import spec_for_buckets
    from duplexumiconsensusreads_tpu.types import ReadBatch

    cfg = SimConfig(
        n_molecules=120, duplex=True, umi_error=0.02, base_error=0.05, seed=19
    )
    batch, _ = simulate_batch(cfg)
    gp = GroupingParams(**gp_kw)
    cp = ConsensusParams(**cp_kw)
    buckets = build_buckets(batch, capacity=512, grouping=gp)
    spec = dc.replace(
        spec_for_buckets(buckets, gp, cp, ssc_method=ssc_method),
        per_base_counts=True,
    )
    checked = total_err = 0
    for bk in buckets:
        out = run_bucket(bk, spec)
        assert "cons_err" in out
        sub = ReadBatch(
            bases=bk.bases, quals=bk.quals, umi=bk.umi,
            pos_key=bk.pos.astype(np.int64), strand_ab=bk.strand_ab,
            frag_end=bk.frag_end, valid=bk.valid,
        )
        fams = group_reads(sub, gp)
        cons = ConsensusCaller(cp, backend="cpu")(sub, fams)
        n = len(cons.valid)
        np.testing.assert_array_equal(
            np.asarray(out["cons_err"])[:n], cons.err
        )
        # padding rows carry zero errors; err bounded by depth per bucket
        assert not np.asarray(out["cons_err"])[n:].any()
        assert (cons.err <= cons.depth).all()
        checked += int(cons.valid.sum())
        total_err += int(cons.err.sum())
    assert checked > 50
    assert total_err > 0  # 5% base error must surface disagreements


def test_fit_impl_counts_end_to_end(tmp_path, monkeypatch):
    """The selectable counts-based error-model fit (DUT_FIT_IMPL=counts,
    the journaled alternative to the default gather) must run the full
    config5 pipeline end to end with a sane truth-validated error rate —
    guards the env knob the perf A/B relies on."""
    import json

    from duplexumiconsensusreads_tpu.cli.main import main as cli_main

    bam = str(tmp_path / "in.bam")
    truth = str(tmp_path / "t.npz")
    assert cli_main([
        "simulate", "-o", bam, "--truth", truth, "--molecules", "150",
        "--family-size", "5", "--base-error", "0.01",
        "--cycle-error-slope", "0.002", "--sorted", "--seed", "77",
    ]) == 0
    outs = {}
    for impl in ("gather", "counts"):
        monkeypatch.setenv("DUT_FIT_IMPL", impl)
        out = str(tmp_path / f"c_{impl}.bam")
        assert cli_main([
            "call", bam, "-o", out, "--config", "config5",
            "--capacity", "512", "--backend", "tpu",
        ]) == 0
        import io as _io
        from contextlib import redirect_stdout

        buf = _io.StringIO()
        with redirect_stdout(buf):
            assert cli_main(["validate", out, "--truth", truth, "--json"]) == 0
        outs[impl] = json.loads(buf.getvalue().strip().splitlines()[-1])
    # the slope makes late cycles ~30% raw error (0.01 + 0.002*150);
    # the consensus must beat the MEAN raw error by >10x, and both
    # formulations — exact up to GEMM-layout tie cells — must land
    # near-identical rates
    mean_raw = 0.01 + 0.002 * 75
    for impl, v in outs.items():
        assert v["n_unmatched"] == 0, impl
        assert v["error_rate"] < mean_raw / 10, (impl, v["error_rate"])
    assert abs(outs["gather"]["error_rate"] - outs["counts"]["error_rate"]) < 2e-3
