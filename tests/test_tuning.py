"""Bucket ladders + the profile-guided auto-tuner (tuning/).

The acceptance contract this suite pins:

  * output bytes are IDENTICAL at every --bucket-ladder setting —
    {off, auto, explicit 2-rung, explicit 3-rung} — vs the off/serial
    reference (the ladder is a shape transform, never a result
    transform), jumbo-family interaction included;
  * the ladder DP is exact: covers the run, respects rung bounds,
    never costs more padded rows than the single-capacity greedy;
  * auto verdicts are ledgered (tuner_verdict in the capture) and
    auditable (fill-factor attrs on every h2d ledger record, counters
    in the summary, the wirestat fill column/sum-check);
  * the ids-lane u16 fetch rung is byte-exact, saves d2h bytes where
    the full compaction is gated off, and downgrades with a ledgered
    reason at capacity >= 2**16 (the per-class rung decision is one
    pure helper, unit-tested over the whole gate matrix);
  * tools/tune_ssc.py's JSON contract records the raced winner.
"""

import json
import subprocess
import sys

import numpy as np
import pytest

from duplexumiconsensusreads_tpu import tuning
from duplexumiconsensusreads_tpu.bucketing import build_buckets
from duplexumiconsensusreads_tpu.bucketing.buckets import _ladder_partition
from duplexumiconsensusreads_tpu.io import read_bam, simulated_bam
from duplexumiconsensusreads_tpu.runtime.stream import stream_call_consensus
from duplexumiconsensusreads_tpu.simulate import SimConfig, simulate_batch
from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams

GP = GroupingParams(strategy="adjacency", paired=True)
CP = ConsensusParams(mode="duplex")


# ------------------------------------------------------------ the DP


class TestLadderPartition:
    def _check(self, sizes, ladder):
        bounds = np.concatenate([[0], np.cumsum(sizes)])
        cuts = _ladder_partition(bounds, ladder)
        # exact coverage, rung membership, per-bucket bound
        assert cuts[0][0] == 0 and cuts[-1][1] == int(bounds[-1])
        for (a, b, cap), (a2, _, _) in zip(cuts, cuts[1:] + [(bounds[-1],) * 3]):
            assert cap in ladder and b - a <= cap
            assert a2 == b
        return sum(c for _, _, c in cuts)

    def test_covers_bounds_and_beats_greedy(self):
        rng = np.random.default_rng(5)
        sizes = rng.integers(1, 512, size=200)
        ladder = (64, 128, 512)
        cost = self._check(sizes, ladder)
        base = tuning.single_capacity_cost(sizes, 512)
        # the greedy single-capacity partition is a feasible ladder
        # solution (every bucket at the top rung), so the DP can never
        # pad more
        assert cost <= base["rows_padded"]

    def test_small_tail_takes_small_rung(self):
        cost = self._check([100] * 5 + [30], (32, 128, 512))
        assert cost == 512 + 32  # 500 at the top rung + the 30 tail

    def test_single_rung_matches_greedy_cost(self):
        rng = np.random.default_rng(7)
        sizes = rng.integers(1, 200, size=120)
        cost = self._check(sizes, (256,))
        assert cost == tuning.single_capacity_cost(sizes, 256)["rows_padded"]

    def test_coalesce_path_stays_exact(self):
        sizes = np.full(6000, 5)
        cost = self._check(sizes, (256, 1024))
        assert cost >= 30000  # covers every read
        # worst waste bounded by one min-rung//8 block per bucket
        assert cost <= 30000 + (cost // 1024 + 1) * (256 // 8) + 1024


class TestNormalize:
    def test_carriers(self):
        assert tuning.normalize_bucket_ladder("auto") == "auto"
        assert tuning.normalize_bucket_ladder(None) == "off"
        assert tuning.normalize_bucket_ladder("256,1024") == (256, 1024)
        assert tuning.normalize_bucket_ladder([64, 512]) == (64, 512)
        assert tuning.normalize_bucket_ladder((2048,)) == (2048,)

    @pytest.mark.parametrize("bad", [
        "7,13",            # not pow2
        "512,256",         # descending
        "8",               # below MIN_RUNG
        "32,64,128,256,512",  # too many rungs
        "",                # empty
        12,                # wrong carrier
        [64, 64],          # duplicate
    ])
    def test_rejections(self, bad):
        with pytest.raises(ValueError):
            tuning.normalize_bucket_ladder(bad)


class TestChooseLadder:
    def test_verdict_shape_and_roundtrip(self):
        sizes = np.array([40] * 50 + [700] * 4 + [25] * 30)
        v = tuning.choose_ladder(sizes, 1024, pack_mult=2)
        assert v.ladder[-1] == v.capacity == 1024
        assert 1 <= len(v.ladder) <= tuning.MAX_RUNGS
        assert v.fill_factor >= v.fill_factor_off
        assert v.predicted_speedup >= 1.0
        assert v.pack_mult == 2 and v.n_reads == int(sizes.sum())
        assert tuning.TunerVerdict.from_dict(v.to_dict()) == v

    def test_long_tail_picks_a_ladder(self):
        # shallow tiles + hot tail: the classic win case — the tuner
        # must find a multi-rung ladder and predict a real gain
        rng = np.random.default_rng(11)
        sizes = np.concatenate([
            rng.integers(20, 90, size=400),
            rng.integers(900, 1800, size=30),
        ])
        rng.shuffle(sizes)
        v = tuning.choose_ladder(sizes, 2048)
        assert len(v.ladder) >= 2
        assert v.fill_factor > v.fill_factor_off
        assert v.predicted_speedup > 1.0

    def test_uniform_mix_keeps_single_capacity(self):
        # nothing to win: near-full greedy buckets — the class-overhead
        # term must stop rung proliferation
        sizes = np.full(2000, 16)
        v = tuning.choose_ladder(sizes, 1024)
        assert v.ladder == (1024,)
        assert v.predicted_speedup == 1.0


# -------------------------------------------------- bucketer integration


class TestBuildBucketsLadder:
    def _batch(self, **kw):
        cfg = SimConfig(
            n_molecules=kw.pop("n_molecules", 300),
            n_positions=kw.pop("n_positions", 40),
            umi_error=0.02, duplex=True, seed=kw.pop("seed", 3), **kw,
        )
        batch, _ = simulate_batch(cfg)
        return batch

    def test_read_set_identical_and_padding_shrinks(self):
        batch = self._batch()
        valid = int(np.asarray(batch.valid).sum())
        pads = {}
        for lad in (None, (64, 512), (32, 128, 512)):
            bks = build_buckets(batch, capacity=512, grouping=GP, ladder=lad)
            idx = np.concatenate(
                [b.read_index[b.read_index >= 0] for b in bks]
            )
            assert len(idx) == len(set(idx.tolist())) == valid
            for b in bks:
                assert int(b.valid.sum()) <= b.capacity
                if lad is not None and b.capacity <= 512:
                    assert b.capacity in lad
            pads[lad] = sum(b.capacity for b in bks)
        assert pads[(32, 128, 512)] <= pads[None]

    def test_ladder_validation(self):
        batch = self._batch()
        with pytest.raises(ValueError):
            build_buckets(batch, capacity=512, grouping=GP, ladder=(64, 256))
        with pytest.raises(ValueError):
            build_buckets(batch, capacity=512, grouping=GP, ladder=(512, 64))

    def test_jumbo_families_ride_their_own_pow2_class(self):
        # a family larger than the TOP rung still gets its next-pow2
        # jumbo bucket; plain buckets stay on the ladder's rungs
        batch = self._batch(
            n_molecules=30, n_positions=3, mean_family_size=24,
            max_family_size=120, seed=9,
        )
        bks = build_buckets(batch, capacity=64, grouping=GP, ladder=(32, 64))
        caps = {b.capacity for b in bks}
        assert any(c > 64 for c in caps), "fixture produced no jumbo family"
        for b in bks:
            if b.capacity > 64:
                assert b.capacity == 1 << (b.capacity.bit_length() - 1)
            else:
                assert b.capacity in (32, 64)
        idx = np.concatenate([b.read_index[b.read_index >= 0] for b in bks])
        assert len(idx) == len(set(idx.tolist())) == int(
            np.asarray(batch.valid).sum()
        )


# ------------------------------------------------------ streaming matrix


class TestLadderMatrix:
    """The acceptance A/B: every --bucket-ladder setting must produce
    output BYTE-IDENTICAL to the off/serial reference."""

    @pytest.fixture(scope="class")
    def matrix_sim(self, tmp_path_factory):
        d = tmp_path_factory.mktemp("ladder")
        path = str(d / "in.bam")
        cfg = SimConfig(n_molecules=60, n_positions=8, umi_error=0.02, seed=31)
        simulated_bam(cfg, path=path, sort=True)
        ref = str(d / "ref.bam")
        # serial reference: single drain worker, ladder off
        rep = stream_call_consensus(
            path, ref, GP, CP, capacity=128, chunk_reads=90,
            drain_workers=1, bucket_ladder="off",
        )
        assert rep.n_chunks >= 3
        with open(ref, "rb") as f:
            return path, f.read(), rep

    @pytest.mark.parametrize("ladder", ["off", "auto", "32,128", "32,64,128"])
    def test_byte_identity(self, matrix_sim, tmp_path, ladder):
        path, ref_bytes, ref_rep = matrix_sim
        out = str(tmp_path / f"l_{ladder.replace(',', '_')}.bam")
        rep = stream_call_consensus(
            path, out, GP, CP, capacity=128, chunk_reads=90,
            bucket_ladder=ladder,
        )
        with open(out, "rb") as f:
            assert f.read() == ref_bytes
        assert rep.n_consensus == ref_rep.n_consensus
        # the resolved ladder is reported; explicit 3-rung must shrink
        # the padded rows the serial reference paid
        if ladder == "off":
            assert rep.bucket_ladder == []
            assert rep.n_rows_padded == ref_rep.n_rows_padded
        elif ladder == "auto":
            assert rep.bucket_ladder and rep.bucket_ladder[-1] == 128
        else:
            assert rep.bucket_ladder == [int(x) for x in ladder.split(",")]
            assert rep.n_rows_padded < ref_rep.n_rows_padded
        assert 0 < rep.n_rows_real <= rep.n_rows_padded

    def test_explicit_top_rung_replaces_capacity(self, matrix_sim, tmp_path):
        # a ladder whose top rung differs from --capacity wins: the top
        # rung IS the effective capacity (documented knob precedence),
        # and bytes still match the reference
        path, ref_bytes, _ = matrix_sim
        out = str(tmp_path / "top.bam")
        rep = stream_call_consensus(
            path, out, GP, CP, capacity=128, chunk_reads=90,
            bucket_ladder=(32, 64),
        )
        with open(out, "rb") as f:
            assert f.read() == ref_bytes
        assert rep.bucket_ladder == [32, 64]

    def test_jumbo_plus_ladder_byte_identity(self, tmp_path):
        # jumbo families (> top rung) and a ladder at once: the
        # interaction case the issue names
        path = str(tmp_path / "jumbo.bam")
        cfg = SimConfig(
            n_molecules=30, n_positions=3, mean_family_size=24,
            max_family_size=120, umi_error=0.01, seed=9,
        )
        simulated_bam(cfg, path=path, sort=True)
        outs = {}
        for name, lad in (("off", "off"), ("ladder", (32, 64))):
            out = str(tmp_path / f"{name}.bam")
            rep = stream_call_consensus(
                path, out, GP, CP, capacity=64, chunk_reads=80,
                bucket_ladder=lad,
            )
            assert rep.n_consensus > 0
            with open(out, "rb") as f:
                outs[name] = f.read()
        assert outs["ladder"] == outs["off"]


# --------------------------------------------- observability + wirestat


class TestLadderObservability:
    @pytest.fixture(scope="class")
    def traced(self, tmp_path_factory):
        d = tmp_path_factory.mktemp("ladder_trace")
        path = str(d / "in.bam")
        simulated_bam(
            SimConfig(n_molecules=60, n_positions=8, umi_error=0.02, seed=31),
            path=path, sort=True,
        )
        out = str(d / "out.bam")
        trace = str(d / "trace.jsonl")
        rep = stream_call_consensus(
            path, out, GP, CP, capacity=128, chunk_reads=90,
            bucket_ladder="auto", trace_path=trace,
        )
        with open(trace) as f:
            records = [json.loads(line) for line in f]
        return records, rep, trace

    def test_tuner_verdict_is_ledgered(self, traced):
        records, rep, _ = traced
        evs = [
            r for r in records
            if r.get("type") == "event" and r.get("name") == "tuner_verdict"
        ]
        assert len(evs) == 1  # one verdict per run, at the first chunk
        ev = evs[0]
        assert ev["ladder"] == rep.bucket_ladder
        assert 0 < ev["fill_factor_off"] <= 1
        assert ev["predicted_speedup"] >= 1.0
        # the capture still validates against the run schema
        from duplexumiconsensusreads_tpu.telemetry import report
        assert report.validate_trace(records) == []

    def test_fill_attrs_and_summary_counters(self, traced):
        from duplexumiconsensusreads_tpu.telemetry import ledger

        records, rep, _ = traced
        fill = ledger.fill_stats(records)
        assert fill["rows_real"] == rep.n_rows_real
        assert fill["rows_pad"] == rep.n_rows_padded
        assert fill["sum_check_ok"] is True
        assert 0 < fill["fill_factor"] <= 1
        per = ledger.per_chunk_bytes(records)
        assert any(
            row.get("h2d", {}).get("rows_pad") for row in per.values()
        )

    def test_wirestat_fill_column_and_exit_codes(self, traced, tmp_path):
        _, _, trace = traced
        env = dict(JAX_PLATFORMS="cpu")
        import os as _os

        env = {**_os.environ, "JAX_PLATFORMS": "cpu"}
        proc = subprocess.run(
            [sys.executable, "tools/wirestat.py", trace, "--json"],
            capture_output=True, text=True, env=env,
            cwd=_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
        )
        assert proc.returncode == 0, proc.stderr
        doc = json.loads(proc.stdout)
        assert doc["fill"]["sum_check_ok"] is True
        assert 0 < doc["fill"]["fill_factor"] <= 1
        # tampered rows must trip the fill sum-check like the byte one
        bad = str(tmp_path / "bad.jsonl")
        with open(trace) as f, open(bad, "w") as g:
            for line in f:
                rec = json.loads(line)
                if rec.get("type") == "xfer" and rec.get("dir") == "h2d":
                    rec["rows_pad"] = rec["rows_pad"] + 64
                g.write(json.dumps(rec) + "\n")
        proc = subprocess.run(
            [sys.executable, "tools/wirestat.py", bad],
            capture_output=True, text=True, env=env,
            cwd=_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
        )
        assert proc.returncode == 1


# --------------------------------------------------- ids-lane u16 rung


class TestIds16Rung:
    def test_rung_decision_matrix(self):
        from duplexumiconsensusreads_tpu.runtime.executor import (
            d2h_rung_for_class,
        )

        # full rung healthy
        assert d2h_rung_for_class(True, True, 128, False) == ("packed", None)
        # full rung defeated by a jumbo class: established reason
        assert d2h_rung_for_class(True, True, 1 << 16, False) == (
            "off", "jumbo-class-capacity-overflows-u16",
        )
        # per-base tags force the partial rung
        assert d2h_rung_for_class(False, True, 128, True) == ("ids16", None)
        # the partial rung's own capacity gate, ledgered (the satellite:
        # gated at capacity >= 2**16 with a fallback event)
        assert d2h_rung_for_class(False, True, 1 << 16, True) == (
            "off", "ids-lane-overflows-u16",
        )
        assert d2h_rung_for_class(False, True, (1 << 16) // 2, True) == (
            "ids16", None,
        )
        # both knobs off: silent, honest baseline
        assert d2h_rung_for_class(False, False, 128, False) == ("off", None)

    def test_per_base_tags_byte_identity_and_savings(self, tmp_path):
        path = str(tmp_path / "in.bam")
        simulated_bam(
            SimConfig(n_molecules=60, n_positions=8, umi_error=0.02, seed=31),
            path=path, sort=True,
        )
        outs, reps = {}, {}
        for name, kw in (
            ("base", dict(packed="off", d2h_packed="off")),
            ("ids16", dict(packed="auto", d2h_packed="auto")),
        ):
            out = str(tmp_path / f"{name}.bam")
            reps[name] = stream_call_consensus(
                path, out, GP, CP, capacity=128, chunk_reads=90,
                per_base_tags=True, **kw,
            )
            with open(out, "rb") as f:
                outs[name] = f.read()
        assert outs["ids16"] == outs["base"]
        # per-base tags gate the FULL compaction off, so the saving here
        # is exactly the ids lane: 2x (B, R) i32 -> 1x (B, R) u16
        assert reps["ids16"].bytes_d2h < reps["base"].bytes_d2h

    def test_unpack_roundtrip_and_logical_bytes(self):
        from duplexumiconsensusreads_tpu.ops.pipeline import PipelineSpec
        from duplexumiconsensusreads_tpu.runtime.executor import (
            d2h_logical_nbytes,
            unpack_fetch_outputs,
        )

        spec = PipelineSpec(
            grouping=GroupingParams(strategy="adjacency", paired=True),
            consensus=ConsensusParams(mode="duplex"),
        )
        ids = np.array([[3, 0, -1, 7]], np.int32)
        fetched = {
            "ids16": (ids + 1).astype(np.uint16),
            "n_families": np.array([2], np.int32),
            "n_molecules": np.array([2], np.int32),
        }
        out = unpack_fetch_outputs(fetched, [], spec)
        assert "ids16" not in out and "family_id" not in out
        assert out["molecule_id"].dtype == np.int32
        np.testing.assert_array_equal(out["molecule_id"], ids)
        # logical = wire - u16 lane + BOTH i32 lanes
        wire = sum(v.nbytes for v in fetched.values())
        assert d2h_logical_nbytes(fetched, [], spec) == (
            wire - fetched["ids16"].nbytes + 2 * ids.size * 4
        )


# --------------------------------------------------------- verdict store


class TestVerdictStore:
    def test_roundtrip_and_corruption_tolerance(self, tmp_path):
        store = tuning.VerdictStore(str(tmp_path / "v.json"))
        assert store.get("k") is None
        store.put("k", {"ladder": [64, 256], "fill_factor": 0.9})
        assert store.get("k")["ladder"] == [64, 256]
        assert len(store) == 1
        # torn/garbage store degrades to empty, never raises
        with open(store.path, "w") as f:
            f.write("{not json")
        assert store.get("k") is None
        store.put("k2", {"ladder": [128]})
        assert store.get("k2") == {"ladder": [128]}

    def test_bounded(self, tmp_path, monkeypatch):
        from duplexumiconsensusreads_tpu.tuning import store as store_mod

        monkeypatch.setattr(store_mod, "MAX_VERDICTS_KEPT", 3)
        store = tuning.VerdictStore(str(tmp_path / "v.json"))
        for i in range(5):
            store.put(f"k{i}", {"ladder": [64]})
        assert len(store) == 3
        assert store.get("k0") is None and store.get("k4") is not None

    def test_profile_key_tracks_input_identity(self, tmp_path):
        p = tmp_path / "a.bam"
        p.write_bytes(b"x" * 10)
        k1 = tuning.profile_key(str(p), "sig")
        assert k1 == tuning.profile_key(str(p), "sig")
        assert k1 != tuning.profile_key(str(p), "other-sig")
        p.write_bytes(b"y" * 11)
        assert k1 != tuning.profile_key(str(p), "sig")


# ------------------------------------------------------------- tune_ssc


class TestTuneSsc:
    def test_build_result_records_winner(self):
        sys.path.insert(0, "tools")
        try:
            import tune_ssc
        finally:
            sys.path.pop(0)
        race = {
            "backend": "cpu", "n_reads": 100, "capacity": 128, "reps": 1,
            "methods": {
                "matmul": {"method": "matmul", "blockseg_t": None,
                           "step_s": 0.2, "reads_per_sec": 500.0},
                "blockseg(T=64)": {"method": "blockseg", "blockseg_t": 64,
                                   "step_s": 0.1, "reads_per_sec": 1000.0},
            },
            "winner": "blockseg(T=64)", "winner_method": "blockseg",
        }
        res = tune_ssc.build_result(race)
        assert res["winner"] == "blockseg(T=64)"
        assert res["winner_method"] == "blockseg"
        assert res["version"] == 2 and res["tool"] == "tune_ssc"
        json.dumps(res)  # the whole result must be JSON-serialisable

    def test_race_runs_live_kernels(self):
        # tiny geometry, one method pair: proves the race harness runs
        # the CURRENT fused pipeline end to end and ranks by measured
        # reads/s (the post-r5 re-race contract)
        race = tuning.race_ssc_methods(
            methods=("matmul", "blockseg"), blockseg_ts=(64,), reps=1,
            n_molecules=120, read_len=32, n_positions=6, capacity=64,
        )
        assert set(race["methods"]) == {"matmul", "blockseg(T=64)"}
        assert race["winner"] in race["methods"]
        assert race["winner_method"] in ("matmul", "blockseg")
        for row in race["methods"].values():
            assert row["step_s"] > 0 and row["reads_per_sec"] > 0


# ------------------------------------------------------------------ CLI


class TestCliFlag:
    def test_whole_file_refuses_ladder(self, tmp_path):
        from duplexumiconsensusreads_tpu.cli import main

        path = str(tmp_path / "in.bam")
        simulated_bam(SimConfig(n_molecules=10), path=path, sort=True)
        with pytest.raises(SystemExit, match="bucket-ladder"):
            main(["call", path, "-o", str(tmp_path / "o.bam"),
                  "--bucket-ladder", "auto"])

    def test_bad_value_refused(self, tmp_path):
        from duplexumiconsensusreads_tpu.cli import main

        with pytest.raises(SystemExit, match="bucket-ladder"):
            main(["call", str(tmp_path / "in.bam"), "-o",
                  str(tmp_path / "o.bam"), "--chunk-reads", "90",
                  "--bucket-ladder", "7,9"])

    def test_streaming_cli_happy_path(self, tmp_path):
        from duplexumiconsensusreads_tpu.cli import main

        path = str(tmp_path / "in.bam")
        simulated_bam(
            SimConfig(n_molecules=40, n_positions=6, umi_error=0.02, seed=5),
            path=path, sort=True,
        )
        out_l = str(tmp_path / "l.bam")
        out_o = str(tmp_path / "o.bam")
        assert main(["call", path, "-o", out_l, "--config", "config3",
                     "--capacity", "128", "--chunk-reads", "90",
                     "--bucket-ladder", "32,128"]) == 0
        assert main(["call", path, "-o", out_o, "--config", "config3",
                     "--capacity", "128", "--chunk-reads", "90"]) == 0
        _, rl = read_bam(out_l)
        _, ro = read_bam(out_o)
        assert len(rl) == len(ro)
        np.testing.assert_array_equal(rl.seq, ro.seq)
        np.testing.assert_array_equal(rl.qual, ro.qual)


# ------------------------------------------------------ bench tuner leg


class TestBucketTunerBench:
    def test_fill_improves_on_the_long_tail_fixture(self, monkeypatch):
        """The acceptance criterion, verbatim: the CPU bench sim's
        e2e_fill_factor improves vs single-capacity bucketing on the
        canonical long-tail fixture (MEASURED through build_buckets,
        not just the cost model's prediction)."""
        monkeypatch.setenv("DUT_BENCH_TUNER_MOLECULES", "6000")
        monkeypatch.setenv("DUT_BENCH_CAPACITY", "2048")
        from duplexumiconsensusreads_tpu.benchmark import (
            run_bucket_tuner_bench,
        )

        res = run_bucket_tuner_bench()
        assert res["e2e_fill_factor"] > res["bucket_tuner_fill_factor_off"]
        assert res["tuner_predicted_speedup"] > 1.0
        assert len(res["tuner_ladder"]) >= 2
        assert res["tuner_ladder"][-1] == 2048

    def test_leg_keys_ride_the_compact_line_and_trajectory(self):
        from duplexumiconsensusreads_tpu import benchhist
        from duplexumiconsensusreads_tpu.benchmark import COMPACT_KEYS

        canon = {k for k, _, _ in benchhist.CANONICAL_METRICS}
        for key in ("e2e_fill_factor", "tuner_predicted_speedup"):
            assert key in COMPACT_KEYS
            assert key in canon
            # informational, never gated: shape decisions follow the
            # input mix, and the gate must not cry weather
            assert not dict(
                (k, g) for k, _, g in benchhist.CANONICAL_METRICS
            )[key]


# --------------------------------------------------- review regressions


class TestReviewRegressions:
    def test_coalesce_never_builds_an_infeasible_block(self):
        """A partial coalesce block followed by a near-capacity group
        must not merge past the top rung (was a TypeError crash on the
        at-scale hot-tail inputs the tuner targets)."""
        sizes = [1, 4096] + [1] * 4200
        bounds = np.concatenate([[0], np.cumsum(sizes)])
        cuts = _ladder_partition(bounds, (256, 4096))
        assert cuts[0][0] == 0 and cuts[-1][1] == int(bounds[-1])
        for a, b, cap in cuts:
            assert b - a <= cap and cap in (256, 4096)

    def test_off_baseline_flushes_at_oversized_groups(self):
        """single_capacity_cost must close the open bucket at an
        oversized group exactly like the real packer's special-path
        flush — the model and the run may never disagree."""
        got = tuning.single_capacity_cost(np.array([100, 300, 100]), 256)
        assert got["n_buckets"] == 2 and got["rows_padded"] == 512

    def test_ladder_config_variants_normalise_everywhere(self):
        """'AUTO' / spaced rung strings must behave exactly like their
        canonical forms: same compile signature, same kwargs — a
        cosmetic variant must not bypass the verdict store."""
        from duplexumiconsensusreads_tpu.serve.job import (
            job_params,
            spec_signature,
            validate_spec,
        )

        def spec(ladder):
            return validate_spec({
                "job_id": "j", "input": "/i.bam", "output": "/o.bam",
                "config": {"chunk_reads": 90, "capacity": 128,
                           "bucket_ladder": ladder},
            })

        canon, shouty = spec("auto"), spec("AUTO")
        assert spec_signature(canon) == spec_signature(shouty)
        assert job_params(shouty)[2]["bucket_ladder"] == "auto"
        spaced, listy = spec(" 32 , 128 "), spec([32, 128])
        assert spec_signature(spaced) == spec_signature(listy)
        assert job_params(spaced)[2]["bucket_ladder"] == (32, 128)

    def test_unreusable_single_rung_verdicts_are_not_persisted(self, tmp_path):
        """A resolved capacity that validate_ladder would refuse on
        reuse (non-pow2 / below MIN_RUNG) must not be persisted —
        persisting it would make every later slice hit, fail, and
        re-put the store forever."""
        from duplexumiconsensusreads_tpu.serve.worker import WarmWorker

        w = WarmWorker()
        store = tuning.VerdictStore(str(tmp_path / "v.json"))
        w._note_verdict(store, "k", False, [16], 10, 20)  # below MIN_RUNG
        w._note_verdict(store, "k", False, [96], 10, 20)  # not pow2
        assert len(store) == 0 and w.n_verdict_puts == 0
        w._note_verdict(store, "k", False, [128], 10, 20)
        assert store.get("k")["ladder"] == [128] and w.n_verdict_puts == 1

    def test_shard_subjobs_get_range_scoped_verdict_keys(self, tmp_path):
        """Sibling shard sub-jobs (and the whole-file job) must not
        collide on one verdict-store key: each profiles its own
        range's group-size mix."""
        from duplexumiconsensusreads_tpu.serve.job import validate_spec
        from duplexumiconsensusreads_tpu.serve.worker import verdict_key

        p = tmp_path / "in.bam"
        p.write_bytes(b"x" * 64)

        def key(shard):
            d = {"job_id": "j", "input": str(p), "output": "/o.bam",
                 "config": {"chunk_reads": 90, "bucket_ladder": "auto"}}
            if shard:
                d["job_id"] = f"j.s{shard['idx']}"
                d["shard"] = shard
            return verdict_key(validate_spec(d))

        whole = key(None)
        s0 = key({"parent": "j", "idx": 0, "k": 2, "chunk_base": 0,
                  "key_lo": 0, "key_hi": 50})
        s1 = key({"parent": "j", "idx": 1, "k": 2, "chunk_base": 5,
                  "key_lo": 50, "key_hi": None})
        assert len({whole, s0, s1}) == 3
