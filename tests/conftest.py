"""Test env: force CPU platform with VIRTUAL DEVICES before backend init.

This mirrors the driver's multi-chip dry-run: the suite runs on a
virtual 2-device CPU mesh, so every streaming/serve/chaos test
exercises REAL mesh-sharded execution (per-device H2D puts, per-shard
packed-D2H compaction, mesh-pad ledgering) — the same code paths hit
real TPU chips in production (see parallel/mesh.py and
parallel/sharded.py's shard_map form).

Two devices, not eight, as the default: 2 is the smallest real mesh
(every multi-device invariant — even sharding, pad buckets, per-device
lanes, collective-freedom — is exercised), while 8-way SPMD on a CPU
multiplies every tiny test's per-dispatch overhead several-fold.
tests/test_mesh.py covers the 8-device legs of the byte-identity
matrix (DUT_TEST_DEVICES=8 runs them in-process; its subprocess test
covers them in the default run), and the driver's multichip entry runs
the real 8-device consensus.

NOTE: this environment pre-imports jax at interpreter startup, so the
config must be applied before FIRST BACKEND USE, not first import.
jax.config.update("jax_platforms") still works because the backend is
initialised lazily; the device count rides XLA_FLAGS, which the CPU
client reads at that same lazy init (jax.config's own
jax_num_cpu_devices knob does not exist on this jax version — it was
tried here and silently left the suite on one device). Set
DUT_TEST_TPU=1 to run the suite against the real chip instead.
"""

import os

import jax

if not os.environ.get("DUT_TEST_TPU"):
    n_dev = int(os.environ.get("DUT_TEST_DEVICES", "2"))
    flag = f"--xla_force_host_platform_device_count={n_dev}"
    if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""
    ):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " " + flag
        ).strip()
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        # backend already initialised (pre-provisioned via XLA_FLAGS or
        # a plugin touching jax.devices() first) — run on whatever
        # exists
        pass
