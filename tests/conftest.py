"""Test env: force CPU platform with 8 virtual devices BEFORE backend init.

This mirrors the driver's multi-chip dry-run: all sharding tests run on
a virtual 8-device CPU mesh; the same code paths hit real TPU chips in
production (see parallel/mesh.py).

NOTE: this environment pre-imports jax at interpreter startup, so
setting JAX_PLATFORMS via os.environ here is too late — the config
default was already captured. jax.config.update still works because the
backend itself is initialised lazily on first use. Set DUT_TEST_TPU=1
to run the suite against the real chip instead.
"""

import os

import jax

if not os.environ.get("DUT_TEST_TPU"):
    try:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", 8)
    except Exception:
        # backend already initialised (pre-provisioned via XLA_FLAGS or a
        # plugin touching jax.devices() first) — run on whatever exists
        pass
