"""Test env: force CPU platform with 8 virtual devices BEFORE jax import.

This mirrors the driver's multi-chip dry-run: all sharding tests run on
a virtual 8-device CPU mesh; the same code paths hit real TPU chips in
production (see parallel/mesh.py).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")
