"""Standard BAI index + output-header conformance (VERDICT r3 missing
#1/#2): coordinate-sorted SO, spec §5.2 bin/chunk/linear structure on a
multi-reference file, voffsets that truly address records, header
provenance (@RG/@CO preserved, @PG chained), and the consensus @RG."""

import struct

import numpy as np
import pytest

from duplexumiconsensusreads_tpu.cli import main
from duplexumiconsensusreads_tpu.io import bgzf, read_bam
from duplexumiconsensusreads_tpu.io.bai import METADATA_BIN, build_bai, read_bai
from duplexumiconsensusreads_tpu.io.bam import (
    BamHeader,
    BamRecords,
    _reg2bin,
    write_bam,
)


def _multi_ref_bam(path, n_per_ref=40, n_ref=3, seed=5):
    """Coordinate-sorted BAM spanning several references, positions
    spread so records cross multiple 16 kb linear windows and several
    bin levels."""
    rng = np.random.default_rng(seed)
    names, flags, rid, pos, ln = [], [], [], [], []
    n = n_per_ref * n_ref
    L = 24
    for r in range(n_ref):
        p = np.sort(rng.integers(0, 300_000, n_per_ref))
        for k, pp in enumerate(p.tolist()):
            names.append(f"r{r}_{k}")
            flags.append(0)
            rid.append(r)
            pos.append(pp)
            ln.append(L)
    seq = rng.integers(0, 4, (n, L)).astype(np.uint8)
    qual = np.full((n, L), 30, np.uint8)
    recs = BamRecords(
        names=names,
        flags=np.array(flags, np.uint16),
        ref_id=np.array(rid, np.int32),
        pos=np.array(pos, np.int32),
        mapq=np.full(n, 60, np.uint8),
        next_ref_id=np.full(n, -1, np.int32),
        next_pos=np.full(n, -1, np.int32),
        tlen=np.zeros(n, np.int32),
        lengths=np.full(n, L, np.int32),
        seq=seq,
        qual=qual,
        cigars=[[(L, "M")] for _ in range(n)],
        umi=[""] * n,
        aux_raw=[b"RXZACGTAA\x00" for _ in range(n)],
    )
    header = BamHeader.synthetic(
        ref_names=tuple(f"chr{r+1}" for r in range(n_ref)),
        ref_lengths=(1_000_000,) * n_ref,
        sort_order="coordinate",
    )
    write_bam(path, header, recs)
    return recs


def _record_at_voffset(path, v):
    """Decompress the BGZF block a virtual offset points into and parse
    the record there — proves the BAI's voffsets address real records."""
    coff, uoff = v >> 16, v & 0xFFFF
    with open(path, "rb") as f:
        data = f.read()
    size = bgzf.read_block_size(data, coff)
    payload = bytearray(bgzf.decompress_block(data, coff, size))
    # a record may span into following blocks; extend as needed
    (bsz,) = struct.unpack_from("<i", payload, uoff)
    nxt = coff + size
    while uoff + 4 + bsz > len(payload):
        size = bgzf.read_block_size(data, nxt)
        payload += bgzf.decompress_block(data, nxt, size)
        nxt += size
    ref_id, pos = struct.unpack_from("<ii", payload, uoff + 4)
    return ref_id, pos


def test_bai_structure_multi_ref(tmp_path):
    path = str(tmp_path / "mr.bam")
    recs = _multi_ref_bam(path)
    bai_path = build_bai(path)
    idx = read_bai(bai_path)
    assert idx["n_ref"] == 3
    assert idx["n_no_coor"] == 0

    L = 24
    for r in range(3):
        ref = idx["refs"][r]
        sel = np.asarray(recs.ref_id) == r
        n_rec = int(sel.sum())
        # metadata pseudo-bin counts
        assert ref["meta"] is not None
        off_beg, off_end, n_mapped, n_unmapped = ref["meta"]
        assert (n_mapped, n_unmapped) == (n_rec, 0)
        assert off_beg < off_end
        # every record's bin exists and some chunk of exactly that bin
        # covers a voffset range inside the ref's file span
        total_chunks = 0
        for pp in np.asarray(recs.pos)[sel].tolist():
            b = _reg2bin(pp, pp + L)
            assert b in ref["bins"], f"ref {r} pos {pp}: bin {b} missing"
        for b, chunks in ref["bins"].items():
            total_chunks += len(chunks)
            for beg_v, end_v in chunks:
                assert off_beg <= beg_v < end_v <= off_end
                # the chunk's first voffset addresses a real record of
                # this ref whose reg2bin is exactly this bin
                rid_at, pos_at = _record_at_voffset(path, beg_v)
                assert rid_at == r
                assert _reg2bin(pos_at, pos_at + L) == b
        assert total_chunks >= 1
        # linear index: monotone coverage — for every record the window
        # entry exists, is nonzero, and does not point past the record
        lin = ref["linear"]
        pos_r = np.asarray(recs.pos)[sel]
        for pp in pos_r.tolist():
            w = pp >> 14
            assert w < len(lin)
            assert lin[w] != 0
            assert lin[w] <= off_end
        # backfilled: no zero holes after the first nonzero entry
        nz = [i for i, v in enumerate(lin) if v]
        if nz:
            assert all(lin[i] != 0 for i in range(nz[0], len(lin)))


def test_bai_clamps_positionless_placed_records(tmp_path):
    """Spec-legal ref_id>=0, pos=-1 records (placed but positionless)
    must clamp to window 0, matching the serializers' own bin math —
    not crash or poison the last linear window (r4 review finding)."""
    path = str(tmp_path / "pm1.bam")
    recs = _multi_ref_bam(path, n_per_ref=5, n_ref=1)
    recs.pos[0] = -1
    recs.flags[0] = 4  # unmapped-with-coordinate, as aligners emit them
    header = BamHeader.synthetic(
        ref_names=("chr1",), ref_lengths=(1_000_000,), sort_order="coordinate"
    )
    write_bam(path, header, recs)
    idx = read_bai(build_bai(path))
    ref = idx["refs"][0]
    assert ref["meta"][2] == 4 and ref["meta"][3] == 1  # 4 mapped + 1 unmapped
    assert ref["linear"][0] != 0  # clamped into window 0


def test_bai_rejects_unsorted(tmp_path):
    path = str(tmp_path / "uns.bam")
    recs = _multi_ref_bam(path)
    # swap two records out of order and rewrite
    order = np.arange(len(recs.names))
    order[0], order[5] = order[5], order[0]
    recs2 = BamRecords(
        names=[recs.names[i] for i in order],
        flags=recs.flags[order],
        ref_id=recs.ref_id[order],
        pos=recs.pos[order],
        mapq=recs.mapq[order],
        next_ref_id=recs.next_ref_id[order],
        next_pos=recs.next_pos[order],
        tlen=recs.tlen[order],
        lengths=recs.lengths[order],
        seq=recs.seq[order],
        qual=recs.qual[order],
        cigars=[recs.cigars[i] for i in order],
        umi=[recs.umi[i] for i in order],
        aux_raw=[recs.aux_raw[i] for i in order],
    )
    header = BamHeader.synthetic(
        ref_names=("chr1", "chr2", "chr3"), ref_lengths=(1_000_000,) * 3
    )
    write_bam(path, header, recs2)
    with pytest.raises(ValueError, match="not coordinate-sorted"):
        build_bai(path)


def _sim_with_provenance(tmp_path):
    """Simulated sorted input with @RG/@CO lines and RG tags grafted in
    — the provenance a real pipeline BAM carries."""
    bam = str(tmp_path / "in.bam")
    assert main([
        "simulate", "-o", bam, "--molecules", "60", "--read-len", "40",
        "--positions", "6", "--umi-error", "0.02", "--seed", "17", "--sorted",
    ]) == 0
    h, recs = read_bam(bam)
    lines = h.text.rstrip("\n").splitlines()
    lines.insert(1, "@RG\tID:rg1\tSM:sampleA")
    lines.insert(2, "@RG\tID:rg2\tSM:sampleB")
    lines.append("@CO\tprovenance comment")
    h2 = BamHeader(
        text="\n".join(lines) + "\n",
        ref_names=h.ref_names,
        ref_lengths=h.ref_lengths,
    )
    for i in range(len(recs)):
        rg = b"rg1" if i % 2 else b"rg2"
        recs.aux_raw[i] = recs.aux_raw[i] + b"RGZ" + rg + b"\x00"
    write_bam(bam, h2, recs)
    return bam


@pytest.mark.parametrize("mode", ["whole", "stream"])
def test_output_header_and_read_group(tmp_path, mode):
    """call output: SO:coordinate, input @RG/@CO/@PG preserved, a new
    @PG chained with PP:, the consensus @RG appended, RG:Z on every
    record — in both the whole-file and streamed paths."""
    bam = _sim_with_provenance(tmp_path)
    out = str(tmp_path / "cons.bam")
    extra = ["--chunk-reads", "120"] if mode == "stream" else []
    assert main([
        "call", bam, "-o", out, "--config", "config3", "--capacity", "256",
        "--write-index", *extra,
    ]) == 0
    h, recs = read_bam(out)
    text = h.text
    assert "SO:coordinate" in text.splitlines()[0]
    assert "@RG\tID:rg1\tSM:sampleA" in text
    assert "@RG\tID:rg2\tSM:sampleB" in text
    assert "@CO\tprovenance comment" in text
    # the input's own @PG survives and the new one chains to it
    pg_lines = [l for l in text.splitlines() if l.startswith("@PG")]
    assert any("ID:duplexumi\t" in l or l.endswith("ID:duplexumi") for l in pg_lines)
    new_pg = [l for l in pg_lines if "PP:" in l]
    assert len(new_pg) == 1
    assert "PP:duplexumi" in new_pg[0]  # chained to the simulate @PG
    # consensus @RG with SM union of input samples
    rg_lines = [l for l in text.splitlines() if l.startswith("@RG")]
    assert any("ID:A" in l and "sampleA" in l and "sampleB" in l for l in rg_lines)
    assert len(recs) > 0
    assert all(b"RGZA\x00" in a for a in recs.aux_raw)
    # records really are coordinate-sorted and the .bai stands up
    key = np.asarray(recs.ref_id).astype(np.int64) << 32 | np.asarray(recs.pos)
    assert (np.diff(key) >= 0).all()
    idx = read_bai(out + ".bai")
    n_indexed = sum(
        (r["meta"][2] + r["meta"][3]) for r in idx["refs"] if r["meta"]
    )
    assert n_indexed == len(recs)


def test_read_group_id_collision_uniquified(tmp_path):
    """Input already carrying @RG ID:A (e.g. an fgbio-made input) must
    NOT have consensus records attributed to that existing group — the
    id uniquifies like @PG ids do (r4 review finding)."""
    bam = str(tmp_path / "in.bam")
    assert main([
        "simulate", "-o", bam, "--molecules", "40", "--read-len", "40",
        "--positions", "4", "--seed", "3", "--sorted",
    ]) == 0
    h, recs = read_bam(bam)
    lines = h.text.rstrip("\n").splitlines()
    lines.insert(1, "@RG\tID:A\tSM:prior_consensus")
    write_bam(bam, BamHeader("\n".join(lines) + "\n", h.ref_names, h.ref_lengths), recs)
    out = str(tmp_path / "c.bam")
    assert main(["call", bam, "-o", out, "--config", "config3",
                 "--capacity", "256"]) == 0
    h2, r2 = read_bam(out)
    rg_lines = [l for l in h2.text.splitlines() if l.startswith("@RG")]
    assert any("ID:A\t" in l and "prior_consensus" in l for l in rg_lines)
    assert any("ID:A.1" in l for l in rg_lines)
    assert all(b"RGZA.1\x00" in a for a in r2.aux_raw)


def test_custom_read_group_id(tmp_path):
    bam = _sim_with_provenance(tmp_path)
    out = str(tmp_path / "cons.bam")
    assert main([
        "call", bam, "-o", out, "--config", "config3", "--capacity", "256",
        "--read-group-id", "ctdna1",
    ]) == 0
    h, recs = read_bam(out)
    assert any(
        l.startswith("@RG") and "ID:ctdna1" in l for l in h.text.splitlines()
    )
    assert all(b"RGZctdna1\x00" in a for a in recs.aux_raw)


def test_filter_and_group_chain_pg(tmp_path):
    bam = _sim_with_provenance(tmp_path)
    out = str(tmp_path / "cons.bam")
    assert main([
        "call", bam, "-o", out, "--config", "config3", "--capacity", "256",
    ]) == 0
    n_pg = len([l for l in read_bam(out)[0].text.splitlines() if l.startswith("@PG")])
    filt = str(tmp_path / "filt.bam")
    assert main(["filter", out, "-o", filt, "--min-depth", "1"]) == 0
    h_f = read_bam(filt)[0]
    pg_f = [l for l in h_f.text.splitlines() if l.startswith("@PG")]
    assert len(pg_f) == n_pg + 1
    # collision-free id + chained to the call run's entry
    assert any("ID:duplexumi.1" in l or "ID:duplexumi.2" in l for l in pg_f)
    grp = str(tmp_path / "grp.bam")
    assert main(["group", bam, "-o", grp, "--duplex"]) == 0
    pg_g = [l for l in read_bam(grp)[0].text.splitlines() if l.startswith("@PG")]
    assert any("PP:" in l for l in pg_g)


def test_view_region_query_matches_bruteforce(tmp_path, capsys):
    """`duplexumi view` consumes the tool's OWN .bai (the written index
    must also be readable): for random regions the one-seek indexed
    query must return exactly the records a brute-force full scan
    selects by overlap."""
    import json as _json

    path = str(tmp_path / "mr.bam")
    recs = _multi_ref_bam(path, n_per_ref=60, n_ref=3, seed=9)
    L = 24
    rng = np.random.default_rng(2)
    ref_names = ["chr1", "chr2", "chr3"]
    for _ in range(12):
        r = int(rng.integers(0, 3))
        beg = int(rng.integers(0, 300_000))
        end = beg + int(rng.integers(1, 60_000))
        sel = (
            (np.asarray(recs.ref_id) == r)
            & (np.asarray(recs.pos) < end)
            & (np.asarray(recs.pos) + L > beg)
        )
        region = f"{ref_names[r]}:{beg + 1}-{end}"
        out = str(tmp_path / "sel.bam")
        assert main(["view", path, region, "-o", out, "--json"]) == 0
        res = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert res["n_records"] == int(sel.sum()), region
        _, got = read_bam(out)
        assert sorted(got.names) == sorted(
            np.array(recs.names)[sel].tolist()
        ), region
    # whole-reference form
    assert main(["view", path, "chr2", "--json"]) == 0
    res = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert res["n_records"] == 60
    # unknown reference is a loud error
    with pytest.raises(SystemExit, match="unknown reference"):
        main(["view", path, "chrX:1-100"])


def test_view_colon_contig_and_unmapped_tail(tmp_path, capsys):
    """References whose names contain ':' (GRCh38 HLA alt contigs) must
    be queryable, and a last-reference query must TERMINATE at the
    unmapped tail instead of decoding it (r4 review findings)."""
    import json as _json

    path = str(tmp_path / "hla.bam")
    n, L = 8, 24
    rng = np.random.default_rng(4)
    pos = np.r_[np.sort(rng.integers(0, 50_000, n - 2)), [-1, -1]].astype(np.int32)
    rid = np.r_[np.zeros(n - 2), [-1, -1]].astype(np.int32)
    flags = np.r_[np.zeros(n - 2), [4, 4]].astype(np.uint16)
    recs = BamRecords(
        names=[f"r{i}" for i in range(n)],
        flags=flags,
        ref_id=rid,
        pos=pos,
        mapq=np.full(n, 60, np.uint8),
        next_ref_id=np.full(n, -1, np.int32),
        next_pos=np.full(n, -1, np.int32),
        tlen=np.zeros(n, np.int32),
        lengths=np.full(n, L, np.int32),
        seq=rng.integers(0, 4, (n, L)).astype(np.uint8),
        qual=np.full((n, L), 30, np.uint8),
        cigars=[[(L, "M")] for _ in range(n - 2)] + [[], []],
        umi=[""] * n,
        aux_raw=[b"RXZACGTAA\x00"] * n,
    )
    header = BamHeader.synthetic(
        ref_names=("HLA-A*01:01:01:01",), ref_lengths=(100_000,),
        sort_order="coordinate",
    )
    write_bam(path, header, recs)
    # whole-reference form with a colon-bearing name
    assert main(["view", path, "HLA-A*01:01:01:01", "--json"]) == 0
    res = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert res["n_records"] == n - 2  # the unmapped tail is excluded
    # ranged form on the colon-bearing name
    assert main(["view", path, "HLA-A*01:01:01:01:1-100000", "--json"]) == 0
    res = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert res["n_records"] == n - 2


def test_bai_refuses_contig_over_512mbp(tmp_path):
    """BAI bins address coordinates < 2^29; a longer contig must be
    refused loudly (pointing at CSI) rather than silently mis-indexed
    (VERDICT r4 item 8)."""
    path = str(tmp_path / "long.bam")
    n, L = 4, 24
    rng = np.random.default_rng(2)
    recs = BamRecords(
        names=[f"r{i}" for i in range(n)],
        flags=np.zeros(n, np.uint16),
        ref_id=np.zeros(n, np.int32),
        pos=np.arange(n, dtype=np.int32) * 100,
        mapq=np.full(n, 60, np.uint8),
        next_ref_id=np.full(n, -1, np.int32),
        next_pos=np.full(n, -1, np.int32),
        tlen=np.zeros(n, np.int32),
        lengths=np.full(n, L, np.int32),
        seq=rng.integers(0, 4, (n, L)).astype(np.uint8),
        qual=np.full((n, L), 30, np.uint8),
        cigars=[[(L, "M")] for _ in range(n)],
        umi=[""] * n,
        aux_raw=[b"" for _ in range(n)],
    )
    header = BamHeader.synthetic(
        ref_names=("big1",), ref_lengths=(600_000_000,),
        sort_order="coordinate",
    )
    write_bam(path, header, recs)
    with pytest.raises(ValueError, match="2\\^29.*CSI|CSI"):
        build_bai(path)


def test_bai_scale_indexes_fast(tmp_path):
    """The vectorised builder must index ~100k records in seconds, not
    minutes (VERDICT r4 item 7: the per-record walk cost minutes of
    host time per million records on the 200M-read critical path)."""
    import time

    path = str(tmp_path / "big.bam")
    _multi_ref_bam(path, n_per_ref=50_000, n_ref=2, seed=3)
    t0 = time.time()
    build_bai(path)
    dt = time.time() - t0
    idx = read_bai(path + ".bai")
    total = sum(r["meta"][2] + r["meta"][3] for r in idx["refs"])
    assert total == 100_000
    # generous bound for a contended 1-core box; the per-record walk
    # took ~40s+ here and scales linearly
    assert dt < 15, f"build_bai took {dt:.1f}s for 100k records"
