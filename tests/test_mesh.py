"""Mesh-sharded streaming execution: REAL multi-device consensus.

The contract under test is the one `--drain-workers` and `--shards K`
already obey: device count must not change output bytes. Chunk order
is the commit order, mesh-pad buckets are proven empty (n_out == 0),
and the per-chunk (pos_key, UMI) sort makes bytes a pure function of
the read set — so the byte-identity matrix here pins {1, 2, 8}
devices x {packed d2h on/off} x {bucket ladder off/auto} against the
1-device fully-unpacked serial reference.

Also covered: the per-device byte ledger (dev-N lanes, mesh_pad attrs,
wirestat's mesh sum-check in both directions), per-shard packed-D2H
compaction (whose absence DEADLOCKED concurrent multi-device
dispatches — see runtime/executor.py's packed-D2H comment), chaos
kill/resume convergence on the mesh path, daemon device pinning, and
the serve-side `mesh` job config.

Runs on the virtual 8-device CPU mesh tests/conftest.py provisions;
every multi-device test skips cleanly when fewer devices are visible
(DUT_TEST_TPU single-chip runs).
"""

import json
import os
import subprocess
import sys

import pytest

import jax

from duplexumiconsensusreads_tpu.io import simulated_bam
from duplexumiconsensusreads_tpu.runtime import faults
from duplexumiconsensusreads_tpu.runtime.stream import stream_call_consensus
from duplexumiconsensusreads_tpu.simulate import SimConfig
from duplexumiconsensusreads_tpu.telemetry import ledger, report
from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams

needs8 = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)
needs2 = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs >= 2 devices"
)

GP = GroupingParams(strategy="adjacency", paired=True)
CP = ConsensusParams(mode="duplex")
KW = dict(capacity=128, chunk_reads=96)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def mesh_sim(tmp_path_factory):
    """Sorted sim input + the 1-device fully-unpacked serial reference
    (the same baseline shape TestWireDietMatrix anchors on)."""
    d = tmp_path_factory.mktemp("mesh")
    path = str(d / "in.bam")
    simulated_bam(
        SimConfig(n_molecules=70, n_positions=10, umi_error=0.02, seed=52),
        path=path, sort=True,
    )
    ref = str(d / "ref.bam")
    rep = stream_call_consensus(
        path, ref, GP, CP, n_devices=1,
        packed="off", d2h_packed="off", **KW,
    )
    assert rep.n_chunks >= 3  # the matrix must cross chunk boundaries
    with open(ref, "rb") as f:
        return path, f.read(), rep


class TestMeshByteIdentityMatrix:
    """The acceptance matrix: output bytes are a pure function of the
    read set at ANY device count, whatever the wire diet and bucket
    ladder are doing around them."""

    @needs2
    @pytest.mark.parametrize("ladder", ["off", "auto"])
    @pytest.mark.parametrize("d2h", ["auto", "off"])
    @pytest.mark.parametrize(
        "n_dev", [1, 2, pytest.param(8, marks=needs8)]
    )
    def test_byte_identity(self, mesh_sim, tmp_path, n_dev, d2h, ladder):
        path, ref_bytes, ref_rep = mesh_sim
        out = str(tmp_path / f"{n_dev}_{d2h}_{ladder}.bam")
        rep = stream_call_consensus(
            path, out, GP, CP, n_devices=n_dev,
            d2h_packed=d2h, bucket_ladder=ladder, **KW,
        )
        with open(out, "rb") as f:
            assert f.read() == ref_bytes
        assert rep.n_devices == n_dev
        assert rep.n_consensus == ref_rep.n_consensus
        if n_dev == 1:
            # no mesh alignment on one device: the counter must agree
            assert rep.n_mesh_pad_buckets == 0
        else:
            # tiny chunks against a wide mesh: padding must be real
            # and counted (the ledger tests below pin that it is also
            # SHIPPED — per-device wire sums include the pad buckets)
            assert rep.n_mesh_pad_buckets > 0

    @needs2
    def test_device_subset_pinning(self, mesh_sim, tmp_path):
        """`devices=` (the dut-serve --devices pinning) runs the mesh
        on an index subset — bytes identical, bad indices loud."""
        path, ref_bytes, _ = mesh_sim
        out = str(tmp_path / "pin.bam")
        rep = stream_call_consensus(
            path, out, GP, CP, devices=[1, 0], **KW
        )
        assert rep.n_devices == 2
        with open(out, "rb") as f:
            assert f.read() == ref_bytes
        with pytest.raises(ValueError, match="out of range"):
            stream_call_consensus(
                path, str(tmp_path / "x.bam"), GP, CP,
                devices=[0, 99], **KW,
            )

    def test_eight_device_byte_identity_subprocess(self, mesh_sim, tmp_path):
        """The 8-wide leg without widening the whole suite's mesh: a
        fresh interpreter with 8 forced virtual devices (the same
        XLA_FLAGS trick the driver's multichip entry uses) streams the
        same input at 8 devices and at 1, and the two outputs must be
        byte-identical (self-contained in one process so the @PG argv
        provenance line cancels out; the in-process matrix above ties
        the 1/2-device legs to the fixture reference)."""
        path, _, _ = mesh_sim
        o8 = str(tmp_path / "o8.bam")
        o1 = str(tmp_path / "o1.bam")
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        }
        code = (
            "import jax\n"
            "from duplexumiconsensusreads_tpu.runtime.stream import"
            " stream_call_consensus\n"
            "from duplexumiconsensusreads_tpu.types import"
            " ConsensusParams, GroupingParams\n"
            "gp = GroupingParams(strategy='adjacency', paired=True)\n"
            "cp = ConsensusParams(mode='duplex')\n"
            f"kw = dict(capacity={KW['capacity']},"
            f" chunk_reads={KW['chunk_reads']})\n"
            f"rep = stream_call_consensus({path!r}, {o8!r}, gp, cp,"
            " n_devices=8, **kw)\n"
            "assert rep.n_devices == 8, rep.n_devices\n"
            "assert rep.n_mesh_pad_buckets > 0\n"
            f"stream_call_consensus({path!r}, {o1!r}, gp, cp,"
            " n_devices=1, **kw)\n"
            f"assert open({o8!r}, 'rb').read() =="
            f" open({o1!r}, 'rb').read(), '8-dev bytes differ from 1-dev'\n"
        )
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, env=env, cwd=_REPO,
        )
        assert r.returncode == 0, r.stderr[-2000:]


@pytest.fixture(scope="module")
def traced_mesh(mesh_sim, tmp_path_factory):
    """One traced 2-device run: the per-device ledger under test."""
    path, ref_bytes, _ = mesh_sim
    d = tmp_path_factory.mktemp("meshtrace")
    out = str(d / "out.bam")
    trace = str(d / "trace.jsonl")
    rep = stream_call_consensus(
        path, out, GP, CP, n_devices=2, trace_path=trace, **KW
    )
    with open(out, "rb") as f:
        assert f.read() == ref_bytes
    records = report.load_trace(trace)
    assert not report.validate_trace(records)
    return records, rep, trace


@needs2
class TestMeshLedger:
    """Per-device wire attribution: every h2d/d2h ledger record of a
    multi-device run rides a dev-N lane, mesh_pad attrs sum to the
    summary counter exactly, and wirestat holds both verdicts."""

    def test_per_device_lanes_and_mesh_pad_sums(self, traced_mesh):
        records, rep, _ = traced_mesh
        xf = ledger.xfer_records(records)
        wire_lanes = {
            r["lane"] for r in xf if r["dir"] in ("h2d", "d2h")
        }
        assert wire_lanes == {"dev-0", "dev-1"}
        # per-record byte sums reproduce the run totals exactly, per
        # direction AND per device (the split is exact, not estimated)
        devs = ledger.device_lanes(records)
        assert set(devs) == {"dev-0", "dev-1"}
        assert sum(d["h2d_wire"] for d in devs.values()) == rep.bytes_h2d
        assert sum(d["d2h_wire"] for d in devs.values()) == rep.bytes_d2h
        assert (
            sum(d["mesh_pad"] for d in devs.values())
            == rep.n_mesh_pad_buckets
            > 0
        )
        # h2d records carry the mesh_pad attr; the fill stats fold it
        # into the padding sum-check against the summary counter
        assert all("mesh_pad" in r for r in xf if r["dir"] == "h2d")
        fill = ledger.fill_stats(records)
        assert fill["mesh_pad_buckets"] == rep.n_mesh_pad_buckets
        assert fill["sum_check_ok"]

    def test_mesh_h2d_spans_on_device_lanes(self, traced_mesh):
        records, rep, _ = traced_mesh
        spans = [
            r for r in records
            if r.get("type") == "span" and r.get("stage") == "mesh_h2d"
        ]
        assert spans, "a multi-device run must record mesh_h2d spans"
        assert {s["lane"] for s in spans} == {"dev-0", "dev-1"}
        # the span/phase pairing holds for the new stage too
        total = sum(s["dur"] for s in spans)
        assert total == pytest.approx(rep.seconds["mesh_h2d"], abs=0.05)

    def test_trace_report_and_wirestat_green(self, traced_mesh):
        _, _, trace = traced_mesh
        for tool in ("tools/trace_report.py", "tools/wirestat.py"):
            r = subprocess.run(
                [sys.executable, os.path.join(_REPO, tool), trace],
                capture_output=True, text=True,
            )
            assert r.returncode == 0, (tool, r.stdout, r.stderr)
        # the human wirestat output carries the per-device table
        r = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools/wirestat.py"),
             trace],
            capture_output=True, text=True,
        )
        assert "dev-0" in r.stdout and "mesh_pad" in r.stdout

    def test_tampered_mesh_pad_fails_wirestat(self, traced_mesh, tmp_path):
        """The corruption direction: grow one record's mesh_pad and the
        padding sum-check must catch the drift (exit 1)."""
        records, _, trace = traced_mesh
        bad = str(tmp_path / "bad.jsonl")
        tampered = False
        with open(trace) as src, open(bad, "w") as dst:
            for line in src:
                rec = json.loads(line)
                if (
                    not tampered
                    and rec.get("type") == "xfer"
                    and rec.get("dir") == "h2d"
                ):
                    rec["mesh_pad"] = int(rec.get("mesh_pad", 0)) + 3
                    tampered = True
                dst.write(json.dumps(rec) + "\n")
        assert tampered
        r = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools/wirestat.py"),
             bad],
            capture_output=True, text=True,
        )
        assert r.returncode == 1, r.stdout


@needs2
@pytest.mark.chaos
class TestMeshChaos:
    """The recovery spine holds on the mesh path: kills at the
    established boundary sites + resume converge to the reference."""

    @pytest.mark.parametrize("site,nth", [
        ("shard.write", 1),
        ("fetch.unpack", 2),  # the per-shard packed-D2H unpack
    ])
    def test_kill_then_resume_converges(
        self, mesh_sim, tmp_path, site, nth
    ):
        path, ref_bytes, _ = mesh_sim
        out = str(tmp_path / "k.bam")
        faults.install(faults.FaultPlan.parse(f"{site}:{nth}:kill"))
        try:
            with pytest.raises(faults.InjectedKill):
                stream_call_consensus(
                    path, out, GP, CP, n_devices=2, **KW
                )
        finally:
            faults.uninstall()
        assert not os.path.exists(out)
        rep = stream_call_consensus(
            path, out, GP, CP, n_devices=2, resume=True, **KW
        )
        assert rep.n_devices == 2
        with open(out, "rb") as f:
            assert f.read() == ref_bytes

    def test_mesh_resumes_single_device_checkpoint(
        self, mesh_sim, tmp_path
    ):
        """Mesh shape stays OUT of the checkpoint fingerprint (like the
        bucket ladder): a prefix committed at 1 device resumes under a
        2-device mesh, byte-identical — a fleet can re-place a job on a
        daemon with a different device pool mid-run."""
        path, ref_bytes, _ = mesh_sim
        out = str(tmp_path / "x.bam")
        faults.install(faults.FaultPlan.parse("finalise.write:2:kill"))
        try:
            with pytest.raises(faults.InjectedKill):
                stream_call_consensus(
                    path, out, GP, CP, n_devices=1, **KW
                )
        finally:
            faults.uninstall()
        rep = stream_call_consensus(
            path, out, GP, CP, n_devices=2, resume=True, **KW
        )
        assert rep.n_chunks_skipped >= 1  # the 1-device prefix survived
        with open(out, "rb") as f:
            assert f.read() == ref_bytes


@needs2
@pytest.mark.serve
class TestServeMesh:
    """The mesh knob through the service: a job carrying config
    mesh=2 produces bytes identical to the one-shot reference, and the
    @PG provenance line excludes the mesh (bytes are mesh-invariant)."""

    def test_mesh_job_byte_identical(self, mesh_sim, tmp_path):
        from duplexumiconsensusreads_tpu.serve import (
            ConsensusService,
            client,
        )

        path, ref_bytes, _ = mesh_sim
        spool = str(tmp_path / "spool")
        out = str(tmp_path / "job.bam")
        config = dict(
            grouping="adjacency", mode="duplex", mesh=2,
            capacity=KW["capacity"], chunk_reads=KW["chunk_reads"],
        )
        job = client.submit(spool, path, out, config=config)
        ConsensusService(spool).run_until_idle()
        st = client.status(spool, job)
        assert st["state"] == "done", st
        with open(out, "rb") as f:
            job_bytes = f.read()
        # one-shot with the service's canonical provenance CL: the
        # mesh key must not have leaked into the header
        from duplexumiconsensusreads_tpu.serve.job import serve_provenance

        ref2 = str(tmp_path / "oneshot.bam")
        stream_call_consensus(
            path, ref2, GP, CP, n_devices=1,
            provenance_cl=serve_provenance(config), **KW,
        )
        with open(ref2, "rb") as f:
            assert job_bytes == f.read()
        assert "mesh" not in serve_provenance(config)

    def test_submission_refuses_bad_mesh(self, mesh_sim, tmp_path):
        from duplexumiconsensusreads_tpu.serve import client

        path, _, _ = mesh_sim
        spool = str(tmp_path / "spool")
        for bad in (0, -2, True, "2"):
            with pytest.raises(ValueError, match="mesh"):
                client.submit(
                    spool, path, str(tmp_path / "o.bam"),
                    config={"mesh": bad},
                )


@needs2
def test_cli_mesh_flag_streams_byte_identical(mesh_sim, tmp_path):
    """`call --mesh 2` end to end through the CLI, vs the reference."""
    from duplexumiconsensusreads_tpu.cli.main import main

    path, ref_bytes, _ = mesh_sim
    out = str(tmp_path / "cli.bam")
    assert main([
        "call", path, "-o", out, "--mode", "duplex",
        "--grouping", "adjacency", "--capacity", str(KW["capacity"]),
        "--chunk-reads", str(KW["chunk_reads"]), "--mesh", "2",
    ]) == 0
    with open(out, "rb") as f:
        assert f.read() == ref_bytes


def test_cli_mesh_refused_on_whole_file(mesh_sim, tmp_path):
    from duplexumiconsensusreads_tpu.cli.main import main

    path, _, _ = mesh_sim
    with pytest.raises(SystemExit, match="--mesh requires the streaming"):
        main(["call", path, "-o", str(tmp_path / "x.bam"), "--mesh", "2"])


def test_daemon_devices_parse():
    from duplexumiconsensusreads_tpu.serve.daemon import parse_devices

    assert parse_devices(None) == (None, None)
    assert parse_devices("4") == (4, None)
    assert parse_devices("0,2") == (None, [0, 2])
    assert parse_devices(" 1 , 3 ") == (None, [1, 3])
    # single-chip pin: the one-element list form (a bare int is the
    # legacy count; the count error names this form)
    assert parse_devices("2,") == (None, [2])
    for bad in ("", "a", "0,0", "-1,2", "0"):
        with pytest.raises(ValueError):
            parse_devices(bad)
    with pytest.raises(ValueError, match="one-element list"):
        parse_devices("0")
