"""CIGAR/indel policy tests (VERDICT r1 item 6).

Consensus operates on raw cycles, so a read whose CIGAR differs from
its family's (1bp indel, clipping) would misalign every column it
contributes to. The policy: within each exact family, drop reads not
carrying the family's modal CIGAR — at input conversion, identically
in the Python codec, the native loader, and hence for both backends.
"""

import numpy as np
import pytest

from duplexumiconsensusreads_tpu.io import read_bam, simulated_bam
from duplexumiconsensusreads_tpu.io.convert import (
    cigar_hashes,
    inject_indels,
    modal_cigar_keep,
    records_to_readbatch,
)
from duplexumiconsensusreads_tpu.simulate import SimConfig
from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams


def test_modal_cigar_keep_drops_minority():
    pos = np.array([5, 5, 5, 5, 5, 9], np.int64)
    umi = np.zeros((6, 4), np.uint8)
    valid = np.ones(6, bool)
    # reads 0-4 one family: 0-3 share a cigar, 4 differs; read 5 is a
    # singleton family with its own cigar (kept)
    h = np.array([7, 7, 7, 7, 12345, 999], np.uint64)
    keep = modal_cigar_keep(pos, umi, valid, h)
    np.testing.assert_array_equal(keep, [True, True, True, True, False, True])


def test_modal_cigar_vote_is_per_strand():
    """A/B strand sub-families are independent alignments: a minority
    strand with its own (legitimately different) soft-clipping must NOT
    be dropped by the other strand's modal vote (ADVICE r2)."""
    pos = np.zeros(5, np.int64)
    umi = np.zeros((5, 4), np.uint8)
    valid = np.ones(5, bool)
    # 3 top-strand reads share cigar 7; 2 bottom-strand reads share 9.
    strand = np.array([True, True, True, False, False])
    h = np.array([7, 7, 7, 9, 9], np.uint64)
    keep = modal_cigar_keep(pos, umi, valid, h, strand)
    np.testing.assert_array_equal(keep, [True] * 5)
    # within one strand the minority cigar still loses
    h2 = np.array([7, 7, 12, 9, 9], np.uint64)
    keep2 = modal_cigar_keep(pos, umi, valid, h2, strand)
    np.testing.assert_array_equal(keep2, [True, True, False, True, True])


def test_modal_cigar_tie_deterministic():
    """2-2 tie: the smaller hash wins, deterministically."""
    pos = np.zeros(4, np.int64)
    umi = np.zeros((4, 2), np.uint8)
    h = np.array([9, 9, 3, 3], np.uint64)
    keep = modal_cigar_keep(pos, umi, np.ones(4, bool), h)
    np.testing.assert_array_equal(keep, [False, False, True, True])


def test_all_indel_family_is_kept():
    """A true indel molecule: every read shares the indel CIGAR — the
    family survives intact (the filter only removes minority CIGARs)."""
    cfg = SimConfig(n_molecules=20, duplex=True, seed=2)
    header, recs, _, _ = simulated_bam(cfg, sort=True)
    # give EVERY read of one family the same indel cigar
    batch0, _ = records_to_readbatch(recs, duplex=True)
    fam_key = np.asarray(batch0.pos_key)
    target = fam_key[np.asarray(batch0.valid)][0]
    members = np.nonzero(fam_key == target)[0]
    l = int(recs.lengths[members[0]])
    for i in members:
        recs.cigars[i] = [(10, "M"), (1, "D"), (l - 10, "M")]
    batch, info = records_to_readbatch(recs, duplex=True)
    # nothing dropped: within each (pos, UMI) family the cigar is modal
    assert info["n_dropped_cigar"] == 0
    assert np.asarray(batch.valid)[members].all()


def test_python_native_agree_on_indel_input(tmp_path):
    from duplexumiconsensusreads_tpu.io.native_reader import read_bam_native
    from duplexumiconsensusreads_tpu.native import native_available

    if not native_available():
        pytest.skip("native loader unavailable")
    path = str(tmp_path / "indel.bam")
    cfg = SimConfig(
        n_molecules=80, mean_family_size=5, indel_error=0.08, duplex=True, seed=4
    )
    simulated_bam(cfg, path=path, sort=True)
    header, recs = read_bam(path)
    b_py, i_py = records_to_readbatch(recs, duplex=True)
    _, b_nat, i_nat = read_bam_native(path, duplex=True)
    assert i_py["n_dropped_cigar"] == i_nat["n_dropped_cigar"] > 0
    np.testing.assert_array_equal(b_py.valid, b_nat.valid)
    np.testing.assert_array_equal(b_py.strand_ab, b_nat.strand_ab)
    np.testing.assert_array_equal(b_py.umi, b_nat.umi)


def test_cigar_hash_matches_bam_bytes():
    """The Python hash must equal FNV-1a64 over the BAM-encoded cigar
    bytes (the native loader hashes the raw bytes)."""
    cigs = [[(150, "M")], [(10, "M"), (1, "I"), (139, "M")], []]
    h = cigar_hashes(cigs)

    def fnv(data):
        x = 0xCBF29CE484222325
        for b in data:
            x = ((x ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
        return x

    import struct

    ops = {c: i for i, c in enumerate("MIDNSHP=X")}
    for k, cig in enumerate(cigs):
        if not cig:
            assert h[k] == 0
            continue
        raw = b"".join(struct.pack("<I", (n << 4) | ops[o]) for n, o in cig)
        assert h[k] == fnv(raw)


def test_indel_reads_dropped_end_to_end(tmp_path, capsys):
    """Simulate with indels, call, validate: the filter keeps the
    consensus error rate at indel-free levels instead of letting
    misaligned reads corrupt columns."""
    import json

    from duplexumiconsensusreads_tpu.cli import main

    bam = str(tmp_path / "in.bam")
    truth = str(tmp_path / "t.npz")
    out = str(tmp_path / "o.bam")
    assert main(
        ["simulate", "-o", bam, "--truth", truth, "--molecules", "150",
         "--read-len", "60", "--positions", "8", "--family-size", "6",
         "--indel-error", "0.05", "--sorted", "--seed", "13"]
    ) == 0
    rep_path = str(tmp_path / "rep.json")
    assert main(
        ["call", bam, "-o", out, "--config", "config3", "--capacity", "512",
         "--report", rep_path]
    ) == 0
    rep = json.load(open(rep_path))
    assert rep["n_dropped"] > 0  # indel reads were filtered
    assert main(["validate", out, "--truth", truth, "--json"]) == 0
    res = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert res["error_rate"] < 5e-3


def test_inject_indels_shapes():
    cfg = SimConfig(n_molecules=30, duplex=False, seed=6)
    _, recs, _, _ = simulated_bam(cfg, sort=True)
    sel = inject_indels(recs, 0.3, seed=1)
    assert len(sel) > 0
    for i in sel:
        ops = recs.cigars[i]
        consumed = sum(n for n, o in ops if o in "MIS=X")
        assert consumed == int(recs.lengths[i])  # read-consuming ops add up


def _clip_family_bam(tmp_path, name="sc.bam"):
    """One exact family of 4 same-length reads: three modal 5S30M5S,
    one 3S30M7S (identical 30M aligned core, clips shifted by 2) — the
    soft-clip rescue case; plus a family whose minority read carries an
    indel core (non-rescuable)."""
    from duplexumiconsensusreads_tpu.io.bam import BamHeader, BamRecords, write_bam

    rng = np.random.default_rng(3)
    L = 40
    cigs = [
        [(5, "S"), (30, "M"), (5, "S")],
        [(5, "S"), (30, "M"), (5, "S")],
        [(5, "S"), (30, "M"), (5, "S")],
        [(3, "S"), (30, "M"), (7, "S")],  # rescuable
        # second family (pos 500): 2 modal + 1 indel-core minority
        [(40, "M")],
        [(40, "M")],
        [(20, "M"), (1, "I"), (19, "M")],  # NOT rescuable
    ]
    n = len(cigs)
    pos = np.array([100, 100, 100, 100, 500, 500, 500], np.int32)
    seq = rng.integers(0, 4, (n, L)).astype(np.uint8)
    qual = rng.integers(20, 40, (n, L)).astype(np.uint8)
    recs = BamRecords(
        names=[f"r{i}" for i in range(n)],
        flags=np.zeros(n, np.uint16),
        ref_id=np.zeros(n, np.int32),
        pos=pos,
        mapq=np.full(n, 60, np.uint8),
        next_ref_id=np.full(n, -1, np.int32),
        next_pos=np.full(n, -1, np.int32),
        tlen=np.zeros(n, np.int32),
        lengths=np.full(n, L, np.int32),
        seq=seq,
        qual=qual,
        cigars=cigs,
        umi=["ACGTAA"] * n,
        aux_raw=[b"RXZACGTAA\x00"] * n,
    )
    path = str(tmp_path / name)
    write_bam(path, BamHeader.synthetic(sort_order="coordinate"), recs)
    return path, recs


def test_softclip_rescue_trims_and_shifts(tmp_path):
    """A minority read differing from the modal CIGAR by soft-clipping
    only is RESCUED: trimmed to its aligned span and shifted into the
    modal cycle space, instead of losing its evidence (VERDICT r3 item
    7). An indel-core minority still drops, with per-strand counters."""
    from duplexumiconsensusreads_tpu.constants import BASE_PAD

    path, recs = _clip_family_bam(tmp_path)
    _, r2 = read_bam(path)
    batch, info = records_to_readbatch(r2, duplex=False)
    assert info["n_rescued_cigar"] == 1
    assert info["n_dropped_cigar"] == 1  # the indel-core read only
    assert info["n_dropped_cigar_ab"] == 1  # unpaired forward = top
    assert info["n_dropped_cigar_ba"] == 0
    v = np.asarray(batch.valid)
    assert v[3] and not v[6]
    # rescued row: query 3..32 (its 30M core) placed at cycles 5..34
    # (the modal lead), everything else masked PAD with qual 0
    b = np.asarray(batch.bases)
    q = np.asarray(batch.quals)
    np.testing.assert_array_equal(b[3, 5:35], np.asarray(r2.seq)[3, 3:33])
    np.testing.assert_array_equal(q[3, 5:35], np.asarray(r2.qual)[3, 3:33])
    assert (b[3, :5] == BASE_PAD).all() and (b[3, 35:] == BASE_PAD).all()
    assert (q[3, :5] == 0).all() and (q[3, 35:] == 0).all()


def test_softclip_rescue_native_parity(tmp_path):
    """Both codecs must apply the identical rescue transform — the
    batches (bases, quals, valid) stay bit-equal."""
    from duplexumiconsensusreads_tpu.io.native_reader import read_bam_native
    from duplexumiconsensusreads_tpu.native import native_available

    if not native_available():
        pytest.skip("native loader unavailable")
    path, _ = _clip_family_bam(tmp_path)
    _, r2 = read_bam(path)
    b_py, i_py = records_to_readbatch(r2, duplex=False)
    _, b_nat, i_nat = read_bam_native(path, duplex=False)
    for k in ("n_rescued_cigar", "n_dropped_cigar", "n_dropped_cigar_ab",
              "n_dropped_cigar_ba"):
        assert i_py[k] == i_nat[k], k
    np.testing.assert_array_equal(b_py.valid, b_nat.valid)
    np.testing.assert_array_equal(
        np.asarray(b_py.bases)[b_py.valid], np.asarray(b_nat.bases)[b_nat.valid]
    )
    np.testing.assert_array_equal(
        np.asarray(b_py.quals)[b_py.valid], np.asarray(b_nat.quals)[b_nat.valid]
    )


def test_cigar_drop_fraction_bounded_on_indel_sim(tmp_path):
    """Validate-side evidence-loss ceiling (VERDICT r3 item 7): on the
    indel sim the CIGAR policy must discard only a bounded fraction of
    reads, and the report states the loss per strand."""
    import json as _json

    from duplexumiconsensusreads_tpu.cli import main

    path = str(tmp_path / "indel.bam")
    cfg = SimConfig(
        n_molecules=120, mean_family_size=5, indel_error=0.06, duplex=True,
        seed=8,
    )
    simulated_bam(cfg, path=path, sort=True)
    out = str(tmp_path / "c.bam")
    rep_path = str(tmp_path / "r.json")
    assert main([
        "call", path, "-o", out, "--config", "config3", "--capacity", "256",
        "--report", rep_path,
    ]) == 0
    rep = _json.load(open(rep_path))
    dropped = rep["n_dropped_cigar_ab"] + rep["n_dropped_cigar_ba"]
    assert dropped > 0  # the sim does produce minority indel reads
    # ceiling: with 6% per-read indel prob and ~5-read families, the
    # modal vote should never discard more than ~12% of records
    assert dropped / rep["n_records"] < 0.12
    # both strands appear in the split (duplex sim, symmetric error)
    assert rep["n_dropped_cigar_ab"] > 0 and rep["n_dropped_cigar_ba"] > 0


def test_softclip_rescue_requires_same_alignment_start(tmp_path):
    """Family membership does NOT imply same alignment start: paired
    mates share (pos_key, UMI, strand) while their own POS differ, and
    a repeat-region minority can start a few bases off. The rescue must
    skip both — a clip-lead-only shift would inject misaligned
    evidence (r4 review finding)."""
    from duplexumiconsensusreads_tpu.io.bam import (
        FLAG_PAIRED,
        FLAG_READ1,
        FLAG_READ2,
        FLAG_REVERSE,
        BamHeader,
        BamRecords,
        write_bam,
    )

    rng = np.random.default_rng(6)
    L = 40
    # one template: three R1 copies at pos 100 (modal cigar) and one R2
    # at pos 250 whose cigar is a soft-clip variant of the SAME core —
    # same pos_key (min(pos, next_pos) = 100), same strand (F1R2 -> R1
    # fwd top, R2 rev top)
    cigs = [
        [(5, "S"), (30, "M"), (5, "S")],
        [(5, "S"), (30, "M"), (5, "S")],
        [(5, "S"), (30, "M"), (5, "S")],
        [(3, "S"), (30, "M"), (7, "S")],
    ]
    n = len(cigs)
    flags = np.array(
        [FLAG_PAIRED | FLAG_READ1] * 3
        + [FLAG_PAIRED | FLAG_READ2 | FLAG_REVERSE],
        np.uint16,
    )
    pos = np.array([100, 100, 100, 250], np.int32)
    next_pos = np.array([250, 250, 250, 100], np.int32)
    recs = BamRecords(
        names=[f"t{i}" for i in range(n)],
        flags=flags,
        ref_id=np.zeros(n, np.int32),
        pos=pos,
        mapq=np.full(n, 60, np.uint8),
        next_ref_id=np.zeros(n, np.int32),
        next_pos=next_pos,
        tlen=np.zeros(n, np.int32),
        lengths=np.full(n, L, np.int32),
        seq=rng.integers(0, 4, (n, L)).astype(np.uint8),
        qual=np.full((n, L), 30, np.uint8),
        cigars=cigs,
        umi=["ACGTAA"] * n,
        aux_raw=[b"RXZACGTAA\x00"] * n,
    )
    path = str(tmp_path / "mates.bam")
    write_bam(path, BamHeader.synthetic(sort_order="coordinate"), recs)
    _, r2 = read_bam(path)
    batch, info = records_to_readbatch(r2, duplex=False)
    # the R2 read must stay DROPPED (not rescued into R1's cycle space)
    assert info["n_rescued_cigar"] == 0
    assert info["n_dropped_cigar"] == 1
    assert not np.asarray(batch.valid)[3]


def test_softclip_rescue_per_mate_donor(tmp_path):
    """Each (family, strand, own-POS) side gets its OWN rescue donor:
    when R1 copies sort first, a family-keyed donor table would pick an
    R1 donor and then skip the R2 minority on the own-POS guard — a
    missed rescue (advisor r4). With the POS in the donor key, the R2
    soft-clip variant is rescued against a kept R2."""
    from duplexumiconsensusreads_tpu.constants import BASE_PAD
    from duplexumiconsensusreads_tpu.io.bam import (
        FLAG_PAIRED,
        FLAG_READ1,
        FLAG_READ2,
        FLAG_REVERSE,
        BamHeader,
        BamRecords,
        write_bam,
    )

    rng = np.random.default_rng(9)
    L = 40
    # 3 R1 at pos 100 + 2 R2 at pos 250 share the modal cigar; one R2
    # at pos 250 is a soft-clip variant of the same 30M core
    cigs = [
        [(5, "S"), (30, "M"), (5, "S")],
        [(5, "S"), (30, "M"), (5, "S")],
        [(5, "S"), (30, "M"), (5, "S")],
        [(5, "S"), (30, "M"), (5, "S")],
        [(5, "S"), (30, "M"), (5, "S")],
        [(3, "S"), (30, "M"), (7, "S")],
    ]
    n = len(cigs)
    flags = np.array(
        [FLAG_PAIRED | FLAG_READ1] * 3
        + [FLAG_PAIRED | FLAG_READ2 | FLAG_REVERSE] * 3,
        np.uint16,
    )
    pos = np.array([100, 100, 100, 250, 250, 250], np.int32)
    next_pos = np.where(pos == 100, 250, 100).astype(np.int32)
    recs = BamRecords(
        names=[f"t{i}" for i in range(n)],
        flags=flags,
        ref_id=np.zeros(n, np.int32),
        pos=pos,
        mapq=np.full(n, 60, np.uint8),
        next_ref_id=np.zeros(n, np.int32),
        next_pos=next_pos,
        tlen=np.zeros(n, np.int32),
        lengths=np.full(n, L, np.int32),
        seq=rng.integers(0, 4, (n, L)).astype(np.uint8),
        qual=np.full((n, L), 30, np.uint8),
        cigars=cigs,
        umi=["ACGTAA"] * n,
        aux_raw=[b"RXZACGTAA\x00"] * n,
    )
    path = str(tmp_path / "mate_donor.bam")
    write_bam(path, BamHeader.synthetic(sort_order="coordinate"), recs)
    _, r2 = read_bam(path)
    batch, info = records_to_readbatch(r2, duplex=False)
    assert info["n_rescued_cigar"] == 1
    assert np.asarray(batch.valid).all()
    # rescued row 5: its 30M core (query 3..32) lands at the R2 donor's
    # modal lead (cycles 5..34)
    b = np.asarray(batch.bases)
    np.testing.assert_array_equal(b[5, 5:35], np.asarray(r2.seq)[5, 3:33])
    assert (b[5, :5] == BASE_PAD).all() and (b[5, 35:] == BASE_PAD).all()
