"""Streaming executor tests: rolling BGZF/BAM reader, chunk boundary
(family carry-over) handling, streamed-vs-wholefile equivalence, and
checkpoint/resume."""

import json

import numpy as np
import pytest

from duplexumiconsensusreads_tpu.cli import main
from duplexumiconsensusreads_tpu.io import read_bam, simulated_bam
from duplexumiconsensusreads_tpu.runtime.stream import (
    BamStreamReader,
    iter_record_chunks,
    stream_call_consensus,
)
from duplexumiconsensusreads_tpu.simulate import SimConfig
from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams


def _sorted_bam(tmp_path, n_mol=120, **kw):
    path = str(tmp_path / "sorted.bam")
    cfg = SimConfig(
        n_molecules=n_mol,
        n_positions=kw.pop("n_positions", 12),
        umi_error=kw.pop("umi_error", 0.02),
        seed=kw.pop("seed", 23),
        **kw,
    )
    header, recs, batch, truth = simulated_bam(cfg, path=path, sort=True)
    return path, recs, truth


class TestStreamReader:
    def test_header_and_records_match_wholefile(self, tmp_path):
        path, recs, _ = _sorted_bam(tmp_path)
        r = BamStreamReader(path, read_size=4096)  # force many refills
        assert r.header.ref_names == ["chr1"]
        total = 0
        while True:
            raw = r.read_raw_records(37)
            if raw is None:
                break
            total += raw.count(b"RXZ")  # one RX tag per record
        r.close()
        assert total == len(recs)

    def test_chunks_cover_all_reads_without_splitting_groups(self, tmp_path):
        path, recs, _ = _sorted_bam(tmp_path)
        seen = 0
        for header, chunk in iter_record_chunks(path, chunk_reads=97):
            pos = np.asarray(chunk.pos)
            seen += len(chunk)
            # within a chunk, positions non-decreasing
            assert (np.diff(pos) >= 0).all()
        assert seen == len(recs)
        # group integrity: every position appears in exactly one chunk
        chunks = list(iter_record_chunks(path, chunk_reads=97))
        pos_sets = [set(np.asarray(c.pos).tolist()) for _, c in chunks]
        for i in range(len(pos_sets)):
            for j in range(i + 1, len(pos_sets)):
                assert not (pos_sets[i] & pos_sets[j])

    def test_native_stream_matches_python_codec(self, tmp_path):
        from duplexumiconsensusreads_tpu.native import native_available

        if not native_available():
            pytest.skip("native loader unavailable")
        path, recs, _ = _sorted_bam(tmp_path)

        def drain(use_native, read_size):
            r = BamStreamReader(path, read_size=read_size, use_native=use_native)
            out = []
            while True:
                raw = r.read_raw_records(41)
                if raw is None:
                    break
                out.append(raw)
            r.close()
            return r.header, b"".join(out)

        h_py, raw_py = drain(False, 4096)
        h_nat, raw_nat = drain(True, 4096)  # small reads: many native calls
        assert h_py.text == h_nat.text and h_py.ref_names == h_nat.ref_names
        assert raw_py == raw_nat
        # large read_size: whole file in one native inflate batch
        _, raw_one = drain(True, 64 << 20)
        assert raw_one == raw_py

    def test_single_position_file(self, tmp_path):
        path, recs, _ = _sorted_bam(tmp_path, n_mol=30, n_positions=1)
        chunks = list(iter_record_chunks(path, chunk_reads=10))
        assert len(chunks) == 1  # one giant group, one chunk
        assert len(chunks[0][1]) == len(recs)

    @pytest.mark.parametrize("paired_end", [False, True])
    def test_iter_batch_chunks_native_matches_python(
        self, tmp_path, monkeypatch, paired_end
    ):
        """The native chunk iterator must produce bit-identical batches
        AND identical chunk boundaries to the per-record Python path
        (checkpoint manifests depend on the boundary equivalence)."""
        from duplexumiconsensusreads_tpu.native import native_available
        from duplexumiconsensusreads_tpu.runtime.stream import iter_batch_chunks

        if not native_available():
            pytest.skip("native loader unavailable")
        path = str(tmp_path / "in.bam")
        cfg = SimConfig(n_molecules=90, n_positions=10, umi_error=0.02, seed=7)
        simulated_bam(cfg, path=path, sort=True, paired_end=paired_end)

        def drain():
            return [
                (b, i) for _, b, i in iter_batch_chunks(path, 83, duplex=True)
            ]

        nat = drain()
        monkeypatch.setenv("DUT_NO_NATIVE", "1")
        py = drain()
        assert len(nat) == len(py)
        for (bn, infn), (bp, infp) in zip(nat, py):
            assert infn["n_valid"] == infp["n_valid"]
            np.testing.assert_array_equal(bn.pos_key, bp.pos_key)
            np.testing.assert_array_equal(bn.umi, bp.umi)
            np.testing.assert_array_equal(bn.bases, bp.bases)
            np.testing.assert_array_equal(bn.quals, bp.quals)
            np.testing.assert_array_equal(bn.strand_ab, bp.strand_ab)
            np.testing.assert_array_equal(bn.valid, bp.valid)


class TestStreamedCall:
    def _call(self, path, out, **kw):
        gp = GroupingParams(strategy="adjacency", paired=True)
        cp = ConsensusParams(mode="duplex")
        return stream_call_consensus(
            path, out, gp, cp, capacity=256, chunk_reads=150, **kw
        )

    def test_matches_wholefile(self, tmp_path):
        path, _, _ = _sorted_bam(tmp_path)
        out_s = str(tmp_path / "stream.bam")
        out_w = str(tmp_path / "whole.bam")
        rep = self._call(path, out_s)
        assert rep.n_consensus > 0
        assert main(
            ["call", path, "-o", out_w, "--config", "config3",
             "--backend", "tpu", "--capacity", "256"]
        ) == 0
        _, rs = read_bam(out_s)
        _, rw = read_bam(out_w)
        assert len(rs) == len(rw)
        key_s = {(int(rs.pos[i]), rs.umi[i]): i for i in range(len(rs))}
        for j in range(len(rw)):
            i = key_s[(int(rw.pos[j]), rw.umi[j])]
            np.testing.assert_array_equal(rs.seq[i], rw.seq[j])
            np.testing.assert_array_equal(rs.qual[i], rw.qual[j])

    def test_checkpoint_resume_skips_done_chunks(self, tmp_path):
        path, _, _ = _sorted_bam(tmp_path)
        out = str(tmp_path / "c.bam")
        ck = str(tmp_path / "ck.json")
        rep1 = self._call(path, out, checkpoint_path=ck, resume=False)
        with open(ck) as f:
            manifest = json.load(f)
        assert len(manifest["done"]) >= 2
        _, r1 = read_bam(out)

        # resume: all chunks already done -> no device work needed,
        # output identical
        rep2 = self._call(path, out, checkpoint_path=ck, resume=True)
        assert rep2.n_buckets == 0  # nothing re-dispatched
        _, r2 = read_bam(out)
        assert r1.names == r2.names
        np.testing.assert_array_equal(r1.seq, r2.seq)

    def test_fingerprint_invalidation(self, tmp_path):
        path, _, _ = _sorted_bam(tmp_path)
        out = str(tmp_path / "d.bam")
        ck = str(tmp_path / "ck2.json")
        self._call(path, out, checkpoint_path=ck, resume=False)
        # different params -> fingerprint mismatch -> full re-run
        gp = GroupingParams(strategy="exact", paired=True)
        cp = ConsensusParams(mode="duplex")
        rep = stream_call_consensus(
            path, out, gp, cp, capacity=256, chunk_reads=150,
            checkpoint_path=ck, resume=True,
        )
        assert rep.n_buckets > 0  # did not skip


def test_unmapped_reads_at_eof_stream_cleanly(tmp_path):
    """A standard coordinate-sorted BAM carries its unmapped reads at
    EOF (ref_id=-1, pos=-1). Their pos_key must sort LAST (sentinel),
    not sign-extend to -1 and trip the sort-contract check; conversion
    must drop them via the FLAG filter."""
    from duplexumiconsensusreads_tpu.io import write_bam
    from duplexumiconsensusreads_tpu.io.bam import FLAG_UNMAPPED
    from duplexumiconsensusreads_tpu.io.convert import records_to_readbatch
    from duplexumiconsensusreads_tpu.runtime.stream import _concat_records, _slice_records

    path = str(tmp_path / "mapped.bam")
    cfg = SimConfig(n_molecules=40, n_positions=6, seed=7)
    header, recs, *_ = simulated_bam(cfg, path=path, sort=True)

    import copy as _copy

    # tail LARGER than chunk_reads: the flush branch must fire on
    # multiple consecutive all-sentinel chunks without tripping the
    # cross-boundary repeat check or accumulating carry
    tail = _copy.deepcopy(_slice_records(recs, 0, 150))  # slices are views
    tail.flags[:] = FLAG_UNMAPPED
    tail.ref_id[:] = -1
    tail.pos[:] = -1
    tail.next_ref_id[:] = -1
    tail.next_pos[:] = -1
    full = _concat_records(recs, tail)
    path2 = str(tmp_path / "with_unmapped.bam")
    write_bam(path2, header, full)

    seen = 0
    n_flag_dropped = 0
    for _, chunk in iter_record_chunks(path2, chunk_reads=60):
        assert len(chunk) <= 60 + 150  # no unbounded carry growth
        _, info = records_to_readbatch(chunk, duplex=True)
        n_flag_dropped += info["n_dropped_flag"]
        seen += len(chunk)
    assert seen == len(recs) + 150
    assert n_flag_dropped == 150


def test_mapped_after_unmapped_tail_rejected(tmp_path):
    """Mapped records AFTER the unmapped tail violate the sort contract
    and must raise (the flush path must not let them slip past the
    cross-boundary repeat check and split a family)."""
    import copy as _copy

    from duplexumiconsensusreads_tpu.io import write_bam
    from duplexumiconsensusreads_tpu.io.bam import FLAG_UNMAPPED
    from duplexumiconsensusreads_tpu.runtime.stream import _concat_records, _slice_records

    path = str(tmp_path / "m.bam")
    cfg = SimConfig(n_molecules=30, n_positions=5, seed=9)
    header, recs, *_ = simulated_bam(cfg, path=path, sort=True)
    mid = _copy.deepcopy(_slice_records(recs, 0, 40))
    mid.flags[:] = FLAG_UNMAPPED
    mid.ref_id[:] = -1
    mid.pos[:] = -1
    mid.next_ref_id[:] = -1
    mid.next_pos[:] = -1
    bad = _concat_records(
        _concat_records(_slice_records(recs, 0, len(recs) // 2), mid),
        _slice_records(recs, len(recs) // 2, len(recs)),
    )
    path2 = str(tmp_path / "bad_order.bam")
    write_bam(path2, header, bad)
    with pytest.raises(ValueError, match="sort contract"):
        list(iter_record_chunks(path2, chunk_reads=30))


def test_resume_report_counts_fresh_work_only(tmp_path):
    path, _, _ = _sorted_bam(tmp_path, n_mol=60)
    out = str(tmp_path / "r.bam")
    ck = str(tmp_path / "ckr.json")
    gp = GroupingParams(strategy="exact", paired=True)
    cp = ConsensusParams(mode="duplex")
    kw = dict(capacity=256, chunk_reads=120, checkpoint_path=ck)
    rep1 = stream_call_consensus(path, out, gp, cp, resume=False, **kw)
    rep2 = stream_call_consensus(path, out, gp, cp, resume=True, **kw)
    # fully-resumed run did no fresh work: per-read counters are zero,
    # chunk accounting still covers the file
    assert rep2.n_records == 0
    assert rep2.n_valid_reads == 0
    assert rep2.n_chunks == rep1.n_chunks
    assert rep2.n_chunks_skipped == rep1.n_chunks
    assert rep2.n_consensus == rep1.n_consensus


def test_nonresume_clears_manifest_on_disk(tmp_path):
    """resume=False must persist the cleared manifest BEFORE any work:
    if the run crashes before its first mark(), stale done-entries must
    not survive on disk to be resurrected by a later --resume."""
    from duplexumiconsensusreads_tpu.runtime.stream import _fingerprint

    # unsorted input raises inside the chunk loop, before any mark()
    bad = str(tmp_path / "unsorted.bam")
    cfg = SimConfig(n_molecules=60, n_positions=8, seed=2)
    simulated_bam(cfg, path=bad, sort=False)
    gp = GroupingParams(strategy="exact", paired=True)
    cp = ConsensusParams(mode="duplex")

    ck = str(tmp_path / "ck3.json")
    shard = str(tmp_path / "stale_shard")
    open(shard, "w").close()  # must exist: load_or_create prunes dead paths
    fp = _fingerprint(bad, gp, cp, 256, 50)
    # stale manifests with BOTH matching and mismatching fingerprints
    # must be wiped: this run overwrites the shard files either way
    for stale_fp in (fp, "0123456789abcdef"):
        with open(ck, "w") as f:
            json.dump({"fingerprint": stale_fp, "done": {"0": shard}}, f)
        with pytest.raises(ValueError, match="sort contract"):
            stream_call_consensus(
                bad, str(tmp_path / "o.bam"), gp, cp, capacity=256,
                chunk_reads=50, checkpoint_path=ck, resume=False,
            )
        with open(ck) as f:
            d = json.load(f)
        assert d["done"] == {} and d["fingerprint"] == fp

    # resume=True with a MISMATCHED fingerprint has the same crash
    # window: load_or_create must persist the fresh manifest up front
    with open(ck, "w") as f:
        json.dump({"fingerprint": "feedfacefeedface", "done": {"0": shard}}, f)
    with pytest.raises(ValueError, match="sort contract"):
        stream_call_consensus(
            bad, str(tmp_path / "o.bam"), gp, cp, capacity=256,
            chunk_reads=50, checkpoint_path=ck, resume=True,
        )
    with open(ck) as f:
        d = json.load(f)
    assert d["done"] == {} and d["fingerprint"] == fp


def test_unsorted_input_rejected(tmp_path):
    """The streaming sort contract is validated, not assumed: unsorted
    input must raise instead of silently splitting families."""
    path = str(tmp_path / "unsorted.bam")
    cfg = SimConfig(n_molecules=60, n_positions=8, seed=2)
    simulated_bam(cfg, path=path, sort=False)  # simulator shuffles reads
    with pytest.raises(ValueError, match="sort contract"):
        list(iter_record_chunks(path, chunk_reads=50))


def test_unsorted_final_range_chunk_rejected(tmp_path):
    """Range mode's key_hi early-exit must validate the sort contract
    BEFORE its searchsorted cut: an unsorted final in-range chunk has
    to raise, not silently mis-truncate (ADVICE r2)."""
    from duplexumiconsensusreads_tpu.runtime.stream import iter_batch_chunks

    path = str(tmp_path / "unsorted.bam")
    cfg = SimConfig(n_molecules=60, n_positions=8, seed=2)
    simulated_bam(cfg, path=path, sort=False)  # simulator shuffles reads
    # a key_hi below the max pos_key forces the early-exit path on the
    # very first (unsorted) chunk
    with pytest.raises(ValueError, match="sort contract"):
        # keys are in [1000, 8000]; key_hi=999 guarantees the final
        # chunk triggers the early exit (keys[-1] >= key_hi) where the
        # old code would silently emit nothing
        list(iter_batch_chunks(path, 10_000, duplex=True, key_hi=999))


def test_shards_cleaned_without_checkpoint(tmp_path):
    import os

    path, _, _ = _sorted_bam(tmp_path, n_mol=40)
    out = str(tmp_path / "clean.bam")
    gp = GroupingParams(strategy="exact", paired=True)
    cp = ConsensusParams(mode="duplex")
    stream_call_consensus(path, out, gp, cp, capacity=256, chunk_reads=100)
    assert not os.path.exists(out + ".shards")
    _, recs = read_bam(out)
    assert len(recs) > 0


class TestFaultRecovery:
    def _sim(self, tmp_path):
        return _sorted_bam(tmp_path, n_mol=80, n_positions=8)

    def test_transient_dispatch_failures_recovered(self, tmp_path, monkeypatch):
        """Kill several device dispatches; the run must complete with
        output identical to a fault-free run (VERDICT r1 item 10)."""
        import duplexumiconsensusreads_tpu.parallel.sharded as sharded

        path, _, _ = self._sim(tmp_path)
        gp = GroupingParams(strategy="adjacency", paired=True)
        cp = ConsensusParams(mode="duplex")
        kw = dict(capacity=128, chunk_reads=120, max_retries=2)

        ref = str(tmp_path / "ref.bam")
        rep0 = stream_call_consensus(path, ref, gp, cp, **kw)

        # presharded_pipeline is THE dispatch seam: the 1-device path
        # reaches it through sharded_pipeline and the multi-device path
        # calls it directly after its per-device puts
        real = sharded.presharded_pipeline
        calls = {"n": 0}

        def flaky(*a, **k):
            calls["n"] += 1
            if calls["n"] in (2, 3, 5):  # transient outage
                raise RuntimeError("injected device failure")
            return real(*a, **k)

        monkeypatch.setattr(sharded, "presharded_pipeline", flaky)
        monkeypatch.setattr(
            "duplexumiconsensusreads_tpu.runtime.stream.time.sleep",
            lambda s: None,
            raising=False,
        )
        out = str(tmp_path / "faulty.bam")
        rep = stream_call_consensus(path, out, gp, cp, **kw)
        assert rep.n_retries >= 1
        assert rep.n_consensus == rep0.n_consensus
        _, r_ref = read_bam(ref)
        _, r_out = read_bam(out)
        np.testing.assert_array_equal(r_ref.pos, r_out.pos)
        np.testing.assert_array_equal(r_ref.seq, r_out.seq)
        np.testing.assert_array_equal(r_ref.qual, r_out.qual)

    def test_poisoned_class_isolated_per_bucket(self, tmp_path, monkeypatch):
        """A class whose stacked dispatch always fails must fall back to
        bucket-by-bucket dispatch and still finish."""
        import duplexumiconsensusreads_tpu.parallel.sharded as sharded

        path, _, _ = self._sim(tmp_path)
        gp = GroupingParams(strategy="adjacency", paired=True)
        cp = ConsensusParams(mode="duplex")
        real = sharded.presharded_pipeline

        def multi_bucket_fails(stacked, spec, mesh, *a, **k):
            if stacked["pos"].shape[0] > 1:
                raise RuntimeError("injected: stacked dispatch down")
            return real(stacked, spec, mesh, *a, **k)

        monkeypatch.setattr(
            sharded, "presharded_pipeline", multi_bucket_fails
        )
        monkeypatch.setattr(
            "duplexumiconsensusreads_tpu.runtime.stream.time.sleep",
            lambda s: None,
            raising=False,
        )
        out = str(tmp_path / "iso.bam")
        rep = stream_call_consensus(
            path, out, gp, cp, capacity=128, chunk_reads=120,
            max_retries=1, n_devices=1,
        )
        assert rep.n_retries >= 1
        _, recs = read_bam(out)
        assert len(recs) == rep.n_consensus > 0

    def test_permanent_failure_raises(self, tmp_path, monkeypatch):
        import duplexumiconsensusreads_tpu.parallel.sharded as sharded

        path, _, _ = self._sim(tmp_path)
        gp = GroupingParams(strategy="exact", paired=True)
        cp = ConsensusParams(mode="duplex")

        def dead(*a, **k):
            raise RuntimeError("injected: device gone")

        monkeypatch.setattr(sharded, "presharded_pipeline", dead)
        monkeypatch.setattr(
            "duplexumiconsensusreads_tpu.runtime.stream.time.sleep",
            lambda s: None,
            raising=False,
        )
        with pytest.raises(RuntimeError, match="giving up"):
            stream_call_consensus(
                path, str(tmp_path / "x.bam"), gp, cp,
                capacity=128, chunk_reads=120, max_retries=1,
            )

    def test_auto_checkpoint_resume_after_crash(self, tmp_path, monkeypatch):
        """Chunked runs checkpoint by default: crash mid-run, rerun with
        resume=True and no explicit checkpoint path -> finished chunks
        are skipped and output is complete."""
        import os

        path, _, _ = _sorted_bam(tmp_path, n_mol=120, n_positions=12)
        gp = GroupingParams(strategy="adjacency", paired=True)
        cp = ConsensusParams(mode="duplex")
        out = str(tmp_path / "auto.bam")
        kw = dict(capacity=128, chunk_reads=100)

        boom = {"after": 2}

        def crashing_progress(k, rep):
            if rep.n_chunks >= boom["after"]:
                raise KeyboardInterrupt("injected crash")

        with pytest.raises(KeyboardInterrupt):
            stream_call_consensus(
                path, out, gp, cp, progress=crashing_progress, **kw
            )
        assert os.path.exists(out + ".ckpt")  # auto checkpoint persisted

        rep = stream_call_consensus(path, out, gp, cp, resume=True, **kw)
        assert rep.n_chunks_skipped >= 1
        assert not os.path.exists(out + ".ckpt")  # cleaned on success
        assert not os.path.exists(out + ".shards")
        ref = str(tmp_path / "ref.bam")
        rep0 = stream_call_consensus(path, ref, gp, cp, **kw)
        _, r_ref = read_bam(ref)
        _, r_out = read_bam(out)
        assert rep.n_consensus == rep0.n_consensus
        np.testing.assert_array_equal(r_ref.seq, r_out.seq)


def test_drain_workers_ab_byte_identical(tmp_path):
    """The acceptance A/B: serial drain (--drain-workers 1) vs a wide
    pool must produce byte-identical output, and the report must carry
    the overlapped busy-time accounting fields."""
    path, _, _ = _sorted_bam(tmp_path)
    gp = GroupingParams(strategy="adjacency", paired=True)
    cp = ConsensusParams(mode="duplex")
    outs = {}
    for n in (1, 3):
        out = str(tmp_path / f"dw{n}.bam")
        rep = stream_call_consensus(
            path, out, gp, cp, capacity=256, chunk_reads=150, drain_workers=n
        )
        assert rep.n_drain_workers == n
        assert "main_loop_stall" in rep.seconds
        assert "drain_utilization" in rep.seconds
        assert 0.0 <= rep.seconds["drain_utilization"] <= 1.0
        with open(out, "rb") as f:
            outs[n] = f.read()
    assert outs[1] == outs[3]


def test_drain_workers_validated():
    gp = GroupingParams(strategy="exact", paired=True)
    cp = ConsensusParams(mode="duplex")
    with pytest.raises(ValueError, match="drain_workers"):
        stream_call_consensus(
            "nonexistent.bam", "out.bam", gp, cp, chunk_reads=10,
            drain_workers=0,
        )


def test_wire_diet_knobs_validated():
    gp = GroupingParams(strategy="exact", paired=True)
    cp = ConsensusParams(mode="duplex")
    for kw, match in [
        (dict(prefetch_depth=0), "prefetch_depth"),
        (dict(packed="subbyte"), "packed"),
        (dict(d2h_packed="on"), "d2h_packed"),
        (dict(ingest_overlap="background"), "ingest_overlap"),
    ]:
        with pytest.raises(ValueError, match=match):
            stream_call_consensus(
                "nonexistent.bam", "out.bam", gp, cp, chunk_reads=10, **kw
            )


class TestWireDietMatrix:
    """The wire-diet acceptance A/B: every combination of H2D packing
    rung (off / byte / auto=sub-byte), packed D2H (on / off) and
    prefetch depth (1 / 2 / 3) must produce output BYTE-IDENTICAL to
    the fully-unpacked serial reference — packing and prefetch are wire
    transforms, never result transforms."""

    @pytest.fixture(scope="class")
    def matrix_sim(self, tmp_path_factory):
        d = tmp_path_factory.mktemp("wirediet")
        path = str(d / "in.bam")
        # default qual model (uniform 20..40: a 21-value alphabet) so
        # "auto" exercises the 5-bit-dictionary (7 bits/cycle) rung
        cfg = SimConfig(n_molecules=60, n_positions=8, umi_error=0.02, seed=31)
        simulated_bam(cfg, path=path, sort=True)
        gp = GroupingParams(strategy="adjacency", paired=True)
        cp = ConsensusParams(mode="duplex")
        ref = str(d / "ref.bam")
        rep = stream_call_consensus(
            path, ref, gp, cp, capacity=128, chunk_reads=90,
            packed="off", d2h_packed="off", prefetch_depth=1,
        )
        assert rep.n_chunks >= 3
        with open(ref, "rb") as f:
            return path, gp, cp, f.read(), rep

    @pytest.mark.parametrize("packed", ["off", "byte", "auto"])
    @pytest.mark.parametrize("d2h", ["off", "auto"])
    @pytest.mark.parametrize("depth", [1, 2, 3])
    def test_byte_identity(self, matrix_sim, tmp_path, packed, d2h, depth):
        path, gp, cp, ref_bytes, ref_rep = matrix_sim
        out = str(tmp_path / f"{packed}_{d2h}_{depth}.bam")
        rep = stream_call_consensus(
            path, out, gp, cp, capacity=128, chunk_reads=90,
            packed=packed, d2h_packed=d2h, prefetch_depth=depth,
        )
        with open(out, "rb") as f:
            assert f.read() == ref_bytes
        # the knobs really moved bytes (h2d shrinks with the rung, d2h
        # with the packed return path), while results never change
        assert rep.n_consensus == ref_rep.n_consensus
        if packed == "off":
            assert rep.bytes_h2d == ref_rep.bytes_h2d
        else:
            assert rep.bytes_h2d < ref_rep.bytes_h2d
        if d2h == "auto" and packed != "off":
            assert rep.bytes_d2h < ref_rep.bytes_d2h
        else:
            assert rep.bytes_d2h == ref_rep.bytes_d2h

    def test_auto_outpacks_byte_rung(self, matrix_sim, tmp_path):
        """The sub-byte dictionary rung moves strictly fewer H2D bytes
        than the byte rung on a dictionary-fitting alphabet."""
        path, gp, cp, ref_bytes, _ = matrix_sim
        reps = {}
        for packed in ("byte", "auto"):
            out = str(tmp_path / f"r_{packed}.bam")
            reps[packed] = stream_call_consensus(
                path, out, gp, cp, capacity=128, chunk_reads=90,
                packed=packed, d2h_packed="off",
            )
            with open(out, "rb") as f:
                assert f.read() == ref_bytes
        assert reps["auto"].bytes_h2d < reps["byte"].bytes_h2d


class TestIngestOverlap:
    """The pipelined-ingest acceptance A/B: the background producer is
    a scheduling transform, never a result transform — every combination
    of ingest_overlap rung (off / on / auto=on) and prefetch depth
    (1 / 2, which bounds the handoff queue) on the 2-virtual-device mesh
    must produce output BYTE-IDENTICAL to the synchronous serial
    reference, with the report flag telling the truth about which path
    ran."""

    @pytest.fixture(scope="class")
    def overlap_sim(self, tmp_path_factory):
        d = tmp_path_factory.mktemp("ingestoverlap")
        path = str(d / "in.bam")
        cfg = SimConfig(n_molecules=60, n_positions=8, umi_error=0.02, seed=31)
        simulated_bam(cfg, path=path, sort=True)
        gp = GroupingParams(strategy="adjacency", paired=True)
        cp = ConsensusParams(mode="duplex")
        ref = str(d / "ref.bam")
        rep = stream_call_consensus(
            path, ref, gp, cp, capacity=128, chunk_reads=90,
            ingest_overlap="off", prefetch_depth=1,
        )
        assert rep.n_chunks >= 3  # several producer handoffs per run
        assert rep.ingest_overlap is False
        with open(ref, "rb") as f:
            return path, gp, cp, f.read(), rep

    @pytest.mark.parametrize("overlap", ["off", "on", "auto"])
    @pytest.mark.parametrize("depth", [1, 2])
    def test_byte_identity(self, overlap_sim, tmp_path, overlap, depth):
        path, gp, cp, ref_bytes, ref_rep = overlap_sim
        out = str(tmp_path / f"ov_{overlap}_{depth}.bam")
        rep = stream_call_consensus(
            path, out, gp, cp, capacity=128, chunk_reads=90,
            ingest_overlap=overlap, prefetch_depth=depth,
        )
        with open(out, "rb") as f:
            assert f.read() == ref_bytes
        assert rep.n_consensus == ref_rep.n_consensus
        assert rep.n_chunks == ref_rep.n_chunks
        assert rep.ingest_overlap is (overlap != "off")
        # the knob schedules host work, it never moves different bytes
        assert rep.bytes_h2d == ref_rep.bytes_h2d
        assert rep.bytes_d2h == ref_rep.bytes_d2h

    def test_overlap_run_reports_ingest_lane_and_stall_keys(
        self, overlap_sim, tmp_path
    ):
        """An overlap run's trace carries ingest/bucketing spans on the
        dedicated ingest lane, and the report's seconds table accounts
        the producer's stall/backpressure phases."""
        from duplexumiconsensusreads_tpu.telemetry.report import validate_trace

        path, gp, cp, ref_bytes, _ = overlap_sim
        out = str(tmp_path / "traced.bam")
        tr = str(tmp_path / "traced.trace.jsonl")
        rep = stream_call_consensus(
            path, out, gp, cp, capacity=128, chunk_reads=90,
            ingest_overlap="on", trace_path=tr,
        )
        with open(out, "rb") as f:
            assert f.read() == ref_bytes
        assert rep.ingest_overlap is True
        assert {"ingest_stall", "ingest_backpressure"} <= set(rep.seconds)
        with open(tr) as f:
            records = [json.loads(line) for line in f if line.strip()]
        assert validate_trace(records) == []
        lanes = {
            r.get("lane") for r in records
            if r.get("type") == "span" and r.get("stage") in ("ingest", "bucketing")
        }
        assert "ingest" in lanes

    def test_off_run_has_no_ingest_lane(self, overlap_sim, tmp_path):
        path, gp, cp, ref_bytes, _ = overlap_sim
        out = str(tmp_path / "sync.bam")
        tr = str(tmp_path / "sync.trace.jsonl")
        stream_call_consensus(
            path, out, gp, cp, capacity=128, chunk_reads=90,
            ingest_overlap="off", trace_path=tr,
        )
        with open(tr) as f:
            records = [json.loads(line) for line in f if line.strip()]
        assert not any(
            r.get("lane") == "ingest" for r in records if r.get("type") == "span"
        )


class TestPackingRungSelection:
    """Per-chunk rung decisions: dictionary width follows the qual
    alphabet, overflow falls back losslessly, and the pos-u16 capacity
    gate downgrades at partition time instead of failing mid-dispatch."""

    def _run_pair(self, tmp_path, cfg, **kw):
        path = str(tmp_path / "in.bam")
        simulated_bam(cfg, path=path, sort=True)
        gp = GroupingParams(strategy="adjacency", paired=True)
        cp = ConsensusParams(mode="duplex")
        outs = {}
        reps = {}
        for name, pk in [("off", "off"), ("auto", "auto")]:
            out = str(tmp_path / f"{name}.bam")
            reps[name] = stream_call_consensus(
                path, out, gp, cp, capacity=128, chunk_reads=90,
                packed=pk, d2h_packed="off", **kw,
            )
            with open(out, "rb") as f:
                outs[name] = f.read()
        assert outs["auto"] == outs["off"]
        return reps

    def test_narrow_alphabet_takes_5bit_rung(self, tmp_path):
        """A <= 7-value alphabet (RTA-binned instruments) packs at 5
        bits/cycle — strictly below the byte rung's bytes."""
        cfg = SimConfig(
            n_molecules=60, n_positions=8, umi_error=0.02, seed=31,
            qual_lo=30, qual_hi=33,  # 4 distinct quals
        )
        reps = self._run_pair(tmp_path, cfg)
        # 150-cycle reads: 5 bits/cycle stores 5*ceil(150/8)=95 bytes
        # vs 150 at the byte rung — the ratio must beat the byte rung
        assert reps["auto"].bytes_h2d < reps["off"].bytes_h2d * 0.5

    def test_wide_alphabet_falls_back_to_byte_rung_lossless(self, tmp_path):
        """An alphabet past the widest dictionary (> 31 values) must
        fall back to the byte rung — still packed, still lossless."""
        cfg = SimConfig(
            n_molecules=60, n_positions=8, umi_error=0.02, seed=31,
            qual_lo=2, qual_hi=40,  # 39 distinct quals: overflow
        )
        reps = self._run_pair(tmp_path, cfg)
        # byte rung: bases+quals collapse 2 bytes -> 1 per cycle
        assert reps["off"].bytes_h2d * 0.4 < reps["auto"].bytes_h2d
        assert reps["auto"].bytes_h2d < reps["off"].bytes_h2d

    def test_subbyte_rung_exact_past_input_qual_cap(self, tmp_path):
        """The dictionary rung carries quals verbatim, so it stays
        exact even where the byte rung's 6-bit payload gate
        (max_input_qual > 62) would force unpacked transfer."""
        path = str(tmp_path / "in.bam")
        cfg = SimConfig(
            n_molecules=60, n_positions=8, umi_error=0.02, seed=31,
            qual_lo=30, qual_hi=33,
        )
        simulated_bam(cfg, path=path, sort=True)
        gp = GroupingParams(strategy="adjacency", paired=True)
        cp = ConsensusParams(mode="duplex", max_input_qual=80)
        outs = {}
        reps = {}
        for pk in ("off", "auto", "byte"):
            out = str(tmp_path / f"{pk}.bam")
            reps[pk] = stream_call_consensus(
                path, out, gp, cp, capacity=128, chunk_reads=90,
                packed=pk, d2h_packed="off",
            )
            with open(out, "rb") as f:
                outs[pk] = f.read()
        assert outs["auto"] == outs["off"] == outs["byte"]
        # byte rung is gated off (6-bit payload would clip qual 80's
        # cap semantics): its leg runs unpacked...
        assert reps["byte"].bytes_h2d == reps["off"].bytes_h2d
        # ...while the dictionary rung still packs
        assert reps["auto"].bytes_h2d < reps["off"].bytes_h2d

    def test_capacity_gate_downgrades_at_partition_time(self, tmp_path):
        """A bucket class whose capacity overflows the u16 pos lane
        must run UNPACKED with a ledgered packed_fallback reason — the
        old pack_stacked ValueError surfaced mid-dispatch inside the
        retry/isolation ladder and poisoned the bucket."""
        from duplexumiconsensusreads_tpu.bucketing import build_buckets
        from duplexumiconsensusreads_tpu.runtime.executor import (
            partition_buckets,
        )
        from duplexumiconsensusreads_tpu.simulate import (
            SimConfig as _SC,
            simulate_batch,
        )
        from duplexumiconsensusreads_tpu.telemetry import trace as telemetry

        batch, _ = simulate_batch(_SC(n_molecules=40, seed=5, duplex=True))
        gp = GroupingParams(strategy="adjacency", paired=True)
        cp = ConsensusParams(mode="duplex")
        buckets = build_buckets(batch, capacity=128, grouping=gp)

        # forge an over-u16 capacity on the class (a real 131072-row
        # bucket would need gigabytes; partition only reads the field)
        class _FakeCap:
            def __init__(self, bk, cap):
                self._bk = bk
                self._cap = cap

            def __getattr__(self, name):
                return getattr(self._bk, name)

            @property
            def capacity(self):
                return self._cap

        big = [_FakeCap(bk, 1 << 17) for bk in buckets]

        events = []
        rec = type(
            "R", (), {"event": lambda self, name, **a: events.append((name, a))}
        )()
        telemetry.install(rec)
        try:
            part = partition_buckets(
                big, gp, cp, packed_io=True,
                qual_alphabet=(30, 31, 32, 33),
            )
        finally:
            telemetry.uninstall()
        assert all(not spec.packed_io for _, spec in part)
        assert any(
            name == "packed_fallback"
            and a["reason"] == "pos-ids-overflow-u16"
            for name, a in events
        )
        # the same buckets at sane capacity pack (and pick the rung)
        part2 = partition_buckets(
            buckets, gp, cp, packed_io=True, qual_alphabet=(30, 31, 32, 33),
        )
        assert all(spec.packed_io for _, spec in part2)
        assert all(spec.packed_qbits == 3 for _, spec in part2)
        # boundary pin: capacity EXACTLY 2**16 still fits the u16 pos
        # lane (dense ids < capacity, so <= 65535) and must pack
        edge = [_FakeCap(bk, 1 << 16) for bk in buckets]
        part3 = partition_buckets(
            edge, gp, cp, packed_io=True, qual_alphabet=(30, 31, 32, 33),
        )
        assert all(spec.packed_io for _, spec in part3)

    def test_d2h_compaction_overflow_is_loud_and_unretried(self, tmp_path):
        """A forged device count past the compaction bound must raise
        the dedicated D2hCompactionOverflow — the deterministic
        invariant violation the streaming ladder re-raises immediately
        instead of burning re-dispatches on."""
        import numpy as np

        from duplexumiconsensusreads_tpu.bucketing import (
            build_buckets,
            stack_buckets,
        )
        from duplexumiconsensusreads_tpu.ops.pipeline import spec_for_buckets
        from duplexumiconsensusreads_tpu.parallel import make_mesh
        from duplexumiconsensusreads_tpu.parallel.sharded import (
            sharded_pipeline,
        )
        from duplexumiconsensusreads_tpu.runtime.executor import (
            D2hCompactionOverflow,
            d2h_k_pad,
            fetch_outputs,
            pack_fetch_outputs,
            start_fetch,
            unpack_fetch_outputs,
        )
        from duplexumiconsensusreads_tpu.simulate import (
            SimConfig as _SC,
            simulate_batch,
        )

        batch, _ = simulate_batch(
            _SC(n_molecules=60, duplex=True, umi_error=0.02, seed=7)
        )
        gp = GroupingParams(strategy="adjacency", paired=True)
        cp = ConsensusParams(mode="duplex")
        buckets = build_buckets(batch, capacity=128, grouping=gp)
        spec = spec_for_buckets(buckets, gp, cp)
        out = sharded_pipeline(stack_buckets(buckets), spec, make_mesh(1))
        k_pad = d2h_k_pad(buckets, spec)
        fetched = fetch_outputs(
            start_fetch(
                pack_fetch_outputs(out, spec, k_pad),
                keys=tuple(pack_fetch_outputs(out, spec, k_pad)),
            )
        )
        # sanity: the honest counts round-trip
        unpack_fetch_outputs(dict(fetched), buckets, spec)
        # forge counts past the bound -> loud, typed failure (the
        # unpack clips counts to m_max, so the forge only overflows
        # when the bound is tighter than the padded row count — assert
        # the precondition so the test can't silently go vacuous)
        n_b = np.asarray(fetched["n_molecules"]).shape[0]
        assert k_pad < n_b * (spec.m_max or 128)
        forged = dict(fetched)
        forged["n_molecules"] = np.full_like(
            np.asarray(fetched["n_molecules"]), 1 << 20
        )
        with pytest.raises(D2hCompactionOverflow, match="overflow"):
            unpack_fetch_outputs(forged, buckets, spec)


def test_busy_wall_table_flags_impossible_accounting():
    """The profile/CI canary: a single-threaded stage reporting more
    busy time than the wall is an accounting bug; pooled stages may
    exceed the wall up to their pool size."""
    from duplexumiconsensusreads_tpu.runtime.executor import busy_wall_table

    seconds = {
        "ingest": 12.0,  # > wall on a 1-thread stage: impossible
        "dispatch": 30.0,  # 4-worker pool, <= 4 * wall: legitimate
        "scatter": 15.0,  # 2 drain workers, <= 2 * wall: legitimate
        "main_loop_stall": 1.0,
        "drain_utilization": 0.75,
        "total": 10.0,
    }
    lines, bugs = busy_wall_table(seconds, drain_workers=2)
    assert bugs == ["ingest"]
    assert any("BUSY>WALL" in ln for ln in lines)
    assert not any("scatter" in b for b in bugs)
    # all-sane report: no flags
    _, bugs2 = busy_wall_table(
        {"ingest": 3.0, "scatter": 12.0, "total": 10.0}, drain_workers=2
    )
    assert bugs2 == []


def test_cli_stream_and_validate(tmp_path):
    bam = str(tmp_path / "s.bam")
    truth = str(tmp_path / "t.npz")
    out = str(tmp_path / "o.bam")
    assert main(
        ["simulate", "-o", bam, "--truth", truth, "--molecules", "150",
         "--read-len", "40", "--positions", "10", "--sorted",
         "--base-error", "0.02", "--seed", "3"]
    ) == 0
    assert main(
        ["call", bam, "-o", out, "--config", "config5", "--capacity", "256",
         "--chunk-reads", "200", "--checkpoint", str(tmp_path / "ck.json")]
    ) == 0
    import io as _io
    import contextlib

    buf = _io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert main(["validate", out, "--truth", truth, "--json"]) == 0
    res = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert res["error_rate"] < 0.004
    assert res["n_matched_to_truth"] > 0
