"""Streaming executor tests: rolling BGZF/BAM reader, chunk boundary
(family carry-over) handling, streamed-vs-wholefile equivalence, and
checkpoint/resume."""

import json

import numpy as np
import pytest

from duplexumiconsensusreads_tpu.cli import main
from duplexumiconsensusreads_tpu.io import read_bam, simulated_bam
from duplexumiconsensusreads_tpu.runtime.stream import (
    BamStreamReader,
    iter_record_chunks,
    stream_call_consensus,
)
from duplexumiconsensusreads_tpu.simulate import SimConfig
from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams


def _sorted_bam(tmp_path, n_mol=120, **kw):
    path = str(tmp_path / "sorted.bam")
    cfg = SimConfig(
        n_molecules=n_mol,
        n_positions=kw.pop("n_positions", 12),
        umi_error=kw.pop("umi_error", 0.02),
        seed=kw.pop("seed", 23),
        **kw,
    )
    header, recs, batch, truth = simulated_bam(cfg, path=path, sort=True)
    return path, recs, truth


class TestStreamReader:
    def test_header_and_records_match_wholefile(self, tmp_path):
        path, recs, _ = _sorted_bam(tmp_path)
        r = BamStreamReader(path, read_size=4096)  # force many refills
        assert r.header.ref_names == ["chr1"]
        total = 0
        while True:
            raw = r.read_raw_records(37)
            if raw is None:
                break
            total += raw.count(b"RXZ")  # one RX tag per record
        r.close()
        assert total == len(recs)

    def test_chunks_cover_all_reads_without_splitting_groups(self, tmp_path):
        path, recs, _ = _sorted_bam(tmp_path)
        seen = 0
        for header, chunk in iter_record_chunks(path, chunk_reads=97):
            pos = np.asarray(chunk.pos)
            seen += len(chunk)
            # within a chunk, positions non-decreasing
            assert (np.diff(pos) >= 0).all()
        assert seen == len(recs)
        # group integrity: every position appears in exactly one chunk
        chunks = list(iter_record_chunks(path, chunk_reads=97))
        pos_sets = [set(np.asarray(c.pos).tolist()) for _, c in chunks]
        for i in range(len(pos_sets)):
            for j in range(i + 1, len(pos_sets)):
                assert not (pos_sets[i] & pos_sets[j])

    def test_native_stream_matches_python_codec(self, tmp_path):
        from duplexumiconsensusreads_tpu.native import native_available

        if not native_available():
            pytest.skip("native loader unavailable")
        path, recs, _ = _sorted_bam(tmp_path)

        def drain(use_native, read_size):
            r = BamStreamReader(path, read_size=read_size, use_native=use_native)
            out = []
            while True:
                raw = r.read_raw_records(41)
                if raw is None:
                    break
                out.append(raw)
            r.close()
            return r.header, b"".join(out)

        h_py, raw_py = drain(False, 4096)
        h_nat, raw_nat = drain(True, 4096)  # small reads: many native calls
        assert h_py.text == h_nat.text and h_py.ref_names == h_nat.ref_names
        assert raw_py == raw_nat
        # large read_size: whole file in one native inflate batch
        _, raw_one = drain(True, 64 << 20)
        assert raw_one == raw_py

    def test_single_position_file(self, tmp_path):
        path, recs, _ = _sorted_bam(tmp_path, n_mol=30, n_positions=1)
        chunks = list(iter_record_chunks(path, chunk_reads=10))
        assert len(chunks) == 1  # one giant group, one chunk
        assert len(chunks[0][1]) == len(recs)

    @pytest.mark.parametrize("paired_end", [False, True])
    def test_iter_batch_chunks_native_matches_python(
        self, tmp_path, monkeypatch, paired_end
    ):
        """The native chunk iterator must produce bit-identical batches
        AND identical chunk boundaries to the per-record Python path
        (checkpoint manifests depend on the boundary equivalence)."""
        from duplexumiconsensusreads_tpu.native import native_available
        from duplexumiconsensusreads_tpu.runtime.stream import iter_batch_chunks

        if not native_available():
            pytest.skip("native loader unavailable")
        path = str(tmp_path / "in.bam")
        cfg = SimConfig(n_molecules=90, n_positions=10, umi_error=0.02, seed=7)
        simulated_bam(cfg, path=path, sort=True, paired_end=paired_end)

        def drain():
            return [
                (b, i) for _, b, i in iter_batch_chunks(path, 83, duplex=True)
            ]

        nat = drain()
        monkeypatch.setenv("DUT_NO_NATIVE", "1")
        py = drain()
        assert len(nat) == len(py)
        for (bn, infn), (bp, infp) in zip(nat, py):
            assert infn["n_valid"] == infp["n_valid"]
            np.testing.assert_array_equal(bn.pos_key, bp.pos_key)
            np.testing.assert_array_equal(bn.umi, bp.umi)
            np.testing.assert_array_equal(bn.bases, bp.bases)
            np.testing.assert_array_equal(bn.quals, bp.quals)
            np.testing.assert_array_equal(bn.strand_ab, bp.strand_ab)
            np.testing.assert_array_equal(bn.valid, bp.valid)


class TestStreamedCall:
    def _call(self, path, out, **kw):
        gp = GroupingParams(strategy="adjacency", paired=True)
        cp = ConsensusParams(mode="duplex")
        return stream_call_consensus(
            path, out, gp, cp, capacity=256, chunk_reads=150, **kw
        )

    def test_matches_wholefile(self, tmp_path):
        path, _, _ = _sorted_bam(tmp_path)
        out_s = str(tmp_path / "stream.bam")
        out_w = str(tmp_path / "whole.bam")
        rep = self._call(path, out_s)
        assert rep.n_consensus > 0
        assert main(
            ["call", path, "-o", out_w, "--config", "config3",
             "--backend", "tpu", "--capacity", "256"]
        ) == 0
        _, rs = read_bam(out_s)
        _, rw = read_bam(out_w)
        assert len(rs) == len(rw)
        key_s = {(int(rs.pos[i]), rs.umi[i]): i for i in range(len(rs))}
        for j in range(len(rw)):
            i = key_s[(int(rw.pos[j]), rw.umi[j])]
            np.testing.assert_array_equal(rs.seq[i], rw.seq[j])
            np.testing.assert_array_equal(rs.qual[i], rw.qual[j])

    def test_checkpoint_resume_skips_done_chunks(self, tmp_path):
        path, _, _ = _sorted_bam(tmp_path)
        out = str(tmp_path / "c.bam")
        ck = str(tmp_path / "ck.json")
        rep1 = self._call(path, out, checkpoint_path=ck, resume=False)
        with open(ck) as f:
            manifest = json.load(f)
        assert len(manifest["done"]) >= 2
        _, r1 = read_bam(out)

        # resume: all chunks already done -> no device work needed,
        # output identical
        rep2 = self._call(path, out, checkpoint_path=ck, resume=True)
        assert rep2.n_buckets == 0  # nothing re-dispatched
        _, r2 = read_bam(out)
        assert r1.names == r2.names
        np.testing.assert_array_equal(r1.seq, r2.seq)

    def test_fingerprint_invalidation(self, tmp_path):
        path, _, _ = _sorted_bam(tmp_path)
        out = str(tmp_path / "d.bam")
        ck = str(tmp_path / "ck2.json")
        self._call(path, out, checkpoint_path=ck, resume=False)
        # different params -> fingerprint mismatch -> full re-run
        gp = GroupingParams(strategy="exact", paired=True)
        cp = ConsensusParams(mode="duplex")
        rep = stream_call_consensus(
            path, out, gp, cp, capacity=256, chunk_reads=150,
            checkpoint_path=ck, resume=True,
        )
        assert rep.n_buckets > 0  # did not skip


def test_unmapped_reads_at_eof_stream_cleanly(tmp_path):
    """A standard coordinate-sorted BAM carries its unmapped reads at
    EOF (ref_id=-1, pos=-1). Their pos_key must sort LAST (sentinel),
    not sign-extend to -1 and trip the sort-contract check; conversion
    must drop them via the FLAG filter."""
    from duplexumiconsensusreads_tpu.io import write_bam
    from duplexumiconsensusreads_tpu.io.bam import FLAG_UNMAPPED
    from duplexumiconsensusreads_tpu.io.convert import records_to_readbatch
    from duplexumiconsensusreads_tpu.runtime.stream import _concat_records, _slice_records

    path = str(tmp_path / "mapped.bam")
    cfg = SimConfig(n_molecules=40, n_positions=6, seed=7)
    header, recs, *_ = simulated_bam(cfg, path=path, sort=True)

    import copy as _copy

    # tail LARGER than chunk_reads: the flush branch must fire on
    # multiple consecutive all-sentinel chunks without tripping the
    # cross-boundary repeat check or accumulating carry
    tail = _copy.deepcopy(_slice_records(recs, 0, 150))  # slices are views
    tail.flags[:] = FLAG_UNMAPPED
    tail.ref_id[:] = -1
    tail.pos[:] = -1
    tail.next_ref_id[:] = -1
    tail.next_pos[:] = -1
    full = _concat_records(recs, tail)
    path2 = str(tmp_path / "with_unmapped.bam")
    write_bam(path2, header, full)

    seen = 0
    n_flag_dropped = 0
    for _, chunk in iter_record_chunks(path2, chunk_reads=60):
        assert len(chunk) <= 60 + 150  # no unbounded carry growth
        _, info = records_to_readbatch(chunk, duplex=True)
        n_flag_dropped += info["n_dropped_flag"]
        seen += len(chunk)
    assert seen == len(recs) + 150
    assert n_flag_dropped == 150


def test_mapped_after_unmapped_tail_rejected(tmp_path):
    """Mapped records AFTER the unmapped tail violate the sort contract
    and must raise (the flush path must not let them slip past the
    cross-boundary repeat check and split a family)."""
    import copy as _copy

    from duplexumiconsensusreads_tpu.io import write_bam
    from duplexumiconsensusreads_tpu.io.bam import FLAG_UNMAPPED
    from duplexumiconsensusreads_tpu.runtime.stream import _concat_records, _slice_records

    path = str(tmp_path / "m.bam")
    cfg = SimConfig(n_molecules=30, n_positions=5, seed=9)
    header, recs, *_ = simulated_bam(cfg, path=path, sort=True)
    mid = _copy.deepcopy(_slice_records(recs, 0, 40))
    mid.flags[:] = FLAG_UNMAPPED
    mid.ref_id[:] = -1
    mid.pos[:] = -1
    mid.next_ref_id[:] = -1
    mid.next_pos[:] = -1
    bad = _concat_records(
        _concat_records(_slice_records(recs, 0, len(recs) // 2), mid),
        _slice_records(recs, len(recs) // 2, len(recs)),
    )
    path2 = str(tmp_path / "bad_order.bam")
    write_bam(path2, header, bad)
    with pytest.raises(ValueError, match="sort contract"):
        list(iter_record_chunks(path2, chunk_reads=30))


def test_resume_report_counts_fresh_work_only(tmp_path):
    path, _, _ = _sorted_bam(tmp_path, n_mol=60)
    out = str(tmp_path / "r.bam")
    ck = str(tmp_path / "ckr.json")
    gp = GroupingParams(strategy="exact", paired=True)
    cp = ConsensusParams(mode="duplex")
    kw = dict(capacity=256, chunk_reads=120, checkpoint_path=ck)
    rep1 = stream_call_consensus(path, out, gp, cp, resume=False, **kw)
    rep2 = stream_call_consensus(path, out, gp, cp, resume=True, **kw)
    # fully-resumed run did no fresh work: per-read counters are zero,
    # chunk accounting still covers the file
    assert rep2.n_records == 0
    assert rep2.n_valid_reads == 0
    assert rep2.n_chunks == rep1.n_chunks
    assert rep2.n_chunks_skipped == rep1.n_chunks
    assert rep2.n_consensus == rep1.n_consensus


def test_nonresume_clears_manifest_on_disk(tmp_path):
    """resume=False must persist the cleared manifest BEFORE any work:
    if the run crashes before its first mark(), stale done-entries must
    not survive on disk to be resurrected by a later --resume."""
    from duplexumiconsensusreads_tpu.runtime.stream import _fingerprint

    # unsorted input raises inside the chunk loop, before any mark()
    bad = str(tmp_path / "unsorted.bam")
    cfg = SimConfig(n_molecules=60, n_positions=8, seed=2)
    simulated_bam(cfg, path=bad, sort=False)
    gp = GroupingParams(strategy="exact", paired=True)
    cp = ConsensusParams(mode="duplex")

    ck = str(tmp_path / "ck3.json")
    shard = str(tmp_path / "stale_shard")
    open(shard, "w").close()  # must exist: load_or_create prunes dead paths
    fp = _fingerprint(bad, gp, cp, 256, 50)
    # stale manifests with BOTH matching and mismatching fingerprints
    # must be wiped: this run overwrites the shard files either way
    for stale_fp in (fp, "0123456789abcdef"):
        with open(ck, "w") as f:
            json.dump({"fingerprint": stale_fp, "done": {"0": shard}}, f)
        with pytest.raises(ValueError, match="sort contract"):
            stream_call_consensus(
                bad, str(tmp_path / "o.bam"), gp, cp, capacity=256,
                chunk_reads=50, checkpoint_path=ck, resume=False,
            )
        with open(ck) as f:
            d = json.load(f)
        assert d["done"] == {} and d["fingerprint"] == fp

    # resume=True with a MISMATCHED fingerprint has the same crash
    # window: load_or_create must persist the fresh manifest up front
    with open(ck, "w") as f:
        json.dump({"fingerprint": "feedfacefeedface", "done": {"0": shard}}, f)
    with pytest.raises(ValueError, match="sort contract"):
        stream_call_consensus(
            bad, str(tmp_path / "o.bam"), gp, cp, capacity=256,
            chunk_reads=50, checkpoint_path=ck, resume=True,
        )
    with open(ck) as f:
        d = json.load(f)
    assert d["done"] == {} and d["fingerprint"] == fp


def test_unsorted_input_rejected(tmp_path):
    """The streaming sort contract is validated, not assumed: unsorted
    input must raise instead of silently splitting families."""
    path = str(tmp_path / "unsorted.bam")
    cfg = SimConfig(n_molecules=60, n_positions=8, seed=2)
    simulated_bam(cfg, path=path, sort=False)  # simulator shuffles reads
    with pytest.raises(ValueError, match="sort contract"):
        list(iter_record_chunks(path, chunk_reads=50))


def test_unsorted_final_range_chunk_rejected(tmp_path):
    """Range mode's key_hi early-exit must validate the sort contract
    BEFORE its searchsorted cut: an unsorted final in-range chunk has
    to raise, not silently mis-truncate (ADVICE r2)."""
    from duplexumiconsensusreads_tpu.runtime.stream import iter_batch_chunks

    path = str(tmp_path / "unsorted.bam")
    cfg = SimConfig(n_molecules=60, n_positions=8, seed=2)
    simulated_bam(cfg, path=path, sort=False)  # simulator shuffles reads
    # a key_hi below the max pos_key forces the early-exit path on the
    # very first (unsorted) chunk
    with pytest.raises(ValueError, match="sort contract"):
        # keys are in [1000, 8000]; key_hi=999 guarantees the final
        # chunk triggers the early exit (keys[-1] >= key_hi) where the
        # old code would silently emit nothing
        list(iter_batch_chunks(path, 10_000, duplex=True, key_hi=999))


def test_shards_cleaned_without_checkpoint(tmp_path):
    import os

    path, _, _ = _sorted_bam(tmp_path, n_mol=40)
    out = str(tmp_path / "clean.bam")
    gp = GroupingParams(strategy="exact", paired=True)
    cp = ConsensusParams(mode="duplex")
    stream_call_consensus(path, out, gp, cp, capacity=256, chunk_reads=100)
    assert not os.path.exists(out + ".shards")
    _, recs = read_bam(out)
    assert len(recs) > 0


class TestFaultRecovery:
    def _sim(self, tmp_path):
        return _sorted_bam(tmp_path, n_mol=80, n_positions=8)

    def test_transient_dispatch_failures_recovered(self, tmp_path, monkeypatch):
        """Kill several device dispatches; the run must complete with
        output identical to a fault-free run (VERDICT r1 item 10)."""
        import duplexumiconsensusreads_tpu.parallel.sharded as sharded

        path, _, _ = self._sim(tmp_path)
        gp = GroupingParams(strategy="adjacency", paired=True)
        cp = ConsensusParams(mode="duplex")
        kw = dict(capacity=128, chunk_reads=120, max_retries=2)

        ref = str(tmp_path / "ref.bam")
        rep0 = stream_call_consensus(path, ref, gp, cp, **kw)

        real = sharded.sharded_pipeline
        calls = {"n": 0}

        def flaky(*a, **k):
            calls["n"] += 1
            if calls["n"] in (2, 3, 5):  # transient outage
                raise RuntimeError("injected device failure")
            return real(*a, **k)

        monkeypatch.setattr(sharded, "sharded_pipeline", flaky)
        monkeypatch.setattr(
            "duplexumiconsensusreads_tpu.runtime.stream.time.sleep",
            lambda s: None,
            raising=False,
        )
        out = str(tmp_path / "faulty.bam")
        rep = stream_call_consensus(path, out, gp, cp, **kw)
        assert rep.n_retries >= 1
        assert rep.n_consensus == rep0.n_consensus
        _, r_ref = read_bam(ref)
        _, r_out = read_bam(out)
        np.testing.assert_array_equal(r_ref.pos, r_out.pos)
        np.testing.assert_array_equal(r_ref.seq, r_out.seq)
        np.testing.assert_array_equal(r_ref.qual, r_out.qual)

    def test_poisoned_class_isolated_per_bucket(self, tmp_path, monkeypatch):
        """A class whose stacked dispatch always fails must fall back to
        bucket-by-bucket dispatch and still finish."""
        import duplexumiconsensusreads_tpu.parallel.sharded as sharded

        path, _, _ = self._sim(tmp_path)
        gp = GroupingParams(strategy="adjacency", paired=True)
        cp = ConsensusParams(mode="duplex")
        real = sharded.sharded_pipeline

        def multi_bucket_fails(stacked, spec, mesh, *a, **k):
            if stacked["pos"].shape[0] > 1:
                raise RuntimeError("injected: stacked dispatch down")
            return real(stacked, spec, mesh, *a, **k)

        monkeypatch.setattr(sharded, "sharded_pipeline", multi_bucket_fails)
        monkeypatch.setattr(
            "duplexumiconsensusreads_tpu.runtime.stream.time.sleep",
            lambda s: None,
            raising=False,
        )
        out = str(tmp_path / "iso.bam")
        rep = stream_call_consensus(
            path, out, gp, cp, capacity=128, chunk_reads=120,
            max_retries=1, n_devices=1,
        )
        assert rep.n_retries >= 1
        _, recs = read_bam(out)
        assert len(recs) == rep.n_consensus > 0

    def test_permanent_failure_raises(self, tmp_path, monkeypatch):
        import duplexumiconsensusreads_tpu.parallel.sharded as sharded

        path, _, _ = self._sim(tmp_path)
        gp = GroupingParams(strategy="exact", paired=True)
        cp = ConsensusParams(mode="duplex")

        def dead(*a, **k):
            raise RuntimeError("injected: device gone")

        monkeypatch.setattr(sharded, "sharded_pipeline", dead)
        monkeypatch.setattr(
            "duplexumiconsensusreads_tpu.runtime.stream.time.sleep",
            lambda s: None,
            raising=False,
        )
        with pytest.raises(RuntimeError, match="giving up"):
            stream_call_consensus(
                path, str(tmp_path / "x.bam"), gp, cp,
                capacity=128, chunk_reads=120, max_retries=1,
            )

    def test_auto_checkpoint_resume_after_crash(self, tmp_path, monkeypatch):
        """Chunked runs checkpoint by default: crash mid-run, rerun with
        resume=True and no explicit checkpoint path -> finished chunks
        are skipped and output is complete."""
        import os

        path, _, _ = _sorted_bam(tmp_path, n_mol=120, n_positions=12)
        gp = GroupingParams(strategy="adjacency", paired=True)
        cp = ConsensusParams(mode="duplex")
        out = str(tmp_path / "auto.bam")
        kw = dict(capacity=128, chunk_reads=100)

        boom = {"after": 2}

        def crashing_progress(k, rep):
            if rep.n_chunks >= boom["after"]:
                raise KeyboardInterrupt("injected crash")

        with pytest.raises(KeyboardInterrupt):
            stream_call_consensus(
                path, out, gp, cp, progress=crashing_progress, **kw
            )
        assert os.path.exists(out + ".ckpt")  # auto checkpoint persisted

        rep = stream_call_consensus(path, out, gp, cp, resume=True, **kw)
        assert rep.n_chunks_skipped >= 1
        assert not os.path.exists(out + ".ckpt")  # cleaned on success
        assert not os.path.exists(out + ".shards")
        ref = str(tmp_path / "ref.bam")
        rep0 = stream_call_consensus(path, ref, gp, cp, **kw)
        _, r_ref = read_bam(ref)
        _, r_out = read_bam(out)
        assert rep.n_consensus == rep0.n_consensus
        np.testing.assert_array_equal(r_ref.seq, r_out.seq)


def test_drain_workers_ab_byte_identical(tmp_path):
    """The acceptance A/B: serial drain (--drain-workers 1) vs a wide
    pool must produce byte-identical output, and the report must carry
    the overlapped busy-time accounting fields."""
    path, _, _ = _sorted_bam(tmp_path)
    gp = GroupingParams(strategy="adjacency", paired=True)
    cp = ConsensusParams(mode="duplex")
    outs = {}
    for n in (1, 3):
        out = str(tmp_path / f"dw{n}.bam")
        rep = stream_call_consensus(
            path, out, gp, cp, capacity=256, chunk_reads=150, drain_workers=n
        )
        assert rep.n_drain_workers == n
        assert "main_loop_stall" in rep.seconds
        assert "drain_utilization" in rep.seconds
        assert 0.0 <= rep.seconds["drain_utilization"] <= 1.0
        with open(out, "rb") as f:
            outs[n] = f.read()
    assert outs[1] == outs[3]


def test_drain_workers_validated():
    gp = GroupingParams(strategy="exact", paired=True)
    cp = ConsensusParams(mode="duplex")
    with pytest.raises(ValueError, match="drain_workers"):
        stream_call_consensus(
            "nonexistent.bam", "out.bam", gp, cp, chunk_reads=10,
            drain_workers=0,
        )


def test_busy_wall_table_flags_impossible_accounting():
    """The profile/CI canary: a single-threaded stage reporting more
    busy time than the wall is an accounting bug; pooled stages may
    exceed the wall up to their pool size."""
    from duplexumiconsensusreads_tpu.runtime.executor import busy_wall_table

    seconds = {
        "ingest": 12.0,  # > wall on a 1-thread stage: impossible
        "dispatch": 30.0,  # 4-worker pool, <= 4 * wall: legitimate
        "scatter": 15.0,  # 2 drain workers, <= 2 * wall: legitimate
        "main_loop_stall": 1.0,
        "drain_utilization": 0.75,
        "total": 10.0,
    }
    lines, bugs = busy_wall_table(seconds, drain_workers=2)
    assert bugs == ["ingest"]
    assert any("BUSY>WALL" in ln for ln in lines)
    assert not any("scatter" in b for b in bugs)
    # all-sane report: no flags
    _, bugs2 = busy_wall_table(
        {"ingest": 3.0, "scatter": 12.0, "total": 10.0}, drain_workers=2
    )
    assert bugs2 == []


def test_cli_stream_and_validate(tmp_path):
    bam = str(tmp_path / "s.bam")
    truth = str(tmp_path / "t.npz")
    out = str(tmp_path / "o.bam")
    assert main(
        ["simulate", "-o", bam, "--truth", truth, "--molecules", "150",
         "--read-len", "40", "--positions", "10", "--sorted",
         "--base-error", "0.02", "--seed", "3"]
    ) == 0
    assert main(
        ["call", bam, "-o", out, "--config", "config5", "--capacity", "256",
         "--chunk-reads", "200", "--checkpoint", str(tmp_path / "ck.json")]
    ) == 0
    import io as _io
    import contextlib

    buf = _io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert main(["validate", out, "--truth", truth, "--json"]) == 0
    res = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert res["error_rate"] < 0.004
    assert res["n_matched_to_truth"] > 0
