"""Mesh sharding tests on the virtual 8-device CPU mesh: 1D data
sharding, 2D (data, cycle) sequence sharding, and multi-host helpers.
Results must be identical no matter how the mesh slices the work."""

import numpy as np
import pytest

import jax

from duplexumiconsensusreads_tpu.bucketing import build_buckets, stack_buckets
from duplexumiconsensusreads_tpu.ops import spec_for_buckets
from duplexumiconsensusreads_tpu.parallel import (
    host_tile_range,
    make_mesh,
    sharded_pipeline,
)
from duplexumiconsensusreads_tpu.simulate import SimConfig, simulate_batch
from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams

needs8 = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)


def _workload(read_len=64):
    batch, _ = simulate_batch(
        SimConfig(
            n_molecules=160, read_len=read_len, n_positions=16,
            umi_error=0.02, duplex=True, seed=77,
        )
    )
    buckets = build_buckets(batch, capacity=256, adjacency=True)
    gp = GroupingParams(strategy="adjacency", paired=True)
    cp = ConsensusParams(mode="duplex", error_model="cycle")
    spec = spec_for_buckets(buckets, gp, cp)
    return buckets, spec


def _run(buckets, spec, mesh, n_dev):
    stacked = stack_buckets(buckets, multiple_of=n_dev)
    out = sharded_pipeline(stacked, spec, mesh)
    return {k: np.asarray(v) for k, v in out.items()}


def _assert_equivalent(ref, out, n):
    """Partitioning must not change results — except that XLA may
    reassociate f32 sums across layouts, which can perturb the tiny
    excluded-max residual behind a high qual. Bases/ids/depth must be
    exact; quals tolerate <0.01% of elements differing (all at the
    high-confidence end where the residual underflows)."""
    for k in ("family_id", "molecule_id", "cons_base", "cons_depth", "cons_valid"):
        np.testing.assert_array_equal(ref[k][:n], out[k][:n], err_msg=k)
    q_ref = ref["cons_qual"][:n].astype(int)
    q_out = out["cons_qual"][:n].astype(int)
    frac = (q_ref != q_out).mean()
    assert frac < 1e-4, f"qual mismatch fraction {frac}"
    # any differing sites must be high-confidence on both sides
    diff = q_ref != q_out
    if diff.any():
        assert q_ref[diff].min() > 60 and q_out[diff].min() > 60


@needs8
def test_data_sharding_matches_single_device():
    buckets, spec = _workload()
    ref = _run(buckets, spec, make_mesh(1), 1)
    out = _run(buckets, spec, make_mesh(8), 8)
    _assert_equivalent(ref, out, len(buckets))


@needs8
@pytest.mark.parametrize("shape", [(4, 2), (2, 4), (1, 8)])
def test_cycle_sharding_matches(shape):
    d, c = shape
    buckets, spec = _workload(read_len=64)
    ref = _run(buckets, spec, make_mesh(1), 1)
    mesh = make_mesh(d * c, cycle_shards=c)
    assert mesh.axis_names == ("data", "cycle")
    out = _run(buckets, spec, mesh, d)
    _assert_equivalent(ref, out, len(buckets))


@pytest.mark.skipif(len(jax.devices()) < 2, reason="needs >=2 devices")
def test_mesh_validation():
    n = len(jax.devices())
    with pytest.raises(ValueError, match="divisible"):
        make_mesh(n, cycle_shards=n + 1)  # never divides evenly
    with pytest.raises(ValueError, match="requested"):
        make_mesh(n + 1)


def test_host_tile_range_partition():
    # simulated 4-process layout must cover all tiles disjointly
    n_tiles = 10
    seen = []
    for pid in range(4):
        r = host_tile_range(n_tiles, process_id=pid, num_processes=4)
        seen.extend(r)
    assert sorted(seen) == list(range(n_tiles))


@needs8
def test_cli_cycle_shards(tmp_path):
    from duplexumiconsensusreads_tpu.cli import main
    from duplexumiconsensusreads_tpu.io import read_bam, simulated_bam

    bam = str(tmp_path / "x.bam")
    simulated_bam(SimConfig(n_molecules=40, duplex=True, seed=6), path=bam)
    out = str(tmp_path / "y.bam")
    assert main(
        ["call", bam, "-o", out, "--config", "config3", "--capacity", "256",
         "--devices", "8", "--cycle-shards", "2"]
    ) == 0
    _, recs = read_bam(out)
    assert len(recs) > 0


def test_init_distributed_single_process():
    from duplexumiconsensusreads_tpu.parallel import init_distributed

    info = init_distributed()  # no coordinator -> no-op
    assert info["num_processes"] == 1
    assert info["global_devices"] == len(jax.devices())
