"""dutlint: per-rule firing/passing fixtures, allowlist semantics, the
CLI contract, and the tier-1 whole-tree gate.

Each rule gets at least one snippet that FIRES and one that PASSES, so
a rule can neither silently die (stops firing on its bad fixture) nor
silently over-reach (starts firing on its good fixture). The whole-tree
test is the actual CI gate: the shipped tree must lint clean modulo the
reasoned allowlist, and the allowlist itself must carry no stale
entries.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from duplexumiconsensusreads_tpu.analysis import Corpus, run_lint
from duplexumiconsensusreads_tpu.analysis.allowlist import ALLOWLIST
from duplexumiconsensusreads_tpu.analysis.cli import default_targets, repo_root
from duplexumiconsensusreads_tpu.analysis.engine import RULES, AllowEntry

REPO = repo_root()


def lint(files: dict, rules=None, allow=()):
    """Run the engine over in-memory snippet files."""
    corpus = Corpus(root="<snippets>")
    for path, src in files.items():
        corpus.add(path, textwrap.dedent(src))
    return run_lint(corpus, allow, only_rules=rules)


def rules_of(result):
    return [(f.rule, f.path) for f in result.findings]


# ---------------------------------------------------------------- engine

class TestEngine:
    def test_registry_has_the_invariant_rules(self):
        assert {
            "clock-discipline", "durability-protocol", "fault-registry",
            "phase-registry", "lock-discipline", "hook-guard",
            "lease-discipline", "deadline-discipline", "host-locality",
            # the protocol model-checker passes
            "state-machine", "txn-discipline", "fence-dominance",
            "exception-contract",
            # the declared thread model (subsumes PR 17's
            # ingest-confinement as the producer row)
            "thread-confinement",
            # the device ledger's FLOP-cost registry closure
            "kernel-cost-registry",
            # the knob registry's determinism-surface model-check
            "knob-taint",
        } <= set(RULES)
        assert "ingest-confinement" not in RULES
        for rule in RULES.values():
            assert rule.title

    def test_unparseable_file_is_itself_a_finding(self):
        res = lint({"pkg/x.py": "def broken(:\n"}, rules=[])
        assert [f.rule for f in res.findings] == ["parse"]
        assert res.findings[0].line >= 1

    def test_allowlist_suppresses_and_reports_usage(self):
        files = {"pkg/runtime/t.py": "import time\nT = time.time()\n"}
        entry = AllowEntry(
            rule="clock-discipline", path="pkg/runtime/t.py",
            reason="fixture: wall-clock wanted here",
        )
        res = lint(files, rules=["clock-discipline"], allow=[entry])
        assert res.ok and len(res.suppressed) == 1
        assert res.suppressed[0][1] is entry
        assert res.unused_allowlist == []

    def test_allowlist_entry_is_per_rule_not_blanket(self):
        files = {"pkg/runtime/t.py": "import time\nT = time.time()\n"}
        other = AllowEntry(
            rule="durability-protocol", path="pkg/runtime/t.py",
            reason="fixture: wrong rule",
        )
        res = lint(files, rules=["clock-discipline"], allow=[other])
        assert not res.ok  # the entry's rule doesn't match: no suppression

    def test_unused_allowlist_entries_are_reported(self):
        entry = AllowEntry(
            rule="clock-discipline", path="pkg/clean.py",
            reason="fixture: nothing to suppress",
        )
        res = lint(
            {"pkg/clean.py": "x = 1\n"}, rules=["clock-discipline"],
            allow=[entry],
        )
        assert res.ok and res.unused_allowlist == [entry]

    def test_allowlist_reason_is_mandatory(self):
        with pytest.raises(ValueError, match="reason"):
            AllowEntry(rule="clock-discipline", path="x.py", reason="  ")

    def test_unknown_rule_id_raises_a_named_error(self):
        with pytest.raises(ValueError, match="clock-discipline"):
            lint({"pkg/a.py": "x = 1\n"}, rules=["clock"])


# ----------------------------------------------------------------- rules

class TestClockDiscipline:
    def test_fires_on_time_time(self):
        res = lint(
            {"pkg/a.py": "import time\ndef f():\n    return time.time()\n"},
            rules=["clock-discipline"],
        )
        assert rules_of(res) == [("clock-discipline", "pkg/a.py")]
        assert res.findings[0].line == 3
        assert "monotonic" in res.findings[0].hint

    def test_fires_on_from_import_alias(self):
        res = lint(
            {"pkg/a.py": "from time import time as now\nT = now()\n"},
            rules=["clock-discipline"],
        )
        assert len(res.findings) == 1

    def test_passes_on_monotonic(self):
        res = lint(
            {"pkg/a.py": "import time\ndef f():\n"
             "    return time.monotonic()\n"},
            rules=["clock-discipline"],
        )
        assert res.ok


class TestDurabilityProtocol:
    BAD = {
        "pkg/io/w.py": """
            def save(path, payload):
                with open(path, "wb") as f:
                    f.write(payload)
            """,
    }

    def test_fires_on_bare_write_open_in_io(self):
        res = lint(self.BAD, rules=["durability-protocol"])
        assert rules_of(res) == [("durability-protocol", "pkg/io/w.py")]
        assert "write_durable" in res.findings[0].hint

    def test_passes_when_protocol_is_used_in_scope(self):
        res = lint(
            {"pkg/io/w.py": """
                from pkg.io.durable import fsync_file, replace_durable
                def save(path, payload):
                    tmp = path + ".tmp"
                    with open(tmp, "wb") as f:
                        f.write(payload)
                        fsync_file(f)
                    replace_durable(tmp, path)
                """},
            rules=["durability-protocol"],
        )
        assert res.ok

    def test_passes_outside_io_runtime_and_on_reads(self):
        res = lint(
            {
                "pkg/telemetry/t.py": 'f = open("cap.jsonl", "w")\n',
                "pkg/io/r.py": 'def load(p):\n    return open(p, "rb").read()\n',
            },
            rules=["durability-protocol"],
        )
        assert res.ok

    def test_serve_layer_is_in_scope(self):
        # the serving layer's crash-recovery story rests on the queue
        # journal being durable: serve/ writes are held to the protocol
        res = lint(
            {"pkg/serve/q.py": """
                def journal(path, payload):
                    with open(path, "w") as f:
                        f.write(payload)
                """},
            rules=["durability-protocol"],
        )
        assert rules_of(res) == [("durability-protocol", "pkg/serve/q.py")]
        ok = lint(
            {"pkg/serve/q.py": """
                from pkg.io.durable import write_durable
                def journal(path, payload):
                    write_durable(path, payload)
                """},
            rules=["durability-protocol"],
        )
        assert ok.ok

    def test_mode_keyword_is_seen(self):
        res = lint(
            {"pkg/runtime/w.py":
             'def f(p):\n    open(p, mode="w").write("x")\n'},
            rules=["durability-protocol"],
        )
        assert len(res.findings) == 1

    def test_update_mode_counts_as_a_write(self):
        res = lint(
            {"pkg/runtime/w.py":
             'def f(p):\n    open(p, "r+b").write(b"patch")\n'},
            rules=["durability-protocol"],
        )
        assert len(res.findings) == 1


FAULTS_OK = """
    KNOWN_SITES = ("ingest.read", "shard.write")
    """
STREAM_USES_BOTH = """
    def go(f):
        _io_retry("ingest.read", f, "read")
        fault_point("shard.write")
    """
CHAOS_COVERS_BOTH = """
    def test_a():
        run("ingest.read:1:oserror")
    def test_b():
        run("shard.write:1:kill")
    """


class TestFaultRegistry:
    def test_passes_when_all_three_agree(self):
        res = lint(
            {
                "pkg/runtime/faults.py": FAULTS_OK,
                "pkg/runtime/stream.py": STREAM_USES_BOTH,
                "tests/test_chaos.py": CHAOS_COVERS_BOTH,
            },
            rules=["fault-registry"],
        )
        assert res.ok

    def test_fires_on_unregistered_site(self):
        res = lint(
            {
                "pkg/runtime/faults.py": FAULTS_OK,
                "pkg/runtime/stream.py": STREAM_USES_BOTH
                + '    fault_point("typo.site")\n',
                "tests/test_chaos.py": CHAOS_COVERS_BOTH,
            },
            rules=["fault-registry"],
        )
        assert [f.message for f in res.findings] == [
            "fault site 'typo.site' is not registered in faults.KNOWN_SITES"
        ]
        assert res.findings[0].path == "pkg/runtime/stream.py"

    def test_fires_on_dead_registry_entry(self):
        res = lint(
            {
                "pkg/runtime/faults.py":
                    'KNOWN_SITES = ("ingest.read", "shard.write", "dead.site")\n',
                "pkg/runtime/stream.py": STREAM_USES_BOTH,
                "tests/test_chaos.py": CHAOS_COVERS_BOTH,
            },
            rules=["fault-registry"],
        )
        msgs = [f.message for f in res.findings]
        assert any("dead.site" in m and "no fault_point" in m for m in msgs)
        # and the uncovered site also surfaces on the chaos side
        assert any("dead.site" in m and "chaos" in m for m in msgs)

    def test_docstring_mentions_do_not_count_as_chaos_coverage(self):
        res = lint(
            {
                "pkg/runtime/faults.py": FAULTS_OK,
                "pkg/runtime/stream.py": STREAM_USES_BOTH,
                "tests/test_chaos.py": '''
                    def test_a():
                        """This docstring talks about shard.write:1:kill
                        but exercises nothing."""
                        run("ingest.read:1:oserror")
                    ''',
            },
            rules=["fault-registry"],
        )
        assert len(res.findings) == 1
        assert "shard.write" in res.findings[0].message

    def test_assigned_schedule_tables_count_as_coverage(self):
        res = lint(
            {
                "pkg/runtime/faults.py": FAULTS_OK,
                "pkg/runtime/stream.py": STREAM_USES_BOTH,
                "tests/test_chaos.py": """
                    KILLS = [("ingest.read", 1), ("shard.write", 2)]
                    def test_each():
                        for site, nth in KILLS:
                            run(site, nth)
                    """,
            },
            rules=["fault-registry"],
        )
        assert res.ok

    def test_missing_chaos_anchor_skips_coverage_check(self):
        res = lint(
            {
                "pkg/runtime/faults.py": FAULTS_OK,
                "pkg/runtime/stream.py": STREAM_USES_BOTH,
            },
            rules=["fault-registry"],
        )
        assert res.ok  # registration checks ran; coverage skipped

    def test_fires_on_chaos_gap_and_respects_blanket_parametrize(self):
        gap = lint(
            {
                "pkg/runtime/faults.py": FAULTS_OK,
                "pkg/runtime/stream.py": STREAM_USES_BOTH,
                "tests/test_chaos.py": """
                    def test_a():
                        run("ingest.read:1:oserror")
                    """,
            },
            rules=["fault-registry"],
        )
        assert [f.rule for f in gap.findings] == ["fault-registry"]
        assert "shard.write" in gap.findings[0].message
        blanket = lint(
            {
                "pkg/runtime/faults.py": FAULTS_OK,
                "pkg/runtime/stream.py": STREAM_USES_BOTH,
                "tests/test_chaos.py": """
                    import pytest
                    from pkg.runtime import faults
                    @pytest.mark.parametrize("site", faults.KNOWN_SITES)
                    def test_each(site):
                        run(site)
                    """,
            },
            rules=["fault-registry"],
        )
        assert blanket.ok


TRACE_OK = """
    KNOWN_STAGES = ("ingest", "finalise")
    KNOWN_EVENTS = ("retry",)
    """
EXEC_OK = 'DRAIN_PHASES = ("finalise",)\n'
STREAM_OK = """
    def run(tr):
        phase = {"ingest": 0.0, "finalise": 0.0}
        if tr is not None:
            tr.span("ingest", 0.0, 1.0)
    """
GOLDEN_OK = """
    def test_streaming_seconds_keys_golden():
        assert set(rep) == {"ingest", "finalise", "drain_utilization",
                            "total"}
    """
TRACE_XFER_OK = """
    KNOWN_STAGES = ("ingest", "finalise")
    KNOWN_EVENTS = ("retry",)
    KNOWN_XFER_DIRS = ("h2d", "d2h", "shard")
    """
TRACE_XFER_ATTRS = """
    KNOWN_STAGES = ("ingest", "finalise")
    KNOWN_EVENTS = ("retry",)
    KNOWN_XFER_DIRS = ("h2d", "d2h", "shard")
    KNOWN_H2D_XFER_ATTRS = ("bpc", "rows_real", "rows_pad", "cap")
    """
TRACE_LANES = """
    KNOWN_STAGES = ("ingest", "finalise")
    KNOWN_EVENTS = ("retry",)
    KNOWN_XFER_DIRS = ("h2d", "d2h", "shard")
    KNOWN_H2D_XFER_ATTRS = ("bpc", "rows_real", "rows_pad", "cap",
                            "mesh_pad")
    KNOWN_LANE_PREFIXES = ("main", "xfer-", "drain-", "job-", "dev-")
    """
FLEET_OK = """
    FLEET_SEGMENT_KINDS = ("run", "split")
    FLEET_GAP_KINDS = ("queue_wait", "takeover")

    def stitch():
        kind = "run" if True else "split"
        pending = "queue_wait"
        pending = "takeover"
        return kind, pending
    """


class TestPhaseRegistry:
    def base(self, **over):
        files = {
            "pkg/telemetry/trace.py": TRACE_OK,
            "pkg/runtime/executor.py": EXEC_OK,
            "pkg/runtime/stream.py": STREAM_OK,
            "tests/test_telemetry.py": GOLDEN_OK,
        }
        files.update(over)
        return lint(files, rules=["phase-registry"])

    def test_passes_when_consistent(self):
        assert self.base().ok

    def test_fires_on_phase_key_not_in_stages(self):
        res = self.base(**{"pkg/runtime/stream.py": """
            def run(tr):
                phase = {"ingest": 0.0, "finalise": 0.0, "mystery": 0.0}
            """})
        assert any("mystery" in f.message for f in res.findings)

    def test_fires_on_stage_missing_from_phase_dict(self):
        res = self.base(**{"pkg/runtime/stream.py": """
            def run(tr):
                phase = {"ingest": 0.0}
            """})
        assert any(
            "'finalise' missing from the phase" in f.message
            for f in res.findings
        )

    def test_fires_on_unknown_span_stage_and_event(self):
        res = self.base(**{"pkg/runtime/stream.py": """
            def run(tr):
                phase = {"ingest": 0.0, "finalise": 0.0}
                if tr is not None:
                    tr.span("warp", 0.0, 1.0)
                    tr.event("uncatalogued")
            """})
        msgs = " | ".join(f.message for f in res.findings)
        assert "warp" in msgs and "uncatalogued" in msgs

    def test_fires_on_drain_phase_outside_stages(self):
        res = self.base(**{
            "pkg/runtime/executor.py": 'DRAIN_PHASES = ("deflate",)\n'
        })
        assert any("deflate" in f.message for f in res.findings)

    def test_fires_on_golden_drift_both_ways(self):
        res = self.base(**{"tests/test_telemetry.py": """
            def test_streaming_seconds_keys_golden():
                assert set(rep) == {"ingest", "drain_utilization", "total",
                                    "bonus"}
            """})
        msgs = " | ".join(f.message for f in res.findings)
        assert "bonus" in msgs  # extra key
        assert "finalise" in msgs  # missing stage

    def test_fires_on_unknown_xfer_dir(self):
        res = self.base(**{
            "pkg/telemetry/trace.py": TRACE_XFER_OK,
            "pkg/runtime/stream.py": """
            def run(tr):
                phase = {"ingest": 0.0, "finalise": 0.0}
                if tr is not None:
                    tr.xfer("warp", 0, 0, 0.0, 0.0)
            """,
        })
        assert any(
            "xfer" in f.message and "warp" in f.message
            for f in res.findings
        )

    def test_passes_on_registered_xfer_dir(self):
        res = self.base(**{
            "pkg/telemetry/trace.py": TRACE_XFER_OK,
            "pkg/runtime/stream.py": """
            def run(tr):
                phase = {"ingest": 0.0, "finalise": 0.0}
                if tr is not None:
                    tr.xfer("h2d", 0, 0, 0.0, 0.0)
            """,
        })
        assert res.ok

    def test_pre_ledger_corpus_skips_the_xfer_check(self):
        # a trace.py without KNOWN_XFER_DIRS (the fixture corpora, old
        # trees) must not fail on xfer literals it cannot pin
        res = self.base(**{"pkg/runtime/stream.py": """
            def run(tr):
                phase = {"ingest": 0.0, "finalise": 0.0}
                if tr is not None:
                    tr.xfer("anything", 0, 0, 0.0, 0.0)
            """})
        assert res.ok

    def test_fires_on_unregistered_h2d_xfer_attr(self):
        res = self.base(**{
            "pkg/telemetry/trace.py": TRACE_XFER_ATTRS,
            "pkg/runtime/stream.py": """
            def run(tr):
                phase = {"ingest": 0.0, "finalise": 0.0}
                if tr is not None:
                    tr.xfer("h2d", 0, 0, 0.0, 0.0, chunk=1, bpc=8,
                            mystery_attr=3)
            """,
        })
        assert any(
            "mystery_attr" in f.message and "KNOWN_H2D_XFER_ATTRS"
            in (f.hint or "") for f in res.findings
        )

    def test_passes_on_registered_h2d_attrs_and_pre_tuner_corpora(self):
        ok = self.base(**{
            "pkg/telemetry/trace.py": TRACE_XFER_ATTRS,
            "pkg/runtime/stream.py": """
            def run(tr):
                phase = {"ingest": 0.0, "finalise": 0.0}
                if tr is not None:
                    tr.xfer("h2d", 0, 0, 0.0, 0.0, chunk=1, bpc=8,
                            rows_real=5, rows_pad=8, cap=8)
            """,
        })
        assert ok.ok
        # no KNOWN_H2D_XFER_ATTRS registry (pre-tuner trees): skip
        legacy = self.base(**{
            "pkg/telemetry/trace.py": TRACE_XFER_OK,
            "pkg/runtime/stream.py": """
            def run(tr):
                phase = {"ingest": 0.0, "finalise": 0.0}
                if tr is not None:
                    tr.xfer("h2d", 0, 0, 0.0, 0.0, anything_goes=1)
            """,
        })
        assert legacy.ok

    def test_fires_on_unregistered_mesh_pad_attr(self):
        """mesh_pad is an h2d schema attr like bpc/rows_*: emitting it
        against a pre-mesh registry (no mesh_pad entry) is the drift
        the registry exists to catch; the current registry passes."""
        emit = """
            def run(tr):
                phase = {"ingest": 0.0, "finalise": 0.0}
                if tr is not None:
                    tr.xfer("h2d", 0, 0, 0.0, 0.0, chunk=1, bpc=8,
                            rows_real=5, rows_pad=8, cap=8, mesh_pad=1)
            """
        res = self.base(**{
            "pkg/telemetry/trace.py": TRACE_XFER_ATTRS,  # pre-mesh
            "pkg/runtime/stream.py": emit,
        })
        assert any("mesh_pad" in f.message for f in res.findings)
        ok = self.base(**{
            "pkg/telemetry/trace.py": TRACE_LANES,
            "pkg/runtime/stream.py": emit,
        })
        assert ok.ok

    def test_fires_on_unregistered_literal_lane(self):
        """A literal lane family outside KNOWN_LANE_PREFIXES forks the
        grouping key the device table / fleet stitcher / chrome export
        key on — plain literals, f-string prefixes, and unpinnable
        placeholder-first f-strings all fire."""
        res = self.base(**{
            "pkg/telemetry/trace.py": TRACE_LANES,
            "pkg/runtime/stream.py": """
            def run(tr, di, x):
                phase = {"ingest": 0.0, "finalise": 0.0}
                if tr is not None:
                    tr.span("ingest", 0.0, 1.0, lane="gpu-0")
                    tr.xfer("h2d", 0, 0, 0.0, 0.0, lane=f"chip{di}")
                    tr.event("retry", lane=f"{x}-lane")
            """,
        })
        msgs = " | ".join(f.message for f in res.findings)
        assert "gpu-0" in msgs and "chip" in msgs
        assert sum("lane" in f.message for f in res.findings) >= 3

    def test_passes_on_registered_lanes_and_pre_mesh_corpora(self):
        ok = self.base(**{
            "pkg/telemetry/trace.py": TRACE_LANES,
            "pkg/runtime/stream.py": """
            def run(tr, di, lane):
                phase = {"ingest": 0.0, "finalise": 0.0}
                if tr is not None:
                    tr.span("ingest", 0.0, 1.0, lane="main")
                    tr.span("ingest", 0.0, 1.0, lane=f"dev-{di}")
                    tr.event("retry", lane=f"job-{di}")
                    tr.xfer("h2d", 0, 0, 0.0, 0.0, lane=lane)
            """,
        })
        assert ok.ok
        # no KNOWN_LANE_PREFIXES registry (pre-mesh trees): skip
        legacy = self.base(**{
            "pkg/telemetry/trace.py": TRACE_XFER_ATTRS,
            "pkg/runtime/stream.py": """
            def run(tr):
                phase = {"ingest": 0.0, "finalise": 0.0}
                if tr is not None:
                    tr.span("ingest", 0.0, 1.0, lane="anything-goes")
            """,
        })
        assert legacy.ok

    def test_fires_on_unregistered_fleet_kind(self):
        res = self.base(**{
            "pkg/telemetry/fleet.py": FLEET_OK,
            "pkg/telemetry/other.py": """
            from pkg.telemetry.fleet import gap_rec, seg_rec

            def build():
                return [seg_rec("warp", 0, 1, "d"),
                        gap_rec("limbo", 0, 1)]
            """,
        })
        msgs = " | ".join(f.message for f in res.findings)
        assert "warp" in msgs and "limbo" in msgs

    def test_passes_on_registered_fleet_kinds_and_pre_fleet_corpora(self):
        ok = self.base(**{
            "pkg/telemetry/fleet.py": FLEET_OK,
            "pkg/telemetry/other.py": """
            from pkg.telemetry.fleet import gap_rec, seg_rec

            def build():
                return [seg_rec("run", 0, 1, "d"),
                        gap_rec("takeover", 0, 1)]
            """,
        })
        assert ok.ok
        # no fleet.py at all (pre-fleet trees): literal kinds unpinnable
        legacy = self.base(**{
            "pkg/telemetry/other.py": """
            def build(gap_rec):
                return gap_rec("anything", 0, 1)
            """,
        })
        assert legacy.ok

    def test_fires_on_dead_fleet_registry_entry(self):
        res = self.base(**{
            "pkg/telemetry/fleet.py": """
            FLEET_SEGMENT_KINDS = ("run",)
            FLEET_GAP_KINDS = ("queue_wait", "never_emitted")

            def stitch():
                kind = "run"
                pending = "queue_wait"
                return kind, pending
            """,
        })
        msgs = " | ".join(f.message for f in res.findings)
        assert "never_emitted" in msgs and "never produces" in msgs
        # the registry tuple's own literal does not count as use, but
        # honest use anywhere else in fleet.py does
        assert "queue_wait" not in msgs.replace("'never_emitted'", "")


class TestLockDiscipline:
    def test_fires_on_blocking_io_under_lock(self):
        res = lint(
            {"pkg/runtime/stream.py": """
                import threading
                def commit(phase_lock, fut, path):
                    with phase_lock:
                        data = fut.result()
                        f = open(path, "wb")
                """},
            rules=["lock-discipline"],
        )
        names = sorted(f.message for f in res.findings)
        assert len(names) == 2
        assert "open()" in names[0] and "result()" in names[1]

    def test_fires_on_compress_under_self_lock(self):
        res = lint(
            {"pkg/telemetry/trace.py": """
                class R:
                    def flush(self, z, data):
                        with self._lock:
                            return z.compress(data)
                """},
            rules=["lock-discipline"],
        )
        assert len(res.findings) == 1

    def test_passes_when_io_is_outside_the_lock(self):
        res = lint(
            {"pkg/runtime/stream.py": """
                def commit(phase_lock, fut, phase):
                    data = fut.result()
                    with phase_lock:
                        phase["finalise"] = 1.0
                """},
            rules=["lock-discipline"],
        )
        assert res.ok

    def test_fires_on_module_mutable_mutated_without_lock(self):
        res = lint(
            {"pkg/runtime/stream.py": """
                _pending = []
                def add(x):
                    _pending.append(x)
                """},
            rules=["lock-discipline"],
        )
        assert rules_of(res) == [("lock-discipline", "pkg/runtime/stream.py")]
        assert "_pending" in res.findings[0].message

    def test_passes_on_module_mutable_under_lock_or_at_import(self):
        res = lint(
            {"pkg/runtime/stream.py": """
                import threading
                _pending = []
                _pending.append("init-time is single-threaded")
                _lock = threading.Lock()
                def add(x):
                    with _lock:
                        _pending.append(x)
                """},
            rules=["lock-discipline"],
        )
        assert res.ok

    def test_out_of_scope_files_are_ignored(self):
        res = lint(
            {"pkg/io/convert.py": """
                def f(lock, p):
                    with lock:
                        open(p, "wb")
                """},
            rules=["lock-discipline"],
        )
        assert res.ok  # rule scope is stream.py + trace.py only


class TestLeaseDiscipline:
    FAULTS = 'KNOWN_SITES = ("shard.write", "serve.lease", "serve.fence")\n'
    QUEUE_OK = """
        from pkg.io.durable import write_durable
        class Q:
            def claim(self, entry):
                entry["token"] = 1
                entry["lease"] = {"owner": "d"}
                self.save()
            def release(self, entry):
                entry.pop("lease", None)
                self.save()
            def save(self):
                write_durable("queue.json", b"{}")
        """
    SERVICE_OK = """
        def loop(q):
            _io_retry("serve.lease", q.claim, "claim")
            _io_retry("serve.fence", q.verify, "fence")
        """
    TESTS_OK = """
        SERVE_SITES = ("serve.lease", "serve.fence")
        def test_kill_matrix():
            run("serve.lease:1:kill")
            run("serve.fence:1:kill")
        """

    def base(self, **over):
        files = {
            "pkg/runtime/faults.py": self.FAULTS,
            "pkg/serve/queue.py": self.QUEUE_OK,
            "pkg/serve/service.py": self.SERVICE_OK,
            "tests/test_serve.py": self.TESTS_OK,
        }
        files.update(over)
        return lint(files, rules=["lease-discipline"])

    def test_passes_when_consistent(self):
        assert self.base().ok

    def test_fires_on_unregistered_serve_site(self):
        res = self.base(**{"pkg/serve/worker.py": """
            def g(f):
                _io_retry("serve.typo", f, "x")
            """})
        assert rules_of(res) == [("lease-discipline", "pkg/serve/worker.py")]
        assert "serve.typo" in res.findings[0].message
        # non-serve sites in serve/ are the fault-registry rule's job
        ok = self.base(**{"pkg/serve/worker.py": """
            def g(f):
                _io_retry("shard.write", f, "x")
            """})
        assert ok.ok

    def test_fires_on_serving_suite_coverage_gap(self):
        res = self.base(**{"tests/test_serve.py": """
            def test_only_lease():
                run("serve.lease:1:kill")
            """})
        assert [f.rule for f in res.findings] == ["lease-discipline"]
        assert "serve.fence" in res.findings[0].message
        assert res.findings[0].path == "tests/test_serve.py"

    def test_missing_serving_suite_skips_coverage_check(self):
        files = {
            "pkg/runtime/faults.py": self.FAULTS,
            "pkg/serve/queue.py": self.QUEUE_OK,
            "pkg/serve/service.py": self.SERVICE_OK,
        }
        assert lint(files, rules=["lease-discipline"]).ok

    def test_fires_on_undurable_lease_mutation(self):
        res = self.base(**{"pkg/serve/queue.py": self.QUEUE_OK + """
        def steal(entry):
            entry["lease"] = {"owner": "thief"}
        """})
        assert [f.rule for f in res.findings] == ["lease-discipline"]
        assert "steal" in res.findings[0].message
        assert "save" in res.findings[0].hint

    def test_fires_on_undurable_lease_pop(self):
        res = self.base(**{"pkg/serve/queue.py": self.QUEUE_OK + """
        def drop(entry):
            entry.pop("lease", None)
        """})
        assert [f.rule for f in res.findings] == ["lease-discipline"]
        assert "drop" in res.findings[0].message

    def test_read_only_lease_access_needs_no_persist(self):
        res = self.base(**{"pkg/serve/queue.py": self.QUEUE_OK + """
        def check(entry, token):
            return entry["lease"]["owner"] == "d" and entry["token"] == token
        """})
        assert res.ok  # reads fence; only WRITES must persist


class TestDeadlineDiscipline:
    QUEUE_OK = """
        import time
        JOB_STATES = ("queued", "running", "done", "expired")
        class Q:
            def stamp(self, entry, deadline_s):
                entry["deadline_m"] = time.monotonic() + deadline_s
            def expire(self, entry):
                entry["state"] = "expired"
        """
    TESTS_OK = """
        def test_states():
            run("queued"); run("running"); run("done"); run("expired")
        """

    def base(self, **over):
        files = {
            "pkg/serve/queue.py": self.QUEUE_OK,
            "tests/test_serve.py": self.TESTS_OK,
        }
        files.update(over)
        return lint(files, rules=["deadline-discipline"])

    def test_passes_when_consistent(self):
        assert self.base().ok

    def test_fires_on_unsuffixed_stamp_key(self):
        res = self.base(**{"pkg/serve/svc.py": """
            def note(entry, t):
                entry["deadline"] = t
            """})
        assert rules_of(res) == [("deadline-discipline", "pkg/serve/svc.py")]
        assert "'deadline'" in res.findings[0].message
        assert "_m" in res.findings[0].hint

    def test_duration_suffix_is_legal(self):
        res = self.base(**{"pkg/serve/svc.py": """
            def note(cfg):
                return cfg.get("deadline_s", 0)
            """})
        assert res.ok

    def test_fires_on_wall_clock_stamp(self):
        # a *_m key fed from anything but time.monotonic() in-scope
        res = self.base(**{"pkg/serve/svc.py": """
            def note(entry, wall):
                entry["expires_m"] = wall + 30
            """})
        assert rules_of(res) == [("deadline-discipline", "pkg/serve/svc.py")]
        assert "monotonic" in res.findings[0].message

    def test_fires_on_unregistered_state_literal(self):
        res = self.base(**{"pkg/serve/svc.py": """
            def zombify(entry):
                entry["state"] = "zombified"
            """})
        assert rules_of(res) == [("deadline-discipline", "pkg/serve/svc.py")]
        assert "zombified" in res.findings[0].message

    def test_dict_literal_into_jobs_is_a_state_write(self):
        res = self.base(**{"pkg/serve/svc.py": """
            def admit(self, jid):
                self.jobs[jid] = {"state": "limbo", "seq": 0}
            """})
        assert [f.rule for f in res.findings] == ["deadline-discipline"]
        assert "limbo" in res.findings[0].message

    def test_temporary_dict_state_write_is_seen(self):
        # the accept_one pattern: entry built as a temporary, THEN
        # journaled — the state literal must not escape the registry
        res = self.base(**{"pkg/serve/svc.py": """
            def admit(self, jid):
                entry = {"state": "zombified", "seq": 0}
                self.jobs[jid] = entry
            """})
        assert [f.rule for f in res.findings] == ["deadline-discipline"]
        assert "zombified" in res.findings[0].message

    def test_fires_on_unexercised_registered_state(self):
        res = self.base(**{"tests/test_serve.py": """
            def test_states():
                run("queued"); run("running"); run("done")
            """})
        assert [f.rule for f in res.findings] == ["deadline-discipline"]
        assert "expired" in res.findings[0].message
        assert res.findings[0].path == "tests/test_serve.py"

    def test_missing_serving_suite_skips_exercise_check(self):
        assert lint(
            {"pkg/serve/queue.py": self.QUEUE_OK},
            rules=["deadline-discipline"],
        ).ok

    def test_read_side_pseudo_states_are_out_of_scope(self):
        # status rendering returns client-visible pseudo-states that are
        # not journal writes — the rule must not chase them
        res = self.base(**{"pkg/serve/svc.py": """
            def status(jid):
                return {"job_id": jid, "state": "submitted"}
            """})
        assert res.ok

    def test_store_clock_read_is_a_monotonic_derivation(self):
        # the host-locality seam: *_m stamps fed from the lease store's
        # clock (store.now() / store.capture_epoch()) are in-domain by
        # construction — forcing time.monotonic() back in would be the
        # exact cross-host bug the store exists to prevent
        res = self.base(**{"pkg/serve/svc.py": """
            def stamp(self, entry, lease_s):
                entry["expires_m"] = round(self.store.now() + lease_s, 3)
            def epoch(self, meta):
                meta["epoch_m"] = round(self.store.capture_epoch(), 6)
            """})
        assert res.ok


class TestHostLocality:
    # a serving layer that routes liveness and stamps through the
    # store seam, over a corpus where the sharedfs backend exists and
    # its I/O sites are registered
    SVC_OK = """
        import os
        def reclaim(self, entry, now):
            reason = self.store.reclaim_reason(
                entry.get("lease"), now, hosts=self.store.observe()
            )
            return reason
        def sweep(self, pid):
            if self.store.pid_alive(pid):
                return
        def wait_age(self, entry):
            return self.store.now() - entry["admitted_m"]
        def ident(self):
            return f"d-{os.getpid()}"
        """
    STORE_OK = """
        import os
        def _pid_alive(pid):
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                return False
            return True
        """
    FAULTS_OK = """
        KNOWN_SITES = ("serve.lease", "serve.hb", "serve.store")
        """

    def base(self, **over):
        files = {
            "pkg/serve/svc.py": self.SVC_OK,
            "pkg/serve/store.py": self.STORE_OK,
            "pkg/runtime/faults.py": self.FAULTS_OK,
        }
        files.update(over)
        return lint(files, rules=["host-locality"])

    def test_passes_when_confined_to_the_store(self):
        # the store backend itself may probe pids — that's its job —
        # and os.getpid() as an identity read is legal anywhere
        assert self.base().ok

    def test_fires_on_os_kill_outside_the_store(self):
        res = self.base(**{"pkg/serve/svc2.py": """
            import os
            def is_live(pid):
                try:
                    os.kill(pid, 0)
                except ProcessLookupError:
                    return False
                return True
            """})
        assert rules_of(res) == [("host-locality", "pkg/serve/svc2.py")]
        assert "os.kill" in res.findings[0].message
        assert "store" in res.findings[0].hint

    def test_fires_on_pid_alive_call_outside_the_store(self):
        res = self.base(**{"pkg/serve/svc2.py": """
            from pkg.serve.store import _pid_alive
            def sweep(pid):
                return _pid_alive(pid)
            """})
        assert rules_of(res) == [("host-locality", "pkg/serve/svc2.py")]
        assert "_pid_alive" in res.findings[0].message

    def test_fires_on_journal_pid_comparison(self):
        # pid equality against a journal record is an ownership/liveness
        # verdict in disguise — two hosts can share a pid number
        res = self.base(**{"pkg/serve/svc2.py": """
            import os
            def mine(lease):
                return lease.get("pid") == os.getpid()
            """})
        assert rules_of(res) == [("host-locality", "pkg/serve/svc2.py")]
        assert "'pid'" in res.findings[0].message

    def test_fires_on_monotonic_vs_stamp_arithmetic(self):
        res = self.base(**{"pkg/serve/svc2.py": """
            import time
            def stalled(entry, budget_s):
                return time.monotonic() - entry["progress_m"] > budget_s
            """})
        assert rules_of(res) == [("host-locality", "pkg/serve/svc2.py")]
        assert "monotonic" in res.findings[0].message
        assert "store.now()" in res.findings[0].hint

    def test_store_now_vs_stamp_is_the_legal_form(self):
        res = self.base(**{"pkg/serve/svc2.py": """
            def stalled(self, entry, budget_s):
                return self.store.now() - entry["progress_m"] > budget_s
            """})
        assert res.ok

    def test_local_monotonic_durations_stay_legal(self):
        # pure local durations (no *_m key in the expression) are fine:
        # lock-wait accounting, elapsed_s, chunk cadence
        res = self.base(**{"pkg/serve/svc2.py": """
            import time
            def waited(start):
                return time.monotonic() - start
            """})
        assert res.ok

    def test_fires_on_unregistered_xhost_site(self):
        res = self.base(**{"pkg/runtime/faults.py": """
            KNOWN_SITES = ("serve.lease", "serve.hb")
            """})
        assert rules_of(res) == [("host-locality", "pkg/runtime/faults.py")]
        assert "serve.store" in res.findings[0].message
        assert "chaos" in res.findings[0].hint

    def test_pre_fleet_corpus_owes_no_sites(self):
        # fixture corpora without the store backend (every older rule's
        # miniature serve/ tree) must not be retrofitted with sites
        res = lint(
            {
                "pkg/serve/svc.py": """
                    def wait_age(self, entry, now):
                        return now - entry["admitted_m"]
                    """,
                "pkg/runtime/faults.py": "KNOWN_SITES = (\"serve.lease\",)\n",
            },
            rules=["host-locality"],
        )
        assert res.ok


class TestHookGuard:
    def test_fires_on_unguarded_hook(self):
        res = lint(
            {"pkg/runtime/stream.py": """
                def run(tr):
                    tr.span("ingest", 0.0, 1.0)
                """},
            rules=["hook-guard"],
        )
        assert rules_of(res) == [("hook-guard", "pkg/runtime/stream.py")]
        assert "tr is not None" in res.findings[0].hint

    def test_passes_on_guarded_hooks(self):
        res = lint(
            {"pkg/runtime/stream.py": """
                def run(tr, resume):
                    if tr is not None:
                        tr.span("ingest", 0.0, 1.0)
                    if tr is not None and resume:
                        tr.event("resume")
                    if tr is None:
                        pass
                    else:
                        tr.event("retry")
                """},
            rules=["hook-guard"],
        )
        assert res.ok

    def test_bare_self_receivers_are_exempt(self):
        res = lint(
            {"pkg/telemetry/trace.py": """
                class Heartbeat:
                    def beat(self):
                        self.event("heartbeat")
                """},
            rules=["hook-guard"],
        )
        assert res.ok

    def test_dotted_receivers_are_checked_not_exempt(self):
        res = lint(
            {"pkg/runtime/stream.py": """
                def run(ctx):
                    ctx.tr.span("ingest", 0.0, 1.0)
                """},
            rules=["hook-guard"],
        )
        assert rules_of(res) == [("hook-guard", "pkg/runtime/stream.py")]
        assert "ctx.tr.span" in res.findings[0].message

    def test_dotted_receiver_guard_matches_the_same_path(self):
        res = lint(
            {"pkg/telemetry/trace.py": """
                class Heartbeat:
                    def beat(self):
                        if self._recorder is not None:
                            self._recorder.event("heartbeat")
                """},
            rules=["hook-guard"],
        )
        assert res.ok

    def test_fires_on_unguarded_xfer_hook(self):
        # the byte-ledger hook carries the same zero-cost-when-off
        # obligation as span/event
        res = lint(
            {"pkg/runtime/stream.py": """
                def dispatch(tr):
                    tr.xfer("h2d", 10, 5, 0.0, 0.1)
                """},
            rules=["hook-guard"],
        )
        assert rules_of(res) == [("hook-guard", "pkg/runtime/stream.py")]
        assert "tr.xfer" in res.findings[0].message

    def test_passes_on_guarded_xfer_hook(self):
        res = lint(
            {"pkg/runtime/stream.py": """
                def dispatch(tr):
                    if tr is not None:
                        tr.xfer("h2d", 10, 5, 0.0, 0.1)
                """},
            rules=["hook-guard"],
        )
        assert res.ok


STATES_OK = """
    JOB_STATES = ("queued", "running", "done", "failed", "quarantined",
                  "rejected")
    INITIAL_STATES = ("queued", "rejected")
    TRANSITIONS = {
        "queued": ("running",),
        "running": ("done", "failed", "queued", "quarantined"),
        "done": (),
        "failed": (),
        "quarantined": (),
        "rejected": (),
    }
    """
# a queue implementing every declared edge, each write with from-state
# evidence (comparison guard, fence-guard call, or membership assert)
QUEUE_SM_OK = """
    class Q:
        def admit(self, jid, ok):
            if ok:
                self.jobs[jid] = {"state": "queued", "seq": 0}
            else:
                self.jobs[jid] = {"state": "rejected", "seq": 0}
        def claim(self, entry):
            if entry.get("state") != "queued":
                return None
            entry["state"] = "running"
        def finish(self, entry, good):
            self._check_fence(entry)
            entry["state"] = "done" if good else "failed"
        def requeue(self, entry):
            self._check_fence(entry)
            entry["state"] = "queued"
        def quarantine(self, entry):
            assert entry.get("state") in CLAIMED_STATES
            entry["state"] = "quarantined"
    """
# a registry-pin referencing TRANSITIONS satisfies the coverage leg
TESTS_SM_OK = """
    from pkg.serve import states
    def test_pin():
        walk(states.TRANSITIONS)
    """


class TestStateMachine:
    def base(self, **over):
        files = {
            "pkg/serve/states.py": STATES_OK,
            "pkg/serve/queue.py": QUEUE_SM_OK,
            "tests/test_serve.py": TESTS_SM_OK,
        }
        files.update(over)
        return lint(files, rules=["state-machine"])

    def test_passes_when_code_matches_the_declared_graph(self):
        assert self.base().ok

    def test_missing_states_module_skips_the_rule(self):
        res = lint(
            {"pkg/serve/queue.py": QUEUE_SM_OK}, rules=["state-machine"]
        )
        assert res.ok

    def test_fires_on_write_over_a_terminal_state(self):
        res = self.base(**{"pkg/serve/svc.py": """
            def resurrect(entry):
                if entry.get("state") == "done":
                    entry["state"] = "queued"
            """})
        assert rules_of(res) == [("state-machine", "pkg/serve/svc.py")]
        assert "terminal" in res.findings[0].message

    def test_fires_on_undeclared_transition(self):
        res = self.base(**{"pkg/serve/svc.py": """
            def unadmit(entry):
                if entry.get("state") == "running":
                    entry["state"] = "rejected"
            """})
        assert rules_of(res) == [("state-machine", "pkg/serve/svc.py")]
        assert "undeclared transition" in res.findings[0].message
        assert "rejected" in res.findings[0].message

    def test_fires_on_write_without_from_state_evidence(self):
        res = self.base(**{"pkg/serve/svc.py": """
            def zap(entry):
                entry["state"] = "queued"
            """})
        assert rules_of(res) == [("state-machine", "pkg/serve/svc.py")]
        assert "no from-state evidence" in res.findings[0].message
        assert "zap" in res.findings[0].message

    def test_fence_guard_counts_as_claimed_evidence(self):
        # the real codebase's idiom: _check_fence proves CLAIMED, so a
        # publish function needs no literal state comparison
        res = self.base(**{"pkg/serve/svc.py": """
            def publish(self, entry):
                self._check_fence(entry)
                entry["state"] = "done"
            """})
        assert res.ok

    def test_fires_on_creation_in_non_initial_state(self):
        res = self.base(**{"pkg/serve/svc.py": """
            def smuggle(self, jid):
                self.jobs[jid] = {"state": "running", "seq": 0}
            """})
        assert rules_of(res) == [("state-machine", "pkg/serve/svc.py")]
        assert "non-initial" in res.findings[0].message

    def test_temporary_dict_creation_is_seen(self):
        # the accept_one pattern: entry built as a temporary, THEN
        # journaled — still a creation, still held to INITIAL_STATES
        res = self.base(**{"pkg/serve/svc.py": """
            def smuggle(self, jid):
                entry = {"state": "running", "seq": 0}
                self.jobs[jid] = entry
            """})
        assert rules_of(res) == [("state-machine", "pkg/serve/svc.py")]
        assert "non-initial" in res.findings[0].message

    def test_status_dicts_that_never_reach_the_cache_are_ignored(self):
        # read-side rendering: a response dict with a state field is
        # not a journal-entry creation
        res = self.base(**{"pkg/serve/svc.py": """
            def status(jid):
                resp = {"state": "done", "detail": "x"}
                return resp
            """})
        assert res.ok

    def test_update_and_setdefault_writes_are_seen(self):
        # state writes in call clothing must not slip the gate
        res = self.base(**{"pkg/serve/svc.py": """
            def sneak(entry):
                if entry.get("state") == "done":
                    entry.update({"state": "queued"})
            def sneak_kw(entry):
                if entry.get("state") == "done":
                    entry.update(state="queued")
            def sneak_sd(entry):
                if entry.get("state") == "done":
                    entry.setdefault("state", "queued")
            """})
        assert [f.rule for f in res.findings] == ["state-machine"] * 3
        assert all("terminal" in f.message for f in res.findings)

    def test_guarded_update_write_passes(self):
        res = self.base(**{"pkg/serve/svc.py": """
            def requeue(entry):
                if entry.get("state") == "running":
                    entry.update({"state": "queued"})
            """})
        assert res.ok

    def test_full_registry_membership_is_not_evidence(self):
        # `in JOB_STATES` proves nothing about the from-state: without
        # this, a meaningless guard would launder terminal-state
        # resurrection past the check
        res = self.base(**{"pkg/serve/svc.py": """
            def launder(entry):
                if entry.get("state") in JOB_STATES:
                    entry["state"] = "queued"
            """})
        assert rules_of(res) == [("state-machine", "pkg/serve/svc.py")]
        assert "no from-state evidence" in res.findings[0].message

    def test_fires_on_unreachable_state(self):
        res = self.base(**{"pkg/serve/states.py": """
            JOB_STATES = ("queued", "running", "done", "failed",
                          "quarantined", "rejected", "limbo")
            INITIAL_STATES = ("queued", "rejected")
            TRANSITIONS = {
                "queued": ("running",),
                "running": ("done", "failed", "queued", "quarantined"),
                "done": (),
                "failed": (),
                "quarantined": (),
                "rejected": (),
                "limbo": (),
            }
            """})
        assert [f.rule for f in res.findings] == ["state-machine"]
        assert "limbo" in res.findings[0].message
        assert "unreachable" in res.findings[0].message

    def test_fires_on_declared_edge_with_no_write_site(self):
        res = self.base(**{"pkg/serve/states.py": STATES_OK.replace(
            '"queued": ("running",),',
            '"queued": ("running", "failed"),',
        )})
        assert [f.rule for f in res.findings] == ["state-machine"]
        assert "no write site" in res.findings[0].message
        assert "failed" in res.findings[0].message

    def test_edge_literals_also_satisfy_the_coverage_leg(self):
        # no TRANSITIONS reference, but every declared edge appears as
        # a "src->dst" literal — the non-blanket coverage form
        res = self.base(**{"tests/test_serve.py": """
            def test_edges():
                for edge in ("queued->running", "running->done",
                             "running->failed", "running->queued",
                             "running->quarantined"):
                    drive(edge)
            """})
        assert res.ok

    def test_fires_on_unexercised_declared_transition(self):
        res = self.base(**{"tests/test_serve.py": """
            def test_edges():
                drive("queued->running")
            """})
        assert res.findings  # the four running->* edges are uncovered
        assert all(f.path == "tests/test_serve.py" for f in res.findings)
        assert any("running->done" in f.message for f in res.findings)


TXN_QUEUE_OK = """
    import contextlib
    TXN_CACHE_HELPERS = ("_load",)
    class Q:
        @contextlib.contextmanager
        def _txn(self):
            self._load()
            yield
        def _load(self):
            self.jobs = {}
        def admit(self, jid, entry):
            with self._txn():
                self.jobs[jid] = entry
                self.save()
        def _compact_locked(self, jid):
            del self.jobs[jid]
        def save(self):
            write_durable("queue.json", b"{}")
    """


class TestTxnDiscipline:
    def base(self, **over):
        files = {"pkg/serve/queue.py": TXN_QUEUE_OK}
        files.update(over)
        return lint(files, rules=["txn-discipline"])

    def test_passes_on_transacted_mutations(self):
        assert self.base().ok

    def test_fires_on_jobs_mutation_outside_a_txn(self):
        res = self.base(**{"pkg/serve/svc.py": """
            def rogue(q, jid):
                q.jobs[jid] = {"state": "queued"}
            """})
        assert rules_of(res) == [("txn-discipline", "pkg/serve/svc.py")]
        assert "outside a journal transaction" in res.findings[0].message

    def test_fires_on_untransacted_save(self):
        res = self.base(**{"pkg/serve/svc.py": """
            def flush(queue):
                queue.save()
            """})
        assert rules_of(res) == [("txn-discipline", "pkg/serve/svc.py")]
        assert "save()" in res.findings[0].message

    def test_non_journal_save_receivers_are_ignored(self):
        # .save() is only a journal persist on self/*queue* receivers —
        # a figure/report object's save has its own semantics
        res = self.base(**{"pkg/serve/svc.py": """
            def snapshot(fig, path):
                fig.save(path)
            """})
        assert res.ok

    def test_locked_suffix_and_registry_helpers_are_exempt(self):
        # _compact_locked and _load mutate the cache with the caller
        # holding the lock — declared, not flagged (the base fixture
        # already passes with both present)
        res = self.base(**{"pkg/serve/svc.py": """
            def _apply_locked(q, jid):
                q.jobs[jid] = {"state": "queued"}
            """})
        assert res.ok

    def test_fires_on_slow_call_inside_a_txn(self):
        res = self.base(**{"pkg/serve/svc.py": """
            import time
            def slow(q, z, data):
                with q._txn():
                    time.sleep(1.0)
                    z.compress(data)
            """})
        msgs = sorted(f.message for f in res.findings)
        assert len(msgs) == 2
        assert "compress()" in msgs[0] and "sleep()" in msgs[1]

    def test_fires_on_nested_txn_via_method_call(self):
        res = self.base(**{"pkg/serve/svc.py": """
            def outer(q, jid, entry):
                with q._txn():
                    q.admit(jid, entry)
            """})
        assert rules_of(res) == [("txn-discipline", "pkg/serve/svc.py")]
        assert "nested journal transaction" in res.findings[0].message
        assert "admit" in res.findings[0].message

    def test_fires_on_directly_nested_txn_with(self):
        res = self.base(**{"pkg/serve/svc.py": """
            def outer(q):
                with q._txn():
                    with q._txn():
                        pass
            """})
        assert rules_of(res) == [("txn-discipline", "pkg/serve/svc.py")]
        assert "with _txn()" in res.findings[0].message

    def test_reads_need_no_txn(self):
        res = self.base(**{"pkg/serve/svc.py": """
            def peek(q, jid):
                return q.jobs.get(jid, {}).get("state")
            """})
        assert res.ok


class TestFenceDominance:
    def test_passes_when_lease_identity_is_passed(self):
        res = lint(
            {"pkg/serve/service.py": """
                def publish(q, jid, result, daemon_id, token):
                    q.mark_done(jid, result, daemon_id, token)
                def requeue(q, jid, n):
                    q.requeue(jid, n, back=True, daemon_id="d", token=3)
                """},
            rules=["fence-dominance"],
        )
        assert res.ok

    def test_passes_under_a_fence_guard_in_scope(self):
        res = lint(
            {"pkg/serve/service.py": """
                def merge(q, jid, dicts):
                    fenced_renew(q, jid)
                    q.register_shards(jid, dicts)
                """},
            rules=["fence-dominance"],
        )
        assert res.ok

    def test_fires_on_identity_less_publish(self):
        res = lint(
            {"pkg/serve/service.py": """
                def publish(q, jid, result):
                    q.mark_done(jid, result)
                """},
            rules=["fence-dominance"],
        )
        assert rules_of(res) == [("fence-dominance", "pkg/serve/service.py")]
        assert "unfenced durable publish mark_done" in res.findings[0].message
        assert "fenced_renew" in res.findings[0].hint

    def test_queue_internals_and_non_serve_files_are_exempt(self):
        res = lint(
            {
                # the implementation side: fences inside its own txn
                "pkg/serve/queue.py": """
                    class Q:
                        def requeue(self, jid, n):
                            self.jobs[jid]["chunks_done"] = n
                    """,
                # outside serve/: not on the job path
                "pkg/runtime/stream.py": """
                    def helper(q, jid):
                        q.requeue(jid, 0)
                    """,
            },
            rules=["fence-dominance"],
        )
        assert res.ok


class TestExceptionContract:
    def test_fires_on_contract_class_with_wrong_base(self):
        res = lint(
            {"pkg/serve/queue.py": """
                class JobFenced(Exception):
                    pass
                """},
            rules=["exception-contract"],
        )
        assert rules_of(res) == [("exception-contract", "pkg/serve/queue.py")]
        assert "BaseException" in res.findings[0].message

    def test_passes_on_declared_base(self):
        res = lint(
            {"pkg/serve/queue.py": """
                class JobFenced(BaseException):
                    pass
                """},
            rules=["exception-contract"],
        )
        assert res.ok

    def test_fires_on_bare_except_in_scope(self):
        res = lint(
            {"pkg/runtime/stream.py": """
                def f(g):
                    try:
                        g()
                    except:
                        pass
                """},
            rules=["exception-contract"],
        )
        assert rules_of(res) == [("exception-contract", "pkg/runtime/stream.py")]
        assert "bare" in res.findings[0].message

    def test_fires_on_swallowed_base_exception(self):
        res = lint(
            {"pkg/serve/service.py": """
                def f(g):
                    try:
                        g()
                    except BaseException:
                        pass
                """},
            rules=["exception-contract"],
        )
        assert rules_of(res) == [("exception-contract", "pkg/serve/service.py")]
        assert "neither re-raises nor captures" in res.findings[0].message

    def test_reraise_and_store_idioms_pass(self):
        res = lint(
            {"pkg/serve/service.py": """
                def cleanup(g, f):
                    try:
                        g()
                    except BaseException:
                        f.close()
                        raise
                def fatal(self, g):
                    try:
                        g()
                    except BaseException as e:
                        self._fatal = e
                """},
            rules=["exception-contract"],
        )
        assert res.ok

    def test_fires_on_deferred_reraise_of_overflow(self):
        res = lint(
            {"pkg/runtime/stream.py": """
                def f(unpack, log):
                    try:
                        return unpack()
                    except D2hCompactionOverflow:
                        log("overflow")
                        raise
                """},
            rules=["exception-contract"],
        )
        assert rules_of(res) == [("exception-contract", "pkg/runtime/stream.py")]
        assert "re-raise immediately" in res.findings[0].message

    def test_immediate_reraise_passes(self):
        res = lint(
            {"pkg/runtime/stream.py": """
                def f(unpack):
                    try:
                        return unpack()
                    except D2hCompactionOverflow:
                        raise
                """},
            rules=["exception-contract"],
        )
        assert res.ok

    RAISER = """
        class D2hCompactionOverflow(RuntimeError):
            pass
        def unpack_fetch_outputs(x):
            raise D2hCompactionOverflow("overflow")
        """

    def test_fires_on_retry_ladder_absorbing_a_deterministic_raise(self):
        # unpack() is one wrapper hop from the raise; the broad retry
        # handler would re-derive the identical overflow forever
        res = lint(
            {
                "pkg/runtime/executor.py": self.RAISER,
                "pkg/runtime/stream.py": """
                    def unpack(x):
                        return unpack_fetch_outputs(x)
                    def materialize(x):
                        err = None
                        for attempt in range(3):
                            try:
                                return unpack(x)
                            except Exception as e:
                                err = e
                        raise err
                    """,
            },
            rules=["exception-contract"],
        )
        assert [f.rule for f in res.findings] == ["exception-contract"]
        assert "broad handler may absorb" in res.findings[0].message
        assert res.findings[0].path == "pkg/runtime/stream.py"

    def test_reraise_guard_before_the_broad_handler_passes(self):
        res = lint(
            {
                "pkg/runtime/executor.py": self.RAISER,
                "pkg/runtime/stream.py": """
                    def unpack(x):
                        return unpack_fetch_outputs(x)
                    def materialize(x):
                        err = None
                        for attempt in range(3):
                            try:
                                return unpack(x)
                            except D2hCompactionOverflow:
                                raise
                            except Exception as e:
                                err = e
                        raise err
                    """,
            },
            rules=["exception-contract"],
        )
        assert res.ok

    def test_out_of_scope_files_are_ignored(self):
        res = lint(
            {"pkg/telemetry/trace.py": """
                def f(g):
                    try:
                        g()
                    except:
                        pass
                """},
            rules=["exception-contract"],
        )
        assert res.ok  # scope is runtime/ + serve/ only


class TestThreadConfinement:
    # a miniature declared thread model: the main loop owns the
    # consumer structures, the producer row mirrors PR 17's contract
    KNOBS_ROLES = """
        THREAD_ROLES = {
            "main": {
                "module": "runtime/stream.py",
                "entry": "",
                "marker": "",
                "may": ("device", "durable", "journal"),
                "shared": (
                    ("inflight", ""), ("done_q", ""),
                    ("prefetch_sem", ""), ("ckpt", ""),
                ),
            },
            "ingest": {
                "module": "runtime/stream.py",
                "entry": "_ingest_producer",
                "marker": "dut-ingest",
                "may": (),
                "handoff": "ingest_q",
                "shared": (
                    ("ingest_q", ""),
                    ("phase", "phase_lock"),
                ),
            },
        }
        """

    # a confined producer: pure host prep handed off through the
    # bounded queue only, declared shared state under its declared lock
    STREAM_OK = """
        import queue as _queue
        def _stream_call(chunk_iter, prefetch_depth, phase, phase_lock):
            ingest_q = _queue.Queue(maxsize=prefetch_depth)
            def _prep_chunk(k, batch):
                return [batch]
            def _q_put(item):
                ingest_q.put(item, timeout=0.05)
            def _ingest_producer():
                for k, item in enumerate(chunk_iter):
                    prep = _prep_chunk(k, item)
                    _q_put(("item", (k, item, prep)))
                with phase_lock:
                    phase["producer"] = "done"
                _q_put(("done", None))
        """

    def base(self, src=STREAM_OK, roles=KNOBS_ROLES):
        files = {"pkg/runtime/stream.py": src}
        if roles is not None:
            files["pkg/runtime/knobs.py"] = roles
        return lint(files, rules=["thread-confinement"])

    def test_passes_on_a_confined_producer(self):
        assert self.base().ok

    def test_passes_on_a_pre_registry_corpus(self):
        # corpora predating the thread model (the other fixture corpora
        # here) owe nothing to this rule
        assert self.base(roles=None).ok

    def test_fires_on_device_call_without_the_grant(self):
        res = self.base(self.STREAM_OK.replace(
            "return [batch]", "return device_put(batch)"
        ))
        assert not res.ok
        assert any("'device' grant" in f.message for f in res.findings)

    def test_fires_on_durable_write_without_the_grant(self):
        # the acceptance case: a producer-thread checkpoint mark
        res = self.base(self.STREAM_OK.replace(
            "return [batch]", "ckpt.mark(k)\n                return [batch]"
        ))
        assert not res.ok
        assert any("'durable' grant" in f.message for f in res.findings)
        # and ckpt itself is another role's structure
        assert any("ckpt" in f.message and "not declared" in f.message
                   for f in res.findings)

    def test_fires_on_journal_txn_without_the_grant(self):
        res = self.base(self.STREAM_OK.replace(
            "return [batch]", "_txn(k)\n                return [batch]"
        ))
        assert not res.ok
        assert any("'journal' grant" in f.message for f in res.findings)

    def test_fires_on_undeclared_shared_structure(self):
        res = self.base(self.STREAM_OK.replace(
            "_q_put((\"done\", None))",
            "prefetch_sem.release()",
        ))
        assert rules_of(res) == [
            ("thread-confinement", "pkg/runtime/stream.py")
        ]
        assert "prefetch_sem" in res.findings[0].message

    def test_fires_on_declared_structure_outside_its_lock(self):
        res = self.base(self.STREAM_OK.replace(
            "                with phase_lock:\n"
            "                    phase[\"producer\"] = \"done\"",
            "                phase[\"producer\"] = \"done\"",
        ))
        assert not res.ok
        assert any(
            "outside its declared lock" in f.message
            and "phase_lock" in f.message
            for f in res.findings
        )

    def test_fires_on_put_to_a_foreign_queue(self):
        res = self.base(self.STREAM_OK.replace(
            "ingest_q.put(item, timeout=0.05)",
            "other_q.put(item, timeout=0.05)",
        ))
        assert not res.ok
        assert any("handoff" in f.message or "handoff" in f.hint
                   for f in res.findings)

    def test_fires_when_the_entry_function_is_renamed_away(self):
        # thread marker present but the declared entry is gone: the
        # rule must refuse to silently skip
        res = self.base("""
            import threading
            def _stream_call():
                t = threading.Thread(target=None, name="dut-ingest")
            """)
        assert not res.ok
        assert "_ingest_producer" in res.findings[0].message

    def test_fires_when_the_registry_is_deleted_but_referenced(self):
        res = self.base(
            "# confined per THREAD_ROLES\ndef _stream_call():\n    pass\n",
            roles=None,
        )
        assert not res.ok
        assert "THREAD_ROLES" in res.findings[0].message

    def test_fires_on_an_unreadable_registry_literal(self):
        res = self.base(roles="THREAD_ROLES = _build_roles()\n")
        assert not res.ok
        assert "readable literal" in res.findings[0].message


# ------------------------------------------------------------ knob-taint

KNOBS_TABLE_OK = """
    SURFACES = (
        "fingerprint", "spec_signature", "provenance", "job_config",
        "streaming_only",
    )
    KNOB_TABLE = {
        "capacity": {
            "flag": "--capacity", "class": "semantic",
            "surfaces": ("fingerprint", "spec_signature", "provenance",
                         "job_config"),
            "default": 2048,
        },
        "drain_workers": {
            "flag": "--drain-workers", "class": "scheduling",
            "surfaces": ("provenance", "job_config"),
            "default": 2,
        },
        "packed": {
            "flag": "--packed", "class": "scheduling",
            "surfaces": ("job_config", "streaming_only"),
            "default": "auto",
        },
    }
"""

FP_STREAM_OK = """
    def _fingerprint(path, capacity):
        return {"path": path, "capacity": capacity}
"""

JOB_OK = """
    from pkg.runtime import knobs
    CONFIG_DEFAULTS = {
        "capacity": 2048, "drain_workers": 2, "packed": "auto",
    }
    def spec_signature(spec):
        return "|".join(str(spec[k]) for k in ("capacity",))
    def serve_provenance(config):
        parts = []
        for key, default in CONFIG_DEFAULTS.items():
            if "provenance" not in knobs.KNOBS[key].surfaces:
                continue
            parts.append(key)
        return " ".join(parts)
"""

CLI_OK = """
    from pkg.runtime import knobs
    def resolve(args, opt):
        capacity = opt("capacity", 2048)
        drain_workers = opt("drain_workers", 2)
        packed = opt("packed", "auto")
        return capacity, drain_workers, packed
"""

TESTS_OK = """
    SCHEDULING_MATRIX = {
        "drain_workers": "tests/test_stream.py::test_dw_ab",
        "packed": "tests/test_stream.py::TestWireDietMatrix",
    }
"""


class TestKnobTaint:
    def base(self, **over):
        files = {
            "pkg/runtime/knobs.py": KNOBS_TABLE_OK,
            "pkg/runtime/stream.py": FP_STREAM_OK,
            "pkg/serve/job.py": JOB_OK,
            "pkg/cli/main.py": CLI_OK,
            "tests/test_knobs.py": TESTS_OK,
        }
        files.update(over)
        files = {k: v for k, v in files.items() if v is not None}
        return lint(files, rules=["knob-taint"])

    def test_passes_when_surfaces_match_declarations(self):
        assert self.base().ok

    def test_passes_on_a_pre_registry_corpus(self):
        res = lint(
            {"pkg/runtime/stream.py": FP_STREAM_OK}, rules=["knob-taint"]
        )
        assert res.ok

    def test_fires_on_scheduling_knob_in_the_fingerprint(self):
        # the acceptance case: seeding a scheduling knob into the
        # fingerprint dict is caught at the seeded line
        res = self.base(**{"pkg/runtime/stream.py": """
            def _fingerprint(path, capacity, drain_workers):
                return {
                    "path": path, "capacity": capacity,
                    "drain_workers": drain_workers,
                }
            """})
        assert not res.ok
        assert any(
            "taints the checkpoint fingerprint" in f.message
            and "drain_workers" in f.message
            for f in res.findings
        )

    def test_fires_on_declared_knob_missing_from_the_fingerprint(self):
        res = self.base(**{"pkg/runtime/stream.py": """
            def _fingerprint(path):
                return {"path": path}
            """})
        assert not res.ok
        assert any(
            "never reaches _fingerprint" in f.message
            and "capacity" in f.message
            for f in res.findings
        )

    def test_fires_on_undeclared_opt_literal(self):
        res = self.base(**{"pkg/cli/main.py": CLI_OK.replace(
            'packed = opt("packed", "auto")',
            'packed = opt("packed", "auto")\n'
            '        turbo = opt("turbo_mode", 1)',
        )})
        assert not res.ok
        assert any(
            "opt('turbo_mode')" in f.message.replace('"', "'")
            for f in res.findings
        )

    def test_fires_on_hand_rolled_provenance_exclusion(self):
        res = self.base(**{"pkg/serve/job.py": JOB_OK.replace(
            "parts.append(key)",
            'if key == "packed":\n'
            "                continue\n"
            "            parts.append(key)",
        )})
        assert not res.ok
        assert any(
            "serve_provenance special-cases" in f.message
            and "packed" in f.message
            for f in res.findings
        )

    def test_fires_on_config_defaults_drift(self):
        res = self.base(**{"pkg/serve/job.py": JOB_OK.replace(
            '"capacity": 2048, "drain_workers": 2, "packed": "auto",',
            '"capacity": 2048, "drain_workers": 2,',
        )})
        assert not res.ok
        assert any(
            "CONFIG_DEFAULTS lacks the key" in f.message
            and "packed" in f.message
            for f in res.findings
        )

    def test_fires_on_unexercised_scheduling_knob(self):
        res = self.base(**{"tests/test_knobs.py": TESTS_OK.replace(
            '"packed": "tests/test_stream.py::TestWireDietMatrix",', ""
        )})
        assert not res.ok
        assert any(
            "no byte-identity exercise" in f.message
            and "packed" in f.message
            for f in res.findings
        )

    def test_coverage_leg_skips_corpora_without_tests(self):
        assert self.base(**{"tests/test_knobs.py": None}).ok

    def test_fires_when_the_registry_is_deleted_but_referenced(self):
        res = lint(
            {"pkg/serve/job.py": "# derived from KNOB_TABLE\nX = 1\n"},
            rules=["knob-taint"],
        )
        assert not res.ok
        assert "KNOB_TABLE" in res.findings[0].message


# ------------------------------------------------- kernel-cost-registry

PIPE_COSTS_OK = """
    def _cost_matmul(spec, r, l, b):
        return 1.0

    SSC_METHOD_COSTS = {
        "matmul": _cost_matmul,
        "blockseg": _cost_matmul,
    }
"""

TRACE_DEV_OK = """
    KNOWN_DEV_FIELDS = (
        "cap", "cycles", "buckets", "method", "flops",
        "h2d_wire", "d2h_wire", "disp_s",
    )
"""

KERNEL_OK = """
    def ssc_kernel(x, method="matmul"):
        if method == "blockseg":
            return x + 1
        return x
"""

STREAM_DEV_OK = """
    def drain(tr):
        if tr is not None:
            tr.dev(0.0, 0.1, chunk=0, cap=128, cycles=9, buckets=1,
                   method="matmul", flops=1.0, h2d_wire=1, d2h_wire=1,
                   disp_s=0.1)
"""


class TestKernelCostRegistry:
    def base(self, **over):
        files = {
            "pkg/ops/pipeline.py": PIPE_COSTS_OK,
            "pkg/telemetry/trace.py": TRACE_DEV_OK,
            "pkg/kernels/ssc.py": KERNEL_OK,
            "pkg/runtime/stream.py": STREAM_DEV_OK,
        }
        files.update(over)
        return lint(files, rules=["kernel-cost-registry"])

    def test_passes_when_registries_are_closed(self):
        assert self.base().ok

    def test_fires_on_unregistered_method_literal(self):
        res = self.base(**{"pkg/kernels/ssc.py": """
            def ssc_kernel(x, method="matmul"):
                if method in ("blockseg", "warp"):
                    return x + 1
                return x
            """})
        assert any(
            "'warp'" in f.message and "FLOP cost" in f.message
            for f in res.findings
        )

    def test_fires_on_unregistered_dev_field(self):
        res = self.base(**{"pkg/runtime/stream.py": """
            def drain(tr):
                if tr is not None:
                    tr.dev(0.0, 0.1, chunk=0, method="matmul", gflops=3.0)
            """})
        assert any("'gflops'" in f.message for f in res.findings)
        # chunk/lane are recorder-envelope args, never findings
        assert not any("'chunk'" in f.message for f in res.findings)

    def test_fires_on_dead_registry_entry(self):
        res = self.base(**{"pkg/kernels/ssc.py": """
            def ssc_kernel(x, method="matmul"):
                return x
            """})
        assert any(
            "'blockseg'" in f.message and "no kernel" in f.message
            for f in res.findings
        )

    def test_skips_pre_registry_corpora(self):
        # corpora without the registries (older anchors, fixtures for
        # other rules) must not fire — the rule has nothing to close
        res = lint(
            {"pkg/kernels/ssc.py": KERNEL_OK},
            rules=["kernel-cost-registry"],
        )
        assert res.ok


# ------------------------------------------------------------------- CLI

class TestCli:
    def test_shipped_tree_is_clean_via_cli(self):
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "dutlint.py"),
             "--json"],
            capture_output=True, text=True, timeout=120,
        )
        assert p.returncode == 0, p.stdout + p.stderr
        rep = json.loads(p.stdout)
        assert rep["ok"] and rep["findings"] == []
        assert rep["n_files"] > 50

    def test_cli_exit_1_names_rule_and_location(self, tmp_path):
        bad = tmp_path / "pkg" / "runtime" / "hot.py"
        bad.parent.mkdir(parents=True)
        bad.write_text("import time\ndef f():\n    return time.time()\n")
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "dutlint.py"),
             "--root", str(tmp_path), "pkg/runtime/hot.py"],
            capture_output=True, text=True, timeout=120,
        )
        assert p.returncode == 1
        assert "pkg/runtime/hot.py:3: [clock-discipline]" in p.stdout

    def test_list_rules(self):
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "dutlint.py"),
             "--list-rules"],
            capture_output=True, text=True, timeout=120,
        )
        assert p.returncode == 0
        for rid in RULES:
            assert rid in p.stdout

    def test_json_findings_are_machine_readable(self, tmp_path):
        # the CI/editor contract: exit 1 + a parseable report naming
        # rule, file, line and message for every finding
        bad = tmp_path / "pkg" / "runtime" / "hot.py"
        bad.parent.mkdir(parents=True)
        bad.write_text("import time\ndef f():\n    return time.time()\n")
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "dutlint.py"),
             "--root", str(tmp_path), "--json", "pkg/runtime/hot.py"],
            capture_output=True, text=True, timeout=120,
        )
        assert p.returncode == 1
        rep = json.loads(p.stdout)
        assert not rep["ok"]
        (f,) = rep["findings"]
        assert f["rule"] == "clock-discipline"
        assert f["path"] == "pkg/runtime/hot.py"
        assert f["line"] == 3
        assert "time.time()" in f["message"]

    def test_rule_selection_runs_only_the_named_pass(self, tmp_path):
        # one file violating two rules; --rule bisects to one of them
        bad = tmp_path / "pkg" / "runtime" / "w.py"
        bad.parent.mkdir(parents=True)
        bad.write_text(
            "import time\n"
            "def f(p):\n"
            "    open(p, 'wb').write(b'x')\n"
            "    return time.time()\n"
        )
        base = [sys.executable, os.path.join(REPO, "tools", "dutlint.py"),
                "--root", str(tmp_path), "--json", "pkg/runtime/w.py"]
        both = json.loads(subprocess.run(
            base, capture_output=True, text=True, timeout=120,
        ).stdout)
        assert {f["rule"] for f in both["findings"]} == {
            "clock-discipline", "durability-protocol",
        }
        only = json.loads(subprocess.run(
            base + ["--rule", "durability-protocol"],
            capture_output=True, text=True, timeout=120,
        ).stdout)
        assert {f["rule"] for f in only["findings"]} == {
            "durability-protocol",
        }

    def test_model_checker_violation_exits_1_naming_rule_and_line(
        self, tmp_path
    ):
        # the new-pass CLI contract end-to-end: a protocol violation in
        # a throwaway corpus exits 1 and names rule + file:line
        states = tmp_path / "pkg" / "serve" / "states.py"
        states.parent.mkdir(parents=True)
        states.write_text(textwrap.dedent(STATES_OK))
        svc = tmp_path / "pkg" / "serve" / "svc.py"
        svc.write_text("def zap(entry):\n    entry['state'] = 'queued'\n")
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "dutlint.py"),
             "--root", str(tmp_path), "--rule", "state-machine",
             "pkg/serve/states.py", "pkg/serve/svc.py"],
            capture_output=True, text=True, timeout=120,
        )
        assert p.returncode == 1
        assert "pkg/serve/svc.py:2: [state-machine]" in p.stdout

    def test_unknown_rule_is_a_usage_error(self):
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "dutlint.py"),
             "--rule", "no-such-rule"],
            capture_output=True, text=True, timeout=120,
        )
        assert p.returncode == 2
        assert "unknown rule" in p.stderr

    def test_knob_taint_violation_exits_1_naming_rule_and_line(
        self, tmp_path
    ):
        # the acceptance case end-to-end: seeding a scheduling knob
        # into the fingerprint dict in a scratch corpus exits 1 and
        # names rule + file:line
        knobs_py = tmp_path / "pkg" / "runtime" / "knobs.py"
        knobs_py.parent.mkdir(parents=True)
        knobs_py.write_text(textwrap.dedent(KNOBS_TABLE_OK))
        stream = tmp_path / "pkg" / "runtime" / "stream.py"
        stream.write_text(textwrap.dedent("""
            def _fingerprint(path, capacity, drain_workers):
                return {
                    "path": path, "capacity": capacity,
                    "drain_workers": drain_workers,
                }
            """))
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "dutlint.py"),
             "--root", str(tmp_path), "--rule", "knob-taint",
             "pkg/runtime/knobs.py", "pkg/runtime/stream.py"],
            capture_output=True, text=True, timeout=120,
        )
        assert p.returncode == 1
        assert "[knob-taint]" in p.stdout
        assert "pkg/runtime/stream.py:" in p.stdout
        assert "drain_workers" in p.stdout

    def test_thread_confinement_violation_exits_1_naming_rule_and_line(
        self, tmp_path
    ):
        # the acceptance case end-to-end: a producer-thread durable
        # write in a scratch corpus exits 1 and names rule + file:line
        knobs_py = tmp_path / "pkg" / "runtime" / "knobs.py"
        knobs_py.parent.mkdir(parents=True)
        knobs_py.write_text(
            textwrap.dedent(TestThreadConfinement.KNOBS_ROLES)
        )
        stream = tmp_path / "pkg" / "runtime" / "stream.py"
        stream.write_text(textwrap.dedent("""
            def _stream_call(chunk_iter, ingest_q, ckpt):
                def _ingest_producer():
                    for k, item in enumerate(chunk_iter):
                        ckpt.mark(k)
                        ingest_q.put((k, item))
            """))
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "dutlint.py"),
             "--root", str(tmp_path), "--rule", "thread-confinement",
             "pkg/runtime/knobs.py", "pkg/runtime/stream.py"],
            capture_output=True, text=True, timeout=120,
        )
        assert p.returncode == 1
        assert "pkg/runtime/stream.py:5: [thread-confinement]" in p.stdout
        assert "'durable' grant" in p.stdout

    def _since_repo(self, tmp_path):
        """A throwaway git repo whose default lint set holds one file."""
        pkg = tmp_path / "duplexumiconsensusreads_tpu" / "runtime"
        pkg.mkdir(parents=True)
        hot = pkg / "hot.py"
        hot.write_text("def f():\n    return 0\n")
        env = {**os.environ,
               "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
               "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t"}
        for cmd in (["git", "init", "-q"],
                    ["git", "add", "-A"],
                    ["git", "commit", "-qm", "seed"]):
            subprocess.run(cmd, cwd=tmp_path, env=env, check=True,
                           capture_output=True, timeout=60)
        return hot

    def test_since_reports_only_changed_files(self, tmp_path):
        hot = self._since_repo(tmp_path)
        base = [sys.executable, os.path.join(REPO, "tools", "dutlint.py"),
                "--root", str(tmp_path)]
        # clean worktree vs HEAD: nothing to report, even though the
        # default-set run would flag nothing here anyway
        p = subprocess.run(base + ["--since", "HEAD"],
                           capture_output=True, text=True, timeout=120)
        assert p.returncode == 0, p.stdout + p.stderr
        # introduce a violation in the worktree: --since HEAD sees it
        hot.write_text("import time\ndef f():\n    return time.time()\n")
        p = subprocess.run(base + ["--since", "HEAD"],
                           capture_output=True, text=True, timeout=120)
        assert p.returncode == 1
        assert "hot.py:3: [clock-discipline]" in p.stdout

    def test_since_hides_findings_in_unchanged_files(self, tmp_path):
        # a COMMITTED violation with a clean worktree: the fast local
        # loop reports nothing (that dirt is CI's whole-tree job)
        hot = self._since_repo(tmp_path)
        env = {**os.environ,
               "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
               "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t"}
        hot.write_text("import time\ndef f():\n    return time.time()\n")
        for cmd in (["git", "add", "-A"],
                    ["git", "commit", "-qm", "dirty"]):
            subprocess.run(cmd, cwd=tmp_path, env=env, check=True,
                           capture_output=True, timeout=60)
        base = [sys.executable, os.path.join(REPO, "tools", "dutlint.py"),
                "--root", str(tmp_path)]
        p = subprocess.run(base + ["--since", "HEAD"],
                           capture_output=True, text=True, timeout=120)
        assert p.returncode == 0, p.stdout + p.stderr
        # ... while the full default-set run still fails
        p = subprocess.run(base, capture_output=True, text=True,
                           timeout=120)
        assert p.returncode == 1

    def test_since_usage_errors(self, tmp_path):
        self._since_repo(tmp_path)
        base = [sys.executable, os.path.join(REPO, "tools", "dutlint.py"),
                "--root", str(tmp_path)]
        bad_rev = subprocess.run(
            base + ["--since", "no-such-rev"],
            capture_output=True, text=True, timeout=120,
        )
        assert bad_rev.returncode == 2
        assert "not a resolvable git rev" in bad_rev.stderr
        both = subprocess.run(
            base + ["--since", "HEAD",
                    "duplexumiconsensusreads_tpu/runtime/hot.py"],
            capture_output=True, text=True, timeout=120,
        )
        assert both.returncode == 2
        assert "mutually exclusive" in both.stderr

    def test_strict_fails_on_stale_allowlist_entries(self, tmp_path):
        # an empty root's default set suppresses nothing, so every real
        # allowlist entry is stale there: --strict turns the warning
        # into exit 1 (the ci_check gate), the default stays advisory
        args = [sys.executable, os.path.join(REPO, "tools", "dutlint.py"),
                "--root", str(tmp_path)]
        lax = subprocess.run(
            args, capture_output=True, text=True, timeout=120,
        )
        assert lax.returncode == 0
        assert "warning: unused allowlist entry" in lax.stderr
        strict = subprocess.run(
            args + ["--strict"], capture_output=True, text=True, timeout=120,
        )
        assert strict.returncode == 1
        assert "error: unused allowlist entry" in strict.stderr


# ---------------------------------------------------------- AST cache

class TestAstCache:
    """Satellite: each corpus file parses ONCE per process (engine
    _AST_CACHE keyed by path+mtime+size) — the lint suite and the CLI
    load the same ~95-file corpus many times, and 16 rules never
    re-parse at all (they share Corpus.trees)."""

    def test_reloading_the_corpus_reparses_nothing(self):
        from duplexumiconsensusreads_tpu.analysis.engine import (
            CACHE_STATS, load_corpus,
        )

        rels = default_targets(REPO)
        c1 = load_corpus(REPO, rels)  # warm (may hit or miss)
        misses0 = CACHE_STATS["misses"]
        hits0 = CACHE_STATS["hits"]
        c2 = load_corpus(REPO, rels)
        assert CACHE_STATS["misses"] == misses0  # zero new parses
        assert CACHE_STATS["hits"] >= hits0 + len(c2.trees)
        # the cached trees are SHARED objects, not re-parses
        for p in list(c1.trees)[:5]:
            assert c2.trees[p] is c1.trees[p]

    def test_lint_suite_runtime_budget(self):
        from duplexumiconsensusreads_tpu.analysis.engine import load_corpus
        import time

        rels = default_targets(REPO)
        load_corpus(REPO, rels)  # warm the cache
        t0 = time.monotonic()
        for _ in range(3):
            corpus = load_corpus(REPO, rels)
            run_lint(corpus, ALLOWLIST)
        dt = time.monotonic() - t0
        # generous even for a loaded CI box: 3 full 16-rule passes over
        # the whole corpus without the cache would re-parse ~285 files
        assert dt < 30.0, f"3 warm lint passes took {dt:.1f}s"


# ------------------------------------------------------------ CI gate script

class TestCiCheck:
    """tools/ci_check.sh is the one-command commit gate (dutlint
    --strict + check_trace --require-summary on the committed fixture
    capture); running it here is what keeps it from rotting."""

    def test_ci_check_passes_on_the_shipped_tree(self):
        p = subprocess.run(
            ["sh", os.path.join(REPO, "tools", "ci_check.sh")],
            capture_output=True, text=True, timeout=300,
            # the gate must lint under THIS suite's interpreter, not
            # whatever `python` resolves to on PATH
            env={**os.environ, "PYTHON": sys.executable},
        )
        assert p.returncode == 0, p.stdout + p.stderr
        assert "[ci_check] OK" in p.stderr

    def test_readme_rule_table_matches_registry(self):
        # the drift the gate's counting leg catches, pinned by NAME
        # here: the documented table is exactly the registered rules
        readme = open(os.path.join(REPO, "README.md")).read()
        block = readme.split("<!-- dutlint-rule-table -->")[1].split(
            "<!-- /dutlint-rule-table -->"
        )[0]
        rows = [ln for ln in block.splitlines() if ln.startswith("| `")]
        names = {ln.split("`")[1] for ln in rows}
        assert names == set(RULES)

    def test_fixture_capture_is_complete_and_pinned(self, tmp_path):
        # the committed capture must carry its terminal summary — and
        # the validator must still FAIL a summary-less (crashed-run)
        # capture, or the --require-summary leg means nothing
        fixture = os.path.join(REPO, "tests", "data",
                               "run.fixture.trace.jsonl")
        lines = open(fixture).read().splitlines()
        assert '"type":"summary"' in lines[-1]
        torn = tmp_path / "torn.trace.jsonl"
        torn.write_text("\n".join(lines[:-1]) + "\n")
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "check_trace.py"),
             str(torn), "--require-summary"],
            capture_output=True, text=True, timeout=120,
        )
        assert p.returncode == 1
        assert "summary" in p.stderr


# ------------------------------------------------------------ tier-1 gate

class TestShippedTree:
    """The actual CI gate: the engine in-process over the default file
    set (package + tools/ + test anchors)."""

    def test_tree_lints_clean_modulo_allowlist(self):
        from duplexumiconsensusreads_tpu.analysis.engine import load_corpus

        corpus = load_corpus(REPO, default_targets(REPO))
        res = run_lint(corpus, ALLOWLIST)
        assert res.ok, "\n".join(f.format() for f in res.findings)
        # the allowlist cannot rot: every entry must still suppress
        # something, or this gate forces it to be pruned
        assert res.unused_allowlist == [], [
            (a.rule, a.path) for a in res.unused_allowlist
        ]

    def test_linted_set_covers_the_contract_files(self):
        targets = set(default_targets(REPO))
        for must in (
            "tools/dutlint.py", "tools/check_trace.py",
            "tools/trace_report.py", "tools/serve_report.py",
            # the byte-ledger / bench-trajectory tools carry the same
            # schema obligations as the trace tools they sit beside
            "tools/wirestat.py", "tools/bench_history.py",
            # the fleet flight recorder: its CLI carries the same
            # schema/sum-check obligations as wirestat/trace_report
            "tools/fleet_report.py",
            # the device ledger's CLI twin of wirestat
            "tools/devstat.py",
            # the profiling/tuning tools carry the same clock +
            # durability obligations as the report tools; anchoring
            # them here means clock/durability drift in any tool is
            # gate-visible, not just in check_trace/trace_report
            "tools/profile_components.py", "tools/profile_phases.py",
            "tools/tune_ssc.py",
            "tests/test_chaos.py", "tests/test_telemetry.py",
            # the serving suite anchors the lease-discipline rule's
            # serve.*-site coverage check
            "tests/test_serve.py",
            # the byte-identity matrix anchoring knob-taint's coverage
            # leg (SCHEDULING_MATRIX)
            "tests/test_knobs.py",
            # the knob/thread registries both new rules read
            os.path.join("duplexumiconsensusreads_tpu", "runtime",
                         "knobs.py"),
            os.path.join("duplexumiconsensusreads_tpu", "runtime",
                         "stream.py"),
            os.path.join("duplexumiconsensusreads_tpu", "serve",
                         "queue.py"),
            os.path.join("duplexumiconsensusreads_tpu", "serve",
                         "service.py"),
            # the declared state machine the model-checker rules anchor
            os.path.join("duplexumiconsensusreads_tpu", "serve",
                         "states.py"),
        ):
            assert must.replace("/", os.sep) in {
                t.replace("/", os.sep) for t in targets
            }, must
