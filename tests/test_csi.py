"""CSI index: structure, long-contig support past BAI's 2^29 limit,
and query parity with both BAI and brute force (VERDICT r4 missing #4:
"CSI index / long-contig support").
"""

import os
import struct

import numpy as np
import pytest

from duplexumiconsensusreads_tpu.cli import main
from duplexumiconsensusreads_tpu.io import read_bam
from duplexumiconsensusreads_tpu.io.bam import BamHeader, BamRecords, write_bam
from duplexumiconsensusreads_tpu.io.bai import build_bai
from duplexumiconsensusreads_tpu.io.csi import (
    CSI_MAGIC,
    build_csi,
    depth_for,
    query_start_voffset_csi,
    read_csi,
    reg2bin_vec,
    reg2bins,
)


def _sorted_bam(path, positions, ref_len=10_000_000, L=50, ref="chr1"):
    n = len(positions)
    rng = np.random.default_rng(1)
    recs = BamRecords(
        names=[f"r{i}" for i in range(n)],
        flags=np.zeros(n, np.uint16),
        ref_id=np.zeros(n, np.int32),
        pos=np.asarray(sorted(positions), np.int32),
        mapq=np.full(n, 60, np.uint8),
        next_ref_id=np.full(n, -1, np.int32),
        next_pos=np.full(n, -1, np.int32),
        tlen=np.zeros(n, np.int32),
        lengths=np.full(n, L, np.int32),
        seq=rng.integers(0, 4, (n, L)).astype(np.uint8),
        qual=np.full((n, L), 30, np.uint8),
        cigars=[[(L, "M")]] * n,
        umi=["ACGT"] * n,
        aux_raw=[b"RXZACGT\x00"] * n,
    )
    write_bam(
        path,
        BamHeader.synthetic(
            ref_names=(ref,), ref_lengths=(ref_len,),
            sort_order="coordinate",
        ),
        recs,
    )
    return recs


def test_reg2bin_matches_bai_scheme():
    """At min_shift=14 / depth=5 the generalized binning must equal the
    BAI-fixed one for every coordinate in BAI's address space."""
    from duplexumiconsensusreads_tpu.io.bam import _reg2bin_vec

    rng = np.random.default_rng(3)
    begs = rng.integers(0, (1 << 29) - 200, 2000)
    ends = begs + rng.integers(1, 200, 2000)
    np.testing.assert_array_equal(
        reg2bin_vec(begs, ends, 14, 5), _reg2bin_vec(begs, ends)
    )
    # and the query-side dual covers the bin of every interval
    for beg, end in zip(begs[:50].tolist(), ends[:50].tolist()):
        b = int(reg2bin_vec(np.r_[beg], np.r_[end], 14, 5)[0])
        assert b in reg2bins(beg, end, 14, 5)


def test_depth_sizing():
    assert depth_for(1 << 29) == 5
    assert depth_for((1 << 29) + 1) == 6
    assert depth_for(1 << 32) == 6
    assert depth_for((1 << 32) + 1) == 7


def test_csi_structure_roundtrip(tmp_path):
    bam = str(tmp_path / "s.bam")
    _sorted_bam(bam, list(range(1000, 90_000, 700)))
    out = build_csi(bam)
    assert out == bam + ".csi"
    with open(out, "rb") as f:
        assert f.read(4) == CSI_MAGIC
    idx = read_csi(out)
    assert idx["min_shift"] == 14 and idx["depth"] == 5
    assert idx["n_ref"] == 1
    ref = idx["refs"][0]
    assert ref["bins"], "no bins accumulated"
    n = len(range(1000, 90_000, 700))
    assert ref["meta"][2] == n and ref["meta"][3] == 0
    # every bin carries a loffset no later than its first chunk begin
    for b, chunks in ref["bins"].items():
        assert ref["loffsets"][b] <= chunks[0][0]


def test_csi_query_matches_bai(tmp_path):
    """Same BAM, both indexes: every region's query start must yield
    the same complete record set (scan-from-voffset semantics are
    shared, so comparing start offsets' completeness via the view
    CLI is the strongest check)."""
    bam = str(tmp_path / "q.bam")
    recs = _sorted_bam(bam, list(range(500, 200_000, 137)))
    build_bai(bam)
    build_csi(bam)
    from duplexumiconsensusreads_tpu.io.bai import (
        query_start_voffset,
        read_bai,
    )

    bai = read_bai(bam + ".bai")
    csi = read_csi(bam + ".csi")
    rng = np.random.default_rng(7)
    for _ in range(40):
        beg = int(rng.integers(0, 200_000))
        end = beg + int(rng.integers(1, 5000))
        vb = query_start_voffset(bai, 0, beg, end)
        vc = query_start_voffset_csi(csi, 0, beg, end)
        # both must start at or before the first overlapping record;
        # identical binning (depth 5) should give identical answers
        assert vb == vc, (beg, end, vb, vc)


def test_long_contig_needs_csi(tmp_path):
    """A 1.2 Gbp contig: BAI refuses loudly, CSI (depth 6) indexes it,
    and a region query at 1.1 Gbp returns exactly the brute-force
    record set through the view CLI."""
    bam = str(tmp_path / "long.bam")
    ref_len = 1_200_000_000
    positions = [5_000 + i * 9_000_037 for i in range(130)]  # spans ~1.17G
    _sorted_bam(bam, positions, ref_len=ref_len)
    with pytest.raises(ValueError, match="CSI"):
        build_bai(bam)
    out = build_csi(bam)
    idx = read_csi(out)
    assert idx["depth"] == 6
    # pick a window around a known record past 2^29
    target = [p for p in positions if p > (1 << 29)][3]
    beg1, end1 = target + 1, target + 40  # 1-based inclusive region
    outbam = str(tmp_path / "hit.bam")
    assert main([
        "view", bam, f"chr1:{beg1}-{end1}", "-o", outbam,
    ]) == 0
    _, got = read_bam(outbam)
    want = [p for p in positions if p < end1 and p + 50 > beg1 - 1]
    assert sorted(np.asarray(got.pos).tolist()) == sorted(want)
    # empty region past every record
    outbam2 = str(tmp_path / "none.bam")
    assert main([
        "view", bam, f"chr1:{ref_len - 100}-{ref_len}", "-o", outbam2,
    ]) == 0
    _, got2 = read_bam(outbam2)
    assert len(got2) == 0


def test_record_bin_zero_past_bai_domain(tmp_path):
    """Records whose span touches coords > 2^29 must carry bin=0 (the
    BAI formula is undefined there and yields invalid-but-u16-fitting
    values like 41305 at 600 Mbp that strict validators flag); records
    inside the domain keep the real reg2bin."""
    from duplexumiconsensusreads_tpu.io.bam import _reg2bin
    from duplexumiconsensusreads_tpu.runtime.stream import BamStreamReader

    bam = str(tmp_path / "b.bam")
    inside, outside = 1000, 600_000_000
    _sorted_bam(bam, [inside, outside], ref_len=1_200_000_000)
    rdr = BamStreamReader(bam)
    try:
        raw = rdr.read_raw_records(16)
    finally:
        rdr.close()
    from duplexumiconsensusreads_tpu.io.index import _record_offsets

    offs = _record_offsets(raw)
    assert len(offs) == 2
    bins = [
        struct.unpack_from("<H", raw, int(o) + 14)[0] for o in offs
    ]
    assert bins[0] == _reg2bin(inside, inside + 50)
    assert bins[1] == 0


def test_view_prefers_existing_csi(tmp_path, capsys):
    """view consumes an existing .csi when no .bai is present (no
    silent rebuild)."""
    bam = str(tmp_path / "v.bam")
    _sorted_bam(bam, list(range(100, 50_000, 911)))
    build_csi(bam)
    assert not os.path.exists(bam + ".bai")
    outbam = str(tmp_path / "o.bam")
    assert main(["view", bam, "chr1:1000-2000", "-o", outbam]) == 0
    assert not os.path.exists(bam + ".bai"), "view rebuilt a BAI needlessly"
    _, got = read_bam(outbam)
    want = [p for p in range(100, 50_000, 911) if p < 2000 and p + 50 > 999]
    assert sorted(np.asarray(got.pos).tolist()) == sorted(want)


def test_index_csi_cli(tmp_path, capsys):
    bam = str(tmp_path / "c.bam")
    _sorted_bam(bam, [10, 500, 900])
    assert main(["index", bam, "--csi"]) == 0
    assert os.path.exists(bam + ".csi")
    idx = read_csi(bam + ".csi")
    assert idx["refs"][0]["meta"][2] == 3


def test_write_index_auto_csi(tmp_path):
    """call --write-index on input whose header contig exceeds 2^29
    writes a .csi (the executor's auto-pick), and the output index
    parses."""
    from duplexumiconsensusreads_tpu.io.convert import simulated_bam
    from duplexumiconsensusreads_tpu.simulate import SimConfig

    bam = str(tmp_path / "in.bam")
    header, recs, _b, _t = simulated_bam(
        SimConfig(n_molecules=20, duplex=False, seed=5), sort=True
    )
    # rewrite with a jumbo contig header (positions stay small — the
    # pick is header-driven, which is the contract)
    write_bam(
        bam,
        BamHeader.synthetic(
            ref_names=tuple(header.ref_names),
            ref_lengths=tuple((1 << 29) + 1 for _ in header.ref_names),
            sort_order="coordinate",
        ),
        recs,
    )
    out = str(tmp_path / "cons.bam")
    assert main([
        "call", bam, "-o", out, "--mode", "ss", "--grouping", "exact",
        "--capacity", "256", "--backend", "cpu", "--write-index",
    ]) == 0
    assert os.path.exists(out + ".csi")
    assert not os.path.exists(out + ".bai")
    idx = read_csi(out + ".csi")
    assert idx["n_ref"] == len(header.ref_names)


@pytest.mark.parametrize("fmt", ["bai", "csi"])
def test_truncated_index_fails_loudly(tmp_path, fmt):
    """A truncated index must raise a ValueError naming the file, never
    leak a bare struct.error (the repo-wide truncation discipline)."""
    from duplexumiconsensusreads_tpu.io.bai import read_bai

    bam = str(tmp_path / "t.bam")
    _sorted_bam(bam, [100, 500, 900, 40_000])
    path = build_bai(bam) if fmt == "bai" else build_csi(bam)
    data = open(path, "rb").read()
    for cut in (10, len(data) // 2):
        trunc = str(tmp_path / f"x{cut}.{fmt}")
        with open(trunc, "wb") as f:
            f.write(data[:cut])
        with pytest.raises(ValueError, match="truncated or corrupt"):
            (read_bai if fmt == "bai" else read_csi)(trunc)


@pytest.mark.parametrize("n_chunk", [0, 1, 3])
def test_metadata_pseudo_bin_chunk_count_validated(tmp_path, n_chunk):
    """A metadata pseudo-bin with n_chunk != 2 must raise the loud
    ValueError-with-path, not escape as a bare IndexError (n_chunk < 2)
    or silently misparse (n_chunk > 2) — ADVICE r5."""
    from duplexumiconsensusreads_tpu.io.bai import METADATA_BIN, read_bai
    from duplexumiconsensusreads_tpu.io.csi import _n_bins

    chunks = struct.pack("<QQ", 0, 0) * n_chunk
    bai = (
        b"BAI\x01" + struct.pack("<i", 1)  # magic, n_ref
        + struct.pack("<i", 1)  # n_bin
        + struct.pack("<Ii", METADATA_BIN, n_chunk) + chunks
        + struct.pack("<i", 0)  # n_intv
        + struct.pack("<Q", 0)  # n_no_coor
    )
    p = tmp_path / "meta.bai"
    p.write_bytes(bai)
    with pytest.raises(ValueError, match=r"meta\.bai.*pseudo-bin"):
        read_bai(str(p))

    meta_bin = _n_bins(5) + 1
    csi = (
        CSI_MAGIC + struct.pack("<iii", 14, 5, 0)  # min_shift, depth, l_aux
        + struct.pack("<i", 1)  # n_ref
        + struct.pack("<i", 1)  # n_bin
        + struct.pack("<IQi", meta_bin, 0, n_chunk) + chunks
        + struct.pack("<Q", 0)  # n_no_coor
    )
    p2 = tmp_path / "meta.csi"
    p2.write_bytes(csi)
    with pytest.raises(ValueError, match=r"meta\.csi.*pseudo-bin"):
        read_csi(str(p2))
