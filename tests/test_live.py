"""Live follow-mode suite (live/): the streaming executor as a
follower of a growing BAM.

The load-bearing contract is the A/B byte-identity matrix
(``TestFollowByteIdentity``): a follow run — over a finished file, a
file that grows while we read it, or a FIFO, at every ``finalize_on``
mode — must produce output (BAI included) byte-identical to the plain
batch run over the same final bytes. That is what makes every live
knob scheduling-class: they steer WHEN input bytes become visible,
never what is computed from them. ``SCHEDULING_MATRIX`` in
tests/test_knobs.py points dutlint's knob-taint coverage leg here.

The other pillars: every published partial snapshot is a valid,
indexed BAM prefix of the final output; a kill mid-tail resumes
exactly-once through the durable admission watermark; a truncated
input at a non-EOF finalisation refuses loudly instead of silently
dropping the torn trailing block.
"""

import os
import shutil
import threading
import time

import pytest

from duplexumiconsensusreads_tpu.io import read_bam, simulated_bam
from duplexumiconsensusreads_tpu.io.bam import parse_bam
from duplexumiconsensusreads_tpu.live import (
    TailSource,
    parse_finalize_on,
    watermark,
)
from duplexumiconsensusreads_tpu.runtime import faults
from duplexumiconsensusreads_tpu.runtime.stream import stream_call_consensus
from duplexumiconsensusreads_tpu.simulate import SimConfig
from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams

GP = GroupingParams(strategy="adjacency", paired=True)
CP = ConsensusParams(mode="duplex")
# write_index=True throughout: the A/B contract includes the BAI bytes
KW = dict(capacity=128, chunk_reads=80, write_index=True)


@pytest.fixture(scope="module")
def sim(tmp_path_factory):
    """(input path, reference output bytes, reference BAI bytes) from a
    plain batch run — the oracle every follow run must reproduce."""
    d = tmp_path_factory.mktemp("live")
    path = str(d / "in.bam")
    cfg = SimConfig(n_molecules=60, n_positions=8, umi_error=0.02, seed=37)
    simulated_bam(cfg, path=path, sort=True)
    ref = str(d / "ref.bam")
    rep = stream_call_consensus(path, ref, GP, CP, **KW)
    assert rep.n_chunks >= 3  # several commit points for snapshots/kills
    with open(ref, "rb") as f:
        ref_bytes = f.read()
    with open(ref + ".bai", "rb") as f:
        ref_bai = f.read()
    return path, ref_bytes, ref_bai


def _follow(path, out, **kw):
    merged = {**KW, "follow": True, "live_poll_s": 0.01, **kw}
    return stream_call_consensus(path, out, GP, CP, **merged)


def _out_files(out):
    with open(out, "rb") as f:
        b = f.read()
    with open(out + ".bai", "rb") as f:
        bai = f.read()
    return b, bai


def _assert_no_live_residue(out):
    # a successful follow run finishes as a plain batch output: no
    # watermark, no snapshot side artifacts, no checkpoint
    for suffix in (".livemark", ".snapshot.bam", ".snapshot.bam.bai",
                   ".snapshot.bam.csi", ".ckpt"):
        assert not os.path.exists(out + suffix), out + suffix


class TestFollowByteIdentity:
    """The A/B matrix: follow output == batch output, bytes and BAI,
    at every finalize_on mode and input arrival shape."""

    def test_eof_mode_over_finished_file(self, sim, tmp_path):
        path, ref_bytes, ref_bai = sim
        out = str(tmp_path / "f.bam")
        rep = _follow(path, out)  # finalize_on default: "eof"
        assert _out_files(out) == (ref_bytes, ref_bai)
        assert rep.snapshot_seq == 0  # no snapshots unless asked
        _assert_no_live_residue(out)

    def test_idle_mode(self, sim, tmp_path):
        path, ref_bytes, ref_bai = sim
        out = str(tmp_path / "f.bam")
        _follow(path, out, finalize_on="idle:0.3")
        assert _out_files(out) == (ref_bytes, ref_bai)
        _assert_no_live_residue(out)

    def test_marker_mode(self, sim, tmp_path):
        path, ref_bytes, ref_bai = sim
        inp = str(tmp_path / "in.bam")
        shutil.copy(path, inp)
        with open(inp + ".done", "w") as f:
            f.write("done\n")
        out = str(tmp_path / "f.bam")
        _follow(inp, out, finalize_on="marker")
        assert _out_files(out) == (ref_bytes, ref_bai)
        _assert_no_live_residue(out)

    def test_growing_file_converges(self, sim, tmp_path):
        """The real case: a writer appends in arbitrary slabs (torn
        mid-block on purpose) while the follower runs; the follower's
        output must still match the batch run over the final bytes."""
        path, ref_bytes, ref_bai = sim
        with open(path, "rb") as f:
            raw = f.read()
        inp = str(tmp_path / "growing.bam")
        slab = max(1, len(raw) // 23)  # prime-ish slab: tears blocks

        def writer():
            with open(inp, "wb") as f:
                for off in range(0, len(raw), slab):
                    f.write(raw[off:off + slab])
                    f.flush()
                    time.sleep(0.01)

        with open(inp, "wb"):
            pass  # the follower may open before the writer's first slab
        t = threading.Thread(target=writer)
        t.start()
        try:
            out = str(tmp_path / "f.bam")
            _follow(inp, out)
        finally:
            t.join()
        assert _out_files(out) == (ref_bytes, ref_bai)
        _assert_no_live_residue(out)

    def test_fifo_input(self, sim, tmp_path):
        """A pipe has no size, no mtime and no second read — the
        harshest arrival shape, and exactly what `sequencer | duplexumi
        call --follow` is."""
        path, ref_bytes, ref_bai = sim
        with open(path, "rb") as f:
            raw = f.read()
        fifo = str(tmp_path / "in.fifo")
        os.mkfifo(fifo)

        def writer():
            with open(fifo, "wb") as f:
                f.write(raw)

        t = threading.Thread(target=writer)
        t.start()
        try:
            out = str(tmp_path / "f.bam")
            _follow(fifo, out)
        finally:
            t.join()
        assert _out_files(out) == (ref_bytes, ref_bai)
        _assert_no_live_residue(out)


def test_snapshot_chunks_ab_byte_identical(sim, tmp_path):
    """snapshot_chunks is scheduling-class: publishing partial
    snapshots must not change a single byte of the final output."""
    path, ref_bytes, ref_bai = sim
    out = str(tmp_path / "f.bam")
    rep = _follow(path, out, snapshot_chunks=1)
    assert rep.snapshot_seq == rep.n_chunks  # one publish per commit
    assert _out_files(out) == (ref_bytes, ref_bai)
    _assert_no_live_residue(out)


def test_every_snapshot_is_a_valid_indexed_bam_prefix(sim, tmp_path):
    """Captured at each commit (the progress callback runs right after
    the publish): every snapshot parses as a complete BAM, carries its
    own index, and its compressed payload is a byte prefix of the
    final output."""
    path, _, _ = sim
    out = str(tmp_path / "f.bam")
    snap_path = out + ".snapshot.bam"
    seen = []

    def progress(_k, _rep):
        with open(snap_path, "rb") as f:
            snap = f.read()
        with open(snap_path + ".bai", "rb") as f:
            bai = f.read()
        seen.append((snap, bai, _rep.snapshot_seq))

    rep = stream_call_consensus(
        path, out, GP, CP, follow=True, live_poll_s=0.01,
        snapshot_chunks=1, progress=progress, **KW
    )
    assert len(seen) == rep.n_chunks >= 3
    final_bytes, _ = _out_files(out)
    n_final = len(read_bam(out)[1].names)
    prev_reads = -1
    for i, (snap, bai, seq) in enumerate(seen):
        assert seq == i + 1  # the published series is dense
        assert bai.startswith(b"BAI\1") and len(bai) > 8
        # the snapshot is literally a committed prefix of the final
        # file: same bytes up to its own EOF block
        assert snap[:-28] == final_bytes[:len(snap) - 28]
        header, recs = parse_bam(snap)  # parses as a complete BAM
        assert prev_reads < len(recs.names) <= n_final
        prev_reads = len(recs.names)
    assert prev_reads == n_final  # the last snapshot is the whole run
    _assert_no_live_residue(out)


def test_kill_mid_tail_then_resume_exactly_once(sim, tmp_path):
    """SIGKILL-equivalent (InjectedKill) while the tailer polls: the
    admission watermark pins the run identity, so resume=True accepts
    its own checkpoint over the 'growing' input and converges to the
    batch bytes — snapshot series continuing, not restarting."""
    path, ref_bytes, ref_bai = sim
    with open(path, "rb") as f:
        raw = f.read()
    inp = str(tmp_path / "growing.bam")
    slab = max(1, len(raw) // 23)

    def writer():
        with open(inp, "wb") as f:
            for off in range(0, len(raw), slab):
                f.write(raw[off:off + slab])
                f.flush()
                time.sleep(0.02)

    with open(inp, "wb"):
        pass
    out = str(tmp_path / "k.bam")
    t = threading.Thread(target=writer)
    t.start()
    faults.install(faults.FaultPlan.parse("live.poll:4:kill"))
    try:
        with pytest.raises(faults.InjectedKill):
            _follow(inp, out, snapshot_chunks=1)
    finally:
        faults.uninstall()
        t.join()  # the writer finishes the input regardless of our death
    assert not os.path.exists(out)  # atomic finalise held
    mark = watermark.load(out)
    assert mark is not None  # the durable identity survived the kill
    pre_seq = int(mark["snapshot_seq"])
    rep = _follow(inp, out, snapshot_chunks=1, resume=True)
    assert rep.snapshot_seq >= max(pre_seq, 1)  # monotone across the kill
    assert _out_files(out) == (ref_bytes, ref_bai)
    _assert_no_live_residue(out)


def test_truncated_input_refuses_loudly(sim, tmp_path):
    """A non-EOF finalisation reached with a torn trailing block means
    the writer died mid-record: the run must fail naming the
    truncation, never publish an output silently missing reads."""
    path, _, _ = sim
    with open(path, "rb") as f:
        raw = f.read()
    inp = str(tmp_path / "torn.bam")
    with open(inp, "wb") as f:
        f.write(raw[:-40])  # tears the trailing EOF block
    out = str(tmp_path / "f.bam")
    with pytest.raises(ValueError, match="truncated trailing BGZF block"):
        _follow(inp, out, finalize_on="idle:0.2")
    assert not os.path.exists(out)


class TestTailSource:
    def test_parse_finalize_on(self):
        assert parse_finalize_on("eof") == ("eof", None)
        assert parse_finalize_on("marker") == ("marker", None)
        assert parse_finalize_on("idle:2.5") == ("idle", 2.5)
        for bad in ("idle:0", "idle:-1", "idle:", "idle:x", "never", ""):
            with pytest.raises(ValueError):
                parse_finalize_on(bad)

    def test_reads_complete_blocks_and_finishes_on_eof(self, sim):
        path, _, _ = sim
        with open(path, "rb") as f:
            raw = f.read()
        src = TailSource(path, poll_s=0.01)
        try:
            got = b""
            while True:
                b = src.read(1 << 16)
                if not b:
                    break
                got += b
            assert got == raw
            assert src.finish_reason == "eof"
            assert src.tell() == len(raw) == src.admitted_bytes()
        finally:
            src.close()

    def test_forward_only_seek(self, sim):
        path, _, _ = sim
        src = TailSource(path, poll_s=0.01)
        try:
            first = src.read(1 << 14)
            assert src.seek(len(first)) == len(first)  # current pos: ok
            with pytest.raises(ValueError, match="forward-only"):
                src.seek(0)
        finally:
            src.close()

    def test_phase_seconds_drain(self, sim, tmp_path):
        """take_phase_seconds is a drain: accumulated poll/wait time is
        handed over once, then starts from zero (the executor folds it
        into the live_poll/live_wait phase ledger at chunk boundaries)."""
        path, _, _ = sim
        inp = str(tmp_path / "slow.bam")
        with open(inp, "wb"):
            pass  # empty: the tailer can only poll and the reader wait
        src = TailSource(inp, poll_s=0.01, finalize_on="idle:10")
        try:
            time.sleep(0.15)  # the tailer can only idle-poll
            poll_s, _ = src.take_phase_seconds()
            assert poll_s > 0  # the tailer really idled
            again, _ = src.take_phase_seconds()
            assert again < poll_s  # drained: the clock restarted
        finally:
            src.close()


class TestWatermark:
    def test_reuse_and_head_invalidation(self, sim, tmp_path):
        path, _, _ = sim
        out = str(tmp_path / "o.bam")
        m1 = watermark.load_or_create(out, path)
        m2 = watermark.load_or_create(out, path)
        assert m1["stat_sig"] == m2["stat_sig"]  # same run resumes itself
        # resume=False always re-pins
        m3 = watermark.load_or_create(out, path, resume=False)
        assert m3["stat_sig"] != m1["stat_sig"]
        # a rewritten head is a different run: the mark is discarded
        # (work on a copy — the shared sim input must stay intact)
        inp = str(tmp_path / "in.bam")
        shutil.copy(path, inp)
        ma = watermark.load_or_create(out, inp, resume=False)
        with open(inp, "r+b") as f:
            f.write(b"XXXX")
        mb = watermark.load_or_create(out, inp)
        assert mb["stat_sig"] != ma["stat_sig"]
        watermark.clear(out)
        assert watermark.load(out) is None

    def test_fifo_resume_refused(self, tmp_path):
        fifo = str(tmp_path / "p.fifo")
        os.mkfifo(fifo)
        out = str(tmp_path / "o.bam")
        watermark.load_or_create(out, fifo)  # fresh: fine
        with pytest.raises(ValueError, match="FIFO"):
            watermark.load_or_create(out, fifo)  # the bytes are gone


def test_status_document_passes_live_counters():
    """call --status/--wait --json: the journal's live counters (stamped
    through the fenced per-chunk renewal) reach the normalized document."""
    from duplexumiconsensusreads_tpu.serve.client import status_document

    doc = status_document({
        "job_id": "j", "state": "running",
        "snapshot_seq": 3, "reads_emitted": 120,
    })
    assert doc["snapshot_seq"] == 3
    assert doc["reads_emitted"] == 120
