"""Driver entry point: delegates to the installable benchmark module.

Prints ONE JSON line (see duplexumiconsensusreads_tpu/benchmark.py for
the metric definition and env knobs).
"""

import sys

sys.path.insert(0, __file__.rsplit("/", 1)[0])

from duplexumiconsensusreads_tpu.benchmark import main

if __name__ == "__main__":
    main()
