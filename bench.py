"""Driver entry point: delegates to the installable benchmark module.

Prints ONE JSON line (see duplexumiconsensusreads_tpu/benchmark.py for
the metric definition and env knobs). The human journal on stderr now
includes the canonical e2e capture's busy-vs-wall table, and the JSON
carries per-chunk latency percentiles reconstructed from the e2e span
capture (left in the bench cache for tools/trace_report.py).
"""

import sys

sys.path.insert(0, __file__.rsplit("/", 1)[0])

from duplexumiconsensusreads_tpu.benchmark import main

if __name__ == "__main__":
    main()
