"""Chrome trace-event exporter: open a capture in Perfetto.

Maps a JSONL capture (telemetry/trace.py) onto the Trace Event Format
consumed by https://ui.perfetto.dev and chrome://tracing — spans become
complete ('X') slices, point events become instants ('i'), byte-ledger
xfer records become counter ('C') tracks of bytes-in-flight per lane,
device-ledger dev records become FLOP/s counter tracks (the roofline's
numerator, live under the timeline), and each LANE becomes one named
pseudo-thread so the main loop,
transfer workers, and every drain worker render as parallel tracks. That
side-by-side rendering is the whole point: overlap that hides the
critical path in aggregate numbers is visible at a glance.

Timestamps: trace seconds (monotonic-relative) -> microseconds, the
unit the format requires.
"""

from __future__ import annotations

import json
import os

# one synthetic process for the whole capture
_PID = 1


def _lane_order(lane: str) -> tuple:
    """Stable track order: main first, then xfer, then drain, then any
    stray lanes, each numerically within its pool."""
    for rank, prefix in ((0, "main"), (1, "xfer-"), (2, "drain-")):
        if lane == prefix or lane.startswith(prefix):
            tail = lane[len(prefix):]
            return (rank, int(tail) if tail.isdigit() else 0, lane)
    return (3, 0, lane)


def to_chrome(records) -> dict:
    """Convert parsed capture records to a Chrome trace-event dict.

    ``records`` is any iterable of the dicts a JSONL capture holds
    (``telemetry.report.load_trace`` output). Returns the JSON-object
    form ({"traceEvents": [...]}), which Perfetto accepts directly.
    """
    spans, instants, xfers, devs, lanes = [], [], [], [], set()
    for rec in records:
        kind = rec.get("type")
        if kind not in ("span", "event", "xfer", "dev"):
            continue
        lane = rec.get("lane", "?")
        lanes.add(lane)
        # "dur" maps onto the X-event field for spans only; on point
        # events (e.g. durable_write's fsync cost) it is a payload
        # attribute and must survive into args
        drop = ("type", "stage", "name", "t", "lane")
        drop += ("dur",) if kind == "span" else ()
        args = {k: v for k, v in rec.items() if k not in drop}
        if kind == "span":
            spans.append((rec, lane, args))
        elif kind == "xfer":
            xfers.append((rec, lane))
        elif kind == "dev":
            devs.append((rec, lane))
        else:
            instants.append((rec, lane, args))

    tid = {
        lane: i + 1 for i, lane in enumerate(sorted(lanes, key=_lane_order))
    }
    events = [
        {
            "name": "process_name", "ph": "M", "pid": _PID, "tid": 0,
            "args": {"name": "duplexumi streaming executor"},
        }
    ]
    for lane, t in tid.items():
        events.append({
            "name": "thread_name", "ph": "M", "pid": _PID, "tid": t,
            "args": {"name": lane},
        })
        events.append({
            "name": "thread_sort_index", "ph": "M", "pid": _PID, "tid": t,
            "args": {"sort_index": t},
        })
    for rec, lane, args in spans:
        events.append({
            "name": rec.get("stage", "?"), "cat": "stage", "ph": "X",
            "ts": round(float(rec.get("t", 0.0)) * 1e6, 3),
            "dur": round(float(rec.get("dur", 0.0)) * 1e6, 3),
            "pid": _PID, "tid": tid[lane], "args": args,
        })
    for rec, lane, args in instants:
        events.append({
            "name": rec.get("name", "?"), "cat": "event", "ph": "i",
            "ts": round(float(rec.get("t", 0.0)) * 1e6, 3),
            "pid": _PID, "tid": tid[lane], "s": "t", "args": args,
        })
    # byte-ledger records render as COUNTER tracks ("C"): each transfer
    # raises "<dir>_bytes (<lane>)" to its wire size for its span and
    # drops it back to zero at the end, so Perfetto shows H2D/D2H
    # bytes-in-flight per lane right under the span timeline — transfer
    # pressure next to the time it cost. Counter identity is (pid,
    # name); the lane rides in the name because tids don't key counters.
    for rec, lane in xfers:
        name = f"{rec.get('dir', '?')}_bytes ({lane})"
        t0 = round(float(rec.get("t", 0.0)) * 1e6, 3)
        t1 = round(
            (float(rec.get("t", 0.0)) + float(rec.get("dur", 0.0))) * 1e6, 3
        )
        wire = int(rec.get("wire", 0))
        events.append({
            "name": name, "cat": "xfer", "ph": "C", "ts": t0,
            "pid": _PID, "tid": tid[lane], "args": {"bytes": wire},
        })
        events.append({
            "name": name, "cat": "xfer", "ph": "C", "ts": t1,
            "pid": _PID, "tid": tid[lane], "args": {"bytes": 0},
        })
    # device-ledger records render as a FLOP/s counter track: each dev
    # record raises "device_gflops_s (<class>)" to its average rate
    # (flops/dur) for its device-wait window and drops it back to zero
    # — so Perfetto shows WHICH bucket class the MXU was earning on at
    # any instant, right under the span timeline. Same raise/drop
    # pattern as the byte counters; the class rides in the name because
    # counter identity is (pid, name).
    for rec, lane in devs:
        cap = int(rec.get("cap", 0))
        name = (
            f"device_gflops_s (c{cap}xL{int(rec.get('cycles', 0))}/"
            f"{rec.get('method', '?')})"
        )
        t0 = round(float(rec.get("t", 0.0)) * 1e6, 3)
        dur = float(rec.get("dur", 0.0))
        t1 = round((float(rec.get("t", 0.0)) + dur) * 1e6, 3)
        rate = float(rec.get("flops", 0.0)) / dur / 1e9 if dur > 0 else 0.0
        events.append({
            "name": name, "cat": "dev", "ph": "C", "ts": t0,
            "pid": _PID, "tid": tid[lane], "args": {"gflops_s": round(rate, 3)},
        })
        events.append({
            "name": name, "cat": "dev", "ph": "C", "ts": t1,
            "pid": _PID, "tid": tid[lane], "args": {"gflops_s": 0},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome(records, out_path: str) -> int:
    """Export ``records`` as a Chrome trace JSON file; returns the
    number of traceEvents written."""
    doc = to_chrome(records)
    with open(out_path, "w") as f:
        json.dump(doc, f)
    return len(doc["traceEvents"])


# ------------------------------------------------------------ fleet view

def fleet_to_chrome(stitched: dict, run_captures=()) -> dict:
    """Render stitched fleet timelines (telemetry/fleet.py) as Chrome
    trace events: ONE LANE PER DAEMON whose slices are named by job id
    (Perfetto colors slices by name hash, so each job keeps its color
    as it hops lanes — a takeover or a shard fan-out is visible as the
    same color resuming on another daemon's track), plus one lane per
    job carrying its full admission→terminal decomposition (segments
    AND attributed gaps). Per-job run captures, when provided
    (``--trace`` jobs), add their per-chunk spans on a ``run:`` lane
    aligned by their own ``epoch_m``."""
    jobs = stitched["jobs"]
    # one shared origin so Perfetto's clock starts near zero
    t0s = []
    for tl in jobs.values():
        if tl["admission_us"] is not None:
            t0s.append(tl["admission_us"])
        t0s += [s["t0_us"] for s in tl["segments"]]
    origin = min(t0s) if t0s else 0

    lanes = sorted(stitched["daemons"])
    job_lanes = [f"job {j}" for j in sorted(jobs)]
    run_lanes = [f"run:{os.path.basename(c['path'])}" for c in run_captures]
    tid = {}
    events = [{
        "name": "process_name", "ph": "M", "pid": _PID, "tid": 0,
        "args": {"name": "duplexumi fleet"},
    }]
    for i, lane in enumerate(
        [f"daemon {d}" for d in lanes] + job_lanes + run_lanes
    ):
        tid[lane] = i + 1
        events.append({
            "name": "thread_name", "ph": "M", "pid": _PID, "tid": i + 1,
            "args": {"name": lane},
        })
        events.append({
            "name": "thread_sort_index", "ph": "M", "pid": _PID,
            "tid": i + 1, "args": {"sort_index": i + 1},
        })

    def _x(name, t0_us, t1_us, lane, cat, args):
        events.append({
            "name": name, "cat": cat, "ph": "X",
            "ts": round((t0_us - origin), 3),
            "dur": round((t1_us - t0_us), 3),
            "pid": _PID, "tid": tid[lane], "args": args,
        })

    for job_id in sorted(jobs):
        tl = jobs[job_id]
        for s in tl["segments"]:
            args = {k: v for k, v in s.items() if k not in ("t0_us", "t1_us")}
            lane = f"daemon {s['daemon']}"
            if lane in tid:
                _x(job_id, s["t0_us"], s["t1_us"], lane, "segment", args)
            _x(f"{s['kind']} ({s['daemon'][:12]})", s["t0_us"], s["t1_us"],
               f"job {job_id}", "segment", args)
        for g in tl["gaps"]:
            _x(f"gap:{g['kind']}", g["t0_us"], g["t1_us"],
               f"job {job_id}", "gap", {})
    for cap in run_captures:
        lane = f"run:{os.path.basename(cap['path'])}"
        epoch = cap["epoch_us"] or 0
        for rec in cap["records"]:
            if not isinstance(rec, dict) or rec.get("type") != "span":
                continue
            t0 = epoch + round(float(rec.get("t", 0)) * 1e6)
            t1 = t0 + round(float(rec.get("dur", 0)) * 1e6)
            args = {k: v for k, v in rec.items()
                    if k not in ("type", "stage", "t", "dur")}
            _x(rec.get("stage", "?"), t0, t1, lane, "stage", args)
    return {"traceEvents": events, "displayTimeUnit": "ms"}
