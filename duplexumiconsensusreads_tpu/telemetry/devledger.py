"""Device ledger: per-class FLOP attribution and the roofline model.

The byte ledger (`telemetry/ledger.py`) made the WIRE measurable —
bytes per chunk, effective bandwidth, a wire floor computed from the
capture itself. But the compute side of the roofline stayed analytic:
`benchmark.py` derived one whole-run MFU from `analytic_flops` and a
hard-coded peak, and nobody could say which bucket class (capacity
rung x read length x kernel method) actually earned its device time,
or whether a class sat above or below the machine's ridge point. This
module is the FLOP twin of the byte ledger: the streaming executor
emits one typed ``dev`` record per (chunk, dispatch-class) carrying
the class identity, the executed analytic FLOPs, the wire bytes the
byte ledger already charged that dispatch, and the measured device
interval — so per-class honest MFU, arithmetic intensity, and a
measured roofline verdict fall out of ANY capture.

Dev record (one JSONL line in the capture, ``type == "dev"``)::

  {"type": "dev", "t": <rel start s>, "dur": <device-wait span s>,
   "chunk": k, "lane": "...", "cap": 128, "cycles": 9, "buckets": 3,
   "method": "matmul", "flops": 1.23e9, "h2d_wire": ...,
   "d2h_wire": ..., "disp_s": ...}

The record's (t, dur) window IS the chunk's ``device_wait_fetch``
span and ``disp_s`` accrues exactly the seconds the ``dispatch``
phase was charged for that chunk (retries and bucket-isolation
re-dispatches fold into the same record before it is emitted), which
gives the two sum-check identities ``tools/devstat.py`` enforces:

  sum(dev.dur)     == summary.seconds["device_wait_fetch"]
  sum(dev.disp_s)  == summary.seconds["dispatch"]

Drift means records were dropped, double-emitted, or the capture was
edited — exit 1, exactly like the byte sum-check.

Roofline convention: intensity = FLOPs / wire bytes (both directions)
per class; the ridge ("critical") intensity = peak FLOP/s over the
capture's own MEASURED wire bandwidth, so the verdict compares two
numbers measured under the same tunnel weather. A class at intensity
above the ridge is compute-bound (more bytes/FLOP would not help); at
intensity below it the PR 7 wire floor owns the class.

Busy seconds are interval UNIONS (shared with the byte ledger's
helpers) — dev windows from different chunks overlap whenever the
drain pool runs wide, and a sum would claim more device time than the
wall contains.
"""

from __future__ import annotations

from duplexumiconsensusreads_tpu.telemetry.device import (
    device_peak_flops,
    round_mfu,
)
from duplexumiconsensusreads_tpu.telemetry.ledger import (
    _union_seconds,
    byte_totals,
)
from duplexumiconsensusreads_tpu.telemetry.report import (
    _SUM_ABS_TOL,
    _SUM_REL_TOL,
    _is_num,
    summary_record,
)
from duplexumiconsensusreads_tpu.telemetry.trace import KNOWN_DEV_FIELDS

__all__ = [
    "KNOWN_DEV_FIELDS", "dev_records", "class_key", "class_stats",
    "device_totals", "compile_stats", "wire_bandwidth", "roofline",
    "sum_check_dev",
]


def dev_records(records: list[dict]) -> list[dict]:
    return [r for r in records if isinstance(r, dict) and r.get("type") == "dev"]


def class_key(rec: dict) -> str:
    """The bucket-class identity a dev record attributes to: capacity
    rung x cycle count (read length) x kernel method — the same triple
    that keys a pipeline jit entry, minus the spec knobs that don't
    change the FLOP shape."""
    return f"c{int(rec.get('cap', 0))}xL{int(rec.get('cycles', 0))}/{rec.get('method', '?')}"


def class_stats(
    records: list[dict], peak_flops: float | None = None
) -> dict[str, dict]:
    """Per bucket class: record/bucket counts, executed FLOPs, device
    seconds (summed and union-busy), dispatch seconds, wire bytes both
    directions, honest MFU and arithmetic intensity.

    ``mfu`` divides FLOPs by the class's union-busy device seconds
    (overlapping chunk windows collapsed — the device twin of the byte
    ledger's bandwidth denominator) and the resolved peak;
    ``intensity`` is FLOPs per wire byte over BOTH directions — the
    x-axis of the roofline. ``peak_flops`` defaults to the shared
    device table (`telemetry/device.py`); pass the value explicitly
    when analysing a capture from a different machine."""
    if peak_flops is None:
        peak_flops, _ = device_peak_flops()
    out: dict[str, dict] = {}
    spans: dict[str, list[tuple[float, float]]] = {}
    for rec in dev_records(records):
        key = class_key(rec)
        d = out.setdefault(key, {
            "cap": int(rec.get("cap", 0)),
            "cycles": int(rec.get("cycles", 0)),
            "method": rec.get("method", "?"),
            "n": 0, "buckets": 0, "flops": 0.0,
            "dev_s": 0.0, "busy_s": 0.0, "disp_s": 0.0,
            "h2d_wire": 0, "d2h_wire": 0,
        })
        d["n"] += 1
        d["buckets"] += int(rec.get("buckets", 0))
        d["flops"] += float(rec.get("flops", 0.0))
        t = float(rec.get("t", 0.0))
        dur = float(rec.get("dur", 0.0))
        d["dev_s"] += dur
        d["disp_s"] += float(rec.get("disp_s", 0.0))
        d["h2d_wire"] += int(rec.get("h2d_wire", 0))
        d["d2h_wire"] += int(rec.get("d2h_wire", 0))
        spans.setdefault(key, []).append((t, t + dur))
    for key, d in out.items():
        busy = _union_seconds(spans.get(key, []))
        wire = d["h2d_wire"] + d["d2h_wire"]
        d["dev_s"] = round(d["dev_s"], 6)
        d["busy_s"] = round(busy, 6)
        d["disp_s"] = round(d["disp_s"], 6)
        d["flops"] = round(d["flops"], 3)
        d["mfu"] = (
            round_mfu(d["flops"] / busy / peak_flops)
            if busy > 0 and peak_flops > 0 else 0.0
        )
        d["intensity"] = round(d["flops"] / wire, 4) if wire > 0 else 0.0
    # largest FLOP earners first — the classes that own the device
    return dict(sorted(out.items(), key=lambda kv: -kv[1]["flops"]))


def device_totals(records: list[dict], peak_flops: float | None = None) -> dict:
    """Whole-run device view: total executed FLOPs, summed vs
    union-busy device seconds, dispatch seconds, wire bytes, and the
    run's honest MFU (FLOPs over union busy over peak — what the
    machine actually sustained while it had work in flight). {} for
    captures with no dev records (pre-devledger)."""
    recs = dev_records(records)
    if not recs:
        return {}
    if peak_flops is None:
        peak_flops, _ = device_peak_flops()
    flops = sum(float(r.get("flops", 0.0)) for r in recs)
    dev_s = sum(float(r.get("dur", 0.0)) for r in recs)
    disp_s = sum(float(r.get("disp_s", 0.0)) for r in recs)
    busy = _union_seconds([
        (float(r.get("t", 0.0)),
         float(r.get("t", 0.0)) + float(r.get("dur", 0.0)))
        for r in recs
    ])
    h2d = sum(int(r.get("h2d_wire", 0)) for r in recs)
    d2h = sum(int(r.get("d2h_wire", 0)) for r in recs)
    wire = h2d + d2h
    return {
        "n": len(recs),
        "buckets": sum(int(r.get("buckets", 0)) for r in recs),
        "flops": round(flops, 3),
        "dev_s": round(dev_s, 6),
        "busy_s": round(busy, 6),
        "disp_s": round(disp_s, 6),
        "h2d_wire": h2d,
        "d2h_wire": d2h,
        "mfu": (
            round_mfu(flops / busy / peak_flops)
            if busy > 0 and peak_flops > 0 else 0.0
        ),
        "intensity": round(flops / wire, 4) if wire > 0 else 0.0,
    }


def compile_stats(records: list[dict]) -> dict:
    """The jit-cache ledger view: one ``jit_compile`` event per first
    pipeline call per compiled spec, each carrying that call's wall
    seconds (trace + XLA compile + the first execution — JAX dispatches
    asynchronously, so the first call is the only one that blocks on
    compilation). Returns total count/seconds plus the per-class
    breakdown; {} when the capture has no compile events."""
    per: dict[str, dict] = {}
    n = 0
    total = 0.0
    for rec in records:
        if not isinstance(rec, dict) or rec.get("type") != "event":
            continue
        if rec.get("name") != "jit_compile":
            continue
        n += 1
        cs = float(rec.get("compile_s", 0.0))
        total += cs
        key = class_key(rec)
        d = per.setdefault(key, {"n": 0, "compile_s": 0.0})
        d["n"] += 1
        d["compile_s"] = round(d["compile_s"] + cs, 6)
    if not n:
        return {}
    return {
        "n_compiles": n,
        "compile_s": round(total, 6),
        "per_class": dict(sorted(per.items())),
    }


def wire_bandwidth(records: list[dict], totals: dict | None = None) -> float:
    """Measured wire bandwidth of the capture in bytes/s: total wire
    bytes over the union occupancy of BOTH directions' transfer spans
    — the denominator of the roofline's ridge point. 0.0 when the
    capture has no timed transfers."""
    if totals is None:
        totals = byte_totals(records)
    wire = (
        totals.get("h2d", {}).get("wire", 0)
        + totals.get("d2h", {}).get("wire", 0)
    )
    both: list[tuple[float, float]] = []
    for rec in records:
        if not isinstance(rec, dict) or rec.get("type") != "xfer":
            continue
        if rec.get("dir") in ("h2d", "d2h"):
            t = float(rec.get("t", 0.0))
            both.append((t, t + float(rec.get("dur", 0.0))))
    busy = _union_seconds(both)
    return wire / busy if busy > 0 and wire > 0 else 0.0


def roofline(
    records: list[dict],
    peak_flops: float | None = None,
    totals: dict | None = None,
) -> dict:
    """The measured roofline position of every bucket class.

    The ridge ("critical") intensity is peak FLOP/s over the capture's
    own measured wire bandwidth — the FLOPs/byte a class must execute
    for compute to own its wall. Classes above the ridge are
    ``compute-bound`` (the wire could feed them faster than the MXU
    drains them); below it they are ``wire-bound`` — the PR 7 wire
    floor owns them and packing, not kernel work, is the lever. The
    run-level ``attainable_frac`` compares the run's achieved FLOP/s
    against min(peak, run intensity x wire bandwidth): 1.0 means the
    run sat ON its roof; the gap is overhead the roofline model does
    not explain. {} when the capture has no dev records."""
    tot = device_totals(records, peak_flops=peak_flops)
    if not tot:
        return {}
    if peak_flops is None:
        peak_flops, peak_entry = device_peak_flops()
    else:
        peak_entry = "caller"
    bw = wire_bandwidth(records, totals=totals)
    critical = peak_flops / bw if bw > 0 else 0.0
    classes = {}
    for key, d in class_stats(records, peak_flops=peak_flops).items():
        classes[key] = {
            "intensity": d["intensity"],
            "mfu": d["mfu"],
            "verdict": (
                "compute-bound"
                if critical > 0 and d["intensity"] >= critical
                else "wire-bound"
            ),
        }
    achieved = tot["flops"] / tot["busy_s"] if tot["busy_s"] > 0 else 0.0
    roof = (
        min(peak_flops, tot["intensity"] * bw)
        if bw > 0 else peak_flops
    )
    return {
        "peak_flops": peak_flops,
        "peak_entry": peak_entry,
        "wire_bw_b_s": round(bw, 1),
        "critical_intensity": round(critical, 4),
        "achieved_flops_s": round(achieved, 1),
        "attainable_frac": (
            round(min(achieved / roof, 1.0), 4) if roof > 0 else 0.0
        ),
        "classes": classes,
    }


def sum_check_dev(
    records: list[dict], seconds: dict | None = None
) -> tuple[list[dict], bool]:
    """Dev-record totals vs the executor's phase totals — the device
    twin of the byte sum-check.

    Every dev record's window IS a ``device_wait_fetch`` span and its
    ``disp_s`` accrued exactly what the ``dispatch`` phase was charged
    for that chunk, so the record sums must reproduce the summary's
    two phase totals to within the time sum-check's tolerance (floats
    round; bytes don't). A capture truncated by the bounded recorder
    (summary n_dropped > 0) can only under-count: one-sided, records
    <= summary. Returns (rows, ok); a capture with NO dev records
    (pre-devledger) has nothing to check -> ([], True)."""
    recs = dev_records(records)
    if not recs:
        return [], True
    s = summary_record(records)
    dropped = int((s or {}).get("n_dropped") or 0)
    if seconds is None:
        seconds = (s or {}).get("seconds") or {}
    got = {
        "device_wait_fetch": sum(float(r.get("dur", 0.0)) for r in recs),
        "dispatch": sum(float(r.get("disp_s", 0.0)) for r in recs),
    }
    rows = []
    ok_all = True
    for stage, rec_s in got.items():
        sv = seconds.get(stage, 0.0)
        report_s = float(sv) if _is_num(sv) else 0.0
        tol = _SUM_ABS_TOL + _SUM_REL_TOL * report_s
        if dropped:
            ok = rec_s <= report_s + tol
        else:
            ok = abs(rec_s - report_s) <= tol
        ok_all &= ok
        rows.append({
            "stage": stage,
            "records_s": round(rec_s, 3),
            "summary_s": round(report_s, 3),
            "ok": ok,
        })
    return rows, ok_all
