"""Thread-safe span recorder for the streaming executor.

Since the pipelined drain (PR 2), per-stage busy seconds overlap each
other and the main loop, so `RunReport.seconds` can say how much work
each stage did but not WHERE the wall went: a slow run might be
ingest-bound, stalled on drain back-pressure, or serialized on one hot
drain worker, and the aggregate cannot tell them apart. This module is
the missing lens — the Dapper-lineage span model applied to the
per-chunk pipeline:

  span   one timed occurrence of a pipeline stage for one chunk, on
         one LANE (the thread that ran it: "main", "xfer-N",
         "drain-N"). The executor records the SAME (t0, dt) pair it
         adds to its busy-time phase accumulators, so summing a
         stage's spans reproduces `RunReport.seconds[stage]` exactly —
         the sum-check `tools/trace_report.py` enforces.
  event  one structured point occurrence: a fault-injection trigger,
         a retry attempt (site + attempt + backoff), a resume decision
         (shard reused vs recomputed), a durable write, a heartbeat.
  xfer   one byte-ledger transfer (telemetry/ledger.py): logical vs
         wire bytes per chunk per direction (h2d/d2h/shard), with the
         same (t, dur) pair as the stage span that moved them — the
         capture's byte accounting, sum-checked by tools/wirestat.py
         the way spans are sum-checked by tools/trace_report.py.
  dev    one device-ledger dispatch (telemetry/devledger.py): the
         bucket-class identity (capacity/cycles/buckets/method),
         executed analytic FLOPs, the dispatch's wire bytes, and the
         measured device interval — (t, dur) is the SAME pair as the
         chunk's device_wait_fetch span and ``disp_s`` the same
         seconds the dispatch phase accumulator received, so per-class
         MFU/intensity/roofline fall out of any capture and
         tools/devstat.py sum-checks the records against the phase
         totals the way wirestat sum-checks bytes.

Capture format: JSONL, one record per line, strictly in write order —
a `meta` line first, then spans/events as they complete (NOT in start
order: a span is written when it ends), and a `summary` line last on
clean shutdown (a crashed run's capture simply lacks it; the file is
still valid for post-mortem). Timestamps are seconds relative to the
recorder's monotonic epoch — wall-clock never appears, so an NTP step
cannot corrupt a capture any more than it can the phase accounting.

The recorder is BOUNDED: past ``max_events`` records it drops (and
counts) instead of growing the capture without limit — a 200M-read run
must not be able to fill the disk with its own telemetry.

Cost contract: when no recorder is installed, every hook in the hot
path is a single global load + ``None`` check (the same discipline as
``faults.fault_point``) — measured <1% on the e2e capture. When
recording, each span costs one dict build + one ``json.dumps`` + one
buffered write under a lock, per STAGE per CHUNK (not per read).
"""

from __future__ import annotations

import json
import os
import threading
import time

TRACE_VERSION = 1

# Every span stage the streaming executor records — one per step of the
# per-chunk pipeline plus the main loop's back-pressure stall. Keep in
# sync with the instrumentation in runtime/stream.py, the phase dict it
# feeds, and the "Telemetry" section of ARCHITECTURE.md.
KNOWN_STAGES = (
    "ingest",  # rolling BGZF read + native inflate + chunk parse (main)
    "bucketing",  # build_buckets on the parsed chunk (main)
    "dispatch",  # stack/pack/device_put (xfer worker; drain on retry)
    "mesh_h2d",  # per-device H2D puts of a multi-device dispatch: one
    # span per device on its "dev-N" lane, emitted from inside the
    # dispatch body (same threads as "dispatch", whose busy time
    # excludes these windows); 0 on single-device runs
    "device_wait_fetch",  # device execution wait + d2h materialise (drain)
    "scatter",  # scatter-back to batch coordinates (drain)
    "deflate",  # BGZF-compress the shard's record stream (drain)
    "shard_write",  # serialize + durable shard write, minus deflate (drain)
    "ckpt",  # per-chunk checkpoint manifest mark (main)
    "finalise",  # incremental tmp appends + terminal EOF/fsync/rename (main)
    "main_loop_stall",  # main loop blocked on drain back-pressure (main)
    "prefetch_stall",  # main loop blocked on the bounded H2D prefetch
    # window (--prefetch-depth): dispatch of chunk k+depth may not start
    # until chunk k's device results are materialised (main)
    "ingest_stall",  # overlap mode: main loop blocked waiting for the
    # ingest producer's next chunk (main) — the honest residue of
    # ingest cost the background pipeline could NOT hide behind device
    # time; 0 in forced-sync mode, where "ingest" itself is main wall
    "ingest_backpressure",  # overlap mode: the ingest producer blocked
    # on the full bounded handoff queue (ingest lane) — ingest running
    # AHEAD of the pipeline, the healthy steady state
    "live_poll",  # follow mode: tailer poll cycles against the growing
    # input — stat + incremental read + complete-block scan (accrued on
    # the consumer side at chunk boundaries from the tailer's clock)
    "live_wait",  # follow mode: ingest blocked waiting for the tailer
    # to admit more bytes — the instrument-is-slower-than-us residue,
    # distinct from ingest_stall (pipeline slower than ingest)
)

# Structured point events. Attrs are per-name (see the emitting sites);
# unknown extra attrs are legal — the validator checks names and the
# core envelope only, so new context can ride along without a schema
# bump.
KNOWN_EVENTS = (
    "fault_injected",  # runtime/faults.py: a scheduled fault fired
    "retry",  # a bounded-backoff retry attempt (site/attempt/backoff_s)
    "bucket_isolation",  # class retries exhausted -> per-bucket re-dispatch
    "resume",  # per-chunk resume decision: reused vs recomputed
    "durable_write",  # io/durable.py: a tmp+fsync+rename completed
    "heartbeat",  # periodic liveness sample (also printed to stderr)
    "truncated",  # the bounded recorder hit max_events; tail dropped
    "lock_stall",  # serve/queue.py: journal.lock not acquired within
    # the stall threshold — one event per stalled acquisition (attrs:
    # waited_s, spool), the wedged-shared-filesystem-lock alarm; the
    # acquisition itself keeps polling until lock_timeout_s, then
    # fails typed (JournalLockTimeout)
    "packed_fallback",  # wire packing downgraded a rung (pos ids past
    # u16, qual cap past the 6-bit payload, per-base tags forcing an
    # unpacked d2h, a class capacity overflowing the u16 ids lane): the
    # per-chunk packing decision the ledger records instead of a
    # mid-dispatch job failure (attrs: reason, scope)
    "tuner_verdict",  # bucket auto-tuner (tuning/): the profile pass
    # settled the run's bucket ladder (attrs: ladder, fill_factor,
    # fill_factor_off, predicted_speedup, source) — in a run capture at
    # the first profiled chunk, in a service capture when a verdict is
    # persisted/reused for a job's input profile
    "jit_compile",  # device ledger: the FIRST pipeline call of a fresh
    # dispatch class (a spec the executor's jit cache had not seen) —
    # attrs: compile_s (the first-call seconds: trace + XLA compile +
    # the first execution's dispatch), cap/cycles/method (the class
    # identity devstat groups by). Per-class compile cost in the same
    # record stream the per-class MFU comes from.
    "profile_written",  # --profile: the jax.profiler trace directory
    # was finalised (attrs: profile_dir) — the capture records that a
    # profiler trace exists alongside it
    # serving layer (serve/service.py): the job lifecycle in a
    # kind="service" capture. Every job_* event carries a "job" attr and
    # a "job-<id>" lane, so one capture decomposes per job the way a run
    # capture decomposes per chunk (validate_service_trace enforces it).
    "job_accepted",  # admission: inbox submission -> journaled queue
    "job_rejected",  # admission refused (invalid spec)
    "job_shed",  # admission-control rejection: class/queue bound hit
    "job_started",  # a scheduler slice began (attrs: slice, resumed)
    "job_preempted",  # chunk-boundary yield (budget or drain)
    "job_completed",  # finalise done (attrs: wall_s, per-phase seconds)
    "job_failed",  # slice raised; job journaled failed, service lives on
    # fleet lease protocol (serve/queue.py): takeover of a dead/expired
    # lease, and a zombie slice aborted by its stale fencing token
    "lease_takeover",  # running job reclaimed (attrs: reason, prev_owner)
    "job_fenced",  # slice lost its lease; committed nothing, not a failure
    # defensive serving (deadlines / watchdog / quarantine): all
    # job-scoped — they ride job-<id> lanes like every job_* event
    "job_expired",  # deadline passed: terminal, durable reason
    "job_quarantined",  # crash_count hit max_crashes: terminal + diagnosis
    "watchdog_fired",  # no durable progress for watchdog_s: abort-requeue
    # scatter-gather sharding (serve/shard/): the parent's two stage
    # completions — sub-jobs registered (attrs: n_shards, n_chunks) and
    # shard outputs spliced into the final BAM (attrs: merge_s,
    # output_bytes); the parent still gets the standard job_completed
    "job_split",  # planner fanned the parent out into K sub-jobs
    "job_merged",  # shard outputs spliced + indexed into one output
    # live follow-mode ingest (live/ + runtime/stream.py): an indexed
    # partial snapshot (valid BAM prefix + BAI) was durably published
    # at a checkpoint mark (attrs: snapshot_seq, chunks_done, reads)
    "snapshot_published",
)

# Byte-ledger directions (the third record kind, ``xfer`` — see
# telemetry/ledger.py for the record schema and the analysis). One
# registry like KNOWN_STAGES/KNOWN_EVENTS: the capture validator and
# dutlint's phase-registry rule both pin literal ``xfer("...")`` call
# sites to this tuple.
KNOWN_XFER_DIRS = (
    "h2d",  # dispatch: stacked/packed input tensors -> device
    "d2h",  # fetch: consensus output tensors -> host
    "shard",  # drain: raw record stream -> deflated durable shard
)

# Schema attrs an h2d ledger record may carry beyond the core envelope
# (logical/wire/t/dur/chunk/lane) — a registry like the dirs above, so
# dutlint's phase-registry rule pins every literal keyword at the
# emitting site and the xfer schema golden cannot drift silently:
#   bpc        the packing rung's wire bits per base/qual cycle
#   rows_real  real read rows in the dispatch (bucket fill numerator)
#   rows_pad   padded row-slots dispatched (capacity x padded buckets)
#   cap        the dispatch class's bucket capacity (its ladder rung)
#   mesh_pad   mesh-alignment pad buckets in this dispatch (slice):
#              empty buckets appended so the class's bucket count is a
#              device-count multiple — they cross the wire, so they are
#              ledgered; the per-record sums must reproduce the summary
#              counter n_mesh_pad_buckets exactly (wirestat checks)
KNOWN_H2D_XFER_ATTRS = ("bpc", "rows_real", "rows_pad", "cap", "mesh_pad")

# Schema fields a ``dev`` (device-ledger) record carries beyond the
# core envelope (type/t/dur/chunk/lane) — a registry like the h2d
# attrs above; the capture validator checks the envelope against it
# and dutlint's dev-ledger rule pins every literal keyword at the
# emitting site, so the devstat schema cannot drift silently:
#   cap       bucket capacity of the dispatch class (its ladder rung)
#   cycles    read length L of the class's bucket tensors
#   buckets   padded bucket count dispatched (mesh-pad included — pads
#             ride the wire and the GEMM, so they are in the FLOPs too)
#   method    the class's ssc kernel method (a kernels/consensus.py
#             literal; every one has a registered cost function in
#             ops/pipeline.py's SSC_METHOD_COSTS — dutlint enforces it)
#   flops     executed analytic FLOPs of the class's dispatches
#             (analytic_flops x padded bucket count, retries counted
#             like the byte ledger counts re-transfers)
#   h2d_wire  wire bytes the dispatches put on the device (the same
#             bytes the chunk's h2d xfer records ledger)
#   d2h_wire  wire bytes the materialised fetch moved back
#   disp_s    host-side dispatch busy seconds of the class's
#             dispatches — the SAME seconds phase["dispatch"] received,
#             so devstat's dispatch sum-check holds by construction
KNOWN_DEV_FIELDS = (
    "cap", "cycles", "buckets", "method", "flops", "h2d_wire",
    "d2h_wire", "disp_s",
)

# Literal lane ids/prefixes a recording site may pass as ``lane=``.
# Most lanes derive from thread names (current_lane: main / xfer-N /
# drain-N) and are never literals; the two literal families are the
# service's per-job lanes and the mesh dispatch's per-device lanes.
# dutlint's phase-registry rule pins every literal ``lane=`` argument
# (f-string prefixes included) to this registry, so a typo'd lane
# family cannot silently fork the capture schema consumers group by.
KNOWN_LANE_PREFIXES = ("main", "xfer-", "drain-", "job-", "dev-", "ingest")


def current_lane() -> str:
    """Lane id of the calling thread. The executor's pools carry
    ``dut-`` thread-name prefixes precisely so spans can self-identify:
    ``main`` / ``xfer-N`` / ``drain-N`` / ``ingest`` (the background
    producer); anything else keeps its raw thread name (still a valid
    lane)."""
    name = threading.current_thread().name
    if name == "MainThread":
        return "main"
    if name == "dut-ingest":
        return "ingest"
    for prefix, lane in (("dut-xfer_", "xfer-"), ("dut-drain_", "drain-")):
        if name.startswith(prefix):
            return lane + name[len(prefix):]
    return name


class TraceRecorder:
    """Bounded JSONL span/event recorder on one shared monotonic epoch.

    Writes through to ``path`` as records arrive (buffered file I/O —
    a crash loses at most the OS buffer, never corrupts earlier lines).
    All methods are thread-safe; the executor's drain/xfer workers and
    the heartbeat thread all write to one recorder.
    """

    def __init__(
        self, path: str, max_events: int = 1_000_000, kind: str = "run",
        meta: dict | None = None,
    ):
        """``kind`` tags the capture's meta header: "run" (a streaming
        executor capture, the default) or "service" (a serve/ daemon
        capture — job-lifecycle events instead of per-chunk spans).
        Consumers (tools/check_trace.py) key their extra checks on it;
        pre-kind captures read as "run". ``meta`` adds extra attrs to
        the meta header (the service stamps its ``daemon_id`` so a
        capture names its writer — telemetry/fleet.py keys cross-daemon
        stitching on it)."""
        if max_events < 1:
            raise ValueError(f"max_events must be >= 1 (got {max_events})")
        if kind not in ("run", "service"):
            raise ValueError(f"unknown capture kind {kind!r}")
        self.path = path
        self.kind = kind
        self.max_events = max_events
        self.n_events = 0  # admitted spans + events (meta/summary free)
        self.n_dropped = 0
        self._truncated = False
        self._sealed = False  # summary written: no records may follow it
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        # rotate, don't truncate: a capture at this path is most often
        # the PREVIOUS (possibly crashed) run's post-mortem evidence,
        # and the documented recovery flow is to rerun the same command
        # with --resume — which would otherwise destroy it here
        try:
            if os.path.getsize(path) > 0:
                os.replace(path, path + ".prev")
        except OSError:
            pass
        # service captures are LINE-buffered: a SIGKILLed daemon's
        # capture is exactly the evidence the fleet stitcher
        # (telemetry/fleet.py) post-mortems the takeover from, and at
        # block buffering a short-lived daemon's whole capture can die
        # in the 8KB userspace buffer (a real-SIGKILL drive produced a
        # 0-byte file). Event rate is per job lifecycle, so the
        # per-line write cost is noise. Run captures keep block
        # buffering (per-chunk spans at scale) — their kill story is
        # the in-process finally/close path, which flushes.
        self._f = open(path, "w", buffering=1 if kind == "service" else -1)
        # epoch_m: this recorder's epoch as a RAW machine-wide
        # CLOCK_MONOTONIC reading. Record times stay epoch-relative
        # (NTP-proof as documented above), but the epoch itself makes
        # captures from N processes on one host alignable onto one
        # timeline (epoch_m + t), which is what the fleet stitcher
        # reconstructs cross-daemon job timelines from — the same
        # one-host scope flock and the lease clock already impose on a
        # spool.
        self._line({"type": "meta", "version": TRACE_VERSION,
                    "kind": kind, "clock": "monotonic-relative",
                    "epoch_m": round(self._t0, 6), **(meta or {})})
        self._f.flush()  # the header must survive any crash

    # ------------------------------------------------------- internals

    def rel(self, t_monotonic: float) -> float:
        """Map a ``time.monotonic()`` reading onto the trace epoch."""
        return t_monotonic - self._t0

    def _line(self, rec: dict) -> None:
        self._f.write(json.dumps(rec, separators=(",", ":")) + "\n")

    def _emit(self, rec: dict) -> None:
        with self._lock:
            if self._f is None or self._sealed:
                # closed, or the terminal summary is already written (a
                # straggling heartbeat/worker): drop silently — summary
                # must stay the last record, the validator checks it
                return
            if self.n_events >= self.max_events:
                self.n_dropped += 1
                if not self._truncated:
                    self._truncated = True
                    self._line({
                        "type": "event", "name": "truncated",
                        "t": round(time.monotonic() - self._t0, 6),
                        "lane": current_lane(),
                        "max_events": self.max_events,
                    })
                return
            self.n_events += 1
            self._line(rec)

    # ------------------------------------------------------ record API

    def span(
        self,
        stage: str,
        t_start: float,
        dur: float,
        chunk: int | None = None,
        lane: str | None = None,
        **attrs,
    ) -> None:
        """Record one completed span. ``t_start`` is the raw
        ``time.monotonic()`` reading at stage start and ``dur`` the
        measured duration — pass the SAME dt the busy-time phase
        accumulator receives, so the capture's per-stage sums and
        ``RunReport.seconds`` agree by construction."""
        rec = {
            "type": "span", "stage": stage,
            "t": round(self.rel(t_start), 6), "dur": round(dur, 6),
            "lane": lane or current_lane(),
        }
        if chunk is not None:
            rec["chunk"] = int(chunk)
        if attrs:
            rec.update(attrs)
        self._emit(rec)

    def event(
        self,
        name: str,
        chunk: int | None = None,
        lane: str | None = None,
        **attrs,
    ) -> None:
        """Record one structured point event at 'now'."""
        rec = {
            "type": "event", "name": name,
            "t": round(self.rel(time.monotonic()), 6),
            "lane": lane or current_lane(),
        }
        if chunk is not None:
            rec["chunk"] = int(chunk)
        if attrs:
            rec.update(attrs)
        self._emit(rec)

    def xfer(
        self,
        direction: str,
        logical: int | None,
        wire: int,
        t_start: float,
        dur: float,
        chunk: int | None = None,
        lane: str | None = None,
        **attrs,
    ) -> None:
        """Record one byte-ledger transfer (``type == "xfer"``).

        ``logical`` is the payload before packing/deflate and ``wire``
        the bytes actually moved/stored; pass ``logical=None`` when the
        pre-wire size is unknowable (resume-reused shards). ``t_start``
        / ``dur`` are the raw monotonic reading and measured span of
        the transfer — the SAME pair the matching stage span records,
        so (bytes, dt) yields a bandwidth the time sum-check already
        vouches for."""
        rec = {
            "type": "xfer", "dir": direction,
            "t": round(self.rel(t_start), 6), "dur": round(dur, 6),
            "wire": int(wire),
            "lane": lane or current_lane(),
        }
        if logical is not None:
            rec["logical"] = int(logical)
        if chunk is not None:
            rec["chunk"] = int(chunk)
        if attrs:
            rec.update(attrs)
        self._emit(rec)

    def dev(
        self,
        t_start: float,
        dur: float,
        chunk: int | None = None,
        lane: str | None = None,
        **fields,
    ) -> None:
        """Record one device-ledger dispatch (``type == "dev"``).

        ``t_start`` / ``dur`` are the raw monotonic reading and
        measured span of the chunk's device wait + fetch for this
        dispatch class — the SAME pair the ``device_wait_fetch`` span
        records, so summing ``dur`` over a capture's dev records
        reproduces that phase total (the devstat time sum-check), and
        ``fields["disp_s"]`` likewise sums to the dispatch phase.
        ``fields`` are the KNOWN_DEV_FIELDS schema attrs."""
        rec = {
            "type": "dev",
            "t": round(self.rel(t_start), 6), "dur": round(dur, 6),
            "lane": lane or current_lane(),
        }
        if chunk is not None:
            rec["chunk"] = int(chunk)
        if fields:
            rec.update(fields)
        self._emit(rec)

    def write_summary(self, **fields) -> None:
        """Append the terminal summary record (clean shutdown only).
        The executor passes its ``RunReport.seconds`` busy totals here;
        ``tools/trace_report.py`` sum-checks span totals against them."""
        with self._lock:
            if self._f is None or self._sealed:
                return
            self._sealed = True  # nothing may be recorded after this
            self._line({
                "type": "summary",
                "t": round(time.monotonic() - self._t0, 6),
                "n_events": self.n_events,
                "n_dropped": self.n_dropped,
                **fields,
            })

    def close(self) -> None:
        """Flush and close the capture. Idempotent; safe to call from a
        ``finally`` on every exit path — a crashed run's capture simply
        ends without a summary record."""
        with self._lock:
            f, self._f = self._f, None
        if f is not None:
            f.flush()
            f.close()


# ------------------------------------------------- global hook registry
#
# faults.py, io/durable.py and the executor's module-level retry helper
# are not threaded a recorder handle; they emit through this registry.
# Mirrors the faults.py switchboard: one module-global, a single load +
# None check when tracing is off.

_active: TraceRecorder | None = None


def install(recorder: TraceRecorder | None) -> None:
    global _active
    _active = recorder


def uninstall() -> None:
    install(None)


def get_active() -> TraceRecorder | None:
    return _active


def emit_event(name: str, chunk: int | None = None, **attrs) -> None:
    """Hot-path event hook: no-op unless a recorder is installed."""
    tr = _active
    if tr is not None:
        tr.event(name, chunk=chunk, **attrs)


# ------------------------------------------------------------ heartbeat

class Heartbeat:
    """Periodic liveness line for long streaming runs.

    Every ``interval_s`` a daemon thread calls ``stats_fn`` (a cheap
    closure over the executor's live counters) and prints one
    ``[duplexumi] heartbeat`` line to stderr: chunks done/inflight,
    stall fraction, retries, drain utilization. With a recorder
    attached the same sample is also written as a ``heartbeat`` event,
    so a capture carries the run's liveness curve. The thread is a
    daemon and ``stop()`` is join-bounded: a wedged sink can never hold
    the run open.
    """

    def __init__(
        self,
        interval_s: float,
        stats_fn,
        recorder: TraceRecorder | None = None,
        sink=None,  # overridable for tests; defaults to stderr print
    ):
        if interval_s <= 0:
            raise ValueError(f"heartbeat interval must be > 0 (got {interval_s})")
        self.interval_s = interval_s
        self._stats_fn = stats_fn
        self._recorder = recorder
        self._sink = sink
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="dut-heartbeat", daemon=True
        )

    def start(self) -> "Heartbeat":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            # set() wakes the interval wait immediately, so the only
            # thing worth waiting on is an in-flight beat(); a wedged
            # sink must be bounded by ~1s, never the full interval
            self._thread.join(timeout=min(self.interval_s, 1.0))

    def beat(self) -> None:
        """One sample -> stderr line (+ trace event). Exposed for tests
        and for a final sample at shutdown."""
        stats = dict(self._stats_fn())
        line = "[duplexumi] heartbeat " + " ".join(
            f"{k}={v}" for k, v in stats.items()
        )
        if self._sink is not None:
            self._sink(line)
        else:
            import sys

            print(line, file=sys.stderr, flush=True)
        if self._recorder is not None:
            self._recorder.event("heartbeat", **stats)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.beat()
            except Exception:
                # telemetry must never take down the run it observes
                pass
