"""Per-chunk tracing + live telemetry for the streaming executor.

`trace` is the recording side (span recorder, structured events, the
byte-ledger `xfer` records, the heartbeat thread); `chrome` exports a
capture as Chrome trace events (opens in Perfetto / chrome://tracing);
`report` is the offline analysis side (schema validation, per-lane
utilization, per-stage percentiles, per-chunk critical path, the
sum-check against `RunReport.seconds`); `ledger` is the byte twin
(per-chunk byte totals, measured bandwidth, the wire-floor model, the
byte sum-checks `tools/wirestat.py` enforces); `devledger` is the
device twin (per-class FLOPs/MFU/arithmetic intensity, the roofline
verdicts, the dev-interval sum-checks `tools/devstat.py` enforces);
`device` is the shared peak-FLOP/s table every MFU consumer resolves
through. The recording side imports only the stdlib so
`runtime/faults.py` and `io/durable.py` can hook into it without an
import cycle.
"""

from duplexumiconsensusreads_tpu.telemetry.trace import (
    KNOWN_DEV_FIELDS,
    KNOWN_EVENTS,
    KNOWN_STAGES,
    KNOWN_XFER_DIRS,
    Heartbeat,
    TraceRecorder,
    emit_event,
    get_active,
    install,
    uninstall,
)

__all__ = [
    "KNOWN_DEV_FIELDS",
    "KNOWN_EVENTS",
    "KNOWN_STAGES",
    "KNOWN_XFER_DIRS",
    "Heartbeat",
    "TraceRecorder",
    "emit_event",
    "get_active",
    "install",
    "uninstall",
]
