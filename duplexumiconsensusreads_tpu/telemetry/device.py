"""Device peak-FLOP/s table — ONE resolution for every MFU consumer.

Before the device ledger, the only peak lived inline in benchmark.py
(`DUT_PEAK_TFLOPS=197`, the v5e bf16 number) — serve jobs and offline
capture analysis had no peak at all, and a bench run on a v4 silently
normalised against the wrong chip. This module is the single table:
keyed on ``jax.devices()[0].device_kind``, env override wins, and every
consumer (benchmark.py's compute leg, tools/devstat.py, the serving
layer's per-job MFU) resolves through :func:`device_peak_flops` so the
denominators cannot drift apart.

The resolution NAMES its entry (``("env", "v5e", "cpu-sim", ...)``):
an MFU number without its peak provenance is unauditable, so the bench
line prints the entry and devstat carries it in ``--json``.
"""

from __future__ import annotations

import os

# bf16 peak TFLOP/s per device kind. Matching is case-insensitive
# substring over the JAX ``device_kind`` string, first hit wins — v5p
# must precede the bare "v5 lite" family and v4 never collides.
# The cpu-sim entry deliberately keeps the v5e 197: the driver's
# CPU-sim canonical legs have normalised against it since r1, so their
# MFU is a cross-round-comparable ratio, not a host utilisation claim —
# changing it would step every trajectory metric with no code change.
PEAK_TFLOPS_TABLE = (
    ("v5p", ("v5p",), 459.0),
    ("v5e", ("v5 lite", "v5e"), 197.0),
    ("v4", ("v4",), 275.0),
    ("cpu-sim", ("cpu",), 197.0),
)

# unrecognised device kinds (new chip, exotic backend) fall back to the
# v5e number the repo has always assumed — the honest move is a named
# fallback entry, not a crash in a telemetry path
DEFAULT_PEAK = ("default-v5e", 197.0)


def device_peak_flops(device_kind: str | None = None) -> tuple[float, str]:
    """Resolve (peak FLOP/s, entry name) for ``device_kind``.

    ``DUT_PEAK_TFLOPS`` overrides everything (the pre-existing knob —
    other chips, derated clocks); ``device_kind=None`` asks the local
    JAX runtime, degrading to the default entry when no backend is
    reachable (offline capture analysis must never need a device).
    """
    env = os.environ.get("DUT_PEAK_TFLOPS")
    if env:
        return float(env) * 1e12, f"env:{env}T"
    if device_kind is None:
        try:
            import jax

            device_kind = jax.devices()[0].device_kind
        except Exception:  # noqa: BLE001 — offline analysis, no backend
            return DEFAULT_PEAK[1] * 1e12, DEFAULT_PEAK[0]
    kind = str(device_kind).lower()
    for entry, needles, tflops in PEAK_TFLOPS_TABLE:
        if any(n in kind for n in needles):
            return tflops * 1e12, entry
    return DEFAULT_PEAK[1] * 1e12, DEFAULT_PEAK[0]


def round_mfu(x: float) -> float:
    """Round an MFU ratio for JSON to 4 significant figures. Fixed
    decimal places would flush CPU-sim values to zero — a sim device
    against a 197T peak sustains ~1e-7, and 0.0 reads as "no ledger"
    rather than "tiny machine"."""
    if not x:
        return 0.0
    from math import floor, log10

    return round(x, 3 - int(floor(log10(abs(x)))))
