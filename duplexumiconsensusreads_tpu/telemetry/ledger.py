"""Byte ledger: per-chunk transfer accounting and the wire-floor model.

The r5 captures proved the system transfer-bound — the PCIe wire floor
owned 63-72% of the e2e wall while device compute idled at ~5% MFU —
but the trace capture only recorded *time* per stage: nobody could say
how many bytes crossed the wire per chunk, what the packing bought, or
whether a "faster" run actually moved fewer bytes. This module is the
byte twin of the span model: the streaming executor records one typed
``xfer`` ledger record per transfer (same capture, same lane/chunk
ids), and the analysis here turns a capture into a *measured* wire
model — effective bandwidth from (bytes, span dt), a wire-floor
fraction computed from the capture itself rather than hand-waved, and
packing-ratio / bytes-per-read stats.

Ledger record (one JSONL line in the capture, ``type == "xfer"``)::

  {"type": "xfer", "dir": "h2d" | "d2h" | "shard",
   "t": <epoch-relative start s>, "dur": <transfer span s>,
   "logical": <bytes before packing/deflate>, "wire": <bytes moved>,
   "chunk": k, "lane": "...", ...}

Directions (``KNOWN_XFER_DIRS`` — the registry dutlint pins, the byte
analogue of ``trace.KNOWN_STAGES``):

  h2d    device dispatch: logical = stacked input tensors before wire
         packing, wire = bytes actually device_put (after packing —
         records carry a ``bpc`` attr naming the rung's bits/cycle).
         Retried dispatches emit again — the ledger counts wire
         traffic, not input size.
  d2h    device fetch: logical = bytes the full padded FETCH_KEYS
         arrays would have moved, wire = bytes the packed
         consensus-only return path actually fetched (equal when the
         d2h rung is off — the pre-PR-11 state this ledger was built
         to quantify).
  shard  the chunk's durable shard: logical = raw record-stream
         bytes, wire = BGZF-deflated bytes on disk. Resume-reused
         chunks emit ``resumed: true`` with wire only (their raw size
         was never re-derived) — each chunk lands in the ledger
         exactly once per run, so shard totals always sum-check
         against the finalised output.

The terminal summary embeds the executor's running totals under
``bytes`` (plus the finalised output size and the header/EOF overhead
it wrote around the shards), so a capture is self-contained for the
two byte sum-checks ``tools/wirestat.py`` enforces: record totals must
reproduce the summary totals exactly (integer equality — bytes don't
round), and ``output_overhead_bytes + shard wire == output_bytes`` on
disk. Drift in either is instrumentation rot or file corruption,
exit 1 — the byte analogue of ``trace_report.py``'s time sum-check.
"""

from __future__ import annotations

from duplexumiconsensusreads_tpu.telemetry.report import (
    _is_num,
    _pctl,
    summary_record,
    wall_seconds,
)
from duplexumiconsensusreads_tpu.telemetry.trace import KNOWN_XFER_DIRS

__all__ = [
    "KNOWN_XFER_DIRS", "SUMMARY_BYTE_KEYS", "xfer_records", "byte_totals",
    "bandwidth_stats", "wire_floor", "packing_stats", "per_chunk_bytes",
    "summary_bytes", "sum_check_bytes", "output_check", "fill_stats",
    "device_lanes", "overlap_stats",
]

# summary["bytes"] keys the executor embeds (all integers; *_logical
# and *_wire are running totals of the matching xfer records).
# d2h_logical joined with the packed-D2H rung; captures from before it
# simply lack the key and the sum-check skips that row.
SUMMARY_BYTE_KEYS = (
    "h2d_logical", "h2d_wire", "d2h_logical", "d2h_wire",
    "shard_logical", "shard_wire",
    "output_bytes", "output_overhead_bytes",
)


def xfer_records(records: list[dict]) -> list[dict]:
    return [r for r in records if isinstance(r, dict) and r.get("type") == "xfer"]


def _union_seconds(intervals: list[tuple[float, float]]) -> float:
    """Total length of the union of (start, end) intervals — transfer
    WALL occupancy. Summing durations instead would double-count spans
    that overlap across the transfer workers (the pools exist precisely
    to overlap the tunnel's per-call latency), and a "floor" bigger
    than the wall is not a floor."""
    total = 0.0
    cur_a = cur_b = None
    for a, b in sorted(intervals):
        if cur_b is None or a > cur_b:
            if cur_b is not None:
                total += cur_b - cur_a
            cur_a, cur_b = a, b
        else:
            cur_b = max(cur_b, b)
    if cur_b is not None:
        total += cur_b - cur_a
    return total


def _merged(intervals: list[tuple[float, float]]) -> list[tuple[float, float]]:
    """Sorted, non-overlapping form of an interval set (the list
    :func:`_union_seconds` measures, materialised for intersection)."""
    out: list[tuple[float, float]] = []
    for a, b in sorted(intervals):
        if out and a <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], b))
        else:
            out.append((a, b))
    return out


def _intersect_seconds(
    a: list[tuple[float, float]], b: list[tuple[float, float]]
) -> float:
    """Total length of the intersection of two interval sets — the
    wall time during which BOTH activities were genuinely in flight."""
    a, b = _merged(a), _merged(b)
    total = 0.0
    i = j = 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return total


# the two sides of the ingest-overlap ledger: host-side chunk prep
# (read/inflate/parse + bucketing — the work the background producer
# exists to hide) vs the device-facing pipeline it must hide BEHIND
_INGEST_STAGES = ("ingest", "bucketing")
_DEVICE_STAGES = ("dispatch", "mesh_h2d", "device_wait_fetch")


def overlap_stats(records: list[dict]) -> dict:
    """How much of the host-side ingest work the pipelined producer
    actually hid behind device-facing work — the measured verdict on
    the ingest-overlap knob, from the capture's own spans.

    ``ingest_busy_s`` is the wall occupancy (interval union) of the
    ingest + bucketing spans; ``device_busy_s`` the same for dispatch /
    mesh H2D / device-wait-fetch; ``overlap_s`` their intersection —
    wall time when chunk prep and device work ran concurrently.
    ``efficiency`` = overlap_s / ingest_busy_s: 0 is the strictly
    serial pre-overlap pipeline, 1 means every second of host prep was
    hidden. ``mode`` reports which path the run took ("overlap" when
    any span rode the producer's "ingest" lane, else "sync"), and
    ``stall_s`` / ``backpressure_s`` carry the two residue stages —
    what the pipeline could NOT hide, and how long the producer sat on
    a full handoff queue. Returns {} for captures with no ingest spans
    (compute-only or pre-span captures)."""
    ing: list[tuple[float, float]] = []
    dev: list[tuple[float, float]] = []
    stall_s = backpressure_s = 0.0
    saw_ingest_lane = False
    for rec in records:
        if not isinstance(rec, dict) or rec.get("type") != "span":
            continue
        stage = rec.get("stage")
        t = float(rec.get("t", 0.0))
        dur = float(rec.get("dur", 0.0))
        if stage in _INGEST_STAGES:
            ing.append((t, t + dur))
            if rec.get("lane") == "ingest":
                saw_ingest_lane = True
        elif stage in _DEVICE_STAGES:
            dev.append((t, t + dur))
        elif stage == "ingest_stall":
            stall_s += dur
        elif stage == "ingest_backpressure":
            backpressure_s += dur
    if not ing:
        return {}
    ingest_busy = _union_seconds(ing)
    device_busy = _union_seconds(dev)
    overlap = _intersect_seconds(ing, dev)
    return {
        "mode": "overlap" if saw_ingest_lane else "sync",
        "ingest_busy_s": round(ingest_busy, 3),
        "device_busy_s": round(device_busy, 3),
        "overlap_s": round(overlap, 3),
        "efficiency": (
            round(overlap / ingest_busy, 4) if ingest_busy > 0 else 0.0
        ),
        "stall_s": round(stall_s, 3),
        "backpressure_s": round(backpressure_s, 3),
    }


def byte_totals(records: list[dict]) -> dict[str, dict]:
    """Per direction: record count, logical/wire byte sums, summed
    transfer-span seconds (``dur_s``), wall occupancy of the spans'
    union (``busy_s`` — overlap collapsed), and how many records were
    resume-reused (``shard`` only; reused records carry no
    ``logical``)."""
    out: dict[str, dict] = {}
    spans: dict[str, list[tuple[float, float]]] = {}
    for rec in xfer_records(records):
        direction = rec.get("dir", "?")
        d = out.setdefault(
            direction,
            {"n": 0, "logical": 0, "wire": 0, "dur_s": 0.0, "busy_s": 0.0,
             "n_resumed": 0},
        )
        d["n"] += 1
        d["wire"] += int(rec.get("wire", 0))
        if _is_num(rec.get("logical")):
            d["logical"] += int(rec["logical"])
        t = float(rec.get("t", 0.0))
        dur = float(rec.get("dur", 0.0))
        d["dur_s"] += dur
        spans.setdefault(direction, []).append((t, t + dur))
        if rec.get("resumed"):
            d["n_resumed"] += 1
    for direction, d in out.items():
        d["dur_s"] = round(d["dur_s"], 6)
        d["busy_s"] = round(_union_seconds(spans.get(direction, [])), 6)
    return out


def bandwidth_stats(
    records: list[dict], totals: dict | None = None
) -> dict[str, dict]:
    """Measured bandwidth per wire direction (h2d/d2h), decimal MB/s.

    ``effective`` is total wire bytes over the WALL occupancy of the
    direction's transfer spans (their interval union — concurrent
    transfer workers overlap the tunnel's per-call latency, and summed
    durations would under-state the wire); p50/p95 are per-record
    bandwidths, so tunnel weather *within* a run is visible (the r4/r5
    probes showed ~3x intra-day swings between runs; this shows them
    inside one capture). ``totals`` short-circuits the
    :func:`byte_totals` re-scan for callers (wirestat) that already
    computed it."""
    if totals is None:
        totals = byte_totals(records)
    per: dict[str, list[float]] = {}
    for rec in xfer_records(records):
        direction = rec.get("dir")
        if direction not in ("h2d", "d2h"):
            continue
        wire = float(rec.get("wire", 0))
        dur = float(rec.get("dur", 0.0))
        if dur > 0 and wire > 0:
            per.setdefault(direction, []).append(wire / dur / 1e6)
    out = {}
    for direction in ("h2d", "d2h"):
        if direction not in totals:
            continue
        busy = totals[direction]["busy_s"]
        wire = totals[direction]["wire"]
        vals = sorted(per.get(direction, []))
        out[direction] = {
            "n": totals[direction]["n"],
            "effective_mb_s": (
                round(wire / busy / 1e6, 2) if busy > 0 else 0.0
            ),
            "p50_mb_s": round(_pctl(vals, 0.50), 2),
            "p95_mb_s": round(_pctl(vals, 0.95), 2),
        }
    return out


def wire_floor(records: list[dict], totals: dict | None = None) -> dict:
    """The measured wire-floor decomposition of this capture.

    Floor seconds per direction = wall occupancy of that direction's
    transfer spans (interval union); the combined floor is the union
    over BOTH directions, so time when h2d and d2h genuinely overlap
    counts once and ``frac <= 1`` by construction. Both operands are
    MEASURED from the same capture — equivalently wire bytes over the
    effective bandwidth ``bandwidth_stats`` reports — so
    ``e2e_wire_floor_frac`` stops depending on a separate probe whose
    weather may not match the run's (the r5 probes bracketed the wall
    between 0.39 and 0.94 across runs; this number has no bracket)."""
    if totals is None:
        totals = byte_totals(records)
    h2d_s = float(totals.get("h2d", {}).get("busy_s", 0.0))
    d2h_s = float(totals.get("d2h", {}).get("busy_s", 0.0))
    both: list[tuple[float, float]] = []
    for rec in xfer_records(records):
        if rec.get("dir") in ("h2d", "d2h"):
            t = float(rec.get("t", 0.0))
            both.append((t, t + float(rec.get("dur", 0.0))))
    floor_s = _union_seconds(both)
    wall = wall_seconds(records)
    return {
        "h2d_s": round(h2d_s, 3),
        "d2h_s": round(d2h_s, 3),
        "floor_s": round(floor_s, 3),
        "wall_s": round(wall, 3),
        "frac": round(min(floor_s / wall, 1.0), 4) if wall else 0.0,
    }


def packing_stats(records: list[dict], totals: dict | None = None) -> dict:
    """Packing / compression ratios and bytes-per-read.

    Ratios are logical/wire (>1 means the wire moved fewer bytes than
    the logical payload); ``bytes_per_read`` divides the run's total
    wire traffic (both directions) by the fresh reads the summary
    counted — resume-skipped chunks transferred nothing, so the
    denominator matches the numerator by construction."""
    if totals is None:
        totals = byte_totals(records)
    out: dict = {}
    h2d = totals.get("h2d", {})
    if h2d.get("wire"):
        out["h2d_packing_ratio"] = round(h2d["logical"] / h2d["wire"], 3)
    d2h = totals.get("d2h", {})
    if d2h.get("wire") and d2h.get("logical"):
        # the return path's diet (packed consensus-only fetch): 1.0
        # exactly when the d2h rung is off or the capture predates it
        out["d2h_packing_ratio"] = round(d2h["logical"] / d2h["wire"], 3)
    shard = totals.get("shard", {})
    if shard.get("logical") and shard.get("wire"):
        # reused shards carry no logical: ratio over fresh records only
        fresh_wire = shard["wire"] - _resumed_wire(records)
        if fresh_wire > 0:
            out["shard_deflate_ratio"] = round(
                shard["logical"] / fresh_wire, 3
            )
    s = summary_record(records)
    counters = (s or {}).get("counters") or {}
    n_reads = counters.get("n_records")
    if _is_num(n_reads) and n_reads > 0:
        wire = h2d.get("wire", 0) + totals.get("d2h", {}).get("wire", 0)
        out["bytes_per_read"] = round(wire / n_reads, 1)
    return out


def _resumed_wire(records: list[dict]) -> int:
    return sum(
        int(r.get("wire", 0))
        for r in xfer_records(records)
        if r.get("dir") == "shard" and r.get("resumed")
    )


def per_chunk_bytes(records: list[dict]) -> dict[int, dict]:
    """Per chunk: logical/wire byte sums per direction (the byte table
    ``wirestat.py`` prints beside ``trace_report.py``'s time table).
    h2d rows also sum the dispatch records' ``rows_real``/``rows_pad``
    padding attrs (absent on pre-tuner captures), so the table can
    print a per-chunk fill-factor column."""
    out: dict[int, dict] = {}
    for rec in xfer_records(records):
        if "chunk" not in rec:
            continue
        row = out.setdefault(int(rec["chunk"]), {})
        d = row.setdefault(
            rec.get("dir", "?"), {"logical": 0, "wire": 0, "resumed": False}
        )
        if _is_num(rec.get("logical")):
            d["logical"] += int(rec["logical"])
        d["wire"] += int(rec.get("wire", 0))
        d["resumed"] = bool(d["resumed"] or rec.get("resumed"))
        if rec.get("dir") == "h2d" and _is_num(rec.get("rows_pad")):
            d["rows_real"] = d.get("rows_real", 0) + int(rec.get("rows_real", 0))
            d["rows_pad"] = d.get("rows_pad", 0) + int(rec["rows_pad"])
    return dict(sorted(out.items()))


def device_lanes(records: list[dict]) -> dict[str, dict]:
    """Per-device wire attribution of a mesh run: h2d/d2h wire and
    logical byte sums plus mesh-pad bucket counts grouped by the
    ``dev-N`` lanes the mesh-aware dispatch emits its per-device
    ledger records on. {} for single-device (or pre-mesh) captures —
    their records ride thread lanes, not device lanes."""
    out: dict[str, dict] = {}
    for rec in xfer_records(records):
        lane = rec.get("lane", "")
        if not isinstance(lane, str) or not lane.startswith("dev-"):
            continue
        d = out.setdefault(
            lane,
            {"h2d_wire": 0, "h2d_logical": 0, "d2h_wire": 0,
             "d2h_logical": 0, "mesh_pad": 0, "n": 0},
        )
        direction = rec.get("dir")
        if direction not in ("h2d", "d2h"):
            continue
        d["n"] += 1
        d[f"{direction}_wire"] += int(rec.get("wire", 0))
        if _is_num(rec.get("logical")):
            d[f"{direction}_logical"] += int(rec["logical"])
        if direction == "h2d" and _is_num(rec.get("mesh_pad")):
            d["mesh_pad"] += int(rec["mesh_pad"])
    # lanes sort numerically (dev-10 after dev-9)
    return dict(
        sorted(out.items(), key=lambda kv: int(kv[0].split("-", 1)[1])
               if kv[0].split("-", 1)[1].isdigit() else 1 << 30)
    )


def fill_stats(records: list[dict]) -> dict:
    """Bucket fill-factor view of a capture (the padding the tuner
    exists to cut): real read rows vs padded row-slots summed from the
    h2d dispatch records, the run's resolved fill factor, and the
    record-vs-summary cross-check mirroring the byte sum-check — exact
    integer equality, one-sided under recorder truncation, skipped on
    captures whose summary predates the counters. Returns {} for
    pre-tuner captures (no rows attrs anywhere)."""
    rows_real = rows_pad = mesh_pad = 0
    saw_mesh = False
    for rec in xfer_records(records):
        if rec.get("dir") == "h2d" and _is_num(rec.get("rows_pad")):
            rows_real += int(rec.get("rows_real", 0))
            rows_pad += int(rec["rows_pad"])
            if _is_num(rec.get("mesh_pad")):
                saw_mesh = True
                mesh_pad += int(rec["mesh_pad"])
    if not rows_pad:
        return {}
    out = {
        "rows_real": rows_real,
        "rows_pad": rows_pad,
        "fill_factor": round(rows_real / rows_pad, 4),
    }
    if saw_mesh:
        out["mesh_pad_buckets"] = mesh_pad
    s = summary_record(records) or {}
    counters = s.get("counters") or {}
    want_real = counters.get("n_rows_real")
    want_pad = counters.get("n_rows_padded")
    if _is_num(want_real) and _is_num(want_pad):
        dropped = int(s.get("n_dropped") or 0)
        if dropped:
            ok = rows_real <= int(want_real) and rows_pad <= int(want_pad)
        else:
            ok = rows_real == int(want_real) and rows_pad == int(want_pad)
        # the mesh-pad twin of the fill check: per-record mesh_pad
        # attrs vs the summary's n_mesh_pad_buckets counter — exact,
        # one-sided under truncation, skipped on pre-mesh captures
        # (no counter or no attrs anywhere)
        want_mesh = counters.get("n_mesh_pad_buckets")
        if saw_mesh and _is_num(want_mesh):
            ok &= (
                mesh_pad <= int(want_mesh) if dropped
                else mesh_pad == int(want_mesh)
            )
        out["sum_check_ok"] = ok
    return out


def summary_bytes(records: list[dict]) -> dict | None:
    """The executor's ``bytes`` totals from the terminal summary, or
    None (crashed run, or a pre-ledger capture)."""
    s = summary_record(records)
    b = (s or {}).get("bytes")
    return b if isinstance(b, dict) else None


def sum_check_bytes(
    records: list[dict], totals: dict | None = None
) -> tuple[list[dict], bool]:
    """Ledger record totals vs the summary's running totals.

    Bytes are integers and both sides count the same increments, so the
    check is EXACT equality — any drift means records were dropped,
    double-emitted, or the capture was edited. A capture truncated by
    the bounded recorder (summary n_dropped > 0) can only under-count:
    the check degrades to one-sided (records <= summary), mirroring the
    time sum-check's truncation contract. A total key the summary does
    not carry at all is skipped — captures from before that key joined
    the executor (d2h_logical predates the packed-D2H rung) must not
    read as drift. Returns (rows, ok); no summary bytes -> ([], True)
    (nothing to check against)."""
    want = summary_bytes(records)
    if want is None:
        return [], True
    dropped = int((summary_record(records) or {}).get("n_dropped") or 0)
    if totals is None:
        totals = byte_totals(records)
    got = {
        "h2d_logical": totals.get("h2d", {}).get("logical", 0),
        "h2d_wire": totals.get("h2d", {}).get("wire", 0),
        "d2h_logical": totals.get("d2h", {}).get("logical", 0),
        "d2h_wire": totals.get("d2h", {}).get("wire", 0),
        "shard_logical": totals.get("shard", {}).get("logical", 0),
        "shard_wire": totals.get("shard", {}).get("wire", 0),
    }
    rows = []
    ok_all = True
    for key, rec_total in got.items():
        if key not in want:
            continue  # pre-<key> capture: nothing recorded to check
        sv = want.get(key)
        expect = int(sv) if _is_num(sv) else 0
        ok = rec_total <= expect if dropped else rec_total == expect
        ok_all &= ok
        rows.append({
            "key": key, "records": rec_total, "summary": expect, "ok": ok,
        })
    return rows, ok_all


def output_check(
    records: list[dict],
    out_path: str | None = None,
    totals: dict | None = None,
) -> tuple[list[str], bool]:
    """The on-disk drift check: the finalised BAM must be EXACTLY the
    header/EOF overhead plus every ledgered shard's wire bytes, and its
    current on-disk size must still match what the executor measured
    after the atomic rename. Returns (problem strings, ok); a capture
    without summary bytes (crashed run) has nothing to check."""
    import os

    b = summary_bytes(records)
    if b is None:
        return [], True
    problems: list[str] = []
    if totals is None:
        totals = byte_totals(records)
    shard_wire = totals.get("shard", {}).get("wire", 0)
    overhead = b.get("output_overhead_bytes")
    out_bytes = b.get("output_bytes")
    if _is_num(overhead) and _is_num(out_bytes):
        want = int(overhead) + shard_wire
        if want != int(out_bytes):
            problems.append(
                f"ledger shard bytes + overhead = {want} but the summary "
                f"recorded output_bytes = {int(out_bytes)} "
                f"({want - int(out_bytes):+d} drift)"
            )
    path = out_path or b.get("output_path")
    if path and _is_num(out_bytes):
        try:
            disk = os.path.getsize(path)
        except OSError:
            # the output may legitimately have been moved/deleted since
            # the run; only an EXISTING file can disagree
            disk = None
        if disk is not None and disk != int(out_bytes):
            problems.append(
                f"output file {path} is {disk} bytes on disk but the "
                f"ledger accounts for {int(out_bytes)}"
            )
    return problems, not problems
