"""Offline analysis of a trace capture (the consuming side).

A capture (telemetry/trace.py JSONL) answers the question the
RunReport aggregate cannot: WHERE did the wall go. This module holds
the analysis shared by ``tools/trace_report.py`` (human CLI),
``tools/check_trace.py`` (CI schema validator), and ``benchmark.py``
(per-chunk percentiles in the BENCH JSON):

  - schema validation (``validate_trace``) — the capture format is a
    contract between recorder versions and these consumers;
  - per-lane utilization — busy seconds per thread over the wall, the
    direct reading of "which lane is the critical path";
  - per-stage latency percentiles (p50/p95/max of span durations);
  - per-chunk critical path — each chunk's stage chain reassembled
    from its spans, its end-to-end latency, and its dominant stage;
  - the sum-check — per-stage span totals must reproduce
    ``RunReport.seconds`` busy totals (the recorder logs the same
    measured dt), so a capture that disagrees with the report is
    evidence of an instrumentation bug, exactly like the
    busy > wall x pool canary in ``profile_phases.py``.
"""

from __future__ import annotations

import json

from duplexumiconsensusreads_tpu.telemetry.trace import (
    KNOWN_DEV_FIELDS,
    KNOWN_EVENTS,
    KNOWN_STAGES,
    KNOWN_XFER_DIRS,
    TRACE_VERSION,
)

# RunReport.seconds keys that are not span-backed stage totals.
# DELIBERATELY narrower than runtime.executor._NON_STAGE_KEYS:
# main_loop_stall is excluded from the executor's busy-wall TABLE (it
# is blocked wall, not stage busy) but it IS recorded as spans here, so
# the sum-check must cover it — "syncing" this tuple with the
# executor's would silently drop stall accounting from the canary.
_NON_STAGE_KEYS = ("total", "drain_utilization")

# sum-check tolerance: |trace - report| <= abs + rel * report. The
# report rounds to 3 decimals and each span to 6, so honest captures
# agree to well under a millisecond per stage; the slack only absorbs
# that rounding, never a missing span.
_SUM_ABS_TOL = 0.02
_SUM_REL_TOL = 0.01


def load_trace(path: str) -> list[dict]:
    """Parse a JSONL capture. Raises ValueError naming the line on
    malformed JSON — a torn capture must fail loudly, not half-load."""
    records = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{i}: malformed trace line: {e}")
            records.append(rec)
    return records


# ------------------------------------------------------------ validation

def _is_num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def validate_trace(records: list[dict]) -> list[str]:
    """Schema problems as human-readable strings; empty list = valid.

    A capture without a summary record is legal (the run crashed before
    clean shutdown) — everything else in the envelope is mandatory.
    """
    problems: list[str] = []
    if not records:
        return ["empty trace (no records)"]
    meta = records[0]
    if not isinstance(meta, dict) or meta.get("type") != "meta":
        problems.append("record 1: first record must be the meta header")
    elif meta.get("version") != TRACE_VERSION:
        problems.append(
            f"record 1: unsupported trace version {meta.get('version')!r} "
            f"(want {TRACE_VERSION})"
        )
    n_counted = 0
    n_summary = 0
    for i, rec in enumerate(records[1:], 2):
        if not isinstance(rec, dict):
            problems.append(f"record {i}: not a JSON object")
            continue
        kind = rec.get("type")
        if kind == "meta":
            problems.append(f"record {i}: duplicate meta header")
        elif kind == "span":
            stage = rec.get("stage")
            if stage not in KNOWN_STAGES:
                problems.append(f"record {i}: unknown span stage {stage!r}")
            if not _is_num(rec.get("t")) or rec["t"] < 0:
                problems.append(f"record {i}: span needs numeric t >= 0")
            if not _is_num(rec.get("dur")) or rec["dur"] < 0:
                problems.append(f"record {i}: span needs numeric dur >= 0")
            if not isinstance(rec.get("lane"), str) or not rec.get("lane"):
                problems.append(f"record {i}: span needs a non-empty lane")
            if "chunk" in rec and (
                not isinstance(rec["chunk"], int) or rec["chunk"] < 0
            ):
                problems.append(f"record {i}: span chunk must be an int >= 0")
            n_counted += 1
        elif kind == "event":
            name = rec.get("name")
            if name not in KNOWN_EVENTS:
                problems.append(f"record {i}: unknown event name {name!r}")
            if not _is_num(rec.get("t")) or rec["t"] < 0:
                problems.append(f"record {i}: event needs numeric t >= 0")
            if not isinstance(rec.get("lane"), str) or not rec.get("lane"):
                problems.append(f"record {i}: event needs a non-empty lane")
            if name != "truncated":
                n_counted += 1
        elif kind == "xfer":
            # byte-ledger record (telemetry/ledger.py): registered
            # direction, non-negative integer byte counts, the span
            # envelope. `logical` is optional (resume-reused shards
            # never re-derive their raw size) but must be integral
            # when present.
            if rec.get("dir") not in KNOWN_XFER_DIRS:
                problems.append(
                    f"record {i}: unknown xfer dir {rec.get('dir')!r}"
                )
            if not _is_num(rec.get("t")) or rec["t"] < 0:
                problems.append(f"record {i}: xfer needs numeric t >= 0")
            if not _is_num(rec.get("dur")) or rec["dur"] < 0:
                problems.append(f"record {i}: xfer needs numeric dur >= 0")
            if not isinstance(rec.get("wire"), int) or rec["wire"] < 0:
                problems.append(
                    f"record {i}: xfer needs integer wire bytes >= 0"
                )
            if "logical" in rec and (
                not isinstance(rec["logical"], int) or rec["logical"] < 0
            ):
                problems.append(
                    f"record {i}: xfer logical bytes must be an int >= 0"
                )
            if not isinstance(rec.get("lane"), str) or not rec.get("lane"):
                problems.append(f"record {i}: xfer needs a non-empty lane")
            if "chunk" in rec and (
                not isinstance(rec["chunk"], int) or rec["chunk"] < 0
            ):
                problems.append(f"record {i}: xfer chunk must be an int >= 0")
            n_counted += 1
        elif kind == "dev":
            # device-ledger record (telemetry/devledger.py): the span
            # envelope plus the registered dev fields — the class
            # identity integral, the FLOP/second accumulators numeric
            # and non-negative, no unregistered fields (the schema is
            # a closed registry, unlike event attrs: devstat's table
            # and sum-check read every field, so an unknown one is a
            # schema fork, not extra context)
            if not _is_num(rec.get("t")) or rec["t"] < 0:
                problems.append(f"record {i}: dev needs numeric t >= 0")
            if not _is_num(rec.get("dur")) or rec["dur"] < 0:
                problems.append(f"record {i}: dev needs numeric dur >= 0")
            if not isinstance(rec.get("lane"), str) or not rec.get("lane"):
                problems.append(f"record {i}: dev needs a non-empty lane")
            if "chunk" in rec and (
                not isinstance(rec["chunk"], int) or rec["chunk"] < 0
            ):
                problems.append(f"record {i}: dev chunk must be an int >= 0")
            for fk in ("cap", "cycles", "buckets", "h2d_wire", "d2h_wire"):
                fv = rec.get(fk)
                if not isinstance(fv, int) or isinstance(fv, bool) or fv < 0:
                    problems.append(
                        f"record {i}: dev {fk} must be an int >= 0"
                    )
            if not isinstance(rec.get("method"), str) or not rec.get("method"):
                problems.append(
                    f"record {i}: dev needs a non-empty method"
                )
            for fk in ("flops", "disp_s"):
                if not _is_num(rec.get(fk)) or rec[fk] < 0:
                    problems.append(
                        f"record {i}: dev {fk} must be numeric >= 0"
                    )
            for fk in rec:
                if fk in ("type", "t", "dur", "chunk", "lane"):
                    continue
                if fk not in KNOWN_DEV_FIELDS:
                    problems.append(
                        f"record {i}: unregistered dev field {fk!r}"
                    )
            n_counted += 1
        elif kind == "summary":
            n_summary += 1
            if i != len(records):
                problems.append(f"record {i}: summary must be the last record")
            sec = rec.get("seconds", {})
            if not isinstance(sec, dict):
                problems.append(f"record {i}: summary seconds must be a dict")
            else:
                for sk, sv in sec.items():
                    if not _is_num(sv):
                        problems.append(
                            f"record {i}: summary seconds[{sk!r}] is "
                            f"non-numeric"
                        )
            byt = rec.get("bytes")
            if byt is not None:
                if not isinstance(byt, dict):
                    problems.append(f"record {i}: summary bytes must be a dict")
                else:
                    for bk, bv in byt.items():
                        # byte totals are exact integers (the wirestat
                        # sum-check is phrased as equality, and floats
                        # would smuggle rounding slack into it); the
                        # output path tag is the one legal string
                        if bk == "output_path":
                            continue
                        if not isinstance(bv, int) or isinstance(bv, bool):
                            problems.append(
                                f"record {i}: summary bytes[{bk!r}] must "
                                f"be an integer"
                            )
            if isinstance(rec.get("n_events"), int) and rec["n_events"] != n_counted:
                problems.append(
                    f"record {i}: summary n_events={rec['n_events']} but the "
                    f"capture holds {n_counted} span/event records"
                )
        else:
            problems.append(f"record {i}: unknown record type {kind!r}")
    if n_summary > 1:
        problems.append(f"{n_summary} summary records (at most one allowed)")
    return problems


def capture_kind(records: list[dict]) -> str:
    """The capture's kind from its meta header: "run" (streaming
    executor, the default — pre-kind captures read as this) or
    "service" (a serve/ daemon capture)."""
    meta = records[0] if records else None
    if isinstance(meta, dict) and meta.get("type") == "meta":
        k = meta.get("kind")
        if isinstance(k, str) and k:
            return k
    return "run"


_JOB_EVENTS = (
    "job_accepted", "job_rejected", "job_shed", "job_started",
    "job_preempted", "job_completed", "job_failed",
    "lease_takeover", "job_fenced",
    # defensive serving: deadline expiries, poison quarantines and
    # watchdog aborts are per-job verdicts — anonymous ones cannot be
    # decomposed, same contract as every other job event
    "job_expired", "job_quarantined", "watchdog_fired",
    # scatter-gather sharding: the parent's stage completions are
    # job-scoped like every other lifecycle event
    "job_split", "job_merged",
)


def validate_service_trace(records: list[dict]) -> list[str]:
    """The service-capture contract on top of :func:`validate_trace`:
    every job-lifecycle event must name its job (``job`` attr) and be
    recorded on that job's lane (``job-<id>``), and every service
    heartbeat must carry the queue-depth/in-flight sample — a capture
    where job events are anonymous cannot be decomposed per job, which
    is the whole point of the service capture."""
    problems = validate_trace(records)
    if capture_kind(records) != "service":
        problems.append('meta header is not kind="service"')
    for i, rec in enumerate(records, 1):
        if not isinstance(rec, dict) or rec.get("type") != "event":
            continue
        name = rec.get("name")
        if name in _JOB_EVENTS:
            job = rec.get("job")
            if not isinstance(job, str) or not job:
                problems.append(
                    f"record {i}: {name} event without a job id attr"
                )
            elif rec.get("lane") != f"job-{job}":
                problems.append(
                    f"record {i}: {name} event for job {job!r} not on "
                    f"lane 'job-{job}' (got {rec.get('lane')!r})"
                )
        elif name == "heartbeat":
            for attr in ("queue_depth", "jobs_inflight"):
                if not _is_num(rec.get(attr)):
                    problems.append(
                        f"record {i}: service heartbeat lacks numeric "
                        f"{attr!r}"
                    )
    return problems


# -------------------------------------------------------------- analysis

def summary_record(records: list[dict]) -> dict | None:
    last = records[-1] if records else None
    return last if isinstance(last, dict) and last.get("type") == "summary" else None


def wall_seconds(records: list[dict]) -> float:
    """The capture's wall: the report's total when a summary is
    embedded, else the last span end / event time seen."""
    s = summary_record(records)
    if s is not None:
        total = (s.get("seconds") or {}).get("total")
        if _is_num(total) and total > 0:
            return float(total)
    end = 0.0
    for rec in records:
        if rec.get("type") in ("span", "xfer"):
            end = max(end, float(rec.get("t", 0)) + float(rec.get("dur", 0)))
        elif rec.get("type") in ("event", "summary"):
            end = max(end, float(rec.get("t", 0)))
    return end


def _pctl(sorted_vals: list[float], q: float) -> float:
    """Linear-interpolated quantile of an ascending list."""
    if not sorted_vals:
        return 0.0
    pos = q * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    return sorted_vals[lo] + (sorted_vals[hi] - sorted_vals[lo]) * (pos - lo)


def stage_stats(records: list[dict]) -> dict[str, dict]:
    """Per stage: span count, busy total, and p50/p95/max duration."""
    durs: dict[str, list[float]] = {}
    for rec in records:
        if rec.get("type") == "span":
            durs.setdefault(rec["stage"], []).append(float(rec["dur"]))
    out = {}
    for stage in KNOWN_STAGES:  # stable stage order
        if stage not in durs:
            continue
        vals = sorted(durs[stage])
        out[stage] = {
            "count": len(vals),
            "total_s": round(sum(vals), 6),
            "p50_s": round(_pctl(vals, 0.50), 6),
            "p95_s": round(_pctl(vals, 0.95), 6),
            "max_s": round(vals[-1], 6),
        }
    return out


def lane_utilization(records: list[dict]) -> dict[str, dict]:
    """Busy seconds and busy/wall per lane. ``main_loop_stall``,
    ``ingest_stall``, ``ingest_backpressure`` and the follow-mode
    ``live_poll``/``live_wait`` spans are excluded — the thread is
    BLOCKED there, and counting blocked time as busy would hide
    exactly the condition the stall metrics exist to expose. A drain
    lane near 1.0 while main sits low reads as 'the drain pool is the
    critical path'."""
    wall = wall_seconds(records)
    busy: dict[str, float] = {}
    stalled: dict[str, float] = {}
    _stall_stages = (
        "main_loop_stall", "ingest_stall", "ingest_backpressure",
        "live_poll", "live_wait",
    )
    for rec in records:
        if rec.get("type") != "span":
            continue
        tgt = stalled if rec["stage"] in _stall_stages else busy
        lane = rec.get("lane", "?")
        tgt[lane] = tgt.get(lane, 0.0) + float(rec["dur"])
    out = {}
    for lane in sorted(set(busy) | set(stalled)):
        b = busy.get(lane, 0.0)
        out[lane] = {
            "busy_s": round(b, 6),
            "utilization": round(b / wall, 4) if wall else 0.0,
            "stall_s": round(stalled.get(lane, 0.0), 6),
        }
    return out


def chunk_critical_paths(records: list[dict]) -> dict[int, dict]:
    """Per chunk: its stage chain (time order), end-to-end latency from
    first span start to last span end, per-stage busy, and the dominant
    (busiest) stage — the chunk's critical-path verdict. Stall spans
    tagged with the chunk join its chain: a chunk whose 'dominant'
    stage is main_loop_stall was waiting on drain capacity, not work."""
    spans: dict[int, list[dict]] = {}
    for rec in records:
        if rec.get("type") == "span" and "chunk" in rec:
            spans.setdefault(int(rec["chunk"]), []).append(rec)
    out = {}
    for chunk in sorted(spans):
        rows = sorted(spans[chunk], key=lambda r: float(r["t"]))
        start = float(rows[0]["t"])
        end = max(float(r["t"]) + float(r["dur"]) for r in rows)
        stages: dict[str, float] = {}
        for r in rows:
            stages[r["stage"]] = stages.get(r["stage"], 0.0) + float(r["dur"])
        dominant = max(stages.items(), key=lambda kv: kv[1])[0]
        out[chunk] = {
            "chain": [(r["stage"], round(float(r["dur"]), 6)) for r in rows],
            "latency_s": round(end - start, 6),
            "busy_s": round(sum(stages.values()), 6),
            "stages": {k: round(v, 6) for k, v in stages.items()},
            "dominant": dominant,
        }
    return out


def chunk_latency_percentiles(records: list[dict]) -> dict:
    """p50/p95/max of per-chunk end-to-end latency (the number a
    serving SLO is written against), plus the dominant-stage histogram
    across chunks."""
    paths = chunk_critical_paths(records)
    lat = sorted(p["latency_s"] for p in paths.values())
    hist: dict[str, int] = {}
    for p in paths.values():
        hist[p["dominant"]] = hist.get(p["dominant"], 0) + 1
    return {
        "n_chunks": len(lat),
        "p50_s": round(_pctl(lat, 0.50), 6),
        "p95_s": round(_pctl(lat, 0.95), 6),
        "max_s": round(lat[-1], 6) if lat else 0.0,
        "dominant_stages": dict(
            sorted(hist.items(), key=lambda kv: -kv[1])
        ),
    }


def sum_check(
    records: list[dict], seconds: dict | None = None
) -> tuple[list[dict], bool]:
    """Per-stage span totals vs RunReport busy totals.

    ``seconds`` defaults to the capture's embedded summary. Returns
    (rows, ok); rows carry stage/trace_s/report_s/ok. Stages the report
    knows but the capture never saw (and vice versa) fail the check
    unless both sides are ~zero.

    A capture TRUNCATED by the bounded recorder (summary n_dropped > 0)
    cannot account for the spans it dropped, so its totals are a lower
    bound, not a sum: the check degrades to one-sided — only an
    impossible EXCESS (trace > report) fails, never a shortfall. That
    keeps 'exit 1' meaning instrumentation rot, exactly as documented,
    instead of punishing the designed disk-space bound."""
    dropped = int((summary_record(records) or {}).get("n_dropped") or 0)
    if seconds is None:
        s = summary_record(records)
        seconds = (s or {}).get("seconds") or {}
    stats = stage_stats(records)
    rows = []
    ok_all = True
    stages = [k for k in seconds if k not in _NON_STAGE_KEYS]
    stages += [k for k in stats if k not in seconds]
    for stage in stages:
        trace_s = stats.get(stage, {}).get("total_s", 0.0)
        # callers can hand in report JSONs too: a non-numeric entry is
        # a mismatch to surface in the rows, never a TypeError
        rv = seconds.get(stage, 0.0)
        report_s = float(rv) if _is_num(rv) else 0.0
        tol = _SUM_ABS_TOL + _SUM_REL_TOL * report_s
        if dropped:
            ok = trace_s <= report_s + tol
        else:
            ok = abs(trace_s - report_s) <= tol
        ok_all &= ok
        rows.append({
            "stage": stage,
            "trace_s": round(trace_s, 3),
            "report_s": round(report_s, 3),
            "ok": ok,
        })
    return rows, ok_all


# ------------------------------------------------------------- rendering

def render_report(records: list[dict]) -> tuple[list[str], bool]:
    """The human report ``tools/trace_report.py`` prints. Returns
    (lines, ok) — ok is False when the sum-check fails."""
    lines: list[str] = []
    n_spans = sum(1 for r in records if r.get("type") == "span")
    n_events = sum(1 for r in records if r.get("type") == "event")
    s = summary_record(records)
    dropped = (s or {}).get("n_dropped", 0)
    wall = wall_seconds(records)
    lines.append(
        f"capture: {n_spans} spans, {n_events} events, {dropped} dropped; "
        f"wall {wall:.3f}s"
        + ("" if s else "  [no summary record: run did not shut down cleanly]")
    )

    lines.append("")
    lines.append(f"{'lane':<10} {'busy_s':>9} {'util':>6} {'stall_s':>9}")
    for lane, u in lane_utilization(records).items():
        lines.append(
            f"{lane:<10} {u['busy_s']:9.3f} {u['utilization']:6.2f} "
            f"{u['stall_s']:9.3f}"
        )

    lines.append("")
    lines.append(
        f"{'stage':<18} {'count':>6} {'total_s':>9} {'p50_s':>8} "
        f"{'p95_s':>8} {'max_s':>8}"
    )
    for stage, st in stage_stats(records).items():
        lines.append(
            f"{stage:<18} {st['count']:6d} {st['total_s']:9.3f} "
            f"{st['p50_s']:8.4f} {st['p95_s']:8.4f} {st['max_s']:8.4f}"
        )

    pct = chunk_latency_percentiles(records)
    lines.append("")
    lines.append(
        f"chunk critical path: n={pct['n_chunks']} latency "
        f"p50={pct['p50_s']:.3f}s p95={pct['p95_s']:.3f}s "
        f"max={pct['max_s']:.3f}s"
    )
    for stage, n in pct["dominant_stages"].items():
        lines.append(f"  dominant in {n}/{pct['n_chunks']} chunks: {stage}")

    ok = True
    if s is not None and s.get("seconds"):
        rows, ok = sum_check(records)
        bad = [r for r in rows if not r["ok"]]
        lines.append("")
        mode = (
            f"one-sided, {dropped} records dropped by the bounded capture"
            if dropped
            else "exact"
        )
        if ok:
            lines.append(
                f"sum-check vs RunReport.seconds: OK "
                f"({len(rows)} stages within tolerance; {mode})"
            )
        else:
            lines.append(f"sum-check vs RunReport.seconds: FAIL ({mode})")
            for r in bad:
                lines.append(
                    f"  {r['stage']}: trace {r['trace_s']}s vs report "
                    f"{r['report_s']}s"
                )
    return lines, ok
