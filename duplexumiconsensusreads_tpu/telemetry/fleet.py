"""Fleet flight recorder: cross-daemon job timelines + fleet metrics.

Since the serving layer became a fleet (leases/takeover, preemption
slices, watchdog requeues, shard fan-out/merge), one job's life spans
daemons — but every daemon records its OWN service capture and its own
metrics snapshot, so no single artifact can answer "where did job X's
40 seconds go across the fleet?" or "what is fleet-wide p95 queue-wait
per priority class?". This module is the stitching side: it ingests N
daemons' service captures (plus the spool journal and the per-daemon
metrics snapshots when present) and reconstructs, per job, the complete
admission→terminal timeline, then aggregates fleet-level metrics and
evaluates declared SLO gates over them. ``tools/fleet_report.py`` is
the CLI shell (the same split as report.py/trace_report.py and
ledger.py/wirestat.py).

Alignment: every capture's meta header carries ``epoch_m`` — the
recorder's epoch as a raw machine-wide CLOCK_MONOTONIC reading — so a
record's global time is ``epoch_m + t``. That scopes stitching to one
host, exactly the scope flock and the lease clock already impose on a
spool. All stitched times are INTEGER MICROSECONDS on that shared
clock; the journal's ``admitted_m``/``deadline_m`` stamps live in the
same domain and join directly.

The timeline model (per job, keyed (job_id, fencing token, daemon_id)):

  segment  an interval in which one daemon held the job's lease and
           worked it — a ``run`` slice, a planner ``split`` stage, or a
           ``merge`` stage (``FLEET_SEGMENT_KINDS``). A segment opens
           at the owning daemon's ``job_started`` (which names its
           token) and closes at the SAME daemon+token's end event
           (``job_preempted``/``job_completed``/``job_failed``/
           ``job_expired``/``job_split``) — or, when the owner died
           holding the lease, at the ``lease_takeover``/
           ``watchdog_fired`` event with which the fleet durably
           reclaimed it (lease-hold semantics: authority ends at the
           reclaim, wherever the corpse stopped writing).
  gap      an attributed interval in which nobody held the job
           (``FLEET_GAP_KINDS``): ``queue_wait`` (admission → first
           claim, and any sweep-side wait), ``requeued`` (after a clean
           preemption), ``takeover`` / ``watchdog`` (after an unclean
           reclaim, until the next claim — the fleet's recovery
           latency), ``fanned`` (a sharding parent waiting on its
           sub-jobs between ``job_split`` and its merge claim).

THE SUM-CHECK (exact, integers): for every job with an observed
admission and terminal, ``terminal - admission == Σ segments + Σ
gaps``. Like trace_report's time check and wirestat's byte check, the
equality is enforced together with the structural invariants that make
it meaningful: every segment must open with a ``job_started`` and close
with a matching end event on the same (daemon, token), segments may
never overlap (two daemons holding one job at once is a lease-protocol
violation), and terminals are exactly-once across all captures. A
capture written by a daemon that did not shut down cleanly (no summary
record) or that truncated (``n_dropped > 0``) cannot promise complete
testimony, so — same policy as the other sum-checks — the check
degrades to ONE-SIDED for that daemon's slices: an unclosed slice is
closed at the reclaim (or capture end) with a recorded warning instead
of a failure, while impossible structure (overlap, duplicate
terminals, an end event whose start was never recorded in a CLEAN
capture) still fails. Exit 1 in the CLI means a tampered/torn capture
or an instrumentation bug, never the designed bounds.
"""

from __future__ import annotations

import json
import os

from duplexumiconsensusreads_tpu.telemetry.report import (
    _is_num,
    _pctl,
    capture_kind,
    load_trace,
    summary_record,
    validate_service_trace,
    validate_trace,
)

__all__ = [
    "FLEET_SEGMENT_KINDS", "FLEET_GAP_KINDS", "FLEET_METRIC_KEYS",
    "seg_rec", "gap_rec", "discover_service_captures",
    "load_capture", "load_captures", "load_journal",
    "load_metrics_docs", "stitch", "fleet_metrics", "run_overlap",
    "run_device", "render_prom", "check_slo", "render_report",
]

# Timeline segment kinds: what a daemon was doing while it held the
# job's lease. One registry like trace.KNOWN_STAGES — dutlint's
# phase-registry rule pins every literal ``seg_rec("...")`` call site
# to this tuple, and the SLO/prom surfaces key on it.
FLEET_SEGMENT_KINDS = (
    "run",  # a consensus slice (WarmWorker.run_slice under a lease)
    "split",  # a sharding parent's planner stage (claim -> job_split)
    "merge",  # a sharding parent's splice stage (claim -> job_completed)
)

# Attributed ownerless intervals between segments — the registry
# ``gap_rec("...")`` literals are pinned to.
FLEET_GAP_KINDS = (
    "queue_wait",  # admission -> first claim (and sweep-side waiting)
    "requeued",  # clean preemption (budget/drain) -> next claim
    "takeover",  # lease_takeover reclaim -> next claim: recovery latency
    "watchdog",  # watchdog_fired reclaim -> next claim
    "fanned",  # parent waiting on sub-jobs: job_split -> merge claim
)

# The fleet-metrics scalar surface: exactly these keys appear at the
# top level of :func:`fleet_metrics` output, in spool/fleet_metrics.json
# and in the Prometheus exposition; SLO gates (--check-slo) may bound
# any of them. Percentile keys also appear per priority class under
# "classes". A golden test pins the builder to this registry.
FLEET_METRIC_KEYS = (
    "fleet_daemons", "fleet_jobs", "fleet_done", "fleet_failed",
    "fleet_expired", "fleet_quarantined", "fleet_shed", "fleet_rejected",
    "fleet_takeovers", "fleet_watchdog_fired", "fleet_fenced",
    "fleet_preemptions", "fleet_splits", "fleet_merges",
    "fleet_wall_s",
    "queue_wait_p50_s", "queue_wait_p95_s",
    "ttfc_p50_s", "ttfc_p95_s",
    "e2e_p50_s", "e2e_p95_s",
    "takeover_gap_p50_s", "takeover_gap_p95_s", "takeover_gap_max_s",
    "deadline_hit_rate",
)

# terminal lifecycle events -> stitched terminal state
_TERMINALS = {
    "job_completed": "done",
    "job_failed": "failed",
    "job_expired": "expired",
    "job_quarantined": "quarantined",
}

# end events only a live slice can emit: seeing one without a matching
# open segment in a clean capture means a record was dropped (tampered
# or torn capture) — the structural half of the sum-check.
# (job_merged is NOT here: it is an annotation inside the merge
# segment, handled before end-event matching; job_completed closes the
# merge and carries the structural check for that stage.)
_SLICE_ONLY_ENDS = ("job_preempted", "job_completed", "job_split")


def _us(seconds) -> int:
    """Seconds (already rounded at record time) -> integer microseconds
    — the sum-check's exact domain. Bytes don't round; neither do these."""
    return round(float(seconds) * 1e6)


def seg_rec(kind: str, t0_us: int, t1_us: int, daemon: str, **attrs) -> dict:
    """One timeline segment. ``kind`` must be registered in
    FLEET_SEGMENT_KINDS — literal call sites are lint-pinned by
    dutlint's phase-registry rule, and the constructor refuses unknown
    kinds at runtime so a computed kind cannot fork the schema either."""
    if kind not in FLEET_SEGMENT_KINDS:
        raise ValueError(f"unknown fleet segment kind {kind!r}")
    rec = {"kind": kind, "t0_us": int(t0_us), "t1_us": int(t1_us),
           "daemon": daemon}
    rec.update(attrs)
    return rec


def gap_rec(kind: str, t0_us: int, t1_us: int, **attrs) -> dict:
    """One attributed gap (``kind`` ∈ FLEET_GAP_KINDS, pinned like
    :func:`seg_rec`)."""
    if kind not in FLEET_GAP_KINDS:
        raise ValueError(f"unknown fleet gap kind {kind!r}")
    rec = {"kind": kind, "t0_us": int(t0_us), "t1_us": int(t1_us)}
    rec.update(attrs)
    return rec


# ------------------------------------------------------------- ingestion

def discover_service_captures(dir_path: str) -> list[str]:
    """Every service capture in a spool directory, name-sorted: the
    per-daemon ``service.<id>.trace.jsonl`` files, their rotated
    ``.prev`` siblings (a restarted daemon's previous life is still
    fleet history), and the legacy shared ``service.trace.jsonl``. The
    ONE definition of the capture naming convention — fleet_report's
    spool discovery, the quarantine diagnosis scan and the bench
    serve_fleet leg all resolve captures through here."""
    try:
        names = sorted(os.listdir(dir_path))
    except OSError:
        return []
    return [
        os.path.join(dir_path, n) for n in names
        if n.startswith("service.") and (
            n.endswith(".trace.jsonl") or n.endswith(".trace.jsonl.prev")
        )
    ]


def load_capture(path: str) -> dict:
    """Parse + validate one capture for stitching. Returns
    ``{path, records, kind, daemon_id, epoch_us, clean, truncated,
    end_us, problems}`` — ``problems`` holds schema violations (the CLI
    fails on them; a summary-less capture is NOT a violation, it is the
    unclean-shutdown marker the lenient policy keys on)."""
    records = load_trace(path)
    kind = capture_kind(records)
    problems = (
        validate_service_trace(records) if kind == "service"
        else validate_trace(records)
    )
    meta = records[0] if records and isinstance(records[0], dict) else {}
    s = summary_record(records)
    epoch = meta.get("epoch_m")
    end = 0.0
    truncated = bool(s and int(s.get("n_dropped") or 0) > 0)
    for rec in records:
        if not isinstance(rec, dict):
            continue
        if rec.get("type") in ("span", "xfer"):
            end = max(end, float(rec.get("t", 0)) + float(rec.get("dur", 0)))
        elif rec.get("type") in ("event", "summary"):
            end = max(end, float(rec.get("t", 0)))
            if rec.get("type") == "event" and rec.get("name") == "truncated":
                truncated = True
    daemon_id = meta.get("daemon_id")
    if not isinstance(daemon_id, str) or not daemon_id:
        # pre-fleet capture: fall back to the filename so single-capture
        # reports still render; multi-capture stitching flags it below
        daemon_id = os.path.basename(path)
    return {
        "path": path,
        "records": records,
        "kind": kind,
        "daemon_id": daemon_id,
        "epoch_us": _us(epoch) if _is_num(epoch) else None,
        "clean": s is not None,
        "truncated": truncated,
        "end_us": (_us(epoch) if _is_num(epoch) else 0) + _us(end),
        "problems": problems,
    }


def load_captures(paths: list[str]) -> dict:
    """Load + classify captures: ``{"service": [...], "run": [...],
    "problems": [...]}``. Run captures (per-job ``--trace``) ride along
    for the Perfetto export; service captures feed the stitcher.
    Multi-capture alignment REQUIRES ``epoch_m`` in every service
    capture's meta — without it two timelines cannot share a clock and
    guessing would silently fabricate gaps."""
    out = {"service": [], "run": [], "problems": []}
    for path in paths:
        cap = load_capture(path)
        out["problems"] += [f"{path}: {p}" for p in cap["problems"]]
        out["service" if cap["kind"] == "service" else "run"].append(cap)
    if len(out["service"]) > 1:
        for cap in out["service"]:
            if cap["epoch_us"] is None:
                out["problems"].append(
                    f"{cap['path']}: capture meta lacks epoch_m — "
                    f"pre-fleet captures cannot be stitched cross-daemon"
                )
    # a daemon_id may legitimately recur across RECORDER LIVES — a
    # restarted daemon's rotated .prev beside its live capture is
    # fleet history, and epoch_m discriminates the lives. Only two
    # captures of the SAME life (one file passed twice, possibly via
    # copies) are a duplicate: they would double every event.
    seen: dict[tuple, str] = {}
    for cap in out["service"]:
        key = (cap["daemon_id"], cap["epoch_us"])
        first = seen.setdefault(key, cap["path"])
        if first != cap["path"]:
            out["problems"].append(
                f"{cap['path']}: duplicate capture for daemon "
                f"{cap['daemon_id']!r} (same recorder epoch as {first}) "
                f"— pass each capture once"
            )
    return out


def load_journal(path: str) -> dict | None:
    """The spool journal's jobs map (None when absent/torn — stitching
    then runs capture-only, skipping the journal cross-checks)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    jobs = doc.get("jobs") if isinstance(doc, dict) else None
    return jobs if isinstance(jobs, dict) else None


def load_metrics_docs(spool: str) -> list[dict]:
    """Every per-daemon metrics snapshot on the spool
    (``metrics/<daemon_id>.json``), falling back to the legacy shared
    ``metrics.json`` when the directory is absent. Torn files are
    skipped — snapshots are observability, not the record."""
    docs = []
    mdir = os.path.join(spool, "metrics")
    paths = []
    try:
        paths = [os.path.join(mdir, n) for n in sorted(os.listdir(mdir))
                 if n.endswith(".json")]
    except OSError:
        pass
    if not paths:
        paths = [os.path.join(spool, "metrics.json")]
    for p in paths:
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(doc, dict):
            docs.append(doc)
    return docs


# -------------------------------------------------------------- stitching

_JOB_ATTR_EVENTS = (
    "job_accepted", "job_rejected", "job_shed", "job_started",
    "job_preempted", "job_completed", "job_failed", "job_expired",
    "job_quarantined", "job_split", "job_merged", "job_fenced",
    "lease_takeover", "watchdog_fired",
)


def _job_events(service_caps: list[dict]) -> dict[str, list[dict]]:
    """Per job: lifecycle events from every capture, each wrapped with
    its global time and writing daemon, in global time order."""
    jobs: dict[str, list[dict]] = {}
    for cap in service_caps:
        epoch = cap["epoch_us"] or 0
        for rec in cap["records"]:
            if not isinstance(rec, dict) or rec.get("type") != "event":
                continue
            if rec.get("name") not in _JOB_ATTR_EVENTS:
                continue
            job = rec.get("job")
            if not isinstance(job, str) or not job:
                continue
            jobs.setdefault(job, []).append({
                "t_us": epoch + _us(rec.get("t", 0)),
                "daemon": cap["daemon_id"],
                "cap": cap,
                "rec": rec,
            })
    for evs in jobs.values():
        evs.sort(key=lambda e: e["t_us"])
    return jobs


def _stitch_job(
    job_id: str,
    evs: list[dict],
    entry: dict | None,
    problems: list[str],
) -> dict:
    """One job's timeline from its merged event stream. Appends
    structural violations to ``problems`` (the CLI's exit-1 surface);
    per-job warnings (lenient closures) land in the returned dict."""
    out: dict = {
        "job_id": job_id, "state": "accepted", "priority": None,
        "segments": [], "gaps": [], "warnings": [],
        "n_fenced": 0, "n_takeovers": 0, "n_watchdog": 0,
        "admission_us": None, "terminal_us": None,
    }
    segs: list[dict] = out["segments"]
    gaps: list[dict] = out["gaps"]
    open_seg: dict | None = None  # {"t0_us","daemon","token","kind","cap"}
    pending_gap = "queue_wait"
    # admission seed: the journal's admitted_m is in the same raw
    # monotonic domain as epoch_m + t, so it anchors the queue-wait gap
    # even when the admitting daemon's capture was rotated away. It is
    # ms-rounded where event times are µs-rounded — clamp to the first
    # event so the tiling can never start after its own first record.
    if entry is not None and _is_num(entry.get("admitted_m")):
        out["admission_us"] = _us(entry["admitted_m"])
    if evs and out["admission_us"] is not None:
        out["admission_us"] = min(out["admission_us"], evs[0]["t_us"])
    cursor: int | None = out["admission_us"]

    def close_seg(t_us: int, end: str, **attrs) -> None:
        nonlocal open_seg, cursor
        s = open_seg
        open_seg = None
        if t_us < s["t0_us"]:
            problems.append(
                f"job {job_id}: segment on {s['daemon']} would close "
                f"before it opened (clock skew or tampered capture)"
            )
            t_us = s["t0_us"]
        segs.append(seg_rec(
            s["kind"], s["t0_us"], t_us, s["daemon"],
            token=s["token"], end=end, **attrs,
        ))
        cursor = t_us

    def push_gap(t_us: int) -> None:
        nonlocal cursor
        if cursor is None:
            cursor = t_us
            return
        if t_us <= cursor:
            return  # zero-length wait (or µs-vs-ms rounding): no gap
        gaps.append(gap_rec(pending_gap, cursor, t_us))
        cursor = t_us

    def lenient(cap: dict) -> bool:
        # a daemon that died (no summary) or truncated its capture
        # cannot testify completely: one-sided policy for ITS records
        return not cap["clean"] or cap["truncated"]

    for ev in evs:
        rec, t_us, daemon = ev["rec"], ev["t_us"], ev["daemon"]
        name = rec["name"]
        token = rec.get("token")
        if name == "job_accepted":
            out["admission_us"] = (
                t_us if out["admission_us"] is None
                else min(out["admission_us"], t_us)
            )
            out["priority"] = rec.get("priority", out["priority"])
            if cursor is None:
                cursor = out["admission_us"]
            continue
        if name in ("job_rejected", "job_shed"):
            out["state"] = "shed" if name == "job_shed" else "rejected"
            out["priority"] = rec.get("priority", out["priority"])
            out["terminal_us"] = t_us
            continue
        if name == "job_started":
            stage = rec.get("stage")
            kind = (
                "split" if stage == "split"
                else "merge" if stage == "merge" else "run"
            )
            if open_seg is not None:
                # two leases at once is what fencing exists to prevent:
                # real protocol violation, or a dropped end record
                if lenient(open_seg["cap"]):
                    out["warnings"].append(
                        f"slice on {open_seg['daemon']} closed at the "
                        f"next claim (its capture is unclean/truncated)"
                    )
                    close_seg(t_us, "truncated", truncated=True)
                else:
                    problems.append(
                        f"job {job_id}: job_started on {daemon} (token "
                        f"{token}) while the slice on "
                        f"{open_seg['daemon']} (token "
                        f"{open_seg['token']}) is still open — "
                        f"overlapping segments"
                    )
                    close_seg(t_us, "overlap")
            push_gap(t_us)
            out["state"] = "running"
            open_seg = {"t0_us": t_us, "daemon": daemon, "token": token,
                        "kind": kind, "cap": ev["cap"]}
            continue
        if name in ("lease_takeover", "watchdog_fired"):
            which = "takeover" if name == "lease_takeover" else "watchdog"
            out["n_takeovers" if which == "takeover" else "n_watchdog"] += 1
            if open_seg is not None:
                # lease-hold semantics: the dead owner's authority ends
                # HERE, at the durable reclaim — not wherever its
                # capture happens to stop
                close_seg(t_us, which)
            else:
                out["warnings"].append(
                    f"{name} at t={t_us}us reclaimed a slice no capture "
                    f"recorded a start for"
                )
                push_gap(t_us)
            pending_gap = which
            out["state"] = "queued"
            continue
        if name == "job_fenced":
            out["n_fenced"] += 1
            if open_seg is not None and open_seg["daemon"] == daemon:
                # the zombie's own too-late abort: its authority already
                # ended at the takeover; only an unclean fleet (no
                # takeover event captured) leaves the segment open here
                out["warnings"].append(
                    f"slice on {daemon} closed at its fence (no reclaim "
                    f"event captured before it)"
                )
                close_seg(t_us, "fenced")
                pending_gap = "takeover"
            continue
        if name == "job_merged":
            # annotation inside the merge segment (job_completed closes)
            out["merge_s"] = rec.get("merge_s")
            continue
        # end events: close the owning segment (slice path) or the
        # pending gap (sweep-side terminals carry no open slice)
        is_terminal = name in _TERMINALS
        if open_seg is not None and open_seg["daemon"] == daemon and (
            token is None or open_seg["token"] is None
            or int(token) == int(open_seg["token"])
        ):
            after = {
                "job_preempted": "requeued",
                "job_split": "fanned",
            }.get(name)
            close_seg(t_us, name.removeprefix("job_"),
                      **({"reason": rec["reason"]}
                         if isinstance(rec.get("reason"), str) else {}))
            if after:
                pending_gap = after
                out["state"] = "fanned" if name == "job_split" else "queued"
        elif name in _SLICE_ONLY_ENDS:
            cap = ev["cap"]
            if lenient(cap):
                out["warnings"].append(
                    f"{name} on {daemon} without a recorded slice start "
                    f"(capture unclean/truncated)"
                )
                push_gap(t_us)
            else:
                problems.append(
                    f"job {job_id}: {name} on {daemon} (token {token}) "
                    f"has no matching job_started in a clean capture — "
                    f"dropped slice segment"
                )
                push_gap(t_us)
        else:
            # sweep-side job_failed/job_expired/job_quarantined: the
            # waiting interval ends here
            push_gap(t_us)
        if is_terminal:
            if out["terminal_us"] is not None:
                problems.append(
                    f"job {job_id}: duplicate terminal {name} on "
                    f"{daemon} — the fleet completed it more than once"
                )
            out["terminal_us"] = t_us
            out["state"] = _TERMINALS[name]

    if open_seg is not None:
        cap = open_seg["cap"]
        if lenient(cap):
            out["warnings"].append(
                f"slice on {open_seg['daemon']} never closed; closed at "
                f"its capture's end (unclean shutdown)"
            )
            close_seg(max(cap["end_us"], open_seg["t0_us"]), "truncated",
                      truncated=True)
        else:
            problems.append(
                f"job {job_id}: slice on {open_seg['daemon']} (token "
                f"{open_seg['token']}) never closed in a clean capture "
                f"— dropped end record"
            )
            close_seg(max(cap["end_us"], open_seg["t0_us"]), "unclosed")

    # journal cross-checks + fallbacks: the journal is the durable
    # record; the captures are testimony. Where both speak they must
    # agree.
    if entry is not None:
        if out["priority"] is None:
            out["priority"] = entry.get("priority")
        n_started = sum(
            1 for e in evs if e["rec"]["name"] == "job_started"
        )
        slices = entry.get("slices")
        if (
            isinstance(slices, int) and n_started
            and not any(lenient(e["cap"]) for e in evs)
            and slices != n_started
        ):
            problems.append(
                f"job {job_id}: journal says {slices} slices but the "
                f"captures hold {n_started} job_started events — a "
                f"daemon's capture is missing or tampered"
            )
        jstate = entry.get("state")
        if (
            jstate in _TERMINALS.values()
            and out["state"] in _TERMINALS.values()
            and jstate != out["state"]
        ):
            problems.append(
                f"job {job_id}: journal state {jstate!r} disagrees with "
                f"stitched terminal {out['state']!r}"
            )
        out["parent"] = entry.get("parent")
        out["deadline"] = _is_num(entry.get("deadline_m"))
    if out["priority"] is None:
        out["priority"] = 1

    # THE SUM-CHECK: segments + gaps must tile admission -> terminal
    # exactly. The structure above makes honest captures tile by
    # construction, so any drift left IS evidence of overlap/clamping —
    # i.e. of the violations the problems list narrates.
    adm, term = out["admission_us"], out["terminal_us"]
    if adm is not None and term is not None and out["state"] != "shed" \
            and out["state"] != "rejected":
        head = segs[0]["t0_us"] if segs else term
        if gaps and gaps[0]["t0_us"] < adm:
            problems.append(
                f"job {job_id}: timeline begins {adm - gaps[0]['t0_us']}us "
                f"before admission"
            )
        total = sum(s["t1_us"] - s["t0_us"] for s in segs)
        total += sum(g["t1_us"] - g["t0_us"] for g in gaps)
        out["wall_us"] = term - adm
        out["busy_us"] = sum(s["t1_us"] - s["t0_us"] for s in segs)
        out["sum_check_ok"] = (total == out["wall_us"]) and head >= adm
        if not out["sum_check_ok"]:
            problems.append(
                f"job {job_id}: SUM-CHECK DRIFT — admission→terminal "
                f"{out['wall_us']}us != Σ segments + Σ gaps {total}us"
            )
    else:
        out["sum_check_ok"] = None  # open/shed job: nothing to total
    return out


def stitch(captures: dict, journal: dict | None = None) -> dict:
    """Stitch loaded captures (:func:`load_captures` output) + the
    journal into per-job timelines. Returns ``{"jobs": {...},
    "daemons": {...}, "problems": [...], "warnings": [...], "ok":
    bool}`` — ``ok`` is False on any structural violation or sum-check
    drift (the CLI's exit 1)."""
    problems = list(captures.get("problems", ()))
    service_caps = captures.get("service", ())
    jobs_out: dict[str, dict] = {}
    warnings: list[str] = []
    for job_id, evs in sorted(_job_events(list(service_caps)).items()):
        entry = journal.get(job_id) if journal else None
        tl = _stitch_job(job_id, evs, entry, problems)
        warnings += [f"job {job_id}: {w}" for w in tl.pop("warnings")]
        jobs_out[job_id] = tl
    # journal-only jobs (their daemon's capture was rotated away or
    # never passed): surfaced, not stitched — coverage must be audible
    for job_id in sorted(journal or ()):
        if job_id not in jobs_out:
            warnings.append(
                f"job {job_id}: journaled "
                f"({(journal[job_id] or {}).get('state')}) but absent "
                f"from every capture"
            )
    daemons: dict[str, dict] = {}
    for cap in service_caps:
        # a restarted daemon contributes several captures (live +
        # rotated .prev) under one id: an unclean/truncated life marks
        # the daemon, whichever life it was — the lenient one-sided
        # closure stays per-capture above either way
        d = daemons.setdefault(cap["daemon_id"], {
            "path": cap["path"],
            "clean": True,
            "truncated": False,
            "n_slices": 0,
            "busy_us": 0,
        })
        d["clean"] = d["clean"] and cap["clean"]
        d["truncated"] = d["truncated"] or cap["truncated"]
    for tl in jobs_out.values():
        for s in tl["segments"]:
            d = daemons.setdefault(
                s["daemon"],
                {"path": None, "clean": False, "truncated": False,
                 "n_slices": 0, "busy_us": 0},
            )
            d["n_slices"] += 1
            d["busy_us"] += s["t1_us"] - s["t0_us"]
    return {
        "jobs": jobs_out,
        "daemons": daemons,
        "problems": problems,
        "warnings": warnings,
        "ok": not problems,
    }


# ------------------------------------------------------------ aggregation

def _round_us(us: int | None) -> float | None:
    return None if us is None else round(us / 1e6, 6)


def fleet_metrics(
    stitched: dict, metrics_docs: list[dict] | None = None
) -> dict:
    """Fleet-level metrics over the stitched timelines + the per-daemon
    metrics snapshots. Top-level scalars are exactly
    ``FLEET_METRIC_KEYS`` (None where no sample exists); per-class
    percentile tables sit under ``"classes"`` and the per-daemon
    balance under ``"daemons"``."""
    jobs = stitched["jobs"]
    by_class: dict[str, dict[str, list[float]]] = {}

    def _cls(pri) -> dict[str, list[float]]:
        return by_class.setdefault(
            str(pri), {"queue_wait": [], "e2e": [], "ttfc": []}
        )

    takeover_gaps: list[float] = []
    totals = {k: 0 for k in FLEET_METRIC_KEYS if k.startswith("fleet_")}
    n_deadline = n_deadline_hit = 0
    t_lo = t_hi = None
    for tl in jobs.values():
        state = tl["state"]
        totals["fleet_jobs"] += 1
        key = {
            "done": "fleet_done", "failed": "fleet_failed",
            "expired": "fleet_expired", "quarantined": "fleet_quarantined",
            "shed": "fleet_shed", "rejected": "fleet_rejected",
        }.get(state)
        if key:
            totals[key] += 1
        totals["fleet_takeovers"] += tl["n_takeovers"]
        totals["fleet_watchdog_fired"] += tl["n_watchdog"]
        totals["fleet_fenced"] += tl["n_fenced"]
        cls = _cls(tl["priority"])
        for g in tl["gaps"]:
            dur = (g["t1_us"] - g["t0_us"]) / 1e6
            if g["kind"] == "takeover":
                takeover_gaps.append(dur)
        for s in tl["segments"]:
            if s["end"] == "preempted":
                totals["fleet_preemptions"] += 1
            elif s["end"] == "split":
                totals["fleet_splits"] += 1
            if s["kind"] == "merge" and s["end"] == "completed":
                totals["fleet_merges"] += 1
        if tl["gaps"] and tl["gaps"][0]["kind"] == "queue_wait":
            g = tl["gaps"][0]
            cls["queue_wait"].append((g["t1_us"] - g["t0_us"]) / 1e6)
        adm, term = tl["admission_us"], tl["terminal_us"]
        if adm is not None:
            t_lo = adm if t_lo is None else min(t_lo, adm)
        ends = [s["t1_us"] for s in tl["segments"]] + (
            [term] if term is not None else []
        )
        if ends:
            t_hi = max(ends) if t_hi is None else max(t_hi, *ends)
        if state == "done" and adm is not None and term is not None:
            cls["e2e"].append((term - adm) / 1e6)
        if tl.get("deadline"):
            n_deadline += 1
            if state == "done":
                n_deadline_hit += 1
    # TTFC (admission -> first fresh chunk durable) only exists in the
    # services' own sample FIFOs — the capture has no chunk-level
    # events. Merging the raw samples is exact; merging percentiles
    # would not be.
    for doc in metrics_docs or ():
        samples = doc.get("class_latency_samples")
        if not isinstance(samples, dict):
            continue
        for pri, kinds in samples.items():
            if isinstance(kinds, dict) and isinstance(
                kinds.get("ttfc"), list
            ):
                _cls(pri)["ttfc"] += [
                    float(v) for v in kinds["ttfc"] if _is_num(v)
                ]

    daemons = {
        d: {
            "n_slices": info["n_slices"],
            "busy_s": round(info["busy_us"] / 1e6, 6),
            "clean": info["clean"],
            "truncated": info["truncated"],
        }
        for d, info in stitched["daemons"].items()
    }
    fleet_wall = (
        round((t_hi - t_lo) / 1e6, 6)
        if t_lo is not None and t_hi is not None else None
    )
    for d, info in daemons.items():
        info["utilization"] = (
            round(info["busy_s"] / fleet_wall, 4) if fleet_wall else 0.0
        )
    for doc in metrics_docs or ():
        d = doc.get("daemon_id")
        if not isinstance(d, str) or d not in daemons:
            continue
        info = daemons[d]
        for key in ("h2d_bytes", "d2h_bytes", "jobs_done", "jobs_failed",
                    "compile_hit_rate", "verdict_hit_rate",
                    "device_flops", "mfu"):
            if _is_num(doc.get(key)):
                info[key] = doc[key]

    classes = {}
    all_qw: list[float] = []
    all_ttfc: list[float] = []
    all_e2e: list[float] = []
    for pri in sorted(by_class):
        row = {}
        for kind, sink in (("queue_wait", all_qw), ("ttfc", all_ttfc),
                           ("e2e", all_e2e)):
            vals = sorted(by_class[pri][kind])
            sink += vals
            row[f"n_{kind}"] = len(vals)
            row[f"{kind}_p50_s"] = (
                round(_pctl(vals, 0.50), 6) if vals else None
            )
            row[f"{kind}_p95_s"] = (
                round(_pctl(vals, 0.95), 6) if vals else None
            )
        classes[pri] = row

    def _p(vals: list[float], q: float) -> float | None:
        vals = sorted(vals)
        return round(_pctl(vals, q), 6) if vals else None

    out = {
        **totals,
        "fleet_daemons": len(daemons),
        "fleet_wall_s": fleet_wall,
        "queue_wait_p50_s": _p(all_qw, 0.50),
        "queue_wait_p95_s": _p(all_qw, 0.95),
        "ttfc_p50_s": _p(all_ttfc, 0.50),
        "ttfc_p95_s": _p(all_ttfc, 0.95),
        "e2e_p50_s": _p(all_e2e, 0.50),
        "e2e_p95_s": _p(all_e2e, 0.95),
        "takeover_gap_p50_s": _p(takeover_gaps, 0.50),
        "takeover_gap_p95_s": _p(takeover_gaps, 0.95),
        "takeover_gap_max_s": (
            round(max(takeover_gaps), 6) if takeover_gaps else None
        ),
        "deadline_hit_rate": (
            round(n_deadline_hit / n_deadline, 4) if n_deadline else None
        ),
        "classes": classes,
        "daemons": daemons,
        "sum_check_ok": stitched["ok"],
        "n_problems": len(stitched["problems"]),
    }
    return out


def run_overlap(run_caps: list[dict]) -> dict:
    """Ingest-overlap efficiency aggregated over the fleet's per-run
    captures (the ``run``-kind captures that ride along for the
    Perfetto export). Per run: :func:`ledger.overlap_stats`; fleet
    level: byte-ledger-style exact sums, so the fleet efficiency is
    overlap seconds over ingest-busy seconds ACROSS runs — a long run
    weighs proportionally, not one-run-one-vote. Returns {} when no
    run capture carries ingest spans (service-only spools)."""
    from duplexumiconsensusreads_tpu.telemetry import ledger

    per: dict[str, dict] = {}
    ingest = overlap = stall = backpressure = 0.0
    for cap in run_caps:
        ov = ledger.overlap_stats(cap["records"])
        if not ov:
            continue
        per[os.path.basename(cap["path"])] = ov
        ingest += ov["ingest_busy_s"]
        overlap += ov["overlap_s"]
        stall += ov["stall_s"]
        backpressure += ov["backpressure_s"]
    if not per:
        return {}
    return {
        "n_runs": len(per),
        "ingest_busy_s": round(ingest, 3),
        "overlap_s": round(overlap, 3),
        "efficiency": round(overlap / ingest, 4) if ingest > 0 else 0.0,
        "stall_s": round(stall, 3),
        "backpressure_s": round(backpressure, 3),
        "runs": per,
    }


def run_device(run_caps: list[dict]) -> dict:
    """Per-class MFU aggregated over the fleet's per-run captures —
    the fleet view of the device ledger (telemetry/devledger.py).
    Per run: :func:`devledger.device_totals`; fleet level: FLOPs and
    busy seconds sum EXACTLY across runs (distinct captures never share
    a device interval, so the sum IS the union) and the fleet MFU is
    total FLOPs over total busy over peak — a long run weighs
    proportionally, the same weighting :func:`run_overlap` uses.
    Per-class rows merge across runs by class key. Returns {} when no
    run capture carries dev records (pre-devledger captures)."""
    from duplexumiconsensusreads_tpu.telemetry import devledger
    from duplexumiconsensusreads_tpu.telemetry.device import (
        device_peak_flops,
        round_mfu,
    )

    peak, peak_entry = device_peak_flops()
    per: dict[str, dict] = {}
    classes: dict[str, dict] = {}
    flops = busy = 0.0
    for cap in run_caps:
        tot = devledger.device_totals(cap["records"], peak_flops=peak)
        if not tot:
            continue
        per[os.path.basename(cap["path"])] = {
            "flops": tot["flops"], "busy_s": tot["busy_s"],
            "mfu": tot["mfu"], "intensity": tot["intensity"],
        }
        flops += tot["flops"]
        busy += tot["busy_s"]
        for key, d in devledger.class_stats(
            cap["records"], peak_flops=peak
        ).items():
            c = classes.setdefault(key, {"flops": 0.0, "busy_s": 0.0})
            c["flops"] = round(c["flops"] + d["flops"], 3)
            c["busy_s"] = round(c["busy_s"] + d["busy_s"], 6)
    if not per:
        return {}
    for c in classes.values():
        c["mfu"] = (
            round_mfu(c["flops"] / c["busy_s"] / peak)
            if c["busy_s"] > 0 and peak > 0 else 0.0
        )
    return {
        "n_runs": len(per),
        "peak_entry": peak_entry,
        "flops": round(flops, 3),
        "busy_s": round(busy, 6),
        "mfu": round_mfu(flops / busy / peak) if busy > 0 and peak > 0 else 0.0,
        "classes": dict(sorted(classes.items(),
                               key=lambda kv: -kv[1]["flops"])),
        "runs": per,
    }


# ----------------------------------------------------------- exposition

def render_prom(metrics: dict) -> str:
    """Prometheus textfile exposition of the fleet metrics: one
    ``dut_fleet_<key>`` gauge per FLEET_METRIC_KEYS scalar (absent
    samples are omitted, not zeroed — a missing percentile is not a
    zero-latency fleet), plus ``{class=...}``-labeled percentile
    variants and ``{daemon=...}``-labeled balance gauges. Written by
    ``fleet_report --prom`` for the node-exporter textfile collector."""
    lines: list[str] = []
    for key in FLEET_METRIC_KEYS:
        v = metrics.get(key)
        if not _is_num(v):
            continue
        name = f"dut_fleet_{key}"
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {v}")
    for pri, row in sorted(metrics.get("classes", {}).items()):
        for k, v in sorted(row.items()):
            if not _is_num(v) or k.startswith("n_"):
                continue
            name = f"dut_fleet_class_{k}"
            lines.append(f'{name}{{class="{pri}"}} {v}')
    for d, info in sorted(metrics.get("daemons", {}).items()):
        for k in ("n_slices", "busy_s", "utilization",
                  "h2d_bytes", "d2h_bytes", "device_flops", "mfu"):
            v = info.get(k)
            if _is_num(v):
                lines.append(f'dut_fleet_daemon_{k}{{daemon="{d}"}} {v}')
    return "\n".join(lines) + "\n"


def check_slo(metrics: dict, slo: dict) -> tuple[list[dict], bool]:
    """Evaluate declared SLO gates against the fleet metrics.

    ``slo`` is the parsed TOML document: each table names a metric from
    ``FLEET_METRIC_KEYS`` and bounds it with ``max`` and/or ``min``
    (floats); an optional ``class = "N"`` scopes a percentile gate to
    one priority class (the per-class table key, e.g. ``queue_wait_p95_s``
    under ``classes["0"]``). Returns (rows, ok): one row per gate with
    the measured value and verdict. A gate over a metric with NO data
    (None) is reported ``skipped`` and does not fail — an SLO on an
    idle fleet is vacuous, not violated; an unknown metric name is an
    error row and fails (a typo'd gate that silently passes is worse
    than no gate)."""
    rows: list[dict] = []
    ok = True
    for key in sorted(slo):
        gate = slo[key]
        if not isinstance(gate, dict):
            rows.append({"metric": key, "verdict": "error",
                         "detail": "gate must be a TOML table"})
            ok = False
            continue
        if key not in FLEET_METRIC_KEYS:
            rows.append({
                "metric": key, "verdict": "error",
                "detail": f"unknown fleet metric (known: "
                          f"{', '.join(FLEET_METRIC_KEYS)})",
            })
            ok = False
            continue
        cls = gate.get("class")
        if cls is not None:
            value = (metrics.get("classes", {}).get(str(cls)) or {}).get(key)
        else:
            value = metrics.get(key)
        row = {"metric": key, "value": value}
        if cls is not None:
            row["class"] = str(cls)
        if not _is_num(value):
            row["verdict"] = "skipped"
            row["detail"] = "no data"
            rows.append(row)
            continue
        verdict = "pass"
        if _is_num(gate.get("max")) and value > gate["max"]:
            verdict = "fail"
            row["bound"] = f"max {gate['max']}"
        if _is_num(gate.get("min")) and value < gate["min"]:
            verdict = "fail"
            row["bound"] = f"min {gate['min']}"
        if verdict == "pass":
            row["bound"] = " ".join(
                f"{b} {gate[b]}" for b in ("max", "min") if _is_num(gate.get(b))
            )
        row["verdict"] = verdict
        ok &= verdict == "pass"
        rows.append(row)
    return rows, ok


# ------------------------------------------------------------- rendering

def render_report(stitched: dict, metrics: dict) -> list[str]:
    """The human report ``tools/fleet_report.py`` prints: per-daemon
    balance, per-class latency, and one timeline line per job."""
    lines: list[str] = []
    jobs = stitched["jobs"]
    lines.append(
        f"fleet: {metrics['fleet_daemons']} daemons, "
        f"{metrics['fleet_jobs']} jobs ({metrics['fleet_done']} done, "
        f"{metrics['fleet_failed']} failed, "
        f"{metrics['fleet_expired']} expired, "
        f"{metrics['fleet_quarantined']} quarantined, "
        f"{metrics['fleet_shed']} shed), "
        f"{metrics['fleet_takeovers']} takeovers, "
        f"{metrics['fleet_watchdog_fired']} watchdog fires, "
        f"{metrics['fleet_preemptions']} preemptions"
    )
    if metrics["fleet_wall_s"] is not None:
        lines.append(f"wall: {metrics['fleet_wall_s']:.3f}s")
    lines.append("")
    lines.append(f"{'daemon':<24} {'slices':>6} {'busy_s':>9} {'util':>6} "
                 f"{'clean':>6}")
    for d, info in sorted(metrics["daemons"].items()):
        lines.append(
            f"{d[:24]:<24} {info['n_slices']:>6} {info['busy_s']:>9.3f} "
            f"{info['utilization']:>6.2f} {str(info['clean']):>6}"
        )
    if metrics["classes"]:
        lines.append("")
        lines.append(f"{'class':<6} {'n':>4} {'qwait_p50':>10} "
                     f"{'qwait_p95':>10} {'ttfc_p95':>9} {'e2e_p95':>9}")
        for pri, row in sorted(metrics["classes"].items()):

            def _f(v):
                return f"{v:.3f}" if _is_num(v) else "-"

            lines.append(
                f"{pri:<6} {row['n_queue_wait']:>4} "
                f"{_f(row['queue_wait_p50_s']):>10} "
                f"{_f(row['queue_wait_p95_s']):>10} "
                f"{_f(row['ttfc_p95_s']):>9} {_f(row['e2e_p95_s']):>9}"
            )
    if _is_num(metrics["takeover_gap_max_s"]):
        lines.append(
            f"takeover gaps: p50 {metrics['takeover_gap_p50_s']}s "
            f"p95 {metrics['takeover_gap_p95_s']}s "
            f"max {metrics['takeover_gap_max_s']}s"
        )
    lines.append("")
    for job_id in sorted(jobs):
        tl = jobs[job_id]
        chain = " → ".join(
            f"{s['kind']}@{s['daemon'][:12]}"
            f"[{(s['t1_us'] - s['t0_us']) / 1e6:.3f}s]"
            for s in tl["segments"]
        ) or "(no slices captured)"
        wall = (
            f" wall {(tl['terminal_us'] - tl['admission_us']) / 1e6:.3f}s"
            if tl["admission_us"] is not None
            and tl["terminal_us"] is not None else ""
        )
        check = (
            "" if tl["sum_check_ok"] is None
            else " ✓" if tl["sum_check_ok"] else " SUM-CHECK FAIL"
        )
        lines.append(f"{job_id}: {tl['state']}{wall}{check}  {chain}")
        for g in tl["gaps"]:
            if g["kind"] != "queue_wait" or g is tl["gaps"][0]:
                lines.append(
                    f"  gap {g['kind']}: "
                    f"{(g['t1_us'] - g['t0_us']) / 1e6:.3f}s"
                )
    if stitched["warnings"]:
        lines.append("")
        for w in stitched["warnings"]:
            lines.append(f"warning: {w}")
    if stitched["problems"]:
        lines.append("")
        for p in stitched["problems"]:
            lines.append(f"PROBLEM: {p}")
    return lines
