"""Host-side bucketing: pack reads into fixed-shape device buckets.

This is the shape-static trick the north-star mandates ("families
bucketed by (genomic tile, family-size) to keep shapes static"): the
heavy-tailed family-size distribution never reaches XLA — every bucket
is a (R, L) padded tensor, compiled once per geometry.

Rules:
- reads are sorted by (pos_key, packed UMI) so whole position groups
  (and within them, whole exact families) stay contiguous;
- buckets are filled greedily with whole position groups (adjacency
  clustering is position-local, so a split position group would miss
  cluster merges);
- a position group larger than the capacity is split at exact-family
  boundaries (safe for exact grouping; a warning is raised in
  adjacency mode);
- each bucket records source read indices so outputs can be scattered
  back to the caller's order.
"""

from __future__ import annotations

import dataclasses
import warnings

import numpy as np

from duplexumiconsensusreads_tpu.constants import BASE_PAD
from duplexumiconsensusreads_tpu.ops.grouper import dense_pos_ids
from duplexumiconsensusreads_tpu.types import ReadBatch
from duplexumiconsensusreads_tpu.utils.phred import pack_umi_words64


@dataclasses.dataclass
class Bucket:
    """One fixed-shape unit of device work (host NumPy arrays)."""

    pos: np.ndarray  # (R,) i32 bucket-local dense position ids
    umi: np.ndarray  # (R, B) u8
    strand_ab: np.ndarray  # (R,) bool
    valid: np.ndarray  # (R,) bool
    bases: np.ndarray  # (R, L) u8
    quals: np.ndarray  # (R, L) u8
    read_index: np.ndarray  # (R,) i64 into the source batch; -1 = padding
    n_unique_umi: int  # unique (pos, UMI) pairs — must be <= u_max

    @property
    def capacity(self) -> int:
        return self.pos.shape[0]


def _empty_bucket(r: int, l: int, b: int) -> Bucket:
    return Bucket(
        pos=np.zeros(r, np.int32),
        umi=np.zeros((r, b), np.uint8),
        strand_ab=np.zeros(r, bool),
        valid=np.zeros(r, bool),
        bases=np.full((r, l), BASE_PAD, np.uint8),
        quals=np.zeros((r, l), np.uint8),
        read_index=np.full(r, -1, np.int64),
        n_unique_umi=0,
    )


def _fill_bucket(batch: ReadBatch, idx: np.ndarray, r: int) -> Bucket:
    l, b = batch.read_len, batch.umi_len
    bk = _empty_bucket(r, l, b)
    n = len(idx)
    bk.pos[:n] = dense_pos_ids(np.asarray(batch.pos_key)[idx])
    bk.umi[:n] = np.asarray(batch.umi)[idx]
    bk.strand_ab[:n] = np.asarray(batch.strand_ab)[idx]
    bk.valid[:n] = np.asarray(batch.valid)[idx]
    bk.bases[:n] = np.asarray(batch.bases)[idx]
    bk.quals[:n] = np.asarray(batch.quals)[idx]
    bk.read_index[:n] = idx
    key = np.column_stack(
        [np.asarray(batch.pos_key)[idx], pack_umi_words64(np.asarray(batch.umi)[idx])]
    )
    bk.n_unique_umi = len(np.unique(key, axis=0))
    return bk


def build_buckets(
    batch: ReadBatch,
    capacity: int,
    adjacency: bool = False,
) -> list[Bucket]:
    """Pack a host ReadBatch into fixed-capacity buckets."""
    valid = np.asarray(batch.valid, bool)
    idx_all = np.nonzero(valid)[0]
    if len(idx_all) == 0:
        return []
    pos = np.asarray(batch.pos_key)[idx_all]
    words = pack_umi_words64(np.asarray(batch.umi)[idx_all])  # any UMI length
    w = words.shape[1]
    order = np.lexsort((*[words[:, i] for i in range(w - 1, -1, -1)], pos))
    idx_sorted = idx_all[order]
    pos_s = pos[order]
    words_s = words[order]

    # position-group and family boundaries in sorted order
    n = len(idx_sorted)
    pos_start = np.nonzero(np.r_[True, pos_s[1:] != pos_s[:-1]])[0]
    fam_start = np.nonzero(
        np.r_[
            True,
            (pos_s[1:] != pos_s[:-1]) | (words_s[1:] != words_s[:-1]).any(axis=1),
        ]
    )[0]

    buckets: list[np.ndarray] = []
    cur: list[np.ndarray] = []
    cur_n = 0

    def flush():
        nonlocal cur, cur_n
        if cur:
            buckets.append(np.concatenate(cur))
            cur, cur_n = [], 0

    pos_bounds = np.r_[pos_start, n]
    for gi in range(len(pos_start)):
        s, e = pos_bounds[gi], pos_bounds[gi + 1]
        size = e - s
        if size > capacity:
            if adjacency:
                warnings.warn(
                    f"position group of {size} reads exceeds bucket capacity "
                    f"{capacity}; adjacency clustering will not merge UMIs "
                    "across the split"
                )
            # split at family boundaries
            fs = fam_start[(fam_start >= s) & (fam_start < e)]
            fam_bounds = np.r_[fs, e]
            flush()
            chunk_s = s
            for fi in range(1, len(fam_bounds)):
                while fam_bounds[fi] - chunk_s > capacity:
                    cut = fam_bounds[fi - 1]
                    if cut <= chunk_s:  # single family > capacity: hard cuts
                        warnings.warn(
                            f"single UMI family of {fam_bounds[fi]-chunk_s} reads "
                            f"exceeds capacity {capacity}; splitting the family"
                        )
                        cut = chunk_s + capacity
                    buckets.append(idx_sorted[chunk_s:cut])
                    chunk_s = cut
            if e > chunk_s:
                cur = [idx_sorted[chunk_s:e]]
                cur_n = e - chunk_s
            continue
        if cur_n + size > capacity:
            flush()
        cur.append(idx_sorted[s:e])
        cur_n += size
    flush()

    return [_fill_bucket(batch, b, capacity) for b in buckets]


def stack_buckets(buckets: list[Bucket], multiple_of: int = 1) -> dict:
    """Stack buckets into (B, R, ...) arrays, padding the bucket count up
    to a multiple (for even mesh sharding)."""
    if not buckets:
        raise ValueError("no buckets to stack")
    r = buckets[0].capacity
    l = buckets[0].bases.shape[1]
    b = buckets[0].umi.shape[1]
    n = len(buckets)
    n_pad = (-n) % multiple_of
    padded = buckets + [_empty_bucket(r, l, b) for _ in range(n_pad)]
    return {
        "pos": np.stack([x.pos for x in padded]),
        "umi": np.stack([x.umi for x in padded]),
        "strand_ab": np.stack([x.strand_ab for x in padded]),
        "valid": np.stack([x.valid for x in padded]),
        "bases": np.stack([x.bases for x in padded]),
        "quals": np.stack([x.quals for x in padded]),
        "read_index": np.stack([x.read_index for x in padded]),
        "n_real_buckets": n,
    }
