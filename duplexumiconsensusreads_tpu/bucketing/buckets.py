"""Host-side bucketing: pack reads into fixed-shape device buckets.

This is the shape-static trick the north-star mandates ("families
bucketed by (genomic tile, family-size) to keep shapes static"): the
heavy-tailed family-size distribution never reaches XLA — every bucket
is a (R, L) padded tensor, compiled once per geometry.

Rules:
- reads are sorted by (pos_key, packed UMI) so whole position groups
  (and within them, whole exact families) stay contiguous;
- buckets are filled greedily with whole position groups (adjacency
  clustering is position-local, so a split position group would miss
  cluster merges);
- a position group larger than the capacity is handled WITHOUT changing
  results: in adjacency mode the group is preclustered on the host with
  the oracle's directional algorithm and its reads' UMIs are relabeled
  to the cluster seed, after which splitting at (relabeled) family
  boundaries is lossless under exact grouping — the kernel result then
  matches the oracle exactly no matter how large the group is;
- a single family larger than the capacity goes to its own "jumbo"
  bucket with a next-pow2 capacity (dispatched as its own size class),
  so consensus sees the whole family in one piece;
- each bucket records source read indices so outputs can be scattered
  back to the caller's order.

Bucket LADDERS (``ladder=`` — the profile-guided auto-tuner's lever,
see tuning/): instead of one global capacity, a run may carry 2-4 pow2
size classes, e.g. ``(256, 1024, 4096)``. Contiguous runs of position
groups are then partitioned by an exact DP that minimises total padded
row-slots over the ladder (``_ladder_partition``) — a long-tail group
mix stops forcing every bucket to the top rung's padding. The
partition NEVER changes results: buckets still hold whole position
groups, each bucket's geometry invariants (u_max/f_max sized from its
own n_unique) hold per rung because dispatch classes key on capacity,
and the executors' final (pos_key, UMI) sort makes output bytes a pure
function of the read set — byte-identical at ANY ladder (pinned by
tests/test_tuning.py's matrix). The top rung plays the old capacity's
role for the oversized-group and jumbo escapes.
"""

from __future__ import annotations

import dataclasses
import warnings

import numpy as np

from duplexumiconsensusreads_tpu.constants import BASE_PAD
from duplexumiconsensusreads_tpu.ops.grouper import dense_pos_ids
from duplexumiconsensusreads_tpu.types import GroupingParams, ReadBatch
from duplexumiconsensusreads_tpu.utils.phred import pack_umi_words64

# Host preclustering builds an nU x nU adjacency matrix; beyond this
# many unique UMIs in ONE position group (far past any real panel
# hotspot) fall back to the old family-boundary split with a warning.
PRECLUSTER_MAX_UNIQUE = 40_000


@dataclasses.dataclass
class Bucket:
    """One fixed-shape unit of device work (host NumPy arrays)."""

    pos: np.ndarray  # (R,) i32 bucket-local dense position ids
    umi: np.ndarray  # (R, B) u8
    strand_ab: np.ndarray  # (R,) bool
    frag_end: np.ndarray  # (R,) bool
    valid: np.ndarray  # (R,) bool
    bases: np.ndarray  # (R, L) u8
    quals: np.ndarray  # (R, L) u8
    read_index: np.ndarray  # (R,) i64 into the source batch; -1 = padding
    n_unique_umi: int  # unique (pos, UMI) pairs — must be <= u_max
    # True: UMIs were host-preclustered (relabeled to their directional
    # cluster seed); the dispatcher must run this bucket with exact
    # grouping so the device does not re-cluster relabeled seeds.
    preclustered: bool = False

    @property
    def capacity(self) -> int:
        return self.pos.shape[0]


def _empty_bucket(r: int, l: int, b: int) -> Bucket:
    return Bucket(
        pos=np.zeros(r, np.int32),
        umi=np.zeros((r, b), np.uint8),
        strand_ab=np.zeros(r, bool),
        frag_end=np.zeros(r, bool),
        valid=np.zeros(r, bool),
        bases=np.full((r, l), BASE_PAD, np.uint8),
        quals=np.zeros((r, l), np.uint8),
        read_index=np.full(r, -1, np.int64),
        n_unique_umi=0,
    )


def _fill_bucket(
    batch: ReadBatch,
    idx: np.ndarray,
    r: int,
    umi_override: np.ndarray | None = None,
    preclustered: bool = False,
    n_unique: int | None = None,
) -> Bucket:
    l, b = batch.read_len, batch.umi_len
    bk = _empty_bucket(r, l, b)
    n = len(idx)
    umi = umi_override if umi_override is not None else np.asarray(batch.umi)[idx]
    bk.pos[:n] = dense_pos_ids(np.asarray(batch.pos_key)[idx])
    bk.umi[:n] = umi
    bk.strand_ab[:n] = np.asarray(batch.strand_ab)[idx]
    bk.frag_end[:n] = np.asarray(batch.frag_end)[idx]
    bk.valid[:n] = np.asarray(batch.valid)[idx]
    bk.bases[:n] = np.asarray(batch.bases)[idx]
    bk.quals[:n] = np.asarray(batch.quals)[idx]
    bk.read_index[:n] = idx
    bk.preclustered = preclustered
    if n_unique is not None:
        # caller derived the unique-(pos, UMI) count from the chunk's
        # family-run boundaries — per-bucket pack+unique was a top host
        # cost at scale
        bk.n_unique_umi = n_unique
    else:
        key = np.column_stack(
            [np.asarray(batch.pos_key)[idx], pack_umi_words64(umi)]
        )
        bk.n_unique_umi = len(np.unique(key, axis=0))
    return bk


def _pow2(n: int) -> int:
    return 1 << max(n - 1, 0).bit_length()


def _rung_for(n: int, ladder: tuple) -> int:
    """Smallest ladder rung holding ``n`` rows (ladder is ascending and
    its top rung bounds every caller's ``n`` by construction)."""
    for r in ladder:
        if n <= r:
            return r
    return ladder[-1]


# past this many position groups in one contiguous run, the ladder DP
# coalesces consecutive groups into blocks of up to min(ladder)//8 rows
# first — bucket boundaries then land on block edges, bounding the DP at
# O(reads/block * |ladder|) python steps for a worst waste of one block
# per bucket (<= 12.5% of the smallest rung)
_LADDER_DP_MAX_GROUPS = 4096


def _ladder_partition(
    bounds: np.ndarray, ladder: tuple
) -> list[tuple[int, int, int]]:
    """Partition a contiguous run of whole position groups into buckets
    drawn from ``ladder``, minimising total padded row-slots.

    ``bounds`` holds the groups' half-open offsets (len m+1, ascending);
    every single group fits the top rung (oversized groups took the
    precluster/jumbo escapes before this is called). Returns
    ``[(start, end, rung), ...]`` covering ``bounds[0]..bounds[-1]``.

    Exact DP: cost(i) = min over rungs r of cost(j_min(r, i)) + r where
    j_min is the earliest cut such that groups (j..i] fit r. Prefix
    costs are monotone (truncating a feasible packing stays feasible),
    so the earliest cut in each rung's window is optimal and a
    two-pointer per rung makes the whole thing O(m * |ladder|). The
    single-rung case degenerates to the classic greedy's cost, so a
    1-rung ladder pads exactly like the legacy single-capacity path.
    """
    if len(bounds) > _LADDER_DP_MAX_GROUPS + 1:
        block = max(min(ladder) // 8, 1)
        keep = [0]
        for i in range(1, len(bounds)):
            # close BEFORE a group that would overflow a non-empty
            # block: every coalesced block is then either <= `block`
            # rows or one single group (<= the top rung by the caller's
            # contract), so the DP below always stays feasible — a
            # block merging a partial run with a near-capacity group
            # could otherwise exceed every rung and leave cost(i)
            # unreachable
            if bounds[i] - bounds[keep[-1]] > block and i - 1 > keep[-1]:
                keep.append(i - 1)
        if keep[-1] != len(bounds) - 1:
            keep.append(len(bounds) - 1)
        bounds = bounds[np.asarray(keep)]
    m = len(bounds) - 1
    if m <= 0:
        return []
    inf = float("inf")
    cost = [0.0] + [inf] * m
    choice: list[tuple[int, int] | None] = [None] * (m + 1)
    jmin = [0] * len(ladder)
    b0 = int(bounds[0])
    for i in range(1, m + 1):
        hi = int(bounds[i])
        for ri, r in enumerate(ladder):
            j = jmin[ri]
            while hi - int(bounds[j]) > r:
                j += 1
            jmin[ri] = j
            if j < i and cost[j] + r < cost[i]:
                cost[i] = cost[j] + r
                choice[i] = (j, r)
    out: list[tuple[int, int, int]] = []
    i = m
    while i > 0:
        j, r = choice[i]  # type: ignore[misc]
        out.append((int(bounds[j]), int(bounds[i]), r))
        i = j
    out.reverse()
    assert out[0][0] == b0 and out[-1][1] == int(bounds[-1])
    return out


#: counter keys build_buckets increments when a RESULT-CHANGING
#: fallback fires (VERDICT r2: every deviation from oracle semantics
#: must be tallied, not just warned about)
FALLBACK_COUNTERS = (
    "n_precluster_fallback_groups",  # >PRECLUSTER_MAX_UNIQUE position groups
    "n_precluster_fallback_reads",  # reads in those groups
    "n_jumbo_hardcut_families",  # families split past the jumbo limit
    "n_jumbo_hardcut_splits",  # pieces emitted for them (each gets its
    # own consensus record — duplicates by oracle semantics)
)


def build_buckets(
    batch: ReadBatch,
    capacity: int,
    adjacency: bool = False,
    grouping: GroupingParams | None = None,
    counters: dict | None = None,
    ladder: tuple | None = None,
) -> list[Bucket]:
    """Pack a host ReadBatch into fixed-capacity buckets.

    ``grouping`` supplies the directional parameters used to
    host-precluster oversized position groups in adjacency mode; if
    omitted, UMI-tools defaults (Hamming<=1, count_ratio 2) are used.
    ``counters`` (a plain dict) is incremented with FALLBACK_COUNTERS
    whenever a result-changing fallback fires.

    ``ladder`` (ascending pow2 rung capacities whose top rung equals
    ``capacity``) switches the plain-bucket packer from the greedy
    single-capacity fill to the padded-rows-minimising DP over the
    rungs (see the module docstring); the oversized-group and jumbo
    escapes keep their ``capacity``-keyed behaviour, but the family
    runs they emit round up to the smallest fitting rung instead of
    always paying the top rung. Results are identical at any ladder.
    """
    if ladder is not None:
        ladder = tuple(int(r) for r in ladder)
        if len(ladder) < 1 or list(ladder) != sorted(set(ladder)):
            raise ValueError(f"ladder must be ascending distinct rungs, got {ladder}")
        if ladder[-1] != capacity:
            raise ValueError(
                f"ladder top rung {ladder[-1]} must equal capacity {capacity}"
            )
        if len(ladder) == 1:
            ladder = None  # degenerate: the classic single-capacity path
    if grouping is not None:
        adjacency = adjacency or grouping.strategy in ("adjacency", "cluster")
    valid = np.asarray(batch.valid, bool)
    idx_all = np.nonzero(valid)[0]
    if len(idx_all) == 0:
        return []
    pos = np.asarray(batch.pos_key)[idx_all]
    words = pack_umi_words64(np.asarray(batch.umi)[idx_all])  # any UMI length
    w = words.shape[1]
    order = None
    if w == 1 and len(pos) and (np.diff(pos) >= 0).all():
        # fast path for streaming chunks (pos already non-decreasing,
        # single-word UMIs): one packed-key argsort instead of a
        # multi-key lexsort. Dense pos ids come from run boundaries;
        # the UMI word's payload sits in the TOP 2*31 bits, so shift it
        # down to its true width before packing beside the dense id.
        dense = np.cumsum(np.r_[True, pos[1:] != pos[:-1]]) - 1
        u_bits = 2 * batch.umi_len
        if u_bits + int(dense[-1] + 1).bit_length() <= 63:
            keyv = (dense.astype(np.int64) << u_bits) | (
                words[:, 0] >> (62 - u_bits) if u_bits else 0
            )
            order = np.argsort(keyv, kind="stable")
    if order is None:
        order = np.lexsort((*[words[:, i] for i in range(w - 1, -1, -1)], pos))
    idx_sorted = idx_all[order]
    pos_s = pos[order]
    words_s = words[order]

    # position-group and family boundaries in sorted order
    n = len(idx_sorted)
    pos_start = np.nonzero(np.r_[True, pos_s[1:] != pos_s[:-1]])[0]
    fam_start = np.nonzero(
        np.r_[
            True,
            (pos_s[1:] != pos_s[:-1]) | (words_s[1:] != words_s[:-1]).any(axis=1),
        ]
    )[0]

    # plain buckets as contiguous [start, end, bucket_capacity) ranges
    # of idx_sorted — their unique-(pos, UMI) counts come from fam_start
    # (no per-bucket pack+unique, which was a top host cost at scale)
    ranges: list[tuple] = []
    # (idx, umi_override|None, capacity, preclustered, n_unique)
    special: list[tuple] = []
    cur_start = cur_end = 0
    # ladder mode: pending contiguous position-group bounds awaiting the
    # DP cut (offsets into idx_sorted; groups stay whole either way)
    pend: list[int] = []

    def flush():
        nonlocal cur_start, cur_end
        if ladder is not None:
            if len(pend) > 1:
                for a, b, cap in _ladder_partition(
                    np.asarray(pend, np.int64), ladder
                ):
                    ranges.append((a, b, cap))
            pend.clear()
            return
        if cur_end > cur_start:
            ranges.append((cur_start, cur_end, capacity))
            cur_start = cur_end

    # Jumbo buckets keep a whole >capacity family in one piece, but the
    # geometry must stay bounded (stack_buckets pads the class with
    # same-shape empties and XLA compiles per capacity): families past
    # 64x the base capacity are hard-cut with a warning, the bounded
    # behaviour the old splitter had.
    jumbo_max = capacity * 64

    def count(key, by=1):
        if counters is not None:
            counters[key] = counters.get(key, 0) + by

    def run_cap(n: int) -> int:
        # ladder mode: a family run of n rows pays the smallest rung
        # that holds it instead of the top capacity
        return capacity if ladder is None else _rung_for(n, ladder)

    def pack_family_runs(idx_g, bounds, umi_rows, preclustered):
        """Greedy-pack whole families (runs delimited by ``bounds``,
        local offsets into ``idx_g``) into capacity-sized buckets; a
        family larger than the capacity gets a jumbo pow2 bucket."""

        def emit(a, b, cap, n_uni):
            special.append(
                (
                    idx_g[a:b],
                    None if umi_rows is None else umi_rows[a:b],
                    cap,
                    preclustered,
                    n_uni,
                )
            )

        run_s = 0
        run_n = 0
        run_fi = 0
        for fi in range(len(bounds) - 1):
            fs, fe = int(bounds[fi]), int(bounds[fi + 1])
            fsize = fe - fs
            if fsize > jumbo_max:
                warnings.warn(
                    f"single UMI family of {fsize} reads exceeds the jumbo "
                    f"bucket limit {jumbo_max}; splitting the family "
                    "(consensus will emit one record per split)"
                )
                count("n_jumbo_hardcut_families")
                if run_n:
                    emit(run_s, fs, run_cap(fs - run_s), fi - run_fi)
                for cs in range(fs, fe, jumbo_max):
                    ce = min(cs + jumbo_max, fe)
                    count("n_jumbo_hardcut_splits")
                    emit(cs, ce, _pow2(ce - cs), 1)
                run_s, run_n, run_fi = fe, 0, fi + 1
                continue
            if fsize > capacity:
                if run_n:
                    emit(run_s, fs, run_cap(fs - run_s), fi - run_fi)
                emit(fs, fe, _pow2(fsize), 1)
                run_s, run_n, run_fi = fe, 0, fi + 1
                continue
            if run_n + fsize > capacity:
                emit(run_s, fs, run_cap(fs - run_s), fi - run_fi)
                run_s, run_n, run_fi = fs, 0, fi
            run_n += fsize
        if run_n:
            emit(
                run_s, len(idx_g), run_cap(len(idx_g) - run_s),
                len(bounds) - 1 - run_fi,
            )

    pos_bounds = np.r_[pos_start, n]
    for gi in range(len(pos_start)):
        s, e = pos_bounds[gi], pos_bounds[gi + 1]
        size = e - s
        if size > capacity:
            flush()
            sel = idx_sorted[s:e]
            if adjacency:
                g = grouping or GroupingParams(strategy="adjacency")
                umi_g = np.asarray(batch.umi)[sel]
                uu, inv, cnt = np.unique(
                    umi_g, axis=0, return_inverse=True, return_counts=True
                )
                if len(uu) > PRECLUSTER_MAX_UNIQUE:
                    warnings.warn(
                        f"position group with {len(uu)} unique UMIs exceeds "
                        f"the precluster limit {PRECLUSTER_MAX_UNIQUE}; "
                        "falling back to a family-boundary split (adjacency "
                        "merges across the split will be missed)"
                    )
                    count("n_precluster_fallback_groups")
                    count("n_precluster_fallback_reads", int(size))
                    fs_ = fam_start[(fam_start >= s) & (fam_start < e)]
                    pack_family_runs(sel, np.r_[fs_, e] - s, None, False)
                    # NO early continue: fall through to the shared
                    # range reset below — skipping it would let the
                    # final flush re-emit these reads in a plain bucket
                else:
                    from duplexumiconsensusreads_tpu.oracle.grouping import (
                        directional_seeds,
                    )

                    seed_of = directional_seeds(
                        uu, cnt, g.max_hamming, g.effective_count_ratio
                    )
                    new_umi = uu[seed_of][inv]  # (size, B) seed-relabeled
                    w2 = pack_umi_words64(new_umi)
                    order_g = np.lexsort(
                        tuple(w2[:, i] for i in range(w2.shape[1] - 1, -1, -1))
                    )
                    sel = sel[order_g]
                    new_umi = new_umi[order_g]
                    w2 = w2[order_g]
                    fam_b = np.nonzero(
                        np.r_[True, (w2[1:] != w2[:-1]).any(axis=1)]
                    )[0]
                    pack_family_runs(sel, np.r_[fam_b, size], new_umi, True)
            else:
                fs_ = fam_start[(fam_start >= s) & (fam_start < e)]
                pack_family_runs(sel, np.r_[fs_, e] - s, None, False)
            cur_start = cur_end = e  # special paths consumed [s, e)
            continue
        if ladder is not None:
            if not pend:
                pend.append(int(s))
            pend.append(int(e))
            continue
        if (cur_end - cur_start) + size > capacity:
            flush()
            cur_start = s
        cur_end = e
    flush()

    out = [
        _fill_bucket(
            batch,
            idx_sorted[a:b],
            cap,
            n_unique=int(
                np.searchsorted(fam_start, b, side="left")
                - np.searchsorted(fam_start, a, side="left")
            ),
        )
        for a, b, cap in ranges
    ]
    out.extend(
        _fill_bucket(
            batch, idx, cap, umi_override=um, preclustered=pc, n_unique=nu
        )
        for idx, um, cap, pc, nu in special
    )
    return out


def stack_buckets(buckets: list[Bucket], multiple_of: int = 1) -> dict:
    """Stack buckets into (B, R, ...) arrays, padding the bucket count up
    to a multiple (for even mesh sharding)."""
    if not buckets:
        raise ValueError("no buckets to stack")
    r = buckets[0].capacity
    l = buckets[0].bases.shape[1]
    b = buckets[0].umi.shape[1]
    n = len(buckets)
    n_pad = (-n) % multiple_of
    padded = buckets + [_empty_bucket(r, l, b) for _ in range(n_pad)]
    return {
        "pos": np.stack([x.pos for x in padded]),
        "umi": np.stack([x.umi for x in padded]),
        "strand_ab": np.stack([x.strand_ab for x in padded]),
        "frag_end": np.stack([x.frag_end for x in padded]),
        "valid": np.stack([x.valid for x in padded]),
        "bases": np.stack([x.bases for x in padded]),
        "quals": np.stack([x.quals for x in padded]),
        "read_index": np.stack([x.read_index for x in padded]),
        "n_real_buckets": n,
    }
