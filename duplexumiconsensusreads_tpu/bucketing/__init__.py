from duplexumiconsensusreads_tpu.bucketing.buckets import (  # noqa: F401
    Bucket,
    build_buckets,
    stack_buckets,
)
