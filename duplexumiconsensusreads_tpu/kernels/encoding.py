"""Device-side encodings: multi-word 2-bit UMI packing, one-hot helpers.

TPU-first note: everything stays int32/float32 — no int64 on device.
UMIs of B bases pack big-endian into ceil(B/15) int32 words (15 2-bit
codes per word keeps the sign bit clear), so lexicographic comparison
of the word tuple equals comparison of the packed UMI, matching the
host oracle's single-int64 ``pack_umi`` ordering for B <= 31.
"""

from __future__ import annotations

import jax.numpy as jnp

CODES_PER_WORD = 15


def n_umi_words(umi_len: int) -> int:
    return max(1, -(-umi_len // CODES_PER_WORD))


def pack_umi_words(umi_codes: jnp.ndarray) -> jnp.ndarray:
    """(..., B) u8 codes in {0..3} -> (..., W) i32 big-endian words."""
    b = umi_codes.shape[-1]
    w = n_umi_words(b)
    pad = w * CODES_PER_WORD - b
    c = jnp.pad(umi_codes.astype(jnp.int32), [(0, 0)] * (umi_codes.ndim - 1) + [(0, pad)])
    c = c.reshape(*umi_codes.shape[:-1], w, CODES_PER_WORD)
    shifts = jnp.arange(CODES_PER_WORD - 1, -1, -1, dtype=jnp.int32) * 2
    return (c << shifts).sum(axis=-1).astype(jnp.int32)


def one_hot_bases(codes: jnp.ndarray, n: int = 4, dtype=jnp.float32) -> jnp.ndarray:
    """(...,) codes -> (..., n) one-hot; codes >= n produce all-zero rows."""
    return (codes[..., None] == jnp.arange(n, dtype=codes.dtype)).astype(dtype)
