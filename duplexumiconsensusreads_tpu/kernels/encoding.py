"""Device-side encodings: multi-word 2-bit UMI packing, one-hot helpers.

TPU-first note: everything stays int32/float32 — no int64 on device.
UMIs of B bases pack big-endian into ceil(B/15) int32 words (15 2-bit
codes per word keeps the sign bit clear), so lexicographic comparison
of the word tuple equals comparison of the packed UMI, matching the
host oracle's single-int64 ``pack_umi`` ordering for B <= 31.
"""

from __future__ import annotations

import jax.numpy as jnp

CODES_PER_WORD = 15


def n_umi_words(umi_len: int) -> int:
    return max(1, -(-umi_len // CODES_PER_WORD))


def pack_umi_words(umi_codes: jnp.ndarray) -> jnp.ndarray:
    """(..., B) u8 codes in {0..3} -> (..., W) i32 big-endian words."""
    b = umi_codes.shape[-1]
    w = n_umi_words(b)
    pad = w * CODES_PER_WORD - b
    c = jnp.pad(umi_codes.astype(jnp.int32), [(0, 0)] * (umi_codes.ndim - 1) + [(0, pad)])
    c = c.reshape(*umi_codes.shape[:-1], w, CODES_PER_WORD)
    shifts = jnp.arange(CODES_PER_WORD - 1, -1, -1, dtype=jnp.int32) * 2
    return (c << shifts).sum(axis=-1).astype(jnp.int32)


def one_hot_bases(codes: jnp.ndarray, n: int = 4, dtype=jnp.float32) -> jnp.ndarray:
    """(...,) codes -> (..., n) one-hot; codes >= n produce all-zero rows."""
    return (codes[..., None] == jnp.arange(n, dtype=codes.dtype)).astype(dtype)


def unpack_bitplanes(packed: jnp.ndarray, l: int, nbits: int) -> jnp.ndarray:
    """(..., nbits*ceil(l/8)) u8 bit-planes -> (..., l) u8 codes.

    The wire layout of the sub-byte H2D rung (ops/pipeline.pack_stacked):
    ``nbits`` separate little-endian bit-planes, each ceil(l/8) bytes,
    concatenated along the last axis — plane b holds bit b of every
    cycle's code. Pure VPU shifts/reshapes, so the decode fuses into the
    first consumers exactly like the byte rung's."""
    l8 = packed.shape[-1] // nbits
    planes = packed.reshape(*packed.shape[:-1], nbits, l8)
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (planes[..., None] >> shifts) & jnp.uint8(1)  # (..., nbits, l8, 8)
    bits = bits.reshape(*packed.shape[:-1], nbits, l8 * 8)[..., :l]
    plane_shifts = jnp.arange(nbits, dtype=jnp.uint8)
    return (bits << plane_shifts[..., :, None]).sum(
        axis=-2, dtype=jnp.uint8
    )


def pack_2bit(codes: jnp.ndarray) -> jnp.ndarray:
    """(..., l) u8 codes in {0..3} -> (..., ceil(l/4)) u8, four per byte
    (little-endian pairs — the device side of the packed-D2H base lane;
    runtime/executor unpacks with the mirrored NumPy shifts)."""
    l = codes.shape[-1]
    pad = (-l) % 4
    if pad:
        codes = jnp.pad(codes, [(0, 0)] * (codes.ndim - 1) + [(0, pad)])
    c4 = codes.reshape(*codes.shape[:-1], -1, 4)
    return (
        c4[..., 0] | (c4[..., 1] << 2) | (c4[..., 2] << 4) | (c4[..., 3] << 6)
    ).astype(jnp.uint8)
