"""Fused on-device UMI-family grouping kernel (exact + directional adjacency).

TPU-first design, all static shapes, no data-dependent control flow:

1. Lexsort reads by (pos, UMI words) — XLA sort network on the VPU.
2. Exact families = run boundaries in the sorted key stream (cumsum).
3. Adjacency mode additionally:
   a. compacts the unique (pos, UMI) table into ``u_max`` static slots
      via a drop-mode scatter,
   b. computes all-pairs Hamming distance as a one-hot matmul on the
      MXU (matches = X @ X.T over (U, 4B) one-hots),
   c. builds the directed UMI-tools edge matrix
      edge[u,v] = ham<=h AND same pos AND cnt[u] >= r*cnt[v]-1,
   d. runs transitive closure by repeated boolean matrix squaring
      (ceil(log2 U) MXU matmuls — closure distance doubles per step),
   e. assigns each UMI to the minimum-rank node that reaches it
      (rank = descending count, ties by packed UMI).
      This is provably identical to the oracle's sequential
      BFS-with-removal: the minimal-rank node reaching v cannot itself
      be reached by any lower-rank node (else that node would reach v,
      contradicting minimality), hence it is a BFS seed, and no earlier
      seed reaches v — so v lands in exactly that seed's cluster.
4. Dense molecule ids = run boundaries of a second lexsort over
   (pos, cluster UMI); paired mode splits families by strand (AB first),
   matching the oracle's np.unique row ordering bit-for-bit.

Reference parity note: the reference mount was empty (SURVEY.md §0);
the semantic contract is the oracle in oracle/grouping.py.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from duplexumiconsensusreads_tpu.constants import NO_FAMILY
from duplexumiconsensusreads_tpu.kernels.encoding import pack_umi_words

I32_MAX = jnp.iinfo(jnp.int32).max


def _run_ids(keys: list[jnp.ndarray]) -> jnp.ndarray:
    """Dense ids for runs of equal sorted keys: (R,) i32 via cumsum."""
    new = jnp.zeros(keys[0].shape[0], bool).at[0].set(True)
    for k in keys:
        new = new | jnp.concatenate([jnp.ones((1,), bool), k[1:] != k[:-1]])
    return jnp.cumsum(new.astype(jnp.int32)) - 1


def _directional_cluster(
    u_words: jnp.ndarray,  # (U, W) i32
    u_codes: jnp.ndarray,  # (U, B) i32 one-hot-able
    u_pos: jnp.ndarray,  # (U,) i32
    u_cnt: jnp.ndarray,  # (U,) i32
    u_valid: jnp.ndarray,  # (U,) bool
    max_hamming: int,
    count_ratio: int,
) -> jnp.ndarray:
    """Seed index per unique-UMI slot (directional clustering)."""
    u, b = u_codes.shape
    onehot = (u_codes[:, :, None] == jnp.arange(4, dtype=jnp.int32)).astype(jnp.float32)
    matches = jnp.dot(
        onehot.reshape(u, 4 * b),
        onehot.reshape(u, 4 * b).T,
        preferred_element_type=jnp.float32,
    )
    ham = b - matches.astype(jnp.int32)
    edge = (
        (ham <= max_hamming)
        & (u_pos[:, None] == u_pos[None, :])
        & (u_cnt[:, None] >= count_ratio * u_cnt[None, :] - 1)
        & u_valid[:, None]
        & u_valid[None, :]
        & ~jnp.eye(u, dtype=bool)
    )

    # rank by (-count, packed UMI words); invalid slots rank last
    cnt_key = jnp.where(u_valid, -u_cnt, I32_MAX)
    order = jnp.lexsort((*[u_words[:, i] for i in range(u_words.shape[1] - 1, -1, -1)], cnt_key))
    rank = jnp.zeros(u, jnp.int32).at[order].set(jnp.arange(u, dtype=jnp.int32))

    # transitive closure by repeated squaring on the MXU
    reach = (edge | jnp.eye(u, dtype=bool)).astype(jnp.float32)
    n_iters = max(1, (u - 1).bit_length())
    for _ in range(n_iters):
        reach = (jnp.dot(reach, reach, preferred_element_type=jnp.float32) > 0).astype(
            jnp.float32
        )
    reach_b = reach > 0  # reach_b[u, v]: u reaches v

    masked_rank = jnp.where(reach_b, rank[:, None], I32_MAX)
    return jnp.argmin(masked_rank, axis=0).astype(jnp.int32)  # seed per column v


@partial(
    jax.jit,
    static_argnames=("strategy", "max_hamming", "count_ratio", "paired", "u_max"),
)
def group_kernel(
    pos: jnp.ndarray,  # (R,) i32 bucket-local dense position key
    umi_codes: jnp.ndarray,  # (R, B) u8 codes in {0..3} (N-UMI reads pre-dropped)
    strand_ab: jnp.ndarray,  # (R,) bool
    valid: jnp.ndarray,  # (R,) bool
    *,
    strategy: str = "exact",
    max_hamming: int = 1,
    count_ratio: int = 2,
    paired: bool = False,
    u_max: int | None = None,
):
    """Returns (family_id, molecule_id, n_families, n_molecules, n_overflow).

    family_id / molecule_id are (R,) i32 in original read order with
    NO_FAMILY on invalid or overflowed reads; ids are dense and ordered
    exactly like the oracle's (sorted (pos, cluster_umi[, strand])).
    n_overflow counts reads dropped because the unique-UMI table
    exceeded u_max slots (adjacency mode only; size buckets so it's 0).
    """
    r = pos.shape[0]
    if u_max is None:
        u_max = r
    words = pack_umi_words(umi_codes.astype(jnp.int32))  # (R, W)
    w = words.shape[1]

    pos_m = jnp.where(valid, pos.astype(jnp.int32), I32_MAX)
    words_m = jnp.where(valid[:, None], words, I32_MAX)

    order = jnp.lexsort((*[words_m[:, i] for i in range(w - 1, -1, -1)], pos_m))
    spos = pos_m[order]
    swords = words_m[order]
    svalid = valid[order]
    uid = _run_ids([spos] + [swords[:, i] for i in range(w)])  # exact-group id, sorted order

    if strategy == "exact":
        cluster_words_sorted = swords
        overflow_sorted = jnp.zeros(r, bool)
    elif strategy == "adjacency":
        first = jnp.concatenate([jnp.ones((1,), bool), uid[1:] != uid[:-1]]) & svalid
        slot = uid  # unique index; valid iff < u_max
        scodes = umi_codes.astype(jnp.int32)[order]
        # first occurrences define the table; non-firsts scatter to the
        # dropped out-of-range slot u_max
        u_words = jnp.full((u_max, w), I32_MAX, jnp.int32).at[
            jnp.where(first, slot, u_max)
        ].set(swords, mode="drop")
        u_codes = jnp.zeros((u_max, scodes.shape[1]), jnp.int32).at[
            jnp.where(first, slot, u_max)
        ].set(scodes, mode="drop")
        u_pos = jnp.full((u_max,), I32_MAX, jnp.int32).at[
            jnp.where(first, slot, u_max)
        ].set(spos, mode="drop")
        u_cnt = (
            jnp.zeros((u_max + 1,), jnp.int32)
            .at[jnp.minimum(slot, u_max)]
            .add(svalid.astype(jnp.int32), mode="drop")[:u_max]
        )
        u_valid = u_cnt > 0
        seed = _directional_cluster(
            u_words, u_codes, u_pos, u_cnt, u_valid, max_hamming, count_ratio
        )
        cluster_words_unique = jnp.take(u_words, seed, axis=0)  # (u_max, W)
        in_table = slot < u_max
        cluster_words_sorted = jnp.where(
            (in_table & svalid)[:, None],
            jnp.take(cluster_words_unique, jnp.minimum(slot, u_max - 1), axis=0),
            I32_MAX,
        )
        overflow_sorted = svalid & ~in_table
    else:
        raise ValueError(f"unknown grouping strategy {strategy!r}")

    ok_sorted = svalid & ~overflow_sorted
    # scatter back to original order
    inv = jnp.zeros(r, jnp.int32).at[order].set(jnp.arange(r, dtype=jnp.int32))
    cluster_words = jnp.take(cluster_words_sorted, inv, axis=0)
    ok = jnp.take(ok_sorted, inv)

    # dense molecule ids over sorted (pos, cluster_words)
    pos_m2 = jnp.where(ok, pos.astype(jnp.int32), I32_MAX)
    cw_m = jnp.where(ok[:, None], cluster_words, I32_MAX)
    order2 = jnp.lexsort((*[cw_m[:, i] for i in range(w - 1, -1, -1)], pos_m2))
    mid_sorted = _run_ids([pos_m2[order2]] + [cw_m[order2][:, i] for i in range(w)])
    ok2 = ok[order2]
    n_mol = jnp.where(ok2.any(), mid_sorted[jnp.sum(ok2) - 1] + 1, 0).astype(jnp.int32)
    molecule_id = (
        jnp.full(r, NO_FAMILY, jnp.int32)
        .at[order2]
        .set(jnp.where(ok2, mid_sorted, NO_FAMILY))
    )

    if paired:
        strand_ba = (~strand_ab).astype(jnp.int32)
        sb_m = jnp.where(ok, strand_ba, I32_MAX)
        order3 = jnp.lexsort(
            (sb_m, *[cw_m[:, i] for i in range(w - 1, -1, -1)], pos_m2)
        )
        fid_sorted = _run_ids(
            [pos_m2[order3]]
            + [cw_m[order3][:, i] for i in range(w)]
            + [sb_m[order3]]
        )
        ok3 = ok[order3]
        n_fam = jnp.where(ok3.any(), fid_sorted[jnp.sum(ok3) - 1] + 1, 0).astype(jnp.int32)
        family_id = (
            jnp.full(r, NO_FAMILY, jnp.int32)
            .at[order3]
            .set(jnp.where(ok3, fid_sorted, NO_FAMILY))
        )
    else:
        family_id = molecule_id
        n_fam = n_mol

    n_overflow = jnp.sum(valid & ~ok).astype(jnp.int32)
    return family_id, molecule_id, n_fam, n_mol, n_overflow
