"""Fused on-device UMI-family grouping kernel (exact + directional adjacency).

TPU-first design, all static shapes, no data-dependent control flow:

1. Reads arrive sorted by (pos, UMI words). The host bucketing layer
   (bucketing/buckets.py) already guarantees this order, so the
   pipeline path sets ``presorted=True`` and the kernel runs ZERO
   read-length sorts — XLA's O(n log^2 n) bitonic device sort was the
   single most expensive op in the whole pipeline. The operator path
   (ops/grouper.py) accepts arbitrary order and sorts on device first.
2. Exact families = run boundaries in the sorted key stream (cumsum).
3. A compact unique-(pos, UMI) table of ``u_max`` static slots is built
   with drop-mode scatters. Slots are occupied in stream order, so the
   table itself is sorted by (pos, words) by construction.
4. Adjacency mode additionally, on the table only (u_max << R):
   a. all-pairs Hamming distance as a one-hot matmul on the MXU —
      bf16 is exact here (0/1 terms, counts < 256),
   b. the directed UMI-tools edge matrix
      edge[u,v] = ham<=h AND same pos AND cnt[u] >= r*cnt[v]-1,
   c. min-ancestor-rank propagation over the edge grid (O(u^2) VPU
      sweeps to the fixpoint — replaced the O(u^3) closure squarings,
      measured 1.6x faster at bench shapes, bit-identical seeds),
   d. each UMI joins the minimum-rank node that reaches it
      (rank = descending count, ties by packed UMI).
      This is provably identical to the oracle's sequential
      BFS-with-removal: the minimal-rank node reaching v cannot itself
      be reached by any lower-rank node (else that node would reach v,
      contradicting minimality), hence it is a BFS seed, and no earlier
      seed reaches v — so v lands in exactly that seed's cluster.
5. Dense ids come from the TABLE, never from re-sorting reads:
   molecule id = rank of the slot's cluster key (pos, seed words)
   (exact mode: the already-sorted slot index; adjacency: one
   u_max-sized lexsort); paired family id = prefix-sum rank over the
   (molecule, strand) presence array, AB before BA — bit-for-bit the
   oracle's sorted np.unique ordering.

Reference parity note: the reference mount was empty (SURVEY.md §0);
the semantic contract is the oracle in oracle/grouping.py.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from duplexumiconsensusreads_tpu.constants import NO_FAMILY
from duplexumiconsensusreads_tpu.kernels.encoding import pack_umi_words

I32_MAX = jnp.iinfo(jnp.int32).max


def _pairwise_less_eq(primary_less, primary_eq, words):
    """Lexicographic pairwise compare on a (U, U) grid: extends the
    primary key's less/eq masks with the word columns of ``words``
    (U, W). Orientation: out_less[i, j] == key_j < key_i (so a row-sum
    over valid j is key_i's rank). Shared by the two compare-count
    rankings below — the orientation subtlety must live in ONE place.
    """
    less, eq = primary_less, primary_eq
    for k in range(words.shape[1]):
        a = words[:, k]
        less = less | (eq & (a[None, :] < a[:, None]))
        eq = eq & (a[None, :] == a[:, None])
    return less, eq


def _run_ids(keys: list[jnp.ndarray]) -> jnp.ndarray:
    """Dense ids for runs of equal sorted keys: (R,) i32 via cumsum."""
    new = jnp.zeros(keys[0].shape[0], bool).at[0].set(True)
    for k in keys:
        new = new | jnp.concatenate([jnp.ones((1,), bool), k[1:] != k[:-1]])
    return jnp.cumsum(new.astype(jnp.int32)) - 1


def _directional_cluster(
    u_words: jnp.ndarray,  # (U, W) i32
    u_codes: jnp.ndarray,  # (U, B) i32 one-hot-able
    u_pos: jnp.ndarray,  # (U,) i32
    u_cnt: jnp.ndarray,  # (U,) i32
    u_valid: jnp.ndarray,  # (U,) bool
    max_hamming: int,
    count_ratio: int,
) -> jnp.ndarray:
    """Seed index per unique-UMI slot (directional clustering)."""
    u, b = u_codes.shape
    # bf16 inputs + f32 accumulation is exact for any UMI length: the
    # one-hot entries 0/1 are exactly representable in bf16, each
    # product is 0 or 1, and preferred_element_type=float32 makes the
    # MXU accumulate in f32, which sums integers exactly up to 2^24
    # terms — far beyond any UMI length.
    onehot = (u_codes[:, :, None] == jnp.arange(4, dtype=jnp.int32)).astype(
        jnp.bfloat16
    )
    matches = jnp.dot(
        onehot.reshape(u, 4 * b),
        onehot.reshape(u, 4 * b).T,
        preferred_element_type=jnp.float32,
    )
    ham = b - matches.astype(jnp.int32)
    edge = (
        (ham <= max_hamming)
        & (u_pos[:, None] == u_pos[None, :])
        & (u_cnt[:, None] >= count_ratio * u_cnt[None, :] - 1)
        & u_valid[:, None]
        & u_valid[None, :]
        & ~jnp.eye(u, dtype=bool)
    )

    # rank by (-count, packed UMI words) via PAIRWISE COMPARE-COUNT on
    # the (U, U) grid the edge matrix already lives on — no lexsort, no
    # scatter (r4: the two table lexsorts were a measurable share of
    # the adjacency machinery). rank[i] = #{valid j : key_j < key_i}.
    # Keys can tie only ACROSS positions (words are unique within a
    # position group, the table is unique (pos, UMI)); reachability is
    # position-local, so the argmin below never compares tied ranks —
    # equal ranks across positions are harmless, exactly as the old
    # stable lexsort's index tie-break was.
    cj, ci = u_cnt[None, :], u_cnt[:, None]
    less, _ = _pairwise_less_eq(cj > ci, cj == ci, u_words)  # count desc
    rank = jnp.sum(less & u_valid[None, :], axis=1).astype(jnp.int32)
    rank = jnp.where(u_valid, rank, I32_MAX - 1)  # invalid slots rank last

    # The seed of column v is argmin-rank over v's ancestors. Instead of
    # materialising the transitive closure (repeated O(u^3) boolean
    # squarings on the MXU — the r1-r4 design), propagate the MIN
    # ancestor COMBINED KEY rank*U + index directly over the edge grid:
    # each sweep is one (U, U) masked select + a column min — O(u^2)
    # VPU work — and a sweep reaches one more hop, so the fixpoint
    # arrives in graph diameter sweeps (directional chains are shallow,
    # 2-4 hops). The index rides in the low bits, so the seed pops out
    # of the fixpoint as s_min % U — no (U, U) rank-match + argmax
    # recovery pass (the r5 first cut carried rank alone and spent one
    # extra U^2 pass recovering the index). Exactness: ranks are unique
    # among valid slots within a position group and edges are
    # position-local, so the min never tie-breaks on the index; invalid
    # slots get rank U (> every valid rank, no edges) and seed
    # themselves, exactly as the closure's eye() self-reach did. Fits
    # i32: (U+1)*U + U < 2^23 at U <= 2048. Measured r5 on v5e at bench
    # shapes (280 x 512, jit+vmap): closure 20.7 ms -> rank propagation
    # 13.1 ms; the combined key then measures within chip noise of the
    # rank-only form in-pipeline (161.8 vs 164.3-164.8 ms full step
    # across runs) — kept because it is strictly one less (U, U) pass
    # and bit-identical seeds. The while loop's extra sweep past the
    # fixpoint is idempotent, so the early exit is exact.
    idx = jnp.arange(u, dtype=jnp.int32)
    key0 = jnp.where(u_valid, rank, u) * u + idx

    def _step(carry):
        s, i, _ = carry
        cand = jnp.min(jnp.where(edge, s[:, None], I32_MAX), axis=0)
        new = jnp.minimum(s, cand)
        return new, i + 1, jnp.any(new != s)

    def _cond(carry):
        _, i, changed = carry
        return changed & (i < u)

    s_min, _, _ = jax.lax.while_loop(
        _cond, _step, (key0, jnp.int32(0), jnp.bool_(True))
    )
    return (s_min % u).astype(jnp.int32)


@partial(
    jax.jit,
    static_argnames=(
        "strategy", "max_hamming", "count_ratio", "paired", "mate_aware",
        "u_max", "presorted",
    ),
)
def group_kernel(
    pos: jnp.ndarray,  # (R,) i32 bucket-local dense position key
    umi_codes: jnp.ndarray,  # (R, B) u8 codes in {0..3} (N-UMI reads pre-dropped)
    strand_ab: jnp.ndarray,  # (R,) bool
    frag_end: jnp.ndarray,  # (R,) bool (see ReadBatch.frag_end)
    valid: jnp.ndarray,  # (R,) bool
    *,
    strategy: str = "exact",
    max_hamming: int = 1,
    count_ratio: int = 2,
    paired: bool = False,
    mate_aware: bool = False,
    u_max: int | None = None,
    presorted: bool = False,
):
    """Returns (family_id, molecule_id, pair_id, n_families, n_molecules,
    n_overflow).

    family_id / molecule_id / pair_id are (R,) i32 in original read
    order with NO_FAMILY on invalid or overflowed reads; ids are dense
    and ordered exactly like the oracle's (sorted (pos,
    cluster_umi[, frag_end][, strand])). Under mate-aware grouping the
    fragment-end bit joins the family identity, molecule_id becomes the
    dense (molecule, frag_end) consensus-unit id (each unit emits its
    own duplex call — top-R1 with bottom-R2), and pair_id carries the
    true molecule so the two units of one template can be re-linked as
    consensus R1/R2 mates at emission. Without mate_aware (or with no
    second-end reads present) molecule_id == pair_id and ids are
    bit-identical to the pre-mate-aware kernel.

    n_overflow counts reads dropped because the unique-(pos, UMI) table
    exceeded u_max slots — BOTH strategies route ids through the table,
    so size u_max >= the unique-key count (u_max=None defaults to R,
    which can never overflow; spec_for_buckets sizes it from the data).

    presorted=True asserts the caller's contract that valid reads are
    already in ascending (pos, UMI-words) order AND invalid reads sit
    only at the tail (an interleaved invalid row would split a run).
    The bucketing layer guarantees exactly this, letting the kernel
    skip every read-length device sort. The frag_end/strand bits need
    no sort of their own: family/unit ids come from order-independent
    presence scatters over (molecule, bits) keys.
    """
    if strategy not in ("exact", "adjacency", "cluster"):
        raise ValueError(f"unknown grouping strategy {strategy!r}")
    if strategy == "cluster":
        # UMI-tools cluster method == adjacency with the count
        # condition removed: ratio 0 makes the directed edge condition
        # cnt >= -1 vacuously true, the edge set symmetric, and the
        # min-rank propagation labels whole connected components by
        # their highest-count member (types.GroupingParams docstring)
        count_ratio = 0
    r = pos.shape[0]
    if u_max is None:
        u_max = r
    words = pack_umi_words(umi_codes.astype(jnp.int32))  # (R, W)
    w = words.shape[1]

    pos_m = jnp.where(valid, pos.astype(jnp.int32), I32_MAX)
    words_m = jnp.where(valid[:, None], words, I32_MAX)

    if presorted:
        order = jnp.arange(r, dtype=jnp.int32)
        spos, swords, svalid = pos_m, words_m, valid
    else:
        order = jnp.lexsort((*[words_m[:, i] for i in range(w - 1, -1, -1)], pos_m))
        spos = pos_m[order]
        swords = words_m[order]
        svalid = valid[order]

    # Exact-group id along the sorted stream; invalid reads (keys MAX)
    # land in trailing runs that never enter the table.
    uid_raw = _run_ids([spos] + [swords[:, i] for i in range(w)])
    uid = jnp.where(svalid, uid_raw, u_max)  # invalid -> dropped slot

    # ---- unique-(pos, UMI) table; slots occupied in stream order, so
    # the table is sorted by (pos, words) by construction ----
    first = (
        jnp.concatenate([jnp.ones((1,), bool), uid_raw[1:] != uid_raw[:-1]]) & svalid
    )
    tslot = jnp.where(first, jnp.minimum(uid, u_max), u_max)
    u_words = jnp.full((u_max, w), I32_MAX, jnp.int32).at[tslot].set(
        swords, mode="drop"
    )
    u_pos = jnp.full((u_max,), I32_MAX, jnp.int32).at[tslot].set(spos, mode="drop")
    u_valid = u_pos != I32_MAX
    in_table = uid < u_max
    ok_sorted = svalid & in_table

    if strategy == "exact":
        # table already sorted & slots dense: molecule id == slot index
        mid_of_slot = jnp.arange(u_max, dtype=jnp.int32)
        n_mol = jnp.sum(u_valid).astype(jnp.int32)
    else:
        scodes = umi_codes.astype(jnp.int32)[order] if not presorted else umi_codes.astype(jnp.int32)
        u_codes = jnp.zeros((u_max, scodes.shape[1]), jnp.int32).at[tslot].set(
            scodes, mode="drop"
        )
        u_cnt = (
            jnp.zeros((u_max + 1,), jnp.int32)
            .at[jnp.minimum(uid, u_max)]
            .add(svalid.astype(jnp.int32), mode="drop")[:u_max]
        )
        seed = _directional_cluster(
            u_words, u_codes, u_pos, u_cnt, u_valid, max_hamming, count_ratio
        )
        # cluster key per slot = (pos, seed's words); dense ids over
        # DISTINCT keys in sorted-key order, via pairwise compare-count
        # on the (u_max, u_max) grid instead of a lexsort + run-id
        # cumsum + scatter (r4). mid[i] = #distinct valid keys < key_i;
        # "distinct" is enforced by counting only each key's first
        # occurrence. Exact: integer compares, same sorted-key id order
        # as the oracle's np.unique.
        seed_words = jnp.take(u_words, seed, axis=0)
        key_w = jnp.where(u_valid[:, None], seed_words, I32_MAX)
        key_p = jnp.where(u_valid, u_pos, I32_MAX)
        kless, keq = _pairwise_less_eq(
            key_p[None, :] < key_p[:, None],
            key_p[None, :] == key_p[:, None],
            key_w,
        )
        idx_u = jnp.arange(u_max, dtype=jnp.int32)
        first = ~jnp.any(keq & (idx_u[None, :] < idx_u[:, None]), axis=1)
        fv_col = (first & u_valid)[None, :]
        mid_raw_t = jnp.sum(kless & fv_col, axis=1).astype(jnp.int32)
        n_mol = jnp.sum(first & u_valid).astype(jnp.int32)
        mid_of_slot = jnp.where(u_valid, mid_raw_t, I32_MAX)

    slot_c = jnp.minimum(uid, u_max - 1)
    mid_raw = jnp.take(mid_of_slot, slot_c)
    mid_sorted = jnp.where(ok_sorted, mid_raw, NO_FAMILY)

    def dense_rank(key_raw, k):
        """Dense ids over present (molecule*k + bits) keys via a
        presence scatter + cumsum — keys are monotone in the oracle's
        sort order, so the ranks match np.unique with zero sorts."""
        emb = jnp.where(ok_sorted, key_raw, k * u_max)
        pres = jnp.zeros((k * u_max,), jnp.int32).at[emb].set(1, mode="drop")
        rank = jnp.cumsum(pres) - 1
        ids = jnp.where(
            ok_sorted, jnp.take(rank, jnp.minimum(emb, k * u_max - 1)), NO_FAMILY
        )
        return ids, jnp.sum(pres).astype(jnp.int32)

    sba = jnp.where(
        (~strand_ab if presorted else ~strand_ab[order]), 1, 0
    ).astype(jnp.int32)
    e2 = jnp.where(
        (frag_end if presorted else frag_end[order]), 1, 0
    ).astype(jnp.int32)

    # family key = (molecule[, frag_end][, strand_ba]); the embedding is
    # monotone in the oracle's sorted key, so a presence cumsum yields
    # dense ids in oracle order (end1 before end2, AB before BA)
    if mate_aware and paired:
        fid_sorted, n_fam = dense_rank(mid_raw * 4 + e2 * 2 + sba, 4)
    elif mate_aware:
        fid_sorted, n_fam = dense_rank(mid_raw * 2 + e2, 2)
    elif paired:
        fid_sorted, n_fam = dense_rank(mid_raw * 2 + sba, 2)
    else:
        fid_sorted, n_fam = mid_sorted, n_mol

    # mate-aware paired: the consensus output unit is (molecule,
    # frag_end) — duplex merges its AB and BA families, which hold the
    # opposite-mate reads covering the SAME fragment end
    pair_sorted = mid_sorted
    if mate_aware and paired:
        mid_out_sorted, n_mol_out = dense_rank(mid_raw * 2 + e2, 2)
    else:
        mid_out_sorted, n_mol_out = mid_sorted, n_mol

    if presorted:
        family_id, molecule_id, pair_id = fid_sorted, mid_out_sorted, pair_sorted
        ok = ok_sorted
    else:
        inv = jnp.zeros(r, jnp.int32).at[order].set(jnp.arange(r, dtype=jnp.int32))
        family_id = jnp.take(fid_sorted, inv)
        molecule_id = jnp.take(mid_out_sorted, inv)
        pair_id = jnp.take(pair_sorted, inv)
        ok = jnp.take(ok_sorted, inv)

    n_overflow = jnp.sum(valid & ~ok).astype(jnp.int32)
    return family_id, molecule_id, pair_id, n_fam, n_mol_out, n_overflow
