"""Pallas TPU kernel: band-masked one-hot segment GEMM.

The consensus hot op reduces per-read evidence rows into per-family
accumulators: ``out[f] = sum_{r: fid[r]==f} big[r]`` — expressed in
kernels/consensus.py as a dense one-hot matmul ``(F,R)@(R,C)`` so it
rides the MXU. That dense GEMM does F/avg_family_size more FLOPs than
the reduction needs and materialises an (R, F) one-hot in HBM.

This kernel exploits the structure bucketing guarantees: reads arrive
sorted by (position, UMI) and dense family ids follow that same sort
order, so the one-hot matrix is (approximately) block-banded. We tile
the (family, read) space, compute a per-read-tile [min_fid, max_fid]
band on the XLA side, prefetch the resulting tile mask as scalars, and
skip every (f_tile, r_tile) grid step outside the band — the one-hot
tile itself is built in VMEM with an iota compare (never touching
HBM), and each live tile is one MXU ``dot_general``.

Worst case (families randomly scattered in the bucket) degrades to the
dense GEMM's FLOPs, never worse; typical buckets skip most tiles.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _seg_gemm_kernel(mask_ref, fid_ref, big_ref, out_ref):
    i = pl.program_id(0)  # family-tile index
    j = pl.program_id(1)  # read-tile index (sequential: accumulates)
    n_j = pl.num_programs(1)
    f_tile = out_ref.shape[0]

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    @pl.when(mask_ref[i * n_j + j] != 0)
    def _():
        fid = fid_ref[0, :]  # (r_tile,) i32; -1 = dead read
        f0 = i * f_tile
        col = jax.lax.broadcasted_iota(jnp.int32, (1, f_tile), 1)
        onehot = (fid[:, None] == f0 + col).astype(jnp.float32)  # (r_tile, f_tile)
        # HIGHEST: consensus log-likelihoods must accumulate in true f32
        # (default bf16 MXU passes perturb Phred rounding vs the oracle)
        out_ref[:] += jax.lax.dot_general(
            onehot,
            big_ref[:],
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )


@partial(
    jax.jit,
    static_argnames=("f_max", "r_tile", "f_tile", "interpret"),
)
def segment_gemm(
    big: jnp.ndarray,  # (R, C) f32 per-read evidence rows
    fid: jnp.ndarray,  # (R,) i32 dense family ids; anything outside
    #                    [0, f_max) contributes nowhere
    *,
    f_max: int,
    r_tile: int = 512,
    f_tile: int = 256,
    interpret: bool = False,
) -> jnp.ndarray:
    """out (f_max, C) f32 with out[f] = sum of big rows where fid == f."""
    r, c = big.shape
    r_pad = _round_up(max(r, r_tile), r_tile)
    f_pad = _round_up(max(f_max, f_tile), f_tile)
    c_pad = _round_up(max(c, 128), 128)

    big_p = jnp.pad(big.astype(jnp.float32), ((0, r_pad - r), (0, c_pad - c)))
    fid_p = jnp.pad(fid.astype(jnp.int32), (0, r_pad - r), constant_values=-1)
    fid_p = jnp.where((fid_p < 0) | (fid_p >= f_max), -1, fid_p)

    n_ft, n_rt = f_pad // f_tile, r_pad // r_tile

    # Per-read-tile family band → (n_ft, n_rt) tile liveness mask.
    fid_t = fid_p.reshape(n_rt, r_tile)
    live = fid_t >= 0
    lo = jnp.min(jnp.where(live, fid_t, f_max), axis=1) // f_tile
    hi = jnp.max(jnp.where(live, fid_t, -1), axis=1) // f_tile
    ft = jnp.arange(n_ft, dtype=jnp.int32)
    mask = (ft[:, None] >= lo[None, :]) & (ft[:, None] <= hi[None, :])
    mask = mask.astype(jnp.int32).ravel()

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_ft, n_rt),
        in_specs=[
            pl.BlockSpec((1, r_tile), lambda i, j, *_: (0, j)),
            pl.BlockSpec((r_tile, c_pad), lambda i, j, *_: (j, 0)),
        ],
        out_specs=pl.BlockSpec((f_tile, c_pad), lambda i, j, *_: (i, 0)),
    )
    out = pl.pallas_call(
        _seg_gemm_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((f_pad, c_pad), jnp.float32),
        compiler_params=getattr(
            pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
        )(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(mask, fid_p[None, :], big_p)
    return out[:f_max, :c]


def on_tpu() -> bool:
    """True when the default backend is a real TPU (incl. axon plugin)."""
    try:
        plat = jax.devices()[0].platform
    except Exception:
        return False
    return plat in ("tpu", "axon")
