from duplexumiconsensusreads_tpu.kernels.encoding import pack_umi_words  # noqa: F401
from duplexumiconsensusreads_tpu.kernels.grouping import group_kernel  # noqa: F401
from duplexumiconsensusreads_tpu.kernels.consensus import (  # noqa: F401
    ssc_kernel,
    duplex_kernel,
    duplex_merge_strided,
)
from duplexumiconsensusreads_tpu.kernels.error_model import (  # noqa: F401
    fit_cycle_cap_kernel,
    fit_cycle_cap_from_counts,
    apply_cycle_cap,
)
