"""Per-cycle base-quality error model, device side (benchmark config 5).

Fit: per-cycle read-vs-family-consensus mismatch rates (Laplace
smoothed) -> a Phred cap per cycle. Apply: clip input qualities at the
cap. Both are pure elementwise/reduction math that XLA fuses into the
surrounding consensus kernels; the fused config-5 pipeline is
ssc -> fit -> apply -> ssc -> duplex in one jit (ops/pipeline.py).

Mirrors oracle/error_model.py.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from duplexumiconsensusreads_tpu.constants import N_REAL_BASES


@partial(jax.jit, static_argnames=("max_phred_cap",))
def fit_cycle_cap_kernel(
    bases: jnp.ndarray,  # (R, L) u8
    family_id: jnp.ndarray,  # (R,) i32
    valid: jnp.ndarray,  # (R,) bool
    cons_base: jnp.ndarray,  # (F, L) i32 single-strand consensus
    fam_valid: jnp.ndarray,  # (F,) bool
    *,
    max_phred_cap: int = 60,
) -> jnp.ndarray:
    """Per-cycle Phred cap (L,) i32."""
    ok = valid & (family_id >= 0)
    fid = jnp.where(ok, family_id, 0)
    # u8 gather: base codes are 0..5, and the (R, L) row-gather is the
    # fit's dominant cost on TPU (r4 micro: i32 19.5ms vs u8 13.0ms at
    # bench shapes) — gather narrow, compare wide
    cb = jnp.take(cons_base.astype(jnp.uint8), fid, axis=0)  # (R, L)
    fv = jnp.take(fam_valid, fid)
    contrib = (
        ok[:, None]
        & fv[:, None]
        & (bases < N_REAL_BASES)
        & (cb < N_REAL_BASES)
    )
    mism = jnp.sum(contrib & (bases != cb), axis=0)
    total = jnp.sum(contrib, axis=0)
    # Exact-threshold Phred cap — comparisons, not log10: IEEE f32
    # multiply/compare are bit-identical across NumPy and XLA, f32
    # log10 is not. The table is shared with the oracle so parity can't
    # drift (see utils.phred.phred_cap_from_counts).
    from duplexumiconsensusreads_tpu.utils.phred import phred_cap_thresholds

    thr = jnp.asarray(phred_cap_thresholds(max_phred_cap))
    m = (mism + 1).astype(jnp.float32)
    t = (total + 2).astype(jnp.float32)
    count = jnp.sum(
        (m[:, None] <= t[:, None] * thr[None, :]).astype(jnp.int32), axis=1
    )
    return jnp.clip(count - 1, 2, max_phred_cap).astype(jnp.int32)


def apply_cycle_cap(quals: jnp.ndarray, cycle_cap: jnp.ndarray) -> jnp.ndarray:
    """Clip qualities (R, L) at the per-cycle cap (L,)."""
    return jnp.minimum(quals.astype(jnp.int32), cycle_cap[None, :]).astype(quals.dtype)


@partial(jax.jit, static_argnames=("max_phred_cap",))
def fit_cycle_cap_from_counts(
    cons_base: jnp.ndarray,  # (F, L) i32 unmasked ssc fit argmax (BASE_N = no call)
    counts: jnp.ndarray,  # (F, 4L) f32 per-base counts, column l*4+b
    fam_valid: jnp.ndarray,  # (F,) bool
    *,
    max_phred_cap: int = 60,
) -> jnp.ndarray:
    """Per-cycle Phred cap (L,) i32 — the family-side fit.

    Bit-identical to fit_cycle_cap_kernel but consumes the per-family
    per-base counts the ssc reduction GEMM already produced instead of
    re-visiting read space: the read-vs-consensus mismatch tally
    collapses to  mism[l] = sum_f total_f[l] - counts[f, l*4 + cons],
    four strided minor-axis slices + selects. Removes the (R, L)
    consensus row-gather that was the fit's dominant cost (r4 micro:
    u8 take 30.4 ms standalone at bench shapes; the one-hot-GEMM gather
    variant measured 33.1 ms — both refuted by this formulation, which
    adds +4L GEMM columns (~17 ms marginal, measured) and zero gathers).
    Counts stay in the flat GEMM layout — see ssc_kernel on why a
    (F, L, 4) reshape is a TPU-tiling memory catastrophe.
    """
    cons_real = cons_base < N_REAL_BASES
    mask = fam_valid[:, None] & cons_real  # (F, L)
    total_fl = jnp.float32(0)
    match_fl = jnp.float32(0)
    for b in range(4):
        c_b = counts[:, b::4]  # (F, L): base-b counts per cycle
        total_fl = total_fl + c_b
        match_fl = match_fl + jnp.where(cons_base == b, c_b, 0.0)
    total = jnp.sum(jnp.where(mask, total_fl, 0.0), axis=0).astype(jnp.int32)
    mism = jnp.sum(
        jnp.where(mask, total_fl - match_fl, 0.0), axis=0
    ).astype(jnp.int32)
    from duplexumiconsensusreads_tpu.utils.phred import phred_cap_thresholds

    thr = jnp.asarray(phred_cap_thresholds(max_phred_cap))
    m = (mism + 1).astype(jnp.float32)
    t = (total + 2).astype(jnp.float32)
    count = jnp.sum(
        (m[:, None] <= t[:, None] * thr[None, :]).astype(jnp.int32), axis=1
    )
    return jnp.clip(count - 1, 2, max_phred_cap).astype(jnp.int32)
