"""Per-cycle base-quality error model, device side (benchmark config 5).

Fit: per-cycle read-vs-family-consensus mismatch rates (Laplace
smoothed) -> a Phred cap per cycle. Apply: clip input qualities at the
cap. Both are pure elementwise/reduction math that XLA fuses into the
surrounding consensus kernels; the fused config-5 pipeline is
ssc -> fit -> apply -> ssc -> duplex in one jit (ops/pipeline.py).

Mirrors oracle/error_model.py.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from duplexumiconsensusreads_tpu.constants import N_REAL_BASES


@partial(jax.jit, static_argnames=("max_phred_cap",))
def fit_cycle_cap_kernel(
    bases: jnp.ndarray,  # (R, L) u8
    family_id: jnp.ndarray,  # (R,) i32
    valid: jnp.ndarray,  # (R,) bool
    cons_base: jnp.ndarray,  # (F, L) i32 single-strand consensus
    fam_valid: jnp.ndarray,  # (F,) bool
    *,
    max_phred_cap: int = 60,
) -> jnp.ndarray:
    """Per-cycle Phred cap (L,) i32."""
    ok = valid & (family_id >= 0)
    fid = jnp.where(ok, family_id, 0)
    # u8 gather: base codes are 0..5, and the (R, L) row-gather is the
    # fit's dominant cost on TPU (r4 micro: i32 19.5ms vs u8 13.0ms at
    # bench shapes) — gather narrow, compare wide
    cb = jnp.take(cons_base.astype(jnp.uint8), fid, axis=0)  # (R, L)
    fv = jnp.take(fam_valid, fid)
    contrib = (
        ok[:, None]
        & fv[:, None]
        & (bases < N_REAL_BASES)
        & (cb < N_REAL_BASES)
    )
    mism = jnp.sum(contrib & (bases != cb), axis=0)
    total = jnp.sum(contrib, axis=0)
    # Exact-threshold Phred cap — comparisons, not log10: IEEE f32
    # multiply/compare are bit-identical across NumPy and XLA, f32
    # log10 is not. The table is shared with the oracle so parity can't
    # drift (see utils.phred.phred_cap_from_counts).
    from duplexumiconsensusreads_tpu.utils.phred import phred_cap_thresholds

    thr = jnp.asarray(phred_cap_thresholds(max_phred_cap))
    m = (mism + 1).astype(jnp.float32)
    t = (total + 2).astype(jnp.float32)
    count = jnp.sum(
        (m[:, None] <= t[:, None] * thr[None, :]).astype(jnp.int32), axis=1
    )
    return jnp.clip(count - 1, 2, max_phred_cap).astype(jnp.int32)


def apply_cycle_cap(quals: jnp.ndarray, cycle_cap: jnp.ndarray) -> jnp.ndarray:
    """Clip qualities (R, L) at the per-cycle cap (L,)."""
    return jnp.minimum(quals.astype(jnp.int32), cycle_cap[None, :]).astype(quals.dtype)
